"""Backtracking line search as a device ``while_loop``.

The reference's ``linesearch`` (``utils.py:170-182``) evaluates the surrogate
at up to 10 shrinking steps, each trial being a parameter *upload*
(``SetFromFlat``) plus a full-batch ``sess.run`` — up to 20 host↔device
crossings per update. SURVEY §7 flags keeping this on-device as a hard
requirement for the 20× target: the data-dependent early exit becomes a
``lax.while_loop`` carrying the candidate parameter vector in registers.

Acceptance rule is the reference's exactly: accept the first step with
``actual_improve > 0`` and ``actual_improve / expected_improve > accept_ratio``
(expected improvement scaled by the current step fraction); if no step is
accepted, return the original parameters (``utils.py:182``).

Two tail-harvest levers (round 6 — the non-solve ~25% of the update):

* ``f0`` lets the caller pass the already-computed loss at ``x`` so the
  search does not re-pay that full-batch forward (the TRPO update computes
  the surrogate at the current params for its ``surrogate_before`` stat
  anyway — evaluating it again here was a pure duplicate);
* ``has_aux`` makes ``loss_fn`` return ``(loss, aux)`` and carries the
  accepted candidate's ``aux`` through the loop, so downstream consumers
  (the KL-rollback check and the post-update stats pass in ``trpo.py``)
  reuse the accepted trial's forward instead of re-running it — and a
  ``constraint_fn`` receives that same ``aux``, so the KL-aware acceptance
  test costs ZERO extra forwards per trial (it was one full KL forward per
  trial before).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from trpo_tpu.ops.treemath import tree_where

__all__ = ["backtracking_linesearch", "LinesearchResult"]


class LinesearchResult(NamedTuple):
    x: Any                    # accepted params (== input x when nothing accepted)
    success: jax.Array        # bool: did any step pass the acceptance test
    step_fraction: jax.Array  # accepted 0.5**k (0.0 on failure)
    loss: jax.Array           # loss at the returned params
    aux: Any = None           # loss_fn's aux at the returned params
    #                           (has_aux=True only, else None)
    trials: Any = 0           # int32: trial evaluations actually executed
    #                           (1 = accepted first try; max_backtracks =
    #                           exhausted) — the device-side observability
    #                           counter behind stats.linesearch_trials


def backtracking_linesearch(
    loss_fn: Callable[[Any], Any],
    x: Any,
    fullstep: Any,
    expected_improve_rate: jax.Array,
    max_backtracks: int = 10,
    accept_ratio: float = 0.1,
    backtrack_factor: float = 0.5,
    constraint_fn: Optional[Callable[..., jax.Array]] = None,
    has_aux: bool = False,
    f0: Optional[jax.Array] = None,
    aux0: Any = None,
) -> LinesearchResult:
    """Search along ``fullstep`` from ``x`` minimizing ``loss_fn``.

    ``expected_improve_rate`` is the first-order predicted improvement at the
    full step (``gᵀ·fullstep``); the reference scales it by the step fraction
    when forming the ratio (``utils.py:176``).

    ``x``/``fullstep`` may be flat vectors (the reference's contract) or any
    matching pytrees — candidate parameters are carried through the loop in
    whatever (possibly mesh-sharded) layout they arrive in.

    ``constraint_fn`` (optional): a boolean feasibility predicate evaluated
    at each candidate; acceptance then requires the surrogate criterion AND
    the constraint. The TRPO update uses this for the KL-aware search
    (``cfg.linesearch_kl_cap``): backtrack past candidates whose rollout KL
    exceeds the rollback cap instead of discovering the violation post-hoc
    and discarding the whole update. Beyond-reference lever (the
    reference's search checks the surrogate only, ``utils.py:170-182``).

    ``has_aux=True``: ``loss_fn`` returns ``(loss, aux)`` and the aux of
    the returned point comes back in ``LinesearchResult.aux`` (carried in
    the loop — any fixed-structure pytree). ``constraint_fn`` is then
    called as ``constraint_fn(xnew, aux)`` so it can reuse the trial's
    forward instead of running its own.

    ``f0`` (optional): the known loss at ``x`` — skips the search's own
    full-batch evaluation of it. With ``has_aux``, ``aux0`` (the aux at
    ``x``) is required alongside, since it seeds the loop carry and is the
    returned aux when no step is accepted.
    """
    if f0 is not None:
        if has_aux and aux0 is None:
            raise ValueError("f0 with has_aux=True also needs aux0")
        fval, aux_x = f0, aux0
    elif has_aux:
        fval, aux_x = loss_fn(x)
    else:
        fval, aux_x = loss_fn(x), None

    def cond(state):
        k, accepted = state[0], state[1]
        return jnp.logical_and(k < max_backtracks, jnp.logical_not(accepted))

    def body(state):
        k = state[0]
        frac = jnp.asarray(backtrack_factor, jnp.float32) ** k.astype(
            jnp.float32
        )
        # per-leaf dtype-preserving step: keeps the while_loop carry dtypes
        # identical to the input x (which may be bf16 or mixed-dtype)
        xnew = jax.tree_util.tree_map(
            lambda a, s: a + jnp.asarray(frac, a.dtype) * s, x, fullstep
        )
        if has_aux:
            newfval, aux = loss_fn(xnew)
        else:
            newfval, aux = loss_fn(xnew), None
        actual_improve = fval - newfval
        expected_improve = expected_improve_rate * frac
        ratio = actual_improve / expected_improve
        ok = jnp.logical_and(ratio > accept_ratio, actual_improve > 0.0)
        if constraint_fn is not None:
            ok = jnp.logical_and(
                ok,
                constraint_fn(xnew, aux) if has_aux else constraint_fn(xnew),
            )
        out = (k + 1, ok, xnew, newfval, frac)
        return out + (aux,) if has_aux else out

    k0 = jnp.asarray(0, jnp.int32)
    init = (k0, jnp.asarray(False), x, fval, jnp.asarray(0.0, jnp.float32))
    if has_aux:
        init = init + (aux_x,)
    final = lax.while_loop(cond, body, init)
    accepted, xcand, fcand, frac = final[1], final[2], final[3], final[4]
    x_out = tree_where(accepted, xcand, x)
    aux_out = None
    if has_aux:
        aux_out = tree_where(accepted, final[5], aux_x)
    return LinesearchResult(
        x=x_out,
        success=accepted,
        step_fraction=jnp.where(accepted, frac, 0.0),
        loss=jnp.where(accepted, fcand, fval),
        aux=aux_out,
        # the loop counter at exit IS the number of trials evaluated —
        # free observability, no extra computation
        trials=final[0],
    )
