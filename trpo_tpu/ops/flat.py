"""Flat-parameter utilities.

The reference moves all second-order quantities through a single flat vector:
``GetFlat`` / ``SetFromFlat`` build concat/slice+assign graphs over TF
variables (``utils.py:125-158``), ``flatgrad`` concat-reshapes ``tf.gradients``
output (``utils.py:119-122``), with ``var_shape`` / ``numel`` as helpers
(``utils.py:108-116``). In JAX the whole machinery is ``ravel_pytree``: params
are an immutable pytree, so "SetFromFlat" is just the unravel closure — no
assign ops, no device round trip, and it composes with ``jit`` / ``grad``.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

__all__ = ["flatten_params", "flat_grad", "var_shapes", "numel"]


def flatten_params(params) -> Tuple[jax.Array, Callable]:
    """Return ``(flat, unravel)``.

    ``flat`` is the 1-D fp32 concatenation of all leaves (ref ``GetFlat``,
    ``utils.py:151-158``); ``unravel(flat)`` rebuilds the pytree (ref
    ``SetFromFlat``, ``utils.py:125-149``) — functionally, with no mutation.
    """
    return ravel_pytree(params)


def flat_grad(fn: Callable, params) -> jax.Array:
    """Flat gradient of a scalar function of a pytree (ref ``flatgrad``,
    ``utils.py:119-122``)."""
    return ravel_pytree(jax.grad(fn)(params))[0]


def var_shapes(params):
    """Static shapes of every leaf (ref ``var_shape``, ``utils.py:108-112``).

    JAX shapes are always static under ``jit`` tracing, so the reference's
    "shape function not fully known" assert has no analogue."""
    return [leaf.shape for leaf in jax.tree_util.tree_leaves(params)]


def numel(params) -> int:
    """Total element count across the pytree (ref ``numel``,
    ``utils.py:114-116``)."""
    return sum(
        int(jnp.size(leaf)) for leaf in jax.tree_util.tree_leaves(params)
    )
