"""Pytree vector-space helpers for the natural-gradient solve.

The reference's second-order machinery is flat-vector in / flat-vector out
(``GetFlat``/``SetFromFlat``/``flatgrad``, SURVEY §1) — and this framework
keeps that contract in ``ops/flat.py``. But flattening has a cost on a
tensor-sharded mesh: ``ravel_pytree`` concatenates every leaf into ONE
array, which forces an all-gather of model-sharded parameters. These
helpers let CG / FVP / line search run directly on parameter pytrees, so a
``"model"``-sharded layout flows through the whole solve with XLA inserting
only the collectives the math needs (scalar psums for the dot products).

Generic tree arithmetic delegates to ``optax.tree_utils`` (already a
dependency). The ones defined here exist for solver-specific semantics the
optax versions don't give: **fp32 accumulation** of the dot products and
norms regardless of leaf dtype (the solve is fp32-only — see ``ops/cg.py``)
and an fp32 cast helper.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax.tree_utils as _otu
from optax.tree_utils import (  # noqa: F401  (re-exported)
    tree_sub,
    tree_where,
    tree_zeros_like,
)

# optax renamed these across releases (0.2.x: tree_add_scalar_mul /
# tree_scalar_mul; later: tree_add_scale / tree_scale). Resolve whichever
# the installed version exports so the solver does not chase optax's API.
_optax_add_scaled = getattr(
    _otu, "tree_add_scale", getattr(_otu, "tree_add_scalar_mul", None)
)
_optax_tree_scale = getattr(
    _otu, "tree_scale", getattr(_otu, "tree_scalar_mul", None)
)


def tree_add_scaled(x, alpha, y):
    """``x + alpha · y`` leafwise (CG's axpy step)."""
    if _optax_add_scaled is not None:
        return _optax_add_scaled(x, alpha, y)
    return jax.tree_util.tree_map(lambda a, b: a + alpha * b, x, y)

__all__ = [
    "tree_f32",
    "tree_zeros_like",
    "tree_vdot",
    "tree_norm",
    "tree_add_scaled",
    "tree_scale",
    "tree_sub",
    "tree_where",
]

_map = jax.tree_util.tree_map


def tree_f32(t):
    """Cast every leaf to float32."""
    return _map(lambda x: jnp.asarray(x, jnp.float32), t)


def tree_scale(alpha, t):
    if _optax_tree_scale is not None:
        return _optax_tree_scale(alpha, t)
    return _map(lambda x: alpha * x, t)


def tree_vdot(a, b) -> jax.Array:
    """Σ over leaves of ⟨a_leaf, b_leaf⟩, accumulated in fp32 (unlike
    ``optax.tree_utils.tree_vdot``, which accumulates in the leaf dtype)."""
    dots = _map(
        lambda x, y: jnp.vdot(
            jnp.asarray(x, jnp.float32), jnp.asarray(y, jnp.float32)
        ),
        a,
        b,
    )
    return jax.tree_util.tree_reduce(
        jnp.add, dots, jnp.asarray(0.0, jnp.float32)
    )


def tree_norm(t) -> jax.Array:
    return jnp.sqrt(tree_vdot(t, t))
