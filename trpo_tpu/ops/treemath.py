"""Pytree vector-space helpers for the natural-gradient solve.

The reference's second-order machinery is flat-vector in / flat-vector out
(``GetFlat``/``SetFromFlat``/``flatgrad``, SURVEY §1) — and this framework
keeps that contract in ``ops/flat.py``. But flattening has a cost on a
tensor-sharded mesh: ``ravel_pytree`` concatenates every leaf into ONE
array, which forces an all-gather of model-sharded parameters. These
helpers let CG / FVP / line search run directly on parameter pytrees, so a
``"model"``-sharded layout flows through the whole solve with XLA inserting
only the collectives the math needs (scalar psums for the dot products).

All reductions accumulate in fp32 regardless of leaf dtype (the solve is
fp32-only — see ``ops/cg.py``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "tree_f32",
    "tree_zeros_like",
    "tree_vdot",
    "tree_norm",
    "tree_add_scaled",
    "tree_scale",
    "tree_sub",
    "tree_where",
]

_map = jax.tree_util.tree_map


def tree_f32(t):
    """Cast every leaf to float32."""
    return _map(lambda x: jnp.asarray(x, jnp.float32), t)


def tree_zeros_like(t):
    return _map(jnp.zeros_like, t)


def tree_vdot(a, b) -> jax.Array:
    """Σ over leaves of ⟨a_leaf, b_leaf⟩, accumulated in fp32."""
    dots = _map(
        lambda x, y: jnp.vdot(
            jnp.asarray(x, jnp.float32), jnp.asarray(y, jnp.float32)
        ),
        a,
        b,
    )
    return jax.tree_util.tree_reduce(jnp.add, dots, jnp.asarray(0.0, jnp.float32))


def tree_norm(t) -> jax.Array:
    return jnp.sqrt(tree_vdot(t, t))


def tree_add_scaled(x, alpha, y):
    """``x + alpha · y`` leafwise (alpha a scalar)."""
    return _map(lambda a, b: a + alpha * b, x, y)


def tree_scale(alpha, t):
    return _map(lambda x: alpha * x, t)


def tree_sub(a, b):
    return _map(lambda x, y: x - y, a, b)


def tree_where(pred, a, b):
    """Leafwise ``jnp.where(pred, a, b)`` for a scalar predicate."""
    return _map(lambda x, y: jnp.where(pred, x, y), a, b)
