"""Numeric / optimizer ops: the TPU-native realization of the reference's
``utils.py`` boundary (SURVEY §1: flat-vector in, flat-vector out)."""

from trpo_tpu.ops.flat import (  # noqa: F401
    flatten_params,
    flat_grad,
    var_shapes,
    numel,
)
from trpo_tpu.ops.returns import (  # noqa: F401
    discount,
    discounted_returns_segmented,
    gae_advantages,
    gae_from_next_values,
)
from trpo_tpu.ops.cg import conjugate_gradient  # noqa: F401
from trpo_tpu.ops.precond import (  # noqa: F401
    hutchinson_diag,
    hutchinson_diag_inv,
)
from trpo_tpu.ops.linesearch import backtracking_linesearch  # noqa: F401
from trpo_tpu.ops.fvp import (  # noqa: F401
    make_fvp,
    make_ggn_fvp,
    materialize_fisher,
)
from trpo_tpu.ops.fused_fvp import (  # noqa: F401
    fused_fvp_supported,
    make_fused_gaussian_mlp_fvp,
)
