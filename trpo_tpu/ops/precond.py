"""Diagonal (Jacobi) preconditioning for the natural-gradient solve.

Why it exists (VERDICT r3 item 2): the reference runs CG at a fixed 10
iterations with constant damping (``utils.py:185-201``,
``trpo_inksci.py:124-126``), which is fine early in training — but the
flagship Humanoid evidence run's CG residual grew from 5e-3 to 11.8 over
2417 iterations as the policy sharpened. A shrinking Gaussian ``log_std``
multiplies the mean-head rows of the Fisher by ``1/σ²`` while torso blocks
stay O(1), so the ill-conditioning is dominated by per-coordinate SCALE
spread — exactly what a diagonal preconditioner removes.

The diagonal is estimated matrix-free with Hutchinson probes: for Rademacher
``v`` (entries ±1), ``E[v ⊙ Av] = diag(A)``, so ``K`` probes cost ``K``
extra Fisher-vector products per update (vs ``cg_iters+1`` for the solve
itself) and reuse the same jitted FVP operator — sharded operators stay
sharded; no new collectives. The estimate is clipped below at the damping
λ (``diag(F + λI) ≥ λ`` exactly), which also absorbs probe noise on
near-zero curvature coordinates.

Probe keys are deterministic (a fixed fold of a caller-supplied key), so
updates stay bit-reproducible run-to-run.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

from typing import NamedTuple

from trpo_tpu.ops.treemath import tree_f32, tree_zeros_like

__all__ = [
    "PrecondState",
    "apply_gaussian_head_block_inv",
    "gaussian_head_gram",
    "head_gram_eigh",
    "hutchinson_diag",
    "hutchinson_diag_inv",
    "init_gaussian_head_precond",
    "make_gaussian_head_block_inv",
]


class PrecondState(NamedTuple):
    """Amortized head-block preconditioner factors carried across updates
    (``TRPOConfig.precond_refresh_every > 1`` — VERDICT r5 item 4: the
    per-update ``eigh`` cost +19% wall; the torso-activation Gram it
    factors drifts slowly, so refreshing every k updates keeps the
    solver-hygiene wins at ~1/k of the cost, K-FAC-style).

    ``age`` counts updates since initialization; the factors are
    recomputed (inside a ``lax.cond``, so a stale update pays neither the
    torso forward nor the eigh) whenever ``age % refresh_every == 0`` —
    age 0 always refreshes, so zero-initialized factors are never used.
    Staleness is safe: any SPD map is a valid CG preconditioner (it moves
    the convergence rate, never the solution), and the log-std / damping
    dependent parts of the inverse are closed-form and applied FRESH every
    update (:func:`apply_gaussian_head_block_inv`).
    """

    u: jax.Array      # (H+1, H+1) eigenvectors of the head Gram S̃
    s_eig: jax.Array  # (H+1,) eigenvalues, clamped ≥ 0
    age: jax.Array    # int32 scalar — updates since init


def _rademacher_like(key: jax.Array, like: Any) -> Any:
    """A ±1 probe pytree shaped like ``like`` (f32), one subkey per leaf."""
    leaves, treedef = jax.tree_util.tree_flatten(like)
    keys = jax.random.split(key, len(leaves))
    probes = [
        jax.random.rademacher(k, jnp.shape(x), jnp.float32)
        for k, x in zip(keys, leaves)
    ]
    return jax.tree_util.tree_unflatten(treedef, probes)


def hutchinson_diag(
    f_Av: Callable[[Any], Any],
    like: Any,
    n_probes: int,
    key: jax.Array,
) -> Any:
    """Estimate ``diag(A)`` of the SPD operator ``f_Av`` matrix-free.

    ``like`` fixes the domain pytree (a flat vector or a params pytree —
    the operator is domain-polymorphic like everything in ``ops/``). For a
    DIAGONAL ``A`` a single probe is already exact (``v ⊙ Av = v² ⊙ diag =
    diag``); off-diagonal mass decays as ``1/√n_probes``. Runs as a
    ``fori_loop`` so probe count does not multiply live memory.
    """
    if n_probes < 1:
        raise ValueError(f"n_probes must be >= 1, got {n_probes}")
    like = tree_f32(like)

    def body(i, acc):
        v = _rademacher_like(jax.random.fold_in(key, i), like)
        av = tree_f32(f_Av(v))
        return jax.tree_util.tree_map(
            lambda a, vv, avv: a + vv * avv, acc, v, av
        )

    total = lax.fori_loop(0, n_probes, body, tree_zeros_like(like))
    return jax.tree_util.tree_map(lambda t: t / n_probes, total)


def hutchinson_diag_inv(
    f_Av: Callable[[Any], Any],
    like: Any,
    n_probes: int,
    key: jax.Array,
    floor: jax.Array | float,
) -> Any:
    """``M⁻¹ = 1 / max(diag-estimate, floor)`` — the Jacobi preconditioner
    pytree :func:`trpo_tpu.ops.cg.conjugate_gradient` takes as ``M_inv``.

    ``floor`` must be positive; for the damped Fisher ``F + λI`` pass
    ``λ`` (the true diagonal is ≥ λ, so flooring there only removes probe
    noise, never information).
    """
    diag = hutchinson_diag(f_Av, like, n_probes, key)
    floor = jnp.asarray(floor, jnp.float32)
    return jax.tree_util.tree_map(
        lambda d: 1.0 / jnp.maximum(d, floor), diag
    )


def gaussian_head_gram(policy_apply_net, net_params, obs, weight):
    """The bias-augmented, weight-normalized activation second moment
    ``S̃ = h̃ᵀ diag(wₙ) h̃`` over ``h̃ = [h, 1]`` — the ONLY part of the
    Gaussian-head Fisher block that depends on the torso params and the
    batch, hence the only part the amortized refresh must recompute.
    ``policy_apply_net(net_params, obs)`` must return the LAST HIDDEN
    activation ``h`` (B, H); returns ``S̃`` as (H+1, H+1) f32."""
    h = policy_apply_net(net_params, obs)
    w = weight.reshape(-1).astype(jnp.float32)
    sum_w = jnp.maximum(jnp.sum(w), 1.0)
    wn = w / sum_w
    h1 = jnp.concatenate(
        [jnp.asarray(h, jnp.float32), jnp.ones((h.shape[0], 1))], axis=1
    )
    return (h1 * wn[:, None]).T @ h1                   # (H+1, H+1)


def head_gram_eigh(S):
    """``(s_eig, U)`` of the head Gram — a single (H+1)² symmetric
    eigendecomposition, f32, traced INTO the update program so it runs on
    the device backend the solve runs on (no host callback). Eigenvalues
    are clamped ≥ 0 (SPD guard against f32 roundoff)."""
    s_eig, U = jnp.linalg.eigh(jnp.asarray(S, jnp.float32))
    return jnp.maximum(s_eig, 0.0), U


def init_gaussian_head_precond(params) -> PrecondState:
    """Zero-initialized :class:`PrecondState` for a plain-MLP Gaussian
    policy's params pytree (``{"net", "log_std"}``). ``age`` starts at 0,
    so the first update always refreshes — the zero factors are never
    applied."""
    H = params["net"]["layers"][-1]["w"].shape[0]
    return PrecondState(
        u=jnp.zeros((H + 1, H + 1), jnp.float32),
        s_eig=jnp.zeros((H + 1,), jnp.float32),
        age=jnp.asarray(0, jnp.int32),
    )


def apply_gaussian_head_block_inv(
    s_eig, U, weight, log_std, damping, unravel=None
):
    """Close over ``(s_eig, U)`` (possibly stale — see
    :class:`PrecondState`) and the CURRENT log-std / damping / weights,
    returning the callable ``r ↦ M⁻¹r`` for ``conjugate_gradient``.

    The split matters for the amortization: ``m = e^{-2σ}`` and λ move
    every update (σ is a trained parameter; λ may be adaptive) but enter
    the inverse in closed form — only the Gram factors are expensive, and
    only they are cached.
    """
    w = weight.reshape(-1).astype(jnp.float32)
    sum_w = jnp.maximum(jnp.sum(w), 1.0)
    wn_sum = jnp.sum(w / sum_w)
    m = jnp.exp(-2.0 * jnp.asarray(log_std, jnp.float32))
    damping = jnp.asarray(damping, jnp.float32)
    # floor keeps the map SPD and finite even at damping 0 with a
    # rank-deficient S̃ (curvature batch < H+1): zero-curvature modes
    # pass through at a huge-but-finite scale instead of going inf/NaN
    denom = jnp.maximum(
        s_eig[:, None] * m[None, :] + damping, 1e-12
    )                                                  # (H+1, A)
    sigma_denom = jnp.maximum(2.0 * wn_sum + damping, 1e-12)

    def apply_tree(r):
        layers = r["net"]["layers"]
        head = layers[-1]
        X = jnp.concatenate(
            [
                jnp.asarray(head["w"], jnp.float32),
                jnp.asarray(head["b"], jnp.float32)[None, :],
            ],
            axis=0,
        )
        Y = U @ ((U.T @ X) / denom)
        new_head = {"w": Y[:-1, :], "b": Y[-1, :]}
        new_layers = list(layers[:-1]) + [new_head]
        return {
            "net": {**r["net"], "layers": new_layers},
            "log_std": jnp.asarray(r["log_std"], jnp.float32)
            / sigma_denom,
        }

    if unravel is None:
        return apply_tree

    from trpo_tpu.ops.flat import flatten_params

    def apply_flat(r_flat):
        return flatten_params(apply_tree(unravel(r_flat)))[0]

    return apply_flat


def make_gaussian_head_block_inv(
    policy_apply_net, net_params, obs, weight, log_std, damping,
    unravel=None,
):
    """EXACT inverse of the damped Fisher's Gaussian-head block, identity
    on the torso — a structured (per-layer block) preconditioner for CG
    (round-5, VERDICT r4 item 7).

    For a linear head ``mean = h W + b`` with state-independent
    ``log_std``, the (W, b) Fisher block is exactly ``S̃ ⊗ diag(m)``
    where ``S̃ = h̃ᵀ diag(wₙ) h̃`` over ``h̃ = [h, 1]`` (the bias
    column absorbed) and ``m = e^{-2σ}``, and the log-std block is
    exactly ``2·Σwₙ·I`` — so ``(F + λI)⁻¹`` restricted to the head is a
    closed form via one ``eigh`` of the (H+1)² activation second moment
    (``ops/fvp.py`` derives the same structure for the fused kernel).
    Late-training sharpening (σ↓) blows the head curvature up ∝ 1/σ²,
    which is exactly the block this inverts; the torso (whose
    off-diagonal mass defeated the Jacobi diagonal —
    ``scripts/late_cg_r04_cpu.json``) is left untouched.

    This is the per-update (refresh-every-1) composition of
    :func:`gaussian_head_gram` → :func:`head_gram_eigh` →
    :func:`apply_gaussian_head_block_inv`; the amortized path in
    ``trpo.py`` calls the pieces with the Gram/eigh under a refresh
    ``lax.cond``. Returns a CALLABLE ``r ↦ M⁻¹r`` over flat vectors
    (``unravel`` given) or param pytrees, for
    ``conjugate_gradient(..., M_inv=...)``.
    """
    S = gaussian_head_gram(policy_apply_net, net_params, obs, weight)
    s_eig, U = head_gram_eigh(S)
    return apply_gaussian_head_block_inv(
        s_eig, U, weight, log_std, damping, unravel=unravel
    )
