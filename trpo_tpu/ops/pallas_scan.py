"""Pallas TPU kernel for the segmented reverse affine scan.

Both return/advantage computations in this framework reduce to one
recurrence over time-major ``(T, N)`` tensors (``ops/returns.py``):

    y_t = x_t + c_t · y_{t+1},   y_T = 0

(the reference computes the ``c_t = γ`` special case on host with a SciPy
IIR filter, ``utils.py:14-16``). The XLA path implements it as an
``associative_scan`` — O(log T) depth but ~log T passes over the data in
HBM. This kernel is the bandwidth-optimal alternative: ONE pass, time
sequential in-register, envs vectorized across the 128-wide lane dimension,
grid-parallel over env blocks. T·N·4-byte blocks stream HBM→VMEM once and
results stream back once.

Layout notes (pallas_guide.md): the env axis is the lane axis (last dim,
128); each grid program owns a ``(T, BLOCK_N)`` block resident in VMEM
(T=1000 → ~0.5 MB per operand per block, well under the ~16 MB budget); the
time loop is a ``fori_loop`` carrying one ``(1, BLOCK_N)`` row.

Used via ``ops.returns.gae_from_next_values(..., backend="pallas")`` /
``discounted_returns_segmented(..., backend="pallas")``; ``interpret=True``
(automatic off-TPU) runs the same kernel through the Pallas interpreter so
CPU tests cover it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

__all__ = ["reverse_affine_scan_pallas"]


def _scan_kernel(c_ref, x_ref, y_ref):
    """y[t] = x[t] + c[t] * y[t+1], computed t = T-1 … 0 in one pass."""
    T = x_ref.shape[0]

    def body(i, carry):
        t = T - 1 - i
        y = x_ref[pl.ds(t, 1), :] + c_ref[pl.ds(t, 1), :] * carry
        y_ref[pl.ds(t, 1), :] = y
        return y

    lax.fori_loop(
        0, T, body, jnp.zeros((1, x_ref.shape[1]), x_ref.dtype)
    )


@functools.partial(jax.jit, static_argnames=("block_n", "interpret"))
def _scan_call(coeffs, x, block_n: int, interpret: bool):
    T, N = x.shape
    pad = (-N) % block_n
    if pad:
        coeffs = jnp.pad(coeffs, ((0, 0), (0, pad)))
        x = jnp.pad(x, ((0, 0), (0, pad)))
    n_padded = N + pad

    spec = pl.BlockSpec((T, block_n), lambda i: (0, i))
    out = pl.pallas_call(
        _scan_kernel,
        grid=(n_padded // block_n,),
        in_specs=[spec, spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((T, n_padded), x.dtype),
        interpret=interpret,
    )(coeffs, x)
    return out[:, :N]


def reverse_affine_scan_pallas(
    coeffs: jax.Array,
    x: jax.Array,
    block_n: int = 128,
    interpret: bool | None = None,
) -> jax.Array:
    """Single-pass ``y_t = x_t + c_t·y_{t+1}`` over ``(T, N)`` tensors.

    Drop-in for ``ops.returns._reverse_affine_scan`` (same math, one HBM
    pass instead of an associative scan's log-T passes). ``interpret``
    defaults to True off-TPU so the kernel is testable anywhere.
    """
    x = jnp.asarray(x, jnp.float32)
    coeffs = jnp.asarray(coeffs, jnp.float32)
    if x.ndim != 2:
        raise ValueError(f"expected (T, N) tensors, got shape {x.shape}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _scan_call(coeffs, x, block_n, interpret)
