"""Discounted returns and generalized advantage estimation as device scans.

The reference computes returns with a host-side SciPy IIR filter
(``discount``, ``utils.py:14-16``) applied per episode, and advantages as
plain ``returns − baseline`` (``trpo_inksci.py:104-105``) — no GAE. Here both
are ``lax.scan`` / ``lax.associative_scan`` programs over fixed-length
``(T, N)`` trajectory tensors with a ``done`` mask handling episode
boundaries, which is the long-trajectory ("sequence-parallel") analogue this
problem actually admits (SURVEY §5): static shapes, O(log T) depth on device,
batched over N envs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "discount",
    "discounted_returns_segmented",
    "gae_advantages",
    "gae_from_next_values",
]


def _affine_combine(right, left):
    """Monoid op for reverse affine scans of ``y_t = b_t + a_t · y_{t+1}``.

    With ``reverse=True`` the scan hands us (higher-index block, lower-index
    block); composing outer∘inner gives ``(a_out·a_in, b_out + a_out·b_in)``
    where the lower-index map is the outer one.
    """
    a_in, b_in = right
    a_out, b_out = left
    return a_out * a_in, b_out + a_out * b_in


def _reverse_affine_scan(gammas, x, backend: str = "xla"):
    """``y_t = x_t + γ_t·y_{t+1}``: O(log T)-depth associative scan
    (``backend="xla"``) or the single-HBM-pass Pallas kernel
    (``backend="pallas"``, (T, N) tensors only — see ``ops/pallas_scan.py``).
    """
    if backend == "pallas":
        from trpo_tpu.ops.pallas_scan import reverse_affine_scan_pallas

        return reverse_affine_scan_pallas(gammas, x)
    if backend != "xla":
        raise ValueError(f"unknown backend {backend!r}; have 'xla', 'pallas'")
    _, y = lax.associative_scan(_affine_combine, (gammas, x), reverse=True)
    return y


def discount(x: jax.Array, gamma: float) -> jax.Array:
    """Discounted cumulative sum along axis 0: ``y_t = Σ_k γ^k x_{t+k}``.

    Exact functional replacement for the reference's
    ``scipy.signal.lfilter([1], [1, -gamma], x[::-1])[::-1]``
    (``utils.py:14-16``), as an O(log T) associative scan: the recurrence
    ``y_t = x_t + γ y_{t+1}`` composes as an affine map scanned in reverse.
    """
    x = jnp.asarray(x)
    if not jnp.issubdtype(x.dtype, jnp.floating):
        x = x.astype(jnp.float32)
    return discounted_returns_segmented(x, jnp.zeros_like(x), gamma)


def discounted_returns_segmented(
    rewards: jax.Array, dones: jax.Array, gamma: float, backend: str = "xla"
) -> jax.Array:
    """Per-step discounted return with episode boundaries.

    ``rewards``, ``dones``: ``(T, ...)`` with dones ∈ {0,1} marking the last
    step of an episode. The discount factor is zeroed across a boundary, so
    returns never leak between episodes packed into one fixed-length tensor.
    """
    rewards = jnp.asarray(rewards)
    if not jnp.issubdtype(rewards.dtype, jnp.floating):
        rewards = rewards.astype(jnp.float32)
    dones = jnp.asarray(dones).astype(rewards.dtype)
    gammas = gamma * (1.0 - dones)
    return _reverse_affine_scan(gammas, rewards, backend)


def gae_from_next_values(
    rewards: jax.Array,
    values: jax.Array,
    next_values: jax.Array,
    terminated: jax.Array,
    done: jax.Array,
    gamma: float,
    lam: float,
    backend: str = "xla",
) -> tuple[jax.Array, jax.Array]:
    """GAE(λ) with explicit per-step successor values and a split
    terminated/done mask — the general form for packed vectorized rollouts.

    ``terminated`` marks true terminal states (no bootstrap: the TD target
    drops ``γ·V(s')``); ``done`` marks every episode boundary including
    time-limit truncations (the λ-accumulation cut). A truncated step thus
    still bootstraps through ``next_values`` — the fix for the reference's
    lost-final-state rollout bug (``utils.py:44``, SURVEY §7 "hard parts").

    Returns ``(advantages, value_targets)``, both shaped like ``rewards``.
    """
    rewards = jnp.asarray(rewards)
    terminated = jnp.asarray(terminated).astype(rewards.dtype)
    done = jnp.asarray(done).astype(rewards.dtype)
    deltas = rewards + gamma * (1.0 - terminated) * next_values - values
    adv = _reverse_affine_scan(gamma * lam * (1.0 - done), deltas, backend)
    return adv, adv + values


def gae_advantages(
    rewards: jax.Array,
    values: jax.Array,
    dones: jax.Array,
    last_values: jax.Array,
    gamma: float,
    lam: float,
    terminated: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array]:
    """GAE(λ) advantages and value targets over ``(T, N)`` tensors.

    Convenience form of :func:`gae_from_next_values` deriving successor
    values from ``values`` shifted one step, with ``last_values`` (``(N,)``)
    bootstrapping the state after step T-1. ``terminated`` defaults to
    ``dones`` (every boundary treated as terminal — correct when no
    mid-batch truncations exist); pass it separately when packing truncated
    episodes. With ``lam=1`` and a zero baseline this reduces to the
    reference's plain discounted-returns advantage
    (``trpo_inksci.py:104-105``).

    Returns ``(advantages, value_targets)``, both ``(T, N)``.
    """
    rewards = jnp.asarray(rewards)
    dones = jnp.asarray(dones).astype(rewards.dtype)
    if terminated is None:
        terminated = dones
    next_values = jnp.concatenate([values[1:], last_values[None]], axis=0)
    return gae_from_next_values(
        rewards, values, next_values, terminated, dones, gamma, lam
    )
