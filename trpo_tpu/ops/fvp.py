"""Fisher-vector products via forward-over-reverse differentiation.

The reference builds the FVP graph with *double reverse-mode backprop*
(``trpo_inksci.py:56-70``): gradient of (gradient-of-KL · tangent), with the
tangent arriving through a placeholder that is sliced and reshaped per
variable (``:58-67``), and damping added host-side per CG iteration
(``:124-126``). The TPU-native formulation (SURVEY §3.4) is
``jvp(grad(kl))`` — forward-mode over the KL gradient — which is cheaper
(one forward tangent pass instead of a second full backprop), more precise,
and composes directly into the jitted CG ``while_loop``. Damping is fused
into the operator, not bolted on by the host.

``kl_firstfixed`` semantics: the KL is taken between the *current* policy and
itself with the first argument's dependence on θ severed (the reference's
``stop_gradient`` at ``trpo_inksci.py:56``). Its Hessian at θ is exactly the
Fisher information matrix, so no explicit "old" distribution is needed.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

__all__ = ["make_fvp", "make_tree_fvp", "materialize_fisher"]


def make_fvp(
    kl_fn: Callable[[jax.Array], jax.Array],
    flat_params: jax.Array,
    damping: float = 0.0,
) -> Callable[[jax.Array], jax.Array]:
    """Return ``v ↦ (F + damping·I) v`` at ``flat_params``.

    ``kl_fn(flat) -> scalar`` must be the mean KL(stop_grad(π_θ) ‖ π_flat)
    over the batch; its Hessian at ``flat_params`` is the Fisher metric.
    The returned operator is pure and jit-traceable — it is *meant* to be
    closed over by :func:`trpo_tpu.ops.conjugate_gradient` inside one XLA
    program (no host round trips, unlike ref ``trpo_inksci.py:124-126``).
    """
    grad_kl = jax.grad(kl_fn)

    def fvp(v: jax.Array) -> jax.Array:
        hv = jax.jvp(grad_kl, (flat_params,), (v,))[1]
        return jnp.asarray(hv, jnp.float32) + damping * v

    return fvp


def make_tree_fvp(
    kl_fn: Callable[[Any], jax.Array],
    params: Any,
    damping: float = 0.0,
) -> Callable[[Any], Any]:
    """``make_fvp`` in the parameter-pytree domain: ``v ↦ (F + λI)v`` where
    ``v`` shares ``params``'s pytree structure.

    Same ``jvp∘grad`` math as :func:`make_fvp` without flattening — so a
    tensor-sharded (``"model"``-axis) parameter layout is preserved through
    the operator, and with it through the CG iterates that call it
    (``ops/cg.py`` is pytree-polymorphic). This is what makes the
    natural-gradient solve tensor-parallel: ``ravel_pytree`` would
    all-gather every sharded leaf into one replicated vector.
    """
    grad_kl = jax.grad(kl_fn)

    def fvp(v: Any) -> Any:
        hv = jax.jvp(grad_kl, (params,), (v,))[1]
        return jax.tree_util.tree_map(
            lambda h, t: jnp.asarray(h, jnp.float32) + damping * t, hv, v
        )

    return fvp


def materialize_fisher(
    kl_fn: Callable[[jax.Array], jax.Array], flat_params: jax.Array
) -> jax.Array:
    """Dense Fisher matrix (Hessian of ``kl_fn``) — test/diagnostic only.

    O(P²); used by the unit tests to validate :func:`make_fvp` against an
    explicitly materialized Fisher on tiny networks (SURVEY §4).
    """
    return jax.hessian(kl_fn)(flat_params)
