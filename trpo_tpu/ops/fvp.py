"""Fisher-vector products via forward-over-reverse differentiation.

The reference builds the FVP graph with *double reverse-mode backprop*
(``trpo_inksci.py:56-70``): gradient of (gradient-of-KL · tangent), with the
tangent arriving through a placeholder that is sliced and reshaped per
variable (``:58-67``), and damping added host-side per CG iteration
(``:124-126``). The TPU-native formulation (SURVEY §3.4) is
``jvp(grad(kl))`` — forward-mode over the KL gradient — which is cheaper
(one forward tangent pass instead of a second full backprop), more precise,
and composes directly into the jitted CG ``while_loop``. Damping is fused
into the operator, not bolted on by the host.

``kl_firstfixed`` semantics: the KL is taken between the *current* policy and
itself with the first argument's dependence on θ severed (the reference's
``stop_gradient`` at ``trpo_inksci.py:56``). Its Hessian at θ is exactly the
Fisher information matrix, so no explicit "old" distribution is needed.

Precision: the operators here are dtype-agnostic — the matvec's matmul
dtype is whatever the ``apply_fn``/``kl_fn`` closure computes in (the
solver precision ladder's ``cfg.fvp_dtype="bf16"`` passes a
``Policy.apply_cast`` closure), while every operator OUTPUT is cast f32
and damping is added in f32, so ``ops/cg.py``'s all-f32 accumulator
contract holds under any matvec dtype.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

__all__ = [
    "make_fvp",
    "make_ggn_fvp",
    "make_tree_fvp",
    "materialize_fisher",
]


def make_fvp(
    kl_fn: Callable[[jax.Array], jax.Array],
    flat_params: jax.Array,
    damping: float = 0.0,
) -> Callable[[jax.Array], jax.Array]:
    """Return ``v ↦ (F + damping·I) v`` at ``flat_params``.

    ``kl_fn(flat) -> scalar`` must be the mean KL(stop_grad(π_θ) ‖ π_flat)
    over the batch; its Hessian at ``flat_params`` is the Fisher metric.
    The returned operator is pure and jit-traceable — it is *meant* to be
    closed over by :func:`trpo_tpu.ops.conjugate_gradient` inside one XLA
    program (no host round trips, unlike ref ``trpo_inksci.py:124-126``).
    """
    grad_kl = jax.grad(kl_fn)

    def fvp(v: jax.Array) -> jax.Array:
        hv = jax.jvp(grad_kl, (flat_params,), (v,))[1]
        return jnp.asarray(hv, jnp.float32) + damping * v

    return fvp


def make_tree_fvp(
    kl_fn: Callable[[Any], jax.Array],
    params: Any,
    damping: float = 0.0,
) -> Callable[[Any], Any]:
    """``make_fvp`` in the parameter-pytree domain: ``v ↦ (F + λI)v`` where
    ``v`` shares ``params``'s pytree structure.

    Same ``jvp∘grad`` math as :func:`make_fvp` without flattening — so a
    tensor-sharded (``"model"``-axis) parameter layout is preserved through
    the operator, and with it through the CG iterates that call it
    (``ops/cg.py`` is pytree-polymorphic). This is what makes the
    natural-gradient solve tensor-parallel: ``ravel_pytree`` would
    all-gather every sharded leaf into one replicated vector.
    """
    grad_kl = jax.grad(kl_fn)

    def fvp(v: Any) -> Any:
        hv = jax.jvp(grad_kl, (params,), (v,))[1]
        return jax.tree_util.tree_map(
            lambda h, t: jnp.asarray(h, jnp.float32) + damping * t, hv, v
        )

    return fvp


def make_ggn_fvp(
    apply_fn: Callable[[Any], Any],
    fisher_weight: Callable[[Any, Any], Any],
    x0: Any,
    weight: jax.Array,
    damping: float = 0.0,
) -> Callable[[Any], Any]:
    """Gauss-Newton form of the Fisher-vector product:
    ``F·v = Jᵀ (M · (J v))`` with ``J`` the Jacobian of the dist params
    w.r.t. the optimization variable and ``M`` the dist-space KL Hessian
    (``dist.fisher_weight``). For exponential-family heads this is
    EXACTLY the Fisher/KL-Hessian the reference differentiates twice for
    (``trpo_inksci.py:56-70``) — same math, different factorization.

    Why it exists: the ``jvp∘grad`` form (:func:`make_fvp`) replays a
    tangent sweep through the forward *and backward* graph every CG
    iteration; this form replays a forward tangent plus a plain backward
    — same FLOPs (~3 forward-equivalents) but a better memory-access
    pattern. Measured on the v5e at the Humanoid operating point
    (376→256²→17, batch 50k, bf16 matmuls): **0.44 vs 0.83 ms/iter,
    1.9×**, solution cosine 1.0 (``scripts/explore_ggn.py``).

    ``apply_fn(x) -> dist_params`` must close over the batch obs;
    ``weight`` is the per-sample weight column (padding-exact weighted
    mean, broadcast against the dist leaves' trailing axis). ``x0`` may
    be a flat vector or a params pytree — the operator is domain-
    polymorphic like everything in ``ops/``. Linearization residuals are
    computed once (``jax.linearize`` / ``jax.vjp`` outside the caller's
    CG loop) and reused across iterations."""
    d0, f_jvp = jax.linearize(apply_fn, x0)
    # transpose the ONE linearization instead of a second jax.vjp trace —
    # same pullback, and eager callers don't pay a duplicate primal
    # forward (inside jit XLA CSE would dedup it anyway)
    f_vjp = jax.linear_transpose(f_jvp, x0)
    d0 = jax.lax.stop_gradient(d0)
    w_norm = weight / jnp.maximum(jnp.sum(weight), 1.0)

    def fvp(v: Any) -> Any:
        d = f_jvp(v)
        m = fisher_weight(d0, d)
        m = jax.tree_util.tree_map(
            lambda t: jnp.asarray(t, jnp.float32)
            * jnp.expand_dims(w_norm, -1),
            m,
        )
        hv = f_vjp(m)[0]
        return jax.tree_util.tree_map(
            lambda h, t: jnp.asarray(h, jnp.float32) + damping * t, hv, v
        )

    return fvp


def materialize_fisher(
    kl_fn: Callable[[jax.Array], jax.Array], flat_params: jax.Array
) -> jax.Array:
    """Dense Fisher matrix (Hessian of ``kl_fn``) — test/diagnostic only.

    O(P²); used by the unit tests to validate :func:`make_fvp` against an
    explicitly materialized Fisher on tiny networks (SURVEY §4).
    """
    return jax.hessian(kl_fn)(flat_params)
