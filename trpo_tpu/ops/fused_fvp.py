"""Fused Gauss-Newton Fisher-vector product — one Pallas TPU kernel.

The XLA Gauss-Newton FVP (``ops/fvp.make_ggn_fvp``) lowers to a chain of
~10 separate matmul kernels per CG iteration (tangent forward, dist-space
weighting, backward dgrads + wgrads).  At the flagship Humanoid shape
(obs 376 → 256 → 256 → act 17, batch 50k) the round-4 orientation
microbench (``scripts/width512_r04.json``) showed that chain is
*HBM-bandwidth-bound*, not MXU-bound: every op re-reads a ``(B, 256)``
activation or tangent from HBM (25.6 MB each at bf16), and the four
17-wide action-head matmuls — 0.9% of the FLOPs — run at ~12-14 TF/s
because each is a full HBM pass over a 25.6 MB operand to touch a
``(B, 17)`` result (measured ~870 GB/s: at the bandwidth roofline).

This module fuses the ENTIRE operator into one kernel: the batch streams
through VMEM in row blocks; for each block the kernel runs the tangent
forward sweep, the diagonal-Gaussian Fisher weighting, and the full
backward sweep (dgrad + wgrad for every layer) without any intermediate
ever touching HBM, accumulating the parameter-space cotangents in VMEM
across the (sequential) grid.  Per CG iteration the only HBM traffic is
one read of ``obs`` and of each stored activation (~89 MB at the
flagship shape vs ~350 MB unfused) and a parameter-sized write — the
operator flips from bandwidth-bound to MXU-bound.

Scope (the fast path is *chosen*, never silently wrong): MLP torso with
an activation whose derivative is expressible from its output (tanh,
relu, elu — see ``_ACT_DERIV``), diagonal-Gaussian head with
state-independent ``log_std``.  That is exactly the BASELINE.json MuJoCo
family (the reference's own network shape, ``trpo_inksci.py:38-40``,
generalized).  Everything else — conv/recurrent/MoE policies,
categorical heads, tensor-sharded pytree solves — uses the XLA GGN path,
which remains the general contract.

Math (identical to ``make_ggn_fvp``; same Fisher the reference builds by
double backprop, ``trpo_inksci.py:56-70``):

    F·v = Jᵀ M J v + λv,   J = ∂(dist params)/∂θ at θ₀,
    M   = diag(wᵢ/Σw) ⊗ [e^{-2σ} on the mean block, 2·I on log σ]

The log-std block never enters the kernel: with state-independent
``log_std`` its J is the identity broadcast, so its cotangent is the
closed form ``2·(Σwₙ)·v_σ`` — zero matmuls.

Layout notes: the action head is zero-padded to the 128-lane width
inside the kernel (padding *columns* of ``W_head`` and of ``M`` — zero
Fisher weight on pad lanes makes the padding exact, not approximate);
the batch is zero-padded to the row-block size with zero sample weights
(every padded row's Fisher weight is zero, so its contribution vanishes
identically).  Accumulation is fp32 everywhere; matmul operands are the
configured compute dtype (bf16 on TPU), matching the XLA path's
precision contract (``models/mlp.py``).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = [
    "fused_fvp_supported",
    "make_fused_gaussian_mlp_fvp",
    "probe_compile_fused_fvp",
]

_LANE = 128  # MXU/VPU lane width: minor-dim tile for every TPU generation


def _ceil_to(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


# Activation derivatives expressed from the activation OUTPUT h = act(x):
# the kernel only stores post-activation values (same arrays the forward
# pass produces), so only output-expressible activations are eligible.
_ACT_DERIV: Dict[str, Callable[[jax.Array], jax.Array]] = {
    "tanh": lambda h: 1.0 - h * h,
    "relu": lambda h: (h > 0.0).astype(jnp.float32),
    "elu": lambda h: jnp.where(h > 0.0, 1.0, h + 1.0),
}


def fused_fvp_supported(activation: str, net_params: Any) -> bool:
    """Whether the fused kernel covers this (activation, torso) pair."""
    if activation not in _ACT_DERIV:
        return False
    try:
        layers = net_params["layers"]
    except (TypeError, KeyError):
        return False
    if not isinstance(layers, (list, tuple)) or len(layers) < 2:
        return False
    for layer in layers:
        try:
            w, _ = layer["w"], layer["b"]
        except (TypeError, KeyError):
            return False
        if getattr(w, "ndim", None) != 2:
            return False
    return True


def _pad2(x: jax.Array, rows: int, cols: int) -> jax.Array:
    """Zero-pad a 2-D array up to (rows, cols)."""
    return jnp.pad(x, ((0, rows - x.shape[0]), (0, cols - x.shape[1])))


# VMEM budget for auto block sizing: ~16 MB/core scoped limit, with
# headroom for the model's context-dependent underestimate — compiler-
# reported flagship footprints: block 4096 → 28.0 MB (26.7 modeled);
# 2048 → 14.2 modeled, fits standalone but hits 17.5 MB inside the full
# fused update's nested while-loops (+23% vs model, the round-5 driver
# OOM). 12 MB keeps the flagship at block 1024 (~7.9 modeled, ~10 real),
# which measures within ~2% of 2048 anyway; pass block_rows explicitly
# to override.
_VMEM_BUDGET = 12 * 2**20


def _block_cost_model(D0p: int, hidden, Ap: int):
    """(fixed_bytes, per_row_bytes) VMEM estimate for the kernel."""
    w_elems = (
        D0p * hidden[0]
        + sum(hidden[k - 1] * hidden[k] for k in range(1, len(hidden)))
        + hidden[-1] * Ap
    )
    # weights + tangents at 2 B (bf16) + f32 cotangent outputs
    fixed = w_elems * (2 + 2 + 4)
    # double-buffered bf16 row blocks (obs + activations + wn) and the
    # live f32 tangent/backward intermediates (~2 row arrays of max width)
    per_row = 4.0 * (D0p + sum(hidden) + Ap) + 8.0 * max(hidden)
    return fixed, per_row


def _auto_block_rows(D0p: int, hidden, Ap: int) -> int:
    fixed, per_row = _block_cost_model(D0p, hidden, Ap)
    for blk in (2048, 1024, 512, 256, 128):
        if fixed + blk * per_row <= _VMEM_BUDGET:
            return blk
    raise ValueError(
        f"fused FVP does not fit VMEM at obs={D0p}, hidden={tuple(hidden)}, "
        f"act={Ap} (estimated {fixed / 2**20:.1f} MB of weights/outputs "
        "alone); use the XLA GGN path"
    )


def _fvp_kernel(n_hidden: int, activation: str, *refs):
    """Kernel body; ``refs`` layout (inputs then outputs):

    inputs:  obs, h_0..h_{L-1}, wn, m,
             W_1..W_{L-1}, Wh,
             V_0..V_{L-1}, Vh,
             vb_0..vb_{L-1}, vbh
    outputs: cW_0..cW_{L-1}, cWh, cb (stacked (L+1, lane-padded max width))
    """
    L = n_hidden
    it = iter(refs)
    obs_ref = next(it)
    h_refs = [next(it) for _ in range(L)]
    wn_ref = next(it)
    m_ref = next(it)
    w_refs = [next(it) for _ in range(L - 1)] + [next(it)]  # W_1..W_{L-1}, Wh
    v_refs = [next(it) for _ in range(L + 1)]               # V_0..V_{L-1}, Vh
    vb_refs = [next(it) for _ in range(L + 1)]              # vb_0.., vbh
    cw_refs = [next(it) for _ in range(L + 1)]              # cW_0.., cWh
    cb_ref = next(it)

    deriv = _ACT_DERIV[activation]
    f32 = jnp.float32
    dot_kw = dict(preferred_element_type=f32)
    cdtype = obs_ref.dtype

    @pl.when(pl.program_id(0) == 0)
    def _init():
        for ref in cw_refs:
            ref[...] = jnp.zeros_like(ref)
        cb_ref[...] = jnp.zeros_like(cb_ref)

    obs = obs_ref[...]
    hs = [r[...] for r in h_refs]
    derivs = [deriv(h.astype(f32)) for h in hs]

    # ---- tangent forward sweep -------------------------------------
    dp = jnp.dot(obs, v_refs[0][...], **dot_kw) + vb_refs[0][...]
    dh = (derivs[0] * dp).astype(cdtype)
    for k in range(1, L):
        dp = (
            jnp.dot(hs[k - 1], v_refs[k][...], **dot_kw)
            + jnp.dot(dh, w_refs[k - 1][...], **dot_kw)
            + vb_refs[k][...]
        )
        dh = (derivs[k] * dp).astype(cdtype)
    d_mean = (
        jnp.dot(dh, w_refs[L - 1][...], **dot_kw)
        + jnp.dot(hs[L - 1], v_refs[L][...], **dot_kw)
        + vb_refs[L][...]
    )

    # ---- dist-space Fisher weighting (padded lanes carry m = 0) ----
    c32 = d_mean * (wn_ref[...] * m_ref[...])
    c = c32.astype(cdtype)

    # ---- backward sweep: head, then torso layers top-down ----------
    cw_refs[L][...] += lax.dot_general(
        hs[L - 1], c, (((0,), (0,)), ((), ())), **dot_kw
    )
    cb_ref[0:1, : c32.shape[1]] += jnp.sum(c32, axis=0, keepdims=True)
    ch = lax.dot_general(c, w_refs[L - 1][...], (((1,), (1,)), ((), ())), **dot_kw)
    for k in range(L - 1, 0, -1):
        g32 = derivs[k] * ch
        g = g32.astype(cdtype)
        cw_refs[k][...] += lax.dot_general(
            hs[k - 1], g, (((0,), (0,)), ((), ())), **dot_kw
        )
        cb_ref[L - k : L - k + 1, : g32.shape[1]] += jnp.sum(
            g32, axis=0, keepdims=True
        )
        ch = lax.dot_general(
            g, w_refs[k - 1][...], (((1,), (1,)), ((), ())), **dot_kw
        )
    g32 = derivs[0] * ch
    g = g32.astype(cdtype)
    cw_refs[0][...] += lax.dot_general(
        obs, g, (((0,), (0,)), ((), ())), **dot_kw
    )
    cb_ref[L : L + 1, : g32.shape[1]] += jnp.sum(g32, axis=0, keepdims=True)


# shape-signature -> None (compiled fine) | failure reason string. One
# probe compile per distinct (backend, activation, dtype, shapes) tuple
# for the process lifetime — selection-time cost is paid once.
_probe_cache: Dict[tuple, Optional[str]] = {}


def probe_compile_fused_fvp(
    net_params: Any,
    obs,
    weight,
    log_std,
    *,
    activation: str,
    compute_dtype,
) -> Optional[str]:
    """Compile the fused kernel for this problem's SHAPES, standalone and
    cached — returns ``None`` when the backend accepts it, else the
    failure reason.

    The trace-time checks (``fused_fvp_supported``, the VMEM cost model)
    cannot see backend-side failures: Mosaic lowering errors and real
    VMEM OOMs surface only when the ENCLOSING jit compiles, long after
    ``fvp_mode="auto"`` committed to the kernel — crashing the training
    step instead of falling back (ADVICE r5). This probe runs
    ``jit(...).lower(...).compile()`` on abstract ``ShapeDtypeStruct``
    inputs (safe to call from inside another trace — nothing traced leaks
    in), so auto mode can demote compile-time failures to an XLA fallback
    at selection time. Any exception is reported, never raised."""
    sds = lambda x: jax.ShapeDtypeStruct(tuple(x.shape), jnp.dtype(x.dtype))
    abs_net = jax.tree_util.tree_map(sds, net_params)
    abs_obs, abs_w, abs_ls = sds(obs), sds(weight), sds(log_std)
    abs_v = {"net": abs_net, "log_std": abs_ls}
    sig = jax.tree_util.tree_structure(abs_net)
    key = (
        jax.default_backend(),
        activation,
        str(jnp.dtype(compute_dtype)),
        str(sig),
        tuple(
            (leaf.shape, str(leaf.dtype))
            for leaf in jax.tree_util.tree_leaves(
                (abs_net, abs_obs, abs_w, abs_ls)
            )
        ),
    )
    if key in _probe_cache:
        return _probe_cache[key]

    def _probe(net, o, w, ls, damping, v):
        return make_fused_gaussian_mlp_fvp(
            net, o, w, ls, damping,
            activation=activation, compute_dtype=compute_dtype,
        )(v)

    try:
        jax.jit(_probe).lower(
            abs_net, abs_obs, abs_w, abs_ls,
            jax.ShapeDtypeStruct((), jnp.float32), abs_v,
        ).compile()
        reason = None
    except Exception as e:  # Mosaic lowering / VMEM OOM / anything else
        reason = f"{type(e).__name__}: {e}"
    _probe_cache[key] = reason
    return reason


def make_fused_gaussian_mlp_fvp(
    net_params: Any,
    obs: jax.Array,
    weight: jax.Array,
    log_std: jax.Array,
    damping,
    *,
    activation: str = "tanh",
    compute_dtype=jnp.bfloat16,
    block_rows: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> Callable[[Any], Any]:
    """Build ``v ↦ (F + λI)v`` as a fused Pallas kernel.

    ``net_params`` is the MLP pytree (``{"layers": [{"w", "b"}, ...]}``);
    the returned operator takes/returns the full policy-param pytree
    structure ``{"net": ..., "log_std": ...}`` (what the flat-domain
    update's ``unravel`` produces).  Setup — forward activations, padded
    operands — runs once at trace time, so inside the fused CG
    ``while_loop`` it is loop-invariant and hoisted, exactly like
    ``make_ggn_fvp``'s ``jax.linearize``.
    """
    if activation not in _ACT_DERIV:
        raise ValueError(
            f"fused FVP supports activations {sorted(_ACT_DERIV)}, "
            f"got {activation!r}"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    layers = net_params["layers"]
    L = len(layers) - 1  # hidden layers
    if L < 1:
        raise ValueError("fused FVP needs at least one hidden layer")
    obs = obs.reshape(obs.shape[0], -1)
    B, D0 = obs.shape
    act_dim = layers[-1]["w"].shape[1]
    hidden = [layers[k]["w"].shape[1] for k in range(L)]
    if any(h % _LANE for h in hidden):
        raise ValueError(
            f"fused FVP needs lane-multiple hidden widths, got {hidden}"
        )

    D0p = _ceil_to(D0, _LANE)
    Ap = _ceil_to(act_dim, _LANE)
    if block_rows is None:
        block_rows = _auto_block_rows(D0p, hidden, Ap)
    Bp = _ceil_to(B, block_rows)
    cd = compute_dtype
    f32 = jnp.float32
    act_fn = {"tanh": jnp.tanh, "relu": jax.nn.relu, "elu": jax.nn.elu}[
        activation
    ]

    # ---- once-per-update setup (loop-invariant under the CG loop) ----
    obs_p = _pad2(obs.astype(cd), Bp, D0p)
    h = obs.astype(cd)
    acts: List[jax.Array] = []
    for k in range(L):
        w = layers[k]["w"].astype(cd)
        b = layers[k]["b"].astype(cd)
        h = act_fn(h @ w + b)
        acts.append(_pad2(h, Bp, hidden[k]))
    w_mid = [layers[k]["w"].astype(cd) for k in range(1, L)]
    w_head = _pad2(layers[L]["w"].astype(cd), hidden[-1], Ap)

    weight = weight.reshape(-1).astype(f32)
    sum_w = jnp.sum(weight)
    norm = jnp.maximum(sum_w, 1.0)
    wn = jnp.pad(weight / norm, (0, Bp - B))[:, None]  # (Bp, 1)
    inv_var = jnp.exp(-2.0 * log_std.astype(f32))
    m_row = jnp.pad(inv_var, (0, Ap - act_dim))[None, :]  # (1, Ap)
    sum_wn = sum_w / norm  # Σ of normalized weights (=1 for real batches)

    damping = jnp.asarray(damping, f32)
    cbw = max(max(hidden), Ap)  # stacked bias-cotangent row width

    grid = (Bp // block_rows,)
    row_spec = lambda width: pl.BlockSpec(
        (block_rows, width), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    full_spec = lambda shape: pl.BlockSpec(
        shape, lambda i: tuple(0 for _ in shape), memory_space=pltpu.VMEM
    )

    in_specs = (
        [row_spec(D0p)]
        + [row_spec(hk) for hk in hidden]
        + [row_spec(1)]
        + [full_spec((1, Ap))]
        + [full_spec(w.shape) for w in w_mid]
        + [full_spec(w_head.shape)]
        + [full_spec((D0p, hidden[0]))]
        + [full_spec((hidden[k - 1], hidden[k])) for k in range(1, L)]
        + [full_spec((hidden[-1], Ap))]
        + [full_spec((1, hk)) for hk in hidden]
        + [full_spec((1, Ap))]
    )
    out_shapes = (
        [jax.ShapeDtypeStruct((D0p, hidden[0]), f32)]
        + [
            jax.ShapeDtypeStruct((hidden[k - 1], hidden[k]), f32)
            for k in range(1, L)
        ]
        + [jax.ShapeDtypeStruct((hidden[-1], Ap), f32)]
        + [jax.ShapeDtypeStruct((L + 1, cbw), f32)]
    )
    out_specs = [full_spec(s.shape) for s in out_shapes]

    kernel = pl.pallas_call(
        functools.partial(_fvp_kernel, L, activation),
        grid=grid,
        in_specs=in_specs,
        out_shape=out_shapes,
        out_specs=out_specs,
        interpret=interpret,
    )

    def fvp(v: Any) -> Any:
        vl = v["net"]["layers"]
        v0 = _pad2(vl[0]["w"].astype(cd), D0p, hidden[0])
        v_mid = [vl[k]["w"].astype(cd) for k in range(1, L)]
        v_head = _pad2(vl[L]["w"].astype(cd), hidden[-1], Ap)
        vbs = [vl[k]["b"].astype(f32)[None, :] for k in range(L)]
        vbh = jnp.pad(vl[L]["b"].astype(f32), (0, Ap - act_dim))[None, :]

        outs = kernel(
            obs_p, *acts, wn, m_row,
            *w_mid, w_head,
            v0, *v_mid, v_head,
            *vbs, vbh,
        )
        cws, cb = list(outs[: L + 1]), outs[L + 1]

        out_layers = []
        for k in range(L + 1):
            cw = cws[k]
            if k == 0:
                cw = cw[:D0, :]
            elif k == L:
                cw = cw[:, :act_dim]
            row = L if k == 0 else (L - k if k < L else 0)
            width = act_dim if k == L else hidden[k]
            cb_k = cb[row, :width]
            out_layers.append(
                {
                    "w": cw + damping * vl[k]["w"].astype(f32),
                    "b": cb_k + damping * vl[k]["b"].astype(f32),
                }
            )
        # log_std block: J is the identity broadcast (state-independent
        # σ), dist-space Hessian 2·I — closed form, no kernel work.
        c_sigma = (2.0 * sum_wn + damping) * v["log_std"].astype(f32)
        return {"net": {"layers": out_layers}, "log_std": c_sigma}

    return fvp
