"""Conjugate gradient as a single device program.

The reference's ``conjugate_gradient`` (``utils.py:185-201``) is a host NumPy
loop whose matrix-vector product closure triggers a full-batch ``sess.run``
(double-backprop FVP) per iteration — 10+ host↔device round trips per policy
update, the #1 performance defect called out in SURVEY §1. Here the same
textbook CG (same iteration count, same residual early-exit semantics) is a
``lax.while_loop`` that jits into the surrounding TRPO step: the FVP operator
is inlined into one XLA program and no intermediate ever touches the host.

Two beyond-reference solver levers (VERDICT r3 item 2 — the flagship
Humanoid run's late-training residual grew 2000× at fixed iterations):

* ``M_inv`` — a diagonal (Jacobi) preconditioner, given as a pytree of
  inverse-diagonal entries matching ``b``. Preconditioned CG minimizes the
  same A-norm error over the preconditioned Krylov space; with ``M_inv``
  from :func:`trpo_tpu.ops.precond.hutchinson_diag_inv` it counteracts the
  per-coordinate scale spread a sharpening policy induces on the Fisher.
  ``M_inv=None`` is bit-identical to plain CG.
* ``residual_rtol`` — a RELATIVE stopping rule ``‖r‖² ≤ rtol²·‖b‖²`` on top
  of the reference's absolute ``residual_tol``, so ``cg_iters`` can be set
  as a cap ("iterate until solved, at most N") instead of a fixed count.

The solve is always fp32 regardless of the forward-pass compute dtype —
Fisher conditioning at Humanoid-scale batches does not survive bf16
accumulation (SURVEY §7 "hard parts"). This is the solver precision
ladder's dtype contract (``cfg.fvp_dtype``, ISSUE 8): the FVP *matvec*
may run its matmuls in bf16, but every quantity THIS module owns — the
iterates ``x``/``r``/``p``, both dot products, and the residual test —
is f32: ``tree_f32`` casts ``b`` and every ``f_Ax`` result on entry, so
a bf16 operator contributes rounded *values*, never reduced-precision
*accumulation*.

``cg_iters`` may be a traced int32 scalar (the ladder's adaptive
iteration budget, ``cfg.cg_budget_adaptive``): the ``while_loop`` bound
is data-dependent already, so a carried budget costs nothing.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from trpo_tpu.ops.treemath import (
    tree_add_scaled,
    tree_f32,
    tree_vdot,
    tree_zeros_like,
)

__all__ = ["conjugate_gradient", "CGResult"]


class CGResult(NamedTuple):
    x: Any                  # approximate solution of A x = b (same pytree as b)
    residual_norm_sq: jax.Array
    iterations: jax.Array   # iterations actually executed (early exit aware)


def _apply_Minv(M_inv: Optional[Any], r: Any) -> Any:
    """z = M⁻¹ r. ``M_inv`` may be a pytree of inverse-diagonal entries
    (Jacobi), a callable ``r ↦ M⁻¹r`` (structured/block preconditioners —
    must be SPD and jit-traceable), or None (identity)."""
    if M_inv is None:
        return r
    if callable(M_inv):
        return M_inv(r)
    return jax.tree_util.tree_map(
        lambda m, x: jnp.asarray(m, jnp.float32) * x, M_inv, r
    )


def conjugate_gradient(
    f_Ax: Callable[[Any], Any],
    b: Any,
    cg_iters: int = 10,
    residual_tol: float = 1e-10,
    M_inv: Optional[Any] = None,
    residual_rtol: float = 0.0,
) -> CGResult:
    """Solve ``A x = b`` for SPD ``A`` given only the matvec ``f_Ax``.

    Matches the reference algorithm (``utils.py:185-201``): x₀ = 0, r₀ = p₀ =
    b, standard Hestenes–Stiefel updates, early exit when ``rᵀr <
    residual_tol``. Differences are purely about execution: this is a traced
    ``lax.while_loop`` (data-dependent exit without leaving the device), and
    it returns diagnostics alongside the solution.

    ``M_inv`` (optional) makes this preconditioned CG — a pytree of
    inverse-diagonal entries shaped like ``b``; the search directions become
    M-conjugate while the early-exit test stays on the TRUE residual
    ``rᵀr``, so plain and preconditioned solves are directly comparable.
    With ``M_inv=None`` the recurrence is bit-identical to unpreconditioned
    CG. ``residual_rtol`` adds a relative exit ``rᵀr ≤ rtol²·bᵀb``.

    ``b`` may be a flat vector (the reference's contract) or ANY pytree —
    e.g. a parameter pytree whose leaves are tensor-sharded over a
    ``"model"`` mesh axis: the iterates keep ``b``'s structure/sharding and
    only the scalar dot products reduce across the mesh.
    """
    b = tree_f32(b)
    x0 = tree_zeros_like(b)
    rdotr0 = tree_vdot(b, b)
    z0 = _apply_Minv(M_inv, b)
    rdotz0 = tree_vdot(b, z0) if M_inv is not None else rdotr0
    # threshold on rᵀr: absolute tol OR relative to the RHS norm
    stop = jnp.maximum(
        jnp.asarray(residual_tol, jnp.float32),
        jnp.asarray(residual_rtol, jnp.float32) ** 2 * rdotr0,
    )

    def cond(state):
        i, _, _, _, _, rdotr = state
        return jnp.logical_and(i < cg_iters, rdotr > stop)

    def body(state):
        i, x, r, p, rdotz, rdotr = state
        w = tree_f32(f_Ax(p))
        alpha = rdotz / tree_vdot(p, w)
        x = tree_add_scaled(x, alpha, p)
        r = tree_add_scaled(r, -alpha, w)
        z = _apply_Minv(M_inv, r)
        new_rdotr = tree_vdot(r, r)
        new_rdotz = tree_vdot(r, z) if M_inv is not None else new_rdotr
        mu = new_rdotz / rdotz
        p = tree_add_scaled(z, mu, p)
        return i + 1, x, r, p, new_rdotz, new_rdotr

    i, x, r, _, _, rdotr = lax.while_loop(
        cond, body, (jnp.asarray(0, jnp.int32), x0, b, z0, rdotz0, rdotr0)
    )
    del r
    return CGResult(x=x, residual_norm_sq=rdotr, iterations=i)
