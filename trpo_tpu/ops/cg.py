"""Conjugate gradient as a single device program.

The reference's ``conjugate_gradient`` (``utils.py:185-201``) is a host NumPy
loop whose matrix-vector product closure triggers a full-batch ``sess.run``
(double-backprop FVP) per iteration — 10+ host↔device round trips per policy
update, the #1 performance defect called out in SURVEY §1. Here the same
textbook CG (same iteration count, same residual early-exit semantics) is a
``lax.while_loop`` that jits into the surrounding TRPO step: the FVP operator
is inlined into one XLA program and no intermediate ever touches the host.

The solve is always fp32 regardless of the forward-pass compute dtype —
Fisher conditioning at Humanoid-scale batches does not survive bf16
accumulation (SURVEY §7 "hard parts").
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["conjugate_gradient", "CGResult"]


class CGResult(NamedTuple):
    x: jax.Array            # approximate solution of A x = b
    residual_norm_sq: jax.Array
    iterations: jax.Array   # iterations actually executed (early exit aware)


def conjugate_gradient(
    f_Ax: Callable[[jax.Array], jax.Array],
    b: jax.Array,
    cg_iters: int = 10,
    residual_tol: float = 1e-10,
) -> CGResult:
    """Solve ``A x = b`` for SPD ``A`` given only the matvec ``f_Ax``.

    Matches the reference algorithm (``utils.py:185-201``): x₀ = 0, r₀ = p₀ =
    b, standard Hestenes–Stiefel updates, early exit when ``rᵀr <
    residual_tol``. Differences are purely about execution: this is a traced
    ``lax.while_loop`` (data-dependent exit without leaving the device), and
    it returns diagnostics alongside the solution.
    """
    b = jnp.asarray(b, jnp.float32)
    x0 = jnp.zeros_like(b)
    rdotr0 = jnp.dot(b, b)

    def cond(state):
        i, _, _, _, rdotr = state
        return jnp.logical_and(i < cg_iters, rdotr > residual_tol)

    def body(state):
        i, x, r, p, rdotr = state
        z = jnp.asarray(f_Ax(p), jnp.float32)
        alpha = rdotr / jnp.dot(p, z)
        x = x + alpha * p
        r = r - alpha * z
        new_rdotr = jnp.dot(r, r)
        mu = new_rdotr / rdotr
        p = r + mu * p
        return i + 1, x, r, p, new_rdotr

    i, x, r, _, rdotr = lax.while_loop(
        cond, body, (jnp.asarray(0, jnp.int32), x0, b, b, rdotr0)
    )
    del r
    return CGResult(x=x, residual_norm_sq=rdotr, iterations=i)
