"""Conjugate gradient as a single device program.

The reference's ``conjugate_gradient`` (``utils.py:185-201``) is a host NumPy
loop whose matrix-vector product closure triggers a full-batch ``sess.run``
(double-backprop FVP) per iteration — 10+ host↔device round trips per policy
update, the #1 performance defect called out in SURVEY §1. Here the same
textbook CG (same iteration count, same residual early-exit semantics) is a
``lax.while_loop`` that jits into the surrounding TRPO step: the FVP operator
is inlined into one XLA program and no intermediate ever touches the host.

The solve is always fp32 regardless of the forward-pass compute dtype —
Fisher conditioning at Humanoid-scale batches does not survive bf16
accumulation (SURVEY §7 "hard parts").
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from trpo_tpu.ops.treemath import (
    tree_add_scaled,
    tree_f32,
    tree_vdot,
    tree_zeros_like,
)

__all__ = ["conjugate_gradient", "CGResult"]


class CGResult(NamedTuple):
    x: Any                  # approximate solution of A x = b (same pytree as b)
    residual_norm_sq: jax.Array
    iterations: jax.Array   # iterations actually executed (early exit aware)


def conjugate_gradient(
    f_Ax: Callable[[Any], Any],
    b: Any,
    cg_iters: int = 10,
    residual_tol: float = 1e-10,
) -> CGResult:
    """Solve ``A x = b`` for SPD ``A`` given only the matvec ``f_Ax``.

    Matches the reference algorithm (``utils.py:185-201``): x₀ = 0, r₀ = p₀ =
    b, standard Hestenes–Stiefel updates, early exit when ``rᵀr <
    residual_tol``. Differences are purely about execution: this is a traced
    ``lax.while_loop`` (data-dependent exit without leaving the device), and
    it returns diagnostics alongside the solution.

    ``b`` may be a flat vector (the reference's contract) or ANY pytree —
    e.g. a parameter pytree whose leaves are tensor-sharded over a
    ``"model"`` mesh axis: the iterates keep ``b``'s structure/sharding and
    only the scalar dot products reduce across the mesh.
    """
    b = tree_f32(b)
    x0 = tree_zeros_like(b)
    rdotr0 = tree_vdot(b, b)

    def cond(state):
        i, _, _, _, rdotr = state
        return jnp.logical_and(i < cg_iters, rdotr > residual_tol)

    def body(state):
        i, x, r, p, rdotr = state
        z = tree_f32(f_Ax(p))
        alpha = rdotr / tree_vdot(p, z)
        x = tree_add_scaled(x, alpha, p)
        r = tree_add_scaled(r, -alpha, z)
        new_rdotr = tree_vdot(r, r)
        mu = new_rdotr / rdotr
        p = tree_add_scaled(r, mu, p)
        return i + 1, x, r, p, new_rdotr

    i, x, r, _, rdotr = lax.while_loop(
        cond, body, (jnp.asarray(0, jnp.int32), x0, b, b, rdotr0)
    )
    del r
    return CGResult(x=x, residual_norm_sq=rdotr, iterations=i)
