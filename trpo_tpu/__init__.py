"""trpo_tpu — a TPU-native Trust Region Policy Optimization framework.

A ground-up JAX/XLA re-design of the capability set of the reference
implementation (inksci/TRPO: ``trpo_inksci.py`` + ``utils.py``): TRPO with a
natural-gradient step solved by conjugate gradient over Fisher-vector
products, a backtracking line search, a value-function baseline, and
environment rollouts — but engineered TPU-first:

* the entire policy update (gradient -> CG -> step scaling -> line search ->
  KL rollback) is **one jit-compiled device program** (`trpo_tpu.trpo`),
  where the reference ran a host NumPy loop with one ``sess.run`` round trip
  per CG iteration (reference ``utils.py:185-201``);
* Fisher-vector products use forward-over-reverse ``jvp(grad(kl))`` instead
  of the reference's double reverse-mode backprop (``trpo_inksci.py:56-70``);
* rollouts run on-device via ``lax.scan`` over batched pure-JAX environments
  (`trpo_tpu.envs`), replacing the per-step ``sess.run`` dispatch of the
  reference (``utils.py:18-45``);
* data parallelism is expressed with `jax.sharding` over a device Mesh, and
  XLA emits the ICI collectives (`trpo_tpu.parallel`) — there is no NCCL/MPI
  analogue to port because computation is single-program SPMD.

Package map
-----------
- ``trpo_tpu.config``         — dataclass config + presets (ref: module globals)
- ``trpo_tpu.distributions``  — categorical + diagonal-Gaussian policy heads
- ``trpo_tpu.models``         — MLP / conv policy + value networks
- ``trpo_tpu.ops``            — flat-param utils, returns/GAE scans, CG,
                                line search, Fisher-vector products
- ``trpo_tpu.trpo``           — the fused TRPO update step
- ``trpo_tpu.vf``             — value-function baseline (critic)
- ``trpo_tpu.envs``           — pure-JAX envs (CartPole, Pendulum, ...) +
                                gymnasium adapter + FakeEnv
- ``trpo_tpu.rollout``        — on-device scan rollouts / host rollouts
- ``trpo_tpu.agent``          — ``TRPOAgent`` (init / act / learn), the
                                reference's top-level API
- ``trpo_tpu.parallel``       — mesh construction, sharded update, multihost
- ``trpo_tpu.population``     — vmapped multi-seed population training
- ``trpo_tpu.train``          — training loop + CLI
- ``trpo_tpu.utils``          — metrics/JSONL logging, phase timers,
                                Orbax checkpointing, running obs statistics
- ``trpo_tpu.compat``         — the reference ``utils.py`` helper surface
                                re-expressed over JAX (discount, linesearch,
                                conjugate_gradient, cat_sample, ...)

See ``docs/API.md`` for the full public surface and ``PARITY.md`` for the
component-by-component reference mapping.
"""

__version__ = "0.1.0"

from trpo_tpu.config import TRPOConfig  # noqa: F401
