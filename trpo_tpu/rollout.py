"""Rollout collection.

The reference's ``rollout`` (``utils.py:18-45``) is a serial host loop —
one ``sess.run`` per environment step, one env, ragged path dicts, and a
latent stale-``path`` bug for non-terminating episodes (``utils.py:44``).
Here the device path is a ``lax.scan`` over time of a ``vmap``-batched
env+policy step with in-graph auto-reset: fixed ``(T, N)`` tensors, zero
host dispatch, episodes packed contiguously with explicit
``terminated``/``done`` flags (truncation bootstraps through the critic —
the bug fix SURVEY §7 prescribes).

For host-side simulators (MuJoCo/Atari via gymnasium) the same trajectory
layout is produced by :func:`host_rollout`, with policy inference batched
over the vectorized envs — one device call per *timestep across all envs*
rather than per step of one env.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from trpo_tpu.models.policy import Policy

__all__ = [
    "Trajectory",
    "device_rollout",
    "ChunkedRollout",
    "init_env_states",
    "host_rollout",
    "pipelined_host_rollout",
    "make_host_act_fn",
]


class Trajectory(NamedTuple):
    """Fixed-shape ``(T, N, ...)`` rollout tensors (time-major)."""
    obs: jax.Array          # (T, N, *obs_shape) — s_t
    actions: jax.Array      # (T, N) or (T, N, D)
    rewards: jax.Array      # (T, N)
    terminated: jax.Array   # (T, N) — env reached a terminal state at t
    done: jax.Array         # (T, N) — terminated OR truncated (episode ends)
    old_dist: Any           # dist params pytree (T, N, ...)
    next_obs: jax.Array     # (T, N, *obs_shape) — s_{t+1} BEFORE auto-reset
    episode_return: jax.Array  # (T, N) — running return, valid where done
    episode_length: jax.Array  # (T, N) — running length, valid where done
    # Recurrent policies only (None otherwise): per-step "hidden state was
    # zeroed before consuming obs[t]" flags, and the (N, H) hidden state
    # that entered this window — together they let the TRPO update replay
    # the window exactly (models/recurrent.py SeqObs). ``policy_h``/
    # ``policy_h_next`` are the memory before/after consuming obs[t] — the
    # critic's history features (the TPU analogue of the reference VF
    # taking the action distribution as an input, utils.py:70-77).
    reset: Any = None          # (T, N) bool
    policy_h0: Any = None      # (N, H)
    policy_h: Any = None       # (T, N, H) — context entering step t
    policy_h_next: Any = None  # (T, N, H) — context after obs[t] (pre-reset),
    #                            i.e. the memory held when seeing next_obs[t]


def init_env_states(env, key, n_envs: int):
    """Reset ``n_envs`` device envs; returns ``(states, obs)`` batched."""
    keys = jax.random.split(key, n_envs)
    states, obs = jax.vmap(env.reset)(keys)
    return states, obs


def _make_step_fn(env, policy: Policy, params, deterministic: bool,
                  recurrent: bool):
    """The ONE rollout scan body (shared by the unchunked, in-graph
    chunked, and host-driven chunked paths — chunking must never fork the
    step semantics): ``(carry, step_key) -> (carry, Trajectory_step)``."""

    def step_fn(c, step_key):
        if recurrent:
            states, obs, ep_ret, ep_len, h, prev_done = c
        else:
            states, obs, ep_ret, ep_len = c
            h = prev_done = None
        k_act, k_step, k_reset = jax.random.split(step_key, 3)
        n = obs.shape[0]

        if recurrent:
            h_new, dist = policy.step(params, h, obs)
        else:
            dist = policy.apply(params, obs)
        if deterministic:
            actions = policy.dist.mode(dist)
        else:
            actions = policy.dist.sample(k_act, dist)

        step_keys = jax.random.split(k_step, n)
        new_states, next_obs, rewards, terminated, truncated = jax.vmap(
            env.step
        )(states, actions, step_keys)
        done = jnp.logical_or(terminated, truncated)

        ep_ret = ep_ret + rewards
        ep_len = ep_len + 1

        # In-graph auto-reset for finished episodes.
        reset_keys = jax.random.split(k_reset, n)
        reset_states, reset_obs = jax.vmap(env.reset)(reset_keys)
        sel = lambda d, a, b: jnp.where(
            d.reshape((-1,) + (1,) * (a.ndim - 1)), a, b
        )
        carried_states = jax.tree_util.tree_map(
            lambda r, s: sel(done, r, s), reset_states, new_states
        )
        carried_obs = sel(done, reset_obs, next_obs)

        out = Trajectory(
            obs=obs,
            actions=actions,
            rewards=rewards,
            terminated=terminated,
            done=done,
            old_dist=dist,
            next_obs=next_obs,
            episode_return=ep_ret,
            episode_length=ep_len,
            # reset flag for THIS step: h was zeroed before consuming obs
            reset=prev_done,
            policy_h=h,
            policy_h_next=h_new if recurrent else None,
        )
        ep_ret = jnp.where(done, 0.0, ep_ret)
        ep_len = jnp.where(done, 0, ep_len)
        if recurrent:
            h_next = jnp.where(done[:, None], 0.0, h_new)
            return (
                carried_states, carried_obs, ep_ret, ep_len, h_next, done,
            ), out
        return (carried_states, carried_obs, ep_ret, ep_len), out

    return step_fn


def _rollout_scan(env, policy: Policy, params, carry, step_keys,
                  deterministic: bool = False):
    """Scan the shared step body over pre-split ``step_keys``; returns
    ``(new_carry, Trajectory)`` with ``policy_h0`` filled for recurrent
    policies. The common core of :func:`device_rollout` and the
    :class:`ChunkedRollout` chunk program."""
    recurrent = hasattr(policy, "step")
    step_fn = _make_step_fn(env, policy, params, deterministic, recurrent)
    new_carry, traj = jax.lax.scan(step_fn, carry, step_keys)
    if recurrent:
        traj = traj._replace(policy_h0=carry[4])
    return new_carry, traj


def device_rollout(
    env,
    policy: Policy,
    params,
    carry,
    key,
    n_steps: int,
    deterministic: bool = False,
    chunk: int = None,
):
    """Collect ``n_steps × n_envs`` transitions fully on-device.

    ``carry`` is ``(env_states, obs, episode_return, episode_length)`` from
    :func:`init_env_states` / a previous call — env state persists across
    training iterations so episodes continue rather than restarting every
    batch (the reference restarts its env every batch, discarding progress
    mid-episode — see ``utils.py:22-26``).

    ``deterministic=True`` acts greedily (distribution mode) instead of
    sampling — the reference's eval path (``trpo_inksci.py:82-83``) minus
    the render call.

    Jit-safe: designed to be traced inside the full training-step program.
    Returns ``(new_carry, Trajectory)``.

    ``chunk`` (``cfg.rollout_chunk``): time-chunked rollout — an outer
    ``lax.scan`` over ``n_steps // chunk`` time-chunks of the SAME step
    body, the env-state/obs-norm/policy carry threaded through the chunk
    boundary, each chunk's live emission buffer ``(chunk, N, ...)``. The
    stacked chunks reshape back to the ``(T, N, ...)`` layout GAE and the
    critic fit consume, so the chunked path is BIT-EXACT vs unchunked
    (same per-step keys, same step order, same float ops — pinned by
    tests/test_env_fleet.py, auto-reset, truncation bootstrap and
    recurrent ``policy_h`` threading included). ``chunk`` must divide
    ``n_steps``; ``None``/``n_steps`` is the single flat scan.

    Recurrent policies (``models/recurrent.py``): the carry gains the policy
    hidden state and a ``prev_done`` flag — ``h`` threads through the scan,
    is zeroed at episode boundaries, and the emitted trajectory carries the
    ``reset`` flags + window-entry ``h0`` the training replay needs.
    """
    recurrent = hasattr(policy, "step")
    if chunk is not None and not 1 <= chunk <= n_steps:
        raise ValueError(
            f"rollout chunk must be in [1, n_steps={n_steps}], got {chunk}"
        )
    if chunk is not None and n_steps % chunk:
        raise ValueError(
            f"rollout chunk ({chunk}) must divide the steps per rollout "
            f"({n_steps}) — pad batch_timesteps or pick a divisor"
        )
    step_keys = jax.random.split(key, n_steps)
    if chunk is None or chunk == n_steps:
        return _rollout_scan(
            env, policy, params, carry, step_keys, deterministic
        )

    step_fn = _make_step_fn(env, policy, params, deterministic, recurrent)
    n_chunks = n_steps // chunk
    # (T, ...) keys -> (n_chunks, chunk, ...): trailing key dims (typed
    # keys have none; legacy uint32 keys carry (2,)) ride along untouched
    keys_c = step_keys.reshape((n_chunks, chunk) + step_keys.shape[1:])

    def chunk_body(c, chunk_keys):
        return jax.lax.scan(step_fn, c, chunk_keys)

    new_carry, traj = jax.lax.scan(chunk_body, carry, keys_c)
    # (n_chunks, chunk, N, ...) -> (T, N, ...): row-major reshape of the
    # stacked chunks IS the unchunked stacking order
    traj = jax.tree_util.tree_map(
        lambda x: x.reshape((n_steps,) + x.shape[2:]), traj
    )
    if recurrent:
        traj = traj._replace(policy_h0=carry[4])
    return new_carry, traj


class ChunkedRollout:
    """Host-driven time-chunked rollout: ONE compiled chunk program,
    looped over ``n_steps // chunk`` chunks.

    Where :func:`device_rollout`'s ``chunk`` mode nests the time-chunks
    inside one traced program (for the fused iteration), this driver jits
    the chunk alone — so (a) the COMPILED program's memory grows with
    ``chunk``, not with the total horizon ``T`` (the ``env_fleet`` bench
    quotes ``program_memory_analysis`` of exactly this program), and
    (b) changing the chunk COUNT (any ``n_steps`` multiple of ``chunk``
    at fixed ``(chunk, N)`` shapes) re-runs the same executable with
    ZERO retraces (``self.traces`` pins it in tests).

    The per-chunk memory claim belongs to the CONSUMPTION mode:
    :meth:`iter_chunks` streams one ``(chunk, N, ...)`` emission at a
    time (plus the donated carry) — only that chunk and the carry are
    live between dispatches. :meth:`__call__` is the convenience that
    assembles the full ``(T, N, ...)`` trajectory, which by construction
    holds every chunk live and transiently ~2× the trajectory during the
    final concatenation — a rollout sized against the memory ceiling
    must consume :meth:`iter_chunks` instead.

    Donation contract (the agent module docstring's rule, applied at the
    chunk boundary): every call DONATES the carry it is given — the env
    states / episode accumulators / recurrent ``h`` buffers are reused in
    place for the next chunk's carry, so a T-step rollout holds ONE
    carry-sized working set regardless of chunk count. The caller's
    original carry is dead after ``__call__``; keep using the returned
    one.

    Bit-exact vs :func:`device_rollout` (chunked or not): same step body
    (``_make_step_fn``), same ``jax.random.split(key, n_steps)`` key
    sequence, chunks concatenated in time order.
    """

    def __init__(self, env, policy: Policy, chunk: int,
                 deterministic: bool = False):
        if chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        self.env = env
        self.policy = policy
        self.chunk = chunk
        self.deterministic = deterministic
        self.traces = 0  # trace counter — tests pin zero retraces

        def chunk_prog(params, carry, step_keys):
            self.traces += 1
            return _rollout_scan(
                env, policy, params, carry, step_keys, deterministic
            )

        # donate the carry: chunk i+1's carry reuses chunk i's buffers
        self._fn = jax.jit(chunk_prog, donate_argnums=1)

    def iter_chunks(self, params, carry, key, n_steps: int):
        """Stream the rollout chunk by chunk: yields ``(carry_after,
        Trajectory_chunk)`` per chunk, each trajectory ``(chunk, N,
        ...)`` — the memory-winning consumption mode (one chunk + the
        donated carry live at a time; class docstring). The carry of the
        LAST yield is the rollout's final carry; each chunk's
        ``policy_h0`` is that chunk's own entry memory. ``carry`` is
        DONATED."""
        c = self.chunk
        if n_steps < 1 or n_steps % c:
            raise ValueError(
                f"n_steps ({n_steps}) must be a positive multiple of the "
                f"chunk ({c})"
            )
        keys = jax.random.split(key, n_steps)
        for i in range(n_steps // c):
            carry, traj = self._fn(params, carry, keys[i * c:(i + 1) * c])
            yield carry, traj

    def __call__(self, params, carry, key, n_steps: int):
        """Roll ``n_steps`` (a multiple of ``chunk``) steps; returns
        ``(new_carry, Trajectory)`` with the standard ``(T, N, ...)``
        layout — assembled from every chunk, so the full trajectory
        (transiently ~2×, during the concatenation) is live; use
        :meth:`iter_chunks` when that footprint is the constraint.
        ``carry`` is DONATED (class docstring)."""
        recurrent = hasattr(self.policy, "step")
        parts = []
        h0 = None
        for carry, traj in self.iter_chunks(params, carry, key, n_steps):
            if recurrent:
                if h0 is None:
                    h0 = traj.policy_h0  # window-entry memory: chunk 0's
                # per-chunk h0 is (N, H) — strip before the time concat
                traj = traj._replace(policy_h0=None)
            parts.append(traj)
        if len(parts) == 1:
            traj = parts[0]
        else:
            traj = jax.tree_util.tree_map(
                lambda *xs: jnp.concatenate(xs, axis=0), *parts
            )
        if recurrent:
            traj = traj._replace(policy_h0=h0)
        return carry, traj


def init_carry(env, key, n_envs: int, policy=None):
    """Full rollout carry: env states + obs + episode accumulators; for a
    recurrent ``policy``, also its zero hidden state and a ``prev_done``
    flag (True: the first window step starts a fresh episode memory)."""
    states, obs = init_env_states(env, key, n_envs)
    carry = (
        states,
        obs,
        jnp.zeros(n_envs, jnp.float32),
        jnp.zeros(n_envs, jnp.int32),
    )
    if policy is not None and hasattr(policy, "step"):
        carry = carry + (
            policy.initial_state(n_envs),
            jnp.ones(n_envs, bool),
        )
    return carry


# ---------------------------------------------------------------------------
# Host-simulator path (gymnasium)
# ---------------------------------------------------------------------------


def make_host_act_fn(
    policy: Policy, deterministic: bool = False, pack: bool = True
):
    """The ONE builder for host-loop policy inference (used by
    :func:`host_rollout`'s default and cached by the agent):
    ``(params, obs, key) -> (actions, dist)`` — recurrent policies take a
    trailing ``h`` and return a trailing ``h'``.

    ``pack=True`` (feedforward only): the jitted program concatenates the
    actions and every distribution leaf into ONE ``(N, K)`` float32 array,
    fetched with a single transfer and split back on the host. Each
    device→host fetch is a full round trip — on a tunneled TPU ~100 ms
    regardless of size — and the unpacked path pays one per actions array
    plus one per dist leaf, so packing cuts the per-step rollout latency by
    that factor (~3× for a Gaussian policy). The split/casts are exact
    (float32 leaves round-trip bitwise; integer actions are < 2²⁴).
    ``pack=False`` returns device arrays and lets the caller control the
    fetches."""
    if hasattr(policy, "step"):
        def act_rec(params, obs, key, h):
            h_new, dist = policy.step(params, h, obs)
            action = (
                policy.dist.mode(dist)
                if deterministic
                else policy.dist.sample(key, dist)
            )
            return action, dist, h_new

        return jax.jit(act_rec)

    def act(params, obs, key):
        dist = policy.apply(params, obs)
        action = (
            policy.dist.mode(dist)
            if deterministic
            else policy.dist.sample(key, dist)
        )
        return action, dist

    if not pack:
        return jax.jit(act)

    def act_packed(params, obs, key):
        action, dist = act(params, obs, key)
        n = obs.shape[0]
        cols = [action.reshape(n, -1).astype(jnp.float32)] + [
            leaf.reshape(n, -1).astype(jnp.float32)
            for leaf in jax.tree_util.tree_leaves(dist)
        ]
        return jnp.concatenate(cols, axis=1)

    jitted = jax.jit(act_packed)
    jitted_unpacked = jax.jit(act)
    meta_cache: dict = {}  # obs trailing shape -> unpack recipe (or None)

    def _f32_safe(dt: np.dtype) -> bool:
        # exact through a float32 round trip: f32 itself, narrower floats
        # (bf16/f16 upcast losslessly), and integers whose whole range fits
        # the 24-bit mantissa. Wider integers and float64 would silently
        # round — don't pack them here (the action leaf gets its own
        # bounded-range check below).
        if dt == np.float32:
            return True
        if np.issubdtype(dt, np.integer):
            return np.dtype(dt).itemsize <= 2
        return np.issubdtype(dt, np.floating) and np.dtype(dt).itemsize < 4

    def _int_action_safe(dist_leaves) -> bool:
        # a wide (int32/int64) action leaf packs exactly only when its
        # VALUES are < 2²⁴; that bound is knowable only for categorical
        # policies, where indices range over the logits width
        if getattr(policy.dist, "name", None) != "categorical":
            return False
        widths = [
            leaf.shape[-1] for leaf in dist_leaves if len(leaf.shape) > 1
        ]
        return bool(widths) and max(widths) < 2**24

    def call(params, obs, key):
        m = meta_cache.get(obs.shape[1:], "?")
        if m == "?":
            a_s, d_s = jax.eval_shape(act, params, obs, key)
            leaves, treedef = jax.tree_util.tree_flatten(d_s)
            action_ok = _f32_safe(np.dtype(a_s.dtype)) or (
                np.issubdtype(np.dtype(a_s.dtype), np.integer)
                and _int_action_safe(leaves)
            )
            if action_ok and all(
                _f32_safe(np.dtype(x.dtype)) for x in leaves
            ):
                m = (
                    a_s.shape[1:],
                    np.dtype(a_s.dtype),
                    [
                        (leaf.shape[1:], np.dtype(leaf.dtype))
                        for leaf in leaves
                    ],
                    treedef,
                )
            else:
                m = None  # e.g. x64 mode — packing would round f64 leaves
            meta_cache[obs.shape[1:]] = m
        if m is None:
            return jitted_unpacked(params, obs, key)
        a_trail, a_dtype, leaf_meta, treedef = m
        out = np.asarray(jitted(params, obs, key))  # the ONE transfer
        n = out.shape[0]
        ncols = int(np.prod(a_trail, dtype=int))
        action = out[:, :ncols].reshape((n,) + a_trail).astype(a_dtype)
        off = ncols
        leaves = []
        for trail, dt in leaf_meta:
            c = int(np.prod(trail, dtype=int))
            leaves.append(
                out[:, off:off + c].reshape((n,) + trail).astype(dt)
            )
            off += c
        return action, jax.tree_util.tree_unflatten(treedef, leaves)

    return call


def host_rollout(
    vec_env,
    policy: Policy,
    params,
    key,
    n_steps: int,
    act_fn=None,
    policy_state=None,
    deterministic: bool = False,
    step_callback=None,
):
    """Collect a ``(T, N)`` trajectory from a host vectorized env.

    ``vec_env`` is a :class:`trpo_tpu.envs.gym_adapter.GymVecEnv`. Policy
    inference is jitted and batched over the N envs (``act_fn`` may be a
    pre-jitted callable to reuse across calls: feedforward
    ``(params, obs, key) -> (actions, dist)``; recurrent
    ``(params, obs, key, h) -> (actions, dist, h')``). The env boundary is
    the only host↔device traffic: one transfer per timestep for all envs,
    vs the reference's per-env-step ``sess.run`` (``trpo_inksci.py:78``).

    Recurrent policies: ``policy_state`` is ``(h, prev_done)`` from the
    previous window (``None`` → fresh zeros), the hidden state is zeroed at
    episode boundaries exactly like the device path, and the return value
    becomes ``(Trajectory, new_policy_state)`` — the trajectory carries
    ``reset``/``policy_h0``/``policy_h``/``policy_h_next`` for the
    training-time replay.
    """
    recurrent = hasattr(policy, "step")
    if act_fn is None:
        act_fn = make_host_act_fn(policy, deterministic=deterministic)

    obs = vec_env.current_obs()
    T, N = n_steps, vec_env.n_envs
    if recurrent:
        if policy_state is None:
            policy_state = (
                policy.initial_state(N),
                np.ones(N, bool),
            )
        h, prev_done = policy_state
        h0_window = jnp.asarray(h)
    obs_buf, act_buf, rew_buf = [], [], []
    term_buf, done_buf, dist_buf, next_obs_buf = [], [], [], []
    ret_buf, len_buf = [], []
    reset_buf, h_pre_buf, h_post_buf = [], [], []

    for t in range(T):
        key, k_act = jax.random.split(key)
        # obs stays a NumPy array: jit places it with the computation,
        # which follows the COMMITTED params — on-device params keep the
        # old behavior, CPU-committed params (host_inference="cpu") keep
        # the whole act chain on the host with zero device round trips.
        # A jnp.asarray here would pin obs to the default (device) backend
        # and force a transfer per step in CPU-inference mode.
        if recurrent:
            actions, dist, h_new = act_fn(params, obs, k_act, h)
            reset_buf.append(np.asarray(prev_done).copy())
            h_pre_buf.append(np.asarray(h))
            h_post_buf.append(np.asarray(h_new))
        else:
            actions, dist = act_fn(params, obs, k_act)
        if step_callback is not None:
            # pre-step hook, reference semantics: the frame shows the state
            # the policy just acted on (the ref renders inside eval-mode
            # act, trpo_inksci.py:82) — after host_step, finished envs are
            # already auto-reset and the acted-on state is gone
            step_callback(t)
        actions_np = np.asarray(actions)
        next_obs, rewards, terminated, truncated, final_obs = vec_env.host_step(
            actions_np
        )
        done = np.logical_or(terminated, truncated)
        obs_buf.append(np.asarray(obs))
        act_buf.append(actions_np)
        rew_buf.append(rewards)
        term_buf.append(terminated)
        done_buf.append(done)
        dist_buf.append(jax.tree_util.tree_map(np.asarray, dist))
        # next_obs pre-reset: where an episode ended, the true successor
        # state is final_obs (gymnasium autoresets under us).
        next_obs_buf.append(final_obs)
        ret_buf.append(vec_env.last_episode_returns.copy())
        len_buf.append(vec_env.last_episode_lengths.copy())
        obs = next_obs
        if recurrent:
            # zero memory at episode boundaries (device-path parity);
            # done stays NumPy so the where runs wherever h_new lives
            h = jnp.where(done[:, None], 0.0, h_new)
            prev_done = done

    stack = lambda xs: jnp.asarray(np.stack(xs))
    traj = Trajectory(
        obs=stack(obs_buf),
        actions=stack(act_buf),
        rewards=stack(rew_buf).astype(jnp.float32),
        terminated=stack(term_buf),
        done=stack(done_buf),
        old_dist=jax.tree_util.tree_map(
            lambda *xs: jnp.asarray(np.stack(xs)), *dist_buf
        ),
        next_obs=stack(next_obs_buf),
        episode_return=stack(ret_buf).astype(jnp.float32),
        episode_length=stack(len_buf),
    )
    if not recurrent:
        return traj
    traj = traj._replace(
        reset=stack(reset_buf),
        policy_h0=h0_window,
        policy_h=stack(h_pre_buf),
        policy_h_next=stack(h_post_buf),
    )
    return traj, (h, prev_done)


def pipelined_host_rollout(
    vec_env,
    policy: Policy,
    params,
    key,
    n_steps: int,
    n_groups: int = 2,
    act_fn=None,
    deterministic: bool = False,
    stage_to_device: bool = False,
):
    """Host rollout with device inference and host env stepping OVERLAPPED.

    :func:`host_rollout` is a strict alternation: the host blocks on the
    device for the batch's actions, then the device sits idle while the host
    steps every env. This variant splits the ``N`` envs into ``n_groups``
    contiguous groups and software-pipelines them — when group ``g``'s
    actions are fetched and its envs are stepping on the host (via the
    adapters' ``host_step_slice``), the inference for the OTHER groups is
    already in flight on the device (JAX dispatch is asynchronous; only the
    ``np.asarray`` fetch of a group's own actions blocks). Device compute —
    and, on a tunneled TPU, the transfer round trip — hides behind host
    simulation instead of adding to it. This is the "overlap env stepping
    with device compute" obligation of SURVEY §7; the reference's rollout
    is the degenerate fully-serial case (one env, one ``sess.run`` per step,
    ``utils.py:18-45``).

    ``stage_to_device=True`` additionally overlaps the trajectory's
    host→device transfer with env stepping: the moment a group finishes its
    window, its stacked ``(T, m_g, ...)`` buffers are handed to
    ``jax.device_put`` (async dispatch — the transfer streams while the
    OTHER groups are still stepping), and the final assembly is a
    device-side concatenation instead of one big blocking end-of-rollout
    ``device_put`` of the full ``(T, N, ...)`` batch. Value-identical to
    the unstaged path — the same bytes arrive, grouped differently.

    Semantics match :func:`host_rollout` per group and per timestep (every
    group advances exactly once per ``t``; the trajectory is the env-axis
    concatenation of the groups, in env order). Each group runs in its own
    thread: a group's act→fetch→step chain is inherently serial, so the
    concurrency is ACROSS groups — one group's device round trip overlaps
    another group's env stepping, and env stepping itself spreads over
    cores wherever the simulator releases the GIL (MuJoCo bindings, the
    native C++ stepper, device transfers all do; JAX's dispatch/compile
    paths are thread-safe). With a deterministic policy the result is
    bit-identical to the serial rollout — group chains are independent, so
    thread scheduling cannot change values. With sampling the per-group
    PRNG keys necessarily differ from the serial batch key. With shared
    obs-normalization the window runs in the adapter's DEFERRED mode: every
    observation normalizes under the window-start statistics (the host
    analogue of the device path's start-of-iteration stats) and the raw
    batches merge in deterministic group order afterwards — so a fixed seed
    reproduces bitwise despite thread scheduling, and each recorded
    observation is exactly what the policy saw. Feedforward
    policies only: a recurrent policy's hidden state is carried strictly in
    step order per env, which the pipeline preserves, but the window-replay
    bookkeeping is not wired here — use :func:`host_rollout`.
    """
    if hasattr(policy, "step"):
        raise NotImplementedError(
            "pipelined_host_rollout supports feedforward policies; "
            "recurrent policies use host_rollout"
        )
    if not hasattr(vec_env, "host_step_slice"):
        raise TypeError(
            f"{type(vec_env).__name__} has no host_step_slice — the env "
            "adapter does not support group stepping"
        )
    N = vec_env.n_envs
    if not 2 <= n_groups <= N:
        raise ValueError(
            f"n_groups must be in [2, n_envs={N}], got {n_groups} "
            "(1 group is host_rollout)"
        )
    if act_fn is None:
        act_fn = make_host_act_fn(policy, deterministic=deterministic)

    # contiguous near-equal groups covering [0, N)
    cuts = np.linspace(0, N, n_groups + 1).round().astype(int)
    groups = [(int(cuts[g]), int(cuts[g + 1])) for g in range(n_groups)]

    T = n_steps
    obs0 = np.asarray(vec_env.current_obs())
    # per-group time-major buffers; assembled by env-axis concat at the end
    buf = [
        {
            "obs": [], "actions": [], "rewards": [], "terminated": [],
            "done": [], "dist": [], "next_obs": [], "ret": [], "len": [],
        }
        for _ in range(n_groups)
    ]

    # flat (T·G,) split indexed as [t·G + g]: works for typed keys AND
    # legacy uint32 PRNGKey arrays (whose trailing (2,) would break a
    # (T, G) reshape)
    keys = jax.random.split(key, T * n_groups)

    def run_group(g: int) -> None:
        lo, hi = groups[g]
        b = buf[g]
        obs = obs0[lo:hi]
        for t in range(T):
            # NumPy obs: placement follows the committed params (see
            # host_rollout) — also what keeps this thread's dispatch on
            # the CPU backend under host_inference="cpu", where thread-
            # local default-device context would not propagate here
            actions_dev, dist_dev = act_fn(
                params, obs, keys[t * n_groups + g]
            )
            # blocks on THIS group's chain only; the other groups step
            # their envs / fetch their actions concurrently
            actions_np = np.asarray(actions_dev)
            dist_np = jax.tree_util.tree_map(np.asarray, dist_dev)
            next_obs, rewards, terminated, truncated, final_obs = (
                vec_env.host_step_slice(actions_np, lo, hi)
            )
            b["obs"].append(obs)
            b["actions"].append(actions_np)
            b["rewards"].append(rewards)
            b["terminated"].append(terminated)
            b["done"].append(np.logical_or(terminated, truncated))
            b["dist"].append(dist_np)
            b["next_obs"].append(final_obs)
            b["ret"].append(vec_env.last_episode_returns[lo:hi].copy())
            b["len"].append(vec_env.last_episode_lengths[lo:hi].copy())
            obs = next_obs
        if stage_to_device:
            # Stage THIS group's slice now, on the group's own thread:
            # device_put dispatches asynchronously, so the transfer of
            # group g streams to the device while the later-finishing
            # groups are still stepping their envs — by the time the last
            # group completes, most of the batch is already resident.
            for k in ("obs", "actions", "rewards", "terminated", "done",
                      "next_obs", "ret", "len"):
                b[k] = jax.device_put(np.stack(b[k]))
            b["dist"] = jax.device_put(
                jax.tree_util.tree_map(
                    lambda *xs: np.stack(xs), *b["dist"]
                )
            )

    import concurrent.futures

    # shared-normalization adapters: normalize the window under start-of-
    # window statistics, merge folds deterministically at the end (see
    # ObsNormMixin.begin_deferred_fold — scheduler-independent results)
    deferred = hasattr(vec_env, "begin_deferred_fold")
    if deferred:
        vec_env.begin_deferred_fold()
    try:
        with concurrent.futures.ThreadPoolExecutor(n_groups) as pool:
            futures = [pool.submit(run_group, g) for g in range(n_groups)]
            for f in futures:
                f.result()  # re-raises any group's exception
    finally:
        if deferred:
            vec_env.end_deferred_fold()

    # (T, m_g, ...) per group → (T, N, ...) by env-axis concatenation —
    # on device when the groups were staged (their arrays already live
    # there), host-side with one transfer per field otherwise
    if stage_to_device:
        cat = lambda k: jnp.concatenate(
            [buf[g][k] for g in range(n_groups)], axis=1
        )
        old_dist = jax.tree_util.tree_map(
            lambda *xs: jnp.concatenate(xs, axis=1),
            *[buf[g]["dist"] for g in range(n_groups)],
        )
    else:
        cat = lambda k: jnp.asarray(
            np.concatenate(
                [np.stack(buf[g][k]) for g in range(n_groups)], axis=1
            )
        )
        dist_groups = [
            jax.tree_util.tree_map(lambda *xs: np.stack(xs), *buf[g]["dist"])
            for g in range(n_groups)
        ]
        old_dist = jax.tree_util.tree_map(
            lambda *xs: jnp.asarray(np.concatenate(xs, axis=1)), *dist_groups
        )
    return Trajectory(
        obs=cat("obs"),
        actions=cat("actions"),
        rewards=cat("rewards").astype(jnp.float32),
        terminated=cat("terminated"),
        done=cat("done"),
        old_dist=old_dist,
        next_obs=cat("next_obs"),
        episode_return=cat("ret").astype(jnp.float32),
        episode_length=cat("len"),
    )
