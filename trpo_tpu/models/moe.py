"""Mixture-of-experts policy torso — the expert-parallel model family.

The reference has exactly one network shape (a 64-tanh MLP,
``trpo_inksci.py:38-40``). This module adds a soft (dense) mixture of
experts: ``K`` parallel MLP torsos whose outputs are blended by a learned
softmax gate, feeding the usual distribution head. Soft routing is chosen
deliberately over hard top-k:

* it is smooth, so the natural-gradient machinery — which differentiates
  the policy TWICE (the FVP is ``jvp(grad(kl))``, SURVEY §3.4) — needs no
  straight-through estimators or routing discontinuities;
* it is one batched einsum per layer over a stacked ``(K, d_in, d_out)``
  weight tensor — a single large MXU contraction instead of K small ones.

TPU mapping (the "EP" mesh axis): every expert-stacked leaf has leading
axis ``K`` and shards as ``P("expert", ...)`` (``parallel/tp.py``); the
gate and head replicate. Under GSPMD the per-expert contractions compute
shard-locally and the blend's contraction over ``k`` becomes one
all-reduce — the dense-MoE analogue of Megatron's row-parallel reduce.
The natural-gradient solve keeps the expert sharding end-to-end via the
pytree-domain update (``trpo.make_tree_trpo_update``), exactly like
tensor parallelism does.
"""

from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp

from trpo_tpu.distributions import Categorical, DiagGaussian
from trpo_tpu.models.mlp import ACTIVATIONS, init_linear
from trpo_tpu.models.policy import BoxSpec, DiscreteSpec, Policy

__all__ = ["init_moe_mlp", "apply_moe_mlp", "make_moe_policy"]


def init_moe_mlp(key, n_experts: int, in_dim: int, hidden, out_dim: int):
    """Expert-stacked MLP params: each leaf gains a leading ``(K,)`` axis
    (``w (K, d_in, d_out)``, ``b (K, d_out)``) — the layout the
    ``"expert"`` mesh axis shards."""
    sizes = [in_dim, *hidden, out_dim]
    keys = jax.random.split(key, (len(sizes) - 1) * n_experts).reshape(
        len(sizes) - 1, n_experts
    )
    layers = []
    for i, (d_in, d_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        per_expert = [init_linear(keys[i, k], d_in, d_out) for k in
                      range(n_experts)]
        layers.append({
            "w": jnp.stack([p["w"] for p in per_expert]),
            "b": jnp.stack([p["b"] for p in per_expert]),
        })
    return {"layers": layers}


def apply_moe_mlp(params, gate_weights, x, activation="tanh",
                  compute_dtype=jnp.float32):
    """All experts forward densely, then blend by the gate.

    ``x (B, d)``, ``gate_weights (B, K)`` → ``(B, out)``. One einsum per
    layer over the stacked weights; the final blend contracts the expert
    axis (the all-reduce point under expert sharding)."""
    act = ACTIVATIONS[activation]
    cd = compute_dtype
    h = jnp.asarray(x, cd)  # (B, d); gains the expert axis at layer 0
    layers = params["layers"]
    for i, layer in enumerate(layers):
        w = jnp.asarray(layer["w"], cd)
        b = jnp.asarray(layer["b"], cd)
        eq = "bi,kio->bko" if h.ndim == 2 else "bki,kio->bko"
        h = jnp.einsum(eq, h, w) + b[None]
        if i < len(layers) - 1:
            h = act(h)
    # blend: contract the expert axis with the gate — psum under sharding
    out = jnp.einsum("bko,bk->bo", h, jnp.asarray(gate_weights, cd))
    return jnp.asarray(out, jnp.float32)


def make_moe_policy(
    obs_shape: Tuple[int, ...],
    action_spec,
    hidden: Tuple[int, ...] = (64,),
    n_experts: int = 4,
    activation: str = "tanh",
    init_log_std: float = 0.0,
    compute_dtype=jnp.float32,
) -> Policy:
    """Soft-MoE policy: gate(obs) blends ``n_experts`` MLP torsos into the
    distribution head. Same :class:`Policy` contract as ``make_policy`` —
    every consumer (rollout, critic, the fused update) is unchanged."""
    if activation not in ACTIVATIONS:
        raise KeyError(
            f"unknown activation {activation!r}; have {sorted(ACTIVATIONS)}"
        )
    if n_experts < 2:
        raise ValueError(f"n_experts must be >= 2, got {n_experts}")
    if isinstance(action_spec, DiscreteSpec):
        out_dim, dist = action_spec.n, Categorical
    elif isinstance(action_spec, BoxSpec):
        out_dim, dist = action_spec.dim, DiagGaussian
    else:
        raise TypeError(f"unsupported action spec: {action_spec!r}")
    if len(obs_shape) != 1:
        raise ValueError("MoE torso takes 1-D observations")
    obs_dim = math.prod(obs_shape)
    feat_dim = hidden[-1] if hidden else obs_dim

    def init(key):
        k_gate, k_experts, k_head = jax.random.split(key, 3)
        params = {
            "gate": init_linear(k_gate, obs_dim, n_experts, scale=0.01),
            "experts": init_moe_mlp(
                k_experts, n_experts, obs_dim, hidden[:-1], feat_dim
            ),
            # small final scale: near-uniform initial policy (models/mlp.py)
            "head": init_linear(k_head, feat_dim, out_dim, scale=0.01),
        }
        if dist is DiagGaussian:
            params["log_std"] = jnp.full((out_dim,), init_log_std,
                                         jnp.float32)
        return params

    def apply(params, obs):
        x = obs.reshape(obs.shape[0], -1)
        cd = compute_dtype
        gw = jnp.asarray(params["gate"]["w"], cd)
        gb = jnp.asarray(params["gate"]["b"], cd)
        gate = jax.nn.softmax(jnp.asarray(x, cd) @ gw + gb, axis=-1)
        # activation after the blend: the experts' last layer is the
        # torso's feature layer (mirrors the recurrent torso's convention)
        feats = ACTIVATIONS[activation](
            apply_moe_mlp(
                params["experts"], gate, x, activation, compute_dtype
            )
        )
        hw = jnp.asarray(params["head"]["w"], cd)
        hb = jnp.asarray(params["head"]["b"], cd)
        raw = jnp.asarray(jnp.asarray(feats, cd) @ hw + hb, jnp.float32)
        if dist is Categorical:
            return {"logits": raw}
        return {
            "mean": raw,
            "log_std": jnp.broadcast_to(params["log_std"], raw.shape),
        }

    return Policy(init=init, apply=apply, dist=dist, action_spec=action_spec)
