"""Recurrent (GRU / LSTM) policies for partially observable tasks.

The reference has no recurrence — its only nod to history is a vestigial
``prev_action`` one-hot buffer that is maintained but never fed to the
network (``trpo_inksci.py:31,85-86``, a leftover from its ancestor repo).
This module supplies the real capability: a recurrent cell (GRU or LSTM)
between the MLP torso and the distribution head, so the policy can
integrate observations over time (POMDPs: masked velocities, flickering
pixels, memory tasks).

Both cells share one external contract: the recurrent state is ONE
``(N, state_size)`` array (``state_size = H`` for GRU; ``2H`` for LSTM,
``[h | c]`` packed along the feature axis). Packing keeps every consumer —
the rollout scan's carry, episode-boundary zeroing, the trajectory's
``policy_h`` tensors, the POMDP critic's ``[obs, state]`` features,
checkpointing, mesh sharding — cell-agnostic.

TPU-first design notes:

* The GRU's three gates are computed with TWO fused matmuls per step
  (``x @ Wx`` and ``h @ Wh``, each ``(·, 3H)``) — one MXU pass per operand
  instead of six small ones; gate nonlinearities fuse into the matmul
  epilogue under XLA.
* Sequence application is a ``lax.scan`` over time of that step — static
  shapes, compiled once.  Episode boundaries inside a rollout window are
  handled *in-graph*: a per-step ``reset`` flag zeroes the hidden state
  before the step consumes it, so one fixed-shape ``(T, N)`` window can
  contain many episodes (the same packing the feedforward path uses).
* The hidden state that enters a training window (``h0``) is carried data,
  not a parameter: ``apply`` wraps it in ``stop_gradient`` — gradients do
  not flow across window boundaries (truncated BPTT at the window length).

The TRPO update machinery (``trpo_tpu.trpo``) is reused untouched: its loss
body only touches observations through ``policy.apply(params, batch.obs)``
and reduces with shape-agnostic weighted means, so a recurrent batch simply
keeps the ``(T, N)`` axes and passes a :class:`SeqObs` pytree where the
feedforward path passes a flat ``(B, obs)`` array.
"""

from __future__ import annotations

import math
from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from trpo_tpu.distributions import Categorical, DiagGaussian
from trpo_tpu.models.mlp import ACTIVATIONS, apply_mlp, init_linear, init_mlp
from trpo_tpu.models.policy import BoxSpec, DiscreteSpec

__all__ = [
    "SeqObs",
    "RecurrentPolicy",
    "init_gru",
    "gru_step",
    "init_lstm",
    "lstm_step",
    "make_recurrent_policy",
]


class SeqObs(NamedTuple):
    """The "observation" a recurrent policy's ``apply`` consumes: a whole
    time-major window plus the state context needed to replay it."""
    obs: jax.Array      # (T, N, *obs_shape)
    reset: jax.Array    # (T, N) bool — hidden state is zeroed BEFORE step t
    h0: jax.Array       # (N, H) hidden state entering the window


class RecurrentPolicy(NamedTuple):
    """`Policy` plus the recurrent surface.

    ``apply`` takes a :class:`SeqObs` (not a flat obs array) and returns
    dist params with leading ``(T, N)``; ``step``/``initial_state`` are the
    single-timestep interface the rollout threads through its scan.
    """
    init: Callable[[jax.Array], Any]
    apply: Callable[[Any, SeqObs], Any]
    dist: Any
    action_spec: Any
    initial_state: Callable[[int], jax.Array]  # n_envs -> (N, state) zeros
    step: Callable[[Any, jax.Array, jax.Array], Tuple[jax.Array, Any]]
    hidden_size: int     # the cell's H
    state_size: int = 0  # carried-state width: H (GRU) or 2H (LSTM [h|c]);
    #                      0 is a pre-state_size default, see make_*
    head: Any = None     # (params, state (..., S)) -> dist params — the
    #                      state→dist head alone, exposed so the serving
    #                      engine (serve/session.py) can recompute it
    #                      PER ROW inside a batched epoch: the narrow
    #                      head matmul is the one op whose XLA lowering
    #                      varies with batch width, so a row-mapped head
    #                      is what keeps epoch-batched actions bit-exact
    #                      with batch-1 stepping at every rung


def init_gru(key, in_dim: int, hidden: int):
    """GRU parameters with fused gate weights: ``wx (in, 3H)``,
    ``wh (H, 3H)``, gate order ``[reset, update, candidate]``."""
    k_x, k_h = jax.random.split(key)
    # Orthogonal per gate block (standard RNN init), assembled fused.
    ortho = jax.nn.initializers.orthogonal(1.0)
    wx = jnp.concatenate(
        [ortho(k, (in_dim, hidden), jnp.float32)
         for k in jax.random.split(k_x, 3)], axis=1,
    )
    wh = jnp.concatenate(
        [ortho(k, (hidden, hidden), jnp.float32)
         for k in jax.random.split(k_h, 3)], axis=1,
    )
    return {"wx": wx, "wh": wh, "b": jnp.zeros((3 * hidden,), jnp.float32)}


def _gru_from_xw(params, h, xw, compute_dtype=jnp.float32):
    """GRU update given the precomputed input projection ``xw = x @ wx + b``.

    Split out so sequence replay can hoist the time-independent ``x @ wx``
    (and the whole torso) into ONE large batched matmul over the window —
    only the ``h @ wh`` recurrence genuinely needs to live in the scan."""
    H = params["wh"].shape[0]
    cd = compute_dtype
    hw = jnp.asarray(h, cd) @ jnp.asarray(params["wh"], cd)
    xr, xz, xn = xw[..., :H], xw[..., H:2 * H], xw[..., 2 * H:]
    hr, hz, hn = hw[..., :H], hw[..., H:2 * H], hw[..., 2 * H:]
    r = jax.nn.sigmoid(xr + hr)
    z = jax.nn.sigmoid(xz + hz)
    n = jnp.tanh(xn + r * hn)
    h_new = (1.0 - z) * n + z * jnp.asarray(h, cd)
    return jnp.asarray(h_new, jnp.float32)


def _input_proj(params, x, compute_dtype=jnp.float32):
    """``x @ wx + b`` — the gates' input half, batchable over any axes."""
    cd = compute_dtype
    return jnp.asarray(x, cd) @ jnp.asarray(params["wx"], cd) + jnp.asarray(
        params["b"], cd
    )


def gru_step(params, h, x, compute_dtype=jnp.float32):
    """One GRU step, batched over leading axes. Two fused matmuls; solver-
    facing output stays fp32 (same contract as ``apply_mlp``)."""
    return _gru_from_xw(
        params, h, _input_proj(params, x, compute_dtype), compute_dtype
    )


def init_lstm(key, in_dim: int, hidden: int):
    """LSTM parameters with fused gate weights: ``wx (in, 4H)``,
    ``wh (H, 4H)``, gate order ``[input, forget, cell, output]``; the
    forget-gate bias starts at 1.0 (the standard long-memory init)."""
    k_x, k_h = jax.random.split(key)
    ortho = jax.nn.initializers.orthogonal(1.0)
    wx = jnp.concatenate(
        [ortho(k, (in_dim, hidden), jnp.float32)
         for k in jax.random.split(k_x, 4)], axis=1,
    )
    wh = jnp.concatenate(
        [ortho(k, (hidden, hidden), jnp.float32)
         for k in jax.random.split(k_h, 4)], axis=1,
    )
    b = jnp.zeros((4 * hidden,), jnp.float32)
    b = b.at[hidden:2 * hidden].set(1.0)  # forget gate
    return {"wx": wx, "wh": wh, "b": b}


def _lstm_from_xw(params, state, xw, compute_dtype=jnp.float32):
    """LSTM update given the precomputed input projection. ``state`` is the
    packed ``[h | c]`` ``(..., 2H)`` array (see module docstring)."""
    H = params["wh"].shape[0]
    cd = compute_dtype
    h, c = state[..., :H], state[..., H:]
    hw = jnp.asarray(h, cd) @ jnp.asarray(params["wh"], cd)
    xi, xf, xg, xo = (
        xw[..., :H], xw[..., H:2 * H], xw[..., 2 * H:3 * H], xw[..., 3 * H:]
    )
    hi, hf, hg, ho = (
        hw[..., :H], hw[..., H:2 * H], hw[..., 2 * H:3 * H], hw[..., 3 * H:]
    )
    i = jax.nn.sigmoid(xi + hi)
    f = jax.nn.sigmoid(xf + hf)
    g = jnp.tanh(xg + hg)
    o = jax.nn.sigmoid(xo + ho)
    c_new = f * jnp.asarray(c, cd) + i * g
    h_new = o * jnp.tanh(c_new)
    return jnp.asarray(
        jnp.concatenate([h_new, c_new], axis=-1), jnp.float32
    )


def lstm_step(params, state, x, compute_dtype=jnp.float32):
    """One LSTM step over the packed ``[h | c]`` state, batched over
    leading axes."""
    return _lstm_from_xw(
        params, state, _input_proj(params, x, compute_dtype), compute_dtype
    )


# cell name -> (param key/init, step-from-xw, gate count, state multiple)
_CELLS = {
    "gru": (init_gru, _gru_from_xw, 3, 1),
    "lstm": (init_lstm, _lstm_from_xw, 4, 2),
}


def make_recurrent_policy(
    obs_shape: Tuple[int, ...],
    action_spec,
    hidden: Tuple[int, ...] = (64,),
    gru_size: int = 64,
    activation: str = "tanh",
    init_log_std: float = 0.0,
    compute_dtype=jnp.float32,
    cell: str = "gru",
) -> RecurrentPolicy:
    """MLP torso → recurrent cell(``gru_size``) → linear head.

    ``cell`` selects the recurrence: ``"gru"`` (default) or ``"lstm"``
    (packed ``[h | c]`` state — see module docstring). ``hidden`` sizes the
    torso (activation applied after every torso layer, including the last —
    the cell is the "output layer" of the torso stack). 1-D observations
    only; a conv torso can be composed later the same way the feedforward
    path does it.
    """
    if activation not in ACTIVATIONS:
        raise KeyError(
            f"unknown activation {activation!r}; have {sorted(ACTIVATIONS)}"
        )
    if cell not in _CELLS:
        raise KeyError(f"unknown cell {cell!r}; have {sorted(_CELLS)}")
    cell_init, cell_from_xw, _n_gates, state_mult = _CELLS[cell]
    if isinstance(action_spec, DiscreteSpec):
        out_dim, dist = action_spec.n, Categorical
    elif isinstance(action_spec, BoxSpec):
        out_dim, dist = action_spec.dim, DiagGaussian
    else:
        raise TypeError(f"unsupported action spec: {action_spec!r}")
    obs_dim = math.prod(obs_shape)
    feat_dim = hidden[-1] if hidden else obs_dim
    act = ACTIVATIONS[activation]

    def init(key):
        k_torso, k_gru, k_head = jax.random.split(key, 3)
        params = {
            cell: cell_init(k_gru, feat_dim, gru_size),
            # small final scale: near-uniform initial policy (models/mlp.py)
            "head": init_linear(k_head, gru_size, out_dim, scale=0.01),
        }
        if hidden:
            # torso as an MLP whose "output layer" is the last hidden size;
            # apply_mlp skips the activation on the final layer, so it is
            # applied in _features below.
            params["torso"] = init_mlp(
                k_torso, obs_dim, hidden[:-1], hidden[-1], final_scale=None
            )
        if dist is DiagGaussian:
            params["log_std"] = jnp.full((out_dim,), init_log_std, jnp.float32)
        return params

    def _features(params, obs):
        x = obs.reshape(obs.shape[:-len(obs_shape)] + (obs_dim,))
        if hidden:
            x = act(apply_mlp(params["torso"], x, activation, compute_dtype))
        return x

    def _head(params, state):
        # LSTM: the head (like the next step's projections) consumes the h
        # half of the packed state; c is memory only
        h = state[..., :gru_size]
        w = jnp.asarray(params["head"]["w"], compute_dtype)
        b = jnp.asarray(params["head"]["b"], compute_dtype)
        raw = jnp.asarray(jnp.asarray(h, compute_dtype) @ w + b, jnp.float32)
        if dist is Categorical:
            return {"logits": raw}
        return {
            "mean": raw,
            "log_std": jnp.broadcast_to(params["log_std"], raw.shape),
        }

    def initial_state(n_envs: int):
        return jnp.zeros((n_envs, gru_size * state_mult), jnp.float32)

    def step(params, h, obs):
        """(params, state (N,S), obs (N,*o)) -> (state', dist (N,...))."""
        h_new = cell_from_xw(
            params[cell],
            h,
            _input_proj(params[cell], _features(params, obs), compute_dtype),
            compute_dtype,
        )
        return h_new, _head(params, h_new)

    def apply(params, seq: SeqObs):
        """Replay a window: dist params with leading (T, N).

        The torso and the gates' input projection are time-independent, so
        they run as ONE (T·N)-row matmul each BEFORE the scan (large MXU
        tiles); the scan body is only the (N, H)·(H, gates·H) recurrence."""
        h0 = jax.lax.stop_gradient(seq.h0)  # truncated BPTT at the window
        xw = _input_proj(
            params[cell], _features(params, seq.obs), compute_dtype
        )  # (T, N, gates·H)

        def scan_step(h, inputs):
            xw_t, reset_t = inputs
            h = jnp.where(reset_t[:, None], 0.0, h)
            h = cell_from_xw(params[cell], h, xw_t, compute_dtype)
            return h, h

        _, hs = jax.lax.scan(scan_step, h0, (xw, seq.reset))
        return _head(params, hs)

    return RecurrentPolicy(
        init=init,
        apply=apply,
        dist=dist,
        action_spec=action_spec,
        initial_state=initial_state,
        step=step,
        hidden_size=gru_size,
        state_size=gru_size * state_mult,
        head=_head,
    )
