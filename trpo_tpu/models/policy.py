"""Policy abstraction: obs -> distribution parameters.

Replaces the reference's hard-wired discrete softmax head
(``trpo_inksci.py:26,38-40`` — which asserts ``Discrete`` action spaces by
construction). A :class:`Policy` bundles a pure ``init`` and ``apply`` with
the matching distribution; continuous (Box) action spaces get a
state-independent learned ``log_std`` head, the standard TRPO/MuJoCo
parameterization required by BASELINE.json.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from trpo_tpu.distributions import Categorical, DiagGaussian
from trpo_tpu.models.mlp import apply_mlp, init_mlp
from trpo_tpu.models.conv import apply_atari_torso, init_atari_torso

__all__ = ["DiscreteSpec", "BoxSpec", "Policy", "make_policy", "spec_from_env"]


@dataclasses.dataclass(frozen=True)
class DiscreteSpec:
    """n discrete actions (gym/gymnasium ``Discrete``)."""
    n: int


@dataclasses.dataclass(frozen=True)
class BoxSpec:
    """dim-dimensional continuous actions (gym/gymnasium ``Box``)."""
    dim: int


class Policy(NamedTuple):
    init: Callable[[jax.Array], Any]            # key -> params pytree
    apply: Callable[[Any, jax.Array], Any]      # (params, obs) -> dist params
    dist: Any                                   # Categorical | DiagGaussian
    action_spec: Any
    # Structural metadata for the plain-MLP fast path (None for conv /
    # MoE / recurrent policies): lets the update layer choose the fused
    # Pallas FVP kernel (ops/fused_fvp.py) when the architecture matches.
    mlp_spec: Any = None
    # ``apply`` with the matmul compute dtype overridden per call:
    # ``apply_cast(params, obs, dtype) -> dist params``. This is the
    # solver precision ladder's bf16 FVP boundary (cfg.fvp_dtype): the
    # Fisher-vector matvec re-runs the forward/tangent matmuls in bf16
    # while params, dist outputs, and every CG accumulator stay f32.
    # None for model families without a castable forward (recurrent,
    # MoE) — the update layer rejects fvp_dtype="bf16" there.
    apply_cast: Any = None


def make_policy(
    obs_shape: Tuple[int, ...],
    action_spec,
    hidden: Tuple[int, ...] = (64,),
    activation: str = "tanh",
    init_log_std: float = 0.0,
    compute_dtype=jnp.float32,
    conv_torso: Optional[bool] = None,
) -> Policy:
    """Build a policy for ``obs_shape`` / ``action_spec``.

    1-D observations get an MLP (the reference's shape,
    ``trpo_inksci.py:38-40``, generalized to arbitrary depth); 3-D (H, W, C)
    observations get the Atari conv torso + dense head.
    """
    if conv_torso is None:
        conv_torso = len(obs_shape) == 3

    if isinstance(action_spec, DiscreteSpec):
        out_dim, dist = action_spec.n, Categorical
    elif isinstance(action_spec, BoxSpec):
        out_dim, dist = action_spec.dim, DiagGaussian
    else:
        raise TypeError(f"unsupported action spec: {action_spec!r}")

    if conv_torso:
        if len(obs_shape) != 3:
            raise ValueError("conv torso needs (H, W, C) observations")

        def _feat_dim(torso_params):
            # Derive the flattened feature width from the real forward fn
            # (zero FLOPs) so it can never diverge from apply_atari_torso.
            out = jax.eval_shape(
                apply_atari_torso,
                torso_params,
                jax.ShapeDtypeStruct((1, *obs_shape), jnp.float32),
            )
            return out.shape[-1]

        def init(key):
            k_torso, k_head, k_std = jax.random.split(key, 3)
            torso = init_atari_torso(k_torso, in_channels=obs_shape[2])
            params = {
                "torso": torso,
                "head": init_mlp(k_head, _feat_dim(torso), hidden, out_dim),
            }
            if dist is DiagGaussian:
                params["log_std"] = jnp.full(
                    (out_dim,), init_log_std, jnp.float32
                )
            return params

        def head_forward(params, obs, dtype=None):
            dtype = compute_dtype if dtype is None else dtype
            feats = apply_atari_torso(
                params["torso"], obs, compute_dtype=dtype
            )
            return apply_mlp(params["head"], feats, activation, dtype)
    else:
        obs_dim = math.prod(obs_shape)

        def init(key):
            k_net, _ = jax.random.split(key)
            params = {"net": init_mlp(k_net, obs_dim, hidden, out_dim)}
            if dist is DiagGaussian:
                params["log_std"] = jnp.full(
                    (out_dim,), init_log_std, jnp.float32
                )
            return params

        def head_forward(params, obs, dtype=None):
            obs = obs.reshape(obs.shape[0], -1)
            return apply_mlp(
                params["net"], obs, activation,
                compute_dtype if dtype is None else dtype,
            )

    def _apply(params, obs, dtype):
        raw = head_forward(params, obs, dtype)
        if dist is Categorical:
            return {"logits": raw}
        log_std = jnp.broadcast_to(params["log_std"], raw.shape)
        return {"mean": raw, "log_std": log_std}

    def apply(params, obs):
        return _apply(params, obs, None)

    def apply_cast(params, obs, dtype):
        """``apply`` with the matmul dtype overridden (f32 everywhere
        else) — the fvp_dtype="bf16" matvec boundary."""
        return _apply(params, obs, dtype)

    mlp_spec = None
    if not conv_torso:
        mlp_spec = {
            "activation": activation,
            "compute_dtype": compute_dtype,
            "hidden": tuple(hidden),
        }
    return Policy(
        init=init,
        apply=apply,
        dist=dist,
        action_spec=action_spec,
        mlp_spec=mlp_spec,
        apply_cast=apply_cast,
    )


def spec_from_env(env) -> Tuple[Tuple[int, ...], Any]:
    """(obs_shape, action_spec) from a trpo_tpu env or gymnasium env."""
    # trpo_tpu pure-JAX envs expose these directly.
    if hasattr(env, "obs_shape") and hasattr(env, "action_spec"):
        return tuple(env.obs_shape), env.action_spec
    # gymnasium
    obs_shape = tuple(env.observation_space.shape)
    space = env.action_space
    if hasattr(space, "n"):
        return obs_shape, DiscreteSpec(int(space.n))
    return obs_shape, BoxSpec(int(space.shape[0]))
