"""Convolutional torso for pixel policies (Atari rung of BASELINE.json).

The reference has no conv nets (its only model is a 64-wide MLP,
``trpo_inksci.py:38-40``); the Atari config in ``BASELINE.json`` ("pixel conv
policy, high-param FVP") makes one a build obligation. Layout is NHWC —
channels-last is the TPU-native layout (the MXU consumes the trailing
dimension) — and the filter spec is the classic Nature-DQN torso
(8×8/4 → 4×4/2 → 3×3/1), whose large channel counts map well onto 128-lane
tiles.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["init_atari_torso", "apply_atari_torso", "ATARI_TORSO_SPEC"]

# (kernel_h, kernel_w, out_channels, stride)
ATARI_TORSO_SPEC = ((8, 8, 32, 4), (4, 4, 64, 2), (3, 3, 64, 1))

_DIMSPEC = ("NHWC", "HWIO", "NHWC")


def init_atari_torso(key, in_channels: int = 4, spec=ATARI_TORSO_SPEC):
    keys = jax.random.split(key, len(spec))
    convs = []
    c_in = in_channels
    for k, (kh, kw, c_out, _stride) in zip(keys, spec):
        fan_in = kh * kw * c_in
        w = jax.random.normal(k, (kh, kw, c_in, c_out), jnp.float32)
        w = w * jnp.sqrt(2.0 / fan_in)
        convs.append({"w": w, "b": jnp.zeros((c_out,), jnp.float32)})
        c_in = c_out
    return {"convs": convs}


def apply_atari_torso(
    params, x, spec=ATARI_TORSO_SPEC, compute_dtype=jnp.float32
):
    """``x``: (N, H, W, C) uint8 or float. Returns (N, features) fp32."""
    h = jnp.asarray(x, compute_dtype)
    if x.dtype == jnp.uint8:
        h = h / jnp.asarray(255.0, compute_dtype)
    for layer, (_kh, _kw, _c, stride) in zip(params["convs"], spec):
        w = jnp.asarray(layer["w"], compute_dtype)
        b = jnp.asarray(layer["b"], compute_dtype)
        h = lax.conv_general_dilated(
            h, w, window_strides=(stride, stride), padding="VALID",
            dimension_numbers=_DIMSPEC,
        )
        h = jax.nn.relu(h + b)
    h = h.reshape(h.shape[0], -1)
    return jnp.asarray(h, jnp.float32)
