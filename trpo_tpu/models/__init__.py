"""Policy and value networks (pure-functional pytree modules)."""

from trpo_tpu.models.mlp import init_mlp, apply_mlp, init_linear  # noqa: F401
from trpo_tpu.models.conv import init_atari_torso, apply_atari_torso  # noqa: F401
from trpo_tpu.models.policy import (  # noqa: F401
    DiscreteSpec,
    BoxSpec,
    Policy,
    make_policy,
    spec_from_env,
)
from trpo_tpu.models.recurrent import (  # noqa: F401
    RecurrentPolicy,
    SeqObs,
    make_recurrent_policy,
)
from trpo_tpu.models.moe import make_moe_policy  # noqa: F401
