"""Pure-functional MLPs.

The reference's policy is a prettytensor one-hidden-layer tanh net with a
softmax head (``trpo_inksci.py:38-40``) and its critic a 64-relu-64-relu-1
net (``utils.py:59-61``). Here networks are explicit pytrees of
``{"w", "b"}`` dicts with a pure ``apply`` — no module framework, so params
flow directly through ``ravel_pytree`` (the flat-vector contract, SURVEY §1)
and through ``jax.sharding`` annotations for tensor-sharded wide layers.

Compute dtype: ``apply_mlp`` optionally casts to bfloat16 for the matmuls
(MXU-friendly) while keeping params and outputs fp32 — the trust-region
solve itself always runs fp32 (see ``ops/cg.py``).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

__all__ = ["init_linear", "init_mlp", "apply_mlp", "ACTIVATIONS"]

ACTIVATIONS = {
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
    "gelu": jax.nn.gelu,
    "elu": jax.nn.elu,
}


def init_linear(key, in_dim: int, out_dim: int, scale: float | None = None):
    """Orthogonal weight init (standard for on-policy RL), zero bias."""
    if scale is None:
        scale = float(jnp.sqrt(2.0))
    w = jax.nn.initializers.orthogonal(scale)(key, (in_dim, out_dim), jnp.float32)
    return {"w": w, "b": jnp.zeros((out_dim,), jnp.float32)}


def init_mlp(
    key,
    in_dim: int,
    hidden: Sequence[int],
    out_dim: int,
    final_scale: float = 0.01,
):
    """Init an MLP ``in_dim -> hidden... -> out_dim``.

    The small ``final_scale`` keeps the initial policy near-uniform /
    near-zero-mean, which stabilizes early trust-region steps.
    """
    sizes = [in_dim, *hidden, out_dim]
    keys = jax.random.split(key, len(sizes) - 1)
    layers = []
    for i, (k, d_in, d_out) in enumerate(zip(keys, sizes[:-1], sizes[1:])):
        scale = final_scale if i == len(sizes) - 2 else None
        layers.append(init_linear(k, d_in, d_out, scale))
    return {"layers": layers}


def apply_mlp(params, x, activation: str = "tanh", compute_dtype=jnp.float32):
    """Forward pass; activation on all but the last layer.

    Matmuls run in ``compute_dtype`` (bf16 on TPU keeps them on the MXU at
    full rate); the result is returned in fp32.
    """
    act = ACTIVATIONS[activation]
    h = jnp.asarray(x, compute_dtype)
    layers = params["layers"]
    for i, layer in enumerate(layers):
        w = jnp.asarray(layer["w"], compute_dtype)
        b = jnp.asarray(layer["b"], compute_dtype)
        h = h @ w + b
        if i < len(layers) - 1:
            h = act(h)
    return jnp.asarray(h, jnp.float32)
