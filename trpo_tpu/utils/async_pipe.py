"""Dispatch/drain machinery for the asynchronous host-env iteration
pipeline (``agent.TRPOAgent.learn`` with ``cfg.host_async_pipeline``).

The serial host-env loop pays a full host↔device round trip per iteration
just to FETCH the stats pytree it logs (~100 ms on a tunneled TPU,
ARCHITECTURE.md's measurement) — on the critical path, after the update
and before the next rollout. The async pipeline dispatches the device
update and hands the (still-pending) stats pytree to a :class:`StatsDrain`
instead: a background thread blocks on the transfer, so logging,
stop-condition evaluation and user callbacks ride behind the NEXT
iteration's host env stepping rather than in front of it.

Ordering contract (pinned by ``tests/test_async_pipeline.py``): stats are
delivered to the consumer strictly in submission order, exactly once each
— a FIFO queue serviced by one thread gives this for free — and an early
stop still delivers every iteration submitted before the stop, so the log
never has holes. Consumer exceptions (e.g. the NaN-entropy abort) are
captured and re-raised on the main thread at the next ``raise_if_failed``
/ ``drain`` / ``close`` call, preserving the exception type the serial
driver would have raised.

Boundedness (PR 3, closing the PR-1 review's open item): ``maxsize``
bounds the queue. On a link where the per-item stats fetch exceeds the
iteration time, an unbounded queue let stop conditions lag arbitrarily
and undrained device buffers pile up; with a bound, ``submit`` BLOCKS
once ``maxsize`` items are in flight — natural backpressure that caps the
stop-condition lag at the bound (the agent passes
``cfg.stats_drain_maxsize``, default 2 — the documented ≤2-iteration
overshoot) while costing nothing when the drain keeps up. ``depth`` and
``high_water`` are observable gauges; the health monitor
(``trpo_tpu.obs.health``) warns when the bound is hit.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Optional

import jax

__all__ = ["StatsDrain"]

_SENTINEL = object()


class StatsDrain:
    """Background fetch-and-consume of device stats pytrees, in order.

    ``consume(tag, host_stats)`` runs on the drain thread with the
    device→host transfer already done; return a truthy value to request a
    stop (the main loop polls :attr:`stop_requested`). After an error the
    drain stops consuming (remaining items are discarded so ``drain``
    cannot deadlock — and so a bounded ``submit`` can never block forever
    behind a dead consumer) and the first exception is re-raised on the
    main thread.

    ``maxsize > 0`` bounds the queue: ``submit`` blocks while ``maxsize``
    items are pending (see module docstring). 0 = unbounded (the PR-1
    behavior, kept for direct users of this class).
    """

    def __init__(
        self,
        consume: Callable[[Any, Any], Any],
        timer=None,
        span_name: str = "stats_drain",
        maxsize: int = 0,
        span_context: tuple = (),
    ):
        self._consume = consume
        self._timer = timer
        self._span_name = span_name
        # ONE fixed context for every drain span (a PhaseTimer
        # current_context() capture): per-submit capture would split the
        # stage's timing across summary keys depending on which call site
        # happened to submit (inside vs outside the rollout phase)
        self._span_context = tuple(span_context)
        self.maxsize = maxsize
        self._q: queue.Queue = queue.Queue(maxsize)
        self._gauge_lock = threading.Lock()
        self._high_water = 0
        self._error: Optional[BaseException] = None
        self._stop = threading.Event()
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name="trpo-stats-drain", daemon=True
        )
        self._thread.start()

    # -- main-thread surface ----------------------------------------------

    def submit(self, tag, device_stats) -> None:
        """Enqueue one iteration's (still-pending) stats pytree; the drain
        thread does the device_get. Non-blocking while the queue is below
        ``maxsize``; at the bound it blocks until the drain catches up
        (backpressure — the documented stop-condition lag cap)."""
        if self._closed:
            raise RuntimeError("StatsDrain is closed")
        self._q.put((tag, device_stats))
        with self._gauge_lock:
            self._high_water = max(self._high_water, self._q.qsize())

    @property
    def depth(self) -> int:
        """Items currently pending (approximate, by nature of a live
        queue) — a host-side gauge, no device sync."""
        return self._q.qsize()

    @property
    def high_water(self) -> int:
        """Deepest the queue has been at any submit."""
        with self._gauge_lock:
            return self._high_water

    @property
    def stop_requested(self) -> bool:
        """True once ``consume`` returned truthy (or errored)."""
        return self._stop.is_set()

    def raise_if_failed(self) -> None:
        """Re-raise the first drain-thread exception on the caller."""
        if self._error is not None:
            raise self._error

    def drain(self) -> None:
        """Block until everything submitted so far is consumed, then
        surface any drain-thread error."""
        self._q.join()
        self.raise_if_failed()

    def close(self) -> None:
        """Drain, stop the thread, and surface any error. Idempotent."""
        if not self._closed:
            self._closed = True
            self._q.put(_SENTINEL)
            self._thread.join()
        self.raise_if_failed()

    # -- drain thread ------------------------------------------------------

    def _run(self) -> None:
        while True:
            item = self._q.get()
            try:
                if item is _SENTINEL:
                    return
                if self._error is not None:
                    continue  # post-error: discard, but keep join() live
                tag, stats = item
                span = (
                    self._timer.span(
                        self._span_name, context=self._span_context
                    )
                    if self._timer is not None
                    else None
                )
                try:
                    host_stats = jax.device_get(stats)
                    if self._consume(tag, host_stats):
                        self._stop.set()
                except BaseException as e:  # noqa: BLE001 — re-raised
                    self._error = e
                    self._stop.set()
                finally:
                    if span is not None:
                        span.end()
            finally:
                self._q.task_done()
