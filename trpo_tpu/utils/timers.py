"""Phase timers (SURVEY §5 tracing/profiling obligation).

The reference records only total wall-clock ("Time elapsed",
``trpo_inksci.py:89,167``). ``PhaseTimer`` gives per-phase cumulative and
per-call timings around rollout / CG-solve / update, and can emit
``jax.profiler`` trace annotations so phases show up named in TPU profiles.

Phases NEST (PR 3): each thread carries a stack of open phase names, and a
phase entered inside another records under the slash-joined path
("rollout/stats_drain"), so summaries attribute time hierarchically. The
async host-env pipeline times stages from more than one thread — the main
loop's rollout/dispatch spans and the drain thread's stats fetches — so
all accounting is lock-protected, :meth:`span` offers an explicit
begin/end handle for stages whose start and finish live in different
scopes, and :meth:`current_context` captures one thread's open-phase stack
so a span created (or recorded) on ANOTHER thread still lands under the
right parent — ``utils/async_pipe.StatsDrain`` takes such a capture as its
fixed ``span_context`` so its drain-thread spans nest deterministically.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import defaultdict
from typing import Tuple

import jax

__all__ = ["PhaseTimer"]


class _Span:
    """An open timing span — ``end()`` records it (idempotent)."""

    __slots__ = ("_timer", "name", "_start", "_done")

    def __init__(self, timer: "PhaseTimer", name: str):
        self._timer = timer
        self.name = name
        self._start = time.perf_counter()
        self._done = False

    def end(self) -> float:
        """Close the span; returns its duration in seconds. Safe to call
        more than once (only the first call records)."""
        dt = time.perf_counter() - self._start
        if not self._done:
            self._done = True
            self._timer.record(self.name, dt)
        return dt


class PhaseTimer:
    def __init__(self, use_jax_profiler: bool = False):
        self.totals = defaultdict(float)
        self.counts = defaultdict(int)
        self.last = {}
        self.use_jax_profiler = use_jax_profiler
        self._lock = threading.Lock()
        self._tls = threading.local()

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def current_context(self) -> Tuple[str, ...]:
        """THIS thread's open-phase path — pass it to :meth:`span` from
        another thread so cross-thread stages nest under the phase that
        launched them (the dispatch/drain split of ``utils/async_pipe``)."""
        return tuple(self._stack())

    def record(self, name: str, seconds: float) -> None:
        """Fold one completed measurement in (thread-safe — the drain
        thread of the async pipeline records here concurrently with the
        main loop's ``phase`` contexts)."""
        with self._lock:
            self.totals[name] += seconds
            self.counts[name] += 1
            self.last[name] = seconds

    def span(self, name: str, context: Tuple[str, ...] = ()) -> _Span:
        """Begin a pipeline-stage span; call ``.end()`` on the returned
        handle where the stage actually finishes — possibly on another
        thread. ``context`` (a :meth:`current_context` capture) prefixes
        the recorded name so the span nests under its launching phase."""
        return _Span(self, "/".join(tuple(context) + (name,)))

    @contextlib.contextmanager
    def phase(self, name: str, block_on=None):
        """Time a phase. Pass ``block_on`` (any jax pytree) to block until
        its computation is done — without it, async dispatch makes device
        phases look free. Nested phases record under the joined path
        ("outer/inner") per thread."""
        stack = self._stack()
        full = "/".join(stack + [name]) if stack else name
        ctx = (
            jax.profiler.TraceAnnotation(full)
            if self.use_jax_profiler
            else contextlib.nullcontext()
        )
        stack.append(name)
        start = time.perf_counter()
        try:
            with ctx:
                yield
                if block_on is not None:
                    jax.block_until_ready(block_on)
        finally:
            stack.pop()
            self.record(full, time.perf_counter() - start)

    def last_ms(self, name: str) -> float:
        with self._lock:
            return self.last.get(name, 0.0) * 1e3

    def mean_ms(self, name: str) -> float:
        with self._lock:
            if not self.counts[name]:
                return 0.0
            return self.totals[name] / self.counts[name] * 1e3

    def summary(self) -> dict:
        with self._lock:
            return {
                name: {
                    "mean_ms": self.totals[name] / self.counts[name] * 1e3,
                    "total_s": self.totals[name],
                    "calls": self.counts[name],
                }
                for name in self.totals
            }
