"""Phase timers (SURVEY §5 tracing/profiling obligation).

The reference records only total wall-clock ("Time elapsed",
``trpo_inksci.py:89,167``). ``PhaseTimer`` gives per-phase cumulative and
per-call timings around rollout / CG-solve / update, and can emit
``jax.profiler`` trace annotations so phases show up named in TPU profiles.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict

import jax

__all__ = ["PhaseTimer"]


class PhaseTimer:
    def __init__(self, use_jax_profiler: bool = False):
        self.totals = defaultdict(float)
        self.counts = defaultdict(int)
        self.last = {}
        self.use_jax_profiler = use_jax_profiler

    @contextlib.contextmanager
    def phase(self, name: str, block_on=None):
        """Time a phase. Pass ``block_on`` (any jax pytree) to block until
        its computation is done — without it, async dispatch makes device
        phases look free."""
        ctx = (
            jax.profiler.TraceAnnotation(name)
            if self.use_jax_profiler
            else contextlib.nullcontext()
        )
        start = time.perf_counter()
        with ctx:
            yield
            if block_on is not None:
                jax.block_until_ready(block_on)
        dt = time.perf_counter() - start
        self.totals[name] += dt
        self.counts[name] += 1
        self.last[name] = dt

    def last_ms(self, name: str) -> float:
        return self.last.get(name, 0.0) * 1e3

    def mean_ms(self, name: str) -> float:
        if not self.counts[name]:
            return 0.0
        return self.totals[name] / self.counts[name] * 1e3

    def summary(self) -> dict:
        return {
            name: {
                "mean_ms": self.mean_ms(name),
                "total_s": self.totals[name],
                "calls": self.counts[name],
            }
            for name in self.totals
        }
