"""Shared stdlib HTTP plumbing for the in-process endpoints.

Two subsystems serve HTTP out of a training/serving process: the
introspection endpoint (``obs/server.StatusServer`` — PR 5) and the
policy-inference front end (``serve/server.PolicyServer`` — PR 6).
Both need the same non-negotiables, first proven by the introspection
endpoint and factored here so the contracts stay in ONE place:

* **ThreadingHTTPServer on a daemon thread** — a hung client never
  blocks interpreter exit, and serving never runs on the training or
  batching thread.
* **Silenced ``log_message``/``handle_error``** — scrapes and dropped
  connections (``curl | head``, a scraper timing out mid-response) must
  not spray the console; a broken pipe in ``wfile.write`` is the
  CLIENT's problem.
* **``allow_reuse_address``** — a relaunched run must rebind the same
  port immediately (TIME_WAIT would otherwise hold it for minutes).
* **Port 0 = ephemeral** — the OS picks; the bound port is exposed as
  ``.port`` so callers can print/announce it.

Handlers are plain callables returning ``(status, content_type,
body_bytes)``: GET handlers take no arguments, POST handlers take the
raw request body. A handler raising is a bug in the handler, but it
must degrade to a 500 for THAT request — never kill the server thread
or traceback onto the console (same silence contract as above).

Dynamic paths (the serving tier's session protocol routes by id:
``POST /session/<id>/act``) use ``post_prefix``: ``{prefix:
fn(path, body)}`` — consulted only after the exact tables miss, longest
prefix wins, and the handler receives the FULL path so it can parse the
dynamic segment itself.

Request headers (ISSUE 15 — trace propagation): handlers keep their
zero-argument / ``(body)`` signatures; a handler that needs the
incoming headers (the tracing layer reading ``X-Trace-Id``) calls
:func:`request_headers`, which returns the CURRENT request's header
mapping from a thread-local the dispatcher sets around every handler
invocation (handlers run on the per-connection handler thread, so the
thread-local is exact). Outside a handler it returns ``None``.

ISSUE 16 adds two things. (1) **Unix-domain-socket listeners**
(``uds_path=``): the same routes answered on an ``AF_UNIX`` socket
next to the TCP port — the router's same-host hop skips the TCP stack
(no Nagle, no delayed ACK, no conntrack) while cross-host hops stay
TCP. The UDS listener keeps the data-plane socket settings that ARE
meaningful off-TCP (backlog 128, non-inheritable/close-on-exec fds)
and drops the one that is not (``TCP_NODELAY`` — setting it on an
AF_UNIX socket raises). (2) :class:`AsyncBackgroundServer`: a
single-event-loop HTTP/1.1 server for the router's data plane —
connections are coroutines, not threads, so a thousand keep-alive
clients cost a thousand small state machines instead of a thousand
stacks + GIL handoffs. Exact-table sync handlers keep working (they
run on a small executor with the same :func:`request_headers`
contract); the hot paths register **async** handlers that run ON the
loop (``async fn(path, body, headers)``), where the router's
loop-owned connection pools live.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import http.server
import os
import socket
import socketserver
import threading
from typing import Callable, Dict, Optional, Tuple

__all__ = [
    "BackgroundHTTPServer",
    "AsyncBackgroundServer",
    "request_headers",
]

_tls = threading.local()


def request_headers():
    """The in-flight request's headers (``.get(name)``-able,
    case-insensitive) while called from inside an HTTP handler on this
    server; ``None`` anywhere else."""
    return getattr(_tls, "headers", None)

# handler return type: (status_code, content_type, body)
Response = Tuple[int, str, bytes]


def _cleanup_uds(path: str) -> None:
    """Unlink a stale socket file so a relaunched run can rebind — the
    AF_UNIX equivalent of ``allow_reuse_address`` (binding over an
    existing path raises EADDRINUSE even with no listener alive)."""
    try:
        if os.path.exists(path):
            os.unlink(path)
    except OSError:
        pass


class BackgroundHTTPServer:
    """A stdlib ``ThreadingHTTPServer`` on a background daemon thread,
    routing by exact path.

    ``get``: ``{path: fn() -> (status, ctype, body)}``;
    ``post``: ``{path: fn(body_bytes) -> (status, ctype, body)}``.
    Unknown paths get a 404 carrying ``not_found`` (which should name
    the paths that DO exist — the introspection endpoint's
    "have /status and /metrics" idiom). ``max_body_bytes`` bounds POST
    bodies: an oversized request is refused with 413 before the read,
    so a hostile client cannot balloon the handler thread's memory.

    ``uds_path`` additionally binds the SAME routes on an AF_UNIX
    socket (its own acceptor thread; handlers are shared), exposed as
    ``.uds_path`` so a replica can advertise it for same-host dials.
    """

    def __init__(
        self,
        port: int,
        host: str = "127.0.0.1",
        get: Optional[Dict[str, Callable[[], Response]]] = None,
        post: Optional[Dict[str, Callable[[bytes], Response]]] = None,
        post_prefix: Optional[
            Dict[str, Callable[[str, bytes], Response]]
        ] = None,
        not_found: str = "unknown path",
        thread_name: str = "httpd",
        max_body_bytes: int = 1 << 20,
        uds_path: Optional[str] = None,
    ):
        get_routes = dict(get or {})
        post_routes = dict(post or {})
        # longest prefix first, so "/session/" can coexist with a more
        # specific prefix without registration-order surprises
        prefix_routes = sorted(
            (post_prefix or {}).items(), key=lambda kv: -len(kv[0])
        )
        # which listener served each routed request (ISSUE 16): the
        # replica's /metrics proves same-host traffic actually moved
        # off TCP instead of silently falling back
        self.transport_requests_total = {"tcp": 0, "uds": 0}
        counter_lock = threading.Lock()

        def _respond(handler, status: int, ctype: str, body: bytes) -> None:
            handler.send_response(status)
            handler.send_header("Content-Type", ctype)
            handler.send_header("Content-Length", str(len(body)))
            handler.end_headers()
            handler.wfile.write(body)

        def _run(handler, fn, *args) -> None:
            with counter_lock:
                self.transport_requests_total[handler.via] += 1
            _tls.headers = handler.headers  # request_headers() scope
            try:
                status, ctype, body = fn(*args)
            except Exception as e:  # a handler bug degrades to a 500 for
                # THIS request; the server thread and console stay clean
                status, ctype = 500, "text/plain; charset=utf-8"
                body = f"internal error: {type(e).__name__}".encode()
            finally:
                _tls.headers = None
            _respond(handler, status, ctype, body)

        class _Handler(http.server.BaseHTTPRequestHandler):
            # HTTP/1.1: connections persist across requests (every
            # response here carries Content-Length, so framing is
            # sound). A data plane dies by per-request connection
            # setup — a fresh TCP handshake plus a fresh handler
            # THREAD per request (ThreadingHTTPServer spawns per
            # CONNECTION) costs more than a small model's inference;
            # keep-alive amortizes both across a client's whole run.
            protocol_version = "HTTP/1.1"
            via = "tcp"  # which listener family served this request
            # TCP_NODELAY: a small JSON response held back by Nagle
            # waiting on the peer's delayed ACK adds ~40 ms to a
            # millisecond-scale request; inference traffic is
            # latency-bound, never bandwidth-bound
            disable_nagle_algorithm = True

            def do_GET(handler):  # noqa: N805 — handler, not self
                path = handler.path.split("?", 1)[0]
                fn = get_routes.get(path)
                if fn is None:
                    handler.send_error(404, not_found)
                    return
                _run(handler, fn)

            def do_POST(handler):  # noqa: N805
                path = handler.path.split("?", 1)[0]
                fn = post_routes.get(path)
                args = ()
                if fn is None:
                    for prefix, pfn in prefix_routes:
                        if path.startswith(prefix):
                            fn, args = pfn, (path,)
                            break
                if fn is None:
                    handler.send_error(404, not_found)
                    return
                try:
                    length = int(handler.headers.get("Content-Length", 0))
                except ValueError:
                    length = -1
                if length < 0 or length > max_body_bytes:
                    handler.send_error(413, "request body too large")
                    return
                body = handler.rfile.read(length) if length else b""
                _run(handler, fn, *args, body)

            def log_message(handler, *args):  # noqa: N805
                pass  # requests must not spray the owning console

        class _UdsHandler(_Handler):
            via = "uds"
            # TCP_NODELAY does not exist on AF_UNIX — setting it
            # raises; Nagle never applied either, so nothing is lost
            disable_nagle_algorithm = False

            def address_string(handler):  # noqa: N805 — AF_UNIX peers
                return "uds"        # have no (host, port) to render

        class _Server(http.server.ThreadingHTTPServer):
            daemon_threads = True
            # a relaunched run must be able to rebind the same port
            # immediately (TIME_WAIT would otherwise hold it for minutes)
            allow_reuse_address = True
            # the stdlib default listen backlog is 5: a burst of
            # concurrent clients dialing at once overflows it and the
            # dropped SYNs retransmit after ~1 s — a whole second of
            # connect stall that reads as a p99 cliff. Size the backlog
            # for a data plane, not a debug endpoint.
            request_queue_size = 128

            def __init__(server, *args, **kw):  # noqa: N805
                super().__init__(*args, **kw)
                # live accepted sockets: keep-alive means a connection
                # outlives any one request, and close() must sever them
                # — a closed server still answering on old keep-alive
                # conns (with its components torn down) would look
                # ALIVE to a pooled client, where a real process death
                # looks like a dropped socket
                server._active = set()
                server._active_lock = threading.Lock()

            def process_request(server, request, client_address):  # noqa: N805
                with server._active_lock:
                    server._active.add(request)
                super().process_request(request, client_address)

            def shutdown_request(server, request):  # noqa: N805
                with server._active_lock:
                    server._active.discard(request)
                super().shutdown_request(request)

            def close_active(server) -> None:  # noqa: N805
                with server._active_lock:
                    conns = list(server._active)
                for sock in conns:
                    try:
                        sock.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass

            def handle_error(server, request, client_address):  # noqa: N805
                # a client dropping the connection mid-response raises in
                # wfile.write; the default handler tracebacks onto the
                # console — same silence contract as log_message above
                pass

        class _UdsServer(_Server):
            address_family = socket.AF_UNIX
            allow_reuse_address = False  # meaningless on AF_UNIX — the
            #                              stale path is unlinked instead

            def server_bind(server):  # noqa: N805
                # HTTPServer.server_bind assumes (host, port) — on
                # AF_UNIX the address is a PATH; bind at the TCPServer
                # layer and fill the name fields by hand. The listen fd
                # stays non-inheritable (close-on-exec): a launched
                # replica subprocess must not hold its parent's listener
                # open past exec (PEP 446 default, asserted here so a
                # future stdlib change fails loudly, not silently).
                socketserver.TCPServer.server_bind(server)
                assert not server.socket.get_inheritable()
                server.server_name = "localhost"
                server.server_port = 0

            def get_request(server):  # noqa: N805 — an AF_UNIX accept
                # returns '' as the peer address; BaseHTTPRequestHandler
                # indexes client_address[0] in log helpers, so shape it
                request, _ = server.socket.accept()
                return request, ("uds", 0)

        self._httpd = _Server((host, port), _Handler)
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            name=thread_name,
            daemon=True,
        )
        self._thread.start()

        self.uds_path: Optional[str] = None
        self._uds_httpd = None
        self._uds_thread = None
        if uds_path:
            _cleanup_uds(uds_path)
            self._uds_httpd = _UdsServer(uds_path, _UdsHandler)
            self.uds_path = uds_path
            self._uds_thread = threading.Thread(
                target=self._uds_httpd.serve_forever,
                kwargs={"poll_interval": 0.1},
                name=f"{thread_name}-uds",
                daemon=True,
            )
            self._uds_thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is None:
            return
        httpd.shutdown()
        # sever surviving keep-alive connections: to a pooled client a
        # closed server must look exactly like a dead one (dropped
        # socket), never a live one answering with torn-down components
        httpd.close_active()
        httpd.server_close()
        self._thread.join(timeout=5.0)
        if self._uds_httpd is not None:
            self._uds_httpd.shutdown()
            self._uds_httpd.close_active()
            self._uds_httpd.server_close()
            self._uds_thread.join(timeout=5.0)
            if self.uds_path:
                _cleanup_uds(self.uds_path)


class _CIHeaders(dict):
    """Case-insensitive ``.get`` over lower-cased keys — the shape
    every trace/negotiation consumer already relies on (stdlib
    ``email.message.Message`` is case-insensitive too)."""

    def get(self, name, default=None):  # noqa: A003
        return super().get(name.lower(), default)


class AsyncBackgroundServer:
    """A single-event-loop HTTP/1.1 server on a daemon thread — the
    asyncio half of the serving data plane (ISSUE 16).

    Route tables match :class:`BackgroundHTTPServer` (``get``/``post``/
    ``post_prefix`` of SYNC handlers — they run on a bounded executor
    with the :func:`request_headers` thread-local set, so existing
    control-plane handlers port unchanged), plus ``async_post`` /
    ``async_post_prefix``: ``async fn(path, body, headers) -> (status,
    ctype, body)`` coroutines that run ON the loop — the hot path.
    The owning loop is exposed as ``.loop`` so the router can park its
    connection pools there.

    Listens on TCP (``port``, 0 = ephemeral) and optionally the same
    routes on an AF_UNIX path (``uds_path``) — both acceptors are
    plain asyncio servers with backlog 128; every response carries
    ``Content-Length``, connections are keep-alive by default and
    honor ``Connection: close``.
    """

    def __init__(
        self,
        port: int,
        host: str = "127.0.0.1",
        get: Optional[Dict[str, Callable[[], Response]]] = None,
        post: Optional[Dict[str, Callable[[bytes], Response]]] = None,
        post_prefix: Optional[
            Dict[str, Callable[[str, bytes], Response]]
        ] = None,
        async_post: Optional[Dict[str, Callable]] = None,
        async_post_prefix: Optional[Dict[str, Callable]] = None,
        not_found: str = "unknown path",
        thread_name: str = "ahttpd",
        max_body_bytes: int = 1 << 20,
        uds_path: Optional[str] = None,
        executor_workers: int = 8,
    ):
        self._get = dict(get or {})
        self._post = dict(post or {})
        self._post_prefix = sorted(
            (post_prefix or {}).items(), key=lambda kv: -len(kv[0])
        )
        self._apost = dict(async_post or {})
        self._apost_prefix = sorted(
            (async_post_prefix or {}).items(), key=lambda kv: -len(kv[0])
        )
        self._not_found = not_found
        self._max_body = int(max_body_bytes)
        # loop-owned (incremented only from connection coroutines), so
        # no lock — same listener-family accounting as the threaded
        # server's counters
        self.transport_requests_total = {"tcp": 0, "uds": 0}
        self.host = host
        self.uds_path: Optional[str] = None
        self._want_uds = uds_path
        # sync (control-plane) handlers run here — bounded, so a stuck
        # handler can exhaust the executor but never the loop
        self._executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=executor_workers,
            thread_name_prefix=f"{thread_name}-h",
        )
        self.loop = asyncio.new_event_loop()
        self._servers: list = []
        started = threading.Event()
        boot_err: list = []

        async def _boot():
            try:
                srv = await asyncio.start_server(
                    self._serve_conn, host, port, backlog=128
                )
                self._servers.append(srv)
                self.port = int(srv.sockets[0].getsockname()[1])
                if uds_path:
                    _cleanup_uds(uds_path)
                    usrv = await asyncio.start_unix_server(
                        self._serve_conn, path=uds_path, backlog=128
                    )
                    # close-on-exec audit (PEP 446 default, pinned)
                    assert not usrv.sockets[0].get_inheritable()
                    self._servers.append(usrv)
                    self.uds_path = uds_path
            except Exception as e:  # surface bind errors to the caller
                boot_err.append(e)
            finally:
                started.set()

        loop = self.loop

        def _run_loop():
            asyncio.set_event_loop(loop)
            loop.create_task(_boot())
            loop.run_forever()
            # drain callbacks scheduled during shutdown, then close
            loop.run_until_complete(loop.shutdown_asyncgens())
            loop.close()

        self._thread = threading.Thread(
            target=_run_loop, name=thread_name, daemon=True
        )
        self._thread.start()
        started.wait(timeout=30.0)
        if boot_err:
            self.close()
            raise boot_err[0]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # -- per-connection coroutine -----------------------------------------

    async def _serve_conn(self, reader, writer) -> None:
        sock = writer.get_extra_info("socket")
        via = (
            "uds"
            if sock is not None and sock.family == socket.AF_UNIX
            else "tcp"
        )
        try:
            while True:
                line = await reader.readline()
                if not line:
                    return
                try:
                    method, target, _version = (
                        line.decode("latin-1").rstrip("\r\n").split(" ", 2)
                    )
                except ValueError:
                    return  # unparseable request line: drop the conn
                headers = _CIHeaders()
                while True:
                    hline = await reader.readline()
                    if hline in (b"\r\n", b"\n", b""):
                        break
                    if len(headers) > 100:
                        return
                    name, _, value = (
                        hline.decode("latin-1").partition(":")
                    )
                    headers[name.strip().lower()] = value.strip()
                try:
                    length = int(headers.get("Content-Length") or 0)
                except ValueError:
                    length = -1
                if length < 0 or length > self._max_body:
                    await self._write_response(
                        writer, 413, "text/plain; charset=utf-8",
                        b"request body too large", close=True,
                    )
                    return
                body = (
                    await reader.readexactly(length) if length else b""
                )
                path = target.split("?", 1)[0]
                self.transport_requests_total[via] += 1
                status, ctype, out = await self._handle(
                    method, path, body, headers
                )
                close = (
                    (headers.get("Connection") or "").lower() == "close"
                )
                await self._write_response(
                    writer, status, ctype, out, close=close
                )
                if close:
                    return
        except (
            asyncio.IncompleteReadError,
            ConnectionError,
            asyncio.CancelledError,
        ):
            return  # a dropped client is the client's problem
        except Exception:
            return  # never let one connection's bug spray the console
        finally:
            try:
                writer.close()
            except Exception:
                pass

    _REASONS = {
        200: "OK", 400: "Bad Request", 404: "Not Found",
        409: "Conflict", 413: "Payload Too Large", 429: "Too Many "
        "Requests", 500: "Internal Server Error", 502: "Bad Gateway",
        503: "Service Unavailable", 504: "Gateway Timeout",
    }

    async def _write_response(
        self, writer, status: int, ctype: str, body: bytes,
        close: bool = False,
    ) -> None:
        reason = self._REASONS.get(status, "Unknown")
        head = (
            f"HTTP/1.1 {status} {reason}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(body)}\r\n"
            f"{'Connection: close' + chr(13) + chr(10) if close else ''}"
            "\r\n"
        ).encode("latin-1")
        writer.write(head + body)
        await writer.drain()

    async def _handle(self, method, path, body, headers):
        try:
            if method == "POST":
                afn = self._apost.get(path)
                if afn is None:
                    for prefix, pfn in self._apost_prefix:
                        if path.startswith(prefix):
                            afn = pfn
                            break
                if afn is not None:
                    try:
                        return await afn(path, body, headers)
                    except Exception as e:
                        return (
                            500, "text/plain; charset=utf-8",
                            f"internal error: {type(e).__name__}".encode(),
                        )
                fn = self._post.get(path)
                args = (body,)
                if fn is None:
                    for prefix, pfn in self._post_prefix:
                        if path.startswith(prefix):
                            fn, args = pfn, (path, body)
                            break
                if fn is not None:
                    return await self._run_sync(fn, args, headers)
            elif method == "GET":
                fn = self._get.get(path)
                if fn is not None:
                    return await self._run_sync(fn, (), headers)
            return (
                404, "text/plain; charset=utf-8",
                self._not_found.encode(),
            )
        except asyncio.CancelledError:
            raise
        except Exception as e:
            return (
                500, "text/plain; charset=utf-8",
                f"internal error: {type(e).__name__}".encode(),
            )

    async def _run_sync(self, fn, args, headers):
        """A sync handler on the executor, with the
        :func:`request_headers` thread-local set for its duration —
        the exact contract the threaded server gives it."""

        def _call():
            _tls.headers = headers
            try:
                return fn(*args)
            except Exception as e:
                return (
                    500, "text/plain; charset=utf-8",
                    f"internal error: {type(e).__name__}".encode(),
                )
            finally:
                _tls.headers = None

        return await self.loop.run_in_executor(self._executor, _call)

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        loop, self.loop = self.loop, None
        if loop is None:
            return

        def _stop():
            for srv in self._servers:
                srv.close()
            # cancel the per-connection coroutines so their finally
            # blocks close the sockets — same closed-looks-dead
            # contract as the threaded server's close_active
            for task in asyncio.all_tasks(loop):
                task.cancel()
            loop.call_soon(loop.stop)

        try:
            loop.call_soon_threadsafe(_stop)
        except RuntimeError:
            pass  # loop already gone
        self._thread.join(timeout=5.0)
        self._executor.shutdown(wait=False)
        if self.uds_path:
            _cleanup_uds(self.uds_path)
