"""Shared stdlib HTTP plumbing for the in-process endpoints.

Two subsystems serve HTTP out of a training/serving process: the
introspection endpoint (``obs/server.StatusServer`` — PR 5) and the
policy-inference front end (``serve/server.PolicyServer`` — this PR).
Both need the same non-negotiables, first proven by the introspection
endpoint and factored here so the contracts stay in ONE place:

* **ThreadingHTTPServer on a daemon thread** — a hung client never
  blocks interpreter exit, and serving never runs on the training or
  batching thread.
* **Silenced ``log_message``/``handle_error``** — scrapes and dropped
  connections (``curl | head``, a scraper timing out mid-response) must
  not spray the console; a broken pipe in ``wfile.write`` is the
  CLIENT's problem.
* **``allow_reuse_address``** — a relaunched run must rebind the same
  port immediately (TIME_WAIT would otherwise hold it for minutes).
* **Port 0 = ephemeral** — the OS picks; the bound port is exposed as
  ``.port`` so callers can print/announce it.

Handlers are plain callables returning ``(status, content_type,
body_bytes)``: GET handlers take no arguments, POST handlers take the
raw request body. A handler raising is a bug in the handler, but it
must degrade to a 500 for THAT request — never kill the server thread
or traceback onto the console (same silence contract as above).

Dynamic paths (the serving tier's session protocol routes by id:
``POST /session/<id>/act``) use ``post_prefix``: ``{prefix:
fn(path, body)}`` — consulted only after the exact tables miss, longest
prefix wins, and the handler receives the FULL path so it can parse the
dynamic segment itself.

Request headers (ISSUE 15 — trace propagation): handlers keep their
zero-argument / ``(body)`` signatures; a handler that needs the
incoming headers (the tracing layer reading ``X-Trace-Id``) calls
:func:`request_headers`, which returns the CURRENT request's header
mapping from a thread-local the dispatcher sets around every handler
invocation (handlers run on the per-connection handler thread, so the
thread-local is exact). Outside a handler it returns ``None``.
"""

from __future__ import annotations

import http.server
import threading
from typing import Callable, Dict, Optional, Tuple

__all__ = ["BackgroundHTTPServer", "request_headers"]

_tls = threading.local()


def request_headers():
    """The in-flight request's headers (an ``email.message.Message`` —
    ``.get(name)``-able, case-insensitive) while called from inside an
    HTTP handler on this server; ``None`` anywhere else."""
    return getattr(_tls, "headers", None)

# handler return type: (status_code, content_type, body)
Response = Tuple[int, str, bytes]


class BackgroundHTTPServer:
    """A stdlib ``ThreadingHTTPServer`` on a background daemon thread,
    routing by exact path.

    ``get``: ``{path: fn() -> (status, ctype, body)}``;
    ``post``: ``{path: fn(body_bytes) -> (status, ctype, body)}``.
    Unknown paths get a 404 carrying ``not_found`` (which should name
    the paths that DO exist — the introspection endpoint's
    "have /status and /metrics" idiom). ``max_body_bytes`` bounds POST
    bodies: an oversized request is refused with 413 before the read,
    so a hostile client cannot balloon the handler thread's memory.
    """

    def __init__(
        self,
        port: int,
        host: str = "127.0.0.1",
        get: Optional[Dict[str, Callable[[], Response]]] = None,
        post: Optional[Dict[str, Callable[[bytes], Response]]] = None,
        post_prefix: Optional[
            Dict[str, Callable[[str, bytes], Response]]
        ] = None,
        not_found: str = "unknown path",
        thread_name: str = "httpd",
        max_body_bytes: int = 1 << 20,
    ):
        get_routes = dict(get or {})
        post_routes = dict(post or {})
        # longest prefix first, so "/session/" can coexist with a more
        # specific prefix without registration-order surprises
        prefix_routes = sorted(
            (post_prefix or {}).items(), key=lambda kv: -len(kv[0])
        )

        def _respond(handler, status: int, ctype: str, body: bytes) -> None:
            handler.send_response(status)
            handler.send_header("Content-Type", ctype)
            handler.send_header("Content-Length", str(len(body)))
            handler.end_headers()
            handler.wfile.write(body)

        def _run(handler, fn, *args) -> None:
            _tls.headers = handler.headers  # request_headers() scope
            try:
                status, ctype, body = fn(*args)
            except Exception as e:  # a handler bug degrades to a 500 for
                # THIS request; the server thread and console stay clean
                status, ctype = 500, "text/plain; charset=utf-8"
                body = f"internal error: {type(e).__name__}".encode()
            finally:
                _tls.headers = None
            _respond(handler, status, ctype, body)

        class _Handler(http.server.BaseHTTPRequestHandler):
            # HTTP/1.1: connections persist across requests (every
            # response here carries Content-Length, so framing is
            # sound). A data plane dies by per-request connection
            # setup — a fresh TCP handshake plus a fresh handler
            # THREAD per request (ThreadingHTTPServer spawns per
            # CONNECTION) costs more than a small model's inference;
            # keep-alive amortizes both across a client's whole run.
            protocol_version = "HTTP/1.1"
            # TCP_NODELAY: a small JSON response held back by Nagle
            # waiting on the peer's delayed ACK adds ~40 ms to a
            # millisecond-scale request; inference traffic is
            # latency-bound, never bandwidth-bound
            disable_nagle_algorithm = True

            def do_GET(handler):  # noqa: N805 — handler, not self
                path = handler.path.split("?", 1)[0]
                fn = get_routes.get(path)
                if fn is None:
                    handler.send_error(404, not_found)
                    return
                _run(handler, fn)

            def do_POST(handler):  # noqa: N805
                path = handler.path.split("?", 1)[0]
                fn = post_routes.get(path)
                args = ()
                if fn is None:
                    for prefix, pfn in prefix_routes:
                        if path.startswith(prefix):
                            fn, args = pfn, (path,)
                            break
                if fn is None:
                    handler.send_error(404, not_found)
                    return
                try:
                    length = int(handler.headers.get("Content-Length", 0))
                except ValueError:
                    length = -1
                if length < 0 or length > max_body_bytes:
                    handler.send_error(413, "request body too large")
                    return
                body = handler.rfile.read(length) if length else b""
                _run(handler, fn, *args, body)

            def log_message(handler, *args):  # noqa: N805
                pass  # requests must not spray the owning console

        class _Server(http.server.ThreadingHTTPServer):
            daemon_threads = True
            # a relaunched run must be able to rebind the same port
            # immediately (TIME_WAIT would otherwise hold it for minutes)
            allow_reuse_address = True
            # the stdlib default listen backlog is 5: a burst of
            # concurrent clients dialing at once overflows it and the
            # dropped SYNs retransmit after ~1 s — a whole second of
            # connect stall that reads as a p99 cliff. Size the backlog
            # for a data plane, not a debug endpoint.
            request_queue_size = 128

            def handle_error(server, request, client_address):  # noqa: N805
                # a client dropping the connection mid-response raises in
                # wfile.write; the default handler tracebacks onto the
                # console — same silence contract as log_message above
                pass

        self._httpd = _Server((host, port), _Handler)
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            name=thread_name,
            daemon=True,
        )
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is None:
            return
        httpd.shutdown()
        httpd.server_close()
        self._thread.join(timeout=5.0)
