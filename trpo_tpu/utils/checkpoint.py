"""Checkpoint / resume (SURVEY §5: absent in the reference — its only
"snapshot" is the in-memory flat-param vector used for KL rollback,
``trpo_inksci.py:144,158`` — here a first-class subsystem).

Orbax checkpoints of the full :class:`trpo_tpu.agent.TrainState` (policy +
critic + optimizer + env carry + RNG + counters), so a resumed run continues
exactly where it stopped, including mid-episode env states.

Host-simulator state (gym:/native: adapters) lives OUTSIDE TrainState and
rides as a pickle-free ``.npz`` sidecar next to the Orbax step
(:meth:`save_host_env` / :meth:`restore_host_env`): exact resume for ``native:`` envs (their
state/step/RNG buffers are host NumPy), best-effort for ``gym:`` (MuJoCo
``qpos``/``qvel``/time, classic-control ``state``, TimeLimit counters),
and for opaque backends the documented fallback — episodes restart on
resume while obs-normalization statistics still restore via TrainState.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

__all__ = ["Checkpointer"]


def _is_typed_key(x) -> bool:
    return hasattr(x, "dtype") and jax.dtypes.issubdtype(
        x.dtype, jax.dtypes.prng_key
    )


def _keys_to_data(tree):
    """Typed PRNG-key leaves → raw uint32 key data, in place in the tree.

    The installed orbax serializes ndarray dtypes only — a typed key array
    (``jax.random.key``) raises at save time. Storing ``key_data`` keeps
    the checkpoint a plain-ndarray pytree; :meth:`Checkpointer.restore`
    re-wraps from the template's key leaves."""
    return jax.tree_util.tree_map(
        lambda x: jax.random.key_data(x) if _is_typed_key(x) else x, tree
    )


class Checkpointer:
    def __init__(
        self,
        directory: str,
        max_to_keep: int = 3,
        cg_damping_seed: Optional[float] = None,
        allow_legacy_pickle: Optional[bool] = None,
        bus=None,
    ):
        """``cg_damping_seed``: the run's configured ``cfg.cg_damping`` —
        used only when a fixed→adaptive damping flip is restored through an
        *abstract* template (the normal ``agent.init_state()`` path carries
        the value itself); defaults to the ``TRPOConfig`` class default.

        ``allow_legacy_pickle``: opt in to reading pre-round-3 ``.pkl``
        host-env sidecars, which go through ``pickle.load`` and can execute
        code from a hostile checkpoint directory. Default (None) reads the
        ``TRPO_TPU_ALLOW_PICKLE_SIDECAR`` env var; unset means refuse with
        a warning (episodes restart, nothing else is lost).

        ``bus``: an optional ``trpo_tpu.obs.EventBus`` — checkpoint-layer
        findings that would otherwise only reach stderr (a CORRUPT
        host-env sidecar, a pruned partial save) are emitted as
        ``health`` events on it.
        """
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        self.cg_damping_seed = cg_damping_seed
        self.bus = bus
        if allow_legacy_pickle is None:
            # strict allowlist: only the documented "1" enables the
            # pickle.load path — "false"/"no"/"off" must NOT enable an
            # arbitrary-code-execution surface by accident
            allow_legacy_pickle = (
                os.environ.get("TRPO_TPU_ALLOW_PICKLE_SIDECAR") == "1"
            )
        self.allow_legacy_pickle = allow_legacy_pickle
        os.makedirs(self.directory, exist_ok=True)
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
        )
        # a FRESH directory gets the markers-enabled sentinel before any
        # save: "no markers at all" then means "the only saves ever
        # attempted here were torn", not "legacy pre-marker checkpoint"
        # — without it a kill -9 through the very FIRST save would leave
        # a marker-less directory indistinguishable from a trusted
        # legacy one, and the gate would hand the torn step to resume
        if not self.manager.all_steps():
            with open(self._sentinel_path(), "w") as f:
                f.write("")

    def _health(self, check: str, message: str, **data) -> None:
        """Surface a checkpoint-layer finding: stderr always, plus a
        ``health`` event when a bus is attached — silent degradation at
        restore time is how a fleet quietly loses training state."""
        import sys

        print(f"checkpoint: {message}", file=sys.stderr)
        if self.bus is not None:
            self.bus.emit(
                "health", check=check, level="warn", message=message,
                data=data or None,
            )

    # -- save-integrity gate ------------------------------------------------
    #
    # Orbax's save is atomic per step only up to its own finalize; a
    # ``kill -9`` (a preemption grace window running out) mid-save can
    # leave a step directory that lists in ``all_steps()`` but restores
    # garbage — and a naive ``latest_step()`` would hand exactly that to
    # the next resume. The gate: ``save`` drops a ``step_<n>.complete``
    # marker AFTER ``wait_until_finished``; a step newer than the newest
    # marker without its own marker is a torn save — never selected, and
    # pruned on restore. Steps older than the newest marker are trusted
    # without one (pre-round-7 checkpoints predate markers); a fresh
    # directory is stamped ``.markers_enabled`` at init so a tear during
    # its very FIRST save cannot masquerade as a legacy directory.

    def _marker_path(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step}.complete")

    def _sentinel_path(self) -> str:
        return os.path.join(self.directory, ".markers_enabled")

    def _marked_steps(self) -> set:
        import re

        out = set()
        try:
            names = os.listdir(self.directory)
        except OSError:  # pragma: no cover
            return out
        for name in names:
            m = re.fullmatch(r"step_(\d+)\.complete", name)
            if m:
                out.add(int(m.group(1)))
        return out

    def _complete_steps(self):
        """Steps safe to restore: all of them when no markers exist in a
        LEGACY directory (pre-marker checkpoints), none of them when no
        markers exist in a marker-enabled one (every save ever attempted
        tore), else everything except unmarked steps NEWER than the
        newest marker (= saves a kill -9 tore mid-write)."""
        steps = list(self.manager.all_steps())
        marked = self._marked_steps()
        if not marked:
            if steps and os.path.exists(self._sentinel_path()):
                return []
            return steps
        newest_marked = max(marked)
        return [s for s in steps if s in marked or s < newest_marked]

    def save(self, step: int, state) -> None:
        self.manager.save(
            step, args=self._ocp.args.StandardSave(_keys_to_data(state))
        )
        self.manager.wait_until_finished()
        # marker LAST: its existence asserts the orbax step is finalized
        with open(self._marker_path(step), "w") as f:
            f.write("")
        # prune markers whose step was garbage-collected (max_to_keep)
        live = set(self.manager.all_steps())
        for s in self._marked_steps() - live:
            try:
                os.remove(self._marker_path(s))
            except OSError:  # pragma: no cover
                pass

    def refresh(self) -> None:
        """Re-read the step list from disk. Orbax's ``CheckpointManager``
        caches ``all_steps()`` at construction and tracks only its OWN
        saves afterwards — correct for the writer, blind for a READER
        watching a directory another process appends to (the serving
        tier's hot-reload watcher, a fleet orchestrator). Call this
        before :meth:`latest_step` when the writer is someone else."""
        reload = getattr(self.manager, "reload", None)
        if reload is not None:
            reload()
        else:  # pragma: no cover — older orbax spells it read=True
            self.manager.all_steps(read=True)

    def latest_step(self, refresh: bool = False) -> Optional[int]:
        """Newest COMPLETE step (see the save-integrity gate above) —
        never a save torn by ``kill -9``. ``refresh=True`` re-reads the
        directory first (see :meth:`refresh`) so steps written by a
        DIFFERENT process/manager are visible — the serving tier's
        hot-reload contract."""
        if refresh:
            self.refresh()
        steps = self._complete_steps()
        return max(steps) if steps else None

    def prune_incomplete(self) -> list:
        """Delete torn saves (steps the integrity gate rejects) so they
        never shadow a good step again; returns the pruned step numbers.
        Called by :meth:`restore`; safe to call any time."""
        torn = sorted(
            set(self.manager.all_steps()) - set(self._complete_steps())
        )
        for s in torn:
            try:
                self.manager.delete(s)
            except Exception:  # pragma: no cover — best-effort cleanup
                pass
            self._health(
                "checkpoint_incomplete",
                f"step {s} was interrupted mid-save (no completion "
                "marker) — pruned; restore uses the previous complete "
                "step",
                step=s,
            )
        return torn

    def restore(self, template, step: Optional[int] = None,
                prune: bool = True):
        """Restore into the structure of ``template`` (an abstract or
        concrete TrainState from ``agent.init_state()``). Torn saves
        (kill -9 mid-write — see the save-integrity gate) are pruned
        first, so the default ``step`` is always the newest COMPLETE
        one.

        ``prune=False`` for READERS of a directory a live trainer is
        still writing (the serving tier's hot-reload watcher): to a
        reader, a save currently IN FLIGHT is indistinguishable from a
        torn one (orbax files present, completion marker not yet), and
        pruning it would delete the trainer's write out from under it.
        Readers restore marker-gated steps only and never prune."""
        if prune:
            self.prune_incomplete()
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")

        def as_abstract(x):
            if not hasattr(x, "shape"):
                return x
            if _is_typed_key(x):
                # checkpoints hold raw key DATA (see _keys_to_data);
                # restore its (..., impl) uint32 shape, re-wrap below
                sds = jax.eval_shape(jax.random.key_data, x)
                return jax.ShapeDtypeStruct(sds.shape, sds.dtype)
            # Preserve sharding so a mesh run resumes sharded, not
            # collapsed onto the default device.
            sharding = getattr(x, "sharding", None)
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)

        def rewrap_keys(tmpl, restored_tree):
            return jax.tree_util.tree_map(
                lambda t, r: jax.random.wrap_key_data(r)
                if _is_typed_key(t)
                else r,
                tmpl,
                restored_tree,
            )

        # Four TrainState fields can differ in presence between save and
        # restore, changing the pytree structure: cg_damping (f32 scalar
        # iff cfg.adaptive_damping), precond (ops/precond.PrecondState iff
        # the amortized head-block preconditioner is on — default for the
        # MuJoCo presets since round 6, so pre-r06 checkpoints lack it),
        # metrics (obs/device_metrics.DeviceMetrics — added in round
        # 7, so pre-r07 checkpoints lack it), and ladder
        # (trpo.LadderState iff trpo.ladder_stateful(cfg) — default for
        # the MuJoCo presets since round 8, so pre-r08 checkpoints lack
        # it). Tolerate every presence combination: a dropped field's
        # saved value is discarded, a gained field is seeded from the
        # template below (precond factors, observability counters and the
        # ladder's audit state are all safely reconstructible — age 0
        # refreshes on the first update, counters restart at 0, the
        # ladder re-warms its budget and audit cadence within a few
        # updates).
        flippable = hasattr(template, "_replace") and hasattr(
            template, "cg_damping"
        )

        def damping_alt(t):
            return t._replace(
                cg_damping=None
                if t.cg_damping is not None
                else jax.ShapeDtypeStruct((), "float32")
            )

        def precond_alt(t):
            """Template with the precond presence flipped, or None when
            the flipped form cannot be derived (no plain-MLP params)."""
            if not hasattr(t, "precond"):
                return None
            if t.precond is not None:
                return t._replace(precond=None)
            try:
                H = t.policy_params["net"]["layers"][-1]["w"].shape[0]
            except Exception:
                return None
            from trpo_tpu.ops.precond import PrecondState

            return t._replace(
                precond=PrecondState(
                    u=jax.ShapeDtypeStruct((H + 1, H + 1), "float32"),
                    s_eig=jax.ShapeDtypeStruct((H + 1,), "float32"),
                    age=jax.ShapeDtypeStruct((), "int32"),
                )
            )

        def metrics_alt(t):
            """Template with the metrics subtree absent (pre-round-7
            checkpoints), or None when it already is."""
            if getattr(t, "metrics", None) is None:
                return None
            return t._replace(metrics=None)

        def ladder_alt(t):
            """Template with the solver-precision-ladder state presence
            flipped: stripped when present (pre-round-8 checkpoint, or
            the ladder turned off since the save), added as the 7-scalar
            abstract LadderState when absent (checkpoint saved with the
            ladder on, restored into a ladder-off config)."""
            if not hasattr(t, "ladder"):
                return None
            if t.ladder is not None:
                return t._replace(ladder=None)
            from trpo_tpu.trpo import LadderState

            f32 = jax.ShapeDtypeStruct((), "float32")
            i32 = jax.ShapeDtypeStruct((), "int32")
            return t._replace(
                ladder=LadderState(
                    step=i32, cg_budget=i32, fail_streak=i32,
                    pinned=jax.ShapeDtypeStruct((), "bool"),
                    cosine_min=f32, audit_runs=i32, fallbacks=i32,
                )
            )

        abstract = jax.tree_util.tree_map(as_abstract, template)
        try:
            restored = rewrap_keys(
                template,
                self.manager.restore(
                    step, args=self._ocp.args.StandardRestore(abstract)
                ),
            )
        except Exception as first_err:
            if not flippable:
                raise
            candidates = [damping_alt(template)]
            p_alt = precond_alt(template)
            if p_alt is not None:
                candidates.append(p_alt)
                candidates.append(damping_alt(p_alt))
            # every combination may additionally need the metrics subtree
            # stripped (checkpoint predates TrainState.metrics)
            for alt in [template] + list(candidates):
                m_alt = metrics_alt(alt)
                if m_alt is not None:
                    candidates.append(m_alt)
            # ...and/or the ladder presence flipped (checkpoint predates
            # TrainState.ladder, or the ladder was toggled since the
            # save — the MuJoCo presets arm it by default from round 8)
            for alt in [template] + list(candidates):
                l_alt = ladder_alt(alt)
                if l_alt is not None:
                    candidates.append(l_alt)
            restored = None
            for alt in candidates:
                abstract_alt = jax.tree_util.tree_map(as_abstract, alt)
                try:
                    restored = rewrap_keys(
                        alt,
                        self.manager.restore(
                            step,
                            args=self._ocp.args.StandardRestore(
                                abstract_alt
                            ),
                        ),
                    )
                    break
                except Exception:
                    continue
            if restored is None:
                # the failure was not a known structure flip — surface
                # the original error, not a retry's
                raise first_err
        if flippable and (
            (template.cg_damping is None)
            != (getattr(restored, "cg_damping", None) is None)
        ):
            seed = template.cg_damping
            if seed is not None and not hasattr(seed, "__array__"):
                # abstract template leaf (ShapeDtypeStruct): materialize the
                # run's configured damping (``cg_damping_seed``, threaded
                # from TRPOConfig at construction; class default when the
                # caller didn't) — NOT zero: the first post-resume CG solve
                # must not run undamped (damping exists for Fisher
                # conditioning); the adaptive feedback re-adapts from there
                # within an iteration. A concrete template (the normal
                # agent.init_state() path) seeds cfg.cg_damping itself and
                # never reaches this branch.
                import jax.numpy as jnp

                from trpo_tpu.config import TRPOConfig

                value = (
                    self.cg_damping_seed
                    if self.cg_damping_seed is not None
                    else TRPOConfig.cg_damping
                )
                seed = jnp.full(seed.shape, value, seed.dtype)
            restored = restored._replace(cg_damping=seed)
        if flippable and hasattr(template, "precond"):
            t_has = template.precond is not None
            r_has = getattr(restored, "precond", None) is not None
            if t_has and not r_has:
                # checkpoint predates the amortized preconditioner (or
                # was saved with it off): seed the template's age-0 state
                # — zero factors are never applied, the first update
                # refreshes. Abstract templates materialize the zeros.
                seed = template.precond
                if any(
                    not hasattr(leaf, "__array__")
                    for leaf in jax.tree_util.tree_leaves(seed)
                ):
                    import jax.numpy as jnp

                    seed = jax.tree_util.tree_map(
                        lambda s: jnp.zeros(s.shape, s.dtype), seed
                    )
                restored = restored._replace(precond=seed)
            elif r_has and not t_has:
                # preconditioner turned off since the save: drop the
                # stored factors (pure cache — nothing is lost)
                restored = restored._replace(precond=None)
        if (
            flippable
            and getattr(template, "metrics", None) is not None
            and getattr(restored, "metrics", None) is None
        ):
            # checkpoint predates the device metric counters: restart
            # them at zero (observability-only state — nothing numeric
            # depends on it). Abstract templates materialize the zeros.
            seed = template.metrics
            if any(
                not hasattr(leaf, "__array__")
                for leaf in jax.tree_util.tree_leaves(seed)
            ):
                import jax.numpy as jnp

                seed = jax.tree_util.tree_map(
                    lambda s: jnp.zeros(s.shape, s.dtype), seed
                )
            restored = restored._replace(metrics=seed)
        if flippable and hasattr(template, "ladder"):
            t_has = template.ladder is not None
            r_has = getattr(restored, "ladder", None) is not None
            if t_has and not r_has:
                # checkpoint predates the ladder (or it was off): seed
                # the template's fresh state (the normal init_state path
                # carries concrete trpo.init_ladder values). Abstract
                # templates materialize the init semantics — everything
                # zero except cosine_min (worst-observed tracker, starts
                # at 1.0); a zero cg_budget is clipped up to the config
                # floor at the first solve.
                seed = template.ladder
                if any(
                    not hasattr(leaf, "__array__")
                    for leaf in jax.tree_util.tree_leaves(seed)
                ):
                    import jax.numpy as jnp

                    seed = seed._replace(
                        **{
                            f: jnp.zeros(
                                getattr(seed, f).shape,
                                getattr(seed, f).dtype,
                            )
                            for f in seed._fields
                            if f != "cosine_min"
                        },
                        cosine_min=jnp.ones(
                            seed.cosine_min.shape, seed.cosine_min.dtype
                        ),
                    )
                restored = restored._replace(ladder=seed)
            elif r_has and not t_has:
                # ladder turned off since the save: the audit state is
                # meaningless without the machinery — drop it
                restored = restored._replace(ladder=None)
        return restored

    # -- host-env sidecar --------------------------------------------------
    #
    # Host-simulator state (envs/*.env_state_snapshot) is host-side NumPy
    # with backend-specific, sometimes-absent pieces — it does not belong
    # in the device-resident TrainState pytree (which must keep a stable
    # jit template). It rides NEXT TO the Orbax step as a pickle-free
    # ``.npz`` sidecar (nested dict/list structure as JSON, arrays as npz
    # entries, loaded with ``allow_pickle=False`` so the npz path never
    # executes code on restore): exact resume for native: envs,
    # best-effort (MuJoCo qpos/qvel/time, classic-control state) for
    # gym: envs, documented episode-restart for opaque backends. Legacy
    # ``.pkl`` sidecars from pre-round-3 checkpoints go through
    # ``pickle.load`` — an arbitrary-code-execution surface — so they are
    # only read behind the explicit ``allow_legacy_pickle`` opt-in
    # (constructor flag or TRPO_TPU_ALLOW_PICKLE_SIDECAR=1); otherwise a
    # warning is printed and episodes restart.

    def _aux_path(self, step: int) -> str:
        return os.path.join(self.directory, f"host_env_{step}.npz")

    def _aux_path_legacy(self, step: int) -> str:
        return os.path.join(self.directory, f"host_env_{step}.pkl")

    def save_host_env(self, step: int, snapshot) -> None:
        import numpy as np

        if snapshot is None:
            return
        structure, arrays = _flatten_snapshot(snapshot)
        arrays["__structure__"] = np.asarray(structure)  # JSON, '<U' dtype
        # atomic: a crash mid-dump must not leave a truncated sidecar for
        # the next resume to choke on (a partial *.tmp is pruned by the
        # next save; the Orbax side is already crash-safe via save +
        # wait_until_finished)
        tmp = self._aux_path(step) + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, self._aux_path(step))
        # prune: sidecars whose Orbax step was garbage-collected, plus any
        # *.tmp left by a crash mid-save (always safe to delete — a tmp is
        # only live inside this method)
        keep = {
            p
            for s in list(self.manager.all_steps()) + [step]
            for p in (self._aux_path(s), self._aux_path_legacy(s))
        }
        for name in os.listdir(self.directory):
            if not name.startswith("host_env_"):
                continue
            if not name.endswith((".pkl", ".npz", ".tmp")):
                continue
            p = os.path.join(self.directory, name)
            if p not in keep:
                try:
                    os.remove(p)
                except OSError:
                    pass

    def restore_host_env(self, step: Optional[int] = None):
        """The sidecar for ``step`` (default: latest), or None if that
        checkpoint predates sidecars / the env needed none.

        "No sidecar" and "CORRUPT sidecar" are different findings: the
        former is the documented episode-restart fallback and stays
        silent; the latter means state that WAS saved has been lost
        (truncation, bit rot, a hostile edit) — it still falls back to
        episode restart (training survives) but surfaces loudly: stderr
        plus a ``health`` event when a bus is attached, so the loss is
        auditable instead of silent."""
        import numpy as np

        step = self.latest_step() if step is None else step
        if step is None:
            return None
        path = self._aux_path(step)
        if os.path.exists(path):
            try:
                with np.load(path, allow_pickle=False) as z:
                    return _unflatten_snapshot(
                        str(z["__structure__"]), z
                    )
            except Exception as e:
                # the sidecar EXISTS but cannot be read back — whatever
                # it raises (zip errors, JSON errors, construction-time
                # surprises): fall back to episode restart, but report
                self._health(
                    "host_env_sidecar_corrupt",
                    f"host-env sidecar for step {step} exists but is "
                    f"unreadable ({type(e).__name__}: {e}) — episodes "
                    "will restart",
                    step=step, error=type(e).__name__,
                )
                return None
        legacy = self._aux_path_legacy(step)
        if os.path.exists(legacy):
            import sys

            if not self.allow_legacy_pickle:
                print(
                    f"checkpoint: step {step} has a legacy .pkl "
                    "host-env sidecar, which requires pickle.load "
                    "(can execute code from an untrusted checkpoint "
                    "dir). Refusing without opt-in — pass "
                    "allow_legacy_pickle=True or set "
                    "TRPO_TPU_ALLOW_PICKLE_SIDECAR=1 if this "
                    "checkpoint is your own; episodes will restart.",
                    file=sys.stderr,
                )
                return None
            import pickle

            print(
                f"checkpoint: reading legacy pickle sidecar for step "
                f"{step} (explicitly allowed)",
                file=sys.stderr,
            )
            try:
                with open(legacy, "rb") as f:
                    return pickle.load(f)
            except Exception as e:
                self._health(
                    "host_env_sidecar_corrupt",
                    f"legacy host-env sidecar for step {step} exists "
                    f"but is unreadable ({type(e).__name__}: {e}) — "
                    "episodes will restart",
                    step=step, error=type(e).__name__,
                )
                return None
        # genuinely absent: the documented episode-restart fallback
        return None

    def close(self):
        self.manager.close()


# -- pickle-free snapshot codec -------------------------------------------
#
# Host-env snapshots are nested dict/list/tuple/None/scalar/ndarray
# structures (see envs/*.env_state_snapshot); tuples round-trip as tuples
# via a distinct __tuple__ tag. Arrays go into the npz as entries
# "a0", "a1", ...; the containing structure serializes as JSON with
# {"__npz__": key} placeholders. JSON carries arbitrary-precision ints
# natively, which matters for np_random bit-generator state (PCG64 state
# words exceed uint64). Anything else is a programming error and raises at
# save time — never at restore time.


def _flatten_snapshot(obj):
    import json

    import numpy as np

    arrays = {}

    def flatten(x):
        if x is None or isinstance(x, (bool, int, float, str)):
            return x
        if isinstance(x, np.bool_):
            return bool(x)
        if isinstance(x, np.integer):
            return int(x)
        if isinstance(x, np.floating):
            return float(x)
        if isinstance(x, np.ndarray):
            if x.dtype == object:
                # np.savez would silently PICKLE an object array, making
                # the sidecar fail only at restore time — reject now
                raise TypeError(
                    "host-env snapshot holds an object-dtype array; "
                    "snapshots must use numeric/str dtypes"
                )
            key = f"a{len(arrays)}"
            arrays[key] = x
            return {"__npz__": key}
        if isinstance(x, dict):
            return {"__dict__": {str(k): flatten(v) for k, v in x.items()}}
        if isinstance(x, tuple):
            # distinct tag: an adapter whose env_state_restore distinguishes
            # tuple from list must round-trip exactly (pre-round-4 sidecars
            # collapsed both to __list__; reading those yields lists)
            return {"__tuple__": [flatten(v) for v in x]}
        if isinstance(x, list):
            return {"__list__": [flatten(v) for v in x]}
        raise TypeError(
            f"host-env snapshot holds a {type(x).__name__}; snapshots must "
            "be nested dict/list/None/scalar/ndarray structures"
        )

    return json.dumps(flatten(obj)), arrays


def _unflatten_snapshot(structure_json: str, npz):
    import json

    import numpy as np

    def unflatten(x):
        if isinstance(x, dict):
            if "__npz__" in x:
                return np.asarray(npz[x["__npz__"]])
            if "__dict__" in x:
                return {k: unflatten(v) for k, v in x["__dict__"].items()}
            if "__list__" in x:
                return [unflatten(v) for v in x["__list__"]]
            if "__tuple__" in x:
                return tuple(unflatten(v) for v in x["__tuple__"])
        return x

    return unflatten(json.loads(structure_json))
