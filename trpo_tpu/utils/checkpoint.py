"""Checkpoint / resume (SURVEY §5: absent in the reference — its only
"snapshot" is the in-memory flat-param vector used for KL rollback,
``trpo_inksci.py:144,158`` — here a first-class subsystem).

Orbax checkpoints of the full :class:`trpo_tpu.agent.TrainState` (policy +
critic + optimizer + env carry + RNG + counters), so a resumed run continues
exactly where it stopped, including mid-episode env states.

Host-simulator state (gym:/native: adapters) lives OUTSIDE TrainState and
rides as a pickle sidecar next to the Orbax step (:meth:`save_host_env` /
:meth:`restore_host_env`): exact resume for ``native:`` envs (their
state/step/RNG buffers are host NumPy), best-effort for ``gym:`` (MuJoCo
``qpos``/``qvel``/time, classic-control ``state``, TimeLimit counters),
and for opaque backends the documented fallback — episodes restart on
resume while obs-normalization statistics still restore via TrainState.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

__all__ = ["Checkpointer"]


class Checkpointer:
    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
        )

    def save(self, step: int, state) -> None:
        self.manager.save(
            step, args=self._ocp.args.StandardSave(state)
        )
        self.manager.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self.manager.latest_step()

    def restore(self, template, step: Optional[int] = None):
        """Restore into the structure of ``template`` (an abstract or
        concrete TrainState from ``agent.init_state()``)."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")

        def as_abstract(x):
            if not hasattr(x, "shape"):
                return x
            # Preserve sharding so a mesh run resumes sharded, not
            # collapsed onto the default device.
            sharding = getattr(x, "sharding", None)
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)

        # TrainState.cg_damping is a f32 scalar iff cfg.adaptive_damping,
        # so flipping the flag between save and restore changes the pytree
        # structure. Tolerate both directions: adaptive→fixed drops the
        # saved scalar, fixed→adaptive seeds the scalar from the template
        # (agent.init_state puts cfg.cg_damping there).
        flippable = hasattr(template, "_replace") and hasattr(
            template, "cg_damping"
        )
        abstract = jax.tree_util.tree_map(as_abstract, template)
        try:
            restored = self.manager.restore(
                step, args=self._ocp.args.StandardRestore(abstract)
            )
        except Exception as first_err:
            if not flippable:
                raise
            alt = template._replace(
                cg_damping=None
                if template.cg_damping is not None
                else jax.ShapeDtypeStruct((), "float32")
            )
            abstract_alt = jax.tree_util.tree_map(as_abstract, alt)
            try:
                restored = self.manager.restore(
                    step, args=self._ocp.args.StandardRestore(abstract_alt)
                )
            except Exception:
                # the failure was not a damping flip — surface the
                # original error, not the retry's
                raise first_err
        if flippable and (
            (template.cg_damping is None)
            != (getattr(restored, "cg_damping", None) is None)
        ):
            seed = template.cg_damping
            if seed is not None and not hasattr(seed, "__array__"):
                # abstract template leaf (ShapeDtypeStruct): materialize a
                # concrete zero — the adaptive-damping feedback re-adapts
                # within an iteration; a concrete template (the normal
                # agent.init_state() path) seeds cfg.cg_damping instead
                import jax.numpy as jnp

                seed = jnp.zeros(seed.shape, seed.dtype)
            restored = restored._replace(cg_damping=seed)
        return restored

    # -- host-env sidecar --------------------------------------------------
    #
    # Host-simulator state (envs/*.env_state_snapshot) is host-side NumPy
    # with backend-specific, sometimes-absent pieces — it does not belong
    # in the device-resident TrainState pytree (which must keep a stable
    # jit template). It rides NEXT TO the Orbax step as a pickle sidecar:
    # exact resume for native: envs, best-effort (MuJoCo qpos/qvel/time,
    # classic-control state) for gym: envs, documented episode-restart
    # for opaque backends.

    def _aux_path(self, step: int) -> str:
        return os.path.join(self.directory, f"host_env_{step}.pkl")

    def save_host_env(self, step: int, snapshot) -> None:
        import pickle

        if snapshot is None:
            return
        # atomic: a crash mid-dump must not leave a truncated sidecar for
        # the next resume to choke on (the Orbax side is already
        # crash-safe via save + wait_until_finished)
        tmp = self._aux_path(step) + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(snapshot, f)
        os.replace(tmp, self._aux_path(step))
        # prune sidecars whose Orbax step was garbage-collected
        keep = {self._aux_path(s) for s in self.manager.all_steps()}
        keep.add(self._aux_path(step))
        for name in os.listdir(self.directory):
            if name.startswith("host_env_") and name.endswith(".pkl"):
                p = os.path.join(self.directory, name)
                if p not in keep:
                    try:
                        os.remove(p)
                    except OSError:
                        pass

    def restore_host_env(self, step: Optional[int] = None):
        """The sidecar for ``step`` (default: latest), or None if that
        checkpoint predates sidecars / the env needed none."""
        import pickle

        step = self.latest_step() if step is None else step
        if step is None:
            return None
        try:
            with open(self._aux_path(step), "rb") as f:
                return pickle.load(f)
        except FileNotFoundError:
            return None
        except (OSError, EOFError, pickle.UnpicklingError) as e:
            # unreadable/corrupt sidecar: fall back to the documented
            # episode-restart semantics rather than sinking the resume
            import sys

            print(
                f"checkpoint: host-env sidecar for step {step} unreadable "
                f"({type(e).__name__}) — episodes will restart",
                file=sys.stderr,
            )
            return None

    def close(self):
        self.manager.close()
