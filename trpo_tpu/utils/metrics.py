"""Metrics and structured logging.

The reference's observability is a right-padded printed stats dict with
seven entries — total episodes, mean reward, entropy, baseline explained
variance, elapsed time, KL (old|new), surrogate loss
(``trpo_inksci.py:160-171``) — plus an unused ``logging`` import. This
module keeps those seven stats (parity), adds the SURVEY §5 obligations
(CG-solve timing as a first-class stat, JSONL structured output), and
implements ``explained_variance`` (ref ``utils.py:208-211``) as a
jit-friendly function.

Since PR 3 the JSONL stream is crash-safe (a killed run's truncated final
line is repaired on the next append — :func:`repair_jsonl_tail`, shared
with the event bus's JSONL sink) and every logged row can re-emit through
the run-event bus (``trpo_tpu.obs.events``) as an ``iteration`` event, so
the per-iteration log and the telemetry stream carry ONE schema.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import IO, Optional

import jax.numpy as jnp

__all__ = [
    "explained_variance",
    "StatsLogger",
    "repair_jsonl_tail",
    "quantile_nearest_rank",
]


def quantile_nearest_rank(vals, q: float):
    """Nearest-rank quantile (no interpolation) over ``vals``; None when
    empty. The ONE estimator behind every serving-latency quantile — the
    batcher's ``/metrics`` gauges, ``obs/analyze``'s serving report, and
    ``bench.py``'s serving block all call this, so a scraped gauge, an
    analyzed event log, and a bench artifact tell the same story (three
    hand-rolled copies would silently desynchronize on the first fix to
    one of them)."""
    vals = sorted(vals)
    if not vals:
        return None
    return vals[min(len(vals) - 1, int(q * len(vals)))]


def repair_jsonl_tail(path: str) -> int:
    """Truncate a partial (crash-cut) final line so the file ends at a
    record boundary; returns the number of bytes removed (0 when the file
    is absent, empty, or already ends in a newline). Append-mode writers
    call this before opening — a record is then either fully present or
    absent, never half a line that corrupts the next append's first row."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return 0
    if size == 0:
        return 0
    with open(path, "rb+") as f:
        f.seek(size - 1)
        if f.read(1) == b"\n":
            return 0
        # scan BACKWARD in windows for the last record boundary — a
        # single fixed window would truncate the whole file when the
        # partial tail alone exceeds it
        pos, window = size, 1 << 20
        keep = 0  # no newline anywhere: the file IS one partial line
        while pos > 0:
            start = max(0, pos - window)
            f.seek(start)
            nl = f.read(pos - start).rfind(b"\n")
            if nl >= 0:
                keep = start + nl + 1
                break
            pos = start
        f.truncate(keep)
        return size - keep


def explained_variance(ypred, y, weight=None):
    """``1 − Var(y − ŷ)/Var(y)`` (ref ``utils.py:208-211``).

    Jit-traceable; returns NaN when Var(y)=0 (the reference guards with an
    ``isnan`` check host-side — callers here should use ``jnp.nan_to_num``
    or check, same contract).
    """
    ypred = jnp.asarray(ypred, jnp.float32).reshape(-1)
    y = jnp.asarray(y, jnp.float32).reshape(-1)
    if weight is None:
        weight = jnp.ones_like(y)
    weight = jnp.asarray(weight, jnp.float32).reshape(-1)
    wsum = jnp.maximum(jnp.sum(weight), 1.0)

    def wvar(v):
        m = jnp.sum(v * weight) / wsum
        return jnp.sum((v - m) ** 2 * weight) / wsum

    return 1.0 - wvar(y - ypred) / wvar(y)


class StatsLogger:
    """Aligned console stats + optional JSONL stream.

    Console format mirrors the reference's padded two-column print
    (``trpo_inksci.py:168-171``); every ``log`` call also appends one JSON
    object per iteration to ``jsonl_path`` when configured (SURVEY §5
    "structured metrics to stdout + JSONL") — written as ONE ``write``
    call then flushed, after repairing any crash-truncated tail at open.

    ``bus`` (a ``trpo_tpu.obs.events.EventBus``, optional — also
    assignable after construction, which is how ``agent.learn`` attaches a
    Telemetry's bus to a caller-provided logger) re-emits each row as an
    ``iteration`` event, so training logs and telemetry share one schema.
    """

    def __init__(
        self,
        jsonl_path: Optional[str] = None,
        stream: Optional[IO] = None,
        bus=None,
    ):
        # None → resolve sys.stdout at each log() call, not here: binding
        # the stream at construction breaks when stdout is swapped later
        # (pytest capture, CLI redirection).
        self.stream = stream
        self.bus = bus
        self._jsonl: Optional[IO] = None
        if jsonl_path:
            repair_jsonl_tail(jsonl_path)
            self._jsonl = open(jsonl_path, "a")
        self.start_time = time.time()

    def log(self, iteration: int, stats: dict):
        stream = self.stream if self.stream is not None else sys.stdout
        print(
            f"\n-------- Iteration {iteration} ----------",
            file=stream,
        )
        for k, v in stats.items():
            if isinstance(v, float):
                v = f"{v:.6g}"
            print(f"{str(k):<40} {v}", file=stream)
        if self._jsonl is not None:
            rec = {"iteration": iteration}
            for k, v in stats.items():
                rec[k] = v
            self._jsonl.write(json.dumps(rec) + "\n")
            self._jsonl.flush()
        if self.bus is not None:
            # the bus sanitizes numpy/jax scalars itself; one schema for
            # the training log and every other telemetry consumer
            self.bus.emit(
                "iteration", iteration=int(iteration), stats=dict(stats)
            )

    def elapsed_minutes(self) -> float:
        """"Time elapsed" stat, in minutes like the reference
        (``trpo_inksci.py:167``)."""
        return (time.time() - self.start_time) / 60.0

    def close(self):
        """Flush and close the JSONL stream. Idempotent; both drivers
        (and the CLI) call it explicitly, so the final record is always
        fully on disk even when the process exits right after."""
        if self._jsonl is not None:
            self._jsonl.close()
            self._jsonl = None
