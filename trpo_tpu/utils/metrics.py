"""Metrics and structured logging.

The reference's observability is a right-padded printed stats dict with
seven entries — total episodes, mean reward, entropy, baseline explained
variance, elapsed time, KL (old|new), surrogate loss
(``trpo_inksci.py:160-171``) — plus an unused ``logging`` import. This
module keeps those seven stats (parity), adds the SURVEY §5 obligations
(CG-solve timing as a first-class stat, JSONL structured output), and
implements ``explained_variance`` (ref ``utils.py:208-211``) as a
jit-friendly function.
"""

from __future__ import annotations

import json
import sys
import time
from typing import IO, Optional

import jax.numpy as jnp

__all__ = ["explained_variance", "StatsLogger"]


def explained_variance(ypred, y, weight=None):
    """``1 − Var(y − ŷ)/Var(y)`` (ref ``utils.py:208-211``).

    Jit-traceable; returns NaN when Var(y)=0 (the reference guards with an
    ``isnan`` check host-side — callers here should use ``jnp.nan_to_num``
    or check, same contract).
    """
    ypred = jnp.asarray(ypred, jnp.float32).reshape(-1)
    y = jnp.asarray(y, jnp.float32).reshape(-1)
    if weight is None:
        weight = jnp.ones_like(y)
    weight = jnp.asarray(weight, jnp.float32).reshape(-1)
    wsum = jnp.maximum(jnp.sum(weight), 1.0)

    def wvar(v):
        m = jnp.sum(v * weight) / wsum
        return jnp.sum((v - m) ** 2 * weight) / wsum

    return 1.0 - wvar(y - ypred) / wvar(y)


class StatsLogger:
    """Aligned console stats + optional JSONL stream.

    Console format mirrors the reference's padded two-column print
    (``trpo_inksci.py:168-171``); every ``log`` call also appends one JSON
    object per iteration to ``jsonl_path`` when configured (SURVEY §5
    "structured metrics to stdout + JSONL").
    """

    def __init__(
        self,
        jsonl_path: Optional[str] = None,
        stream: Optional[IO] = None,
    ):
        # None → resolve sys.stdout at each log() call, not here: binding
        # the stream at construction breaks when stdout is swapped later
        # (pytest capture, CLI redirection).
        self.stream = stream
        self._jsonl: Optional[IO] = (
            open(jsonl_path, "a") if jsonl_path else None
        )
        self.start_time = time.time()

    def log(self, iteration: int, stats: dict):
        stream = self.stream if self.stream is not None else sys.stdout
        print(
            f"\n-------- Iteration {iteration} ----------",
            file=stream,
        )
        for k, v in stats.items():
            if isinstance(v, float):
                v = f"{v:.6g}"
            print(f"{str(k):<40} {v}", file=stream)
        if self._jsonl is not None:
            rec = {"iteration": iteration}
            for k, v in stats.items():
                rec[k] = v
            self._jsonl.write(json.dumps(rec) + "\n")
            self._jsonl.flush()

    def elapsed_minutes(self) -> float:
        """"Time elapsed" stat, in minutes like the reference
        (``trpo_inksci.py:167``)."""
        return (time.time() - self.start_time) / 60.0

    def close(self):
        if self._jsonl is not None:
            self._jsonl.close()
            self._jsonl = None
