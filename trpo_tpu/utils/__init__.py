"""Observability, checkpointing, and misc utilities."""

from trpo_tpu.utils.metrics import (  # noqa: F401
    explained_variance,
    StatsLogger,
)
from trpo_tpu.utils.timers import PhaseTimer  # noqa: F401
