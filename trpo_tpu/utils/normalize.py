"""Running observation normalization (Welford/Chan parallel merge).

Standard equipment for MuJoCo-scale TRPO (obs components span orders of
magnitude; un-normalized they starve the tanh torso) that the reference
lacks entirely. Implemented as a pure pytree so it lives inside
``TrainState`` — jit-traceable, vmap-safe (population training keeps
per-member statistics), checkpointed with everything else, and mesh-
friendly: the batch moments are plain global means, which GSPMD lowers to
``psum`` reductions when the batch axis is sharded.

The agent applies the statistics *as of the start of an iteration* to both
the rollout and the update replay (so the acting distribution and
``old_dist`` in the batch are computed from identical inputs), then folds
the iteration's raw observations into the statistics for the next one.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

__all__ = ["RunningStats", "init_stats", "update_stats", "normalize"]


class RunningStats(NamedTuple):
    count: jax.Array   # scalar f32 — total weight folded in so far
    mean: jax.Array    # (*shape,)
    m2: jax.Array      # (*shape,) — sum of squared deviations


def init_stats(shape: Tuple[int, ...]) -> RunningStats:
    return RunningStats(
        count=jnp.asarray(0.0, jnp.float32),
        mean=jnp.zeros(shape, jnp.float32),
        m2=jnp.zeros(shape, jnp.float32),
    )


def update_stats(stats: RunningStats, obs: jax.Array) -> RunningStats:
    """Fold a batch of observations (leading axes = batch) into ``stats``
    via Chan et al.'s parallel merge — one pass, no host involvement."""
    feat_ndim = stats.mean.ndim
    batch_axes = tuple(range(obs.ndim - feat_ndim))
    obs = jnp.asarray(obs, jnp.float32)
    n_b = jnp.asarray(
        jnp.prod(jnp.asarray([obs.shape[a] for a in batch_axes])), jnp.float32
    )
    mean_b = jnp.mean(obs, axis=batch_axes)
    m2_b = jnp.sum((obs - mean_b) ** 2, axis=batch_axes)

    delta = mean_b - stats.mean
    tot = stats.count + n_b
    new_mean = stats.mean + delta * (n_b / tot)
    new_m2 = stats.m2 + m2_b + delta**2 * (stats.count * n_b / tot)
    return RunningStats(count=tot, mean=new_mean, m2=new_m2)


def normalize(
    stats: RunningStats, obs: jax.Array, clip: float = 10.0
) -> jax.Array:
    """``(obs − mean) / std`` with the usual ±clip guard; identity while
    no data has been folded in (count == 0)."""
    var = stats.m2 / jnp.maximum(stats.count, 1.0)
    std = jnp.sqrt(var + 1e-8)
    out = jnp.clip((obs - stats.mean) / std, -clip, clip)
    return jnp.where(stats.count > 0.0, out, obs)
