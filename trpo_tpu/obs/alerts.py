"""Declarative SLO alerting over the aggregation plane (ISSUE 20).

Rules are data (:class:`Rule`), not code: each names a series glob on
a target glob and one of six evaluation kinds —

* ``threshold`` — the summed latest value of the matched series,
  compared with ``op`` against ``threshold`` (optionally gated by a
  ``guard_series`` sample floor: a 3-request "p99" must never fire an
  SLO alert — the ``latency_samples`` doctrine).
* ``rate`` — reset-aware counter increase over ``window_s`` (any
  ``*_dropped_total`` moving AT ALL is a firing condition: the
  tracer/capture/journal write-behinds are contractually lossless).
* ``burn_rate`` — the SRE two-window burn rule (Beyer et al., SRE
  ch. 5, scaled from 5m/1h to test timescales): error fraction =
  Δ``series`` / Δ``total_series`` per window, burn = fraction /
  (1 - ``objective``); fires only when BOTH the short (``window_s``)
  and long (``long_window_s``, default 4×) windows burn above
  ``threshold`` — the short window makes it resolve fast, the long
  window keeps a blip from paging.
* ``streak`` — consecutive truthy samples of ``series`` counted once
  per change of ``key_series`` (the KL-rollback streak from
  ``obs/health.py``, lifted into a rule over the scraped
  ``status.stats.kl_rolled_back`` / ``status.iteration`` pair instead
  of a parallel monitor).
* ``stall`` — ``series`` has not increased for ``window_s`` despite
  being watched at least that long (fleet round stall), suppressed
  while ``unless_series`` is truthy (a FINISHED member is not
  stalled).
* ``stale`` — the target itself missed its scrape budget for longer
  than ``threshold`` seconds (reads the aggregator's target states,
  not a series: a dead endpoint produces no series).

:class:`AlertEngine` evaluates every rule against every matching
target each tick and owns the firing/resolved lifecycle: ``for_ticks``
consecutive breaches arm a FIRING ``alert`` event (exactly once — the
dedupe the validator's pairing contract relies on), the first clean
evaluation emits its RESOLVED. A rule whose series simply is not
present on a target does not evaluate — absent data is never a breach,
which is half of the zero-false-positive contract; the other half is
``scripts/validate_events.py`` refusing any firing alert without a
matching cause in its window.

``FAULT_ALERT_RULES`` is the shared fault→expected-rules map: the
validator uses it to demand a firing alert per armed chaos fault, and
``obs/analyze.py`` uses it to report time-to-detect.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from fnmatch import fnmatch
from typing import Dict, List, Optional, Tuple

__all__ = [
    "Rule",
    "AlertEngine",
    "default_rules",
    "FAULT_ALERT_RULES",
]

# chaos fault kind -> alert rule names that count as DETECTING it.
# Shared by scripts/validate_events.py (fault→alert contract: an armed
# fault of these kinds must be matched by a firing alert among its
# rules) and obs/analyze.py (time-to-detect). Faults not listed here
# (kill_replica, drop_carry_journal, ...) are covered by the original
# recovery contracts; listing here ADDS the detection requirement.
FAULT_ALERT_RULES = {
    "overload_storm": ("slo_p99", "shed_rate"),
    "slow_replica": ("slo_p99", "shed_rate", "target_stale"),
    "slow_network": (
        "slo_p99", "shed_rate", "lease_expired", "target_stale",
    ),
    "partition_host": ("target_stale", "lease_expired"),
    "wedge_reload": ("canary_rejected",),
    "corrupt_checkpoint": ("canary_rejected",),
    "regress_checkpoint": ("canary_rejected",),
    "kill_promoter": ("promoter_stuck",),
}

_KINDS = ("threshold", "rate", "burn_rate", "streak", "stall", "stale")
_OPS = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
}


def _pats(p) -> Tuple[str, ...]:
    if not p:
        return ()
    return (p,) if isinstance(p, str) else tuple(p)


@dataclass(frozen=True)
class Rule:
    """One declarative alert rule (see module docstring for kinds)."""

    name: str
    kind: str
    series: Tuple[str, ...] = ()        # fnmatch globs; matches SUMMED
    target: str = "*"                   # glob over target names
    op: str = ">"
    threshold: float = 0.0
    window_s: float = 2.0
    long_window_s: Optional[float] = None   # burn_rate; default 4x
    total_series: Tuple[str, ...] = ()      # burn_rate denominator
    objective: float = 0.99                 # burn_rate SLO objective
    min_total: float = 1.0                  # burn_rate denominator floor
    for_ticks: int = 2
    guard_series: Tuple[str, ...] = ()
    guard_min: float = 0.0
    key_series: Tuple[str, ...] = ()        # streak dedupe key
    streak_n: int = 3
    unless_series: Tuple[str, ...] = ()     # stall suppressor

    def __post_init__(self):
        if not self.name:
            raise ValueError("rule needs a name")
        if self.kind not in _KINDS:
            raise ValueError(
                f"rule {self.name}: kind must be one of {_KINDS}, "
                f"got {self.kind!r}"
            )
        if self.op not in _OPS:
            raise ValueError(
                f"rule {self.name}: op must be one of "
                f"{tuple(_OPS)}, got {self.op!r}"
            )
        if self.for_ticks < 1:
            raise ValueError(
                f"rule {self.name}: for_ticks must be >= 1"
            )
        # normalize the glob fields so callers may pass plain strings
        for f in ("series", "total_series", "guard_series",
                  "key_series", "unless_series"):
            object.__setattr__(self, f, _pats(getattr(self, f)))
        if self.kind != "stale" and not self.series:
            raise ValueError(
                f"rule {self.name}: kind {self.kind!r} needs a series"
            )
        if self.kind == "burn_rate" and not self.total_series:
            raise ValueError(
                f"rule {self.name}: burn_rate needs total_series"
            )
        if not (0.0 < self.objective < 1.0) and self.kind == "burn_rate":
            raise ValueError(
                f"rule {self.name}: objective must be in (0, 1)"
            )

    @property
    def long_window(self) -> float:
        return (
            self.long_window_s if self.long_window_s is not None
            else 4.0 * self.window_s
        )


def default_rules(
    slo_p99_ms: float = 500.0,
    window_s: float = 2.0,
    burn_threshold: float = 2.0,
    stale_after_s: float = 3.0,
    rollback_streak: int = 3,
    stall_window_s: float = 30.0,
    promoter_stuck_s: float = 15.0,
    min_latency_samples: int = 8,
) -> Tuple[Rule, ...]:
    """The ISSUE 20 minimum rule set, windows scaled for test
    timescales (production would use the same shapes with 5m/1h
    burn windows and minutes-long stalls)."""
    w = float(window_s)
    shed_series = (
        "status.counters.shed_*_total",
        "status.counters.backpressure_total",
    )
    return (
        # serve p99 vs the SLO — over the router's TIME-expiring
        # recent window so the alert resolves when the system does,
        # guarded by its sample count (thin windows never fire)
        Rule(
            "slo_p99", "threshold",
            series="status.latency_recent_ms.0.99",
            op=">", threshold=float(slo_p99_ms), window_s=w,
            guard_series="status.latency_recent_samples",
            guard_min=float(min_latency_samples), for_ticks=2,
        ),
        # shed/backpressure burn vs admitted traffic: two-window so a
        # single shed blip is not a page but a storm is
        Rule(
            "shed_rate", "burn_rate",
            series=shed_series,
            total_series=("status.counters.routed_total",) + shed_series,
            objective=0.99, threshold=float(burn_threshold),
            window_s=w, long_window_s=4.0 * w, min_total=8.0,
            for_ticks=1,
        ),
        # failover quality: reestablished (lossy fallback) burning
        # against all session recoveries — objective 0.5 = "at least
        # half of recoveries must be lossless resumes"
        Rule(
            "resumed_fraction", "burn_rate",
            series="status.counters.sessions_reestablished_total",
            total_series=(
                "status.counters.sessions_resumed_total",
                "status.counters.sessions_reestablished_total",
            ),
            objective=0.5, threshold=1.0,
            window_s=2.0 * w, long_window_s=8.0 * w, min_total=2.0,
            for_ticks=1,
        ),
        # any canary rejection/rollback is an event worth a page
        Rule(
            "canary_rejected", "rate",
            series=("*rolled_back_total*", "*canary_rejected*"),
            op=">", threshold=0.0, window_s=2.0 * w, for_ticks=1,
        ),
        Rule(
            "lease_expired", "rate",
            series=("*lease*expired*",),
            op=">", threshold=0.0, window_s=2.0 * w, for_ticks=1,
        ),
        # the write-behinds are contractually lossless: ANY drop fires
        Rule(
            "dropped_events", "rate",
            series=("*dropped_total*",),
            op=">", threshold=0.0, window_s=2.0 * w, for_ticks=1,
        ),
        # obs/health.py's KL-rollback streak, lifted into a rule over
        # the scraped iteration stats (counted once per iteration)
        Rule(
            "kl_rollback_streak", "streak",
            series="status.stats.kl_rolled_back",
            key_series="status.iteration",
            streak_n=int(rollback_streak),
            window_s=max(30.0 * w, 60.0), for_ticks=1,
        ),
        # a member whose iteration counter stops moving (and is not
        # finished) has stalled its round
        Rule(
            "fleet_stall", "stall",
            series="status.iteration",
            unless_series="status.finished",
            window_s=float(stall_window_s), for_ticks=1,
        ),
        # the promoter's journal has carried a non-terminal entry with
        # no transition for too long — stuck in publishing
        Rule(
            "promoter_stuck", "threshold",
            series="promote.unconverged_s",
            op=">", threshold=float(promoter_stuck_s), window_s=w,
            for_ticks=1,
        ),
        # the watcher's own failure mode: a target that stopped
        # answering is an alert, never a silent gap
        Rule(
            "target_stale", "stale",
            threshold=float(stale_after_s), for_ticks=2,
        ),
    )


class _Activation:
    __slots__ = ("breaches", "firing", "fired_t", "value")

    def __init__(self):
        self.breaches = 0
        self.firing = False
        self.fired_t = 0.0
        self.value = None


class AlertEngine:
    """Evaluate rules over a :class:`MetricsAggregator`'s store and
    own the firing/resolved lifecycle. ``history`` keeps every emitted
    alert dict (smoke assertions read it); ``active()`` lists
    currently-firing (rule, target) pairs."""

    def __init__(self, rules, bus=None):
        self.rules: Tuple[Rule, ...] = tuple(rules)
        names = [r.name for r in self.rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate rule names in {names}")
        self.bus = bus
        self._lock = threading.Lock()
        self._act: Dict[Tuple[str, str], _Activation] = {}
        self.history: List[dict] = []
        self.firing_total: Dict[str, int] = {}
        self.resolved_total: Dict[str, int] = {}

    def active(self) -> List[Tuple[str, str]]:
        with self._lock:
            return sorted(
                k for k, a in self._act.items() if a.firing
            )

    # -- evaluation --------------------------------------------------------

    def evaluate(self, agg, now: Optional[float] = None) -> List[dict]:
        """One tick: every rule against every matching target. Returns
        the alert events emitted THIS tick."""
        now = time.time() if now is None else now
        states = agg.target_states(now)
        emitted: List[dict] = []
        for rule in self.rules:
            for target in sorted(states):
                if not fnmatch(target, rule.target):
                    continue
                res = self._eval_rule(rule, agg, target, states, now)
                if res is None:
                    breach, value = False, None
                else:
                    breach, value = res
                evs = self._transition(rule, target, breach, value, now)
                emitted.extend(evs)
        if emitted and self.bus is not None:
            self.bus.emit_batch("alert", emitted)
        with self._lock:
            self.history.extend(emitted)
        return emitted

    def _sum_latest(self, agg, target, patterns, now, max_age):
        """Summed latest value of the matched series; None when no
        matched series has a point young enough."""
        vals = []
        for _, ser in agg.match_series(target, patterns).items():
            last = ser.last()
            if last is not None and now - last[0] <= max_age:
                vals.append(last[1])
        return sum(vals) if vals else None

    def _sum_delta(self, agg, target, patterns, now, window):
        """Summed reset-aware increase over the window across matched
        series; None when NO matched series has a computable delta."""
        deltas = [
            d for _, ser in agg.match_series(target, patterns).items()
            if (d := ser.delta(now, window)) is not None
        ]
        return sum(deltas) if deltas else None

    def _eval_rule(self, rule, agg, target, states, now):
        """(breach, observed value) or None = not evaluable (no data /
        guard floor unmet) — never a breach, never a resolve-blocker."""
        stale_age = max(3.0 * rule.window_s, 10.0)
        if rule.kind == "stale":
            st = states.get(target) or {}
            stale_for = float(st.get("stale_for_s") or 0.0)
            return stale_for > rule.threshold, stale_for
        if rule.guard_series:
            g = self._sum_latest(
                agg, target, rule.guard_series, now, stale_age
            )
            if g is None or g < rule.guard_min:
                return None
        if rule.kind == "threshold":
            v = self._sum_latest(
                agg, target, rule.series, now, stale_age
            )
            if v is None:
                return None
            return _OPS[rule.op](v, rule.threshold), v
        if rule.kind == "rate":
            d = self._sum_delta(
                agg, target, rule.series, now, rule.window_s
            )
            if d is None:
                return None
            return _OPS[rule.op](d, rule.threshold), d
        if rule.kind == "burn_rate":
            burns = []
            for win in (rule.window_s, rule.long_window):
                bad = self._sum_delta(
                    agg, target, rule.series, now, win
                )
                tot_own = self._sum_delta(
                    agg, target, rule.total_series, now, win
                )
                if bad is None or tot_own is None:
                    return None
                if tot_own < rule.min_total:
                    return None
                err = (bad / tot_own) if tot_own > 0 else 0.0
                burns.append(err / (1.0 - rule.objective))
            # both windows must burn: report the SMALLER (the binding
            # one) as the observed value
            return min(burns) > rule.threshold, min(burns)
        if rule.kind == "streak":
            return self._eval_streak(rule, agg, target, now)
        if rule.kind == "stall":
            return self._eval_stall(rule, agg, target, now)
        return None

    def _eval_streak(self, rule, agg, target, now):
        matched = agg.match_series(target, rule.series)
        keys = agg.match_series(target, rule.key_series)
        if not matched or not keys:
            return None
        ser = matched[sorted(matched)[0]]
        key = keys[sorted(keys)[0]]
        pts = ser.window(now, rule.window_s)
        kpts = {t: v for t, v in key.window(now, rule.window_s)}
        if not pts:
            return None
        # scrapes record all of a target's series at the SAME t, so
        # pair by timestamp; count the trailing run of truthy values
        # over DISTINCT key values (one iteration = one vote, however
        # many times it was scraped)
        streak, last_key = 0, None
        for t, v in reversed(pts):
            k = kpts.get(t)
            if k is not None and k == last_key:
                continue
            if v <= 0:
                break
            streak += 1
            last_key = k
        return streak >= rule.streak_n, float(streak)

    def _eval_stall(self, rule, agg, target, now):
        if rule.unless_series:
            u = self._sum_latest(
                agg, target, rule.unless_series, now,
                max(3.0 * rule.window_s, 10.0),
            )
            if u is not None and u > 0:
                return None
        matched = agg.match_series(target, rule.series)
        if not matched:
            return None
        ser = matched[sorted(matched)[0]]
        last_inc = ser.last_increase_t()
        if last_inc is None or ser.span() < rule.window_s:
            # not watched long enough to call anything a stall
            return None
        stalled_for = now - last_inc
        return stalled_for > rule.window_s, stalled_for

    # -- lifecycle ---------------------------------------------------------

    def _transition(self, rule, target, breach, value, now):
        events = []
        with self._lock:
            key = (rule.name, target)
            act = self._act.get(key)
            if act is None:
                act = self._act[key] = _Activation()
            if breach:
                act.breaches += 1
                act.value = value
                if not act.firing and act.breaches >= rule.for_ticks:
                    act.firing = True
                    act.fired_t = now
                    self.firing_total[rule.name] = (
                        self.firing_total.get(rule.name, 0) + 1
                    )
                    events.append({
                        "rule": rule.name, "state": "firing",
                        "target": target,
                        "window_s": float(rule.window_s),
                        "value": float(value),
                        "threshold": float(
                            rule.streak_n if rule.kind == "streak"
                            else rule.threshold
                        ),
                    })
            else:
                act.breaches = 0
                if act.firing:
                    act.firing = False
                    self.resolved_total[rule.name] = (
                        self.resolved_total.get(rule.name, 0) + 1
                    )
                    ev = {
                        "rule": rule.name, "state": "resolved",
                        "target": target,
                        "window_s": float(rule.window_s),
                        "firing_s": max(0.0, now - act.fired_t),
                    }
                    if value is not None:
                        ev["value"] = float(value)
                    events.append(ev)
        return events
