"""``Telemetry`` — the one object a driver threads through a run.

Bundles the event bus (sinks from CLI flags), the health monitor, the
recompile monitor, and the iteration-windowed ``jax.profiler`` capture, so
``agent.learn`` takes ONE optional argument instead of four and the CLI
wiring lives in one place:

* ``--metrics-jsonl PATH``  → JSONL sink on the bus (manifest + iteration
  + phase + health + recompile records, ``scripts/validate_events.py``
  schema);
* ``--health-checks``       → health monitor + console sink for
  health/recompile findings;
* ``--profile-dir D --profile-iteration N`` → a ``jax.profiler`` trace
  window around iteration N only (PhaseTimer names annotate the
  timeline), instead of tracing the entire run.

Lifecycle (driven by ``agent.learn``): ``start_run(cfg, ...)`` emits the
run manifest and attaches the recompile monitor; ``mark_steady()`` after
warmup flips further compilations to "unexpected"; ``on_iteration`` runs
the health rules on each drained stats row (thread-safe — the async
driver calls it from the drain thread); ``finish_run(timer)`` closes the
profile window, emits PhaseTimer summaries as ``phase`` events, and
detaches the recompile monitor. The creator (CLI, test) calls ``close()``
to flush/close the sinks.
"""

from __future__ import annotations

from typing import Any, Optional

from trpo_tpu.obs.events import ConsoleSink, EventBus, JsonlSink, manifest_fields
from trpo_tpu.obs.health import HealthConfig, HealthMonitor
from trpo_tpu.obs.recompile import RecompileMonitor

__all__ = ["Telemetry"]


class Telemetry:
    def __init__(
        self,
        events_jsonl: Optional[str] = None,
        health_checks: bool = False,
        recompile_monitor: bool = True,
        profile_dir: Optional[str] = None,
        profile_iteration: Optional[int] = None,
        health_config: Optional[HealthConfig] = None,
        sinks=(),
    ):
        bus_sinks = list(sinks)
        if events_jsonl:
            bus_sinks.append(JsonlSink(events_jsonl))
        if health_checks:
            # findings must be visible even without a JSONL file
            bus_sinks.append(ConsoleSink(kinds=("health", "recompile")))
        self.bus = EventBus(*bus_sinks)
        self.health = (
            HealthMonitor(bus=self.bus, config=health_config)
            if health_checks
            else None
        )
        self.recompile = (
            RecompileMonitor(bus=self.bus) if recompile_monitor else None
        )
        self.profile_dir = profile_dir
        self.profile_iteration = profile_iteration
        self._profiling = False
        self._profiled = False
        self._closed = False

    # -- run lifecycle -----------------------------------------------------

    def start_run(self, config: Any = None, **extra) -> None:
        self.bus.emit("run_manifest", **manifest_fields(config, extra))
        if self.recompile is not None:
            self.recompile.start()

    def mark_steady(self) -> None:
        if self.recompile is not None:
            self.recompile.mark_steady()

    def on_iteration(self, iteration: int, stats: dict) -> None:
        """Health rules on one drained stats row. Iteration EVENTS are
        emitted by ``StatsLogger`` (which re-logs through the bus), so
        this hook never double-emits them."""
        if self.health is not None:
            self.health.observe_iteration(iteration, stats)

    def observe_drain(self, depth: int, high_water: int,
                      maxsize: int) -> None:
        if self.health is not None:
            self.health.observe_drain(depth, high_water, maxsize)

    # -- iteration-windowed profiler capture -------------------------------

    def profile_tick(self, next_iteration: int, span: int = 1) -> None:
        """Called at the top of each iteration/chunk with the ABSOLUTE
        1-based iteration number about to run and the number of
        iterations the upcoming program covers (``fuse_iterations``
        chunks): opens the ``jax.profiler`` trace when the chunk CONTAINS
        the requested iteration, closes it once the window has passed.
        A target already behind the run (a resume past N) still captures
        the first chunk rather than nothing."""
        if self.profile_dir is None or self.profile_iteration is None:
            return
        import jax

        if (
            not self._profiling
            and not self._profiled
            and next_iteration + span > self.profile_iteration
        ):
            jax.profiler.start_trace(self.profile_dir)
            self._profiling = True
        elif self._profiling and next_iteration > self.profile_iteration:
            jax.profiler.stop_trace()
            self._profiling = False
            self._profiled = True

    def _stop_profile(self) -> None:
        if self._profiling:
            import jax

            jax.profiler.stop_trace()
            self._profiling = False
            self._profiled = True

    # -- teardown ----------------------------------------------------------

    def finish_run(self, timer=None) -> None:
        """End-of-``learn`` hook: close an open profile window, emit the
        PhaseTimer's per-phase summaries as ``phase`` events, and detach
        the recompile monitor (post-run compiles — greedy eval, user code
        — are not retraces). Safe to call more than once."""
        self._stop_profile()
        if timer is not None:
            for name, row in timer.summary().items():
                self.bus.emit(
                    "phase",
                    name=name,
                    ms=row["mean_ms"],
                    calls=row["calls"],
                    total_s=row["total_s"],
                )
        if self.recompile is not None:
            self.recompile.stop()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.finish_run()
        self.bus.close()
