"""``Telemetry`` — the one object a driver threads through a run.

Bundles the event bus (sinks from CLI flags), the health monitor, the
recompile monitor, the iteration-windowed ``jax.profiler`` capture, and
(PR 5) the live status endpoint + device-memory accountant, so
``agent.learn`` takes ONE optional argument and the CLI wiring lives in
one place:

* ``--metrics-jsonl PATH``  → JSONL sink on the bus (manifest + iteration
  + phase + health + recompile + memory records,
  ``scripts/validate_events.py`` schema);
* ``--health-checks``       → health monitor + console sink for
  health/recompile findings;
* ``--status-port P``       → ``obs/server.StatusSink`` on the bus + a
  background HTTP server: ``GET /status`` (JSON snapshot of the run) and
  ``GET /metrics`` (Prometheus text). ``P=0`` = ephemeral; the bound
  port is announced as a ``status`` event right after the manifest.
  Unset → no sink, no thread, event bytes untouched;
* ``--memory-accounting``   → ``obs/memory.MemoryMonitor``: compiled
  ``memory_analysis()`` per core jitted program (one extra compile each,
  pre-steady), per-iteration live-buffer gauges, and the
  ``health:memory_leak`` window rule;
* ``--profile-dir D --profile-iteration N`` → a ``jax.profiler`` trace
  window around iteration N only (PhaseTimer names annotate the
  timeline), instead of tracing the entire run.

Lifecycle (driven by ``agent.learn``): ``start_run(cfg, ...)`` emits the
run manifest (and the ``status`` announcement) and attaches the recompile
monitor; ``mark_steady()`` after warmup flips further compilations to
"unexpected"; ``on_iteration`` runs the health rules and memory gauges on
each drained stats row (thread-safe — the async driver calls it from the
drain thread); ``finish_run(timer)`` closes the profile window, emits
PhaseTimer summaries as ``phase`` events, marks the status snapshot
finished, and detaches the recompile monitor. The creator (CLI, test)
calls ``close()`` to flush/close the sinks and stop the status server.
"""

from __future__ import annotations

from typing import Any, Optional

from trpo_tpu.obs.events import ConsoleSink, EventBus, JsonlSink, manifest_fields
from trpo_tpu.obs.health import HealthConfig, HealthMonitor
from trpo_tpu.obs.recompile import RecompileMonitor

__all__ = ["Telemetry"]


class Telemetry:
    def __init__(
        self,
        events_jsonl: Optional[str] = None,
        health_checks: bool = False,
        recompile_monitor: bool = True,
        profile_dir: Optional[str] = None,
        profile_iteration: Optional[int] = None,
        health_config: Optional[HealthConfig] = None,
        status_port: Optional[int] = None,
        memory_accounting: bool = False,
        sinks=(),
    ):
        bus_sinks = list(sinks)
        if events_jsonl:
            bus_sinks.append(JsonlSink(events_jsonl))
        if health_checks:
            # findings must be visible even without a JSONL file
            bus_sinks.append(ConsoleSink(kinds=("health", "recompile")))
        elif memory_accounting and not events_jsonl and not sinks:
            # --memory-accounting alone must not emit into a SINKLESS
            # bus: the leak detector's health:memory_leak would vanish
            # while the run still paid for the accounting — surface
            # health findings on the console at minimum
            bus_sinks.append(ConsoleSink(kinds=("health",)))
        self.status = None
        self.status_server = None
        if status_port is not None:
            # sink first (it must see every record from the manifest on),
            # server below once the bus exists
            from trpo_tpu.obs.server import StatusSink

            self.status = StatusSink()
            bus_sinks.append(self.status)
        self.bus = EventBus(*bus_sinks)
        self.health = (
            HealthMonitor(bus=self.bus, config=health_config)
            if health_checks
            else None
        )
        self.memory = None
        if memory_accounting:
            from trpo_tpu.obs.memory import MemoryMonitor

            # the leak rule lives in a HealthMonitor; share the
            # --health-checks one when present so its findings list sees
            # the leak too, otherwise a private instance (only the
            # memory rule will ever fire on it)
            self.memory = MemoryMonitor(
                bus=self.bus,
                health=self.health
                or HealthMonitor(bus=self.bus, config=health_config),
            )
        if self.status is not None:
            from trpo_tpu.obs.server import StatusServer

            self.status_server = StatusServer(self.status, status_port)
        self.recompile = (
            RecompileMonitor(bus=self.bus) if recompile_monitor else None
        )
        self.profile_dir = profile_dir
        self.profile_iteration = profile_iteration
        self._profiling = False
        self._profiled = False
        self._timer = None   # attach_timer: live phase timings source
        self._closed = False

    # -- run lifecycle -----------------------------------------------------

    def start_run(self, config: Any = None, **extra) -> None:
        self.bus.emit("run_manifest", **manifest_fields(config, extra))
        if self.status_server is not None:
            # after the manifest: validators require the manifest first,
            # and the log should say where the endpoint lives
            self.bus.emit(
                "status",
                port=self.status_server.port,
                url=self.status_server.url,
                endpoints=list(self.status_server.ENDPOINTS),
            )
        if self.recompile is not None:
            self.recompile.start()

    def mark_steady(self) -> None:
        if self.recompile is not None:
            self.recompile.mark_steady()

    def attach_timer(self, timer) -> None:
        """The driver's PhaseTimer, so the live snapshot can carry
        per-phase timings DURING the run (the bus only gets ``phase``
        events at ``finish_run``, when a mid-run scrape can no longer
        use them). ``summary()`` is lock-protected — safe to read from
        the async driver's drain thread."""
        self._timer = timer

    def on_iteration(self, iteration: int, stats: dict) -> None:
        """Health rules + memory gauges on one drained stats row.
        Iteration EVENTS are emitted by ``StatsLogger`` (which re-logs
        through the bus), so this hook never double-emits them."""
        if self.health is not None:
            self.health.observe_iteration(iteration, stats)
        if self.memory is not None:
            self.memory.on_iteration(iteration)
        if self.status is not None and self._timer is not None:
            self.status.set_phases(self._timer.summary())

    def observe_drain(self, depth: int, high_water: int,
                      maxsize: int) -> None:
        if self.health is not None:
            self.health.observe_drain(depth, high_water, maxsize)
        if self.status is not None:
            self.status.set_gauges(
                depth=depth, high_water=high_water, maxsize=maxsize
            )

    # -- compiled-program memory accounting --------------------------------

    @property
    def wants_program_memory(self) -> bool:
        """True when the drivers should capture abstract argument shapes
        for their jitted programs (``--memory-accounting``)."""
        return self.memory is not None

    def emit_program_memory(self, programs: dict) -> None:
        """``{name: (jitted_fn, abstract_args)}`` → one ``memory``
        event per not-yet-analyzed program. Idempotent per name; the
        drivers call it each chunk with whatever has compiled so far
        (a fused tail chunk's program appears late)."""
        if self.memory is None:
            return
        for name, (fn, args) in programs.items():
            self.memory.emit_program(name, fn, args)

    # -- iteration-windowed profiler capture -------------------------------

    def profile_tick(self, next_iteration: int, span: int = 1) -> None:
        """Called at the top of each iteration/chunk with the ABSOLUTE
        1-based iteration number about to run and the number of
        iterations the upcoming program covers (``fuse_iterations``
        chunks): opens the ``jax.profiler`` trace when the chunk CONTAINS
        the requested iteration, closes it once the window has passed.
        A target already behind the run (a resume past N) still captures
        the first chunk rather than nothing."""
        if self.profile_dir is None or self.profile_iteration is None:
            return
        import jax

        if (
            not self._profiling
            and not self._profiled
            and next_iteration + span > self.profile_iteration
        ):
            jax.profiler.start_trace(self.profile_dir)
            self._profiling = True
        elif self._profiling and next_iteration > self.profile_iteration:
            jax.profiler.stop_trace()
            self._profiling = False
            self._profiled = True

    def _stop_profile(self) -> None:
        if self._profiling:
            import jax

            jax.profiler.stop_trace()
            self._profiling = False
            self._profiled = True

    # -- teardown ----------------------------------------------------------

    def finish_run(self, timer=None) -> None:
        """End-of-``learn`` hook: close an open profile window, emit the
        PhaseTimer's per-phase summaries as ``phase`` events, mark the
        status snapshot finished, and detach the recompile monitor
        (post-run compiles — greedy eval, user code — are not retraces).
        Safe to call more than once."""
        self._stop_profile()
        if timer is not None:
            for name, row in timer.summary().items():
                self.bus.emit(
                    "phase",
                    name=name,
                    ms=row["mean_ms"],
                    calls=row["calls"],
                    total_s=row["total_s"],
                )
        if self.status is not None:
            self.status.mark_finished()
        if self.recompile is not None:
            self.recompile.stop()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.finish_run()
        if self.status_server is not None:
            self.status_server.close()
        self.bus.close()
