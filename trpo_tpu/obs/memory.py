"""Device-memory accounting: predict HBM, watch HBM, catch leaks.

Device memory is the binding constraint for the ROADMAP's flagship
shapes (a 2000×50k Humanoid batch plus donated update buffers): a run
that OOMs three hours in wasted three hours, and a run that leaks a
buffer per iteration dies at an hour no log explains. Three surfaces,
all riding the PR 3 event bus as ``memory`` records:

* **Compiled-program accounting** (``scope="program"``). XLA's
  ``Compiled.memory_analysis()`` knows, at compile time, exactly how
  many bytes a program needs for arguments, outputs and temporaries —
  :func:`program_memory_analysis` lowers a jitted function against
  ABSTRACT argument shapes (``jax.ShapeDtypeStruct``, shardings
  preserved — no data materialized) and returns those numbers. The
  drivers emit one event per core program (the fused iteration, the
  host phase programs) right after warmup; ``bench.py`` embeds the same
  fields next to each headline phase's timing. Cost: one extra XLA
  compile per analyzed program (the AOT path cannot reuse the jit
  cache's executable), which is why this is opt-in
  (``--memory-accounting``) and happens once, before the run is marked
  steady (so the recompile monitor does not count it as a retrace).
* **Live gauges** (``scope="live"``). Per iteration:
  ``jax.live_arrays()`` count/bytes and, where the backend reports it
  (TPU/GPU — CPU returns None), ``device.memory_stats()``
  bytes-in-use/peak. Sampled from ``Telemetry.on_iteration`` — i.e. on
  the async driver's drain thread, off the critical path.
* **Leak detection.** The gauges feed
  ``HealthMonitor.observe_memory``: live bytes growing monotonically
  across a full window of iterations in steady state is a retained
  reference (a stats pytree kept alive, a snapshot window that forgot
  its bound) — surfaced once as a ``health:memory_leak`` event.
"""

from __future__ import annotations

import warnings
from typing import Any, Optional

__all__ = [
    "abstract_args",
    "compiled_memory_fields",
    "program_memory_analysis",
    "live_memory_gauges",
    "MemoryMonitor",
]


def abstract_args(tree: Any):
    """A pytree of ``jax.ShapeDtypeStruct`` mirroring ``tree``'s arrays
    (shape, dtype and — for committed jax arrays — sharding), suitable
    for ``jitted.lower(*abstract)``: the lowering sees exactly the
    specialization the real call compiled, without keeping any data
    alive. Non-array leaves pass through untouched."""
    import jax

    def conv(x):
        if isinstance(x, jax.Array):
            try:
                return jax.ShapeDtypeStruct(
                    x.shape, x.dtype, sharding=x.sharding
                )
            except Exception:
                return jax.ShapeDtypeStruct(x.shape, x.dtype)
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return jax.ShapeDtypeStruct(x.shape, x.dtype)
        return x

    return jax.tree_util.tree_map(conv, tree)


def compiled_memory_fields(compiled) -> Optional[dict]:
    """The byte fields of one ``jax.stages.Compiled``'s
    ``memory_analysis()``, or None when the backend reports nothing.
    ``peak_estimate_bytes`` is the resident-set upper bound while the
    program runs: arguments + outputs + temporaries − donation-aliased
    bytes (aliased buffers are counted in both arguments and outputs
    but exist once)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    fields = {}
    for key, attr in (
        ("argument_bytes", "argument_size_in_bytes"),
        ("output_bytes", "output_size_in_bytes"),
        ("temp_bytes", "temp_size_in_bytes"),
        ("alias_bytes", "alias_size_in_bytes"),
        ("generated_code_bytes", "generated_code_size_in_bytes"),
    ):
        v = getattr(ma, attr, None)
        fields[key] = int(v) if v is not None else 0
    fields["peak_estimate_bytes"] = max(
        0,
        fields["argument_bytes"]
        + fields["output_bytes"]
        + fields["temp_bytes"]
        - fields["alias_bytes"],
    )
    return fields


def program_memory_analysis(jitted_fn, args: tuple) -> Optional[dict]:
    """Lower + compile ``jitted_fn`` against (abstract) ``args`` and
    return :func:`compiled_memory_fields`. Failures come back as None
    with a warning — memory accounting must never take down a run it
    was meant to protect."""
    try:
        with warnings.catch_warnings():
            # lowering a donating program against abstract args re-emits
            # jax's "donated buffers were not usable" warning on backends
            # without donation (CPU) — the real call already surfaced it
            warnings.simplefilter("ignore")
            compiled = jitted_fn.lower(*args).compile()
        return compiled_memory_fields(compiled)
    except Exception as e:
        warnings.warn(
            f"program memory analysis failed ({type(e).__name__}: {e})"
        )
        return None


def live_memory_gauges() -> dict:
    """Host-visible device-memory gauges: live jax array count/bytes,
    plus the backend allocator's bytes-in-use/peak where reported
    (``device.memory_stats()`` — TPU/GPU; CPU has no allocator stats
    and contributes nothing)."""
    import jax

    arrs = jax.live_arrays()
    gauges = {
        "live_buffer_count": len(arrs),
        "live_buffer_bytes": int(
            sum(getattr(a, "nbytes", 0) or 0 for a in arrs)
        ),
    }
    in_use = peak = None
    for d in jax.local_devices():
        try:
            ms = d.memory_stats()
        except Exception:
            ms = None
        if not ms:
            continue
        in_use = (in_use or 0) + int(ms.get("bytes_in_use", 0))
        peak = (peak or 0) + int(
            ms.get("peak_bytes_in_use", ms.get("bytes_in_use", 0))
        )
    if in_use is not None:
        gauges["device_bytes_in_use"] = in_use
        gauges["device_peak_bytes"] = peak
    return gauges


class MemoryMonitor:
    """The run-attached accountant: program events once, live gauges per
    iteration, leak rule via the health monitor.

    ``health`` is a ``HealthMonitor`` (shared with ``--health-checks``
    when both are on, private otherwise) — the leak rule and its
    windowed state live there, next to the other health rules."""

    def __init__(self, bus=None, health=None):
        self.bus = bus
        self.health = health
        self._programs_emitted: set = set()
        self.program_fields: dict = {}

    # -- compiled-program accounting ---------------------------------------

    def emit_program(self, name: str, jitted_fn, args: tuple) -> None:
        """Analyze + emit one program's compiled memory, once per name
        (the drivers call this every chunk with whatever has compiled so
        far; repeats are free)."""
        if name in self._programs_emitted:
            return
        self._programs_emitted.add(name)
        fields = program_memory_analysis(jitted_fn, args)
        if fields is None:
            return
        self.program_fields[name] = fields
        if self.bus is not None:
            self.bus.emit("memory", scope="program", program=name,
                          **fields)

    # -- live gauges + leak detection --------------------------------------

    def on_iteration(self, iteration: int) -> dict:
        """Sample gauges, emit the ``scope="live"`` event, feed the leak
        detector. Runs on whatever thread drains stats — never on the
        device's critical path."""
        gauges = live_memory_gauges()
        if self.bus is not None:
            self.bus.emit(
                "memory", scope="live", iteration=int(iteration), **gauges
            )
        if self.health is not None:
            self.health.observe_memory(
                int(iteration), gauges["live_buffer_bytes"]
            )
        return gauges
