"""Unified telemetry + run introspection: run-event bus, device-side
metric accumulation, recompile/health monitors, the ``Telemetry`` bundle
drivers thread through a run (ISSUE 3 tentpole), and — ISSUE 5 — the
live status/metrics endpoint (``obs/server``), device-memory accounting
(``obs/memory``), cross-run analysis (``obs/analyze``), and — ISSUE 20
— the fleet-wide live observability plane: scrape-everything
aggregation (``obs/aggregate``) and declarative SLO alerting
(``obs/alerts``). See ``ARCHITECTURE.md`` "Telemetry",
"Introspection", and "Live observability"."""

from trpo_tpu.obs.aggregate import (  # noqa: F401
    CallbackTarget,
    HttpTarget,
    JournalTarget,
    MetricsAggregator,
    Series,
)
from trpo_tpu.obs.alerts import (  # noqa: F401
    FAULT_ALERT_RULES,
    AlertEngine,
    Rule,
    default_rules,
)
from trpo_tpu.obs.capture import (  # noqa: F401
    RequestCapture,
    capture_records,
    decode_payload,
    encode_obs_payload,
)
from trpo_tpu.obs.device_metrics import (  # noqa: F401
    DeviceMetrics,
    accumulate_update,
    init_device_metrics,
    metrics_stats,
)
from trpo_tpu.obs.events import (  # noqa: F401
    EVENT_KINDS,
    SCHEMA_VERSION,
    ConsoleSink,
    EventBus,
    JsonlSink,
    manifest_fields,
    validate_event,
)
from trpo_tpu.obs.health import HealthConfig, HealthMonitor  # noqa: F401
from trpo_tpu.obs.memory import (  # noqa: F401
    MemoryMonitor,
    compiled_memory_fields,
    live_memory_gauges,
    program_memory_analysis,
)
from trpo_tpu.obs.recompile import RecompileMonitor  # noqa: F401
from trpo_tpu.obs.replay import (  # noqa: F401
    BUNDLE_VERSION,
    BundleError,
    action_match,
    build_bundle,
    load_bundle,
    scan_journals,
    write_bundle,
)
from trpo_tpu.obs.server import StatusServer, StatusSink  # noqa: F401
from trpo_tpu.obs.telemetry import Telemetry  # noqa: F401

__all__ = [
    "CallbackTarget",
    "HttpTarget",
    "JournalTarget",
    "MetricsAggregator",
    "Series",
    "FAULT_ALERT_RULES",
    "AlertEngine",
    "Rule",
    "default_rules",
    "RequestCapture",
    "capture_records",
    "decode_payload",
    "encode_obs_payload",
    "DeviceMetrics",
    "accumulate_update",
    "init_device_metrics",
    "metrics_stats",
    "EVENT_KINDS",
    "SCHEMA_VERSION",
    "ConsoleSink",
    "EventBus",
    "JsonlSink",
    "manifest_fields",
    "validate_event",
    "HealthConfig",
    "HealthMonitor",
    "MemoryMonitor",
    "compiled_memory_fields",
    "live_memory_gauges",
    "program_memory_analysis",
    "RecompileMonitor",
    "BUNDLE_VERSION",
    "BundleError",
    "action_match",
    "build_bundle",
    "load_bundle",
    "scan_journals",
    "write_bundle",
    "StatusServer",
    "StatusSink",
    "Telemetry",
]
