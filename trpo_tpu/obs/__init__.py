"""Unified telemetry: run-event bus, device-side metric accumulation,
recompile/health monitors, and the ``Telemetry`` bundle drivers thread
through a run (ISSUE 3 tentpole). See ``ARCHITECTURE.md`` "Telemetry"."""

from trpo_tpu.obs.device_metrics import (  # noqa: F401
    DeviceMetrics,
    accumulate_update,
    init_device_metrics,
    metrics_stats,
)
from trpo_tpu.obs.events import (  # noqa: F401
    EVENT_KINDS,
    SCHEMA_VERSION,
    ConsoleSink,
    EventBus,
    JsonlSink,
    manifest_fields,
    validate_event,
)
from trpo_tpu.obs.health import HealthConfig, HealthMonitor  # noqa: F401
from trpo_tpu.obs.recompile import RecompileMonitor  # noqa: F401
from trpo_tpu.obs.telemetry import Telemetry  # noqa: F401

__all__ = [
    "DeviceMetrics",
    "accumulate_update",
    "init_device_metrics",
    "metrics_stats",
    "EVENT_KINDS",
    "SCHEMA_VERSION",
    "ConsoleSink",
    "EventBus",
    "JsonlSink",
    "manifest_fields",
    "validate_event",
    "HealthConfig",
    "HealthMonitor",
    "RecompileMonitor",
    "Telemetry",
]
