"""Cross-run analysis over event JSONL: summarize one run, diff two.

The event stream (``obs/events.py``) made every run self-describing;
this module makes it machine-checkable. One file → a run report:
per-phase time table, throughput, health/recompile summary, peak-memory
report. Two files → per-phase and per-metric regression verdicts with a
threshold — the engine behind ``scripts/analyze_run.py --compare``, the
repo's first automated perf-regression gate (``check.sh`` trains two
short runs and gates a PR on the comparison).

Reader tolerance vs validator strictness: :func:`load_events` is a
READER — it skips a mid-file corrupt record (crash-torn, disk bit rot)
with a ``warnings.warn`` and keeps going, and it tolerates record kinds
it does not know (a newer writer's log still summarizes). The STRICT
side is ``scripts/validate_events.py``, which fails on unknown kinds and
newer schema versions; a pipeline that wants both runs the validator
first.

Comparison semantics (:func:`compare_runs`):

* time-like metrics (phase mean ms, steady iteration ms) regress when
  ``new > base × (1 + threshold_pct/100)``;
* rate-like metrics (timesteps/s) regress when
  ``new < base ÷ (1 + threshold_pct/100)``;
* byte-like metrics (program temp/peak bytes, live-buffer peak) regress
  when they GROW past the threshold — an HBM regression OOMs the
  flagship shape as surely as a slowdown misses the deadline;
* serving runs (``serve`` events) are judged by the same rules: latency
  p50/p99 (overall and per padded rung) are time-like, actions/s is
  rate-like — the ISSUE 6 SLO gate; the rows appear only when at least
  one run actually served;
* replicated-serving runs (``router`` events — ISSUE 9) likewise:
  router p50/p99 time-like, routed actions/s rate-like, rows only when
  a run actually routed; the single-run summary adds the per-replica
  table, the scaling/balance row, and the session lifecycle counts;
* failover quality (ISSUE 11): sessions resumed from a journaled carry
  vs restarted fresh (``resumed_fraction`` rate-like — losing lossless
  failover is a regression) plus carry-journal lag, and canary
  deployment verdicts (``rolled_back`` is a strict counter — any rise
  between clean runs means a checkpoint failed its gate);
* elastic serving (ISSUE 12, ``autoscale`` events): scale events,
  drain durations + sessions moved, shed counts by reason;
  ``drain_aborted`` is a strict counter (a drain that could not move
  its sessions losslessly is never noise), drain duration time-like,
  shed totals grow-is-worse;
* multi-host liveness (ISSUE 14, ``lease`` + ``router scope="host"``
  events): the per-host replica table, lease grant/renew/expire
  counts, fenced journal-write refusals, and injected partition
  durations; ``lease_expired`` and ``fenced_write_refused`` are
  strict counters between clean runs — a lease expiring (or a
  split-brain writer being refused) where the base run had none is a
  liveness event, never noise;
* request traces (ISSUE 15, ``span`` events from ``obs/trace.py``):
  :func:`assemble_traces` joins spans across per-process event logs
  (router + N replicas — merge the files' records first),
  :func:`trace_breakdown` attributes each trace's end-to-end time to
  stages (queue / epoch / engine / network / journal / retry /
  takeover — network is structural: each router hop minus the remote
  handler time nested under it), the summary carries the per-stage
  p50/p99 + share table and the slowest-trace rows, and
  ``compare_runs`` judges the root p99 and every per-stage p99
  time-like — a grown stage is a LOCATED regression;
* alerting-plane runs (ISSUE 20, ``alert`` + ``metric_sample`` events
  from ``obs/aggregate.py`` + ``obs/alerts.py``): the per-rule
  fired/resolved/active table with time-to-detect against the log's
  injected faults; ``false_positives`` (a firing in a provably quiet
  phase — no fault at all in the 120 s before it) is a strict counter
  between clean runs, time-to-detect is time-like, and per-rule fired
  counts grow-is-worse;
* phases below ``min_ms`` in BOTH runs are skipped (a 0.1 ms phase
  doubling is scheduler noise, not a regression), as are metrics absent
  from either run (no silent verdict about unmeasured things — they are
  reported as ``skipped``).

The steady iteration time drops each run *segment*'s FIRST iteration
row when more than two rows exist: the first row after every
``run_manifest`` carries XLA compilation — and a resumed/requeued
member (the ISSUE 7 fleet orchestrator appends the resumed run to the
SAME event file) has one such compile-laden row per segment, which
would otherwise dominate short gate runs and hide real regressions.

Fleet logs (``fleet`` lifecycle records from ``fleet/scheduler.py``)
get their own summary block: per-member last state / attempts /
requeues plus state totals — so ``analyze_run.py`` on a fleet's event
log reads as a fleet report.
"""

from __future__ import annotations

import json
import math
import warnings
from collections import Counter
from typing import Optional

__all__ = [
    "load_events",
    "summarize_run",
    "compare_runs",
    "format_table",
    "assemble_traces",
    "trace_breakdown",
    "render_waterfall",
]


def load_events(path: str) -> list:
    """Parse one event-JSONL file, tolerantly: corrupt lines are skipped
    with a ``UserWarning`` naming the line, blank lines are ignored,
    unknown kinds pass through. Raises ``OSError`` for an unreadable
    file — no events at all is the caller's verdict to make."""
    records = []
    # errors="replace": a non-UTF8 byte (binary garbage, torn gzip) must
    # corrupt THAT line's parse, not abort the whole read — the mangled
    # line then warns-and-skips like any other corrupt record
    with open(path, errors="replace") as f:
        for n, line in enumerate(f, 1):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                warnings.warn(
                    f"{path}:{n}: skipping corrupt record ({e})"
                )
                continue
            if not isinstance(rec, dict):
                warnings.warn(
                    f"{path}:{n}: skipping non-object record"
                )
                continue
            records.append(rec)
    return records


def _finite(v) -> Optional[float]:
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return float(v) if math.isfinite(v) else None


def _mean(vals: list) -> Optional[float]:
    vals = [v for v in (_finite(v) for v in vals) if v is not None]
    return sum(vals) / len(vals) if vals else None


def _quantile(vals: list, q: float) -> Optional[float]:
    """Nearest-rank quantile over the finite values (None when empty) —
    the shared estimator (``utils/metrics.quantile_nearest_rank``), so a
    scraped /metrics gauge and an analyzed event log tell the same
    story."""
    from trpo_tpu.utils.metrics import quantile_nearest_rank

    return quantile_nearest_rank(
        [v for v in (_finite(v) for v in vals) if v is not None], q
    )


def _summarize_serving(records: list) -> Optional[dict]:
    """Aggregate the ``serve`` micro-batch records into the serving SLO
    report: request/batch totals, actions/s over the serving span,
    latency p50/p99 (per-batch oldest-request latency — the conservative,
    SLO-relevant end), and a per-padded-rung breakdown."""
    serves = [r for r in records if r.get("kind") == "serve"]
    if not serves:
        return None
    lats = [r.get("latency_ms") for r in serves]
    requests = sum(
        r.get("requests") for r in serves
        if isinstance(r.get("requests"), int)
    )
    times = [r.get("t") for r in serves if _finite(r.get("t")) is not None]
    span = (max(times) - min(times)) if len(times) >= 2 else None
    shapes: dict = {}
    for r in serves:
        rung = r.get("padded")
        if rung is None:
            continue
        row = shapes.setdefault(str(rung), {"batches": 0, "requests": 0,
                                            "lats": []})
        row["batches"] += 1
        if isinstance(r.get("requests"), int):
            row["requests"] += r["requests"]
        if _finite(r.get("latency_ms")) is not None:
            row["lats"].append(r["latency_ms"])
    return {
        "requests_total": requests,
        "batches_total": len(serves),
        "mean_batch_size": requests / len(serves) if serves else None,
        # span covers first→last dispatch; one lone batch has no rate
        "actions_per_sec": (requests / span) if span else None,
        "latency_p50_ms": _quantile(lats, 0.5),
        "latency_p99_ms": _quantile(lats, 0.99),
        "queue_depth_max": max(
            (r.get("queue_depth") for r in serves
             if _finite(r.get("queue_depth")) is not None),
            default=None,
        ),
        "shapes": {
            rung: {
                "batches": row["batches"],
                "requests": row["requests"],
                "p50_ms": _quantile(row["lats"], 0.5),
                "p99_ms": _quantile(row["lats"], 0.99),
            }
            for rung, row in shapes.items()
        },
    }


def _summarize_router(records: list) -> Optional[dict]:
    """Aggregate the replicated-serving control plane's records (ISSUE
    9): ``router`` ``scope="request"`` rows into routed/retried/failed
    totals, p50/p99 and routed actions/s; ``scope="replica"`` rows into
    a per-replica lifecycle/traffic table; ``session`` rows into the
    session lifecycle counts. The ``scaling`` row reports per-replica
    throughput and load balance (worst/best replica request share —
    1.0 = perfectly even); the CROSS-run scaling efficiency (N-replica
    vs 1-replica actions/s) lives in ``bench.py serving_scale`` /
    BENCH_LADDER, where both legs exist."""
    reqs = [
        r for r in records
        if r.get("kind") == "router" and r.get("scope") == "request"
    ]
    lifecycle = [
        r for r in records
        if r.get("kind") == "router" and r.get("scope") == "replica"
    ]
    sessions = [r for r in records if r.get("kind") == "session"]
    canary = [r for r in records if r.get("kind") == "canary"]
    promote = [r for r in records if r.get("kind") == "promote"]
    autoscale = [r for r in records if r.get("kind") == "autoscale"]
    lease = [r for r in records if r.get("kind") == "lease"]
    host_recs = [
        r for r in records
        if r.get("kind") == "router" and r.get("scope") == "host"
    ]
    partitions = [
        r for r in records
        if r.get("kind") == "fault_injected"
        and r.get("fault") == "partition_host"
    ]
    if not reqs and not lifecycle and not lease and not promote:
        # lease-only logs (a fenced zombie's own event file) still get
        # a summary — the fencing refusals are the story there; same
        # for promote-only logs (a promotion controller's own file)
        return None
    ok_reqs = [r for r in reqs if r.get("ok")]
    lats = [r.get("ms") for r in ok_reqs]
    times = [
        r.get("t") for r in ok_reqs if _finite(r.get("t")) is not None
    ]
    span = (max(times) - min(times)) if len(times) >= 2 else None

    replicas: dict = {}

    def _row(rid):
        return replicas.setdefault(
            str(rid),
            {"requests": 0, "lats": [], "restarts": 0, "deaths": 0,
             "last_state": None},
        )

    for r in lifecycle:
        rid = r.get("replica")
        if rid is None:
            continue
        row = _row(rid)
        state = r.get("state")
        row["last_state"] = state if isinstance(state, str) else "unknown"
        if state == "restarted":
            row["restarts"] += 1
        elif state == "died":
            row["deaths"] += 1
    for r in ok_reqs:
        rid = r.get("replica")
        if rid is None:
            continue
        row = _row(rid)
        row["requests"] += 1
        if _finite(r.get("ms")) is not None:
            row["lats"].append(r["ms"])

    shares = [
        row["requests"] for row in replicas.values() if row["requests"]
    ]
    routed = len(ok_reqs)
    return {
        "routed_total": routed,
        "retried_total": sum(1 for r in reqs if r.get("retried")),
        "failed_total": sum(1 for r in reqs if not r.get("ok")),
        "actions_per_sec": (routed / span) if span else None,
        "latency_p50_ms": _quantile(lats, 0.5),
        "latency_p99_ms": _quantile(lats, 0.99),
        "replicas": {
            rid: {
                "requests": row["requests"],
                "p50_ms": _quantile(row["lats"], 0.5),
                "restarts": row["restarts"],
                "deaths": row["deaths"],
                "last_state": row["last_state"],
            }
            for rid, row in replicas.items()
        },
        "scaling": {
            "replicas": len(replicas),
            "actions_per_sec_per_replica": (
                routed / span / len(replicas)
                if span and replicas else None
            ),
            "balance": (
                min(shares) / max(shares) if shares and max(shares)
                else None
            ),
        },
        "sessions": dict(
            sorted(Counter(r.get("event") for r in sessions).items())
        ) if sessions else None,
        "failover": _failover_rows(sessions),
        "canary": _canary_rows(canary),
        "episodes": _episode_rows(sessions),
        "promote": _promote_rows(promote),
        "autoscale": _autoscale_rows(autoscale),
        "hosts": _host_rows(lifecycle, lease, host_recs),
        "lease": _lease_rows(lease, partitions),
    }


def _host_rows(lifecycle: list, lease: list, host_recs: list):
    """Per-host replica table (ISSUE 14): which replicas ran where,
    deaths and lease expiries per host, and the host's last recorded
    health state. None for single-host logs that never stamped a host
    on anything."""
    hosts: dict = {}

    def _row(host):
        return hosts.setdefault(
            str(host),
            {"replicas": set(), "deaths": 0, "lease_expired": 0,
             "last_state": None},
        )

    for r in lifecycle:
        host = r.get("host")
        if host is None:
            continue
        row = _row(host)
        if isinstance(r.get("replica"), str):
            row["replicas"].add(r["replica"])
        if r.get("state") == "died":
            row["deaths"] += 1
    for r in lease:
        host = r.get("host")
        if host is None:
            continue
        row = _row(host)
        if isinstance(r.get("replica"), str):
            row["replicas"].add(r["replica"])
        if r.get("event") == "expired":
            row["lease_expired"] += 1
    for r in host_recs:
        host = r.get("host")
        if host is None:
            continue
        state = r.get("state")
        _row(host)["last_state"] = (
            state if isinstance(state, str) else "unknown"
        )
    if not hosts:
        return None
    return {
        host: {**row, "replicas": sorted(row["replicas"])}
        for host, row in sorted(hosts.items())
    }


def _lease_rows(lease: list, partitions: list):
    """Lease-liveness summary (ISSUE 14): grant/renew/expire counts,
    fenced journal-write refusals (count + distinct sessions — the
    split-brain writers the fence silenced), and the injected
    partition durations. None for logs with neither lease records nor
    partitions."""
    if not lease and not partitions:
        return None
    counts = Counter(r.get("event") for r in lease)
    fenced_sessions = {
        r.get("session") for r in lease
        if r.get("event") == "fenced_write_refused"
        and isinstance(r.get("session"), str)
    }
    durations = [
        r.get("seconds") for r in partitions
        if _finite(r.get("seconds")) is not None
    ]
    return {
        "granted": counts.get("granted", 0),
        "renewed": counts.get("renewed", 0),
        "expired": counts.get("expired", 0),
        "fenced_write_refused": counts.get("fenced_write_refused", 0),
        "fenced_sessions": len(fenced_sessions),
        "partitions_injected": len(partitions),
        "partition_seconds_max": max(durations) if durations else None,
    }


def _failover_rows(sessions: list) -> Optional[dict]:
    """Failover quality (ISSUE 11): sessions resumed from a journaled
    carry vs restarted fresh, and the carry-journal lag (router-observed
    acts minus journaled steps at resume — 0 = the snapshot was current
    and the continuation bit-exact). None when no failover happened."""
    resumed = [r for r in sessions if r.get("event") == "resumed"]
    fresh = [r for r in sessions if r.get("event") == "reestablished"]
    if not resumed and not fresh:
        return None
    lags = [
        r.get("lag") for r in resumed
        if isinstance(r.get("lag"), int) and not isinstance(
            r.get("lag"), bool
        )
    ]
    total = len(resumed) + len(fresh)
    return {
        "resumed": len(resumed),
        "restarted_fresh": len(fresh),
        "resumed_fraction": len(resumed) / total,
        "journal_lag_mean": (sum(lags) / len(lags)) if lags else None,
        "journal_lag_max": max(lags) if lags else None,
    }


def _canary_rows(canary: list) -> Optional[dict]:
    """Canary deployment verdicts (ISSUE 11): per-lifecycle counts plus
    the per-step outcome table. None for logs with no canary records."""
    if not canary:
        return None
    counts = Counter(r.get("event") for r in canary)
    steps: dict = {}
    for r in canary:
        step = r.get("step")
        if step is None:
            continue
        row = steps.setdefault(
            str(step), {"replica": None, "outcome": "unresolved",
                        "reason": None}
        )
        if isinstance(r.get("replica"), str):
            row["replica"] = r["replica"]
        if r.get("event") in ("promoted", "rolled_back"):
            row["outcome"] = r["event"]
            if r.get("reason") is not None:
                row["reason"] = r["reason"]
    return {
        "started": counts.get("started", 0),
        "promoted": counts.get("promoted", 0),
        "rolled_back": counts.get("rolled_back", 0),
        "steps": steps,
    }


def _episode_rows(sessions: list) -> Optional[dict]:
    """Served realized-return summary (ISSUE 19): the router books a
    ``session``/``episode`` record per completed client episode — the
    feed the canary's reward gate judges and the promotion controller's
    feedback pools. None for logs with no episode records."""
    eps = [r for r in sessions if r.get("event") == "episode"]
    if not eps:
        return None
    returns = [
        r.get("ep_return") for r in eps
        if _finite(r.get("ep_return")) is not None
    ]
    steps = [
        r.get("ep_steps") for r in eps
        if isinstance(r.get("ep_steps"), int)
        and not isinstance(r.get("ep_steps"), bool)
    ]
    by_replica = Counter(
        str(r.get("replica")) for r in eps if r.get("replica") is not None
    )
    return {
        "episodes": len(eps),
        "mean_return": (
            sum(returns) / len(returns) if returns else None
        ),
        "steps_total": sum(steps) if steps else None,
        "by_replica": dict(sorted(by_replica.items())),
    }


def _promote_rows(promote: list) -> Optional[dict]:
    """Train→serve promotion verdicts (ISSUE 19): per-lifecycle counts,
    the per-serving-step outcome table, and the pooled served-return
    feedback. None for logs with no promote records."""
    if not promote:
        return None
    counts = Counter(r.get("event") for r in promote)
    steps: dict = {}
    fb_n = 0
    fb_weighted = 0.0
    for r in promote:
        step = r.get("step")
        if r.get("event") == "feedback":
            n = r.get("episodes")
            m = r.get("mean_return")
            if (
                isinstance(n, int) and not isinstance(n, bool) and n > 0
                and _finite(m) is not None
            ):
                fb_n += n
                fb_weighted += float(m) * n
            continue
        if step is None:
            continue
        row = steps.setdefault(
            str(step), {"member": None, "outcome": "unresolved",
                        "reason": None}
        )
        if isinstance(r.get("member"), str):
            row["member"] = r["member"]
        if r.get("event") in ("promoted", "rejected", "rolled_back"):
            row["outcome"] = r["event"]
            if r.get("reason") is not None:
                row["reason"] = r["reason"]
    return {
        "candidates": counts.get("candidate", 0),
        "promoted": counts.get("promoted", 0),
        "rejected": counts.get("rejected", 0),
        "rolled_back": counts.get("rolled_back", 0),
        "feedback_episodes": fb_n,
        "feedback_mean_return": (
            fb_weighted / fb_n if fb_n > 0 else None
        ),
        "steps": steps,
    }


def _autoscale_rows(autoscale: list) -> Optional[dict]:
    """Elastic-serving control actions (ISSUE 12): scale events, drain
    durations/sessions-moved, and the shed totals (each ``shed`` record
    is an aggregate carrying ``count``). None for logs with no
    autoscale records."""
    if not autoscale:
        return None
    counts = Counter(r.get("event") for r in autoscale)
    durations = [
        r.get("duration_s") for r in autoscale
        if r.get("event") == "drain_completed"
        and _finite(r.get("duration_s")) is not None
    ]
    moved = sum(
        r.get("sessions_moved") for r in autoscale
        if r.get("event") == "drain_completed"
        and isinstance(r.get("sessions_moved"), int)
    )
    sheds = sum(
        r.get("count") for r in autoscale
        if r.get("event") == "shed"
        and isinstance(r.get("count"), int)
    )
    shed_reasons = Counter()
    for r in autoscale:
        if r.get("event") == "shed" and isinstance(r.get("count"), int):
            shed_reasons[str(r.get("reason"))] += r["count"]
    return {
        "scale_out": counts.get("scale_out", 0),
        "drain_completed": counts.get("drain_completed", 0),
        "drain_aborted": counts.get("drain_aborted", 0),
        "sessions_moved": moved,
        "shed_total": sheds,
        "shed_reasons": dict(sorted(shed_reasons.items())),
        "drain_duration_mean_s": _mean(durations),
        "drain_duration_max_s": max(durations) if durations else None,
    }


# ---------------------------------------------------------------------------
# request traces (ISSUE 15)
# ---------------------------------------------------------------------------

# span name → critical-path stage bucket. Stages are ATTRIBUTION, not a
# partition of the root: a hop span contains the replica's handler time,
# so the "network" share is computed structurally (hop minus its remote
# children), never by subtracting buckets from the root.
_SPAN_STAGES = {
    "batch.queue_wait": "queue",
    "engine.step_batch": "epoch",
    "engine.infer": "engine",
    "journal.sync": "journal",
    "router.retry": "retry",
    "router.takeover": "takeover",
    "router.fence": "takeover",
}
_HOP_NAMES = ("router.dispatch", "router.retry")
TRACE_STAGES = (
    "queue", "epoch", "engine", "network", "journal", "retry",
    "takeover",
)


def assemble_traces(records: list, dropped: Optional[list] = None) -> dict:
    """Join span records — from ONE log or several concatenated
    per-process logs (router + N replicas + hosts; the caller merges
    with ``load_events`` per file) — into ``{trace_id: [spans sorted by
    start]}``. Duplicate records (the same file merged twice) collapse
    on ``(span, trace, name)``.

    ``dropped`` (ISSUE 18): a span record with a malformed/missing
    trace id used to be skipped SILENTLY — a replay-bundle builder
    that needed it could only read the miss as "trace never existed".
    Pass a list and every unjoinable record is appended to it, so
    reconstruction can report per-trace completeness instead of
    guessing."""
    traces: dict = {}
    seen = set()
    for r in records:
        if r.get("kind") != "span":
            continue
        tid = r.get("trace")
        if not isinstance(tid, str):
            if dropped is not None:
                dropped.append(r)
            continue
        key = (tid, r.get("span"), r.get("name"))
        if key in seen:
            continue
        seen.add(key)
        traces.setdefault(tid, []).append(r)
    for spans in traces.values():
        spans.sort(key=lambda s: _finite(s.get("start")) or 0.0)
    return traces


def _span_dur(s) -> float:
    return _finite(s.get("dur_ms")) or 0.0


def trace_breakdown(spans: list) -> Optional[dict]:
    """One assembled trace → its critical-path attribution: the root
    span (the edge's end-to-end time), per-stage durations, and the
    structural network share (each router hop's duration minus the
    remote handler time nested under it — what the wire and the
    injected transport latency cost). None when the trace has no root
    (a replica-only fragment)."""
    roots = [
        s for s in spans
        if s.get("parent") is None and not s.get("remote")
    ]
    if not roots:
        return None
    root = max(roots, key=_span_dur)
    stages = {stage: 0.0 for stage in TRACE_STAGES}
    by_parent: dict = {}
    for s in spans:
        p = s.get("parent")
        if p is not None:
            by_parent.setdefault(p, []).append(s)
    for s in spans:
        stage = _SPAN_STAGES.get(s.get("name"))
        if stage is not None:
            stages[stage] += _span_dur(s)
        if s.get("name") in _HOP_NAMES:
            handler = sum(
                _span_dur(c)
                for c in by_parent.get(s.get("span"), [])
                if c.get("remote")
            )
            stages["network"] += max(0.0, _span_dur(s) - handler)
    return {
        "trace": root.get("trace"),
        "root": root.get("name"),
        "root_ms": _span_dur(root),
        "unterminated": root.get("dur_ms") is None,
        "spans": len(spans),
        "stages": {
            k: v for k, v in stages.items() if v > 0.0
        },
    }


def _summarize_hops(spans: list) -> dict:
    """Dispatch-hop stats grouped by wire format (ISSUE 16): every
    ``router.dispatch``/``router.retry`` span carries ``codec``
    (json|binary) and ``transport`` (tcp|uds) attrs, so the per-group
    hop p99 — and the structural network share (hop minus the remote
    handler nested under it) — is exactly the before/after evidence the
    data-plane bench quotes. Keyed ``codec/transport``; spans from old
    logs without the attrs group under ``json/tcp`` (the only path that
    existed before the attrs did)."""
    by_parent: dict = {}
    for s in spans:
        p = s.get("parent")
        if p is not None:
            by_parent.setdefault(p, []).append(s)
    groups: dict = {}
    for s in spans:
        if s.get("name") not in _HOP_NAMES:
            continue
        key = (
            f"{s.get('codec') or 'json'}/{s.get('transport') or 'tcp'}"
        )
        handler = sum(
            _span_dur(c)
            for c in by_parent.get(s.get("span"), [])
            if c.get("remote")
        )
        dur = _span_dur(s)
        groups.setdefault(key, []).append(
            (dur, max(0.0, dur - handler))
        )
    out = {}
    for key in sorted(groups):
        hops = [h for h, _ in groups[key]]
        net = [n for _, n in groups[key]]
        out[key] = {
            "hops": len(hops),
            "hop_p50_ms": _quantile(hops, 0.5),
            "hop_p99_ms": _quantile(hops, 0.99),
            "network_p50_ms": _quantile(net, 0.5),
            "network_p99_ms": _quantile(net, 0.99),
        }
    return out


def _summarize_traces(records: list) -> Optional[dict]:
    """The per-run trace block: trace/span counts, root-duration
    quantiles, per-stage p50/p99 + mean share of the root, and the
    slowest traces (root duration, stage attribution). None for logs
    with no spans."""
    traces = assemble_traces(records)
    if not traces:
        return None
    rows = [
        b for b in (trace_breakdown(s) for s in traces.values())
        if b is not None
    ]
    spans_total = sum(len(s) for s in traces.values())
    wire = _summarize_hops(
        [s for spans in traces.values() for s in spans]
    )
    if not rows:
        return {"count": len(traces), "spans": spans_total,
                "assembled": 0, "stages": {}, "wire": wire,
                "slowest": []}
    roots = [r["root_ms"] for r in rows]
    root_mean = _mean(roots)
    stage_stats: dict = {}
    for stage in TRACE_STAGES:
        vals = [
            r["stages"][stage] for r in rows if stage in r["stages"]
        ]
        if not vals:
            continue
        stage_stats[stage] = {
            "traces": len(vals),
            "p50_ms": _quantile(vals, 0.5),
            "p99_ms": _quantile(vals, 0.99),
            "mean_ms": _mean(vals),
            # the stage's share of mean end-to-end time across ALL
            # assembled traces (absent = 0 for a trace) — the
            # critical-path table's headline column
            "share": (
                sum(vals) / (root_mean * len(rows))
                if root_mean else None
            ),
        }
    slowest = sorted(rows, key=lambda r: -r["root_ms"])[:5]
    return {
        "count": len(traces),
        "assembled": len(rows),
        "spans": spans_total,
        "root_p50_ms": _quantile(roots, 0.5),
        "root_p99_ms": _quantile(roots, 0.99),
        "stages": stage_stats,
        "wire": wire,
        "slowest": [
            {
                "trace": r["trace"],
                "root": r["root"],
                "root_ms": r["root_ms"],
                "stages": {
                    k: round(v, 3) for k, v in r["stages"].items()
                },
            }
            for r in slowest
        ],
    }


def render_waterfall(spans: list) -> str:
    """One assembled trace as a text waterfall: start offsets, scaled
    bars, durations, the stage taxonomy readable at a glance over ssh
    (no deps — the format_table contract)."""
    if not spans:
        return "(no spans)"
    spans = sorted(spans, key=lambda s: _finite(s.get("start")) or 0.0)
    t0 = min(_finite(s.get("start")) or 0.0 for s in spans)
    ends = [
        (_finite(s.get("start")) or 0.0) - t0 + _span_dur(s) / 1e3
        for s in spans
    ]
    window_s = max(max(ends), 1e-9)
    width = 32
    rows = []
    for s in spans:
        off_s = (_finite(s.get("start")) or 0.0) - t0
        dur_s = _span_dur(s) / 1e3
        left = int(off_s / window_s * width)
        bar = max(1, int(dur_s / window_s * width)) if dur_s else 1
        bar = min(bar, width - min(left, width - 1))
        attrs = " ".join(
            f"{k}={s[k]}"
            for k in (
                "process", "host", "replica", "width", "rung",
                "status", "resumed", "cause", "gate_ms",
            )
            if s.get(k) is not None
        )
        rows.append([
            f"{off_s * 1e3:8.2f}",
            "." * min(left, width - 1) + "#" * bar,
            s.get("name"),
            "-" if s.get("dur_ms") is None
            else f"{_span_dur(s):.2f}",
            attrs,
        ])
    head = spans[0].get("trace")
    return f"trace {head}\n" + format_table(
        rows, ["offset_ms", "timeline", "span", "dur_ms", "attrs"]
    )


def _summarize_fleet(records: list) -> Optional[dict]:
    """Aggregate ``fleet`` lifecycle records (fleet/scheduler.py) into a
    per-member table: last state, launch attempts, requeues — plus the
    fleet-wide state totals. None for non-fleet logs."""
    fleet = [r for r in records if r.get("kind") == "fleet"]
    if not fleet:
        return None
    members: dict = {}
    counts: Counter = Counter()
    for r in fleet:
        mid, state = r.get("member"), r.get("state")
        if not isinstance(mid, str):
            continue
        if not isinstance(state, str):
            # reader contract: tolerate what the validator rejects — a
            # stateless record must not make sorted() compare None<str
            state = "unknown"
        counts[state] += 1
        row = members.setdefault(
            mid, {"last_state": None, "attempts": 0, "requeues": 0,
                  "transitions": 0}
        )
        row["last_state"] = state
        row["transitions"] += 1
        a = r.get("attempt")
        if isinstance(a, int) and not isinstance(a, bool):
            row["attempts"] = max(row["attempts"], a)
        if state == "requeued":
            row["requeues"] += 1
    return {"members": members, "counts": dict(sorted(counts.items()))}


def _summarize_alerts(records: list) -> Optional[dict]:
    """Aggregate ``alert`` lifecycle records (obs/alerts.py) into a
    per-rule table: fired / resolved / still-active counts plus the
    fastest time-to-detect against the log's injected faults. None for
    logs without an alerting plane.

    ``false_positives`` is the STRICT counter ``compare_runs`` gates
    on: in a log that injects faults, a firing alert is counted false
    when NO fault at all was injected in the 120 s before it fired —
    an alert going off in a provably quiet phase. The counter is
    deliberately COARSER than the validator's per-rule cause analysis
    (``scripts/validate_events.py`` cross-checks metric evidence and
    control-plane reactions): a fault's collateral damage legitimately
    fires rules outside its own ``FAULT_ALERT_RULES`` contract (a
    checkpoint-chaos phase stalling serving long enough to breach the
    latency SLO), and only the validator can tell that from noise.
    This row is the cross-run trend of the indefensible case."""
    alerts = [r for r in records if r.get("kind") == "alert"]
    if not alerts:
        return None
    from trpo_tpu.obs.alerts import FAULT_ALERT_RULES

    rules: dict = {}
    open_keys: set = set()
    for r in alerts:
        rule = r.get("rule")
        if not isinstance(rule, str):
            continue
        row = rules.setdefault(
            rule,
            {"fired": 0, "resolved": 0, "active": 0, "detect_s": None},
        )
        key = (rule, r.get("target"))
        if r.get("state") == "firing":
            row["fired"] += 1
            open_keys.add(key)
        elif r.get("state") == "resolved":
            row["resolved"] += 1
            open_keys.discard(key)
    for rule, _target in open_keys:
        rules[rule]["active"] += 1

    # time-to-detect: for each armed fault, the first firing of a rule
    # its contract expects; credited both fleet-wide and per rule
    faults = [
        r for r in records
        if r.get("kind") == "fault_injected"
        and isinstance(r.get("fault"), str)
        and _finite(r.get("t")) is not None
    ]
    detects = []
    for f in faults:
        expected = FAULT_ALERT_RULES.get(f["fault"])
        if not expected:
            continue
        best = None
        for a in alerts:
            if (
                a.get("state") == "firing"
                and a.get("rule") in expected
                and _finite(a.get("t")) is not None
                and a["t"] >= f["t"]
            ):
                d = a["t"] - f["t"]
                if best is None or d < best[0]:
                    best = (d, a["rule"])
        if best is not None:
            detects.append(best[0])
            row = rules.get(best[1])
            if row is not None and (
                row["detect_s"] is None or best[0] < row["detect_s"]
            ):
                row["detect_s"] = best[0]

    false_positives = 0
    if faults:
        for a in alerts:
            if a.get("state") != "firing":
                continue
            t = _finite(a.get("t"))
            if t is None:
                continue
            caused = any(
                t - 120.0 <= f["t"] <= t for f in faults
            )
            if not caused:
                false_positives += 1

    return {
        "rules": {k: rules[k] for k in sorted(rules)},
        "fired_total": sum(r["fired"] for r in rules.values()),
        "resolved_total": sum(r["resolved"] for r in rules.values()),
        "active_total": sum(r["active"] for r in rules.values()),
        "false_positives": false_positives,
        "time_to_detect_mean_s": _mean(detects),
        "time_to_detect_max_s": max(detects) if detects else None,
    }


def summarize_run(records: list) -> dict:
    """One run's report, computed from its event records alone."""
    manifest = next(
        (r for r in records if r.get("kind") == "run_manifest"), None
    )
    iters = [r for r in records if r.get("kind") == "iteration"]
    iters.sort(key=lambda r: r.get("iteration", 0))

    # -- iteration metrics -------------------------------------------------
    last_stats = dict(iters[-1].get("stats") or {}) if iters else {}
    iter_ms = [
        (r.get("stats") or {}).get("iteration_ms") for r in iters
    ]
    # every run SEGMENT's first iteration row carries XLA compilation: a
    # resumed/requeued run appends a new manifest + a compile-laden first
    # row mid-file, so the drop is per segment, not just row 1 (records
    # walk in FILE order here — `iters` above is sorted by iteration)
    compile_rows = set()
    awaiting_first = False
    for r in records:
        if r.get("kind") == "run_manifest":
            awaiting_first = True
        elif r.get("kind") == "iteration" and awaiting_first:
            compile_rows.add(id(r))
            awaiting_first = False
    steady_vals = [
        (r.get("stats") or {}).get("iteration_ms")
        for r in iters
        if id(r) not in compile_rows
    ]
    if compile_rows and steady_vals and len(iter_ms) > 2:
        steady_ms = _mean(steady_vals)
    else:  # manifest-less/tiny logs: the pre-fleet single-drop rule
        steady_ms = _mean(iter_ms[1:] if len(iter_ms) > 2 else iter_ms)
    throughput = None
    if len(iters) >= 2:
        ts0 = (iters[0].get("stats") or {}).get("timesteps_total")
        ts1 = (iters[-1].get("stats") or {}).get("timesteps_total")
        t0, t1 = iters[0].get("t"), iters[-1].get("t")
        if None not in (ts0, ts1, t0, t1) and t1 > t0:
            throughput = (ts1 - ts0) / (t1 - t0)
    # env-steps/s as a first-class rate metric (ISSUE 10): per-iteration
    # batch size (the median of consecutive timesteps_total deltas —
    # robust to a resume gap or a dropped row) over the STEADY iteration
    # time, so the regression gate judges rollout throughput directly
    # instead of only iter ms. Differs from timesteps_per_sec above,
    # which divides by wall-clock time between rows (logging, drain and
    # checkpoint stalls included).
    env_steps_per_sec = None
    batch_per_iter = None
    ts_vals = [
        _finite((r.get("stats") or {}).get("timesteps_total"))
        for r in iters
    ]
    deltas = sorted(
        b - a
        for a, b in zip(ts_vals, ts_vals[1:])
        if a is not None and b is not None and b > a
    )
    if deltas:
        batch_per_iter = deltas[len(deltas) // 2]
    if batch_per_iter and steady_ms:
        env_steps_per_sec = batch_per_iter / (steady_ms / 1e3)
    rewards = [
        (r.get("stats") or {}).get("reward_running") for r in iters
    ]
    rewards = [v for v in (_finite(v) for v in rewards) if v is not None]

    # -- phase table (mean ms weighted by calls when present) --------------
    phases: dict = {}
    for r in records:
        if r.get("kind") != "phase":
            continue
        name, ms = r.get("name"), _finite(r.get("ms"))
        if name is None or ms is None:
            continue
        calls = r.get("calls")
        calls = calls if isinstance(calls, int) and calls > 0 else 1
        row = phases.setdefault(
            name, {"ms_sum": 0.0, "calls": 0, "events": 0}
        )
        row["ms_sum"] += ms * calls
        row["calls"] += calls
        row["events"] += 1
    phase_table = {
        name: {
            "mean_ms": row["ms_sum"] / row["calls"],
            "calls": row["calls"],
        }
        for name, row in phases.items()
    }

    # -- health / recompile / faults --------------------------------------
    health = Counter(
        f"{r.get('check')}:{r.get('level')}"
        for r in records
        if r.get("kind") == "health"
    )
    recompiles = [r for r in records if r.get("kind") == "recompile"]
    faults = sum(1 for r in records if r.get("kind") == "fault_injected")
    recoveries = sum(1 for r in records if r.get("kind") == "recovery")

    # -- memory ------------------------------------------------------------
    programs: dict = {}
    live_peak = None
    for r in records:
        if r.get("kind") != "memory":
            continue
        if r.get("scope") == "program":
            programs[r.get("program")] = {
                k: v for k, v in r.items() if k.endswith("_bytes")
            }
        elif r.get("scope") == "live":
            b = _finite(r.get("live_buffer_bytes"))
            if b is not None:
                live_peak = b if live_peak is None else max(live_peak, b)

    serving = _summarize_serving(records)

    # -- solver precision ladder (ISSUE 8) ---------------------------------
    # the counters are run-cumulative (they ride TrainState.ladder), so
    # the LAST row that carries them is the run total; cosine stats come
    # from the per-iteration audit values
    solver_precision = None
    ladder_rows = [
        r for r in iters if "fallbacks" in (r.get("stats") or {})
    ]
    if ladder_rows:
        last_l = ladder_rows[-1].get("stats") or {}
        cosines = [
            v
            for v in (
                _finite((r.get("stats") or {}).get("solve_cosine"))
                for r in ladder_rows
            )
            if v is not None
        ]
        solver_precision = {
            "audit_runs": last_l.get("audit_runs"),
            "fallbacks": last_l.get("fallbacks"),
            "solve_cosine_min": _finite(last_l.get("solve_cosine_min")),
            "solve_cosine_mean": _mean(cosines),
            "cg_budget_final": last_l.get("cg_budget"),
            "pinned": bool(last_l.get("solve_pinned")),
        }

    return {
        "manifest": {
            k: manifest.get(k)
            for k in (
                "config_hash", "backend", "jax_version", "device_count",
                "git_sha", "driver", "n_iterations",
            )
        }
        if manifest
        else None,
        "iterations": len(iters),
        "last_iteration": iters[-1].get("iteration") if iters else None,
        "last_stats": last_stats,
        "final_reward_running": rewards[-1] if rewards else None,
        "steady_iteration_ms": steady_ms,
        "timesteps_per_sec": throughput,
        "env_steps_per_sec": env_steps_per_sec,
        "batch_per_iteration": batch_per_iter,
        "phases": phase_table,
        "health": dict(sorted(health.items())),
        "recompiles": {
            "total": len(recompiles),
            "unexpected": sum(
                1 for r in recompiles if r.get("unexpected")
            ),
        },
        "faults_injected": faults,
        "recoveries": recoveries,
        "memory": {
            "programs": programs,
            "peak_live_buffer_bytes": live_peak,
        },
        "serving": serving,
        "router": _summarize_router(records),
        "traces": _summarize_traces(records),
        "solver_precision": solver_precision,
        "fleet": _summarize_fleet(records),
        "alerts": _summarize_alerts(records),
        "events_total": dict(
            Counter(r.get("kind") for r in records)
        ),
    }


# ---------------------------------------------------------------------------
# comparison
# ---------------------------------------------------------------------------

# direction: "time" (higher is worse), "rate" (lower is worse),
# "bytes" (higher is worse)
_METRIC_DIRECTIONS = {
    "steady_iteration_ms": "time",
    "timesteps_per_sec": "rate",
    # rollout throughput judged directly (ISSUE 10): batch/iteration over
    # steady iteration time — shrink = regress, like any rate
    "env_steps_per_sec": "rate",
    # reward parity (ISSUE 8's mixed-precision gate: a ladder run must
    # land within the threshold of its f32 twin; identical-config gate
    # legs are seed-deterministic, so the row is exact there)
    "final_reward_running": "rate",
}


def _rung_key(rung: str):
    """Numeric sort for padded-rung keys ('8' before '64'), tolerating a
    non-numeric key from a foreign log."""
    try:
        return (0, int(rung))
    except ValueError:
        return (1, rung)


def _verdict(metric, base, new, threshold_pct, direction) -> dict:
    row = {
        "metric": metric,
        "base": base,
        "new": new,
        "direction": direction,
    }
    if base is None or new is None:
        row["verdict"] = "skipped"
        row["delta_pct"] = None
        return row
    if base <= 0:
        # a zero/negative baseline has no meaningful ratio. Growth from
        # zero (e.g. a fully-fused program's temp_bytes going 0 → 2 GiB)
        # must NOT auto-pass as "ok" — report it as skipped so a human
        # sees the row; only a still-zero value is genuinely fine
        row["delta_pct"] = None
        row["verdict"] = "ok" if new <= max(base, 0) else "skipped"
        return row
    delta_pct = (new - base) / base * 100.0
    row["delta_pct"] = delta_pct
    factor = 1.0 + threshold_pct / 100.0
    if direction == "rate":
        regressed = new < base / factor
        improved = new > base * factor
    else:
        regressed = new > base * factor
        improved = new < base / factor
    row["verdict"] = (
        "regressed" if regressed else "improved" if improved else "ok"
    )
    return row


def compare_runs(
    base: dict,
    new: dict,
    threshold_pct: float = 20.0,
    min_ms: float = 1.0,
) -> dict:
    """Regression verdicts between two :func:`summarize_run` outputs.

    Returns ``{"verdicts": [...], "regressed": bool, "threshold_pct",
    "min_ms"}`` — ``regressed`` is True when ANY verdict row regressed
    (the CLI turns it into a nonzero exit)."""
    verdicts = []

    # per-phase mean ms — only phases both runs measured, above the floor
    base_ph = base.get("phases") or {}
    new_ph = new.get("phases") or {}
    for name in sorted(set(base_ph) | set(new_ph)):
        b = (base_ph.get(name) or {}).get("mean_ms")
        n = (new_ph.get(name) or {}).get("mean_ms")
        if b is not None and n is not None and max(b, n) < min_ms:
            continue  # sub-floor phases are scheduler noise
        verdicts.append(
            _verdict(f"phase/{name}", b, n, threshold_pct, "time")
        )

    # scalar run metrics
    for metric, direction in _METRIC_DIRECTIONS.items():
        b, n = base.get(metric), new.get(metric)
        if metric == "final_reward_running" and b is not None and b <= 0:
            # rewards are signed: _verdict's base<=0 branch was written
            # for time/bytes growth-from-zero and would call a collapse
            # from -50 to -400 "ok" (and -50 → +100 "skipped"). A
            # percent threshold is meaningless against a ≤0 baseline —
            # surface the pair for a human instead of auto-judging.
            verdicts.append({
                "metric": metric, "base": b, "new": n,
                "direction": direction, "delta_pct": None,
                "verdict": "skipped",
            })
            continue
        verdicts.append(
            _verdict(metric, b, n, threshold_pct, direction)
        )

    # memory: live peak + per-program compiled footprints
    b_mem = (base.get("memory") or {})
    n_mem = (new.get("memory") or {})
    verdicts.append(
        _verdict(
            "memory/peak_live_buffer_bytes",
            b_mem.get("peak_live_buffer_bytes"),
            n_mem.get("peak_live_buffer_bytes"),
            threshold_pct, "bytes",
        )
    )
    # serving SLOs — only when at least one run served (training-only
    # comparisons must not grow a block of always-skipped rows). Latency
    # is time-like (grow = regress), actions/s is rate-like (shrink =
    # regress) — the ISSUE 6 acceptance contract; per-rung p50 rows use
    # the same union-not-intersection policy as the program-memory rows.
    b_srv = base.get("serving") or {}
    n_srv = new.get("serving") or {}
    if b_srv or n_srv:
        for metric, direction in (
            ("latency_p50_ms", "time"),
            ("latency_p99_ms", "time"),
            ("actions_per_sec", "rate"),
        ):
            verdicts.append(
                _verdict(
                    f"serve/{metric}", b_srv.get(metric),
                    n_srv.get(metric), threshold_pct, direction,
                )
            )
        b_shapes = b_srv.get("shapes") or {}
        n_shapes = n_srv.get("shapes") or {}
        for rung in sorted(set(b_shapes) | set(n_shapes), key=_rung_key):
            verdicts.append(
                _verdict(
                    f"serve/shape{rung}/p50_ms",
                    (b_shapes.get(rung) or {}).get("p50_ms"),
                    (n_shapes.get(rung) or {}).get("p50_ms"),
                    threshold_pct, "time",
                )
            )

    # replicated-serving SLOs (ISSUE 9) — router p50/p99 are time-like,
    # routed actions/s rate-like; rows only when at least one run
    # actually routed (same gating policy as the serve block)
    b_rt = base.get("router") or {}
    n_rt = new.get("router") or {}
    if b_rt or n_rt:
        for metric, direction in (
            ("latency_p50_ms", "time"),
            ("latency_p99_ms", "time"),
            ("actions_per_sec", "rate"),
        ):
            verdicts.append(
                _verdict(
                    f"router/{metric}", b_rt.get(metric),
                    n_rt.get(metric), threshold_pct, direction,
                )
            )
        # failover quality (ISSUE 11): the resumed fraction is
        # rate-like — a serving change that turns lossless failovers
        # back into fresh restarts is a regression; rows only when a
        # run actually failed over (skipped otherwise, per _verdict)
        b_fo = b_rt.get("failover") or {}
        n_fo = n_rt.get("failover") or {}
        if b_fo or n_fo:
            verdicts.append(
                _verdict(
                    "router/failover_resumed_fraction",
                    b_fo.get("resumed_fraction"),
                    n_fo.get("resumed_fraction"),
                    threshold_pct, "rate",
                )
            )
            verdicts.append(
                _verdict(
                    "router/journal_lag_max",
                    b_fo.get("journal_lag_max"),
                    n_fo.get("journal_lag_max"),
                    threshold_pct, "time",
                )
            )
        # canary verdicts: rolled_back is a strict counter (the
        # solve/fallbacks pattern) — ANY rise between two supposedly
        # clean runs means a checkpoint failed its gate, which no
        # noise threshold excuses
        b_cn = b_rt.get("canary") or {}
        n_cn = n_rt.get("canary") or {}
        if b_cn or n_cn:
            b_rb = b_cn.get("rolled_back") or 0
            n_rb = n_cn.get("rolled_back") or 0
            verdicts.append({
                "metric": "router/canary_rolled_back",
                "base": b_rb,
                "new": n_rb,
                "direction": "count",
                "delta_pct": None,
                "verdict": "regressed" if n_rb > b_rb else "ok",
            })
            verdicts.append(
                _verdict(
                    "router/canary_promoted",
                    b_cn.get("promoted"), n_cn.get("promoted"),
                    threshold_pct, "rate",
                )
            )
        # promotion verdicts (ISSUE 19): an unresolved/timed-out
        # promotion (rolled_back) is a strict counter — the canary
        # rolled_back pattern; promoted throughput and the served
        # realized return are rate-like (lower is worse)
        b_pm = b_rt.get("promote") or {}
        n_pm = n_rt.get("promote") or {}
        if b_pm or n_pm:
            b_rb = b_pm.get("rolled_back") or 0
            n_rb = n_pm.get("rolled_back") or 0
            verdicts.append({
                "metric": "router/promote_rolled_back",
                "base": b_rb,
                "new": n_rb,
                "direction": "count",
                "delta_pct": None,
                "verdict": "regressed" if n_rb > b_rb else "ok",
            })
            verdicts.append(
                _verdict(
                    "router/promote_promoted",
                    b_pm.get("promoted"), n_pm.get("promoted"),
                    threshold_pct, "rate",
                )
            )
        b_ep = b_rt.get("episodes") or {}
        n_ep = n_rt.get("episodes") or {}
        if b_ep or n_ep:
            verdicts.append(
                _verdict(
                    "router/served_episodes",
                    b_ep.get("episodes"), n_ep.get("episodes"),
                    threshold_pct, "rate",
                )
            )
        # elastic-serving verdicts (ISSUE 12): an aborted drain is a
        # strict counter (the canary_rolled_back pattern — a drain
        # that could not move its sessions losslessly is never noise);
        # drain duration is time-like, sheds grow-is-worse under the
        # same threshold (comparable runs drive comparable storms)
        b_as = b_rt.get("autoscale") or {}
        n_as = n_rt.get("autoscale") or {}
        if b_as or n_as:
            b_da = b_as.get("drain_aborted") or 0
            n_da = n_as.get("drain_aborted") or 0
            verdicts.append({
                "metric": "router/autoscale_drain_aborted",
                "base": b_da,
                "new": n_da,
                "direction": "count",
                "delta_pct": None,
                "verdict": "regressed" if n_da > b_da else "ok",
            })
            verdicts.append(
                _verdict(
                    "router/autoscale_drain_duration_max_s",
                    b_as.get("drain_duration_max_s"),
                    n_as.get("drain_duration_max_s"),
                    threshold_pct, "time",
                )
            )
            # sheds are a COUNT (grow-is-worse under the threshold —
            # comparable runs drive comparable storms): judged with the
            # time-direction rule, labeled honestly as a count so no
            # consumer renders shed totals in milliseconds
            shed_row = _verdict(
                "router/autoscale_shed_total",
                b_as.get("shed_total"),
                n_as.get("shed_total"),
                threshold_pct, "time",
            )
            shed_row["direction"] = "count"
            verdicts.append(shed_row)
        # multi-host liveness verdicts (ISSUE 14): lease expiries and
        # fenced (split-brain) journal writes are STRICT counters —
        # the drain_aborted pattern: between two supposedly-clean runs
        # a lease expiring, or a zombie writer needing to be refused,
        # is a liveness event no noise threshold excuses
        b_ls = b_rt.get("lease") or {}
        n_ls = n_rt.get("lease") or {}
        if b_ls or n_ls:
            for key in ("expired", "fenced_write_refused"):
                b_v = b_ls.get(key) or 0
                n_v = n_ls.get(key) or 0
                verdicts.append({
                    "metric": f"router/lease_{key}"
                    if key == "expired" else f"router/{key}",
                    "base": b_v,
                    "new": n_v,
                    "direction": "count",
                    "delta_pct": None,
                    "verdict": "regressed" if n_v > b_v else "ok",
                })

    # request-trace critical path (ISSUE 15) — per-stage durations are
    # time-like (a stage's p99 growing past the threshold is a located
    # regression, which is the whole point of attribution); rows only
    # when at least one run traced, and only for stages either run
    # actually spent time in (the union-not-intersection policy)
    b_tr = base.get("traces") or {}
    n_tr = new.get("traces") or {}
    if b_tr or n_tr:
        verdicts.append(
            _verdict(
                "trace/root_p99_ms",
                b_tr.get("root_p99_ms"), n_tr.get("root_p99_ms"),
                threshold_pct, "time",
            )
        )
        b_st = b_tr.get("stages") or {}
        n_st = n_tr.get("stages") or {}
        for stage in sorted(set(b_st) | set(n_st)):
            verdicts.append(
                _verdict(
                    f"trace/stage_{stage}_p99_ms",
                    (b_st.get(stage) or {}).get("p99_ms"),
                    (n_st.get(stage) or {}).get("p99_ms"),
                    threshold_pct, "time",
                )
            )
        # per-wire-format hop rows (ISSUE 16): a codec/transport group
        # whose network p99 grew is a located data-plane regression —
        # same union-not-intersection policy as the stage rows
        b_w = b_tr.get("wire") or {}
        n_w = n_tr.get("wire") or {}
        for key in sorted(set(b_w) | set(n_w)):
            verdicts.append(
                _verdict(
                    f"trace/wire_{key}_network_p99_ms",
                    (b_w.get(key) or {}).get("network_p99_ms"),
                    (n_w.get(key) or {}).get("network_p99_ms"),
                    threshold_pct, "time",
                )
            )

    # alerting-plane verdicts (ISSUE 20) — only when at least one run
    # carried alert records. `false_positives` is a STRICT counter (the
    # drain_aborted pattern): between two supposedly-clean runs an
    # alert firing with no fault whose contract expects it is a broken
    # alert contract, which no noise threshold excuses. Time-to-detect
    # is time-like — a PR that makes the plane slower to notice a
    # proven incident is a located observability regression. Per-rule
    # fired counts are grow-is-worse counts under the threshold
    # (comparable runs inject comparable faults — the shed_total
    # pattern).
    b_al = base.get("alerts") or {}
    n_al = new.get("alerts") or {}
    if b_al or n_al:
        b_fp = b_al.get("false_positives") or 0
        n_fp = n_al.get("false_positives") or 0
        verdicts.append({
            "metric": "alerts/false_positives",
            "base": b_fp,
            "new": n_fp,
            "direction": "count",
            "delta_pct": None,
            "verdict": "regressed" if n_fp > b_fp else "ok",
        })
        verdicts.append(
            _verdict(
                "alerts/time_to_detect_mean_s",
                b_al.get("time_to_detect_mean_s"),
                n_al.get("time_to_detect_mean_s"),
                threshold_pct, "time",
            )
        )
        b_rules = b_al.get("rules") or {}
        n_rules = n_al.get("rules") or {}
        for rule in sorted(set(b_rules) | set(n_rules)):
            row = _verdict(
                f"alerts/{rule}_fired",
                (b_rules.get(rule) or {}).get("fired"),
                (n_rules.get(rule) or {}).get("fired"),
                threshold_pct, "time",
            )
            row["direction"] = "count"
            verdicts.append(row)

    # solver-precision counters (ISSUE 8) — only when at least one run
    # carried the ladder. `fallbacks` is judged as a strict counter: ANY
    # rise is a failed audit, which no noise threshold excuses; cosine
    # floors are config-enforced on-device, so cosine_min is reported
    # (delta row) rather than thresholded here.
    b_sp = base.get("solver_precision") or {}
    n_sp = new.get("solver_precision") or {}
    if b_sp or n_sp:
        b_fb = b_sp.get("fallbacks") or 0
        n_fb = n_sp.get("fallbacks") or 0
        verdicts.append({
            "metric": "solve/fallbacks",
            "base": b_fb,
            "new": n_fb,
            "direction": "count",
            "delta_pct": None,
            "verdict": "regressed" if n_fb > b_fb else "ok",
        })
        verdicts.append(
            _verdict(
                "solve/cosine_min",
                b_sp.get("solve_cosine_min"),
                n_sp.get("solve_cosine_min"),
                threshold_pct, "rate",
            )
        )
        verdicts.append(
            _verdict(
                "solve/cg_budget_final",
                b_sp.get("cg_budget_final"),
                n_sp.get("cg_budget_final"),
                threshold_pct, "time",
            )
        )

    b_prog = b_mem.get("programs") or {}
    n_prog = n_mem.get("programs") or {}
    # union, not intersection: a program only one run measured (added,
    # renamed, or dropped by a PR) must surface as a `skipped` row — an
    # HBM-critical new program escaping the report entirely would
    # violate the no-silent-verdict contract above
    for pname in sorted(set(b_prog) | set(n_prog)):
        for field in ("temp_bytes", "peak_estimate_bytes"):
            verdicts.append(
                _verdict(
                    f"memory/{pname}/{field}",
                    (b_prog.get(pname) or {}).get(field),
                    (n_prog.get(pname) or {}).get(field),
                    threshold_pct, "bytes",
                )
            )

    return {
        "verdicts": verdicts,
        "regressed": any(v["verdict"] == "regressed" for v in verdicts),
        "threshold_pct": threshold_pct,
        "min_ms": min_ms,
    }


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------


def _fmt_bytes(v) -> str:
    if v is None:
        return "-"
    v = float(v)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(v) < 1024 or unit == "TiB":
            return f"{v:.1f}{unit}" if unit != "B" else f"{int(v)}B"
        v /= 1024
    return f"{v:.1f}TiB"


def _fmt(v, nd=2) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


def format_table(rows: list, headers: list) -> str:
    """Plain-text column alignment (no deps — this renders over ssh on
    the TPU host)."""
    rows = [[str(c) for c in row] for row in rows]
    widths = [
        max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
        for i, h in enumerate(headers)
    ]
    def line(cells):
        return "  ".join(c.ljust(w) for c, w in zip(cells, widths)).rstrip()
    sep = "  ".join("-" * w for w in widths)
    return "\n".join([line(headers), sep] + [line(r) for r in rows])


def render_summary(summary: dict) -> str:
    """The single-run report as text: identity, throughput, phase table,
    health/recompile/memory sections."""
    out = []
    man = summary.get("manifest") or {}
    out.append(
        "run: "
        + " ".join(
            f"{k}={man.get(k)}"
            for k in ("config_hash", "backend", "driver", "git_sha")
            if man.get(k) is not None
        )
    )
    out.append(
        f"iterations: {summary['iterations']}"
        f" (last={summary['last_iteration']})"
        f"  steady_iteration_ms={_fmt(summary['steady_iteration_ms'])}"
        f"  timesteps/s={_fmt(summary['timesteps_per_sec'], 1)}"
        f"  env-steps/s={_fmt(summary.get('env_steps_per_sec'), 1)}"
        f"  final_reward_running={_fmt(summary['final_reward_running'])}"
    )
    phases = summary.get("phases") or {}
    if phases:
        out.append("")
        out.append(format_table(
            [
                [name, _fmt(row["mean_ms"]), row["calls"]]
                for name, row in sorted(phases.items())
            ],
            ["phase", "mean_ms", "calls"],
        ))
    health = summary.get("health") or {}
    rc = summary.get("recompiles") or {}
    out.append("")
    out.append(
        "health: "
        + (
            ", ".join(f"{k}×{v}" for k, v in health.items())
            if health
            else "clean"
        )
        + f"  recompiles: {rc.get('total', 0)} "
        f"({rc.get('unexpected', 0)} unexpected)"
        + f"  faults: {summary.get('faults_injected', 0)}"
        f"  recoveries: {summary.get('recoveries', 0)}"
    )
    srv = summary.get("serving") or {}
    if srv:
        out.append("")
        out.append(
            f"serving: requests={srv.get('requests_total')}"
            f" batches={srv.get('batches_total')}"
            f" actions/s={_fmt(srv.get('actions_per_sec'), 1)}"
            f" p50={_fmt(srv.get('latency_p50_ms'))}ms"
            f" p99={_fmt(srv.get('latency_p99_ms'))}ms"
            f" queue_max={srv.get('queue_depth_max')}"
        )
        shapes = srv.get("shapes") or {}
        if shapes:
            out.append(format_table(
                [
                    [
                        rung,
                        row.get("batches"),
                        row.get("requests"),
                        _fmt(row.get("p50_ms")),
                        _fmt(row.get("p99_ms")),
                    ]
                    for rung, row in sorted(
                        shapes.items(), key=lambda kv: _rung_key(kv[0])
                    )
                ],
                ["padded", "batches", "requests", "p50_ms", "p99_ms"],
            ))
    rt = summary.get("router") or {}
    if rt:
        out.append("")
        out.append(
            f"router: routed={rt.get('routed_total')}"
            f" retried={rt.get('retried_total')}"
            f" failed={rt.get('failed_total')}"
            f" actions/s={_fmt(rt.get('actions_per_sec'), 1)}"
            f" p50={_fmt(rt.get('latency_p50_ms'))}ms"
            f" p99={_fmt(rt.get('latency_p99_ms'))}ms"
        )
        replicas = rt.get("replicas") or {}
        if replicas:
            out.append(format_table(
                [
                    [
                        rid,
                        row.get("last_state"),
                        row.get("requests"),
                        _fmt(row.get("p50_ms")),
                        row.get("deaths"),
                        row.get("restarts"),
                    ]
                    for rid, row in sorted(replicas.items())
                ],
                ["replica", "state", "requests", "p50_ms", "deaths",
                 "restarts"],
            ))
        sc = rt.get("scaling") or {}
        if sc.get("replicas"):
            out.append(
                f"scaling: replicas={sc.get('replicas')}"
                "  actions/s/replica="
                + _fmt(sc.get("actions_per_sec_per_replica"), 1)
                + f"  balance={_fmt(sc.get('balance'))}"
            )
        sess = rt.get("sessions") or {}
        if sess:
            out.append(
                "sessions: "
                + ", ".join(f"{k}×{v}" for k, v in sess.items())
            )
        fo = rt.get("failover") or {}
        if fo:
            out.append(
                f"failover: resumed={fo.get('resumed')}"
                f" restarted_fresh={fo.get('restarted_fresh')}"
                f" resumed_fraction={_fmt(fo.get('resumed_fraction'))}"
                f" journal_lag_mean={_fmt(fo.get('journal_lag_mean'))}"
                f" journal_lag_max={fo.get('journal_lag_max')}"
            )
        asr = rt.get("autoscale") or {}
        if asr:
            reasons = asr.get("shed_reasons") or {}
            out.append(
                f"autoscale: scale_out={asr.get('scale_out')}"
                f" drain_completed={asr.get('drain_completed')}"
                f" drain_aborted={asr.get('drain_aborted')}"
                f" sessions_moved={asr.get('sessions_moved')}"
                f" sheds={asr.get('shed_total')}"
                + (
                    " ("
                    + ", ".join(f"{k}×{v}" for k, v in reasons.items())
                    + ")"
                    if reasons else ""
                )
                + f" drain_max={_fmt(asr.get('drain_duration_max_s'))}s"
            )
        hosts = rt.get("hosts") or {}
        if hosts:
            out.append(format_table(
                [
                    [host, ",".join(row.get("replicas") or []) or "-",
                     row.get("deaths"), row.get("lease_expired"),
                     row.get("last_state") or "-"]
                    for host, row in sorted(hosts.items())
                ],
                ["host", "replicas", "deaths", "lease_expired", "state"],
            ))
        ls = rt.get("lease") or {}
        if ls:
            out.append(
                f"lease: granted={ls.get('granted')}"
                f" renewed={ls.get('renewed')}"
                f" expired={ls.get('expired')}"
                f" fenced_writes={ls.get('fenced_write_refused')}"
                f" (sessions={ls.get('fenced_sessions')})"
                + (
                    f"  partitions={ls.get('partitions_injected')}"
                    f" (max {_fmt(ls.get('partition_seconds_max'))}s)"
                    if ls.get("partitions_injected") else ""
                )
            )
        cn = rt.get("canary") or {}
        if cn:
            out.append(
                f"canary: started={cn.get('started')}"
                f" promoted={cn.get('promoted')}"
                f" rolled_back={cn.get('rolled_back')}"
            )
            steps = cn.get("steps") or {}
            if steps:
                out.append(format_table(
                    [
                        [step, row.get("replica"), row.get("outcome"),
                         row.get("reason") or ""]
                        for step, row in sorted(
                            steps.items(), key=lambda kv: _rung_key(kv[0])
                        )
                    ],
                    ["step", "canary", "outcome", "reason"],
                ))
        ep = rt.get("episodes") or {}
        if ep:
            out.append(
                f"episodes: served={ep.get('episodes')}"
                f" mean_return={_fmt(ep.get('mean_return'))}"
                f" steps={ep.get('steps_total')}"
            )
        pm = rt.get("promote") or {}
        if pm:
            out.append(
                f"promote: candidates={pm.get('candidates')}"
                f" promoted={pm.get('promoted')}"
                f" rejected={pm.get('rejected')}"
                f" rolled_back={pm.get('rolled_back')}"
                + (
                    f"  feedback={pm.get('feedback_episodes')}eps"
                    f" mean={_fmt(pm.get('feedback_mean_return'))}"
                    if pm.get("feedback_episodes") else ""
                )
            )
            steps = pm.get("steps") or {}
            if steps:
                out.append(format_table(
                    [
                        [step, row.get("member"), row.get("outcome"),
                         row.get("reason") or ""]
                        for step, row in sorted(
                            steps.items(), key=lambda kv: _rung_key(kv[0])
                        )
                    ],
                    ["step", "member", "outcome", "reason"],
                ))
    tr = summary.get("traces") or {}
    if tr:
        out.append("")
        out.append(
            f"traces: {tr.get('count')} assembled={tr.get('assembled')}"
            f" spans={tr.get('spans')}"
            f" root_p50={_fmt(tr.get('root_p50_ms'))}ms"
            f" root_p99={_fmt(tr.get('root_p99_ms'))}ms"
        )
        stages = tr.get("stages") or {}
        if stages:
            out.append(format_table(
                [
                    [
                        stage,
                        row.get("traces"),
                        _fmt(row.get("p50_ms")),
                        _fmt(row.get("p99_ms")),
                        "-" if row.get("share") is None
                        else f"{row['share'] * 100:.1f}%",
                    ]
                    for stage, row in stages.items()
                ],
                ["stage", "traces", "p50_ms", "p99_ms", "share"],
            ))
        wire = tr.get("wire") or {}
        if wire:
            out.append(format_table(
                [
                    [
                        key,
                        row.get("hops"),
                        _fmt(row.get("hop_p50_ms")),
                        _fmt(row.get("hop_p99_ms")),
                        _fmt(row.get("network_p50_ms")),
                        _fmt(row.get("network_p99_ms")),
                    ]
                    for key, row in wire.items()
                ],
                [
                    "wire", "hops", "hop_p50", "hop_p99",
                    "net_p50", "net_p99",
                ],
            ))
        slowest = tr.get("slowest") or []
        if slowest:
            out.append(format_table(
                [
                    [
                        row.get("trace"),
                        row.get("root"),
                        _fmt(row.get("root_ms")),
                        ", ".join(
                            f"{k}={v:.1f}"
                            for k, v in (row.get("stages") or {}).items()
                        ),
                    ]
                    for row in slowest
                ],
                ["slowest trace", "root", "ms", "stage breakdown (ms)"],
            ))
    sp = summary.get("solver_precision") or {}
    if sp:
        out.append("")
        out.append(
            f"solver precision: audits={sp.get('audit_runs')}"
            f" fallbacks={sp.get('fallbacks')}"
            f" cosine_min={_fmt(sp.get('solve_cosine_min'), 5)}"
            f" cosine_mean={_fmt(sp.get('solve_cosine_mean'), 5)}"
            f" cg_budget={sp.get('cg_budget_final')}"
            + ("  PINNED-AT-F32" if sp.get("pinned") else "")
        )
    fleet = summary.get("fleet") or {}
    if fleet:
        out.append("")
        out.append(
            "fleet: "
            + ", ".join(
                f"{k}×{v}" for k, v in (fleet.get("counts") or {}).items()
            )
        )
        out.append(format_table(
            [
                [mid, row.get("last_state"), row.get("attempts"),
                 row.get("requeues")]
                for mid, row in sorted((fleet.get("members") or {}).items())
            ],
            ["member", "state", "attempts", "requeues"],
        ))
    al = summary.get("alerts") or {}
    if al:
        out.append("")
        out.append(
            f"alerts: fired={al.get('fired_total')}"
            f" resolved={al.get('resolved_total')}"
            f" active={al.get('active_total')}"
            f" false_positives={al.get('false_positives')}"
            f" detect_mean={_fmt(al.get('time_to_detect_mean_s'))}s"
        )
        out.append(format_table(
            [
                [rule, row.get("fired"), row.get("resolved"),
                 row.get("active"),
                 "-" if row.get("detect_s") is None
                 else _fmt(row["detect_s"])]
                for rule, row in (al.get("rules") or {}).items()
            ],
            ["rule", "fired", "resolved", "active", "detect_s"],
        ))
    mem = summary.get("memory") or {}
    progs = mem.get("programs") or {}
    if progs or mem.get("peak_live_buffer_bytes") is not None:
        out.append(
            "memory: peak_live="
            + _fmt_bytes(mem.get("peak_live_buffer_bytes"))
        )
        if progs:
            out.append(format_table(
                [
                    [
                        name,
                        _fmt_bytes(f.get("argument_bytes")),
                        _fmt_bytes(f.get("temp_bytes")),
                        _fmt_bytes(f.get("output_bytes")),
                        _fmt_bytes(f.get("peak_estimate_bytes")),
                    ]
                    for name, f in sorted(progs.items())
                ],
                ["program", "args", "temp", "output", "peak_est"],
            ))
    return "\n".join(out)


def render_comparison(result: dict) -> str:
    rows = []
    for v in result["verdicts"]:
        base, new = v["base"], v["new"]
        is_bytes = v["metric"].startswith("memory/")
        fmt = _fmt_bytes if is_bytes else _fmt
        rows.append([
            v["metric"],
            fmt(base),
            fmt(new),
            "-" if v["delta_pct"] is None else f"{v['delta_pct']:+.1f}%",
            v["verdict"].upper() if v["verdict"] == "regressed"
            else v["verdict"],
        ])
    table = format_table(
        rows, ["metric", "base", "new", "delta", "verdict"]
    )
    tail = (
        f"\nREGRESSED (threshold {result['threshold_pct']:g}%)"
        if result["regressed"]
        else f"\nOK (threshold {result['threshold_pct']:g}%)"
    )
    return table + tail
