"""Live status/metrics endpoint: look at a multi-hour run while it runs.

PR 3 made the framework *emit* a unified event stream; this module is the
first thing that *consumes* it in flight. A :class:`StatusSink` rides the
event bus like any other sink, folding each record into a small in-memory
model of the run (manifest, current iteration row, phase timings, health
findings, recompiles, memory gauges) and publishing it as an immutable
snapshot dict — one reference swap per event, so the HTTP side never
holds the bus's lock and never replays events per request.
:class:`StatusServer` is a stdlib-only ``ThreadingHTTPServer`` on a
background daemon thread serving two paths:

* ``GET /status``  — the full JSON snapshot (what a dashboard or a
  squinting human wants);
* ``GET /metrics`` — the same gauges/counters in Prometheus text
  exposition format (what a scraper wants), so a fleet of TPU runs
  drops into existing monitoring unmodified.

Contracts (test-pinned in ``tests/test_introspection.py``):

* **Zero overhead when unset.** The sink and server exist only when
  ``--status-port`` / ``cfg.status_port`` is given — no thread, no
  socket, and the emitted event bytes are identical to a run without
  the flag.
* **Serving never blocks ``emit``.** ``write`` mutates under the sink's
  own lock and swaps ``self.snapshot`` (a fresh dict each time); request
  handlers read that attribute once (atomic in CPython) and serialize
  outside any lock. A slow/stuck scraper costs the training loop
  nothing.
* **Port 0 = ephemeral**: the OS picks; the bound port is exposed as
  ``StatusServer.port``, printed by the CLI, and announced as a
  ``status`` event on the bus (after the manifest), so the event log
  itself says where the endpoint lived.
"""

from __future__ import annotations

import json
import math
import threading
import time
from collections import Counter, deque
from typing import Optional

__all__ = ["StatusSink", "StatusServer", "render_prometheus"]

_SNAPSHOT_SCHEMA = "trpo-tpu-status"

# manifest fields worth surfacing (the full config is in the event log;
# the status page wants the identity card, not the whole dataclass)
_MANIFEST_KEYS = (
    "config_hash", "jax_version", "backend", "device_count", "git_sha",
    "driver", "n_iterations",
)


class StatusSink:
    """Event-bus sink that maintains the live run snapshot.

    ``write`` is called under the bus lock (whole records, never bytes);
    all internal mutation happens under ``self._lock`` and ends with a
    swap of ``self.snapshot`` — readers take the reference and go.
    Gauges that do not travel over the bus (the async driver's drain
    depth) are pushed in via :meth:`set_gauges`.
    """

    def __init__(self, max_health: int = 20):
        self._lock = threading.Lock()
        self._started_t = time.time()
        self._manifest: Optional[dict] = None
        self._iteration: Optional[int] = None
        self._iteration_t: Optional[float] = None
        self._stats: dict = {}
        self._phases: dict = {}
        self._health_counts: Counter = Counter()
        self._health_last: deque = deque(maxlen=max_health)
        self._recompiles = 0
        self._recompiles_unexpected = 0
        self._faults = 0
        self._events_total: Counter = Counter()
        self._drain: Optional[dict] = None
        self._mem_programs: dict = {}
        self._mem_live: Optional[dict] = None
        self._finished = False
        self.snapshot: dict = self._build()

    # -- bus sink protocol -------------------------------------------------

    def write(self, rec: dict) -> None:
        kind = rec.get("kind")
        with self._lock:
            self._events_total[kind] += 1
            if kind == "run_manifest":
                self._manifest = {
                    k: rec.get(k) for k in _MANIFEST_KEYS if k in rec
                }
            elif kind == "iteration":
                self._iteration = rec.get("iteration")
                self._iteration_t = rec.get("t")
                self._stats = dict(rec.get("stats") or {})
            elif kind == "phase":
                self._phases[rec.get("name")] = {
                    "ms": rec.get("ms"),
                    "calls": rec.get("calls"),
                    "total_s": rec.get("total_s"),
                }
            elif kind == "health":
                self._health_counts[
                    (rec.get("check"), rec.get("level"))
                ] += 1
                self._health_last.append({
                    "t": rec.get("t"),
                    "check": rec.get("check"),
                    "level": rec.get("level"),
                    "message": rec.get("message"),
                    "iteration": rec.get("iteration"),
                })
            elif kind == "recompile":
                self._recompiles += 1
                if rec.get("unexpected"):
                    self._recompiles_unexpected += 1
            elif kind == "fault_injected":
                self._faults += 1
            elif kind == "memory":
                if rec.get("scope") == "program":
                    self._mem_programs[rec.get("program")] = {
                        k: v
                        for k, v in rec.items()
                        if k.endswith("_bytes")
                    }
                else:
                    # "iteration" excluded: it has its own family
                    # (trpo_iteration) and is not a memory gauge
                    self._mem_live = {
                        k: v
                        for k, v in rec.items()
                        if k not in ("v", "kind", "t", "scope",
                                     "iteration")
                    }
            # unknown kinds still count in events_total: readers tolerate,
            # only the strict validator rejects
            self.snapshot = self._build()

    def close(self) -> None:
        pass

    # -- non-bus gauges ----------------------------------------------------

    def set_gauges(self, **drain) -> None:
        """Host-side gauges with no event record (the StatsDrain queue's
        depth/high-water/bound) — pushed per iteration by ``Telemetry``."""
        with self._lock:
            self._drain = drain
            self.snapshot = self._build()

    def set_phases(self, summary: dict) -> None:
        """Live phase timings (``PhaseTimer.summary()`` rows, same keys
        as ``phase`` events) — pushed per iteration by ``Telemetry``,
        since the bus only carries phase events at ``finish_run``, when
        a mid-run scrape can no longer use them."""
        with self._lock:
            self._phases = {
                name: {
                    "ms": row.get("mean_ms"),
                    "calls": row.get("calls"),
                    "total_s": row.get("total_s"),
                }
                for name, row in summary.items()
            }
            self.snapshot = self._build()

    def mark_finished(self) -> None:
        with self._lock:
            self._finished = True
            self.snapshot = self._build()

    # -- snapshot ----------------------------------------------------------

    def _build(self) -> dict:
        """A fresh, immutable-by-convention snapshot dict. Every nested
        container is copied, so a handler serializing an OLD snapshot
        never races a newer ``write``."""
        return {
            "schema": _SNAPSHOT_SCHEMA,
            "started_t": self._started_t,
            "updated_t": time.time(),
            "manifest": dict(self._manifest) if self._manifest else None,
            "iteration": self._iteration,
            "iteration_t": self._iteration_t,
            "stats": dict(self._stats),
            "phases": {k: dict(v) for k, v in self._phases.items()},
            "drain": dict(self._drain) if self._drain else None,
            "health": {
                "counts": {
                    f"{check}:{level}": n
                    for (check, level), n in sorted(
                        self._health_counts.items()
                    )
                },
                "last": list(self._health_last),
            },
            "recompiles": {
                "total": self._recompiles,
                "unexpected": self._recompiles_unexpected,
            },
            "faults_injected": self._faults,
            "memory": {
                "programs": {
                    k: dict(v) for k, v in self._mem_programs.items()
                },
                "live": dict(self._mem_live) if self._mem_live else None,
            },
            "events_total": dict(self._events_total),
            "finished": self._finished,
        }


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------


def _esc(label: str) -> str:
    return (
        str(label)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _num(v):
    """Prometheus sample value, or None to skip (non-numeric)."""
    if isinstance(v, bool):
        return float(v)
    if isinstance(v, (int, float)):
        return float(v)
    return None


def _fmt(v: float) -> str:
    """One Prometheus sample value as text (NaN/±Inf are legal).
    Module-level so the fleet endpoint (fleet/scrape.py) renders
    samples identically instead of keeping a diverging copy."""
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def render_prometheus(snap: dict) -> str:
    """The snapshot as Prometheus text format (version 0.0.4).

    Families: ``trpo_iteration``, every numeric stat of the current row
    as ``trpo_iteration_stat{stat=...}``, phase timings, event/health
    counters, recompiles, drain gauges, memory gauges, and
    ``trpo_run_finished``. NaN/±Inf are legal sample values and pass
    through (a reward with no finished episodes reads as NaN; the JSON
    side, where bare NaN tokens are invalid, serves null instead).
    """
    out = []

    def fam(name, mtype, help_, samples):
        rows = []
        for labels, value in samples:
            v = _num(value)
            if v is None:
                continue
            if labels:
                lbl = ",".join(
                    f'{k}="{_esc(v2)}"' for k, v2 in labels.items()
                )
                rows.append(f"{name}{{{lbl}}} {_fmt(v)}")
            else:
                rows.append(f"{name} {_fmt(v)}")
        if rows:
            out.append(f"# HELP {name} {help_}")
            out.append(f"# TYPE {name} {mtype}")
            out.extend(rows)

    stats = snap.get("stats") or {}
    if snap.get("iteration") is not None:
        fam("trpo_iteration", "gauge", "current training iteration",
            [({}, snap["iteration"])])
    fam(
        "trpo_iteration_stat", "gauge",
        "latest iteration's stats row (one sample per stat)",
        [({"stat": k}, v) for k, v in sorted(stats.items())],
    )
    fam(
        "trpo_phase_ms", "gauge", "per-phase mean milliseconds",
        [
            ({"phase": name}, row.get("ms"))
            for name, row in sorted((snap.get("phases") or {}).items())
        ],
    )
    fam(
        "trpo_events_total", "counter", "event records seen, by kind",
        [
            ({"kind": k}, n)
            for k, n in sorted((snap.get("events_total") or {}).items())
        ],
    )
    health = snap.get("health") or {}
    fam(
        "trpo_health_total", "counter", "health findings, by check:level",
        [
            ({"check": k}, n)
            for k, n in sorted((health.get("counts") or {}).items())
        ],
    )
    rec = snap.get("recompiles") or {}
    fam("trpo_recompile_total", "counter", "XLA compilations observed",
        [({}, rec.get("total", 0))])
    fam(
        "trpo_recompile_unexpected_total", "counter",
        "post-steady-state retraces (should be zero)",
        [({}, rec.get("unexpected", 0))],
    )
    fam("trpo_faults_injected_total", "counter", "chaos faults fired",
        [({}, snap.get("faults_injected", 0))])
    drain = snap.get("drain") or {}
    fam(
        "trpo_stats_drain", "gauge",
        "async stats-drain queue gauges (depth/high_water/maxsize)",
        [({"gauge": k}, v) for k, v in sorted(drain.items())],
    )
    mem = snap.get("memory") or {}
    live = mem.get("live") or {}
    fam(
        "trpo_memory_live", "gauge",
        "live device-memory gauges (bytes/counts)",
        [({"gauge": k}, v) for k, v in sorted(live.items())],
    )
    prog_samples = []
    for pname, fields in sorted((mem.get("programs") or {}).items()):
        for k, v in sorted(fields.items()):
            prog_samples.append(({"program": pname, "kind": k}, v))
    fam(
        "trpo_program_memory_bytes", "gauge",
        "compiled memory_analysis bytes per jitted program",
        prog_samples,
    )
    fam("trpo_run_finished", "gauge", "1 once learn() has finished",
        [({}, 1.0 if snap.get("finished") else 0.0)])
    return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# the HTTP server
# ---------------------------------------------------------------------------


def _json_safe(obj):
    """RFC-valid JSON: nonfinite floats become null (json.dumps would
    emit bare ``NaN``/``Infinity`` tokens that jq / JavaScript / every
    strict parser rejects — and reward_running IS NaN until the first
    episode finishes). Runs per request, never on the emit path."""
    if isinstance(obj, float) and not math.isfinite(obj):
        return None
    if isinstance(obj, dict):
        return {k: _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    return obj


class StatusServer:
    """Background HTTP server over a :class:`StatusSink`.

    Binds ``host:port`` at construction (``port=0`` = OS-assigned; read
    the result from ``.port``) and serves on a daemon thread until
    :meth:`close` — the shared plumbing (daemon handler threads,
    silenced logs/errors, address reuse) lives in
    ``utils/httpd.BackgroundHTTPServer``, which the policy-serving
    front end (``serve/server.py``) reuses.
    """

    ENDPOINTS = ("/status", "/metrics")

    def __init__(self, sink: StatusSink, port: int,
                 host: str = "127.0.0.1"):
        from trpo_tpu.utils.httpd import BackgroundHTTPServer

        self.sink = sink

        def _status():
            body = json.dumps(_json_safe(self.sink.snapshot)).encode()
            return 200, "application/json", body

        def _metrics():
            body = render_prometheus(self.sink.snapshot).encode()
            return 200, "text/plain; version=0.0.4; charset=utf-8", body

        self._httpd = BackgroundHTTPServer(
            port,
            host=host,
            get={"/": _status, "/status": _status, "/metrics": _metrics},
            not_found="have /status and /metrics",
            thread_name="obs-status-server",
        )
        self.host = host
        self.port = self._httpd.port

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is None:
            return
        httpd.close()
