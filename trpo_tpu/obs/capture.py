"""Request capture for deterministic replay (ISSUE 18).

The serving plane journals every carry, fences every write, and traces
every sampled request — a *recording* of production that, before this
module, nothing could play back. :class:`RequestCapture` closes the
recording half of that loop: for every request whose trace is emitted
(the SAME head-sampling verdict the tracer uses — capture and spans
agree with no coordination, and anomaly-forced traces are always
captured), it records the request's replayable inputs on the event bus
as typed ``capture`` records:

* ``trace`` / ``order`` — the trace id and this process's arrival
  order among captured requests (the causal replay order within a
  session is the stamped ``seq``; ``order`` totally orders the
  cross-session interleave).
* ``path`` / ``endpoint`` / ``session`` / ``seq`` — where the request
  went; ``seq`` is the router's dedupe stamp, extracted from the
  stamped body (JSON or wire frame) on the writer thread.
* ``payload`` — the observation, re-encoded as a base64'd binary wire
  frame (``serve/wire.py`` — the codec IS the serializer, so replay
  round-trips the obs bytes bit-exact regardless of whether the client
  spoke JSON or wire).
* ``step`` — the checkpoint step loaded on the answering replica: the
  shadow set must serve the same params for the bit-exact oracle to
  hold.
* ``action`` — the answered action (when the response parsed): the
  recorded side of the replay diff.

Hot-path contract (the PR 15 span-writer pattern, verbatim): the
request path does ONE bounded-deque append of raw bytes — body/response
parsing, wire re-encoding, and base64 all happen on the daemon writer
thread, which drains through ``bus.emit_batch``. Backpressure drops
WHOLE records and counts every one in ``dropped_total`` (exported as
``trpo_capture_dropped_total`` — never silent); anomaly-forced records
overshoot the bound instead of dropping, exactly like forced traces.
"""

from __future__ import annotations

import base64
import binascii
import json
import threading
import time
from collections import deque
from typing import Optional

import numpy as np

__all__ = [
    "RequestCapture",
    "capture_records",
    "decode_payload",
    "encode_obs_payload",
]


def encode_obs_payload(obs: np.ndarray, seq: Optional[int] = None) -> str:
    """One observation as a base64'd wire frame — the capture record's
    ``payload`` field. The wire codec is the serializer (ISSUE 16):
    little-endian raw array bytes, so decode → re-encode → decode is
    bit-exact."""
    from trpo_tpu.serve import wire as _wire

    scalars = {} if seq is None else {"seq": int(seq)}
    frame = _wire.encode_frame(scalars, {"obs": np.asarray(obs)})
    return base64.b64encode(frame).decode("ascii")


def decode_payload(record: dict):
    """``(scalars, obs)`` back out of one capture record's ``payload``
    (None when the record carries no payload — the writer could not
    parse the request body; the bundle builder reports those as
    non-replayable instead of guessing)."""
    payload = record.get("payload")
    if not isinstance(payload, str) or not payload:
        return None
    from trpo_tpu.serve import wire as _wire

    try:
        scalars, arrays = _wire.decode_frame(
            base64.b64decode(payload.encode("ascii"))
        )
    except (_wire.WireError, binascii.Error, ValueError):
        return None
    obs = arrays.get("obs")
    if obs is None:
        return None
    return scalars, np.asarray(obs)


def capture_records(records) -> list:
    """The ``capture`` records out of a loaded event stream, in arrival
    order (``order`` within each capturing process; processes
    interleave by record time)."""
    caps = [r for r in records if r.get("kind") == "capture"]
    caps.sort(key=lambda r: (r.get("t", 0), r.get("order", 0)))
    return caps


class RequestCapture:
    """Write-behind request recorder for one process (router or
    replica) — the :class:`~trpo_tpu.obs.trace.Tracer` pattern applied
    to request inputs.

    ``record()`` is called at request end with the raw body/response
    bytes; it checks the trace's emitting verdict, does one bounded
    append, and returns. The daemon writer parses, wire-encodes, and
    emits batched ``capture`` records through the bus. ``process`` /
    ``host`` stamp every record, so a multi-process incident window
    assembles the same way traces do."""

    def __init__(
        self,
        bus,
        process: Optional[str] = None,
        host: Optional[str] = None,
        max_pending: int = 1024,
        poll_interval: float = 0.2,
    ):
        if max_pending < 1:
            raise ValueError(
                f"max_pending must be >= 1, got {max_pending}"
            )
        self.bus = bus
        self.process = process
        self.host = host
        self.max_pending = int(max_pending)
        self._poll = float(poll_interval)
        # counters (exported by the /metrics handlers): requests_total
        # counts records accepted into the pending buffer, bytes_total
        # the request-payload bytes they carried, dropped_total the
        # records writer backpressure refused — drops are visible,
        # never silent (the tracer contract)
        self.requests_total = 0
        self.dropped_total = 0
        self.bytes_total = 0
        self._order = 0
        self._lock = threading.Lock()
        self._pending: deque = deque()
        self._wake = threading.Event()
        self._stop = False
        self._writer = threading.Thread(
            target=self._loop, name="capture-writer", daemon=True
        )
        self._writer.start()

    # -- producer side (the request path) ----------------------------------

    def record(
        self,
        ctx,
        path: str,
        endpoint: str,
        body: bytes,
        status: int,
        binary: bool = False,
        session: Optional[str] = None,
        replica: Optional[str] = None,
        step: Optional[int] = None,
        action=None,
        response: Optional[bytes] = None,
        response_ctype: Optional[str] = None,
    ) -> bool:
        """Capture one finished request iff its trace is emitting —
        the deterministic head-sampling verdict (plus anomaly forcing)
        is shared with the tracer, so capture and spans name exactly
        the same set of traces. One deque append on the request path;
        everything heavy runs on the writer. Returns whether the
        record was accepted (False = not sampled, or counted drop)."""
        if ctx is None or not ctx.emitting:
            return False
        item = {
            "trace": ctx.trace_id,
            "path": path,
            "endpoint": endpoint,
            "body": body,
            "binary": bool(binary),
            "status": int(status),
            "session": session,
            "replica": replica,
            "step": step,
            "action": action,
            "response": response,
            "response_ctype": response_ctype,
            "forced": bool(ctx.forced),
            "t": time.time(),
        }
        with self._lock:
            if self._stop:
                return False
            if not ctx.forced and len(self._pending) + 1 > self.max_pending:
                # backpressure drops whole records, counted — forced
                # (anomaly) requests overshoot instead: an incident's
                # inputs are exactly what replay exists for
                self.dropped_total += 1
                return False
            item["order"] = self._order
            self._order += 1
            self._pending.append(item)
            self.requests_total += 1
            self.bytes_total += len(body) if body is not None else 0
        self._wake.set()
        return True

    # -- writer side --------------------------------------------------------

    def _encode(self, item: dict) -> dict:
        """One pending item → one ``capture`` record (writer thread:
        body parse, wire re-encode, base64, response-action
        extraction). A body the writer cannot parse still yields a
        record — without ``payload``, so the miss is loud downstream
        (the bundle builder reports the trace non-replayable)."""
        rec = {
            "trace": item["trace"],
            "order": item["order"],
            "path": item["path"],
            "endpoint": item["endpoint"],
            "status": item["status"],
            "t": item["t"],
        }
        for key in ("session", "replica"):
            if item.get(key) is not None:
                rec[key] = item[key]
        if item.get("forced"):
            rec["forced"] = True
        obs, seq = self._parse_body(item["body"], item["binary"])
        if seq is not None:
            rec["seq"] = seq
        if obs is not None:
            try:
                rec["payload"] = encode_obs_payload(obs, seq=seq)
            except Exception:
                pass
        # the answered action and the checkpoint step it ran on: given
        # directly by a replica-side caller, or parsed out of the raw
        # response the router-side caller handed over
        action, step = item.get("action"), item.get("step")
        if item.get("response") is not None and (
            action is None or step is None
        ):
            r_action, r_step = self._parse_response(
                item["response"], item.get("response_ctype")
            )
            action = r_action if action is None else action
            step = r_step if step is None else step
        if isinstance(step, int) and not isinstance(step, bool):
            rec["step"] = step
        if action is not None:
            try:
                rec["action"] = np.asarray(action, np.float64).tolist()
            except (TypeError, ValueError):
                pass
        return rec

    @staticmethod
    def _parse_body(body, binary: bool):
        """``(obs, seq)`` out of one stamped act body (None, None when
        unparseable — the record is emitted payload-less)."""
        if body is None:
            return None, None
        from trpo_tpu.serve import wire as _wire

        if binary:
            try:
                scalars, arrays = _wire.decode_frame(body)
            except _wire.WireError:
                return None, None
            obs = arrays.get("obs")
            seq = scalars.get("seq")
            return (
                np.array(obs) if obs is not None else None,
                int(seq) if isinstance(seq, int) else None,
            )
        try:
            payload = json.loads(body)
        except (ValueError, UnicodeDecodeError):
            return None, None
        if not isinstance(payload, dict):
            return None, None
        obs = payload.get("obs")
        if obs is None:
            return None, None
        try:
            obs = np.asarray(obs, np.float32)
        except (TypeError, ValueError):
            return None, None
        seq = payload.get("seq")
        return obs, int(seq) if isinstance(seq, int) else None

    @staticmethod
    def _parse_response(response: bytes, ctype: Optional[str]):
        """``(action, step)`` out of one response body (JSON or wire
        frame) — the recorded side of the replay diff plus the
        checkpoint step the act actually ran on."""
        from trpo_tpu.serve import wire as _wire

        base = (ctype or "").split(";", 1)[0].strip().lower()
        if base == _wire.WIRE_CONTENT_TYPE:
            try:
                scalars, arrays = _wire.decode_frame(response)
            except _wire.WireError:
                return None, None
            act = arrays.get("action")
            return (
                np.array(act) if act is not None else None,
                scalars.get("step"),
            )
        try:
            out = json.loads(response)
        except (ValueError, UnicodeDecodeError):
            return None, None
        if not isinstance(out, dict):
            return None, None
        return out.get("action"), out.get("step")

    def _loop(self) -> None:
        while True:
            with self._lock:
                pending, self._pending = self._pending, deque()
                stop = self._stop
            if pending:
                stamp = {}
                if self.process is not None:
                    stamp["process"] = self.process
                if self.host is not None:
                    stamp["host"] = self.host
                try:
                    records = [
                        {**self._encode(item), **stamp}
                        for item in pending
                    ]
                    # one bus-lock hold + one sink write per drain —
                    # the batched-emit lesson the tracer's writer
                    # already paid for on the serving bench
                    self.bus.emit_batch("capture", records)
                except Exception:
                    # a closed bus (teardown race) or a sink error
                    # must never kill the writer — but the loss is
                    # COUNTED: dropped_total=0 means genuinely
                    # lossless
                    with self._lock:
                        self.dropped_total += len(pending)
            if stop:
                return
            self._wake.wait(timeout=self._poll)
            self._wake.clear()

    def drain(self, timeout: float = 5.0) -> None:
        """Block until the pending buffer is empty (tests, teardown)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if not self._pending:
                    return
            self._wake.set()
            time.sleep(0.01)

    def close(self) -> None:
        """Flush and stop the writer (the bus is the caller's — closed
        after, like every other bus consumer)."""
        with self._lock:
            self._stop = True
        self._wake.set()
        self._writer.join(timeout=5.0)
