"""Recompile monitor: count and attribute unexpected XLA retraces.

This codebase leans hard on buffer donation and stable jit templates
(``agent.py``'s donation contract, the checkpoint-restore placement
rules) — and the failure mode of getting one of those wrong is SILENT: a
drifting shape/dtype/sharding retraces the program every iteration and
training quietly runs at compile speed. jax already logs every
trace/compile when ``jax_log_compiles`` is on; this monitor turns that
into a counter: a ``logging.Handler`` attached to the ``jax`` logger
parses the per-program "Finished XLA compilation of <name> …" records,
counts compilations per jitted program, and — after the caller marks the
run steady (warmup compiles are expected) — flags every further
compilation as an unexpected retrace, optionally emitting a ``recompile``
event through the bus as it happens.

Scope: counts only while started (the handler is attached per instance,
so concurrent test runs don't bleed into each other); ``jax_log_compiles``
is saved/restored on stop, and while active a filter on the jax logger's
PRE-EXISTING handlers (jax installs its own StreamHandler on ``jax``)
drops the "Finished …" records we consume, so enabling the monitor does
not spray compile logs over stderr while every other jax warning still
prints.
"""

from __future__ import annotations

import logging
import re
import threading
from typing import Optional

import jax

__all__ = ["RecompileMonitor"]

_COMPILE_RE = re.compile(
    r"Finished XLA compilation of (.+?) in ([0-9.eE+~-]+) sec"
)

# every record shape jax emits under jax_log_compiles (tracing,
# jaxpr→MLIR, XLA compilation, pxla's "Compiling <fn> with global
# shapes") — consumed by us, muted on jax's own handlers while the
# monitor is attached
_VERBOSE_RE = re.compile(
    r"^(Finished (tracing|jaxpr|XLA compilation)|Compiling )"
)


class _MuteCompileRecords(logging.Filter):
    def filter(self, record: logging.LogRecord) -> bool:
        return _VERBOSE_RE.search(record.getMessage()) is None


class RecompileMonitor(logging.Handler):
    """Attachable counter of per-program XLA compilations.

    Usage::

        mon = RecompileMonitor()
        with mon:                      # or mon.start() / mon.stop()
            warmup()
            mon.mark_steady()
            train()                    # retraces here are unexpected
        mon.unexpected_retraces()      # {program_name: count}
    """

    def __init__(self, bus=None):
        super().__init__(level=logging.DEBUG)
        self._bus = bus
        self._lock2 = threading.Lock()  # logging.Handler owns self.lock
        self.compiles: dict = {}
        self.unexpected: dict = {}
        self._steady = False
        self._active = False
        self._saved: Optional[tuple] = None
        self._mute: Optional[logging.Filter] = None
        self._muted_handlers: list = []

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._active:
            return
        jax_logger = logging.getLogger("jax")
        self._saved = (jax.config.jax_log_compiles, jax_logger.level)
        jax.config.update("jax_log_compiles", True)
        if jax_logger.getEffectiveLevel() > logging.WARNING:
            # the compile records are WARNING-level (that's how
            # jax_log_compiles surfaces them); make sure they reach us
            jax_logger.setLevel(logging.WARNING)
        # mute the records we consume on jax's own handlers (jax installs
        # a StreamHandler directly on "jax", so propagation flags cannot
        # silence it); other jax warnings keep printing
        self._mute = _MuteCompileRecords()
        self._muted_handlers = list(jax_logger.handlers)
        for h in self._muted_handlers:
            h.addFilter(self._mute)
        jax_logger.addHandler(self)
        self._active = True

    def stop(self) -> None:
        if not self._active:
            return
        jax_logger = logging.getLogger("jax")
        jax_logger.removeHandler(self)
        for h in self._muted_handlers:
            h.removeFilter(self._mute)
        self._muted_handlers = []
        log_compiles, level = self._saved
        jax.config.update("jax_log_compiles", log_compiles)
        jax_logger.setLevel(level)
        self._active = False

    def __enter__(self) -> "RecompileMonitor":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- accounting --------------------------------------------------------

    def mark_steady(self) -> None:
        """Declare warmup over: every compilation from here on is an
        unexpected retrace. Idempotent."""
        with self._lock2:
            self._steady = True

    def emit(self, record: logging.LogRecord) -> None:  # logging.Handler
        m = _COMPILE_RE.search(record.getMessage())
        if m is None:
            return
        name = m.group(1)
        try:
            elapsed_s = float(m.group(2))
        except ValueError:
            elapsed_s = None
        with self._lock2:
            self.compiles[name] = self.compiles.get(name, 0) + 1
            count = self.compiles[name]
            unexpected = self._steady
            if unexpected:
                self.unexpected[name] = self.unexpected.get(name, 0) + 1
        if self._bus is not None:
            self._bus.emit(
                "recompile",
                program=name,
                count=count,
                unexpected=unexpected,
                elapsed_s=elapsed_s,
            )

    def total_compiles(self) -> dict:
        with self._lock2:
            return dict(self.compiles)

    def unexpected_retraces(self) -> dict:
        """Per-program compilations observed AFTER :meth:`mark_steady` —
        each one is a silent perf killer worth attributing."""
        with self._lock2:
            return dict(self.unexpected)
