"""Device-side metric accumulation: counters that ride ``TrainState``.

The solver's per-iteration diagnostics (CG iterations executed, linesearch
trials, rollbacks) already come back in the stats pytree — but CUMULATIVE
counters previously had to be folded on the host, which either puts a
blocking device→host fetch back on the hot path (exactly what the async
pipeline removed) or forgets the counts entirely. Here the counters are a
tiny pytree of int32 scalars carried in ``TrainState.metrics``: the
accumulation is a handful of scalar adds fused into phase A of the update
program, the snapshot rides the SAME deferred stats drain every other stat
uses, and the pytree is donated with the rest of the state — zero extra
transfers, zero extra HBM (``tests/test_observability.py`` pins donation
safety and monotone accumulation).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

__all__ = [
    "DeviceMetrics",
    "init_device_metrics",
    "accumulate_update",
    "metrics_stats",
]


class DeviceMetrics(NamedTuple):
    """Run-cumulative solver counters (all int32 scalars)."""

    cg_iters_total: jax.Array         # CG iterations actually executed
    cg_early_exit_total: jax.Array    # updates whose CG exited before cap
    linesearch_trials_total: jax.Array  # backtracking trials evaluated
    rollback_total: jax.Array         # KL rollbacks fired
    nan_guard_total: jax.Array        # updates with a nonfinite guard trip


def init_device_metrics() -> DeviceMetrics:
    z = lambda: jnp.asarray(0, jnp.int32)
    return DeviceMetrics(z(), z(), z(), z(), z())


def accumulate_update(
    metrics: DeviceMetrics, trpo_stats, cg_iter_cap: int
) -> DeviceMetrics:
    """Fold one TRPO update's ``TRPOStats`` into the counters (traced into
    the update program — ``cg_iter_cap`` is the iteration cap the solve
    actually ran under: the static ``cfg.cg_iters``, or the traced
    ``stats.cg_budget`` when the solver precision ladder's adaptive
    budget shrank it — so "early exit" always means the residual rule
    fired before the cap, never that the cap itself was small)."""
    i32 = lambda x: jnp.asarray(x, jnp.int32)
    return DeviceMetrics(
        cg_iters_total=metrics.cg_iters_total
        + i32(trpo_stats.cg_iterations),
        cg_early_exit_total=metrics.cg_early_exit_total
        + i32(trpo_stats.cg_iterations < cg_iter_cap),
        linesearch_trials_total=metrics.linesearch_trials_total
        + i32(trpo_stats.linesearch_trials),
        rollback_total=metrics.rollback_total + i32(trpo_stats.rolled_back),
        nan_guard_total=metrics.nan_guard_total + i32(trpo_stats.nan_guard),
    )


def metrics_stats(metrics: DeviceMetrics) -> dict:
    """The counters as stats-pytree entries — merged into the phase-B
    stats dict so they drain/log/emit exactly like every other stat."""
    return {
        "cg_iters_total": metrics.cg_iters_total,
        "cg_early_exit_total": metrics.cg_early_exit_total,
        "linesearch_trials_total": metrics.linesearch_trials_total,
        "rollback_total": metrics.rollback_total,
        "nan_guard_total": metrics.nan_guard_total,
    }
