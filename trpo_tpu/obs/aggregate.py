"""The fleet-wide aggregation plane (ISSUE 20).

Every process in the system already exposes its own observability
surface — the router's ``/status`` + ``/metrics``, each member's
``obs/server.StatusServer``, the fleet view's ``FleetStatusServer``,
the promoter's on-disk journal — but nothing *watches* them together
live. This module is that watcher:

* :class:`MetricsAggregator` — polls every registered target on an
  interval into bounded in-memory ring-buffer time series. One poller
  thread PER TARGET, so a dead or wedged endpoint makes exactly its
  own series stale and never blocks the others — staleness is itself
  an alertable condition (:mod:`trpo_tpu.obs.alerts`' ``target_stale``
  rule reads :meth:`MetricsAggregator.target_states`), never a silent
  gap. A synchronous :meth:`MetricsAggregator.tick` drives the same
  scrape+evaluate cycle deterministically for tests and ``--once``
  dashboards.
* Scrape targets (all duck-typed on ``.name`` + ``.scrape(timeout)``):

  - :class:`HttpTarget` — one ``/status`` endpoint (router, replica,
    member StatusServer, fleet view): the JSON tree is flattened to
    dotted numeric series (``status.counters.routed_total``,
    ``status.latency_recent_ms.0.99``, ...); pass ``metrics_path`` to
    also parse the Prometheus text exposition into per-sample series.
  - :class:`JournalTarget` — the promotion controller's durable
    journal (``fleet/promote.py``): derives ``promote.inflight`` (non-
    terminal entries) and ``promote.unconverged_s`` (seconds since the
    journal's last atomic write while anything is inflight) — the
    mtime-based age that makes "promoter stuck in publishing"
    *observable from the outside*, exactly the wedge ``kill_promoter``
    injects.
  - :class:`CallbackTarget` — in-process values (e.g. a
    ``CanaryController``'s ``rolled_back_total``) without an HTTP hop.

* Emission: each evaluation tick batches one ``metric_sample`` event
  per target ``up`` series plus the latest point of every WATCHED
  series (the ones alert rules read, or an explicit ``emit_series``
  glob list) through ``EventBus.emit_batch`` — one lock hold, one
  write, the same ≤2%-overhead discipline the PR 15 tracer set. The
  store keeps everything; the log carries the bounded, alert-relevant
  subset plus proof the plane was armed (the validator's alert
  contracts key off ``metric_sample`` proximity to decide whether a
  fault was injected while anyone was watching).
"""

from __future__ import annotations

import fnmatch
import json
import os
import threading
import time
import urllib.request
from collections import deque
from typing import Callable, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "Series",
    "HttpTarget",
    "JournalTarget",
    "CallbackTarget",
    "MetricsAggregator",
    "flatten_status",
    "parse_prometheus",
]


def _num(v):
    """The numeric leaves a series can hold (bool counts as 0/1 —
    ``finished: true`` should chart)."""
    if isinstance(v, bool):
        return float(v)
    if isinstance(v, (int, float)):
        return float(v)
    return None


def flatten_status(obj, prefix: str = "status") -> Dict[str, float]:
    """A ``/status`` JSON tree as dotted numeric series. Non-numeric
    leaves and lists are skipped (series are time-value charts, not
    documents); dict recursion keeps the path, so the router's
    ``counters.routed_total`` becomes ``status.counters.routed_total``
    and a nested replica row keeps its replica id in the key."""
    out: Dict[str, float] = {}

    def walk(node, path):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(v, f"{path}.{k}")
            return
        v = _num(node)
        if v is not None:
            out[path] = v

    walk(obj, prefix)
    return out


def parse_prometheus(text: str) -> Dict[str, float]:
    """Prometheus text exposition (version 0.0.4) as a series dict —
    the sample name WITH its label block is the series key (labels are
    what make ``trpo_iteration_stat{stat="kl"}`` distinct rows). Bad
    lines are skipped, not fatal: a scraper must survive whatever an
    endpoint mid-restart serves."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        # "name{labels} value" or "name value" (timestamps unused here)
        try:
            key, rest = line.rsplit(" ", 1)
            value = float(rest)
        except ValueError:
            continue
        key = key.strip()
        if key:
            out[key] = value
    return out


class Series:
    """One bounded ring-buffer time series of ``(t, value)`` points.
    Window queries are linear in the window, not the buffer — the
    buffer is small (``maxlen``) by construction."""

    __slots__ = ("_buf",)

    def __init__(self, maxlen: int = 600):
        self._buf: deque = deque(maxlen=maxlen)

    def add(self, t: float, value: float) -> None:
        self._buf.append((float(t), float(value)))

    def __len__(self) -> int:
        return len(self._buf)

    def last(self) -> Optional[Tuple[float, float]]:
        return self._buf[-1] if self._buf else None

    def window(self, now: float, seconds: float) -> List[Tuple[float, float]]:
        lo = now - seconds
        return [(t, v) for t, v in self._buf if t >= lo]

    def span(self) -> float:
        """Seconds between the oldest and newest point (0 if < 2)."""
        if len(self._buf) < 2:
            return 0.0
        return self._buf[-1][0] - self._buf[0][0]

    def delta(self, now: float, seconds: float) -> Optional[float]:
        """Increase of a counter over the window, reset-aware: a drop
        (process restart zeroed the counter) contributes the new
        absolute value, the standard Prometheus ``increase`` rule.
        None when the window holds < 2 points (no rate computable)."""
        win = self.window(now, seconds)
        if len(win) < 2:
            return None
        total, prev = 0.0, win[0][1]
        for _, v in win[1:]:
            total += (v - prev) if v >= prev else v
            prev = v
        return total

    def last_increase_t(self) -> Optional[float]:
        """Timestamp of the most recent strict increase (stall
        detection); the FIRST point's time when the series never
        moved — "has not increased since we started watching"."""
        pts = list(self._buf)
        if not pts:
            return None
        for i in range(len(pts) - 1, 0, -1):
            if pts[i][1] > pts[i - 1][1]:
                return pts[i][0]
        return pts[0][0]


# ---------------------------------------------------------------------------
# scrape targets
# ---------------------------------------------------------------------------


class HttpTarget:
    """One HTTP observability endpoint. ``url`` is the server base
    (``http://host:port``); ``status_path`` is fetched and flattened,
    ``metrics_path`` (optional) is fetched and parsed as Prometheus
    text. Any failure raises — the aggregator owns the stale
    bookkeeping (the ``scrape_member`` tolerance pattern, but the
    *caller* records the miss so it can alert on it)."""

    def __init__(
        self,
        name: str,
        url: str,
        status_path: str = "/status",
        metrics_path: Optional[str] = None,
    ):
        self.name = name
        self.url = url.rstrip("/")
        self.status_path = status_path
        self.metrics_path = metrics_path

    def scrape(self, timeout: float) -> Dict[str, float]:
        out: Dict[str, float] = {}
        if self.status_path:
            with urllib.request.urlopen(
                self.url + self.status_path, timeout=timeout
            ) as r:
                out.update(flatten_status(json.load(r)))
        if self.metrics_path:
            with urllib.request.urlopen(
                self.url + self.metrics_path, timeout=timeout
            ) as r:
                out.update(parse_prometheus(r.read().decode()))
        return out


class JournalTarget:
    """The promotion journal as a scrape target. ``path`` may be the
    journal file or the directory that will contain it. A MISSING
    journal is a successful scrape of "no promotions yet" (inflight
    0), not a failure — the promoter writes it lazily; an unreadable
    one raises (stale), because a journal that exists but cannot be
    parsed is exactly the wedge worth alerting on."""

    JOURNAL_NAME = "promote_journal.json"

    def __init__(self, name: str, path: str):
        self.name = name
        if os.path.isdir(path) or not path.endswith(".json"):
            path = os.path.join(path, self.JOURNAL_NAME)
        self.path = path

    def scrape(self, timeout: float) -> Dict[str, float]:
        try:
            st = os.stat(self.path)
        except FileNotFoundError:
            return {"promote.entries": 0.0, "promote.inflight": 0.0,
                    "promote.unconverged_s": 0.0}
        with open(self.path) as f:
            entries = json.load(f)
        if not isinstance(entries, dict):
            raise ValueError("journal is not an object")
        inflight = sum(
            1 for e in entries.values()
            if isinstance(e, dict) and e.get("outcome") is None
        )
        # the journal is written atomically on every phase transition,
        # so mtime = the moment of the LAST transition: while anything
        # is inflight, its age is "how long the promoter has been
        # stuck" — observable even when the promoter process is gone
        age = max(0.0, time.time() - st.st_mtime) if inflight else 0.0
        return {
            "promote.entries": float(len(entries)),
            "promote.inflight": float(inflight),
            "promote.unconverged_s": age,
        }


class CallbackTarget:
    """In-process values without an HTTP hop: ``fn`` returns a flat
    ``{series: number}`` dict (non-numeric values are dropped)."""

    def __init__(self, name: str, fn: Callable[[], dict]):
        self.name = name
        self._fn = fn

    def scrape(self, timeout: float) -> Dict[str, float]:
        raw = self._fn()
        if not isinstance(raw, dict):
            raise ValueError("callback did not return a dict")
        out = {}
        for k, v in raw.items():
            n = _num(v)
            if n is not None:
                out[str(k)] = n
        return out


# ---------------------------------------------------------------------------
# the aggregator
# ---------------------------------------------------------------------------


class _TargetState:
    __slots__ = (
        "target", "first_attempt_t", "last_ok_t", "failures_total",
        "scrapes_total", "stale",
    )

    def __init__(self, target):
        self.target = target
        self.first_attempt_t: Optional[float] = None
        self.last_ok_t: Optional[float] = None
        self.failures_total = 0
        self.scrapes_total = 0
        self.stale = False


class MetricsAggregator:
    """Poll every registered target into ring-buffer series; evaluate
    alert rules; emit ``metric_sample`` batches.

    Live mode (:meth:`start`): one daemon poller thread per target plus
    one evaluator thread — a slow target saturates its own thread's
    timeout, nothing else. Test/CI mode (:meth:`tick`): one synchronous
    scrape-all + evaluate + emit pass with an injectable clock.

    ``engine`` (an :class:`trpo_tpu.obs.alerts.AlertEngine`) is
    optional; when present its rules also define the default WATCHED
    series set (what gets emitted as ``metric_sample`` events) —
    override with ``emit_series`` globs.
    """

    def __init__(
        self,
        targets: Iterable = (),
        bus=None,
        engine=None,
        interval: float = 0.5,
        timeout: float = 0.75,
        stale_after: Optional[float] = None,
        maxlen: int = 600,
        emit_series: Optional[Iterable[str]] = None,
    ):
        if interval <= 0:
            raise ValueError(f"interval must be > 0, got {interval}")
        self.bus = bus
        self.engine = engine
        self.interval = float(interval)
        self.timeout = float(timeout)
        # a target is stale once it has gone this long without a good
        # scrape — generous vs the interval so one slow poll is not a
        # flap, tight enough that a partitioned host alerts in seconds
        self.stale_after = (
            float(stale_after) if stale_after is not None
            else max(3.0 * self.interval, 2.0)
        )
        self.maxlen = int(maxlen)
        self._emit_patterns = (
            tuple(emit_series) if emit_series is not None else None
        )
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, str], Series] = {}
        self._states: Dict[str, _TargetState] = {}
        self._last_emit_t: Dict[Tuple[str, str], float] = {}
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        self._started = False
        for t in targets:
            self.add_target(t)

    # -- registration / store access --------------------------------------

    def add_target(self, target) -> None:
        name = getattr(target, "name", None)
        if not name or not callable(getattr(target, "scrape", None)):
            raise TypeError(
                "target must have .name and .scrape(timeout)"
            )
        with self._lock:
            if name in self._states:
                raise ValueError(f"duplicate target name {name!r}")
            self._states[name] = _TargetState(target)
        if self._started:
            self._spawn_poller(name)

    def target_names(self) -> List[str]:
        with self._lock:
            return sorted(self._states)

    def target_states(self, now: Optional[float] = None) -> Dict[str, dict]:
        """Per-target scrape health: ``up`` (scraped OK within the
        stale budget), ``stale_for_s`` (seconds since the last good
        scrape — since first attempt when none ever succeeded), and
        the raw counters. The ``target_stale`` alert rule reads this."""
        now = time.time() if now is None else now
        out = {}
        with self._lock:
            for name, st in self._states.items():
                ref = st.last_ok_t or st.first_attempt_t
                stale_for = (now - ref) if ref is not None else 0.0
                stale = (
                    st.last_ok_t is None or
                    (now - st.last_ok_t) > self.stale_after
                ) and stale_for > self.stale_after
                st.stale = stale
                out[name] = {
                    "up": not stale and st.last_ok_t is not None,
                    "stale": stale,
                    "stale_for_s": stale_for if stale else 0.0,
                    "last_ok_t": st.last_ok_t,
                    "failures_total": st.failures_total,
                    "scrapes_total": st.scrapes_total,
                }
        return out

    def series_names(self, target: Optional[str] = None) -> List[str]:
        with self._lock:
            return sorted(
                s for (tg, s) in self._series
                if target is None or tg == target
            )

    def get_series(self, target: str, series: str) -> Optional[Series]:
        with self._lock:
            return self._series.get((target, series))

    def match_series(
        self, target: str, patterns
    ) -> Dict[str, Series]:
        """All of one target's series whose name matches ANY of the
        fnmatch globs (str or iterable of str)."""
        if isinstance(patterns, str):
            patterns = (patterns,)
        with self._lock:
            return {
                s: ser for (tg, s), ser in self._series.items()
                if tg == target
                and any(fnmatch.fnmatch(s, p) for p in patterns)
            }

    def latest(self, target: str, series: str) -> Optional[float]:
        ser = self.get_series(target, series)
        last = ser.last() if ser else None
        return last[1] if last else None

    def snapshot(self, now: Optional[float] = None) -> dict:
        """Dashboard view: per-target health + the latest point of
        every stored series (the observatory's data source)."""
        now = time.time() if now is None else now
        states = self.target_states(now)
        with self._lock:
            latest: Dict[str, Dict[str, float]] = {}
            for (tg, s), ser in self._series.items():
                last = ser.last()
                if last is not None:
                    latest.setdefault(tg, {})[s] = last[1]
        return {"targets": states, "latest": latest, "t": now}

    # -- scraping ----------------------------------------------------------

    def _record(self, name: str, samples: Optional[dict], t: float) -> None:
        with self._lock:
            st = self._states.get(name)
            if st is None:
                return
            if st.first_attempt_t is None:
                st.first_attempt_t = t
            st.scrapes_total += 1
            if samples is None:
                st.failures_total += 1
                return
            st.last_ok_t = t
            for s, v in samples.items():
                key = (name, s)
                ser = self._series.get(key)
                if ser is None:
                    ser = self._series[key] = Series(self.maxlen)
                ser.add(t, v)

    def scrape_target(self, name: str, now: Optional[float] = None) -> bool:
        """One scrape of one target, recorded; True on success. Never
        raises — a failed scrape IS data (the target goes stale)."""
        with self._lock:
            st = self._states.get(name)
            target = st.target if st else None
        if target is None:
            return False
        try:
            samples = target.scrape(self.timeout)
        except Exception:
            samples = None
        self._record(
            name, samples, time.time() if now is None else now
        )
        return samples is not None

    def tick(self, now: Optional[float] = None) -> dict:
        """One synchronous scrape-all + evaluate + emit pass (tests,
        ``--once`` dashboards). Returns :meth:`snapshot`."""
        for name in self.target_names():
            self.scrape_target(name, now=now)
        return self._evaluate_and_emit(now)

    def _watched_patterns(self):
        if self._emit_patterns is not None:
            return self._emit_patterns
        if self.engine is not None:
            pats = []
            for rule in self.engine.rules:
                for attr in ("series", "total_series", "guard_series",
                             "key_series", "unless_series"):
                    p = getattr(rule, attr, None)
                    if not p:
                        continue
                    pats.extend((p,) if isinstance(p, str) else p)
            return tuple(dict.fromkeys(pats))
        return ("*",)

    def _evaluate_and_emit(self, now: Optional[float] = None) -> dict:
        now = time.time() if now is None else now
        states = self.target_states(now)
        if self.engine is not None:
            self.engine.evaluate(self, now=now)
        if self.bus is not None:
            patterns = self._watched_patterns()
            fields: List[dict] = []
            with self._lock:
                for name, st in states.items():
                    fields.append({
                        "target": name, "series": "up",
                        "value": 1.0 if st["up"] else 0.0,
                        "stale": bool(st["stale"]),
                    })
                for (tg, s), ser in self._series.items():
                    if not any(fnmatch.fnmatch(s, p) for p in patterns):
                        continue
                    last = ser.last()
                    if last is None:
                        continue
                    key = (tg, s)
                    # emit each stored point at most once: dashboards
                    # replaying the log see the true series, not one
                    # inflated by the evaluator outpacing the scraper
                    if self._last_emit_t.get(key) == last[0]:
                        continue
                    self._last_emit_t[key] = last[0]
                    fields.append({
                        "target": tg, "series": s, "value": last[1],
                        "stale": bool(states.get(tg, {}).get("stale")),
                    })
            if fields:
                self.bus.emit_batch("metric_sample", fields)
        return self.snapshot(now)

    # -- live mode ---------------------------------------------------------

    def _spawn_poller(self, name: str) -> None:
        th = threading.Thread(
            target=self._poll_loop, args=(name,),
            name=f"obs-agg-{name}", daemon=True,
        )
        self._threads.append(th)
        th.start()

    def _poll_loop(self, name: str) -> None:
        while not self._stop.is_set():
            self.scrape_target(name)
            self._stop.wait(self.interval)

    def _eval_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._evaluate_and_emit()
            except Exception:
                # the watcher must never take the watched down with it
                pass
            self._stop.wait(self.interval)

    def start(self) -> "MetricsAggregator":
        if self._started:
            return self
        self._started = True
        self._stop.clear()
        for name in self.target_names():
            self._spawn_poller(name)
        th = threading.Thread(
            target=self._eval_loop, name="obs-agg-eval", daemon=True
        )
        self._threads.append(th)
        th.start()
        return self

    def close(self) -> None:
        self._stop.set()
        for th in self._threads:
            th.join(timeout=max(2.0, self.timeout + 1.0))
        self._threads = []
        self._started = False
