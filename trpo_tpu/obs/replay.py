"""Replay-bundle reconstruction for trace-driven deterministic replay
(ISSUE 18).

The capture log (``obs/capture.py``) records each sampled request's
inputs; the carry journals record every session's state at journal sync
cadence; the span stream records where the time went. This module joins
the three into a **replay bundle** — a single self-contained JSON
document that :mod:`scripts/replay_run.py` can re-execute against a
fresh shadow replica set:

* ``sessions`` — per recorded session: the acts in causal ``seq``
  order (each with its base64 wire-frame obs payload and the recorded
  action — the bit-exact diff oracle), plus a ``seed`` journal
  snapshot when the capture window opens MID-session (the snapshot
  whose ``seq`` is exactly ``first_captured_seq - 1``; anything else
  would replay from the wrong carry, so a missing aligned snapshot
  marks the trace non-replayable rather than silently diverging —
  the oracle's staleness bound is the journal sync cadence).
* ``stateless`` — the ``/act`` captures, payload + recorded action.
* ``completeness`` — per selected trace: ``replayable: true/false``
  and, when false, WHICH piece is missing (capture payload, aligned
  journal seed, recorded action, assembled spans). The silent-miss
  seam this closes: ``assemble_traces`` used to drop unjoinable spans
  without saying so, and a bundle built over a gap would replay
  *something* and call it the incident.
* ``faults`` — the incident window's fault/lease/session records, so
  the replayed trace can be read against what production was doing.
* ``recorded`` — the recorded traces' stage summary
  (``_summarize_traces`` shape): ``replay_run`` feeds it through
  ``compare_runs`` against the shadow run's own summary for the
  per-stage p99 regression rows.

``build_bundle`` raises :class:`BundleError` (never a stack trace at
the CLI — ``analyze_run.py --export-bundle`` maps it to exit 2) when
the trace id is unknown or the capture log lacks it.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "BUNDLE_VERSION",
    "BundleError",
    "build_bundle",
    "write_bundle",
    "load_bundle",
    "scan_journals",
    "action_match",
]

BUNDLE_VERSION = 1

# fault-timeline slack around the captured acts: detection records
# (lease expiry, session resume) land AFTER the acts that tripped them
_FAULT_SLACK_S = 30.0


class BundleError(ValueError):
    """A bundle that cannot be built, with a message fit for an exit-2
    CLI refusal (unknown trace id, capture log without payloads)."""


def scan_journals(journal_dir: Optional[str]) -> Dict[str, List[dict]]:
    """EVERY entry (not latest-wins) per session across all carry
    journals in ``journal_dir`` — reconstruction needs the snapshot at
    one exact ``seq``, which latest-wins ``read_carry_journal`` throws
    away. A fenced zombie's frozen journal is often exactly the
    pre-takeover snapshot a mid-window replay seeds from, so fences
    are NOT filtered here. Entries per session sort by time."""
    entries: Dict[str, List[dict]] = {}
    if journal_dir is None:
        return entries
    for path in sorted(
        glob.glob(os.path.join(journal_dir, "*.carry.jsonl"))
    ):
        try:
            f = open(path, "rb")
        except OSError:
            continue
        with f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn line: absent, not fatal
                if not isinstance(rec, dict) or rec.get("drop"):
                    continue
                sid = rec.get("session")
                if not isinstance(sid, str) or not sid:
                    continue
                if not isinstance(rec.get("carry"), list):
                    continue
                if not isinstance(rec.get("steps"), int):
                    continue
                rec = dict(rec)
                rec["journal"] = os.path.basename(path)
                entries.setdefault(sid, []).append(rec)
    for sid in entries:
        entries[sid].sort(key=lambda e: e.get("t", 0))
    return entries


def _entry_seq(entry: dict) -> Optional[int]:
    seq = entry.get("seq")
    if isinstance(seq, int) and not isinstance(seq, bool):
        return seq
    steps = entry.get("steps")
    if isinstance(steps, int) and not isinstance(steps, bool):
        # router-stamped flows advance seq and steps in lockstep; a
        # seq-less entry (direct client) falls back to the step count
        return steps
    return None


def _pick_record(candidates: List[dict]) -> dict:
    """One capture record per logical act: the router-side record wins
    (it carries the global arrival order at the public edge), then
    whichever record is most complete (payload + action)."""

    def score(rec: dict) -> tuple:
        return (
            rec.get("process") == "router",
            "payload" in rec,
            "action" in rec,
            -(rec.get("t") or 0),
        )

    return max(candidates, key=score)


def _dedupe(captures: List[dict]) -> List[dict]:
    by_key: Dict[tuple, List[dict]] = {}
    for rec in captures:
        if rec.get("endpoint") == "session_act":
            key = (rec.get("trace"), rec.get("session"), rec.get("seq"))
        else:
            key = (rec.get("trace"), "stateless")
        by_key.setdefault(key, []).append(rec)
    picked = [_pick_record(v) for v in by_key.values()]
    picked.sort(key=lambda r: (r.get("t", 0), r.get("order", 0)))
    return picked


def _act_row(rec: dict) -> dict:
    row = {
        "trace": rec.get("trace"),
        "order": rec.get("order"),
        "path": rec.get("path"),
        "endpoint": rec.get("endpoint"),
        "status": rec.get("status"),
        "t": rec.get("t"),
    }
    for key in ("session", "seq", "payload", "action", "step",
                "replica", "forced"):
        if rec.get(key) is not None:
            row[key] = rec[key]
    return row


def build_bundle(
    records: list,
    trace_id: Optional[str] = None,
    window: Optional[Tuple[float, float]] = None,
    journal_dir: Optional[str] = None,
) -> dict:
    """One replay bundle from a loaded (merged) event stream — select
    by one ``trace_id`` or a ``(start, end)`` unix-seconds ``window``
    (an incident window: every captured trace inside it). Raises
    :class:`BundleError` when the selection is empty or un-replayable
    as a whole (no payloads at all)."""
    from trpo_tpu.obs.analyze import _summarize_traces, assemble_traces
    from trpo_tpu.obs.capture import capture_records

    if (trace_id is None) == (window is None):
        raise BundleError(
            "select exactly one of: a trace id, or --window START END"
        )
    captures = capture_records(records)
    if trace_id is not None:
        selected = [r for r in captures if r.get("trace") == trace_id]
        if not selected:
            dropped_spans: list = []
            traces = assemble_traces(records, dropped=dropped_spans)
            if trace_id in traces:
                raise BundleError(
                    f"trace {trace_id} has {len(traces[trace_id])} "
                    "assembled spans but NO capture records — the "
                    "capture log lacks its payloads (was capture "
                    "armed on the router when it ran?)"
                )
            raise BundleError(
                f"unknown trace id {trace_id!r}: no capture record or "
                f"span names it ({len(traces)} traces, "
                f"{len(captures)} captures in the log"
                + (
                    f"; {len(dropped_spans)} span records had no "
                    "joinable trace id"
                    if dropped_spans else ""
                )
                + ")"
            )
    else:
        start, end = float(window[0]), float(window[1])
        if end < start:
            raise BundleError(
                f"--window END ({end}) precedes START ({start})"
            )
        selected = [
            r for r in captures
            if start <= (r.get("t") or 0) <= end
        ]
        if not selected:
            raise BundleError(
                f"no capture records in window [{start}, {end}] "
                f"({len(captures)} captures in the log)"
            )
    selected = _dedupe(selected)
    tids = sorted({r.get("trace") for r in selected})

    dropped_spans = []
    traces = assemble_traces(records, dropped=dropped_spans)
    journals = scan_journals(journal_dir)

    sessions: Dict[str, dict] = {}
    stateless: List[dict] = []
    for rec in selected:
        row = _act_row(rec)
        if rec.get("endpoint") == "session_act" and rec.get("session"):
            sess = sessions.setdefault(
                rec["session"], {"seed": None, "acts": []}
            )
            sess["acts"].append(row)
        else:
            stateless.append(row)
    for sess in sessions.values():
        # causal order within a session is the stamped seq (arrival
        # order `order` breaks ties for seq-less acts)
        sess["acts"].sort(
            key=lambda a: (
                a.get("seq") if a.get("seq") is not None else 1 << 60,
                a.get("order") or 0,
            )
        )

    # per-trace completeness: a bundle is whole or LOUDLY partial
    completeness = []
    session_missing: Dict[str, str] = {}
    for sid, sess in sessions.items():
        seqs = [
            a["seq"] for a in sess["acts"] if a.get("seq") is not None
        ]
        first = min(seqs) if seqs else None
        sess["first_seq"] = first
        if first is None or first <= 1:
            continue  # the session was created inside the window
        want = first - 1
        aligned = [
            e for e in journals.get(sid, [])
            if _entry_seq(e) == want
        ]
        if aligned:
            sess["seed"] = aligned[-1]
        else:
            have = sorted(
                {
                    s for s in (
                        _entry_seq(e) for e in journals.get(sid, [])
                    )
                    if s is not None
                }
            )
            session_missing[sid] = (
                f"journal snapshot at seq {want} for session {sid} "
                f"(found seqs {have or 'none'} — the bit-exact oracle "
                "only holds from an aligned snapshot; its staleness "
                "bound is the journal sync cadence)"
            )
    for tid in tids:
        missing = []
        recs = [r for r in selected if r.get("trace") == tid]
        for rec in recs:
            if rec.get("payload") is None:
                missing.append(
                    "capture payload (wire-encoded obs) for "
                    f"order {rec.get('order')}"
                )
            if rec.get("action") is None:
                missing.append(
                    "recorded action (the diff oracle) for "
                    f"order {rec.get('order')}"
                )
            sid = rec.get("session")
            if sid in session_missing:
                missing.append(session_missing[sid])
        if tid not in traces:
            missing.append(
                "assembled trace spans (no per-stage baseline"
                + (
                    f"; {len(dropped_spans)} span records in the log "
                    "had no joinable trace id"
                    if dropped_spans else ""
                )
                + ")"
            )
        completeness.append({
            "trace": tid,
            "replayable": not missing,
            "missing": missing,
        })

    steps = [
        r["step"] for r in selected
        if isinstance(r.get("step"), int)
    ]
    checkpoint_step = (
        max(set(steps), key=steps.count) if steps else None
    )

    times = [r.get("t") or 0 for r in selected]
    lo = min(times) - _FAULT_SLACK_S
    hi = max(times) + _FAULT_SLACK_S
    faults = [
        r for r in records
        if (
            r.get("kind") in ("fault_injected", "recovery")
            or (
                r.get("kind") == "lease"
                and r.get("event") in (
                    "expired", "fenced_write_refused"
                )
            )
            or (
                r.get("kind") == "session"
                and r.get("event") in (
                    "resumed", "reestablished", "drained"
                )
            )
        )
        and lo <= (r.get("t") or 0) <= hi
    ]

    recorded = _summarize_traces(
        [
            r for r in records
            if r.get("kind") == "span" and r.get("trace") in set(tids)
        ]
    )

    return {
        "bundle_version": BUNDLE_VERSION,
        "trace_id": trace_id,
        "window": list(window) if window is not None else None,
        "checkpoint_step": checkpoint_step,
        "acts_total": len(selected),
        "sessions": sessions,
        "stateless": stateless,
        "completeness": completeness,
        "replayable": all(c["replayable"] for c in completeness),
        "faults": faults,
        "recorded": recorded,
    }


def write_bundle(bundle: dict, path: str) -> None:
    tmp = path + ".tmp"
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(tmp, "w") as f:
        json.dump(bundle, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def load_bundle(path: str) -> dict:
    """Parse + version-check one bundle file; :class:`BundleError` on
    anything unreadable (the CLI maps it to exit 2)."""
    try:
        with open(path) as f:
            bundle = json.load(f)
    except OSError as e:
        raise BundleError(f"cannot read bundle {path}: {e}")
    except ValueError as e:
        raise BundleError(f"bundle {path} is not JSON: {e}")
    if not isinstance(bundle, dict) or "bundle_version" not in bundle:
        raise BundleError(
            f"{path} is not a replay bundle (no bundle_version)"
        )
    if bundle["bundle_version"] != BUNDLE_VERSION:
        raise BundleError(
            f"bundle version {bundle['bundle_version']} != supported "
            f"{BUNDLE_VERSION}"
        )
    return bundle


def action_match(recorded, replayed) -> bool:
    """The bit-exact oracle: both sides as float64 (JSON float repr
    round-trips float64 exactly, so parsed action lists compare at
    full precision), equal element-for-element or the replay FAILED."""
    try:
        a = np.asarray(recorded, np.float64)
        b = np.asarray(replayed, np.float64)
    except (TypeError, ValueError):
        return False
    return a.shape == b.shape and bool(np.array_equal(a, b))
