"""The run-event bus: typed, versioned JSONL records with pluggable sinks.

PRs 1-2 grew observability ad hoc — PhaseTimer spans, the StatsDrain,
``bench.py``'s ``update_tail_breakdown`` — each with its own output shape,
none visible during a real training run. This module is the ONE schema all
of them emit through: every record is a flat JSON object with a versioned
envelope (``v``, ``kind``, ``t``), and :func:`validate_event` is the single
source of truth for what each kind requires — used by the bus itself (an
invalid emit is a programming error and raises), by
``scripts/validate_events.py`` (artifact checking in ``check.sh``), and by
``tests/test_observability.py`` (schema round-trip).

Kinds:

* ``run_manifest`` — once per run, first record: config + config hash,
  jax/backend versions, device count, git sha. A JSONL file is
  self-describing: a reader never has to guess which code produced it.
* ``iteration`` — one per training iteration (``StatsLogger`` re-emits its
  JSONL row through the bus): the reference's seven stats plus the
  extended set, including the device-accumulated counters from
  ``obs/device_metrics.py``.
* ``phase`` — a named timing (PhaseTimer summaries, ``bench.py``'s
  update-tail phases): same schema for bench artifacts and training logs.
* ``health`` — a monitor finding (``obs/health.py``): check name, level,
  message, optional data.
* ``recompile`` — one XLA compilation observed by the recompile monitor
  (``obs/recompile.py``), flagged ``unexpected`` when it happened after
  the run was marked steady.
* ``fault_injected`` — one fault fired by the chaos injector
  (``resilience/inject.py``): kind, trigger point, the exact spec. A
  chaos run's event log is self-auditing — ``scripts/validate_events.py``
  checks every injected fault produced a matching detection/recovery
  record downstream.
* ``recovery`` — one recovery action taken by the resilience subsystem
  (``resilience/recovery.py``): what was done (``action``), why
  (``reason``), at which iteration.
* ``memory`` — device-memory accounting (``obs/memory.py``).
  ``scope="program"``: one jitted program's compiled
  ``memory_analysis()`` — argument/temp/output bytes plus a peak
  estimate — emitted once at first compile (HBM is the binding
  constraint at the flagship shapes; this is where an OOM is predicted
  instead of discovered). ``scope="live"``: per-iteration live-buffer
  and ``device.memory_stats()`` gauges, feeding the steady-state leak
  detector (``health:memory_leak``).
* ``status`` — the live introspection endpoint announcing itself
  (``obs/server.py``): the bound port and the paths served, so a log
  reader (or a human tailing the JSONL) knows where to ``curl`` while
  the run is in flight.
* ``serve`` — one micro-batch dispatched by the policy-serving tier
  (``serve/batcher.py``): requests coalesced, padded batch rung, queue
  depth left behind, oldest-request latency. ``obs/analyze.py``
  aggregates these into p50/p99 latency and actions/s so
  ``analyze_run.py --compare`` regression-gates serving runs like
  training runs.
* ``fleet`` — one member lifecycle transition recorded by the fleet
  orchestrator (``fleet/scheduler.py``): which member, which state
  (``FLEET_STATES``: launched / preempted / requeued / finished /
  failed / culled / respawned — the last is the PBT exploit/explore
  transition: a culled member reborn from the winner's checkpoint with
  perturbed hyperparameters), and the launch attempt it happened on. A
  fleet's event log is self-auditing the same way a chaos run's is —
  ``scripts/validate_events.py`` checks every ``preempted`` record is
  followed by the member's ``requeued`` or ``failed`` resolution (a
  preemption the scheduler never resolved means the requeue loop is
  broken).
* ``router`` — the replicated serving control plane
  (``serve/{replicaset,router}.py``), scope-discriminated like
  ``memory``: ``scope="replica"`` is one replica lifecycle transition
  (``ROUTER_REPLICA_STATES``: started / healthy / reloading / died /
  evicted / restarted / failed) as seen by the replica supervisor;
  ``scope="request"`` is one client request through the routing front
  end (end-to-end ``ms``, whether it succeeded, whether it took the
  transparent one-shot retry after a replica died mid-request);
  ``scope="host"`` (ISSUE 14) is one HOST health transition
  (``ROUTER_HOST_STATES``: ``suspect`` — transport strikes
  accumulating, the host's replicas held out of new session placement
  — / ``healthy``) from the multi-host degradation ladder. The
  log is self-auditing: ``scripts/validate_events.py`` checks every
  ``died`` replica has a later ``restarted``/``evicted`` resolution —
  a death the supervisor never acted on means the replica-restart
  loop is broken.
* ``lease`` — one lease-liveness transition in the multi-host serving
  plane (ISSUE 14: ``serve/replicaset.py`` grants/renews/expires;
  ``serve/session.CarryJournal`` refuses fenced writes):
  ``LEASE_EVENTS`` — ``granted`` (a replica's first answered healthz
  of an incarnation opens an epoch-numbered lease), ``renewed``
  (throttled), ``expired`` (renewals starved past the TTL — the
  eviction trigger for a partitioned host, since a failed poll alone
  proves nothing there), and ``fenced_write_refused`` (a
  partitioned-but-alive ZOMBIE tried to journal a session the router
  already resumed elsewhere — the write was dropped; carries the
  ``session``). Self-auditing: the validator FAILS an ``expired``
  lease with no later same-replica died/evicted resolution (or
  re-grant) — an expiry nothing acted on means the liveness loop is
  broken.
* ``session`` — one session lifecycle transition in the recurrent
  serving protocol (``serve/session.py`` stores on the replicas,
  ``serve/router.py`` affinity): ``SESSION_EVENTS`` — ``created``
  (replica minted carry), ``resumed`` (the router re-created the
  session FROM the dead replica's journaled carry — lossless failover;
  carries ``steps`` replayed and the journal ``lag``),
  ``reestablished`` (the fresh-carry fallback when no journal entry
  existed), ``expired`` (TTL eviction), ``evicted`` (capacity eviction
  from the bounded store), ``episode`` (the router booked one
  client-reported episode return against the answering replica —
  carries ``replica``, ``ep_return``, ``ep_steps``; the realized-return
  feed the reward-aware canary gate and the fleet feedback loop read).
  ``resumed`` vs ``reestablished`` is the failover-quality
  discriminator ``obs/analyze.py`` reports.
* ``canary`` — one gated-deployment transition
  (``serve/replicaset.CanaryController``): which checkpoint ``step``,
  which ``replica`` wore it, and the lifecycle ``event``
  (``CANARY_EVENTS``: ``started`` / ``promoted`` / ``rolled_back``,
  rolled_back carrying a ``reason``). The log is self-auditing the
  same way the fleet's is: ``scripts/validate_events.py`` FAILS a
  ``started`` with no later terminal ``promoted``/``rolled_back`` for
  the same step — an unresolved canary means the gate loop is broken.
* ``promote`` — one train→serve promotion transition
  (``fleet/promote.PromotionController``): which fleet ``member``
  supplies the weights, which serving-side ``step`` they publish as,
  and the lifecycle ``event`` (``PROMOTE_EVENTS``: ``candidate`` —
  winner picked and publish begun — / ``canary`` — marker-gated
  checkpoint published, the serving canary gate is driving — /
  ``promoted`` / ``rejected`` / ``rolled_back`` terminals, plus
  ``feedback`` — served realized-return stats booked back for the next
  fleet round's scoring). Self-auditing like the canary's:
  ``scripts/validate_events.py`` FAILS a ``candidate`` with no later
  same-step terminal — an unresolved promotion means the controller
  died and nothing converged it (the crash-safety contract).
* ``span`` — one finished request-trace span (ISSUE 15:
  ``obs/trace.py`` — the serving plane's per-request attribution
  layer): 128-bit ``trace`` id (minted at the router's public edge or
  accepted from the client's ``X-Trace-Id`` header), 64-bit ``span``
  id, optional ``parent`` (``remote: true`` when the parent was
  emitted by ANOTHER process's log — the id arrived over the
  propagation headers), ``name`` (the stage: ``router.act`` /
  ``router.dispatch`` / ``router.retry`` / ``router.takeover`` /
  ``replica.session_act`` / ``batch.queue_wait`` /
  ``engine.step_batch`` / ``journal.sync`` …), ``start`` (unix
  seconds) and ``dur_ms`` (``None`` ONLY for a span that was never
  terminated). Coalesced session acts share ONE ``engine.step_batch``
  span id across their traces (the shared epoch span — what makes
  epoch-induced tail latency attributable). Self-auditing:
  ``scripts/validate_events.py`` FAILS an orphan span (non-remote
  parent never emitted in the same file), an unterminated root span,
  and a retried request whose trace lacks a retry span.
* ``metric_sample`` — one polled value of one series on one scrape
  target (ISSUE 20: ``obs/aggregate.MetricsAggregator`` — the live
  aggregation plane): ``target`` (the registered endpoint's name),
  ``series`` (the flattened ``/status`` key or Prometheus sample
  name), ``value`` (numeric, or ``null`` when the target could not be
  scraped), and ``stale`` (the target missed its scrape budget — a
  failed scrape marks the target stale instead of blocking the poll
  loop, and staleness is itself an alertable condition). The
  aggregator emits a bounded WATCHED subset of what it stores (the
  per-target ``up`` series plus the series its alert rules read), so
  the log carries proof the aggregation plane was armed without
  carrying every ring buffer.
* ``alert`` — one alert-lifecycle transition (ISSUE 20:
  ``obs/alerts.AlertEngine`` — declarative threshold / rate-of-change
  / two-window burn-rate rules evaluated over the aggregated series):
  ``rule`` (the rule's name), ``state`` (``ALERT_STATES``: ``firing``
  / ``resolved``), and — on firing records (``_ALERT_SCOPED``) — the
  evaluation ``window_s``, the observed ``value``, and the
  ``threshold`` it breached; ``target`` (which scrape target the rule
  fired for) rides along as an optional field. Self-auditing both
  ways (``scripts/validate_events.py``): an armed chaos fault in a
  log that carries alert events must be matched by a FIRING alert of
  the right rule, every firing alert must RESOLVE, and a firing alert
  with no matching cause in its window FAILS the run — the
  zero-false-positive contract.
* ``autoscale`` — one elastic-serving control action (ISSUE 12:
  ``serve/autoscaler.py`` decisions, ``serve/router.py`` sheds):
  ``AUTOSCALE_EVENTS`` — ``scale_out`` (a new replica launched from
  the router's own metrics), ``drain_started`` / ``drain_completed``
  / ``drain_aborted`` (the lossless scale-in protocol: sessions
  resumed onto survivors from the carry journal before the victim is
  terminated; a stalled drain aborts back to rotation), and ``shed``
  (overload admission: deadline-unmeetable 503s, retry-budget skips,
  stateless-headroom refusals — aggregated with a ``count``). Every
  record carries the ``reason`` (with the trigger metrics attached);
  scale/drain records name their ``replica``. The log is
  self-auditing: ``scripts/validate_events.py`` FAILS a
  ``drain_started`` with no later same-replica ``drain_completed``/
  ``drain_aborted`` terminal — a drain that neither finished nor
  aborted means sessions may be stranded on a half-retired replica.

Sinks are append-only and flush-on-write; the JSONL sink repairs a
crash-truncated final line on open (``utils/metrics.repair_jsonl_tail``),
so a killed run never poisons the next append. ``EventBus.emit`` is
thread-safe — the async pipeline's drain thread emits iteration events
while the main thread emits phase/recompile events.
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys
import threading
import time
from typing import IO, Any, Callable, Iterable, Optional

from trpo_tpu.utils.metrics import repair_jsonl_tail

__all__ = [
    "SCHEMA_VERSION",
    "EVENT_KINDS",
    "FLEET_STATES",
    "ROUTER_REPLICA_STATES",
    "ROUTER_HOST_STATES",
    "SESSION_EVENTS",
    "CANARY_EVENTS",
    "PROMOTE_EVENTS",
    "AUTOSCALE_EVENTS",
    "LEASE_EVENTS",
    "ALERT_STATES",
    "EventBus",
    "JsonlSink",
    "ConsoleSink",
    "validate_event",
    "manifest_fields",
]

SCHEMA_VERSION = 1

# member lifecycle states the fleet orchestrator may record (the state
# machine lives in fleet/scheduler.py; the vocabulary lives HERE so the
# validator needs no fleet import)
FLEET_STATES = (
    "launched", "preempted", "requeued", "finished", "failed", "culled",
    "respawned",
)

# replica lifecycle states the serving replica supervisor may record
# (the state machine lives in serve/replicaset.py; the vocabulary lives
# HERE so the validator needs no serve import — the FLEET_STATES pattern).
# `draining`/`drained` are the elastic scale-in states (ISSUE 12): a
# draining replica leaves stateless rotation while its sessions resume
# elsewhere; `drained` is the terminal record of a session-empty replica
# leaving the set.
ROUTER_REPLICA_STATES = (
    "started", "healthy", "reloading", "draining", "drained", "died",
    "evicted", "restarted", "failed",
)

# session lifecycle transitions the recurrent serving protocol records
# (stores live in serve/session.py, router affinity in serve/router.py);
# `resumed` = re-created from a journaled carry (lossless failover),
# `reestablished` = the fresh-carry fallback when no journal entry
# existed — the discriminator the failover report reads; `drained`
# (ISSUE 12) = the same lossless journal move performed ON PURPOSE by
# a scale-in drain, kept distinct so planned migrations never inflate
# the failover-quality metrics
SESSION_EVENTS = (
    "created", "resumed", "reestablished", "expired", "evicted",
    "drained", "episode",
)

# gated-deployment transitions the canary controller records (the state
# machine lives in serve/replicaset.CanaryController; the vocabulary
# lives HERE so the validator needs no serve import — the FLEET_STATES
# pattern). `started` must resolve to `promoted` or `rolled_back`.
CANARY_EVENTS = ("started", "promoted", "rolled_back")

# train→serve promotion transitions the flywheel controller records
# (the state machine lives in fleet/promote.PromotionController; the
# vocabulary lives HERE so the validator needs no fleet import — the
# FLEET_STATES pattern). `candidate` must resolve to a same-step
# `promoted` / `rejected` / `rolled_back` terminal — possibly by a
# RESTARTED controller converging a predecessor's half-done promotion;
# `feedback` books served realized-return stats for fleet re-scoring.
PROMOTE_EVENTS = (
    "candidate", "canary", "promoted", "rejected", "rolled_back",
    "feedback",
)

# elastic-serving control actions (ISSUE 12: serve/autoscaler.py and
# the router's overload sheds; vocabulary HERE so the validator needs
# no serve import). `drain_started` must resolve to a same-replica
# `drain_completed` or `drain_aborted`.
AUTOSCALE_EVENTS = (
    "scale_out", "drain_started", "drain_completed", "drain_aborted",
    "shed",
)

# host health transitions in the multi-host serving plane (ISSUE 14:
# the state machine lives in serve/replicaset.py; vocabulary HERE so
# the validator needs no serve import — the FLEET_STATES pattern).
# `suspect` = transport strikes accumulated: the host's replicas are
# held out of NEW session placement while the lease decides.
ROUTER_HOST_STATES = ("suspect", "healthy")

# lease-liveness transitions (ISSUE 14: serve/replicaset.py grants/
# renews/expires; serve/session.CarryJournal emits the fencing
# refusals). `expired` must resolve to the replica's died/evicted (or
# a re-grant after the partition heals) — the died-needs-terminal
# pattern.
LEASE_EVENTS = ("granted", "renewed", "expired", "fenced_write_refused")

# Shadow-replay lifecycle (ISSUE 18, scripts/replay_run.py): `begin`
# announces the bundle and how many captured acts it will drive, one
# `act` per replayed request, one `verdict` per bit-exact action diff,
# `complete` closes with the tallies — the validator pairs them.
REPLAY_EVENTS = ("begin", "act", "verdict", "complete")

# alert lifecycle (ISSUE 20, obs/alerts.AlertEngine; vocabulary HERE
# so the validator needs no obs.alerts import — the FLEET_STATES
# pattern). Every `firing` must resolve to a later `resolved` for the
# same (rule, target) — the started-needs-terminal pattern.
ALERT_STATES = ("firing", "resolved")

_SCALAR = (bool, int, float, str, type(None))

# kind -> {field: predicate}; extra fields are always allowed (the schema
# is versioned and additive — readers must tolerate fields they don't know)
_REQUIRED = {
    "run_manifest": {
        "schema": lambda v: v == "trpo-tpu-events",
        "jax_version": lambda v: isinstance(v, str),
        "backend": lambda v: isinstance(v, str),
        "config_hash": lambda v: isinstance(v, str) and len(v) >= 8,
        "config": lambda v: v is None or isinstance(v, dict),
    },
    "iteration": {
        "iteration": lambda v: isinstance(v, int) and not isinstance(v, bool),
        "stats": lambda v: isinstance(v, dict)
        and all(isinstance(x, _SCALAR) for x in v.values()),
    },
    "phase": {
        "name": lambda v: isinstance(v, str) and v,
        "ms": lambda v: isinstance(v, (int, float))
        and not isinstance(v, bool),
    },
    "health": {
        "check": lambda v: isinstance(v, str) and v,
        "level": lambda v: v in ("info", "warn", "error"),
        "message": lambda v: isinstance(v, str),
    },
    "recompile": {
        "program": lambda v: isinstance(v, str) and v,
        "count": lambda v: isinstance(v, int) and not isinstance(v, bool),
        "unexpected": lambda v: isinstance(v, bool),
    },
    "fault_injected": {
        "fault": lambda v: isinstance(v, str) and v,
        "at": lambda v: isinstance(v, int) and not isinstance(v, bool),
        "spec": lambda v: isinstance(v, str) and v,
    },
    "recovery": {
        "action": lambda v: isinstance(v, str) and v,
        "reason": lambda v: isinstance(v, str) and v,
        "iteration": lambda v: isinstance(v, int)
        and not isinstance(v, bool),
    },
    "memory": {
        "scope": lambda v: v in ("program", "live"),
    },
    "status": {
        "port": lambda v: isinstance(v, int)
        and not isinstance(v, bool)
        and 0 < v < 65536,
    },
    "serve": {
        # one record per micro-batch the serving tier dispatched
        # (serve/batcher.py): how many real requests coalesced, which
        # ladder rung the batch padded to, what was left waiting, and
        # the oldest coalesced request's end-to-end latency
        "requests": lambda v: isinstance(v, int)
        and not isinstance(v, bool)
        and v >= 1,
        "padded": lambda v: isinstance(v, int)
        and not isinstance(v, bool)
        and v >= 1,
        "queue_depth": lambda v: isinstance(v, int)
        and not isinstance(v, bool)
        and v >= 0,
        "latency_ms": lambda v: isinstance(v, (int, float))
        and not isinstance(v, bool)
        and v >= 0,
    },
    "fleet": {
        # one member lifecycle transition (fleet/scheduler.py): member
        # id, the state entered, and the 1-based launch attempt it
        # happened on (0 for records before any launch)
        "member": lambda v: isinstance(v, str) and v,
        "state": lambda v: v in FLEET_STATES,
        "attempt": lambda v: isinstance(v, int)
        and not isinstance(v, bool)
        and v >= 0,
    },
    "router": {
        # scope-discriminated (like `memory`): "replica" lifecycle
        # transitions vs per-"request" routing records vs per-"host"
        # health transitions (ISSUE 14) — the per-scope required
        # fields live in _ROUTER_SCOPED below
        "scope": lambda v: v in ("replica", "request", "host"),
    },
    "lease": {
        # one lease-liveness transition (ISSUE 14); per-event required
        # fields (epoch on lifecycle records, session on fencing
        # refusals) live in _LEASE_SCOPED below. `host` rides along as
        # an optional field on multi-host records.
        "replica": lambda v: isinstance(v, str) and v,
        "event": lambda v: v in LEASE_EVENTS,
    },
    "session": {
        # one session lifecycle transition (serve/session.py store,
        # serve/router.py affinity); `replica` rides along as an
        # optional field, `steps`/`lag` on resumed records
        "session": lambda v: isinstance(v, str) and v,
        "event": lambda v: v in SESSION_EVENTS,
    },
    "canary": {
        # one gated-deployment transition
        # (serve/replicaset.CanaryController); `reason` rides along on
        # rolled_back records
        "step": lambda v: isinstance(v, int) and not isinstance(v, bool),
        "event": lambda v: v in CANARY_EVENTS,
        "replica": lambda v: isinstance(v, str) and v,
    },
    "promote": {
        # one train→serve promotion transition
        # (fleet/promote.PromotionController): source fleet member,
        # the serving-side step the weights publish as, lifecycle
        # event; `src_step`/`reason`/`score`/`episodes`/`mean_return`
        # ride along as optional fields
        "member": lambda v: isinstance(v, str) and v,
        "event": lambda v: v in PROMOTE_EVENTS,
        "step": lambda v: isinstance(v, int) and not isinstance(v, bool),
    },
    "span": {
        # one finished request-trace span (ISSUE 15, obs/trace.py);
        # `parent`/`remote`/`process`/`host` and stage attrs ride
        # along as optional fields. dur_ms is REQUIRED but nullable:
        # None marks a span that was never terminated — representable
        # so the validator can FAIL an unterminated root instead of
        # the failure mode being an invisible missing record.
        "trace": lambda v: isinstance(v, str) and 8 <= len(v) <= 64,
        "span": lambda v: isinstance(v, str) and v,
        "name": lambda v: isinstance(v, str) and v,
        "start": lambda v: isinstance(v, (int, float))
        and not isinstance(v, bool)
        and v >= 0,
        "dur_ms": lambda v: v is None
        or (
            isinstance(v, (int, float))
            and not isinstance(v, bool)
            and v >= 0
        ),
    },
    "autoscale": {
        # one elastic-serving control action (serve/autoscaler.py /
        # the router's overload sheds); every record says WHY — the
        # trigger metrics (p99_ms, inflight, pressure) ride along as
        # optional fields. Per-event required fields (replica on
        # scale/drain records, count on sheds) live in
        # _AUTOSCALE_SCOPED below.
        "event": lambda v: v in AUTOSCALE_EVENTS,
        "reason": lambda v: isinstance(v, str) and v,
    },
    "capture": {
        # one captured request (ISSUE 18, obs/capture.py): the
        # replayable inputs of one sampled/forced request — path,
        # arrival order, answered status. `payload` (the base64
        # wire-frame obs), `session`, `seq`, `step` (the answering
        # replica's loaded checkpoint step), `action` (the answered
        # action — the replay diff's recorded side), `replica`,
        # `forced`, and the writer's `process`/`host` stamps ride
        # along as optional fields: a body the writer could not parse
        # still produces a record (the bundle builder reports it as
        # non-replayable instead of the miss being invisible).
        "trace": lambda v: isinstance(v, str) and 8 <= len(v) <= 64,
        "order": lambda v: isinstance(v, int)
        and not isinstance(v, bool)
        and v >= 0,
        "path": lambda v: isinstance(v, str) and v.startswith("/"),
        "endpoint": lambda v: v in ("act", "session_act"),
        "status": lambda v: isinstance(v, int) and not isinstance(v, bool),
    },
    "replay": {
        # one shadow-replay lifecycle record (ISSUE 18,
        # scripts/replay_run.py); per-event required fields live in
        # _REPLAY_SCOPED below. The validator's replay-complete
        # contracts pair these: every captured act announced by
        # `begin` must have an `act` record, every `act` its diff
        # `verdict`.
        "event": lambda v: v in REPLAY_EVENTS,
    },
    "metric_sample": {
        # one polled value of one series on one scrape target (ISSUE
        # 20, obs/aggregate.MetricsAggregator). `value` is nullable:
        # a failed scrape still produces the target's `up` sample
        # (value 0.0) and marks it `stale` — the miss is representable
        # instead of invisible. `stale` rides along as an optional
        # bool.
        "target": lambda v: isinstance(v, str) and v,
        "series": lambda v: isinstance(v, str) and v,
        "value": lambda v: v is None
        or (isinstance(v, (int, float)) and not isinstance(v, bool)),
    },
    "alert": {
        # one alert-lifecycle transition (ISSUE 20,
        # obs/alerts.AlertEngine); per-state required fields (the
        # evaluation evidence on firing records) live in
        # _ALERT_SCOPED below. `target` (which scrape target the rule
        # fired for) rides along as an optional field.
        "rule": lambda v: isinstance(v, str) and v,
        "state": lambda v: v in ALERT_STATES,
    },
}

_BYTES = lambda v: isinstance(v, int) and not isinstance(v, bool) and v >= 0

# memory events are scope-discriminated: the per-scope required fields
# (checked by validate_event after the flat table above passes)
_MEMORY_SCOPED = {
    "program": {
        "program": lambda v: isinstance(v, str) and v,
        "argument_bytes": _BYTES,
        "output_bytes": _BYTES,
        "temp_bytes": _BYTES,
    },
    "live": {
        "iteration": lambda v: isinstance(v, int)
        and not isinstance(v, bool),
        "live_buffer_bytes": _BYTES,
    },
}

# router events are scope-discriminated the same way (checked by
# validate_event after the flat table above passes)
_ROUTER_SCOPED = {
    "replica": {
        "replica": lambda v: isinstance(v, str) and v,
        "state": lambda v: v in ROUTER_REPLICA_STATES,
    },
    "request": {
        "ms": lambda v: isinstance(v, (int, float))
        and not isinstance(v, bool)
        and v >= 0,
        "ok": lambda v: isinstance(v, bool),
        "retried": lambda v: isinstance(v, bool),
    },
    "host": {
        "host": lambda v: isinstance(v, str) and v,
        "state": lambda v: v in ROUTER_HOST_STATES,
    },
}

_INT = lambda v: isinstance(v, int) and not isinstance(v, bool)

# lease events are EVENT-discriminated (the autoscale pattern): the
# lifecycle records carry the lease's epoch number; a fencing refusal
# names the session whose write was dropped
_LEASE_SCOPED = {
    "granted": {"epoch": _INT},
    "renewed": {"epoch": _INT},
    "expired": {"epoch": _INT},
    "fenced_write_refused": {
        "session": lambda v: isinstance(v, str) and v,
    },
}

# autoscale events are EVENT-discriminated the same way: scale/drain
# actions name the replica they act on (the validator's drain-terminal
# pairing needs it); sheds aggregate and carry how many they stand for
_AUTOSCALE_SCOPED = {
    "scale_out": {"replica": lambda v: isinstance(v, str) and v},
    "drain_started": {"replica": lambda v: isinstance(v, str) and v},
    "drain_completed": {"replica": lambda v: isinstance(v, str) and v},
    "drain_aborted": {"replica": lambda v: isinstance(v, str) and v},
    "shed": {
        "count": lambda v: isinstance(v, int)
        and not isinstance(v, bool)
        and v >= 1,
    },
}

# replay events are EVENT-discriminated: begin/complete carry the
# tallies the validator's replay-complete pairing counts against, each
# act/verdict names the captured request it answers by (trace, order)
_REPLAY_SCOPED = {
    "begin": {
        "acts": lambda v: isinstance(v, int)
        and not isinstance(v, bool)
        and v >= 0,
    },
    "act": {
        "trace": lambda v: isinstance(v, str) and 8 <= len(v) <= 64,
        "order": lambda v: isinstance(v, int)
        and not isinstance(v, bool)
        and v >= 0,
        "status": _INT,
    },
    "verdict": {
        "trace": lambda v: isinstance(v, str) and 8 <= len(v) <= 64,
        "order": lambda v: isinstance(v, int)
        and not isinstance(v, bool)
        and v >= 0,
        "match": lambda v: isinstance(v, bool),
    },
    "complete": {
        "acts": lambda v: isinstance(v, int)
        and not isinstance(v, bool)
        and v >= 0,
        "mismatches": lambda v: isinstance(v, int)
        and not isinstance(v, bool)
        and v >= 0,
    },
}

# alert records are STATE-discriminated: a firing alert must carry its
# evaluation evidence (the window it was judged over, the observed
# value, the threshold it breached) — the validator's zero-false-
# positive contract reads them; `resolved` needs nothing extra beyond
# naming the rule it closes.
_NUM = (
    lambda v: isinstance(v, (int, float))
    and not isinstance(v, bool)
)
_ALERT_SCOPED = {
    "firing": {
        "window_s": lambda v: _NUM(v) and v >= 0,
        "value": _NUM,
        "threshold": _NUM,
    },
    "resolved": {},
}

EVENT_KINDS = tuple(sorted(_REQUIRED))


def validate_event(rec: Any) -> list:
    """Schema-check one event record; returns a list of error strings
    (empty = valid). Works on freshly built records and on records parsed
    back from JSONL — the round-trip invariant the tests pin."""
    if not isinstance(rec, dict):
        return ["record is not a JSON object"]
    errs = []
    if rec.get("v") != SCHEMA_VERSION:
        errs.append(f"v must be {SCHEMA_VERSION}, got {rec.get('v')!r}")
    if not isinstance(rec.get("t"), (int, float)) or isinstance(
        rec.get("t"), bool
    ):
        errs.append("t (unix seconds) missing or non-numeric")
    kind = rec.get("kind")
    required = _REQUIRED.get(kind)
    if required is None:
        errs.append(f"unknown kind {kind!r} (have {list(EVENT_KINDS)})")
        return errs
    for field, ok in required.items():
        if field not in rec:
            errs.append(f"{kind}: missing required field {field!r}")
        elif not ok(rec[field]):
            errs.append(f"{kind}: field {field!r} failed its check "
                        f"(got {rec[field]!r})")
    for scoped_kind, discriminator, table in (
        ("memory", "scope", _MEMORY_SCOPED),
        ("router", "scope", _ROUTER_SCOPED),
        ("autoscale", "event", _AUTOSCALE_SCOPED),
        ("lease", "event", _LEASE_SCOPED),
        ("replay", "event", _REPLAY_SCOPED),
        ("alert", "state", _ALERT_SCOPED),
    ):
        if kind != scoped_kind:
            continue
        # discriminated record: each scope/event has its own required set
        tag = rec.get(discriminator)
        for field, ok in table.get(tag, {}).items():
            if field not in rec:
                errs.append(
                    f"{kind}[{tag}]: missing required field {field!r}"
                )
            elif not ok(rec[field]):
                errs.append(
                    f"{kind}[{tag}]: field {field!r} failed "
                    f"its check (got {rec[field]!r})"
                )
    return errs


def _json_safe(x):
    """Recursively coerce numpy/jax scalars, tuples, and unknown objects
    into JSON-representable values (the bus sanitizes every record before
    validating/writing, so callers may pass device scalars directly)."""
    if isinstance(x, dict):
        return {str(k): _json_safe(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_json_safe(v) for v in x]
    if isinstance(x, _SCALAR):
        return x
    if hasattr(x, "item"):
        try:
            return _json_safe(x.item())
        except Exception:
            return str(x)
    return str(x)


# ---------------------------------------------------------------------------
# sinks
# ---------------------------------------------------------------------------


class JsonlSink:
    """Append events to a JSONL file: crash-safe open (a partial final
    line from a killed previous run is truncated away first), one
    ``write`` call per record, flush-on-write."""

    def __init__(self, path: str):
        self.path = path
        repair_jsonl_tail(path)
        self._f: Optional[IO] = open(path, "a")

    def write(self, rec: dict) -> None:
        if self._f is None:
            raise RuntimeError(f"JsonlSink({self.path}) is closed")
        self._f.write(json.dumps(rec) + "\n")
        self._f.flush()

    def write_batch(self, recs: list) -> None:
        """Many records, ONE file write + flush (ISSUE 15): the trace
        writer drains dozens of spans per wake, and per-record
        write+flush under the bus lock measurably stalls the serving
        dispatcher threads contending for it. Same crash semantics —
        a torn tail still repairs on the next open."""
        if self._f is None:
            raise RuntimeError(f"JsonlSink({self.path}) is closed")
        self._f.write("".join(json.dumps(r) + "\n" for r in recs))
        self._f.flush()

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


class ConsoleSink:
    """One-line console rendering, optionally restricted to a set of
    kinds (the CLI's ``--health-checks`` prints health/recompile findings
    without drowning stdout in per-iteration records)."""

    def __init__(self, stream: Optional[IO] = None,
                 kinds: Optional[Iterable[str]] = None):
        self.stream = stream
        self.kinds = None if kinds is None else frozenset(kinds)

    def write(self, rec: dict) -> None:
        if self.kinds is not None and rec.get("kind") not in self.kinds:
            return
        stream = self.stream if self.stream is not None else sys.stderr
        body = {k: v for k, v in rec.items() if k not in ("v", "kind", "t")}
        print(f"[obs:{rec.get('kind')}] {json.dumps(body)}", file=stream)

    def close(self) -> None:
        pass


class _CallbackSink:
    def __init__(self, fn: Callable[[dict], Any]):
        self._fn = fn

    def write(self, rec: dict) -> None:
        self._fn(rec)

    def close(self) -> None:
        pass


class EventBus:
    """Validated, thread-safe fan-out of event records to sinks.

    Sinks are objects with ``write(rec)``/``close()`` or bare callables
    (wrapped). ``emit`` sanitizes the record (numpy/jax scalars → Python),
    validates it against the schema (raising on failure — an invalid
    event is a bug in the emitter, never data), then writes to every sink
    under one lock so concurrent emitters (main loop, drain thread,
    logging handlers) interleave whole records, not bytes."""

    def __init__(self, *sinks):
        self._sinks = [self._wrap(s) for s in sinks]
        self._lock = threading.Lock()

    @staticmethod
    def _wrap(sink):
        return sink if hasattr(sink, "write") else _CallbackSink(sink)

    def add_sink(self, sink) -> None:
        with self._lock:
            self._sinks.append(self._wrap(sink))

    def emit(self, kind: str, **fields) -> dict:
        rec = _json_safe(
            {"v": SCHEMA_VERSION, "kind": kind, "t": time.time(), **fields}
        )
        errs = validate_event(rec)
        if errs:
            raise ValueError(f"invalid {kind!r} event: {errs}")
        with self._lock:
            for s in self._sinks:
                s.write(rec)
        return rec

    def emit_batch(self, kind: str, fields_list) -> list:
        """Emit many same-kind records, holding the sink lock ONCE and
        letting batch-capable sinks (``JsonlSink.write_batch``) write
        them in one IO call (ISSUE 15: the trace writer's drain — the
        per-record flush was the measurable hot-path cost). Records are
        sanitized and validated exactly as :meth:`emit` would."""
        recs = []
        for fields in fields_list:
            rec = _json_safe(
                {"v": SCHEMA_VERSION, "kind": kind, "t": time.time(),
                 **fields}
            )
            errs = validate_event(rec)
            if errs:
                raise ValueError(f"invalid {kind!r} event: {errs}")
            recs.append(rec)
        if not recs:
            return recs
        with self._lock:
            for s in self._sinks:
                batch = getattr(s, "write_batch", None)
                if batch is not None:
                    batch(recs)
                else:
                    for rec in recs:
                        s.write(rec)
        return recs

    def close(self) -> None:
        with self._lock:
            for s in self._sinks:
                s.close()
            self._sinks = []


# ---------------------------------------------------------------------------
# run manifest
# ---------------------------------------------------------------------------


def _git_sha() -> Optional[str]:
    """Repo HEAD sha, or None (not a checkout, no git binary, …) — the
    manifest must never fail a run over provenance lookup."""
    root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            capture_output=True, text=True, timeout=5, cwd=root,
        )
        sha = out.stdout.strip()
        return sha if out.returncode == 0 and sha else None
    except Exception:
        return None


def manifest_fields(config: Any = None, extra: Optional[dict] = None) -> dict:
    """The ``run_manifest`` payload: config (dataclass or dict) + a stable
    hash of it, jax/backend/device info, git sha. ``extra`` merges on top
    (driver name, env id, bench parameters, …)."""
    import dataclasses

    import jax

    cfg_dict = None
    if config is not None:
        cfg_dict = (
            dataclasses.asdict(config)
            if dataclasses.is_dataclass(config)
            else dict(config)
        )
        cfg_dict = _json_safe(cfg_dict)
    payload = json.dumps(cfg_dict, sort_keys=True, default=str)
    fields = {
        "schema": "trpo-tpu-events",
        "jax_version": jax.__version__,
        "backend": jax.default_backend(),
        "device_count": jax.device_count(),
        "config": cfg_dict,
        "config_hash": hashlib.sha256(payload.encode()).hexdigest()[:16],
        "git_sha": _git_sha(),
    }
    if extra:
        fields.update(_json_safe(extra))
    return fields
