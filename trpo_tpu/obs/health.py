"""Health monitor: watch the iteration-event stream for known failure
signatures and surface them as ``health`` events.

The reference's only health check is the NaN-entropy ``exit(-1)``
(``trpo_inksci.py:172-173``). The r04/r05 solver studies surfaced richer
signatures worth watching continuously: KL-cap rollback STREAKS (the
residual-aware solve tripled rollbacks before ``linesearch_kl_cap``
landed), explained-variance collapse (a critic gone bad poisons every
subsequent advantage estimate), nonfinite-guard trips inside the update
(caught on device before they reach the entropy stat), — async driver
only — the StatsDrain queue hitting its bound (stop conditions are
lagging; the backpressure documented in ``utils/async_pipe.py`` is
engaged), and — with ``--memory-accounting`` — live device bytes growing
monotonically across a steady-state window (``observe_memory``, fed by
``obs/memory.MemoryMonitor``: a leaked buffer per iteration kills a
multi-hour run at an hour no log explains). Findings go through the event bus, so the pluggable sinks
(console, JSONL, callback) all see one schema.

Warnings are transition-gated: a streak emits when it CROSSES the
threshold, not once per iteration while it persists — a 2000-iteration
run with a bad phase produces a handful of findings, not a flood.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

__all__ = ["HealthConfig", "HealthMonitor"]


@dataclasses.dataclass
class HealthConfig:
    rollback_streak: int = 3       # consecutive KL rollbacks → warn
    ev_collapse: float = -0.5      # explained variance below this → warn
    ev_warmup_iterations: int = 10  # EV is legitimately garbage early on
    memory_leak_window: int = 8    # consecutive iterations of strictly
    #                                growing live bytes → warn (a steady-
    #                                state training loop reuses donated
    #                                buffers; sustained monotone growth
    #                                means something retains a reference
    #                                per iteration)
    memory_leak_min_growth: int = 1 << 20  # total growth over the window
    #                                must exceed this (bytes) — jitter in
    #                                small host-side arrays is not a leak
    memory_leak_warmup: int = 2    # first iterations allocate legitimately
    #                                (compiles, carry buffers): skipped


class HealthMonitor:
    """Evaluate health rules against each iteration's host stats.

    ``observe_iteration`` returns the findings it emitted (empty list =
    healthy), so callers without a bus can still branch on them."""

    def __init__(self, bus=None, config: Optional[HealthConfig] = None):
        self.bus = bus
        self.cfg = config or HealthConfig()
        self._rollback_streak = 0
        self._streak_reported = False
        self._ev_reported = False
        self._drain_reported = False
        self._prev_fallbacks: Optional[int] = None  # solve-ladder counter
        self._pinned_reported = False
        self._mem_samples: list = []   # live-bytes window (leak rule)
        self._mem_seen = 0
        self._leak_reported = False
        self.findings: list = []

    def _emit(self, check: str, level: str, message: str,
              iteration: Optional[int] = None, **data) -> dict:
        finding = {"check": check, "level": level, "message": message}
        if iteration is not None:
            finding["iteration"] = iteration
        if data:
            finding["data"] = data
        self.findings.append(finding)
        if self.bus is not None:
            self.bus.emit("health", **finding)
        return finding

    def observe_iteration(self, iteration: int, stats: dict) -> list:
        out = []
        ent = stats.get("entropy")
        if ent is not None and ent != ent:  # NaN
            out.append(self._emit(
                "nan_entropy", "error",
                "policy entropy is NaN — the NaN abort will fire",
                iteration,
            ))
        if stats.get("nan_guard"):
            out.append(self._emit(
                "nan_guard", "error",
                "nonfinite gradient/surrogate/entropy inside the update",
                iteration,
            ))
        if stats.get("kl_rolled_back"):
            self._rollback_streak += 1
            if (
                self._rollback_streak >= self.cfg.rollback_streak
                and not self._streak_reported
            ):
                self._streak_reported = True
                out.append(self._emit(
                    "kl_rollback_streak", "warn",
                    f"{self._rollback_streak} consecutive KL rollbacks — "
                    "the quadratic step model is miscalibrated (consider "
                    "linesearch_kl_cap / adaptive_damping)",
                    iteration,
                    streak=self._rollback_streak,
                ))
        else:
            self._rollback_streak = 0
            self._streak_reported = False
        # solver precision ladder (ISSUE 8): every rise of the
        # run-cumulative fallback counter is one audit that failed its
        # cosine floor — emitted per rise (fallbacks are at most one per
        # solve_audit_every updates, never a flood), and
        # validate_events.py REQUIRES the pairing, so the emission here
        # is part of the event-log contract, not just advice
        fb = stats.get("fallbacks")
        if fb is not None:
            # baseline 0, not None: the run-cumulative counter starts at
            # 0 by construction (trpo.init_ladder), so a fallback on the
            # VERY FIRST update (the audit always fires at step 0) must
            # report too. A resumed run's first row re-reports the
            # pre-resume total once — informative, and it keeps the
            # validator's pairing rule satisfiable on resumed logs.
            prev = (
                0 if self._prev_fallbacks is None else self._prev_fallbacks
            )
            if fb > prev:
                out.append(self._emit(
                    "solve_fallback", "warn",
                    "solve audit cosine fell below the floor — the "
                    "update used the f32/full-batch solution "
                    f"(fallbacks total {fb})",
                    iteration,
                    fallbacks=fb,
                    solve_cosine=stats.get("solve_cosine"),
                ))
            self._prev_fallbacks = fb
        if stats.get("solve_pinned") and not self._pinned_reported:
            self._pinned_reported = True
            out.append(self._emit(
                "solve_pinned", "error",
                "persistent solve-audit failures — the precision ladder "
                "is pinned at the f32/full-batch solve for the rest of "
                "the run (check fvp_dtype/fvp_subsample against this "
                "problem's conditioning)",
                iteration,
                fallbacks=stats.get("fallbacks"),
            ))
        ev = stats.get("vf_explained_variance")
        if (
            ev is not None
            and ev == ev  # EV is NaN when Var(y)=0 — not a collapse
            and iteration > self.cfg.ev_warmup_iterations
        ):
            if ev < self.cfg.ev_collapse and not self._ev_reported:
                self._ev_reported = True
                out.append(self._emit(
                    "ev_collapse", "warn",
                    f"critic explained variance collapsed to {ev:.3g} — "
                    "advantage estimates are worse than a zero baseline",
                    iteration,
                    explained_variance=ev,
                ))
            elif ev >= self.cfg.ev_collapse:
                self._ev_reported = False  # recovered: re-arm the check
        return out

    def observe_memory(self, iteration: int, live_bytes: int) -> list:
        """The steady-state leak rule (fed by ``obs/memory.MemoryMonitor``
        once per iteration): live device bytes growing STRICTLY at every
        step of a ``memory_leak_window``-long window, by at least
        ``memory_leak_min_growth`` in total, after the warmup iterations
        → one ``health:memory_leak`` error for the run. An EQUAL sample
        is skipped, not treated as a plateau: a fused k-iteration chunk
        drains k rows at one host instant, so its k identical samples
        are one observation — resetting on them would make the window
        structurally unfillable on the fused driver. A SHRINK resets
        the window: freed memory is not a leak."""
        out = []
        self._mem_seen += 1
        if self._mem_seen <= self.cfg.memory_leak_warmup:
            return out
        w = self._mem_samples
        if w and live_bytes == w[-1]:
            return out
        if w and live_bytes < w[-1]:
            self._mem_samples = [live_bytes]
            return out
        w.append(live_bytes)
        if len(w) > self.cfg.memory_leak_window:
            del w[0]
        if (
            not self._leak_reported
            and len(w) == self.cfg.memory_leak_window
            and w[-1] - w[0] >= self.cfg.memory_leak_min_growth
        ):
            self._leak_reported = True
            grown = w[-1] - w[0]
            out.append(self._emit(
                "memory_leak", "error",
                f"live device bytes grew monotonically for "
                f"{len(w)} consecutive iterations "
                f"(+{grown} bytes, ~{grown // max(1, len(w) - 1)} "
                "bytes/iteration) — something retains a buffer per "
                "iteration (an unbounded snapshot window, a stats row "
                "kept alive, a host list of device arrays)",
                iteration,
                live_bytes=live_bytes, window=len(w), growth_bytes=grown,
            ))
        return out

    def observe_drain(self, depth: int, high_water: int,
                      maxsize: int) -> list:
        """Async-driver gauge hook: called once per iteration with the
        StatsDrain queue's depth/high-water/bound (host ints — no device
        sync). Warns on the HIGH-WATER gauge reaching the bound — the
        instantaneous depth races the drain thread's pops (a blocked
        submit can have drained below the bound by the time this polls),
        while high-water latches the event deterministically. Reported
        once per run (high-water never recedes)."""
        out = []
        if maxsize and high_water >= maxsize and not self._drain_reported:
            self._drain_reported = True
            out.append(self._emit(
                "stats_drain_backpressure", "warn",
                f"stats drain queue hit its bound "
                f"({high_water}/{maxsize}) — the per-iteration stats "
                "fetch is slower than the iteration; stop conditions lag "
                "by the full bound",
                depth=depth, high_water=high_water, maxsize=maxsize,
            ))
        return out
