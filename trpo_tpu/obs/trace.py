"""Request-scoped tracing for the serving plane: spans on the event bus.

The serving plane's only latency evidence before this module was
aggregate windowed quantiles — a 500 ms p99 with no way to say whether
the time went to queue admission, epoch coalescing under the deadline
batcher, a retried transport hop, a slow-network host, or a
journal-backed failover. This module adds the missing attribution
layer: every request through the router (or a solo ``PolicyServer``)
gets a 128-bit ``trace_id`` minted at the public edge (or accepted
from a client's ``X-Trace-Id`` header), the id rides every
router→replica HTTP hop as headers (so ``TemplateTransport`` multi-host
hops carry it for free), and each stage emits typed ``span`` records
through the EXISTING event bus — the same JSONL stream
``validate_events.py`` checks and ``obs/analyze.py`` assembles.

Span model (single-record, end-stamped):

* One ``span`` event per finished span: ``trace`` (the 128-bit hex
  trace id), ``span`` (64-bit hex span id), optional ``parent``,
  ``name``, ``start`` (unix seconds), ``dur_ms`` (None ONLY for a span
  that was never terminated — the validator FAILS an unterminated
  root), free-form flat attrs (``replica``, ``host``, ``width``, …).
* ``remote: true`` marks a span whose parent was emitted by ANOTHER
  process (the id arrived in the ``X-Trace-Parent`` header): each
  process's log is self-consistent — ``validate_events.py`` FAILS an
  orphan (non-remote parent never emitted in the same file) without
  false-positives on cross-process edges, and the assembler joins the
  per-process logs back into one tree.
* The SHARED epoch span: every session act coalesced into one
  ``step_batch`` dispatch gets a per-trace copy of the dispatch span
  wearing the SAME ``span`` id (and width/rung attrs) — N traces
  pointing at one span id is what makes epoch-induced tail latency
  visible in the assembled view.

Sampling is HEAD-based and deterministic: the decision is a pure hash
of the trace id against ``sample_rate``, so the router and every
replica agree on one trace without coordination — and the router
additionally stamps the decision into the ``X-Trace-Sampled`` header
so a forced (anomaly) trace propagates too. Anomalies are ALWAYS
sampled regardless of rate: a retried, failed, resumed/re-established,
or chaos-fired request calls :meth:`TraceContext.force`, and the
buffered spans are emitted at finish — every anomaly has a trace.

Hot-path cost: spans buffer in their request's :class:`TraceContext`
(plain object appends); :meth:`Tracer.finish` moves an emitted
context's spans into a BOUNDED pending deque with one list-extend, and
a daemon writer drains them through ``bus.emit`` — the CarryJournal /
StatsDrain write-behind pattern. Writer backpressure DROPS spans (the
bound is a bound) and counts every drop in ``dropped_total`` — never
silent, exported as ``trpo_trace_dropped_total`` on /metrics.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from collections import deque
from typing import Dict, Optional

__all__ = [
    "TRACE_HEADER",
    "PARENT_HEADER",
    "SAMPLED_HEADER",
    "Span",
    "TraceContext",
    "Tracer",
    "mint_trace_id",
    "mint_span_id",
    "valid_trace_id",
    "head_sampled",
]

# the propagation contract (README "Request tracing"): the trace id a
# client may supply / read back, the parent span id of the hop, and the
# edge's sampling decision — plain headers, so every transport that
# carries HTTP (local, ssh-tunneled, k8s) carries traces for free
TRACE_HEADER = "X-Trace-Id"
PARENT_HEADER = "X-Trace-Parent"
SAMPLED_HEADER = "X-Trace-Sampled"


def mint_trace_id() -> str:
    """A fresh 128-bit trace id (32 hex chars) — minted at the public
    edge (router or solo server) unless the client supplied one."""
    return os.urandom(16).hex()


def mint_span_id() -> str:
    """A fresh 64-bit span id (16 hex chars)."""
    return os.urandom(8).hex()


_HEX = frozenset("0123456789abcdefABCDEF")


def valid_trace_id(tid) -> bool:
    """Accept a client-supplied trace id: hex DIGITS ONLY, 8–64 chars
    (``int(x, 16)`` would also take ``0x`` prefixes, signs,
    underscores and whitespace — none of which belong in a log key).
    Anything else is replaced by a minted id: a hostile/typoed header
    must not become an unjoinable key or a log-injection vector."""
    return (
        isinstance(tid, str)
        and 8 <= len(tid) <= 64
        and all(c in _HEX for c in tid)
    )


def head_sampled(trace_id: str, rate: float) -> bool:
    """The head-based sampling decision as a pure function of the trace
    id: every process hashing the same id reaches the same verdict with
    no coordination (client-supplied ids are hashed, not trusted to be
    uniform)."""
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    digest = hashlib.sha256(trace_id.encode()).digest()
    return int.from_bytes(digest[:8], "big") < rate * 2.0**64


class Span:
    """One in-flight span: started now, ended (at most) once. The
    record is built at :meth:`end` and buffered on the owning context —
    never written on the request path."""

    __slots__ = (
        "trace_id", "span_id", "parent_id", "name", "start", "_p0",
        "dur_ms", "remote", "attrs", "_ctx",
    )

    def __init__(
        self,
        ctx: "TraceContext",
        name: str,
        parent_id: Optional[str] = None,
        remote: bool = False,
        span_id: Optional[str] = None,
        **attrs,
    ):
        self.trace_id = ctx.trace_id
        self.span_id = span_id or mint_span_id()
        self.parent_id = parent_id
        self.name = name
        self.start = time.time()
        self._p0 = time.perf_counter()
        self.dur_ms: Optional[float] = None
        self.remote = bool(remote)
        self.attrs = attrs
        self._ctx = ctx

    def end(self, **attrs) -> "Span":
        """Terminate the span (idempotent — the first end wins) and
        buffer its record on the context."""
        if self.dur_ms is not None:
            return self
        self.dur_ms = (time.perf_counter() - self._p0) * 1e3
        if attrs:
            self.attrs.update(attrs)
        self._ctx._add(self._record())
        return self

    def _record(self) -> dict:
        rec = {
            "trace": self.trace_id,
            "span": self.span_id,
            "name": self.name,
            "start": self.start,
            "dur_ms": self.dur_ms,
        }
        if self.parent_id is not None:
            rec["parent"] = self.parent_id
        if self.remote:
            rec["remote"] = True
        rec.update(self.attrs)
        return rec


class TraceContext:
    """One request's trace state: the id, the sampling verdict, and the
    span buffer. Spans from any thread touching the request (handler,
    epoch dispatcher, journal hook) append under one small lock; the
    whole buffer is emitted — or dropped — exactly once at
    :meth:`Tracer.finish`."""

    __slots__ = ("trace_id", "sampled", "forced", "_spans", "_lock")

    def __init__(self, trace_id: str, sampled: bool):
        self.trace_id = trace_id
        self.sampled = bool(sampled)
        self.forced = False
        self._spans: list = []
        self._lock = threading.Lock()

    def span(
        self,
        name: str,
        parent: Optional["Span"] = None,
        parent_id: Optional[str] = None,
        remote: bool = False,
        span_id: Optional[str] = None,
        **attrs,
    ) -> Span:
        """Start a child span (``parent`` wins over ``parent_id``)."""
        if parent is not None:
            parent_id = parent.span_id
        return Span(
            self, name, parent_id=parent_id, remote=remote,
            span_id=span_id, **attrs,
        )

    def record(
        self,
        name: str,
        start: float,
        dur_ms: float,
        parent_id: Optional[str] = None,
        span_id: Optional[str] = None,
        remote: bool = False,
        **attrs,
    ) -> str:
        """Buffer an already-measured span retroactively (the epoch
        batcher times its queue-wait and dispatch windows itself, then
        books them per participating trace — passing the SAME
        ``span_id`` for every coalesced trace's dispatch copy is what
        makes the shared epoch span). Returns the span id."""
        sid = span_id or mint_span_id()
        rec = {
            "trace": self.trace_id,
            "span": sid,
            "name": name,
            "start": start,
            "dur_ms": dur_ms,
        }
        if parent_id is not None:
            rec["parent"] = parent_id
        if remote:
            rec["remote"] = True
        rec.update(attrs)
        self._add(rec)
        return sid

    def force(self) -> None:
        """Mark this trace an ANOMALY (retried / failed / resumed /
        chaos-fired): its spans are emitted regardless of the head
        sampling verdict — every anomaly has a trace."""
        self.forced = True

    @property
    def emitting(self) -> bool:
        return self.sampled or self.forced

    def _add(self, rec: dict) -> None:
        with self._lock:
            self._spans.append(rec)

    def _take(self) -> list:
        with self._lock:
            spans, self._spans = self._spans, []
        return spans


class Tracer:
    """Request-trace fan-in for one process: mints/joins contexts,
    owns the sampling rate, and drains emitted spans to the event bus
    on a daemon writer (write-behind — the act path never touches the
    bus).

    ``process`` (e.g. ``"router"`` or the replica name) and ``host``
    stamp every span this process emits, so the assembler can tell
    which side of a hop each record came from without guessing."""

    def __init__(
        self,
        bus,
        sample_rate: float = 0.0,
        process: Optional[str] = None,
        host: Optional[str] = None,
        max_pending: int = 4096,
        poll_interval: float = 0.2,
    ):
        if not 0.0 <= sample_rate <= 1.0:
            raise ValueError(
                f"sample_rate must be in [0, 1], got {sample_rate}"
            )
        if max_pending < 1:
            raise ValueError(
                f"max_pending must be >= 1, got {max_pending}"
            )
        self.bus = bus
        self.sample_rate = float(sample_rate)
        self.process = process
        self.host = host
        self.max_pending = int(max_pending)
        self._poll = float(poll_interval)
        # counters (read by the /metrics handlers): spans_total counts
        # spans accepted into the pending buffer, sampled_total counts
        # emitted TRACES (contexts), dropped_total counts spans the
        # bounded buffer refused — backpressure is visible, not silent
        self.spans_total = 0
        self.sampled_total = 0
        self.dropped_total = 0
        self._lock = threading.Lock()
        self._pending: deque = deque()
        self._wake = threading.Event()
        self._stop = False
        self._writer = threading.Thread(
            target=self._loop, name="trace-writer", daemon=True
        )
        self._writer.start()

    # -- context lifecycle -------------------------------------------------

    def begin(
        self, trace_id: Optional[str] = None, sampled: Optional[bool] = None
    ) -> TraceContext:
        """The public-edge entry: accept a (valid) client-supplied
        trace id or mint one; head-sample unless the caller already
        knows the verdict (a propagated ``X-Trace-Sampled`` header)."""
        if trace_id is None or not valid_trace_id(trace_id):
            trace_id = mint_trace_id()
        if sampled is None:
            sampled = head_sampled(trace_id, self.sample_rate)
        return TraceContext(trace_id, sampled)

    def join(self, headers) -> Optional[TraceContext]:
        """The replica-side entry: join the trace the incoming hop
        carries, or — when no trace header arrived — act as the public
        edge (a solo server IS the edge). ALWAYS returns a context: an
        unsampled one still buffers (a couple of cheap allocs per
        request), because a replica-side anomaly — a 500, an engine
        failure — must be able to ``force()`` its spans out even when
        the edge's head sample said no; the anomalies-always-trace
        policy holds on BOTH sides of the hop. ``headers`` is any
        ``.get(name)``-able mapping (``http.server`` headers, a plain
        dict, or None)."""
        tid = headers.get(TRACE_HEADER) if headers is not None else None
        if tid is not None and valid_trace_id(tid):
            sampled = (
                headers.get(SAMPLED_HEADER) == "1"
                or head_sampled(tid, self.sample_rate)
            )
            return TraceContext(tid, sampled)
        # no propagated trace: this process is the edge (direct client)
        return self.begin(trace_id=tid)

    def parent_from(self, headers) -> Optional[str]:
        """The propagated parent span id of the incoming hop."""
        pid = headers.get(PARENT_HEADER) if headers is not None else None
        return pid if isinstance(pid, str) and pid else None

    @staticmethod
    def headers_for(ctx: TraceContext, parent: Optional[Span]) -> Dict[str, str]:
        """The headers one outgoing hop carries: trace id, the hop
        span's id as the downstream parent, and the CURRENT sampling
        verdict (a trace forced mid-flight propagates as sampled, so
        the retry/takeover leg's replica spans exist too)."""
        headers = {TRACE_HEADER: ctx.trace_id}
        if parent is not None:
            headers[PARENT_HEADER] = parent.span_id
        if ctx.emitting:
            headers[SAMPLED_HEADER] = "1"
        return headers

    # -- emission ----------------------------------------------------------

    def finish(self, ctx: Optional[TraceContext]) -> bool:
        """The request is over: emit the context's buffered spans when
        the trace is sampled/forced, drop them otherwise. Returns
        whether the trace was emitted (callers stamp ``trace`` onto
        their request event exactly when it was).

        Backpressure drops WHOLE contexts, never span tails: a partial
        trace would manufacture validator failures (the root span ends
        last, so a tail-drop preferentially orphans its children).
        FORCED (anomaly) contexts overshoot the bound instead of
        dropping — they are rare, their request events already named
        the trace, and the validator's retry/takeover contracts depend
        on their spans existing; the overshoot is bounded by one
        request's span count."""
        if ctx is None:
            return False
        spans = ctx._take()
        if not spans or not ctx.emitting:
            return False
        stamp = {}
        if self.process is not None:
            stamp["process"] = self.process
        if self.host is not None:
            stamp["host"] = self.host
        with self._lock:
            if self._stop:
                return False
            if (
                not ctx.forced
                and len(self._pending) + len(spans) > self.max_pending
            ):
                self.dropped_total += len(spans)
                return False
            for rec in spans:
                if stamp:
                    rec = {**rec, **stamp}
                self._pending.append(rec)
            self.spans_total += len(spans)
            self.sampled_total += 1
        self._wake.set()
        return True

    def _loop(self) -> None:
        while True:
            with self._lock:
                pending, self._pending = self._pending, deque()
                stop = self._stop
            if pending:
                try:
                    # ONE bus-lock hold + one sink write for the whole
                    # drain: per-span emit (write+flush each, under the
                    # lock every dispatcher thread shares) was the
                    # measurable hot-path cost on the serving bench
                    self.bus.emit_batch("span", pending)
                except Exception:
                    # a closed bus (teardown race) or a sink error must
                    # never kill the writer — but the loss is COUNTED:
                    # dropped_total=0 must mean genuinely lossless
                    # (spans_total stays "accepted for emission";
                    # written = spans_total - dropped_total)
                    with self._lock:
                        self.dropped_total += len(pending)
            if stop:
                return
            self._wake.wait(timeout=self._poll)
            self._wake.clear()

    def drain(self, timeout: float = 5.0) -> None:
        """Block until the pending buffer is empty (tests, teardown)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if not self._pending:
                    return
            self._wake.set()
            time.sleep(0.01)

    def close(self) -> None:
        """Flush and stop the writer (the bus is the caller's — closed
        after, like every other bus consumer)."""
        with self._lock:
            self._stop = True
        self._wake.set()
        self._writer.join(timeout=5.0)
