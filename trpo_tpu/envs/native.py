"""ctypes bindings for the native (C++) vectorized env stepper.

The reference's environment layer is an interpreted serial loop — one
Python ``env.step`` per timestep per env (reference ``utils.py:18-45``).
:class:`NativeVecEnv` is the compiled host runtime for that layer: batched
C++ physics (``native/vec_env.cpp``, OpenMP over envs) behind the same
host-env interface as :class:`~trpo_tpu.envs.gym_adapter.GymVecEnv`, so
``host_rollout`` and the agent drive it unchanged. Bindings are plain
ctypes over a flat-array C ABI — no pybind11 (not in this image), no copy:
the arrays live in NumPy and C++ steps them in place.

The shared library builds lazily on first use (``make`` in ``native/``)
and is cached; environments gate on :func:`native_available`.
"""

from __future__ import annotations

import ctypes
import os
import pathlib
import shutil
import subprocess
import tempfile
import threading
from typing import Optional

import numpy as np

from trpo_tpu.envs.episode_stats import EpisodeStatsMixin
from trpo_tpu.envs.obs_norm import ObsNormMixin
from trpo_tpu.models.policy import BoxSpec, DiscreteSpec

__all__ = ["NativeVecEnv", "native_available", "load_library"]

_NATIVE_DIR = pathlib.Path(__file__).resolve().parents[2] / "native"
_LIB_NAME = "libtrpo_native.so"
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None

_f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_u64p = np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS")
_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")


def _build() -> pathlib.Path:
    """Build the shared library if stale; atomic against concurrent builders.

    Staleness is checked against every build input (source AND Makefile).
    The compile runs in a scratch dir and the result is ``os.replace``d into
    place — a concurrent process can never ``dlopen`` a half-written file,
    it sees either the old library or the new one.
    """
    lib_path = _NATIVE_DIR / _LIB_NAME
    inputs = [_NATIVE_DIR / "vec_env.cpp", _NATIVE_DIR / "Makefile"]
    if lib_path.exists() and all(
        lib_path.stat().st_mtime >= p.stat().st_mtime for p in inputs
    ):
        return lib_path
    with tempfile.TemporaryDirectory(dir=_NATIVE_DIR) as td:
        scratch = pathlib.Path(td)
        for p in inputs:
            shutil.copy2(p, scratch / p.name)
        subprocess.run(
            ["make", "-s", _LIB_NAME],
            cwd=scratch,
            check=True,
            capture_output=True,
            text=True,
        )
        # same directory => same filesystem => atomic rename
        os.replace(scratch / _LIB_NAME, lib_path)
    return lib_path


def load_library() -> ctypes.CDLL:
    """Build (if needed) and load the native library.

    Success is cached per process; failure is NOT — a transient failure
    (e.g. losing a build race, disk pressure) may clear on retry, and a
    genuine toolchain failure re-raises fast.
    """
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        try:
            lib = ctypes.CDLL(str(_build()))
        except (OSError, subprocess.CalledProcessError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            raise RuntimeError(
                f"native env library unavailable (build failed): {detail}"
            ) from e

        lib.trpo_native_seed.argtypes = [_u64p, ctypes.c_int32, ctypes.c_uint64]
        for prefix, act_p in (
            ("cartpole", _i32p),
            ("pendulum", _f32p),
        ):
            reset = getattr(lib, f"trpo_native_{prefix}_reset")
            reset.argtypes = [_f32p, _i32p, _u64p, ctypes.c_int32]
            step = getattr(lib, f"trpo_native_{prefix}_step")
            step.argtypes = [
                _f32p, _i32p, _u64p, act_p,
                ctypes.c_int32, ctypes.c_int32,
                _f32p, _f32p, _f32p, _u8p, _u8p,
            ]
        _lib = lib
        return lib


def native_available() -> bool:
    """True when the native library builds/loads on this machine."""
    try:
        load_library()
        return True
    except RuntimeError:
        return False


def _default_horizon(kind: str) -> int:
    """Default episode horizon, read from the JAX env class so the native
    and JAX variants of the same env can never diverge on truncation."""
    if kind == "cartpole":
        from trpo_tpu.envs.cartpole import CartPole as cls
    else:
        from trpo_tpu.envs.pendulum import Pendulum as cls
    return cls().max_episode_steps


_KINDS = {
    # kind -> (state_width, obs_dim, discrete_actions)
    "cartpole": (4, 4, True),
    "pendulum": (2, 3, False),
}


class NativeVecEnv(EpisodeStatsMixin, ObsNormMixin):
    """N batched native envs behind the ``GymVecEnv`` host interface."""

    def __init__(
        self,
        kind: str = "cartpole",
        n_envs: int = 8,
        seed: int = 0,
        max_episode_steps: Optional[int] = None,
        normalize_obs: bool = False,
    ):
        if kind not in _KINDS:
            raise KeyError(f"unknown native env {kind!r}; have {sorted(_KINDS)}")
        if n_envs < 1:
            # the batched C++ stepper honors any positive fleet width
            # (wide-N presets included) — but a zero/negative count would
            # allocate empty state arrays and step nothing, silently
            raise ValueError(f"n_envs must be >= 1, got {n_envs}")
        self._lib = load_library()
        state_w, obs_dim, discrete = _KINDS[kind]
        default_steps = _default_horizon(kind)
        self.kind = kind
        self.n_envs = n_envs
        self.max_episode_steps = (
            default_steps if max_episode_steps is None else max_episode_steps
        )
        self.obs_shape = (obs_dim,)
        self.action_spec = DiscreteSpec(2) if discrete else BoxSpec(1)
        self._discrete = discrete

        n = n_envs
        self._state = np.zeros((n, state_w), np.float32)
        self._t = np.zeros(n, np.int32)
        self._rng = np.zeros(n, np.uint64)
        self._lib.trpo_native_seed(self._rng, n, np.uint64(seed))
        self._reset = getattr(self._lib, f"trpo_native_{kind}_reset")
        self._step = getattr(self._lib, f"trpo_native_{kind}_step")
        self._reset(self._state, self._t, self._rng, n)
        # Shared running obs normalization (ObsNormMixin) — same machinery
        # as GymVecEnv, so native: envs support normalize_obs identically.
        self._init_obs_norm(self.obs_shape, normalize_obs)
        self._obs = self._fold_and_normalize(self._observe())

        self._init_episode_stats(n)

    def _observe(self) -> np.ndarray:
        if self.kind == "cartpole":
            return self._state.copy()
        theta, theta_dot = self._state[:, 0], self._state[:, 1]
        return np.stack(
            [np.cos(theta), np.sin(theta), theta_dot], axis=1
        ).astype(np.float32)

    def host_step(self, actions: np.ndarray):
        """Step all envs in native code; auto-reset inside. Same contract as
        ``GymVecEnv.host_step`` (true pre-reset ``final_obs`` for truncation
        bootstrapping)."""
        return self.host_step_slice(actions, 0, self.n_envs)

    def host_step_slice(self, actions: np.ndarray, lo: int, hi: int):
        """Step only envs ``[lo, hi)`` — the group-stepping surface for
        ``rollout.pipelined_host_rollout`` (one group steps in native code
        while another group's inference is in flight on the device). Row
        slices of the state/counter/RNG arrays are C-contiguous views, so
        the C++ stepper runs on them in place with zero copies."""
        m = hi - lo
        if self._discrete:
            acts = np.ascontiguousarray(
                np.asarray(actions).reshape(m), np.int32
            )
        else:
            acts = np.ascontiguousarray(
                np.asarray(actions).reshape(m), np.float32
            )
        next_obs = np.empty((m, self.obs_shape[0]), np.float32)
        final_obs = np.empty_like(next_obs)
        rewards = np.empty(m, np.float32)
        terminated = np.empty(m, np.uint8)
        truncated = np.empty(m, np.uint8)
        self._step(
            self._state[lo:hi], self._t[lo:hi], self._rng[lo:hi], acts,
            np.int32(m), np.int32(self.max_episode_steps),
            next_obs, final_obs, rewards, terminated, truncated,
        )
        terminated = terminated.astype(bool)
        truncated = truncated.astype(bool)

        self._update_episode_stats_slice(
            rewards, np.logical_or(terminated, truncated), lo, hi
        )

        # shared-stats fold (no-op unless normalize_obs); final_obs
        # normalized under the same statistics snapshot, same lock hold
        next_obs, final_obs = self._fold_and_normalize_slice(
            next_obs, lo, hi, extra=final_obs
        )
        self._obs[lo:hi] = next_obs
        return next_obs, rewards, terminated, truncated, final_obs

    def reset_all(self, seed=None) -> np.ndarray:
        """Hard-reset every env (fresh episodes); returns the new obs batch.

        Auto-reset inside ``host_step`` covers steady-state training; this
        is for callers that need episode boundaries under their own control
        (e.g. reference-style serial rollouts, reproducible evaluation —
        ``seed`` reseeds the per-env RNG streams)."""
        if seed is not None:
            self._lib.trpo_native_seed(
                self._rng, self.n_envs, np.uint64(seed)
            )
        self._reset(self._state, self._t, self._rng, self.n_envs)
        self._obs = self._fold_and_normalize(self._observe())
        self._running_returns[:] = 0.0
        self._running_lengths[:] = 0
        # a copy: group stepping updates the cache in place
        return self._obs.copy()

    def current_obs(self) -> np.ndarray:
        # a copy: group stepping (host_step_slice) updates the cache in
        # place, and callers buffer what this returns
        return self._obs.copy()

    def close(self):
        pass

    # -- checkpoint fidelity (exact for native envs) -----------------------

    def env_state_snapshot(self) -> dict:
        """EXACT resume state: simulator buffers live host-side (the C++
        stepper mutates these numpy arrays in place), so unlike external
        simulators nothing is hidden — state + step counters + per-env RNG
        streams + episode counters + obs cache round-trip bitwise. The
        agent's checkpoint path stores this as a host sidecar next to the
        Orbax TrainState (utils/checkpoint.py)."""
        snap = {
            "kind": self.kind,
            "state": self._state.copy(),
            "t": self._t.copy(),
            "rng": self._rng.copy(),
            "obs": self._obs.copy(),
            **self._episode_stats_snapshot(),
        }
        if self.has_obs_norm:
            snap["raw_obs"] = self._raw_obs.copy()
        return snap

    def env_state_restore(self, snap: dict) -> None:
        if snap.get("kind") != self.kind:
            raise ValueError(
                f"snapshot is for native env {snap.get('kind')!r}, "
                f"this adapter is {self.kind!r}"
            )
        snap_state = np.asarray(snap["state"])
        if snap_state.shape[0] != self.n_envs:
            # the n_envs-resume guard: a fleet preset resumed at another
            # width must fail with the actionable count message (a wide-N
            # fleet restored into a narrow adapter would silently drop
            # envs; the reverse would read garbage)
            raise ValueError(
                f"snapshot holds {snap_state.shape[0]} "
                f"envs, this adapter has {self.n_envs} — resume with the "
                "same n_envs (fleet presets pin the width via "
                "fleet_n_envs)"
            )
        if snap_state.shape != self._state.shape:
            raise ValueError(
                f"snapshot state layout {snap_state.shape} does not "
                f"match this {self.kind!r} adapter's "
                f"{self._state.shape} — snapshot from a different env "
                "build?"
            )
        if self.has_obs_norm and "raw_obs" not in snap:
            raise ValueError(
                "snapshot was taken without normalize_obs; resume with "
                "the same normalize_obs setting"
            )
        self._state[:] = snap["state"]
        self._t[:] = snap["t"]
        self._rng[:] = snap["rng"]
        self._obs = np.asarray(snap["obs"]).copy()
        if self.has_obs_norm and "raw_obs" in snap:
            self._raw_obs = np.asarray(snap["raw_obs"]).copy()
        self._episode_stats_restore(snap)
