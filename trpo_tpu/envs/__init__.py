"""Environments.

The reference drives a single host-side gym env with one ``sess.run`` per
step (``utils.py:18-45`` + ``trpo_inksci.py:76-87``) — ~1000 host↔device
round trips per training batch. Here the classic-control envs are pure JAX
(``reset``/``step`` are jittable functions over an explicit state pytree), so
rollouts run *on device* inside ``lax.scan``, batched over N envs with
``vmap`` — zero per-step dispatch. Host-side gymnasium envs (MuJoCo, Atari)
are supported through a vectorized adapter with batched device inference.

``make(name)`` resolves:
- ``"cartpole"``, ``"pendulum"``, ``"fake"`` → pure-JAX classic control
- ``"chain"``, ``"halfcheetah-sim"``, ``"humanoid-sim"`` → pure-JAX
  continuous-control rungs at MuJoCo dimensions (BASELINE.json configs 3-4)
- ``"catch"`` → pure-JAX pixel env for the conv-policy rung (config 5)
- ``"gym:<EnvId>"`` → gymnasium adapter (requires gymnasium + the env's deps)
"""

from trpo_tpu.envs.cartpole import CartPole  # noqa: F401
from trpo_tpu.envs.pendulum import Pendulum  # noqa: F401
from trpo_tpu.envs.fake import FakeEnv  # noqa: F401
from trpo_tpu.envs.locomotion import (  # noqa: F401
    ChainLocomotion,
    HalfCheetahSim,
    HumanoidSim,
)
from trpo_tpu.envs.catch import CatchPixels  # noqa: F401

_JAX_ENVS = {
    "cartpole": CartPole,
    "pendulum": Pendulum,
    "fake": FakeEnv,
    "chain": ChainLocomotion,
    "halfcheetah-sim": HalfCheetahSim,
    "humanoid-sim": HumanoidSim,
    "catch": CatchPixels,
}


def make(name: str, **kwargs):
    """Build an env by preset name (see module docstring for the grammar)."""
    if name.startswith("gym:"):
        from trpo_tpu.envs.gym_adapter import GymVecEnv

        return GymVecEnv(name[4:], **kwargs)
    if name in _JAX_ENVS:
        return _JAX_ENVS[name](**kwargs)
    raise KeyError(
        f"unknown env {name!r}; have {sorted(_JAX_ENVS)} or 'gym:<EnvId>'"
    )


def is_device_env(env) -> bool:
    """True for pure-JAX envs whose step/reset are jittable."""
    return hasattr(env, "step") and hasattr(env, "reset") and hasattr(
        env, "obs_shape"
    ) and not hasattr(env, "host_step")
