"""Environments.

The reference drives a single host-side gym env with one ``sess.run`` per
step (``utils.py:18-45`` + ``trpo_inksci.py:76-87``) — ~1000 host↔device
round trips per training batch. Here the classic-control envs are pure JAX
(``reset``/``step`` are jittable functions over an explicit state pytree), so
rollouts run *on device* inside ``lax.scan``, batched over N envs with
``vmap`` — zero per-step dispatch. Host-side gymnasium envs (MuJoCo, Atari)
are supported through a vectorized adapter with batched device inference.

``make(name)`` resolves:
- ``"cartpole"``, ``"pendulum"``, ``"fake"`` → pure-JAX classic control
- ``"chain"``, ``"halfcheetah-sim"``, ``"humanoid-sim"`` → pure-JAX
  continuous-control rungs at MuJoCo dimensions (BASELINE.json configs 3-4)
- ``"catch"`` → pure-JAX pixel env for the conv-policy rung (config 5)
- ``"pong-sim"`` → Catch at the Nature-DQN Atari shape (84×84×4
  frame-stacked pixels; the high-param conv-FVP rung on device)
- ``"native:cartpole"``, ``"native:pendulum"`` → C++ batched host stepper
  (``native/vec_env.cpp`` via ctypes; builds lazily with g++)
- ``"gym:<EnvId>"`` → gymnasium adapter (requires gymnasium + the env's deps)
- ``"gymproc:<EnvId>"`` → the same adapter surface over a worker-process
  pool (``envs/proc_env.py`` — GIL-free parallel host stepping on
  multicore hosts; bit-identical trajectories to ``gym:``)
"""

from trpo_tpu.envs.cartpole import CartPole  # noqa: F401
from trpo_tpu.envs.pendulum import Pendulum  # noqa: F401
from trpo_tpu.envs.fake import FakeEnv  # noqa: F401
from trpo_tpu.envs.locomotion import (  # noqa: F401
    ChainLocomotion,
    HalfCheetahSim,
    HumanoidSim,
)
from trpo_tpu.envs.catch import CatchPixels  # noqa: F401
from trpo_tpu.envs.wrappers import MaskObservation  # noqa: F401


def _pong_sim(grid: int = 21, cell_px: int = 4, frames: int = 4):
    """Catch at the exact Nature-DQN Atari input shape — 84×84×4 uint8
    frame-stacked pixels (BASELINE.json config 5's on-device stand-in at
    true conv-FVP scale; the real-Atari path is ``gym:ALE/Pong-v5``)."""
    return CatchPixels(grid=grid, cell_px=cell_px, frames=frames)


def _cartpole_po(max_episode_steps: int = 500):
    """CartPole with velocities hidden (obs = [x, theta]) — the classic
    partially observable variant; needs a recurrent policy to solve."""
    return MaskObservation(
        CartPole(max_episode_steps=max_episode_steps), indices=(0, 2)
    )


# Widest env fleet the one-simulator-object-per-env host families
# (gym:, gymproc:) will construct (ISSUE 10): a wide-N fleet preset names
# thousands of envs, which is one vmap axis for device envs and one
# batched C++ call for native:, but thousands of in-process gymnasium
# instances (or worker-pool slices) for gym:/gymproc: — a
# misconfiguration that deserves a clear construction-time error, not an
# OOM an hour in. The cap bounds cfg.fleet_n_envs only; an explicit
# n_envs stays the user's call.
HOST_ENV_FLEET_MAX = 256

_JAX_ENVS = {
    "cartpole": CartPole,
    "cartpole-po": _cartpole_po,
    "pendulum": Pendulum,
    "fake": FakeEnv,
    "chain": ChainLocomotion,
    "halfcheetah-sim": HalfCheetahSim,
    "humanoid-sim": HumanoidSim,
    "catch": CatchPixels,
    "pong-sim": _pong_sim,
}


def make(name: str, max_episode_steps=None, **kwargs):
    """Build an env by preset name (see module docstring for the grammar).

    ``max_episode_steps=None`` keeps each env's own default horizon; a value
    overrides it — forwarded to gymnasium's TimeLimit for ``gym:`` envs, to
    the native stepper for ``native:`` envs, and to the constructor for
    pure-JAX envs that have the knob. Envs with a structurally fixed horizon
    (Catch: the ball reaches the bottom in grid−1 steps) reject an override.
    """
    if max_episode_steps is not None:
        kwargs["max_episode_steps"] = max_episode_steps
    if name.startswith("gym:"):
        from trpo_tpu.envs.gym_adapter import GymVecEnv

        return GymVecEnv(name[4:], **kwargs)
    if name.startswith("gymproc:"):
        from trpo_tpu.envs.proc_env import ProcVecEnv

        return ProcVecEnv(name[len("gymproc:"):], **kwargs)
    if name.startswith("native:"):
        from trpo_tpu.envs.native import NativeVecEnv

        return NativeVecEnv(name[len("native:"):], **kwargs)
    if name in _JAX_ENVS:
        cls = _JAX_ENVS[name]
        if "max_episode_steps" in kwargs:
            import inspect

            # signature() resolves __init__ for classes and works for
            # factory functions (e.g. cartpole-po) alike
            if "max_episode_steps" not in inspect.signature(
                cls
            ).parameters:
                raise TypeError(
                    f"env {name!r} has a fixed horizon; "
                    "max_episode_steps is not supported"
                )
        return cls(**kwargs)
    raise KeyError(
        f"unknown env {name!r}; have {sorted(_JAX_ENVS)} or 'gym:<EnvId>'"
    )


def is_device_env(env) -> bool:
    """True for pure-JAX envs whose step/reset are jittable."""
    return hasattr(env, "step") and hasattr(env, "reset") and hasattr(
        env, "obs_shape"
    ) and not hasattr(env, "host_step")
