"""CartPole as a pure-JAX environment.

Implements the standard cart-pole swing-up-free balancing task (Barto, Sutton
& Anderson 1983; the physics constants and termination bounds are the classic
control ones used by gym/gymnasium CartPole) as jittable ``reset``/``step``
functions over an explicit state pytree. The reference trains on gym's
``CartPole-v0`` through host stepping (``trpo_inksci.py:179``,
``utils.py:24,32``); on-device dynamics let the entire rollout→update
training iteration compile into one XLA program.

Episode cap defaults to 500 steps (the v1 convention), so the reference's
"solved" bar of mean reward > 475-550 is reachable; pass
``max_episode_steps=200`` for v0 semantics.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from trpo_tpu.models.policy import DiscreteSpec


class CartPoleState(NamedTuple):
    x: jax.Array          # cart position
    x_dot: jax.Array
    theta: jax.Array      # pole angle (rad)
    theta_dot: jax.Array
    t: jax.Array          # step index within episode (int32)


class CartPole:
    obs_shape = (4,)
    action_spec = DiscreteSpec(2)

    # Classic control constants.
    gravity = 9.8
    masscart = 1.0
    masspole = 0.1
    length = 0.5            # half the pole length
    force_mag = 10.0
    tau = 0.02              # integration timestep
    x_threshold = 2.4
    theta_threshold = 12 * 2 * jnp.pi / 360

    def __init__(self, max_episode_steps: int = 500):
        self.max_episode_steps = max_episode_steps

    def reset(self, key):
        vals = jax.random.uniform(key, (4,), jnp.float32, -0.05, 0.05)
        state = CartPoleState(
            x=vals[0], x_dot=vals[1], theta=vals[2], theta_dot=vals[3],
            t=jnp.asarray(0, jnp.int32),
        )
        return state, self._obs(state)

    def _obs(self, s: CartPoleState):
        return jnp.stack([s.x, s.x_dot, s.theta, s.theta_dot])

    def step(self, state: CartPoleState, action, key):
        """One Euler step. ``action`` ∈ {0, 1}; ``key`` unused (deterministic
        dynamics) but kept for a uniform env interface.

        Returns ``(state', obs', reward, terminated, truncated)``.
        """
        del key
        force = jnp.where(action == 1, self.force_mag, -self.force_mag)
        cos_t, sin_t = jnp.cos(state.theta), jnp.sin(state.theta)
        total_mass = self.masscart + self.masspole
        polemass_length = self.masspole * self.length

        temp = (
            force + polemass_length * state.theta_dot**2 * sin_t
        ) / total_mass
        theta_acc = (self.gravity * sin_t - cos_t * temp) / (
            self.length * (4.0 / 3.0 - self.masspole * cos_t**2 / total_mass)
        )
        x_acc = temp - polemass_length * theta_acc * cos_t / total_mass

        x = state.x + self.tau * state.x_dot
        x_dot = state.x_dot + self.tau * x_acc
        theta = state.theta + self.tau * state.theta_dot
        theta_dot = state.theta_dot + self.tau * theta_acc
        t = state.t + 1

        new_state = CartPoleState(x, x_dot, theta, theta_dot, t)
        terminated = jnp.logical_or(
            jnp.abs(x) > self.x_threshold,
            jnp.abs(theta) > self.theta_threshold,
        )
        truncated = jnp.logical_and(
            t >= self.max_episode_steps, jnp.logical_not(terminated)
        )
        reward = jnp.asarray(1.0, jnp.float32)
        return new_state, self._obs(new_state), reward, terminated, truncated
