"""Pure-JAX pixel environment for the conv-policy (Atari) rung.

BASELINE.json config 5 is "Atari Pong-ram / pixel conv policy (high-param
FVP, 8 vectorized envs)". Atari ROMs/emulators are not part of this image
(real Atari runs go through ``envs.make("gym:ALE/Pong-v5")`` when
available), so this provides the on-device pixel rung: *Catch* — the
standard pixel control microbenchmark (a falling ball, a paddle, ±1 reward
on the bottom row) — rendered as uint8 images sized for the Nature-DQN conv
torso (``models/conv.py``). Everything (dynamics + rendering) is jittable,
so conv-policy rollouts run inside the same fused ``lax.scan`` program as
the vector envs, exercising the high-param FVP path end to end on TPU.

``frames > 1`` renders the last ``frames`` board positions as stacked
channels (newest first) — the pixel-history observation DQN-style Atari
preprocessing produces by frame-stacking. ``CatchPixels(grid=21, cell_px=4,
frames=4)`` is exactly the Nature input shape: 84×84×4 uint8 (the
``"pong-sim"`` registry name), putting the conv FVP at true Atari scale
(≥1.6M-param policy with the standard 512-dense head).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from trpo_tpu.models.policy import DiscreteSpec

__all__ = ["CatchPixels"]


class CatchState(NamedTuple):
    ball_row: jax.Array    # int32, 0 = top
    ball_col: jax.Array    # int32
    paddle_col: jax.Array  # int32 (paddle lives on the bottom row)
    t: jax.Array           # int32 step counter
    hist: jax.Array        # (frames, 3) int32 [ball_row, ball_col,
    #                        paddle_col] of the last `frames` boards,
    #                        newest first (row 0 == the current state).
    #                        NOTE: adding this field (round 2) changed the
    #                        TrainState.env_carry pytree for catch runs —
    #                        checkpoints saved before frame-stacking
    #                        existed do not restore into the new template


class CatchPixels:
    """``grid×grid`` Catch rendered at ``cell_px`` px/cell, (H, W, frames)
    uint8 — channel ``k`` shows the board as of ``k`` steps ago.

    Actions: 0 = left, 1 = stay, 2 = right. The ball falls one row per
    step; when it reaches the bottom row the episode terminates with
    reward +1 if the paddle is under it, −1 otherwise. Default 10×10 grid
    at 4 px/cell, single frame → 40×40×1 observations (Nature-DQN torso →
    1×1×64 feats); ``grid=21, cell_px=4, frames=4`` → the 84×84×4 Atari
    rung.
    """

    def __init__(self, grid: int = 10, cell_px: int = 4, frames: int = 1):
        if frames < 1:
            raise ValueError(f"frames must be >= 1, got {frames}")
        self.grid = grid
        self.cell_px = cell_px
        self.frames = frames
        side = grid * cell_px
        self.obs_shape = (side, side, frames)
        self.action_spec = DiscreteSpec(3)

    def reset(self, key):
        col = jax.random.randint(key, (), 0, self.grid)
        ball_row = jnp.asarray(0, jnp.int32)
        ball_col = col.astype(jnp.int32)
        paddle_col = jnp.asarray(self.grid // 2, jnp.int32)
        frame = jnp.stack([ball_row, ball_col, paddle_col])
        state = CatchState(
            ball_row=ball_row,
            ball_col=ball_col,
            paddle_col=paddle_col,
            t=jnp.asarray(0, jnp.int32),
            # pre-episode history: the initial board, repeated — the
            # standard frame-stack warmup
            hist=jnp.tile(frame[None, :], (self.frames, 1)),
        )
        return state, self._obs(state)

    def _render_frame(self, ball_row, ball_col, paddle_col):
        g, px = self.grid, self.cell_px
        rows = jnp.arange(g)
        ball = (rows == ball_row)[:, None] * (rows == ball_col)[None, :]
        paddle = (rows == g - 1)[:, None] * (rows == paddle_col)[None, :]
        cells = jnp.logical_or(ball, paddle)
        img = jnp.repeat(jnp.repeat(cells, px, axis=0), px, axis=1)
        return (img * 255).astype(jnp.uint8)

    def _obs(self, s: CatchState):
        # (frames, H, W) → (H, W, frames): channels-last is the TPU-native
        # conv layout (models/conv.py)
        frames = jax.vmap(
            lambda f: self._render_frame(f[0], f[1], f[2])
        )(s.hist)
        return jnp.transpose(frames, (1, 2, 0))

    def step(self, state: CatchState, action, key):
        del key
        move = jnp.reshape(action, ()).astype(jnp.int32) - 1
        paddle = jnp.clip(state.paddle_col + move, 0, self.grid - 1)
        ball_row = state.ball_row + 1
        t = state.t + 1
        frame = jnp.stack([ball_row, state.ball_col, paddle])
        hist = jnp.concatenate([frame[None, :], state.hist[:-1]], axis=0)
        new_state = CatchState(ball_row, state.ball_col, paddle, t, hist)

        at_bottom = ball_row >= self.grid - 1
        caught = jnp.logical_and(at_bottom, paddle == state.ball_col)
        reward = jnp.where(
            at_bottom, jnp.where(caught, 1.0, -1.0), 0.0
        ).astype(jnp.float32)
        terminated = at_bottom
        truncated = jnp.asarray(False)
        return new_state, self._obs(new_state), reward, terminated, truncated
