"""Shared running observation normalization for host vectorized envs.

One statistics object per adapter, folded with the Chan/Welford merge (the
same math as the device path's ``utils/normalize.py``), shared by ALL envs
in the adapter and by both host adapter families (``GymVecEnv``,
``NativeVecEnv``) through this mixin. The agent mirrors the statistics into
``TrainState`` every iteration so checkpoints carry them, re-seeds them on
restore (``set_obs_stats_state``), and freezes folding during evaluation.

Thread-safety: group-stepping threads (``rollout.pipelined_host_rollout``)
fold concurrently — the read-modify-write merge and every normalization
read happen under one lock, so a fold is never observed mid-update.

The reference has no normalization at all (observations feed the policy
raw, ``trpo_inksci.py:77``); this is standard equipment for the MuJoCo-
scale rungs of ``BASELINE.json``.
"""

from __future__ import annotations

import threading

import numpy as np

__all__ = ["ObsNormMixin"]


class ObsNormMixin:
    """Call ``_init_obs_norm(obs_shape, enabled)`` in ``__init__`` (before
    producing the first observation batch), then route every outgoing
    observation batch through ``_fold_and_normalize`` /
    ``_fold_and_normalize_slice``."""

    def _init_obs_norm(self, obs_shape, enabled: bool) -> None:
        self.has_obs_norm = bool(enabled)
        self._norm_frozen = False
        # group-stepping threads share these statistics; the lock keeps the
        # read-modify-write merge atomic per fold
        self._norm_lock = threading.Lock()
        self._deferred = None  # begin_deferred_fold() buffers per group
        if self.has_obs_norm:
            self._n_count = 0.0
            self._n_mean = np.zeros(obs_shape, np.float64)
            self._n_m2 = np.zeros(obs_shape, np.float64)

    # -- folding ----------------------------------------------------------

    def _fold(self, obs_batch: np.ndarray) -> None:
        """Chan/Welford-merge a raw batch into the shared statistics — the
        same math as ``utils/normalize.update_stats``. Caller holds the
        lock."""
        b = np.asarray(obs_batch, np.float64)
        n_b = float(b.shape[0])
        mean_b = b.mean(axis=0)
        m2_b = ((b - mean_b) ** 2).sum(axis=0)
        delta = mean_b - self._n_mean
        tot = self._n_count + n_b
        self._n_mean = self._n_mean + delta * (n_b / tot)
        self._n_m2 = self._n_m2 + m2_b + delta**2 * (
            self._n_count * n_b / tot
        )
        self._n_count = tot

    def _apply_norm(self, obs: np.ndarray) -> np.ndarray:
        """Normalize under the current statistics (lock held by caller on
        concurrent paths)."""
        if not self.has_obs_norm or self._n_count == 0.0:
            return obs
        var = self._n_m2 / max(self._n_count, 1.0)
        std = np.sqrt(var + 1e-8)
        return np.clip(
            (obs - self._n_mean) / std, -10.0, 10.0
        ).astype(np.float32)

    def _fold_and_normalize(self, obs_batch: np.ndarray) -> np.ndarray:
        """Fold a full raw ``(N, *obs)`` batch (unless frozen) and return it
        normalized."""
        if not self.has_obs_norm:
            return obs_batch
        # keep the raw batch: installing restored statistics later must be
        # able to re-normalize the cached current obs (set_obs_stats_state)
        self._raw_obs = np.asarray(obs_batch).copy()
        with self._norm_lock:
            if not self._norm_frozen:
                self._fold(obs_batch)
            return self._apply_norm(obs_batch)

    def _fold_and_normalize_slice(
        self, obs_batch: np.ndarray, lo: int, hi: int, extra=None
    ):
        """Slice variant for group stepping: raw rows ``[lo, hi)`` replace
        their cache entries, the slice folds into the SAME shared statistics
        (one fold per group step instead of per full step — the merge is
        associative, so the statistics converge identically), and the slice
        comes back normalized under the statistics as of now. ``extra`` (the
        truncation-bootstrap ``final_obs``) is normalized under the SAME
        statistics snapshot, inside the same lock hold — a concurrent group
        thread's fold must never be observed mid-update."""
        if not self.has_obs_norm:
            return obs_batch if extra is None else (obs_batch, extra)
        self._raw_obs[lo:hi] = obs_batch
        with self._norm_lock:
            if self._deferred is not None:
                # deferred mode: buffer the raw batch (freshly allocated by
                # the caller — safe to keep by reference) and normalize
                # under the window-start statistics
                self._deferred.setdefault(lo, []).append(obs_batch)
            elif not self._norm_frozen:
                self._fold(obs_batch)
            normed = self._apply_norm(obs_batch)
            if extra is None:
                return normed
            return normed, self._apply_norm(extra)

    # -- deferred folding (pipelined rollouts) -----------------------------

    def begin_deferred_fold(self) -> None:
        """Enter deferred mode: every subsequent slice fold is buffered and
        the whole window normalizes under the statistics as of NOW — the
        host analogue of the device path's start-of-iteration statistics.
        :func:`end_deferred_fold` merges the buffers in deterministic group
        order, so a threaded (scheduler-nondeterministic) rollout produces
        bit-reproducible statistics and observations for a fixed seed."""
        if not self.has_obs_norm:
            return
        with self._norm_lock:
            self._deferred = {}

    def end_deferred_fold(self) -> None:
        """Leave deferred mode, merging the buffered raw batches in (group,
        arrival) order — independent of thread scheduling."""
        if not self.has_obs_norm:
            return
        with self._norm_lock:
            deferred, self._deferred = self._deferred, None
            if deferred and not self._norm_frozen:
                for lo in sorted(deferred):
                    for batch in deferred[lo]:
                        self._fold(batch)
                # the cached current obs were normalized under the
                # window-start statistics; refresh them so the next
                # window's first step is consistent with its batch (the
                # agent path re-installs stats via set_obs_stats_state,
                # but direct pipelined_host_rollout users do not)
                if getattr(self, "_raw_obs", None) is not None:
                    self._obs = self._apply_norm(self._raw_obs)

    # -- checkpoint mirror / control --------------------------------------

    def obs_stats_state(self):
        """(count, mean, m2) float32 arrays — the checkpointable mirror."""
        if not self.has_obs_norm:
            return None
        return (
            np.float32(self._n_count),
            self._n_mean.astype(np.float32),
            self._n_m2.astype(np.float32),
        )

    def set_obs_stats_state(self, state) -> None:
        """Install (count, mean, m2) — e.g. restored from a checkpoint.

        The cached current observations are re-normalized under the new
        statistics so the next rollout's first step is consistent with the
        rest of its batch."""
        count, mean, m2 = state
        with self._norm_lock:
            self._n_count = float(count)
            self._n_mean = np.asarray(mean, np.float64)
            self._n_m2 = np.asarray(m2, np.float64)
            self._obs = self._apply_norm(self._raw_obs)

    def freeze_obs_stats(self, frozen: bool = True) -> None:
        """Stop/resume folding new data in (evaluation must not shift the
        training statistics)."""
        self._norm_frozen = frozen
