"""Pendulum swing-up as a pure-JAX environment (continuous actions).

The classic underactuated pendulum task (gym/gymnasium ``Pendulum``): state
(θ, θ̇), observation (cos θ, sin θ, θ̇), torque action clipped to ±2, cost
``θ² + 0.1·θ̇² + 0.001·u²``. The BASELINE.json ladder's first continuous rung
— exercises the diagonal-Gaussian policy head the reference lacks.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from trpo_tpu.models.policy import BoxSpec


class PendulumState(NamedTuple):
    theta: jax.Array
    theta_dot: jax.Array
    t: jax.Array


def _angle_normalize(x):
    return ((x + jnp.pi) % (2 * jnp.pi)) - jnp.pi


class Pendulum:
    obs_shape = (3,)
    action_spec = BoxSpec(1)

    max_speed = 8.0
    max_torque = 2.0
    dt = 0.05
    g = 10.0
    m = 1.0
    l = 1.0

    def __init__(self, max_episode_steps: int = 200):
        self.max_episode_steps = max_episode_steps

    def reset(self, key):
        k1, k2 = jax.random.split(key)
        theta = jax.random.uniform(k1, (), jnp.float32, -jnp.pi, jnp.pi)
        theta_dot = jax.random.uniform(k2, (), jnp.float32, -1.0, 1.0)
        state = PendulumState(theta, theta_dot, jnp.asarray(0, jnp.int32))
        return state, self._obs(state)

    def _obs(self, s: PendulumState):
        return jnp.stack([jnp.cos(s.theta), jnp.sin(s.theta), s.theta_dot])

    def step(self, state: PendulumState, action, key):
        del key
        u = jnp.clip(
            jnp.reshape(action, ()), -self.max_torque, self.max_torque
        )
        th = _angle_normalize(state.theta)
        cost = th**2 + 0.1 * state.theta_dot**2 + 0.001 * u**2

        new_theta_dot = state.theta_dot + (
            3.0 * self.g / (2.0 * self.l) * jnp.sin(state.theta)
            + 3.0 / (self.m * self.l**2) * u
        ) * self.dt
        new_theta_dot = jnp.clip(new_theta_dot, -self.max_speed, self.max_speed)
        new_theta = state.theta + new_theta_dot * self.dt
        t = state.t + 1

        new_state = PendulumState(new_theta, new_theta_dot, t)
        terminated = jnp.asarray(False)
        truncated = t >= self.max_episode_steps
        return (
            new_state,
            self._obs(new_state),
            -cost.astype(jnp.float32),
            terminated,
            truncated,
        )
