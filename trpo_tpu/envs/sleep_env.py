"""A gymnasium env whose ``step`` blocks in ``time.sleep`` — the overlap
probe for :class:`trpo_tpu.envs.proc_env.ProcVecEnv` (VERDICT r4 item 4).

A process pool's reason to exist is overlap, but this box has one core,
so CPU-bound stepping (real MuJoCo) cannot demonstrate it here.  A
*blocking* step can: ``time.sleep`` releases the core, so W workers
stepping sleep-bound envs complete a fixed step budget in ~serial/W
wall-clock even on one core — the same concurrency structure real
multicore stepping exploits, minus the arithmetic.  Used by
``tests/test_proc_env.py::test_worker_pool_overlap_wallclock`` and
``scripts/proc_overlap_r05.py`` (the BENCH_LADDER row).

The reference steps ONE env serially in-process (``utils.py:18-45``).
"""

from __future__ import annotations

import time

import gymnasium
import numpy as np

__all__ = ["SleepEnv"]


class SleepEnv(gymnasium.Env):
    """4-dim Box obs, 2 discrete actions; ``step`` sleeps ``sleep_ms``."""

    metadata = {"render_modes": []}

    def __init__(self, sleep_ms: float = 2.0, episode_len: int = 1000):
        self.observation_space = gymnasium.spaces.Box(
            -1.0, 1.0, shape=(4,), dtype=np.float32
        )
        self.action_space = gymnasium.spaces.Discrete(2)
        self._sleep_s = float(sleep_ms) * 1e-3
        self._episode_len = int(episode_len)
        self._t = 0
        self._rng = np.random.default_rng(0)

    def reset(self, *, seed=None, options=None):
        super().reset(seed=seed)
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._t = 0
        return self._obs(), {}

    def _obs(self):
        return self._rng.standard_normal(4).astype(np.float32)

    def step(self, action):
        time.sleep(self._sleep_s)
        self._t += 1
        truncated = self._t >= self._episode_len
        return self._obs(), float(action), False, truncated, {}
