"""Device-env wrappers (pure-JAX, jittable).

:class:`MaskObservation` projects observations onto a subset of indices —
the standard way to turn a fully observable classic-control task into a
POMDP (e.g. CartPole with velocities hidden: the policy must estimate them
from history, which requires memory — ``models/recurrent.py``). No
reference analogue (the reference is fully observable by construction).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp

__all__ = ["MaskObservation"]


class MaskObservation:
    """Keep only ``indices`` of a 1-D observation; dynamics untouched.

    Wraps any pure-JAX env (``reset``/``step``/``obs_shape``/``action_spec``
    protocol, ``envs.is_device_env``).
    """

    def __init__(self, env, indices: Sequence[int]):
        if len(env.obs_shape) != 1:
            raise ValueError(
                f"MaskObservation needs 1-D observations, got {env.obs_shape}"
            )
        dim = env.obs_shape[0]
        bad = [i for i in indices if not 0 <= i < dim]
        if bad or not indices:
            raise ValueError(
                f"indices {list(indices)} invalid for obs dim {dim}"
            )
        self.env = env
        self.indices = jnp.asarray(tuple(indices), jnp.int32)
        self.obs_shape: Tuple[int, ...] = (len(indices),)
        self.action_spec = env.action_spec

    def __getattr__(self, name):  # delegate e.g. max_episode_steps
        return getattr(self.env, name)

    def reset(self, key):
        state, obs = self.env.reset(key)
        return state, obs[self.indices]

    def step(self, state, action, key):
        state, obs, reward, terminated, truncated = self.env.step(
            state, action, key
        )
        return state, obs[self.indices], reward, terminated, truncated
