"""Vectorized gymnasium adapter for host-side simulators (MuJoCo, Atari).

The reference steps exactly one gym env from Python (``utils.py:18-45``).
This adapter runs N envs (``BASELINE.json``: "8 vectorized envs"), exposes
the auto-reset bookkeeping the device rollout needs (true pre-reset successor
observations for truncation bootstrapping), and tracks episode returns /
lengths the same way the device path does.

gymnasium is an optional dependency: importing this module without it raises
with a clear message, and env ids whose backends (mujoco, ale-py) are absent
raise at construction — callers gate on availability (see
``trpo_tpu.envs.make``).
"""

from __future__ import annotations

import numpy as np

from trpo_tpu.envs.episode_stats import EpisodeStatsMixin
from trpo_tpu.models.policy import BoxSpec, DiscreteSpec

__all__ = ["GymVecEnv"]


class GymVecEnv(EpisodeStatsMixin):
    """N synchronous gymnasium envs with explicit pre-reset final obs."""

    def __init__(self, env_id: str, n_envs: int = 8, seed: int = 0, **kwargs):
        try:
            import gymnasium
        except ImportError as e:  # pragma: no cover
            raise ImportError(
                "gymnasium is required for gym:* envs; use the pure-JAX envs "
                "('cartpole', 'pendulum') otherwise"
            ) from e
        self._gym = gymnasium
        self.env_id = env_id
        self.n_envs = n_envs
        self.envs = [gymnasium.make(env_id, **kwargs) for _ in range(n_envs)]
        single = self.envs[0]
        self.obs_shape = tuple(single.observation_space.shape)
        space = single.action_space
        if hasattr(space, "n"):
            self.action_spec = DiscreteSpec(int(space.n))
            self._continuous = False
        else:
            self.action_spec = BoxSpec(int(space.shape[0]))
            self._continuous = True
            self._act_low = np.asarray(space.low, np.float32)
            self._act_high = np.asarray(space.high, np.float32)

        self._obs = np.stack(
            [env.reset(seed=seed + i)[0] for i, env in enumerate(self.envs)]
        )
        self._init_episode_stats(n_envs)

    def host_step(self, actions: np.ndarray):
        """Step all envs; auto-reset finished ones.

        Returns ``(next_obs, rewards, terminated, truncated, final_obs)``
        where ``final_obs`` is the TRUE successor observation (pre-reset) —
        the quantity needed to bootstrap truncated episodes, which the
        reference's rollout loses (``utils.py:44``).
        """
        n = self.n_envs
        next_obs = np.empty_like(self._obs)
        final_obs = np.empty_like(self._obs)
        rewards = np.zeros(n, np.float32)
        terminated = np.zeros(n, bool)
        truncated = np.zeros(n, bool)

        for i, env in enumerate(self.envs):
            a = actions[i]
            if self._continuous:
                a = np.clip(a, self._act_low, self._act_high)
            obs_i, r, term, trunc, _info = env.step(a)
            rewards[i] = r
            terminated[i] = term
            truncated[i] = trunc
            final_obs[i] = obs_i
            if term or trunc:
                obs_i, _ = env.reset()
            next_obs[i] = obs_i

        self._update_episode_stats(
            rewards, np.logical_or(terminated, truncated)
        )

        self._obs = next_obs
        return next_obs, rewards, terminated, truncated, final_obs

    def reset_all(self, seed=None) -> np.ndarray:
        """Hard-reset every env (fresh episodes); returns the new obs batch.

        Auto-reset inside ``host_step`` covers steady-state training; this
        is for callers that need episode boundaries under their own control
        (e.g. reference-style serial rollouts, reproducible evaluation —
        ``seed`` reseeds env ``i`` with ``seed + i``)."""
        self._obs = np.stack(
            [
                env.reset(seed=None if seed is None else seed + i)[0]
                for i, env in enumerate(self.envs)
            ]
        )
        self._running_returns[:] = 0.0
        self._running_lengths[:] = 0
        return self._obs

    def current_obs(self) -> np.ndarray:
        return self._obs

    def close(self):
        for env in self.envs:
            env.close()
