"""Vectorized gymnasium adapter for host-side simulators (MuJoCo, Atari).

The reference steps exactly one gym env from Python (``utils.py:18-45``).
This adapter runs N envs (``BASELINE.json``: "8 vectorized envs"), exposes
the auto-reset bookkeeping the device rollout needs (true pre-reset successor
observations for truncation bootstrapping), and tracks episode returns /
lengths the same way the device path does.

gymnasium is an optional dependency: importing this module without it raises
with a clear message, and env ids whose backends (mujoco, ale-py) are absent
raise at construction — callers gate on availability (see
``trpo_tpu.envs.make``).
"""

from __future__ import annotations

import numpy as np

from trpo_tpu.envs.episode_stats import EpisodeStatsMixin
from trpo_tpu.envs.obs_norm import ObsNormMixin
from trpo_tpu.models.policy import BoxSpec, DiscreteSpec

__all__ = ["GymVecEnv"]


class GymVecEnv(EpisodeStatsMixin, ObsNormMixin):
    """N synchronous gymnasium envs with explicit pre-reset final obs."""

    def __init__(self, env_id: str, n_envs: int = 8, seed: int = 0,
                 normalize_obs: bool = False, **kwargs):
        try:
            import gymnasium
        except ImportError as e:  # pragma: no cover
            raise ImportError(
                "gymnasium is required for gym:* envs; use the pure-JAX envs "
                "('cartpole', 'pendulum') otherwise"
            ) from e
        self._gym = gymnasium
        self.env_id = env_id
        self.n_envs = n_envs
        self.envs = []
        try:
            for _ in range(n_envs):
                self.envs.append(gymnasium.make(env_id, **kwargs))
        except Exception as e:
            # release whatever was constructed before the failure (native
            # simulator / render contexts don't wait for GC politely)
            for env in self.envs:
                try:
                    env.close()
                except Exception:
                    pass
            # Re-diagnose ONLY missing-dependency failures (absent ale-py
            # for ALE/*, absent mujoco for MuJoCo ids); anything else —
            # typo'd ids, bad kwargs — propagates gymnasium's own
            # accurate error (e.g. "did you mean CartPole-v1")
            err_mod = getattr(gymnasium, "error", None)
            dep_types = tuple(
                t
                for t in (
                    ImportError,
                    getattr(err_mod, "DependencyNotInstalled", None),
                    getattr(err_mod, "NamespaceNotFound", None),
                )
                if isinstance(t, type)
            )
            if not isinstance(e, dep_types):
                raise
            raise RuntimeError(
                f"could not construct gym env {env_id!r}: {e}\n"
                "The id's simulator backend is likely not installed "
                "(ALE/* needs the 'ale-py' package; MuJoCo ids need "
                "'mujoco'). Install it, or use an on-device stand-in: "
                "'pong-sim' (84x84x4 pixel rung) for ALE/Pong, "
                "'humanoid-sim'/'halfcheetah-sim' for the MuJoCo rungs."
            ) from e
        single = self.envs[0]
        self.obs_shape = tuple(single.observation_space.shape)
        space = single.action_space
        if hasattr(space, "n"):
            self.action_spec = DiscreteSpec(int(space.n))
            self._continuous = False
        else:
            self.action_spec = BoxSpec(int(space.shape[0]))
            self._continuous = True
            self._act_low = np.asarray(space.low, np.float32)
            self._act_high = np.asarray(space.high, np.float32)

        # Shared running obs normalization (ONE statistics object across
        # all envs): ObsNormMixin — the host analogue of the device path's
        # fused RunningStats (utils/normalize.py), shared with NativeVecEnv.
        self._init_obs_norm(self.obs_shape, normalize_obs)

        self._obs = self._fold_and_normalize(
            np.stack(
                [
                    env.reset(seed=seed + i)[0]
                    for i, env in enumerate(self.envs)
                ]
            )
        )
        self._init_episode_stats(n_envs)

    def host_step(self, actions: np.ndarray):
        """Step all envs; auto-reset finished ones.

        Returns ``(next_obs, rewards, terminated, truncated, final_obs)``
        where ``final_obs`` is the TRUE successor observation (pre-reset) —
        the quantity needed to bootstrap truncated episodes, which the
        reference's rollout loses (``utils.py:44``).
        """
        return self.host_step_slice(actions, 0, self.n_envs)

    def host_step_slice(self, actions: np.ndarray, lo: int, hi: int):
        """Step only envs ``[lo, hi)`` — same per-env contract as
        :meth:`host_step` with every array sliced to the group.

        This is the group-stepping surface ``rollout.pipelined_host_rollout``
        drives: one group steps on the host while another group's policy
        inference is in flight on the device. Episode stats and the shared
        normalization statistics update for the slice only; normalization
        folds once per group step (associative merge — same limit as the
        full-batch fold)."""
        m = hi - lo
        next_obs = np.empty((m,) + self._obs.shape[1:], self._obs.dtype)
        final_obs = np.empty_like(next_obs)
        rewards = np.zeros(m, np.float32)
        terminated = np.zeros(m, bool)
        truncated = np.zeros(m, bool)

        for j, env in enumerate(self.envs[lo:hi]):
            a = actions[j]
            if self._continuous:
                a = np.clip(a, self._act_low, self._act_high)
            obs_j, r, term, trunc, _info = env.step(a)
            rewards[j] = r
            terminated[j] = term
            truncated[j] = trunc
            final_obs[j] = obs_j
            if term or trunc:
                obs_j, _ = env.reset()
            next_obs[j] = obs_j

        self._update_episode_stats_slice(
            rewards, np.logical_or(terminated, truncated), lo, hi
        )

        # one shared-stats fold per (group) step; final_obs (truncation
        # bootstrap successors) normalized with the same statistics — under
        # the same lock hold — not re-folded
        next_obs, final_obs = self._fold_and_normalize_slice(
            next_obs, lo, hi, extra=final_obs
        )
        self._obs[lo:hi] = next_obs
        return next_obs, rewards, terminated, truncated, final_obs

    def reset_all(self, seed=None) -> np.ndarray:
        """Hard-reset every env (fresh episodes); returns the new obs batch.

        Auto-reset inside ``host_step`` covers steady-state training; this
        is for callers that need episode boundaries under their own control
        (e.g. reference-style serial rollouts, reproducible evaluation —
        ``seed`` reseeds env ``i`` with ``seed + i``)."""
        self._obs = self._fold_and_normalize(
            np.stack(
                [
                    env.reset(seed=None if seed is None else seed + i)[0]
                    for i, env in enumerate(self.envs)
                ]
            )
        )
        self._running_returns[:] = 0.0
        self._running_lengths[:] = 0
        # a copy: group stepping updates the cache in place
        return self._obs.copy()

    def current_obs(self) -> np.ndarray:
        # a copy: group stepping (host_step_slice) updates the cache in
        # place, and callers buffer what this returns
        return self._obs.copy()

    # -- checkpoint fidelity (best-effort for external simulators) --------
    #
    # Per-env capture/restore lives in envs/gym_state.py (shared with the
    # process-based ProcVecEnv's jax-free workers).

    def env_state_snapshot(self) -> dict:
        """Best-effort mid-episode resume state (SURVEY §5 checkpoint
        obligation): episode counters + obs cache always; per-env
        simulator state where the backend exposes it — MuJoCo
        (qpos/qvel/time via ``MujocoEnv.set_state``) and classic control
        (the ``state`` attribute). Envs whose simulator hides its state
        snapshot as ``None`` and restart episodes on restore (documented
        restart semantics; obs-norm statistics ride TrainState either
        way)."""
        from trpo_tpu.envs.gym_state import snapshot_one

        sims = [snapshot_one(env) for env in self.envs]
        snap = {
            "env_id": self.env_id,
            "sims": sims,
            "obs": self._obs.copy(),
            **self._episode_stats_snapshot(),
        }
        if self.has_obs_norm:
            snap["raw_obs"] = self._raw_obs.copy()
        return snap

    def env_state_restore(self, snap: dict) -> None:
        if snap.get("env_id") != self.env_id:
            raise ValueError(
                f"snapshot is for {snap.get('env_id')!r}, this adapter "
                f"is {self.env_id!r}"
            )
        if len(snap["sims"]) != self.n_envs:
            raise ValueError(
                f"snapshot holds {len(snap['sims'])} envs, this adapter "
                f"has {self.n_envs} — resume with the same n_envs"
            )
        if self.has_obs_norm and "raw_obs" not in snap:
            # silently continuing would leave _obs/_raw_obs inconsistent
            # (set_obs_stats_state re-normalizes from construction-time
            # raw obs while the simulator sits mid-episode)
            raise ValueError(
                "snapshot was taken without normalize_obs; resume with "
                "the same normalize_obs setting"
            )
        from trpo_tpu.envs.gym_state import restore_one

        reset_obs = {}
        for i, (env, sim) in enumerate(zip(self.envs, snap["sims"])):
            # opaque backend (restore_one returns the fresh episode's raw
            # obs): documented restart — this env must see the reset obs
            # and zeroed counters, not the dead pre-checkpoint episode's
            raw = restore_one(env, sim)
            if raw is not None:
                reset_obs[i] = raw
        self._obs = np.asarray(snap["obs"]).copy()
        if self.has_obs_norm and "raw_obs" in snap:
            self._raw_obs = np.asarray(snap["raw_obs"]).copy()
        self._episode_stats_restore(snap)
        for i, raw in reset_obs.items():
            if self.has_obs_norm:
                self._raw_obs[i] = raw
                with self._norm_lock:
                    self._obs[i] = self._apply_norm(raw)
            else:
                self._obs[i] = raw
            self._running_returns[i] = 0.0
            self._running_lengths[i] = 0

    def render_frame(self) -> np.ndarray:
        """RGB frame of env 0 — eval-time rendering (the reference renders
        inside eval-mode ``act``, ``trpo_inksci.py:82``; here a pull-based
        hook the agent's ``evaluate(render=True)`` drives per step).
        Requires construction with ``render_mode="rgb_array"`` (forwarded
        to ``gymnasium.make`` via ``**kwargs``)."""
        frame = self.envs[0].render()
        if frame is None:
            raise RuntimeError(
                "rendering returned None — construct the adapter with "
                "GymVecEnv(env_id, render_mode='rgb_array') (or pass "
                "render_mode through envs.make('gym:<Id>', "
                "render_mode='rgb_array'))"
            )
        return np.asarray(frame)

    def close(self):
        for env in self.envs:
            env.close()
