"""Vectorized gymnasium adapter for host-side simulators (MuJoCo, Atari).

The reference steps exactly one gym env from Python (``utils.py:18-45``).
This adapter runs N envs (``BASELINE.json``: "8 vectorized envs"), exposes
the auto-reset bookkeeping the device rollout needs (true pre-reset successor
observations for truncation bootstrapping), and tracks episode returns /
lengths the same way the device path does.

gymnasium is an optional dependency: importing this module without it raises
with a clear message, and env ids whose backends (mujoco, ale-py) are absent
raise at construction — callers gate on availability (see
``trpo_tpu.envs.make``).
"""

from __future__ import annotations

import threading

import numpy as np

from trpo_tpu.envs.episode_stats import EpisodeStatsMixin
from trpo_tpu.models.policy import BoxSpec, DiscreteSpec

__all__ = ["GymVecEnv"]


class GymVecEnv(EpisodeStatsMixin):
    """N synchronous gymnasium envs with explicit pre-reset final obs."""

    def __init__(self, env_id: str, n_envs: int = 8, seed: int = 0,
                 normalize_obs: bool = False, **kwargs):
        try:
            import gymnasium
        except ImportError as e:  # pragma: no cover
            raise ImportError(
                "gymnasium is required for gym:* envs; use the pure-JAX envs "
                "('cartpole', 'pendulum') otherwise"
            ) from e
        self._gym = gymnasium
        self.env_id = env_id
        self.n_envs = n_envs
        self.envs = [gymnasium.make(env_id, **kwargs) for _ in range(n_envs)]
        single = self.envs[0]
        self.obs_shape = tuple(single.observation_space.shape)
        space = single.action_space
        if hasattr(space, "n"):
            self.action_spec = DiscreteSpec(int(space.n))
            self._continuous = False
        else:
            self.action_spec = BoxSpec(int(space.shape[0]))
            self._continuous = True
            self._act_low = np.asarray(space.low, np.float32)
            self._act_high = np.asarray(space.high, np.float32)

        # Shared running obs normalization (ONE statistics object across all
        # envs — the host analogue of the device path's fused RunningStats,
        # utils/normalize.py). The agent mirrors these into TrainState every
        # iteration so checkpoints carry them, and freezes them during
        # evaluation.
        self.has_obs_norm = bool(normalize_obs)
        self._norm_frozen = False
        # group-stepping threads (pipelined rollout) share these statistics;
        # the lock keeps the read-modify-write merge atomic per fold
        self._norm_lock = threading.Lock()
        if self.has_obs_norm:
            self._n_count = 0.0
            self._n_mean = np.zeros(self.obs_shape, np.float64)
            self._n_m2 = np.zeros(self.obs_shape, np.float64)

        self._obs = self._fold_and_normalize(
            np.stack(
                [
                    env.reset(seed=seed + i)[0]
                    for i, env in enumerate(self.envs)
                ]
            )
        )
        self._init_episode_stats(n_envs)

    # -- shared running obs normalization ---------------------------------

    def _fold(self, obs_batch: np.ndarray) -> None:
        """Chan/Welford-merge a raw batch into the shared statistics — the
        same math as ``utils/normalize.update_stats``."""
        b = np.asarray(obs_batch, np.float64)
        n_b = float(b.shape[0])
        mean_b = b.mean(axis=0)
        m2_b = ((b - mean_b) ** 2).sum(axis=0)
        delta = mean_b - self._n_mean
        tot = self._n_count + n_b
        self._n_mean = self._n_mean + delta * (n_b / tot)
        self._n_m2 = self._n_m2 + m2_b + delta**2 * (
            self._n_count * n_b / tot
        )
        self._n_count = tot

    def _fold_and_normalize(self, obs_batch: np.ndarray) -> np.ndarray:
        """Fold a raw ``(N, *obs)`` batch into the shared statistics (unless
        frozen) and return it normalized."""
        if not self.has_obs_norm:
            return obs_batch
        # keep the raw batch: installing restored statistics later must be
        # able to re-normalize the cached current obs (set_obs_stats_state)
        self._raw_obs = np.asarray(obs_batch).copy()
        if not self._norm_frozen:
            self._fold(obs_batch)
        return self._apply_norm(obs_batch)

    def _fold_and_normalize_slice(
        self, obs_batch: np.ndarray, lo: int, hi: int, extra=None
    ):
        """Slice variant for group stepping: raw rows ``[lo, hi)`` replace
        their cache entries, the slice folds into the SAME shared statistics
        (one fold per group step instead of per full step — the merge is
        associative, so the statistics converge identically), and the slice
        comes back normalized under the statistics as of now. ``extra`` (the
        truncation-bootstrap ``final_obs``) is normalized under the SAME
        statistics snapshot, inside the same lock hold — a concurrent group
        thread's fold must never be observed mid-update."""
        if not self.has_obs_norm:
            return obs_batch if extra is None else (obs_batch, extra)
        self._raw_obs[lo:hi] = obs_batch
        with self._norm_lock:
            if not self._norm_frozen:
                self._fold(obs_batch)
            normed = self._apply_norm(obs_batch)
            if extra is None:
                return normed
            return normed, self._apply_norm(extra)

    def _apply_norm(self, obs: np.ndarray) -> np.ndarray:
        if not self.has_obs_norm or self._n_count == 0.0:
            return obs
        var = self._n_m2 / max(self._n_count, 1.0)
        std = np.sqrt(var + 1e-8)
        return np.clip(
            (obs - self._n_mean) / std, -10.0, 10.0
        ).astype(np.float32)

    def obs_stats_state(self):
        """(count, mean, m2) float32 arrays — the checkpointable mirror."""
        if not self.has_obs_norm:
            return None
        return (
            np.float32(self._n_count),
            self._n_mean.astype(np.float32),
            self._n_m2.astype(np.float32),
        )

    def set_obs_stats_state(self, state) -> None:
        """Install (count, mean, m2) — e.g. restored from a checkpoint.

        The cached current observations are re-normalized under the new
        statistics so the next rollout's first step is consistent with the
        rest of its batch."""
        count, mean, m2 = state
        self._n_count = float(count)
        self._n_mean = np.asarray(mean, np.float64)
        self._n_m2 = np.asarray(m2, np.float64)
        self._obs = self._apply_norm(self._raw_obs)

    def freeze_obs_stats(self, frozen: bool = True) -> None:
        """Stop/resume folding new data in (evaluation must not shift the
        training statistics)."""
        self._norm_frozen = frozen

    def host_step(self, actions: np.ndarray):
        """Step all envs; auto-reset finished ones.

        Returns ``(next_obs, rewards, terminated, truncated, final_obs)``
        where ``final_obs`` is the TRUE successor observation (pre-reset) —
        the quantity needed to bootstrap truncated episodes, which the
        reference's rollout loses (``utils.py:44``).
        """
        return self.host_step_slice(actions, 0, self.n_envs)

    def host_step_slice(self, actions: np.ndarray, lo: int, hi: int):
        """Step only envs ``[lo, hi)`` — same per-env contract as
        :meth:`host_step` with every array sliced to the group.

        This is the group-stepping surface ``rollout.pipelined_host_rollout``
        drives: one group steps on the host while another group's policy
        inference is in flight on the device. Episode stats and the shared
        normalization statistics update for the slice only; normalization
        folds once per group step (associative merge — same limit as the
        full-batch fold)."""
        m = hi - lo
        next_obs = np.empty((m,) + self._obs.shape[1:], self._obs.dtype)
        final_obs = np.empty_like(next_obs)
        rewards = np.zeros(m, np.float32)
        terminated = np.zeros(m, bool)
        truncated = np.zeros(m, bool)

        for j, env in enumerate(self.envs[lo:hi]):
            a = actions[j]
            if self._continuous:
                a = np.clip(a, self._act_low, self._act_high)
            obs_j, r, term, trunc, _info = env.step(a)
            rewards[j] = r
            terminated[j] = term
            truncated[j] = trunc
            final_obs[j] = obs_j
            if term or trunc:
                obs_j, _ = env.reset()
            next_obs[j] = obs_j

        self._update_episode_stats_slice(
            rewards, np.logical_or(terminated, truncated), lo, hi
        )

        # one shared-stats fold per (group) step; final_obs (truncation
        # bootstrap successors) normalized with the same statistics — under
        # the same lock hold — not re-folded
        next_obs, final_obs = self._fold_and_normalize_slice(
            next_obs, lo, hi, extra=final_obs
        )
        self._obs[lo:hi] = next_obs
        return next_obs, rewards, terminated, truncated, final_obs

    def reset_all(self, seed=None) -> np.ndarray:
        """Hard-reset every env (fresh episodes); returns the new obs batch.

        Auto-reset inside ``host_step`` covers steady-state training; this
        is for callers that need episode boundaries under their own control
        (e.g. reference-style serial rollouts, reproducible evaluation —
        ``seed`` reseeds env ``i`` with ``seed + i``)."""
        self._obs = self._fold_and_normalize(
            np.stack(
                [
                    env.reset(seed=None if seed is None else seed + i)[0]
                    for i, env in enumerate(self.envs)
                ]
            )
        )
        self._running_returns[:] = 0.0
        self._running_lengths[:] = 0
        # a copy: group stepping updates the cache in place
        return self._obs.copy()

    def current_obs(self) -> np.ndarray:
        # a copy: group stepping (host_step_slice) updates the cache in
        # place, and callers buffer what this returns
        return self._obs.copy()

    def close(self):
        for env in self.envs:
            env.close()
