"""Scalable pure-JAX continuous-control locomotion envs.

The BASELINE.json ladder's upper rungs are MuJoCo tasks — HalfCheetah-v2
(17-dim obs, 6-dim actions) and Humanoid-v2 (376-dim obs, 17-dim actions,
the "large FVP matvec" config). MuJoCo binaries are not part of this image
(real MuJoCo runs go through ``envs.make("gym:HalfCheetah-v4")`` when
available), so this module provides *dimension-faithful* stand-ins that run
entirely on device: a damped mass-spring chain driven by per-joint torques,
rewarded for forward velocity minus a control cost — the HalfCheetah reward
shape (forward_reward - ctrl_cost) at the same observation/action widths.

Why a chain and not a rigid-body simulator: the framework obligation
(SURVEY §6) is the *natural-gradient solve at Humanoid scale*, which is a
function of obs/act/param dimensions and batch size, not of contact
dynamics. The chain gives honest nontrivial dynamics (coupled oscillators,
velocity damping, control-cost tradeoff — a real RL problem TRPO visibly
improves) with exact gym-style semantics, while every tensor shape matches
the MuJoCo rung it stands in for.

Observation: base features ``[spring extensions (n-1), velocities (n)]``
lifted to ``obs_dim`` by a fixed random projection (seeded constant — the
same matrix for every instance), mirroring how MuJoCo observations are a
redundant nonlinear expansion of a lower-dimensional state.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from trpo_tpu.models.policy import BoxSpec

__all__ = ["ChainLocomotion", "HalfCheetahSim", "HumanoidSim"]


class ChainState(NamedTuple):
    pos: jax.Array   # (n,) absolute mass positions
    vel: jax.Array   # (n,) velocities
    t: jax.Array     # scalar int32 step counter


class ChainLocomotion:
    """N coupled masses on a line; action = per-mass force in [-1, 1].

    Dynamics (semi-implicit Euler):
        acc  = -k·(L q) - c·v + gear·clip(a, -1, 1)
        v'   = v + dt·acc ;  q' = q + dt·v'
    with ``L`` the chain-graph Laplacian (nearest-neighbour springs).
    Reward = mean forward velocity − ctrl_cost·mean(a²), matching the
    HalfCheetah reward decomposition. No termination (like HalfCheetah);
    episodes truncate at ``max_episode_steps``.
    """

    spring_k = 4.0
    damping = 1.0
    gear = 2.0
    dt = 0.05
    ctrl_cost = 0.1
    _OBS_SEED = 7  # fixed: every instance shares one projection matrix

    def __init__(
        self,
        n_masses: int = 6,
        obs_dim: int = 17,
        max_episode_steps: int = 500,
    ):
        if n_masses < 2:
            raise ValueError("need at least 2 masses for a chain")
        self.n_masses = n_masses
        self.obs_dim = obs_dim
        self.max_episode_steps = max_episode_steps
        self.obs_shape = (obs_dim,)
        self.action_spec = BoxSpec(n_masses)

        base_dim = 2 * n_masses - 1  # extensions + velocities
        # Fixed projection, row-normalized so obs components are O(1).
        w = jax.random.normal(
            jax.random.key(self._OBS_SEED), (obs_dim, base_dim), jnp.float32
        )
        self._w = w / jnp.linalg.norm(w, axis=1, keepdims=True)

    def reset(self, key):
        k_pos, k_vel = jax.random.split(key)
        n = self.n_masses
        # Rest spacing 1.0 with small perturbations — near equilibrium.
        pos = jnp.arange(n, dtype=jnp.float32) + 0.05 * jax.random.normal(
            k_pos, (n,), jnp.float32
        )
        vel = 0.05 * jax.random.normal(k_vel, (n,), jnp.float32)
        state = ChainState(pos, vel, jnp.asarray(0, jnp.int32))
        return state, self._obs(state)

    def _obs(self, s: ChainState):
        ext = jnp.diff(s.pos) - 1.0   # deviation from rest length
        base = jnp.concatenate([ext, s.vel])
        return self._w @ base

    def step(self, state: ChainState, action, key):
        del key
        a = jnp.clip(jnp.reshape(action, (self.n_masses,)), -1.0, 1.0)

        ext = jnp.diff(state.pos) - 1.0
        # Spring forces: mass i feels +k·ext[i] from the right neighbour
        # and −k·ext[i-1] from the left — the chain Laplacian on positions.
        f_spring = self.spring_k * (
            jnp.concatenate([ext, jnp.zeros(1)])
            - jnp.concatenate([jnp.zeros(1), ext])
        )
        acc = f_spring - self.damping * state.vel + self.gear * a
        vel = state.vel + self.dt * acc
        pos = state.pos + self.dt * vel
        t = state.t + 1
        new_state = ChainState(pos, vel, t)

        forward_reward = jnp.mean(vel)
        ctrl = self.ctrl_cost * jnp.mean(a**2)
        reward = (forward_reward - ctrl).astype(jnp.float32)

        terminated = jnp.asarray(False)
        truncated = t >= self.max_episode_steps
        return new_state, self._obs(new_state), reward, terminated, truncated


class HalfCheetahSim(ChainLocomotion):
    """HalfCheetah-v2-shaped rung: 17-dim obs, 6-dim actions
    (BASELINE.json config 3)."""

    def __init__(self, max_episode_steps: int = 500):
        super().__init__(
            n_masses=6, obs_dim=17, max_episode_steps=max_episode_steps
        )


class HumanoidSim(ChainLocomotion):
    """Humanoid-v2-shaped rung: 376-dim obs, 17-dim actions — the
    BASELINE.json "large FVP matvec" config (config 4)."""

    def __init__(self, max_episode_steps: int = 500):
        super().__init__(
            n_masses=17, obs_dim=376, max_episode_steps=max_episode_steps
        )
