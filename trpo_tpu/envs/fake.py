"""Deterministic scripted environment for tests (SURVEY §4 "env fakes").

A fixed-length chain: observation is a one-hot of the current position,
reward equals ``position · reward_scale`` when action 1 is taken (else 0),
the episode terminates after ``chain_len`` steps. Everything about a rollout
against it (returns, advantages, episode packing) is computable by hand, so
rollout/advantage tests need no simulator.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from trpo_tpu.models.policy import DiscreteSpec


class FakeState(NamedTuple):
    pos: jax.Array
    t: jax.Array


class FakeEnv:
    def __init__(self, chain_len: int = 5, reward_scale: float = 1.0):
        self.chain_len = chain_len
        self.reward_scale = reward_scale
        self.obs_shape = (chain_len,)
        self.action_spec = DiscreteSpec(2)
        self.max_episode_steps = chain_len

    def reset(self, key):
        del key
        state = FakeState(
            pos=jnp.asarray(0, jnp.int32), t=jnp.asarray(0, jnp.int32)
        )
        return state, self._obs(state)

    def _obs(self, s: FakeState):
        return jax.nn.one_hot(s.pos, self.chain_len, dtype=jnp.float32)

    def step(self, state: FakeState, action, key):
        del key
        reward = jnp.where(
            action == 1, state.pos * self.reward_scale, 0.0
        ).astype(jnp.float32)
        pos = jnp.minimum(state.pos + 1, self.chain_len - 1)
        t = state.t + 1
        new_state = FakeState(pos=pos, t=t)
        terminated = t >= self.chain_len
        truncated = jnp.asarray(False)
        return new_state, self._obs(new_state), reward, terminated, truncated
