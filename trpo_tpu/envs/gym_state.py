"""Per-env gymnasium state capture/restore — shared, jax-free.

Used by both :class:`trpo_tpu.envs.gym_adapter.GymVecEnv` (in-process) and
the :class:`trpo_tpu.envs.proc_env.ProcVecEnv` worker processes. Worker
processes must stay jax-free (this box routes every jax backend init
through a single-tenant TPU tunnel — see ``tests/conftest.py``), so this
module imports numpy only.

Capture is best-effort per backend (SURVEY §5 checkpoint obligation):
MuJoCo (qpos/qvel/ctrl/warmstart/time via ``MujocoEnv.set_state``), classic
control (the ``state`` attribute), and ``None`` for opaque simulators —
which restart their episode on restore (documented semantics). The
episode-reset RNG (``np_random`` bit-generator state) rides along so a
resumed run replays the SAME resets the uninterrupted run would have.
"""

from __future__ import annotations

import numpy as np

__all__ = ["find_time_limit", "snapshot_one", "restore_one"]


def find_time_limit(env):
    """The wrapper carrying TimeLimit's ``_elapsed_steps``, wherever it
    sits in the chain; None when the env has no TimeLimit."""
    e = env
    while e is not None and e is not getattr(e, "unwrapped", None):
        if hasattr(e, "_elapsed_steps"):
            return e
        e = getattr(e, "env", None)
    return None


def snapshot_one(env):
    """Best-effort state dict for one wrapped gymnasium env (or None)."""
    u = env.unwrapped
    tl = find_time_limit(env)
    elapsed = None if tl is None else tl._elapsed_steps
    rng_state = None
    np_random = getattr(u, "np_random", None)
    if np_random is not None and hasattr(np_random, "bit_generator"):
        rng_state = np_random.bit_generator.state
    if hasattr(u, "data") and hasattr(u, "set_state"):
        return {
            "backend": "mujoco",
            "qpos": np.asarray(u.data.qpos, np.float64).copy(),
            "qvel": np.asarray(u.data.qvel, np.float64).copy(),
            "ctrl": np.asarray(u.data.ctrl, np.float64).copy(),
            "qacc_warmstart": np.asarray(
                u.data.qacc_warmstart, np.float64
            ).copy(),
            "time": float(u.data.time),
            "elapsed": elapsed,
            "np_random": rng_state,
        }
    if getattr(u, "state", None) is not None:
        return {
            "backend": "state",
            "state": np.asarray(u.state, np.float64).copy(),
            "elapsed": elapsed,
            "np_random": rng_state,
        }
    return None  # opaque simulator — restart on restore


def restore_one(env, sim):
    """Install ``sim`` (from :func:`snapshot_one`) into ``env``.

    ``sim=None`` (opaque backend): resets the env and returns the fresh
    episode's raw observation — the caller must surface it (obs cache,
    zeroed episode counters). Otherwise returns None."""
    if sim is None:
        obs, _ = env.reset()
        return np.asarray(obs)
    u = env.unwrapped
    # reset first: wrappers (TimeLimit) and lazy backend state need a
    # live episode to overwrite
    env.reset()
    if sim["backend"] == "mujoco":
        u.set_state(sim["qpos"], sim["qvel"])
        u.data.time = sim["time"]
        if sim.get("ctrl") is not None:
            u.data.ctrl[:] = sim["ctrl"]
        if sim.get("qacc_warmstart") is not None:
            u.data.qacc_warmstart[:] = sim["qacc_warmstart"]
    else:
        u.state = np.asarray(sim["state"], np.float64)
    if sim.get("np_random") is not None:
        u.np_random.bit_generator.state = sim["np_random"]
    if sim.get("elapsed") is not None:
        tl = find_time_limit(env)
        if tl is not None:
            tl._elapsed_steps = sim["elapsed"]
    return None
