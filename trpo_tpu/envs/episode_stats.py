"""Shared episode-return/length bookkeeping for host vectorized envs.

Both host env families (``GymVecEnv``, ``NativeVecEnv``) expose
``last_episode_returns`` / ``last_episode_lengths`` snapshots that
``trpo_tpu.rollout`` and the agent's done-masked reward stats consume. The
ordering contract is subtle (snapshot *includes* the current step, and the
running accumulators reset *after* the snapshot, so a ``done`` step's
snapshot holds that episode's final totals) — so it lives here once rather
than being re-implemented per adapter.
"""

from __future__ import annotations

import numpy as np

__all__ = ["EpisodeStatsMixin"]


class EpisodeStatsMixin:
    """Mixin: call ``_init_episode_stats`` in ``__init__`` and
    ``_update_episode_stats`` once per ``host_step``."""

    def _init_episode_stats(self, n_envs: int) -> None:
        self.last_episode_returns = np.zeros(n_envs, np.float32)
        self.last_episode_lengths = np.zeros(n_envs, np.int64)
        self._running_returns = np.zeros(n_envs, np.float32)
        self._running_lengths = np.zeros(n_envs, np.int64)

    def _update_episode_stats(
        self, rewards: np.ndarray, ended: np.ndarray
    ) -> None:
        """Accumulate this step, snapshot, then zero finished episodes.

        On a step where ``ended[i]`` is True, ``last_episode_returns[i]`` /
        ``last_episode_lengths[i]`` hold episode totals including this final
        step — the value the done-masked episode stats read."""
        self._update_episode_stats_slice(rewards, ended, 0, len(rewards))

    def _update_episode_stats_slice(
        self, rewards: np.ndarray, ended: np.ndarray, lo: int, hi: int
    ) -> None:
        """Same contract for envs ``[lo, hi)`` only — the group-stepping
        path (``host_step_slice``) used by the pipelined rollout. Slices of
        the snapshot arrays are written in place; envs outside the slice
        keep their previous snapshot (they are mid-step elsewhere in the
        pipeline)."""
        self._running_returns[lo:hi] += rewards
        self._running_lengths[lo:hi] += 1
        self.last_episode_returns[lo:hi] = self._running_returns[lo:hi]
        self.last_episode_lengths[lo:hi] = self._running_lengths[lo:hi]
        self._running_returns[lo:hi][ended] = 0.0
        self._running_lengths[lo:hi][ended] = 0

    # -- checkpoint mirror -------------------------------------------------

    def _episode_stats_snapshot(self) -> dict:
        """Copy of the counters for host-env checkpoint sidecars (SURVEY §5
        checkpoint obligation; the device path carries its counters in
        TrainState)."""
        return {
            "running_returns": self._running_returns.copy(),
            "running_lengths": self._running_lengths.copy(),
            "last_returns": self.last_episode_returns.copy(),
            "last_lengths": self.last_episode_lengths.copy(),
        }

    def _episode_stats_restore(self, snap: dict) -> None:
        self._running_returns[:] = snap["running_returns"]
        self._running_lengths[:] = snap["running_lengths"]
        self.last_episode_returns[:] = snap["last_returns"]
        self.last_episode_lengths[:] = snap["last_lengths"]
