"""Shared episode-return/length bookkeeping for host vectorized envs.

Both host env families (``GymVecEnv``, ``NativeVecEnv``) expose
``last_episode_returns`` / ``last_episode_lengths`` snapshots that
``trpo_tpu.rollout`` and the agent's done-masked reward stats consume. The
ordering contract is subtle (snapshot *includes* the current step, and the
running accumulators reset *after* the snapshot, so a ``done`` step's
snapshot holds that episode's final totals) — so it lives here once rather
than being re-implemented per adapter.
"""

from __future__ import annotations

from collections import deque

import numpy as np

__all__ = ["EpisodeStatsMixin", "RunningEpisodeMean"]


class RunningEpisodeMean:
    """Cross-batch windowed running mean of completed-episode returns.

    Long-horizon rungs (e.g. HalfCheetah: 1000-step episodes vs 200-step
    per-env batches) complete zero episodes on most iterations, so the
    per-batch ``mean_episode_reward`` is honestly NaN 80% of the time —
    which pushed "last finite value" workarounds into every consumer
    (round-4 verdict weakness 5).  This carries the episode-weighted mean
    over the last ``window`` batches THAT COMPLETED EPISODES, so the
    logged ``reward_running`` is finite from the first finished episode
    onward and every consumer reads one field.

    Host-side by design: it aggregates the per-iteration stats the learn
    loop already fetched, works identically for the fused-device and
    host-simulator paths, and adds zero device state (checkpoint resume
    restarts the window, which re-warms within ``window`` batches).
    """

    def __init__(self, window: int = 100):
        self._entries: deque = deque(maxlen=int(window))  # (sum, count)

    def update(self, mean_reward: float, n_episodes: int) -> None:
        """Fold one batch's (per-batch mean, episode count) in; batches
        with no finished episode (count 0 / NaN mean) are no-ops."""
        n = int(n_episodes)
        if n > 0 and mean_reward == mean_reward:
            self._entries.append((float(mean_reward) * n, n))

    @property
    def count(self) -> int:
        """Episodes inside the current window."""
        return sum(c for _, c in self._entries)

    @property
    def mean(self) -> float:
        """Episode-weighted mean return over the window; NaN only before
        any episode has ever finished."""
        n = self.count
        if n == 0:
            return float("nan")
        return sum(s for s, _ in self._entries) / n


class EpisodeStatsMixin:
    """Mixin: call ``_init_episode_stats`` in ``__init__`` and
    ``_update_episode_stats`` once per ``host_step``."""

    def _init_episode_stats(self, n_envs: int) -> None:
        self.last_episode_returns = np.zeros(n_envs, np.float32)
        self.last_episode_lengths = np.zeros(n_envs, np.int64)
        self._running_returns = np.zeros(n_envs, np.float32)
        self._running_lengths = np.zeros(n_envs, np.int64)

    def _update_episode_stats(
        self, rewards: np.ndarray, ended: np.ndarray
    ) -> None:
        """Accumulate this step, snapshot, then zero finished episodes.

        On a step where ``ended[i]`` is True, ``last_episode_returns[i]`` /
        ``last_episode_lengths[i]`` hold episode totals including this final
        step — the value the done-masked episode stats read."""
        self._update_episode_stats_slice(rewards, ended, 0, len(rewards))

    def _update_episode_stats_slice(
        self, rewards: np.ndarray, ended: np.ndarray, lo: int, hi: int
    ) -> None:
        """Same contract for envs ``[lo, hi)`` only — the group-stepping
        path (``host_step_slice``) used by the pipelined rollout. Slices of
        the snapshot arrays are written in place; envs outside the slice
        keep their previous snapshot (they are mid-step elsewhere in the
        pipeline)."""
        self._running_returns[lo:hi] += rewards
        self._running_lengths[lo:hi] += 1
        self.last_episode_returns[lo:hi] = self._running_returns[lo:hi]
        self.last_episode_lengths[lo:hi] = self._running_lengths[lo:hi]
        self._running_returns[lo:hi][ended] = 0.0
        self._running_lengths[lo:hi][ended] = 0

    # -- checkpoint mirror -------------------------------------------------

    def _episode_stats_snapshot(self) -> dict:
        """Copy of the counters for host-env checkpoint sidecars (SURVEY §5
        checkpoint obligation; the device path carries its counters in
        TrainState)."""
        return {
            "running_returns": self._running_returns.copy(),
            "running_lengths": self._running_lengths.copy(),
            "last_returns": self.last_episode_returns.copy(),
            "last_lengths": self.last_episode_lengths.copy(),
        }

    def _episode_stats_restore(self, snap: dict) -> None:
        self._running_returns[:] = snap["running_returns"]
        self._running_lengths[:] = snap["running_lengths"]
        self.last_episode_returns[:] = snap["last_returns"]
        self.last_episode_lengths[:] = snap["last_lengths"]
