"""Process-based vectorized gymnasium adapter (VERDICT r3 item 6).

Python-stepped simulators (MuJoCo, Atari) serialize on the GIL, so the
threaded pipeline (``rollout.pipelined_host_rollout``) cannot overlap two
env groups' *stepping* — only stepping against device transfers. The
standard fix is a process pool: N envs split over W worker processes, each
stepping its contiguous slice in parallel, with actions/observations
crossing process boundaries over pipes.

Drop-in: :class:`ProcVecEnv` speaks exactly the :class:`GymVecEnv` surface
(``host_step`` / ``host_step_slice`` / ``reset_all`` / ``current_obs`` /
``env_state_snapshot`` / ``env_state_restore`` / ``render_frame`` /
episode stats / shared obs normalization), produces BIT-identical
trajectories to ``GymVecEnv`` for the same seed (same per-env seeding
``seed + i``, same auto-reset bookkeeping, same centralized normalization
fold — asserted by ``tests/test_proc_env.py``), and its snapshots are
interchangeable with ``GymVecEnv``'s (same schema, cross-restorable).

Design constraints honored:

* Workers never initialize a jax backend. The worker body calls only
  numpy + gymnasium (via the jax-free ``envs.gym_state``); jax is imported
  transitively by the package ``__init__`` in the spawned interpreter but
  no jax API runs, so the single-tenant TPU tunnel is never touched. The
  ``spawn`` start method guarantees a clean interpreter (no forked jax
  state).
* Normalization statistics stay **centralized in the parent** (one
  Welford fold per (group) step over the gathered raw slice — the same
  associative merge ``GymVecEnv`` does), so statistics are identical to
  the in-process adapter and checkpointing is unchanged.
* Workers own **contiguous env slices**, so ``host_step_slice`` group
  boundaries that align with worker boundaries touch exactly one worker
  (the pipelined rollout's ``host_pipeline_groups=W`` sweet spot).

Perf note (BENCH_LADDER "process-pool overlap"): this host has ONE core,
so CPU-bound stepping cannot speed up here — but the pool's overlap IS
measured on this box with a sleep-bound probe env (``envs/sleep_env.py``:
``time.sleep`` releases the core): W=4 workers complete a fixed step
budget 3.4× faster than serial (86% of ideal; ``scripts/
proc_overlap_r05.json``, ``tests/test_proc_env.py::
test_worker_pool_overlap_wallclock``). Real-simulator throughput gains
still await a multicore host. The reference steps one env serially
in-process (``utils.py:18-45``).
"""

from __future__ import annotations

import multiprocessing as mp
import os
from collections import deque

import numpy as np

from trpo_tpu.envs.episode_stats import EpisodeStatsMixin
from trpo_tpu.envs.obs_norm import ObsNormMixin

__all__ = ["ProcVecEnv", "WorkerDiedError"]


class WorkerDiedError(RuntimeError):
    """A ``proc_env`` worker stopped answering: its process exited/was
    killed (pipe EOF) or it exceeded the per-command ``step_timeout``
    (hung). Carries everything supervision (``resilience/supervisor.py``)
    needs to revive it: the worker index (``worker``, plus ``workers``
    when one gather found several casualties), the failure ``kind``
    (``"died"`` / ``"timeout"``), and the last action batch the parent
    sent it (``last_action`` — None before the first step)."""

    def __init__(self, worker: int, env_id: str, kind: str = "died",
                 last_action=None, workers=None):
        self.worker = worker
        self.workers = sorted(workers) if workers else [worker]
        self.kind = kind
        self.env_id = env_id
        self.last_action = last_action
        act = (
            "no action sent yet"
            if last_action is None
            else f"last action {np.array_str(np.asarray(last_action))}"
        )
        super().__init__(
            f"ProcVecEnv worker {self.workers} ({env_id}) "
            f"{'timed out' if kind == 'timeout' else 'died'} "
            f"mid-command ({act})"
        )


def _construct_envs(env_id: str, count: int, seed_base: int, kwargs: dict):
    """Build ``count`` envs + the metadata the parent handshake needs.

    Shared by the spawned worker body (:func:`_worker`) and the parent's
    in-process degraded-mode fallback (:class:`_LocalConn`), so both
    construct IDENTICAL envs. Returns
    ``(envs, spec, clip, obs_shape, obs0)``."""
    import gymnasium

    # "package.module:attr" where attr is a class or factory callable
    # constructs envs directly (no registry needed in the spawned
    # interpreter — the overlap probe envs/sleep_env.py uses this).
    # gymnasium's own documented "module:EnvId" form (import module,
    # then make the REGISTERED id) takes precedence: the ctor path is
    # only taken when, after importing the module, the id is absent
    # from gymnasium's registry — otherwise a module-level callable
    # that happens to share the registered id's name would silently
    # bypass the registry's wrappers (TimeLimit, OrderEnforcing,
    # spec-level kwargs). Anything that neither resolves to a callable
    # nor registers falls through to gymnasium.make's own error.
    env_ctor = None
    if ":" in env_id:
        import importlib

        mod_name, attr = env_id.split(":", 1)
        try:
            obj = getattr(importlib.import_module(mod_name), attr)
        except (ImportError, AttributeError):
            obj = None
        if callable(obj) and attr not in gymnasium.registry:
            env_ctor = obj
    if env_ctor is not None:
        envs = [env_ctor(**kwargs) for _ in range(count)]
    else:
        envs = [gymnasium.make(env_id, **kwargs) for _ in range(count)]
    single = envs[0]
    space = single.action_space
    if hasattr(space, "n"):
        spec = ("discrete", int(space.n))
        clip = None
    else:
        lo = np.asarray(space.low, np.float32)
        hi = np.asarray(space.high, np.float32)
        spec = ("box", int(space.shape[0]))
        clip = (lo, hi)
    obs0 = np.stack(
        [env.reset(seed=seed_base + j)[0] for j, env in enumerate(envs)]
    )
    return envs, spec, clip, tuple(single.observation_space.shape), obs0


def _serve(envs: list, clip, obs0: np.ndarray, msg: tuple):
    """Execute ONE worker command against ``envs``; returns
    ``(reply, close)``. The single copy of the command semantics, shared
    by the worker loop and the in-process fallback — errors are the
    caller's to wrap (the worker sends an ``err`` reply, the fallback
    raises in place)."""
    from trpo_tpu.envs.gym_state import restore_one, snapshot_one

    cmd = msg[0]
    if cmd == "step":
        actions = msg[1]
        m = len(envs)
        next_obs = np.empty((m,) + obs0.shape[1:], obs0.dtype)
        final_obs = np.empty_like(next_obs)
        rewards = np.zeros(m, np.float32)
        term = np.zeros(m, bool)
        trunc = np.zeros(m, bool)
        for j, env in enumerate(envs):
            a = actions[j]
            if clip is not None:
                a = np.clip(a, clip[0], clip[1])
            obs_j, r, tm, tr, _info = env.step(a)
            rewards[j] = r
            term[j] = tm
            trunc[j] = tr
            final_obs[j] = obs_j
            if tm or tr:
                obs_j, _ = env.reset()
            next_obs[j] = obs_j
        return ("ok", next_obs, rewards, term, trunc, final_obs), False
    if cmd == "reset_all":
        seed = msg[1]
        obs = np.stack(
            [
                env.reset(seed=None if seed is None else seed + j)[0]
                for j, env in enumerate(envs)
            ]
        )
        return ("ok", obs), False
    if cmd == "snapshot":
        return ("ok", [snapshot_one(env) for env in envs]), False
    if cmd == "restore":
        sims = msg[1]
        reset_obs = {}
        for j, (env, sim) in enumerate(zip(envs, sims)):
            raw = restore_one(env, sim)
            if raw is not None:
                reset_obs[j] = raw
        return ("ok", reset_obs), False
    if cmd == "render":
        return ("ok", envs[0].render()), False
    if cmd == "close":
        for env in envs:
            env.close()
        return ("ok",), True
    return ("err", f"unknown command {cmd!r}"), False


def _worker(conn, env_id: str, count: int, seed_base: int, kwargs: dict):
    """Worker loop: owns ``count`` envs; steps/snapshots/restores them on
    command. Runs in a spawned interpreter; calls numpy + gymnasium only
    (never a jax API — see the module docstring's tunnel constraint)."""
    try:
        envs, spec, clip, obs_shape, obs0 = _construct_envs(
            env_id, count, seed_base, kwargs
        )
        conn.send(("ready", spec, obs_shape, obs0))
    except Exception as e:  # pragma: no cover - construction failures
        import traceback

        conn.send(("err", f"{type(e).__name__}: {e}\n"
                   f"{traceback.format_exc()}"))
        return

    while True:
        try:
            msg = conn.recv()
        except EOFError:  # parent died — exit quietly
            break
        try:
            reply, close = _serve(envs, clip, obs0, msg)
        except Exception as e:
            import traceback

            reply, close = (
                ("err", f"{type(e).__name__}: {e}\n"
                 f"{traceback.format_exc()}"),
                False,
            )
        conn.send(reply)
        if close:
            break


class _LocalConn:
    """In-process stand-in for a worker's pipe endpoint — the degraded
    mode supervision falls back to once a worker slice has exhausted
    ``max_worker_restarts`` (``resilience/supervisor.py``).

    Speaks the exact connection surface the parent uses (``send`` /
    ``poll`` / ``recv`` / ``close``), executing each command synchronously
    in the parent via the SAME :func:`_construct_envs`/:func:`_serve` the
    worker body runs — data stays correct, the slice merely loses process
    parallelism. Construction mirrors the worker handshake: the first
    ``recv`` returns the ``ready`` message."""

    def __init__(self, env_id: str, count: int, seed_base: int,
                 kwargs: dict):
        self._envs, spec, self._clip, obs_shape, self._obs0 = (
            _construct_envs(env_id, count, seed_base, kwargs)
        )
        self._pending: deque = deque(
            [("ready", spec, obs_shape, self._obs0)]
        )
        self._closed = False

    def send(self, msg) -> None:
        if self._closed:
            raise BrokenPipeError("local env slice is closed")
        try:
            reply, close = _serve(self._envs, self._clip, self._obs0, msg)
        except Exception as e:
            import traceback

            reply, close = (
                ("err", f"{type(e).__name__}: {e}\n"
                 f"{traceback.format_exc()}"),
                False,
            )
        self._pending.append(reply)
        if close:
            self._closed = True

    def poll(self, timeout=None) -> bool:
        return bool(self._pending)

    def recv(self):
        if not self._pending:
            raise EOFError("no pending reply on local env slice")
        return self._pending.popleft()

    def close(self) -> None:
        if not self._closed:
            for env in self._envs:
                env.close()
            self._closed = True


class ProcVecEnv(EpisodeStatsMixin, ObsNormMixin):
    """N gymnasium envs over W worker processes — GymVecEnv's surface."""

    def __init__(self, env_id: str, n_envs: int = 8, seed: int = 0,
                 normalize_obs: bool = False, n_workers=None,
                 step_timeout=None, **kwargs):
        """``step_timeout`` (seconds, None = wait forever — the
        pre-round-7 behavior): how long any reply gather waits on a
        worker before declaring it dead with :class:`WorkerDiedError`.
        Without it a worker killed mid-episode hangs ``host_step``
        forever; with it the error names the worker and the last action
        so supervision (``resilience/supervisor.py``) can restart it."""
        self.env_id = env_id
        self.n_envs = n_envs
        self.step_timeout = step_timeout
        if n_workers is None:
            n_workers = max(1, min(n_envs, os.cpu_count() or 1))
        if not 1 <= n_workers <= n_envs:
            raise ValueError(
                f"n_workers must be in [1, n_envs={n_envs}], got {n_workers}"
            )
        self.n_workers = n_workers
        # contiguous balanced slices: first (n_envs % W) workers get one
        # extra env — boundaries usable as host_step_slice groups
        q, r = divmod(n_envs, n_workers)
        self._slices = []
        lo = 0
        for w in range(n_workers):
            hi = lo + q + (1 if w < r else 0)
            self._slices.append((lo, hi))
            lo = hi

        # restart_worker respawns a slice with exactly its construction-
        # time arguments (seed + lo reseeds the fresh episodes the way the
        # initial start did — deterministic, test-pinnable)
        self._seed = seed
        self._kwargs = dict(kwargs)
        self._last_actions: dict = {}

        self._conns, self._procs = [], []
        try:
            for w in range(n_workers):
                conn, p = self._spawn_worker(w)
                self._conns.append(conn)
                self._procs.append(p)
            obs_parts = []
            spec = obs_shape = None
            for w, conn in enumerate(self._conns):
                _, spec, obs_shape, obs0 = self._recv_ready(conn, w)
                obs_parts.append(obs0)
        except Exception:
            self.close()
            raise

        from trpo_tpu.models.policy import BoxSpec, DiscreteSpec

        self.obs_shape = tuple(obs_shape)
        if spec[0] == "discrete":
            self.action_spec = DiscreteSpec(spec[1])
            self._continuous = False
        else:
            self.action_spec = BoxSpec(spec[1])
            self._continuous = True

        self._init_obs_norm(self.obs_shape, normalize_obs)
        self._obs = self._fold_and_normalize(np.concatenate(obs_parts))
        self._init_episode_stats(n_envs)

    # -- worker lifecycle --------------------------------------------------

    def _spawn_worker(self, w: int):
        """Start a fresh worker process for slice ``w``; returns
        ``(parent_conn, process)``. The ready handshake is the caller's
        (``_recv_ready``) so construction can overlap across workers."""
        lo, hi = self._slices[w]
        ctx = mp.get_context("spawn")  # clean interpreters: no forked jax
        # spawn re-runs __main__ from its __file__ in the child; a parent
        # driven from stdin/REPL has __file__ == "<stdin>", which the
        # child fails to re-open. The worker needs nothing from __main__,
        # so hide a non-existent __file__ for the duration of the start.
        import sys

        main_mod = sys.modules.get("__main__")
        main_file = getattr(main_mod, "__file__", None)
        hide_main = main_file is not None and not os.path.exists(main_file)
        if hide_main:
            del main_mod.__file__
        try:
            parent, child = ctx.Pipe()
            p = ctx.Process(
                target=_worker,
                args=(
                    child, self.env_id, hi - lo, self._seed + lo,
                    dict(self._kwargs),
                ),
                daemon=True,
            )
            p.start()
            child.close()
        finally:
            if hide_main:
                main_mod.__file__ = main_file
        return parent, p

    def _recv_ready(self, conn, w: int):
        """Consume a worker's ``ready`` handshake (no step_timeout here:
        construction legitimately takes longer than a step — interpreter
        spawn + imports)."""
        try:
            msg = conn.recv()
        except (EOFError, ConnectionError, OSError) as e:
            raise WorkerDiedError(w, self.env_id) from e
        if msg[0] != "ready":
            raise RuntimeError(
                f"ProcVecEnv worker failed to start:\n{msg[1]}"
            )
        return msg

    def restart_worker(self, w: int, local: bool = False) -> None:
        """Replace worker ``w`` with a fresh process (``local=True``: an
        in-process :class:`_LocalConn` slice — supervision's degraded
        mode) after killing whatever is left of the old one.

        Episode-restart semantics — the same contract as a ``gym:``
        resume without a usable sidecar (``utils/checkpoint.py``): the
        slice's envs are reconstructed and reseeded exactly as at
        construction (``seed + lo``), their fresh reset observations fold
        into the shared normalization statistics (a reset does), and the
        slice's running episode accumulators zero. Whatever the old
        worker was mid-episode on is lost — that is the fault model, not
        a bug."""
        lo, hi = self._slices[w]
        p = self._procs[w]
        if p is not None:
            try:
                p.kill()  # SIGKILL: also takes down a SIGSTOPped hang
                p.join(timeout=5)
            except (OSError, ValueError):  # pragma: no cover
                pass
        try:
            self._conns[w].close()
        except OSError:  # pragma: no cover
            pass
        self._last_actions.pop(w, None)
        if local:
            conn = _LocalConn(
                self.env_id, hi - lo, self._seed + lo, dict(self._kwargs)
            )
            proc = None
            msg = conn.recv()
        else:
            conn, proc = self._spawn_worker(w)
            msg = self._recv_ready(conn, w)
        self._conns[w] = conn
        self._procs[w] = proc
        obs0 = msg[3]
        self._obs[lo:hi] = self._fold_and_normalize_slice(obs0, lo, hi)
        self._running_returns[lo:hi] = 0.0
        self._running_lengths[lo:hi] = 0

    def is_local_worker(self, w: int) -> bool:
        """True when slice ``w`` runs in-process (degraded mode)."""
        return isinstance(self._conns[w], _LocalConn)

    # -- worker RPC --------------------------------------------------------

    def _recv(self, w: int):
        """One reply from worker ``w``, honoring ``step_timeout``. EOF or
        a timeout becomes :class:`WorkerDiedError` naming the worker and
        the last action batch it was sent."""
        conn = self._conns[w]
        try:
            if self.step_timeout is not None and not conn.poll(
                self.step_timeout
            ):
                raise WorkerDiedError(
                    w, self.env_id, kind="timeout",
                    last_action=self._last_actions.get(w),
                )
            return conn.recv()
        except (EOFError, ConnectionError, OSError) as e:
            raise WorkerDiedError(
                w, self.env_id, last_action=self._last_actions.get(w)
            ) from e

    def _reply_all(self, ws):
        """Gather one reply from EVERY worker in ``ws`` before raising.

        Raising on the first error reply would leave the later workers'
        queued replies unconsumed, permanently desyncing the pipe protocol
        — a caller that caught the error would then read a stale step
        reply as the answer to its next command. Drain first, then report
        every failure. Dead/hung workers outrank error replies: they
        surface as one :class:`WorkerDiedError` carrying every casualty,
        so supervision can revive them all in one pass."""
        replies, errors, dead = {}, [], []
        first_died = None
        for w in ws:
            try:
                msg = self._recv(w)
            except WorkerDiedError as e:
                dead.append(w)
                first_died = first_died or e
                continue
            if msg[0] != "ok":
                errors.append(f"worker {w}:\n{msg[1]}")
            else:
                replies[w] = msg[1:]
        if dead:
            raise WorkerDiedError(
                dead[0], self.env_id, kind=first_died.kind,
                last_action=first_died.last_action, workers=dead,
            )
        if errors:
            raise RuntimeError(
                f"ProcVecEnv ({self.env_id}):\n" + "\n".join(errors)
            )
        return replies

    def _scatter_gather(self, msgs: dict):
        """Send every command in ``msgs`` (worker → message tuple), then
        gather every reply, converting send failures into the same
        :class:`WorkerDiedError` the gather raises.

        Send failures must NOT abort mid-scatter: workers already sent to
        would be left with unconsumed replies, desyncing the protocol for
        a caller (supervision) that revives the casualty and retries.
        Every live worker is therefore sent to and drained first; only
        then do the casualties surface — together."""
        dead, sent = [], []
        first_died = None
        for w, msg in msgs.items():
            try:
                self._conns[w].send(msg)
                if msg[0] == "step":
                    self._last_actions[w] = msg[1]
                sent.append(w)
            except (BrokenPipeError, ConnectionError, OSError):
                dead.append(w)
        try:
            replies = self._reply_all(sent)
        except WorkerDiedError as e:
            if dead:
                raise WorkerDiedError(
                    min(e.workers + dead), self.env_id, kind=e.kind,
                    last_action=e.last_action,
                    workers=sorted(set(e.workers) | set(dead)),
                ) from e
            raise
        if dead:
            raise WorkerDiedError(
                dead[0], self.env_id,
                last_action=self._last_actions.get(dead[0]), workers=dead,
            )
        return replies

    def _overlapping(self, lo: int, hi: int):
        """(worker, its-local-range, global-range) for workers ∩ [lo, hi)."""
        out = []
        for w, (wlo, whi) in enumerate(self._slices):
            a, b = max(lo, wlo), min(hi, whi)
            if a < b:
                out.append((w, (a - wlo, b - wlo), (a, b)))
        return out

    # -- GymVecEnv surface -------------------------------------------------

    def host_step(self, actions: np.ndarray):
        """Step all envs in parallel across the workers; auto-reset
        finished ones. Same contract as ``GymVecEnv.host_step``
        (``(next_obs, rewards, terminated, truncated, final_obs)`` with
        pre-reset truncation-bootstrap successors)."""
        return self.host_step_slice(actions, 0, self.n_envs)

    def host_step_slice(self, actions: np.ndarray, lo: int, hi: int):
        """Step envs ``[lo, hi)`` — scatter action sub-slices to the
        overlapping workers, step them CONCURRENTLY, gather, then fold
        stats/normalization centrally exactly as ``GymVecEnv`` does."""
        parts = self._overlapping(lo, hi)
        # validate BEFORE any send: a mid-scatter error would desync the
        # pipe protocol (a worker left with an unconsumed reply)
        for w, (la, lb), _ in parts:
            if la != 0 or lb != self._slices[w][1] - self._slices[w][0]:
                raise ValueError(
                    f"host_step_slice [{lo}, {hi}) splits worker {w}'s env "
                    f"slice {self._slices[w]} — align groups to worker "
                    "boundaries (host_pipeline_groups=n_workers), or use "
                    "host_step"
                )
        # scatter everything first: workers step in parallel
        m = hi - lo
        next_obs = np.empty((m,) + self._obs.shape[1:], self._obs.dtype)
        final_obs = np.empty_like(next_obs)
        rewards = np.zeros(m, np.float32)
        terminated = np.zeros(m, bool)
        truncated = np.zeros(m, bool)
        replies = self._scatter_gather({
            w: ("step", actions[ga - lo: gb - lo])
            for w, _, (ga, gb) in parts
        })
        for w, _, (ga, gb) in parts:
            o, r, tm, tr, f = replies[w]
            s = slice(ga - lo, gb - lo)
            next_obs[s] = o
            rewards[s] = r
            terminated[s] = tm
            truncated[s] = tr
            final_obs[s] = f

        self._update_episode_stats_slice(
            rewards, np.logical_or(terminated, truncated), lo, hi
        )
        next_obs, final_obs = self._fold_and_normalize_slice(
            next_obs, lo, hi, extra=final_obs
        )
        self._obs[lo:hi] = next_obs
        return next_obs, rewards, terminated, truncated, final_obs

    def reset_all(self, seed=None) -> np.ndarray:
        replies = self._scatter_gather({
            w: ("reset_all", None if seed is None else seed + wlo)
            for w, (wlo, _) in enumerate(self._slices)
        })
        obs = np.concatenate(
            [replies[w][0] for w in range(self.n_workers)]
        )
        self._obs = self._fold_and_normalize(obs)
        self._running_returns[:] = 0.0
        self._running_lengths[:] = 0
        return self._obs.copy()

    def current_obs(self) -> np.ndarray:
        return self._obs.copy()

    # -- checkpoint sidecar (same schema as GymVecEnv: cross-restorable) ---

    def env_state_snapshot(self) -> dict:
        replies = self._scatter_gather({
            w: ("snapshot",) for w in range(self.n_workers)
        })
        sims = []
        for w in range(self.n_workers):
            sims.extend(replies[w][0])
        snap = {
            "env_id": self.env_id,
            "sims": sims,
            "obs": self._obs.copy(),
            **self._episode_stats_snapshot(),
        }
        if self.has_obs_norm:
            snap["raw_obs"] = self._raw_obs.copy()
        return snap

    def env_state_restore(self, snap: dict) -> None:
        if snap.get("env_id") != self.env_id:
            raise ValueError(
                f"snapshot is for {snap.get('env_id')!r}, this adapter "
                f"is {self.env_id!r}"
            )
        if len(snap["sims"]) != self.n_envs:
            raise ValueError(
                f"snapshot holds {len(snap['sims'])} envs, this adapter "
                f"has {self.n_envs} — resume with the same n_envs"
            )
        if self.has_obs_norm and "raw_obs" not in snap:
            raise ValueError(
                "snapshot was taken without normalize_obs; resume with "
                "the same normalize_obs setting"
            )
        replies = self._scatter_gather({
            w: ("restore", list(snap["sims"][wlo:whi]))
            for w, (wlo, whi) in enumerate(self._slices)
        })
        reset_obs = {}
        for w, (wlo, _) in enumerate(self._slices):
            for j, raw in replies[w][0].items():
                reset_obs[wlo + j] = raw
        self._obs = np.asarray(snap["obs"]).copy()
        if self.has_obs_norm and "raw_obs" in snap:
            self._raw_obs = np.asarray(snap["raw_obs"]).copy()
        self._episode_stats_restore(snap)
        for i, raw in reset_obs.items():
            if self.has_obs_norm:
                self._raw_obs[i] = raw
                with self._norm_lock:
                    self._obs[i] = self._apply_norm(raw)
            else:
                self._obs[i] = raw
            self._running_returns[i] = 0.0
            self._running_lengths[i] = 0

    def render_frame(self) -> np.ndarray:
        """RGB frame of env 0 (worker 0) — same contract as GymVecEnv."""
        frame = self._scatter_gather({0: ("render",)})[0][0]
        if frame is None:
            raise RuntimeError(
                "rendering returned None — construct ProcVecEnv with "
                "render_mode='rgb_array'"
            )
        return np.asarray(frame)

    def close(self):
        for w, conn in enumerate(getattr(self, "_conns", [])):
            try:
                conn.send(("close",))
            except (BrokenPipeError, OSError):
                pass
        for w, p in enumerate(getattr(self, "_procs", [])):
            if p is None:  # in-process degraded slice: nothing to join
                continue
            p.join(timeout=5)
            if p.is_alive():  # pragma: no cover
                # SIGKILL, not SIGTERM: a SIGSTOPped (hung) worker leaves
                # SIGTERM pending forever and would outlive the parent
                p.kill()
        for conn in getattr(self, "_conns", []):
            try:
                conn.close()
            except OSError:  # pragma: no cover
                pass
