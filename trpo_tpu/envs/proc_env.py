"""Process-based vectorized gymnasium adapter (VERDICT r3 item 6).

Python-stepped simulators (MuJoCo, Atari) serialize on the GIL, so the
threaded pipeline (``rollout.pipelined_host_rollout``) cannot overlap two
env groups' *stepping* — only stepping against device transfers. The
standard fix is a process pool: N envs split over W worker processes, each
stepping its contiguous slice in parallel, with actions/observations
crossing process boundaries over pipes.

Drop-in: :class:`ProcVecEnv` speaks exactly the :class:`GymVecEnv` surface
(``host_step`` / ``host_step_slice`` / ``reset_all`` / ``current_obs`` /
``env_state_snapshot`` / ``env_state_restore`` / ``render_frame`` /
episode stats / shared obs normalization), produces BIT-identical
trajectories to ``GymVecEnv`` for the same seed (same per-env seeding
``seed + i``, same auto-reset bookkeeping, same centralized normalization
fold — asserted by ``tests/test_proc_env.py``), and its snapshots are
interchangeable with ``GymVecEnv``'s (same schema, cross-restorable).

Design constraints honored:

* Workers never initialize a jax backend. The worker body calls only
  numpy + gymnasium (via the jax-free ``envs.gym_state``); jax is imported
  transitively by the package ``__init__`` in the spawned interpreter but
  no jax API runs, so the single-tenant TPU tunnel is never touched. The
  ``spawn`` start method guarantees a clean interpreter (no forked jax
  state).
* Normalization statistics stay **centralized in the parent** (one
  Welford fold per (group) step over the gathered raw slice — the same
  associative merge ``GymVecEnv`` does), so statistics are identical to
  the in-process adapter and checkpointing is unchanged.
* Workers own **contiguous env slices**, so ``host_step_slice`` group
  boundaries that align with worker boundaries touch exactly one worker
  (the pipelined rollout's ``host_pipeline_groups=W`` sweet spot).

Perf note (BENCH_LADDER "process-pool overlap"): this host has ONE core,
so CPU-bound stepping cannot speed up here — but the pool's overlap IS
measured on this box with a sleep-bound probe env (``envs/sleep_env.py``:
``time.sleep`` releases the core): W=4 workers complete a fixed step
budget 3.4× faster than serial (86% of ideal; ``scripts/
proc_overlap_r05.json``, ``tests/test_proc_env.py::
test_worker_pool_overlap_wallclock``). Real-simulator throughput gains
still await a multicore host. The reference steps one env serially
in-process (``utils.py:18-45``).
"""

from __future__ import annotations

import multiprocessing as mp
import os

import numpy as np

from trpo_tpu.envs.episode_stats import EpisodeStatsMixin
from trpo_tpu.envs.obs_norm import ObsNormMixin

__all__ = ["ProcVecEnv"]


def _worker(conn, env_id: str, count: int, seed_base: int, kwargs: dict):
    """Worker loop: owns ``count`` envs; steps/snapshots/restores them on
    command. Runs in a spawned interpreter; calls numpy + gymnasium only
    (never a jax API — see the module docstring's tunnel constraint)."""
    try:
        import gymnasium

        from trpo_tpu.envs.gym_state import restore_one, snapshot_one

        # "package.module:attr" where attr is a class or factory callable
        # constructs envs directly (no registry needed in the spawned
        # interpreter — the overlap probe envs/sleep_env.py uses this).
        # gymnasium's own documented "module:EnvId" form (import module,
        # then make the REGISTERED id) takes precedence: the ctor path is
        # only taken when, after importing the module, the id is absent
        # from gymnasium's registry — otherwise a module-level callable
        # that happens to share the registered id's name would silently
        # bypass the registry's wrappers (TimeLimit, OrderEnforcing,
        # spec-level kwargs). Anything that neither resolves to a callable
        # nor registers falls through to gymnasium.make's own error.
        env_ctor = None
        if ":" in env_id:
            import importlib

            mod_name, attr = env_id.split(":", 1)
            try:
                obj = getattr(importlib.import_module(mod_name), attr)
            except (ImportError, AttributeError):
                obj = None
            if callable(obj) and attr not in gymnasium.registry:
                env_ctor = obj
        if env_ctor is not None:
            envs = [env_ctor(**kwargs) for _ in range(count)]
        else:
            envs = [gymnasium.make(env_id, **kwargs) for _ in range(count)]
        single = envs[0]
        space = single.action_space
        if hasattr(space, "n"):
            spec = ("discrete", int(space.n))
            clip = None
        else:
            lo = np.asarray(space.low, np.float32)
            hi = np.asarray(space.high, np.float32)
            spec = ("box", int(space.shape[0]))
            clip = (lo, hi)
        obs0 = np.stack(
            [env.reset(seed=seed_base + j)[0] for j, env in enumerate(envs)]
        )
        conn.send(("ready", spec, tuple(single.observation_space.shape), obs0))
    except Exception as e:  # pragma: no cover - construction failures
        import traceback

        conn.send(("err", f"{type(e).__name__}: {e}\n"
                   f"{traceback.format_exc()}"))
        return

    while True:
        try:
            msg = conn.recv()
        except EOFError:  # parent died — exit quietly
            break
        cmd = msg[0]
        try:
            if cmd == "step":
                actions = msg[1]
                m = len(envs)
                next_obs = np.empty((m,) + obs0.shape[1:], obs0.dtype)
                final_obs = np.empty_like(next_obs)
                rewards = np.zeros(m, np.float32)
                term = np.zeros(m, bool)
                trunc = np.zeros(m, bool)
                for j, env in enumerate(envs):
                    a = actions[j]
                    if clip is not None:
                        a = np.clip(a, clip[0], clip[1])
                    obs_j, r, tm, tr, _info = env.step(a)
                    rewards[j] = r
                    term[j] = tm
                    trunc[j] = tr
                    final_obs[j] = obs_j
                    if tm or tr:
                        obs_j, _ = env.reset()
                    next_obs[j] = obs_j
                conn.send(("ok", next_obs, rewards, term, trunc, final_obs))
            elif cmd == "reset_all":
                seed = msg[1]
                obs = np.stack(
                    [
                        env.reset(
                            seed=None if seed is None else seed + j
                        )[0]
                        for j, env in enumerate(envs)
                    ]
                )
                conn.send(("ok", obs))
            elif cmd == "snapshot":
                conn.send(("ok", [snapshot_one(env) for env in envs]))
            elif cmd == "restore":
                sims = msg[1]
                reset_obs = {}
                for j, (env, sim) in enumerate(zip(envs, sims)):
                    raw = restore_one(env, sim)
                    if raw is not None:
                        reset_obs[j] = raw
                conn.send(("ok", reset_obs))
            elif cmd == "render":
                conn.send(("ok", envs[0].render()))
            elif cmd == "close":
                for env in envs:
                    env.close()
                conn.send(("ok",))
                break
            else:
                conn.send(("err", f"unknown command {cmd!r}"))
        except Exception as e:
            import traceback

            conn.send(("err", f"{type(e).__name__}: {e}\n"
                       f"{traceback.format_exc()}"))


class ProcVecEnv(EpisodeStatsMixin, ObsNormMixin):
    """N gymnasium envs over W worker processes — GymVecEnv's surface."""

    def __init__(self, env_id: str, n_envs: int = 8, seed: int = 0,
                 normalize_obs: bool = False, n_workers=None, **kwargs):
        self.env_id = env_id
        self.n_envs = n_envs
        if n_workers is None:
            n_workers = max(1, min(n_envs, os.cpu_count() or 1))
        if not 1 <= n_workers <= n_envs:
            raise ValueError(
                f"n_workers must be in [1, n_envs={n_envs}], got {n_workers}"
            )
        self.n_workers = n_workers
        # contiguous balanced slices: first (n_envs % W) workers get one
        # extra env — boundaries usable as host_step_slice groups
        q, r = divmod(n_envs, n_workers)
        self._slices = []
        lo = 0
        for w in range(n_workers):
            hi = lo + q + (1 if w < r else 0)
            self._slices.append((lo, hi))
            lo = hi

        ctx = mp.get_context("spawn")  # clean interpreters: no forked jax
        self._conns, self._procs = [], []
        # spawn re-runs __main__ from its __file__ in the child; a parent
        # driven from stdin/REPL has __file__ == "<stdin>", which the
        # child fails to re-open. The worker needs nothing from __main__,
        # so hide a non-existent __file__ for the duration of the starts.
        import sys

        main_mod = sys.modules.get("__main__")
        main_file = getattr(main_mod, "__file__", None)
        hide_main = main_file is not None and not os.path.exists(main_file)
        if hide_main:
            del main_mod.__file__
        try:
            try:
                for (lo, hi) in self._slices:
                    parent, child = ctx.Pipe()
                    p = ctx.Process(
                        target=_worker,
                        args=(
                            child, env_id, hi - lo, seed + lo, dict(kwargs)
                        ),
                        daemon=True,
                    )
                    p.start()
                    child.close()
                    self._conns.append(parent)
                    self._procs.append(p)
            finally:
                if hide_main:
                    main_mod.__file__ = main_file
            obs_parts = []
            spec = obs_shape = None
            for conn in self._conns:
                msg = conn.recv()
                if msg[0] != "ready":
                    raise RuntimeError(
                        f"ProcVecEnv worker failed to start:\n{msg[1]}"
                    )
                _, spec, obs_shape, obs0 = msg
                obs_parts.append(obs0)
        except Exception:
            self.close()
            raise

        from trpo_tpu.models.policy import BoxSpec, DiscreteSpec

        self.obs_shape = tuple(obs_shape)
        if spec[0] == "discrete":
            self.action_spec = DiscreteSpec(spec[1])
            self._continuous = False
        else:
            self.action_spec = BoxSpec(spec[1])
            self._continuous = True

        self._init_obs_norm(self.obs_shape, normalize_obs)
        self._obs = self._fold_and_normalize(np.concatenate(obs_parts))
        self._init_episode_stats(n_envs)

    # -- worker RPC --------------------------------------------------------

    def _call(self, w: int, *msg):
        self._conns[w].send(msg)

    def _reply(self, w: int):
        msg = self._conns[w].recv()
        if msg[0] != "ok":
            raise RuntimeError(
                f"ProcVecEnv worker {w} ({self.env_id}):\n{msg[1]}"
            )
        return msg[1:]

    def _reply_all(self, ws):
        """Gather one reply from EVERY worker in ``ws`` before raising.

        Raising on the first error reply would leave the later workers'
        queued replies unconsumed, permanently desyncing the pipe protocol
        — a caller that caught the error would then read a stale step
        reply as the answer to its next command. Drain first, then report
        every failure."""
        replies, errors = {}, []
        for w in ws:
            msg = self._conns[w].recv()
            if msg[0] != "ok":
                errors.append(f"worker {w}:\n{msg[1]}")
            else:
                replies[w] = msg[1:]
        if errors:
            raise RuntimeError(
                f"ProcVecEnv ({self.env_id}):\n" + "\n".join(errors)
            )
        return replies

    def _overlapping(self, lo: int, hi: int):
        """(worker, its-local-range, global-range) for workers ∩ [lo, hi)."""
        out = []
        for w, (wlo, whi) in enumerate(self._slices):
            a, b = max(lo, wlo), min(hi, whi)
            if a < b:
                out.append((w, (a - wlo, b - wlo), (a, b)))
        return out

    # -- GymVecEnv surface -------------------------------------------------

    def host_step(self, actions: np.ndarray):
        """Step all envs in parallel across the workers; auto-reset
        finished ones. Same contract as ``GymVecEnv.host_step``
        (``(next_obs, rewards, terminated, truncated, final_obs)`` with
        pre-reset truncation-bootstrap successors)."""
        return self.host_step_slice(actions, 0, self.n_envs)

    def host_step_slice(self, actions: np.ndarray, lo: int, hi: int):
        """Step envs ``[lo, hi)`` — scatter action sub-slices to the
        overlapping workers, step them CONCURRENTLY, gather, then fold
        stats/normalization centrally exactly as ``GymVecEnv`` does."""
        parts = self._overlapping(lo, hi)
        # validate BEFORE any send: a mid-scatter error would desync the
        # pipe protocol (a worker left with an unconsumed reply)
        for w, (la, lb), _ in parts:
            if la != 0 or lb != self._slices[w][1] - self._slices[w][0]:
                raise ValueError(
                    f"host_step_slice [{lo}, {hi}) splits worker {w}'s env "
                    f"slice {self._slices[w]} — align groups to worker "
                    "boundaries (host_pipeline_groups=n_workers), or use "
                    "host_step"
                )
        # scatter everything first: workers step in parallel
        for w, _, (ga, gb) in parts:
            self._call(w, "step", actions[ga - lo: gb - lo])
        m = hi - lo
        next_obs = np.empty((m,) + self._obs.shape[1:], self._obs.dtype)
        final_obs = np.empty_like(next_obs)
        rewards = np.zeros(m, np.float32)
        terminated = np.zeros(m, bool)
        truncated = np.zeros(m, bool)
        replies = self._reply_all([w for w, _, _ in parts])
        for w, _, (ga, gb) in parts:
            o, r, tm, tr, f = replies[w]
            s = slice(ga - lo, gb - lo)
            next_obs[s] = o
            rewards[s] = r
            terminated[s] = tm
            truncated[s] = tr
            final_obs[s] = f

        self._update_episode_stats_slice(
            rewards, np.logical_or(terminated, truncated), lo, hi
        )
        next_obs, final_obs = self._fold_and_normalize_slice(
            next_obs, lo, hi, extra=final_obs
        )
        self._obs[lo:hi] = next_obs
        return next_obs, rewards, terminated, truncated, final_obs

    def reset_all(self, seed=None) -> np.ndarray:
        for w, (wlo, _) in enumerate(self._slices):
            self._call(
                w, "reset_all", None if seed is None else seed + wlo
            )
        replies = self._reply_all(range(self.n_workers))
        obs = np.concatenate(
            [replies[w][0] for w in range(self.n_workers)]
        )
        self._obs = self._fold_and_normalize(obs)
        self._running_returns[:] = 0.0
        self._running_lengths[:] = 0
        return self._obs.copy()

    def current_obs(self) -> np.ndarray:
        return self._obs.copy()

    # -- checkpoint sidecar (same schema as GymVecEnv: cross-restorable) ---

    def env_state_snapshot(self) -> dict:
        for w in range(self.n_workers):
            self._call(w, "snapshot")
        replies = self._reply_all(range(self.n_workers))
        sims = []
        for w in range(self.n_workers):
            sims.extend(replies[w][0])
        snap = {
            "env_id": self.env_id,
            "sims": sims,
            "obs": self._obs.copy(),
            **self._episode_stats_snapshot(),
        }
        if self.has_obs_norm:
            snap["raw_obs"] = self._raw_obs.copy()
        return snap

    def env_state_restore(self, snap: dict) -> None:
        if snap.get("env_id") != self.env_id:
            raise ValueError(
                f"snapshot is for {snap.get('env_id')!r}, this adapter "
                f"is {self.env_id!r}"
            )
        if len(snap["sims"]) != self.n_envs:
            raise ValueError(
                f"snapshot holds {len(snap['sims'])} envs, this adapter "
                f"has {self.n_envs} — resume with the same n_envs"
            )
        if self.has_obs_norm and "raw_obs" not in snap:
            raise ValueError(
                "snapshot was taken without normalize_obs; resume with "
                "the same normalize_obs setting"
            )
        for w, (wlo, whi) in enumerate(self._slices):
            self._call(w, "restore", list(snap["sims"][wlo:whi]))
        replies = self._reply_all(range(self.n_workers))
        reset_obs = {}
        for w, (wlo, _) in enumerate(self._slices):
            for j, raw in replies[w][0].items():
                reset_obs[wlo + j] = raw
        self._obs = np.asarray(snap["obs"]).copy()
        if self.has_obs_norm and "raw_obs" in snap:
            self._raw_obs = np.asarray(snap["raw_obs"]).copy()
        self._episode_stats_restore(snap)
        for i, raw in reset_obs.items():
            if self.has_obs_norm:
                self._raw_obs[i] = raw
                with self._norm_lock:
                    self._obs[i] = self._apply_norm(raw)
            else:
                self._obs[i] = raw
            self._running_returns[i] = 0.0
            self._running_lengths[i] = 0

    def render_frame(self) -> np.ndarray:
        """RGB frame of env 0 (worker 0) — same contract as GymVecEnv."""
        self._call(0, "render")
        frame = self._reply(0)[0]
        if frame is None:
            raise RuntimeError(
                "rendering returned None — construct ProcVecEnv with "
                "render_mode='rgb_array'"
            )
        return np.asarray(frame)

    def close(self):
        for w, conn in enumerate(getattr(self, "_conns", [])):
            try:
                conn.send(("close",))
            except (BrokenPipeError, OSError):
                pass
        for w, p in enumerate(getattr(self, "_procs", [])):
            p.join(timeout=5)
            if p.is_alive():  # pragma: no cover
                p.terminate()
        for conn in getattr(self, "_conns", []):
            try:
                conn.close()
            except OSError:  # pragma: no cover
                pass
