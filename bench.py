"""North-star benchmark: CG-solve wall-clock at the Humanoid rung.

Metric (BASELINE.json): CG-solve ms/iter on a Humanoid-v2-shaped problem —
376-dim observations, 17-dim diagonal-Gaussian actions, 256×256 MLP policy,
batch 50k — comparing:

* **ours**: the framework's fused natural-gradient solve — conjugate
  gradient with the ``jvp∘grad`` Fisher-vector product inlined, 10
  iterations, one jit-compiled XLA program on the default (TPU) backend
  (``trpo_tpu.ops.cg`` + ``trpo_tpu.ops.fvp``).
* **baseline**: the reference's execution semantics (``utils.py:185-201`` +
  ``trpo_inksci.py:124-126``): a host NumPy CG loop that performs one
  device round trip per iteration — tangent uploaded, full-batch FVP
  evaluated, result downloaded, damping added host-side — against a CPU
  backend, which is what TF 1.3 on a 2017 workstation amounts to.

Synthetic observations/actions are used (the metric is solver wall-clock,
not learning curves; MuJoCo binaries are not part of this image).

Prints ONE JSON line:
``{"metric": ..., "value": <ours ms/iter>, "unit": "ms/iter",
"vs_baseline": <baseline_ms_per_iter / ours_ms_per_iter>}``.
"""

from __future__ import annotations

import contextlib
import json
import os
import subprocess
import sys
import time


def _tpu_usable(probe_timeout_s: int = 150) -> bool:
    """Probe accelerator-backend liveness in a throwaway subprocess.

    The axon TPU tunnel is single-tenant; a stale grant leaves backend init
    hanging forever rather than failing. Probing in a killable child keeps
    this process healthy, so a wedged tunnel degrades the benchmark to a
    CPU-vs-CPU comparison instead of hanging the driver.
    """
    code = (
        "import jax; d = jax.devices(); "
        "print('PLATFORM', d[0].platform)"
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            timeout=probe_timeout_s,
            text=True,
        )
        return "PLATFORM" in out.stdout and "cpu" not in out.stdout.lower()
    except subprocess.TimeoutExpired:
        return False


# BENCH_FORCE_CPU=1 skips the accelerator probe entirely (local smoke
# validation without touching the single-tenant TPU tunnel); BENCH_BATCH
# shrinks the problem for the same purpose. The driver runs with neither.
_ACCEL = os.environ.get("BENCH_FORCE_CPU") != "1" and _tpu_usable()
import jax  # noqa: E402

if not _ACCEL:
    print(
        "bench: accelerator backend unusable (wedged tunnel?) — "
        "falling back to CPU for the fused path",
        file=sys.stderr,
    )
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

OBS_DIM = 376          # Humanoid-v2 observation size (BASELINE.json)
ACT_DIM = 17           # Humanoid-v2 action size
HIDDEN = (256, 256)
BATCH = int(os.environ.get("BENCH_BATCH", 50_000))
CG_ITERS = 10
DAMPING = 0.1
FVP_SUB = 0.2          # curvature-subsampling operating point (see main)
CHAIN = 40             # solves chained per timed program (see _device_rtt)
TIMING_REPS = 5        # independent timed program runs; min is reported,
#                        the full per-run list + spread go in the JSON
#                        (VERDICT r3 item 1: the local/driver pair spread
#                        27% while each run's internal reps agreed to 4% —
#                        point estimates need a band and a contention flag)
BASELINE_REPS = 1      # 10 full-batch CPU FVPs per rep — each is seconds

_T0 = time.perf_counter()


def _progress(msg: str) -> None:
    print(f"bench[{time.perf_counter() - _T0:7.1f}s] {msg}", file=sys.stderr)


# -- FLOP / MFU accounting ---------------------------------------------------
#
# Dense bf16-matmul peak per JAX *device* (TPU generations where a chip has
# two TensorCores expose one device per core; v4+ megacore exposes one device
# per chip). Public spec-sheet numbers, TFLOP/s.
_PEAK_BF16_TFLOPS = [
    # (kind substring, bf16 TFLOP/s, HBM GB/s) — spec-sheet numbers
    ("v6", 918.0, 1640.0),
    ("v5p", 459.0, 2765.0),
    ("v5 lite", 197.0, 819.0),   # v5e device_kind is "TPU v5 lite"
    ("v5litepod", 197.0, 819.0),
    ("v5e", 197.0, 819.0),
    ("v5", 459.0, 2765.0),
    ("v4", 275.0, 1228.0),
    ("v3", 61.5, 450.0),
    ("v2", 22.5, 300.0),
]


def _peak_tflops(device):
    """(bf16 dense-matmul peak TFLOP/s, HBM GB/s) for this device, or
    (None, None) when unknown (CPU fallback, exotic kinds) — MFU/roofline
    are then reported as null, never guessed."""
    kind = getattr(device, "device_kind", "").lower()
    if "tpu" not in kind and device.platform != "tpu":
        return None, None
    for tag, peak, bw in _PEAK_BF16_TFLOPS:
        if tag in kind:
            return peak, bw
    return None, None


def _program_flops(jitted, *args):
    """Total FLOPs of one execution of a jitted program, from the compiled
    executable's XLA cost analysis; None when the backend doesn't report.

    ONLY valid for loop-free programs: XLA's cost analysis counts a
    ``while``/``scan`` body ONCE regardless of trip count, so lowering the
    fused (looped) solver would undercount by ~the iteration count. The
    accounting below therefore lowers single-kernel programs (one FVP, one
    grad, one KL eval) and composes them analytically."""
    try:
        an = jitted.lower(*args).compile().cost_analysis()
        if isinstance(an, (list, tuple)):
            an = an[0]
        flops = float(an.get("flops", float("nan")))
        nbytes = float(an.get("bytes accessed", float("nan")))
        if not (np.isfinite(flops) and flops > 0):
            return None, None
        return flops, (nbytes if np.isfinite(nbytes) and nbytes > 0 else None)
    except Exception:
        return None, None


def _forward_flops(hidden=None) -> float:
    """FLOPs of one policy forward pass (2·batch·weights)."""
    hidden = HIDDEN if hidden is None else tuple(hidden)
    dims = [OBS_DIM] + list(hidden)
    weights = sum(a * b for a, b in zip(dims[:-1], dims[1:]))
    weights += hidden[-1] * ACT_DIM  # Gaussian mean head (logstd: no matmul)
    return 2.0 * BATCH * weights


def _analytic_fvp_tangent_flops(hidden=None) -> float:
    """Analytic FLOPs for ONE CG iteration of the FUSED solve: the
    jvp-of-grad tangent pass ≈ 3 forward-equivalents (a forward-mode
    sweep through the forward+backward graph costs about what the
    reverse-mode grad itself does: fwd + 2×bwd ≈ 3 forwards). The primal
    linearization point (grad of KL at flat0) is loop-invariant — XLA's
    while-loop LICM hoists it out of the CG loop, so it is amortized over
    all 10 iterations, and the stop-gradient old-dist forward likewise.
    Cross-checks the XLA cost-analysis number in the JSON."""
    return 3.0 * _forward_flops(hidden)


def _analytic_acct() -> dict:
    """The analytic FLOP model as a full accounting dict — the fallback
    when XLA cost analysis reports nothing on this backend (VERDICT r2
    item 1: the artifact of record must carry non-null MFU, tagged with
    its provenance, instead of nulling out a number the analytic model
    already derives). Mirrors ``flop_accounting``'s composition:
    grad ≈ 3 forwards, tangent ≈ 3 forwards, KL eval = 2 forwards (old +
    new apply). Bytes-derived fields stay absent — traffic is NOT
    analytically modeled (the round-2 overcounting lesson)."""
    forward = _forward_flops()
    tangent = 3.0 * forward
    grad = 3.0 * forward
    kl_eval = 2.0 * forward
    return {
        # standalone GGN FVP: one primal forward + the 3-forward tangent
        "fvp": forward + tangent,
        "forward": forward,
        "grad": grad,
        "kl_eval": kl_eval,
        "tangent": tangent,
        "flops_per_cg_iter": tangent,
        "flops_per_update": (
            2.0 * grad + (CG_ITERS + 1) * tangent + 3.0 * kl_eval
        ),
    }


def _cost_analysis_usable() -> bool:
    """Whether ``cost_analysis()`` reports FLOPs on this backend, probed
    with a trivial program — the round-2 driver run spent ~156 s lowering
    the full 50k-batch programs only to find the tunneled backend reports
    nothing. A 1×1 matmul answers the same question in milliseconds."""
    probe = jax.jit(lambda a: a @ a)
    flops, _ = _program_flops(probe, jnp.ones((4, 4), jnp.float32))
    return flops is not None


def flop_accounting(problem: Problem):
    """Measured FLOP counts for the solver's constituent (loop-free)
    programs, composed into per-CG-iter and per-update totals.

    * ``fvp``: one standalone Gauss-Newton Fisher-vector product (the
      framework's default) — primal linearization + forward tangent +
      backward (≈4 forward-equivalents).
    * ``forward``: one policy apply — the loop-invariant primal the fused
      CG loop hoists (XLA LICM / explicit ``jax.linearize``).
    * ``grad``: one reverse-mode grad of the mean KL (≈3 forwards) — the
      cost model for the surrogate gradient.
    * ``kl_eval``: one KL forward evaluation (two applies, old + new) —
      the cost model for a linesearch trial.
    * ``tangent`` = fvp − forward: the per-iteration cost INSIDE the
      fused CG loop (forward tangent + backward ≈ 3 forwards).

    ``update_model`` composes the fused update's accepted-first-try path
    (the overwhelmingly common case, and a LOWER bound otherwise):
    surrogate grad + primal linearization + (CG_ITERS+1) tangents (10 CG
    + 1 step-scale sᵀFs product) + 3 KL-shaped evals (initial losses, one
    linesearch trial, final losses)."""
    from trpo_tpu.ops import make_ggn_fvp

    weight = jnp.ones((BATCH,), jnp.float32)

    def fvp_prog(flat, v):
        return make_ggn_fvp(
            problem.apply_fn, problem.fisher_weight, flat, weight, DAMPING
        )(v)

    fvp, fvp_bytes = _program_flops(
        jax.jit(fvp_prog), problem.flat0, problem.g
    )
    forward, forward_bytes = _program_flops(
        jax.jit(problem.apply_fn), problem.flat0
    )
    grad, _ = _program_flops(jax.jit(jax.grad(problem.kl_fn)), problem.flat0)
    kl_eval, _ = _program_flops(jax.jit(problem.kl_fn), problem.flat0)
    if fvp is None or forward is None or grad is None:
        return {}
    tangent = max(fvp - forward, 0.0)
    acct = {
        "fvp": fvp,
        "forward": forward,
        "grad": grad,
        "kl_eval": kl_eval,
        "tangent": tangent,
        "flops_per_cg_iter": tangent,
    }
    if fvp_bytes is not None and forward_bytes is not None:
        # HBM traffic of the per-iteration tangent work — with the FLOPs
        # this gives the arithmetic intensity, hence which roofline
        # (compute vs bandwidth) bounds the solve
        acct["bytes_per_cg_iter"] = max(fvp_bytes - forward_bytes, 0.0)
    if kl_eval is not None:
        acct["flops_per_update"] = (
            2.0 * grad + (CG_ITERS + 1) * tangent + 3.0 * kl_eval
        )
    return acct


def _device_rtt() -> float:
    """Median host↔device round-trip seconds for a trivial fetch.

    The tunneled TPU backend has ~100ms latency on any synchronous result
    download, and ``block_until_ready`` can return before execution
    finishes — so per-call host timing is meaningless there. All device
    timings below therefore chain ``CHAIN`` dependent repetitions inside
    ONE jitted program (a ``lax.scan``, sequential by construction), pay a
    single download at the end, and subtract this RTT.
    """
    trip = jax.jit(lambda c: c + 1.0)
    np.asarray(trip(jnp.float32(0)))  # compile + warm
    samples = []
    for i in range(5):
        t0 = time.perf_counter()
        np.asarray(trip(jnp.float32(i + 1)))
        samples.append(time.perf_counter() - t0)
    return sorted(samples)[len(samples) // 2]


def _chain_inputs(g, key, n):
    """``n`` near-identical right-hand sides. Tiny per-row perturbations
    keep every scan step a distinct computation (nothing for the compiler
    to hoist) without changing the solution beyond float noise."""
    noise = jax.random.normal(key, (n, g.shape[0]), jnp.float32)
    return g[None, :] + 1e-6 * noise


class Problem:
    """One benchmark problem instance.

    ``kl_fn`` drives the reference-semantics paths (host CG baseline,
    jvp∘grad ablations); ``apply_fn``/``fisher_weight`` drive the
    framework's default Gauss-Newton solve (``ops/fvp.make_ggn_fvp`` —
    ``cfg.fvp_mode="ggn"``). Both compute the same Fisher (validated by
    the solution-cosine asserts)."""

    def __init__(self, kl_fn, apply_fn, fisher_weight, flat0, g,
                 obs=None, unravel=None):
        self.kl_fn = kl_fn
        self.apply_fn = apply_fn
        self.fisher_weight = fisher_weight
        self.flat0 = flat0
        self.g = g
        self.obs = obs          # batch observations (fused-kernel path)
        self.unravel = unravel  # flat -> params pytree (fused-kernel path)


def build_problem(compute_dtype=None, hidden=None) -> Problem:
    """``compute_dtype=bfloat16`` runs the policy matmuls (forward + jvp/vjp
    inside the FVP) on the MXU at full rate; CG vectors, KL, and all solver
    arithmetic stay fp32 (``ops/cg.py`` casts every iterate) — the
    framework's documented TPU operating point (``models/mlp.py``). The
    baseline path uses fp32 throughout (reference semantics), and the
    solution-cosine assert below checks the bf16-matmul solve against it."""
    from trpo_tpu.models import make_policy, BoxSpec
    from trpo_tpu.ops import flatten_params

    policy = make_policy(
        (OBS_DIM,),
        BoxSpec(ACT_DIM),
        hidden=HIDDEN if hidden is None else tuple(hidden),
        compute_dtype=compute_dtype or jnp.float32,
    )
    params = policy.init(jax.random.key(0))
    obs = jax.random.normal(jax.random.key(1), (BATCH, OBS_DIM), jnp.float32)
    flat0, unravel = flatten_params(params)
    flat0 = jnp.asarray(flat0, jnp.float32)

    def apply_fn_at(flat):
        return policy.apply(unravel(flat), obs)

    def kl_fn(flat):
        cur = jax.lax.stop_gradient(apply_fn_at(flat0))
        dist = apply_fn_at(flat)
        return jnp.mean(policy.dist.kl(cur, dist))

    g = jax.random.normal(jax.random.key(2), flat0.shape, jnp.float32)
    g = g / jnp.linalg.norm(g)
    return Problem(
        kl_fn, apply_fn_at, policy.dist.fisher_weight, flat0, g,
        obs=obs, unravel=unravel,
    )


def _update_bench_setup(device=None, fvp_subsample=None, fvp_dtype=None,
                        cfg_overrides=None):
    """Policy/batch/update builder at the Humanoid operating point —
    shared by :func:`time_full_update`, :func:`update_tail_breakdown`
    and :func:`solve_precision` so the phase programs time EXACTLY the
    shapes/dtypes the full-update metric runs (bf16 matmuls on the
    accelerator, fp32 on the CPU paths). ``fvp_dtype``/``cfg_overrides``
    parameterize the solver-precision-ladder variants; bf16 configs get
    ``solve_audit_every=1`` to satisfy validation — the audit itself
    only traces when a ladder state is threaded (trpo.py's contract), so
    pure timings stay clean."""
    from trpo_tpu.config import TRPOConfig
    from trpo_tpu.models import make_policy, BoxSpec
    from trpo_tpu.trpo import TRPOBatch, make_trpo_update

    policy = make_policy(
        (OBS_DIM,),
        BoxSpec(ACT_DIM),
        hidden=HIDDEN,
        compute_dtype=jnp.bfloat16 if device is None else jnp.float32,
    )
    params = policy.init(jax.random.key(0))
    obs = jax.random.normal(
        jax.random.key(1), (BATCH, OBS_DIM), jnp.float32
    )
    dist = policy.apply(params, obs)
    actions = policy.dist.sample(jax.random.key(2), dist)
    batch = TRPOBatch(
        obs=obs,
        actions=actions,
        advantages=jax.random.normal(
            jax.random.key(3), (BATCH,), jnp.float32
        ),
        old_dist=dist,
        weight=jnp.ones((BATCH,), jnp.float32),
    )
    kw = dict(
        cg_iters=CG_ITERS, cg_damping=DAMPING, cg_residual_tol=0.0,
        fvp_subsample=fvp_subsample,
    )
    if fvp_dtype is not None:
        kw["fvp_dtype"] = fvp_dtype
        if fvp_dtype == "bf16":
            kw["solve_audit_every"] = 1
    kw.update(cfg_overrides or {})
    cfg = TRPOConfig(**kw)
    return policy, params, batch, cfg, make_trpo_update(policy, cfg)


def time_full_update(device=None, fvp_subsample=None, fvp_dtype=None,
                     cfg_overrides=None, thread_ladder=False):
    """Secondary tracked metric (BASELINE.json): policy-updates/sec — the
    ENTIRE fused natural-gradient update (surrogate grad → 10-iter CG over
    FVPs → step scale → line search → KL rollback) as one jitted program at
    the Humanoid operating point.

    ``fvp_subsample``/``fvp_dtype``/``cfg_overrides`` parameterize the
    solver-precision-ladder variants (the ``solve_precision`` block);
    the headline stays full-batch f32 (reference semantics).

    ``thread_ladder`` carries a ``trpo.LadderState`` through the chained
    updates (required for ``cg_budget_adaptive`` to act) and WARMS it
    before timing — three untimed chains converge the adaptive budget,
    then the timed chains run from that steady state. The timed config's
    ``solve_audit_every`` is forced far beyond the chain length so NO
    audit re-solve ever lands inside a timed chain on ANY backend (the
    accelerator path chains 120 updates — at the preset cadence of 25
    that would embed ~5 full-precision re-solves per timed rep): the
    published number is the steady-state non-audit cost, and the
    audit's amortized overhead is ~(full_solve/cheap_solve)/cadence on
    top.

    Returns ``(updates_per_sec, ms_per_update, runs_ms)`` — runs_ms is
    the per-rep list feeding the contention-retry machinery."""
    import contextlib

    ctx = (
        jax.default_device(device)
        if device is not None
        else contextlib.nullcontext()
    )
    with ctx:
        if thread_ladder:
            # audits must never land inside a timed chain (docstring):
            # step 0's audit fires in the first (untimed) warm chain,
            # and the next one sits far past any chain this function
            # ever replays
            cfg_overrides = {
                **(cfg_overrides or {}), "solve_audit_every": 1_000_000,
            }
        policy, params, batch, cfg, update = _update_bench_setup(
            device, fvp_subsample, fvp_dtype, cfg_overrides
        )
        # full updates are ~4× a bare solve; CPU path: see time_fused_solve.
        # The subsampled update is ~5× cheaper — chain proportionally more
        # so the timed window stays SEVERAL× the tunnel-RTT jitter (a
        # ~100 ms window against a ~110 ms RTT made round-1's updates/s
        # wobble ~1.7× between runs).
        if device is not None:
            n_chain = 2
        elif fvp_subsample and fvp_subsample < 1.0:
            n_chain = 3 * CHAIN
        else:
            # with the round-5 fused kernel a full update is ~3.5 ms, so
            # CHAIN updates are only ~140 ms — barely above the ~110 ms
            # tunnel RTT, whose ±20 ms jitter then moves updates/s by
            # ~±12% (the r05 artifacts' 221–292 band). Double the chain
            # so the timed window dominates the correction.
            n_chain = 2 * CHAIN
        # explicit-device (CPU) runs: a single ~15 s rep swung ±25% on a
        # loaded 2-core host (round-6 tail study) — take best of 3
        n_reps = TIMING_REPS if device is None else 3

        if thread_ladder:
            from trpo_tpu.trpo import init_ladder

            ladder0 = init_ladder(cfg)

            @jax.jit
            def chained_updates(carry, batch):
                def body(c, _):
                    p, lad = c
                    new_p, stats = update(p, batch, None, None, lad)
                    return (new_p, stats.ladder_next), stats.kl

                c_last, kls = jax.lax.scan(
                    body, carry, None, length=n_chain
                )
                return c_last, kls

            _progress("full update: compiling (ladder threaded)")
            carry = (params, ladder0)
            # warm the ladder: the adaptive budget converges to the
            # residual rule's exit point before any timed rep
            for _ in range(3):
                carry, kls = chained_updates(carry, batch)
            np.asarray(kls)
            carry0 = carry
            run = lambda: chained_updates(carry0, batch)
        else:
            @jax.jit
            def chained_updates(params, batch):
                def body(p, _):
                    new_p, stats = update(p, batch)
                    # carry the updated params: each step is a genuinely
                    # new problem (serialized, nothing hoistable out of
                    # the scan)
                    return new_p, stats.kl

                p_last, kls = jax.lax.scan(
                    body, params, None, length=n_chain
                )
                return p_last, kls

            _progress("full update: compiling")
            _, kls = chained_updates(params, batch)
            np.asarray(kls)
            run = lambda: chained_updates(params, batch)
        rtt = _device_rtt()
        _progress(f"full update: timing (rtt {rtt * 1e3:.0f} ms)")
        best, runs_ms = float("inf"), []
        for _ in range(n_reps):
            t0 = time.perf_counter()
            _, kls = run()
            np.asarray(kls)
            elapsed = time.perf_counter() - t0
            runs_ms.append(max(elapsed - rtt, 1e-9) / n_chain * 1e3)
            best = min(best, elapsed)
        assert np.all(np.isfinite(np.asarray(kls))), "non-finite KL chain"
        _progress("full update: done")
    per_update = max(best - rtt, 1e-9) / n_chain
    return 1.0 / per_update, per_update * 1e3, runs_ms


def update_tail_breakdown(full_update_ms=None, device=None,
                          ladder_row=None):
    """Phase-level attribution of the full fused update (round 6
    tentpole: the non-solve tail had grown to ~25% of the update budget
    and had never been itemized).

    Each phase is timed as its OWN chained-dependent jitted program at the
    exact full-update shapes/dtypes (``_update_bench_setup``), RTT-
    corrected like every other device timing here, then summed against
    the measured ``full_update_ms`` — ``coverage_of_full_update`` says
    how much of the update the named phases account for (acceptance bar:
    ≥90%; the remainder is while-loop/select scheduling the phase
    programs cannot see). Phases reflect the round-6 FUSED tail (see
    ``trpo._natural_gradient_update``): ``grad`` includes the
    surrogate-before fold (``value_and_grad``), and the single
    ``linesearch_forward`` trial IS the KL-rollback/stats forward — the
    pre-fusion program ran three more full-batch forwards here (the
    search's loss-at-x, the post-hoc KL eval, and the final stats pass).
    """
    import contextlib

    from jax import lax

    from trpo_tpu.ops import conjugate_gradient, flatten_params, make_ggn_fvp
    from trpo_tpu.ops.treemath import tree_where

    ctx = (
        jax.default_device(device)
        if device is not None
        else contextlib.nullcontext()
    )
    on_accel = _ACCEL and device is None
    with ctx:
        if full_update_ms is None:
            _, full_update_ms, _ = time_full_update(device=device)
        policy, params, batch, cfg, _ = _update_bench_setup(device)
        flat0, unravel = flatten_params(params)
        flat0 = jnp.asarray(flat0, jnp.float32)

        # the update's OWN fused surrogate+dist body and weighted mean —
        # imported, not re-implemented, so the phase attribution tracks
        # any future change to the surrogate automatically
        from trpo_tpu.trpo import _wmean as wmean
        from trpo_tpu.trpo import surrogate_and_dist

        def surr_dist(flat, b):
            return surrogate_and_dist(policy, unravel(flat), b)

        u_dir = flat0 / jnp.maximum(jnp.linalg.norm(flat0), 1.0)
        g0 = jax.jit(
            lambda f, b: jax.grad(lambda ff: surr_dist(ff, b)[0])(f)
        )(flat0, batch)
        dist0 = jax.jit(lambda p, b: policy.apply(p, b.obs))(params, batch)

        # Every phase program takes (carry0, flat0, batch, dist0, g0) as
        # jit ARGUMENTS — exactly how the real update receives them. A
        # first cut closed over them instead, and the 100MB of embedded
        # constants (batch + linearization residuals) made the phase
        # programs ~1.5× slower than the same work inside the update.
        def _time_phase(name, body, carry0, n_chain, reps,
                        wrap_scan=True):
            """Per-call ms of ``body(carry, flat0, batch, dist0, g0)``
            (carry → same-structure carry), chained ``n_chain``× in one
            jitted scan, best of ``reps``, RTT-corrected. With
            ``wrap_scan=False``, ``body`` IS the full program
            ``(c0, flat0, batch, dist0, g0) -> (out, probe)`` (phases
            that hoist setup outside their chain, like the CG solve)."""
            if wrap_scan:
                @jax.jit
                def prog(c, f, b, d, g):
                    out, _ = lax.scan(
                        lambda cc, _: (body(cc, f, b, d, g), ()),
                        c, None, length=n_chain,
                    )
                    leaves = jax.tree_util.tree_leaves(out)
                    return out, sum(
                        jnp.sum(jnp.asarray(l, jnp.float32))
                        for l in leaves
                    )
            else:
                prog = jax.jit(body)

            _progress(f"update tail: {name} (chain {n_chain})")
            out, probe = prog(carry0, flat0, batch, dist0, g0)
            np.asarray(probe)
            rtt = _device_rtt()
            best = float("inf")
            for _ in range(reps):
                t0 = time.perf_counter()
                out, probe = prog(carry0, flat0, batch, dist0, g0)
                np.asarray(probe)
                best = min(best, time.perf_counter() - t0)
            return max(best - rtt, 1e-9) / n_chain * 1e3

        if on_accel:
            chains = {"grad": 100, "cg": 10, "lin": 200, "ls": 200,
                      "stats": 200, "select": 400}
            reps = 3
        else:
            chains = {"grad": 3, "cg": 2, "lin": 4, "ls": 4,
                      "stats": 6, "select": 16}
            reps = 3

        # grad (+ the folded surrogate_before / f0 value and the dist0
        # aux, exactly as trpo.py computes them): one value_and_grad pass
        # at a carry-perturbed linearization point
        def grad_body(c, f, b, d0, g):
            (v, d), grad = jax.value_and_grad(
                lambda ff: surr_dist(ff, b), has_aux=True
            )(f + jnp.float32(1e-30) * c)
            d_probe = jnp.sum(
                jnp.asarray(jax.tree_util.tree_leaves(d)[0], jnp.float32)
            )
            return grad * (
                1.0 + jnp.float32(1e-30) * (v + jnp.float32(1e-30) * d_probe)
            )

        grad_ms = _time_phase(
            "grad", grad_body, jnp.zeros_like(flat0),
            chains["grad"], reps,
        )

        # the solve: 10 CG iterations over the GGN FVP plus the +1
        # step-scale FVP (sᵀFs). The FVP is built once outside the
        # chain's scan (its primal linearization hoisted, exactly as the
        # update's jit hoists it out of the CG while_loop; the chain
        # amortizes it further — the linearization is its own phase
        # below), mirroring time_fused_solve's program structure.
        n_cg_chain = chains["cg"]

        def cg_prog(c, f, b, d0, g):
            fvp = make_ggn_fvp(
                lambda ff: policy.apply(unravel(ff), b.obs),
                policy.dist.fisher_weight, f, b.weight, DAMPING,
            )

            def step(cc, _):
                x = conjugate_gradient(
                    fvp, -(g + jnp.float32(1e-30) * cc), CG_ITERS,
                    residual_tol=0.0,
                ).x
                shs = 0.5 * jnp.vdot(x, fvp(x))
                return x * (1.0 + jnp.float32(1e-30) * shs), ()

            out, _ = lax.scan(step, c, None, length=n_cg_chain)
            return out, out.sum()

        cg_ms = _time_phase(
            "cg_solve_plus_step_scale", cg_prog, jnp.zeros_like(flat0),
            n_cg_chain, reps, wrap_scan=False,
        )

        # the once-per-update primal linearization the solve above
        # amortizes away (jax.linearize at a moving point + one probe
        # tangent so the residuals cannot be dead-code-eliminated; the
        # probe slightly overcounts — noted in the emitted dict)
        def lin_body(c, f, b, d0, g):
            _, f_jvp = jax.linearize(
                lambda ff: policy.apply(unravel(ff), b.obs),
                f + (jnp.float32(1e-30) * c) * u_dir,
            )
            d = f_jvp(u_dir)
            return sum(
                jnp.sum(jnp.asarray(l, jnp.float32))
                for l in jax.tree_util.tree_leaves(d)
            )

        lin_ms = _time_phase(
            "fvp_linearization", lin_body, jnp.float32(0.0),
            chains["lin"], reps,
        )

        # one backtracking trial: a full-batch surrogate forward (shared
        # with the KL-cap constraint and, when accepted, with the
        # KL-rollback check and the stats pass)
        def ls_body(c, f, b, d0, g):
            s, d = surr_dist(f + (jnp.float32(1e-30) * c) * u_dir, b)
            d_probe = jnp.sum(
                jnp.asarray(jax.tree_util.tree_leaves(d)[0], jnp.float32)
            )
            return s + jnp.float32(1e-30) * d_probe

        ls_ms = _time_phase(
            "linesearch_forward", ls_body, jnp.float32(0.0),
            chains["ls"], reps,
        )

        # elementwise stats reductions on the (already-paid-for) final
        # dist: logp, surrogate-after, KL, entropy weighted means
        def stats_body(c, f, b, d0, g):
            d = jax.tree_util.tree_map(
                lambda x: x + jnp.asarray(1e-30 * c, x.dtype), d0
            )
            logp_new = policy.dist.logp(d, b.actions)
            logp_old = policy.dist.logp(b.old_dist, b.actions)
            sa = -wmean(
                jnp.exp(logp_new - logp_old) * b.advantages, b.weight
            )
            kl = wmean(policy.dist.kl(b.old_dist, d), b.weight)
            ent = wmean(policy.dist.entropy(d), b.weight)
            return sa + kl + ent

        stats_ms = _time_phase(
            "kl_and_stats_reductions", stats_body, jnp.float32(0.0),
            chains["stats"], reps,
        )

        # the rollback parameter select (tree_where over the flat vector)
        def select_body(c, f, b, d0, g):
            pred = c[0] > jnp.float32(-1e30)
            return tree_where(pred, c + jnp.float32(1e-30), f)

        select_ms = _time_phase(
            "rollback_select", select_body, jnp.zeros_like(flat0),
            chains["select"], reps,
        )

    n_trials = 1  # accepted-first-try: the overwhelmingly common case
    phases = {
        "cg_solve_plus_step_scale": round(cg_ms, 4),
        "fvp_linearization": round(lin_ms, 4),
        "grad_and_surrogate_before": round(grad_ms, 4),
        "linesearch_forward_per_trial": round(ls_ms, 4),
        "kl_and_stats_reductions": round(stats_ms, 4),
        "rollback_select": round(select_ms, 4),
    }
    phases_sum = (
        cg_ms + lin_ms + grad_ms + ls_ms * n_trials + stats_ms + select_ms
    )
    solve_ms = cg_ms + lin_ms
    # the tail as directly MEASURED (its own phase programs) — robust
    # even when the standalone solve phase over-counts its in-situ cost
    tail_measured = grad_ms + ls_ms * n_trials + stats_ms + select_ms
    coverage = phases_sum / full_update_ms
    notes = [
        "cg_solve_plus_step_scale times 11 FVP tangents with the "
        "primal linearization hoisted (as the update's jit hoists "
        "it); fvp_linearization is that once-per-update primal, "
        "measured with one probe tangent (small overcount)",
    ]
    if coverage > 1.05:
        notes.append(
            "coverage > 1: standalone phase programs over-count their "
            "in-situ cost (XLA optimizes the composed update program "
            "beyond the sum of its parts — observed ~15-25% on the "
            "CPU backend's solve phase); the attribution is an upper "
            "bound per phase"
        )
    return {
        "full_update_ms": round(full_update_ms, 4),
        # the configuration these phase programs ran (ISSUE 8: every
        # update-tail row carries its precision tags; the phase programs
        # here time the full-batch f32 reference semantics, cosine 1 by
        # definition)
        "fvp_dtype": "f32",
        "fvp_subsample": None,
        "solve_cosine": 1.0,
        # the ladder's full-update row (solve_precision's "ladder"
        # variant: bf16 FVP + ¾-batch curvature + adaptive budget) —
        # embedded HERE so the regenerated breakdown quotes the ladder
        # delta next to the phase attribution it explains
        "ladder": ladder_row,
        "ladder_speedup_vs_f32": None
        if not ladder_row
        else round(full_update_ms / ladder_row["full_update_ms"], 3),
        "phases_ms": phases,
        "expected_linesearch_trials": n_trials,
        "phases_sum_ms": round(phases_sum, 4),
        "coverage_of_full_update": round(coverage, 4),
        "tail_ms_measured_components": round(tail_measured, 4),
        "tail_fraction_of_phases": round(tail_measured / phases_sum, 4),
        "tail_ms_residual_vs_full": round(full_update_ms - solve_ms, 4),
        "notes": notes,
        "fusions": [
            "surrogate_before folded into the gradient's value_and_grad",
            "linesearch skips re-evaluating the loss at current params "
            "(f0)",
            "accepted trial's forward shared with KL-rollback check and "
            "stats pass (linesearch aux)",
            "linesearch_kl_cap constraint reads the trial's forward — "
            "zero extra forwards per trial",
        ],
    }


def solve_precision(device=None, f32_row=None):
    """The solver-precision-ladder harvest (ISSUE 8 satellite): the full
    fused update at the flagship shape under each ladder rung —

    * ``f32``       — reference semantics (the r06 lineage baseline);
    * ``bf16``      — bf16 FVP matvec, f32 CG accumulators;
    * ``subsample`` — ¾-batch curvature (the preset operating point);
    * ``ladder``    — everything on: bf16 + ¾-batch + the residual-rule
      early exit with the adaptive CG budget, timed with a WARMED
      ``LadderState`` threaded through the chain (steady-state
      non-audit cost; the audit re-solve amortizes over its cadence).

    Every row: min-over-reps via :func:`time_full_update`, the
    contention retry the headline phases use, and a measured
    ``solve_cosine`` tag — one audited update per variant (ladder state
    with ``step=0`` forces the audit) quoting the on-device cosine
    between that variant's solution and the full-precision/full-batch
    solve of the same system.
    """
    import contextlib

    from trpo_tpu.trpo import init_ladder

    # a fresh context manager per use — jax.default_device() objects are
    # single-entry
    make_ctx = lambda: (
        jax.default_device(device)
        if device is not None
        else contextlib.nullcontext()
    )
    load0 = os.getloadavg()[0] if hasattr(os, "getloadavg") else None
    ladder_cfg = {
        "cg_residual_rtol": 1e-2,
        "cg_budget_adaptive": True,
        "cg_budget_floor": 2,
        "solve_audit_every": 25,  # the preset cadence
    }
    variants = [
        ("f32", dict()),
        ("bf16", dict(fvp_dtype="bf16")),
        ("subsample", dict(fvp_subsample=0.75, cfg_overrides={
            "solve_audit_every": 25,
        })),
        ("ladder", dict(fvp_dtype="bf16", fvp_subsample=0.75,
                        cfg_overrides=dict(ladder_cfg),
                        thread_ladder=True)),
    ]
    rows = []
    f32_ms = None
    for label, kw in variants:
        if label == "f32" and f32_row is not None:
            # the headline full-update timing IS this row — reuse it
            ms, runs = f32_row
            retried, runs_first = False, None
        else:
            _progress(f"solve precision: {label}")
            _, ms, runs = time_full_update(device=device, **kw)
            ms, _x, runs, retried, runs_first = _retry_phase_if_contended(
                f"solve_precision/{label}",
                (ms, None, runs),
                lambda kw=kw: (
                    lambda r: (r[1], None, r[2])
                )(time_full_update(device=device, **kw)),
                load=load0,
            )
        # measured solution cosine: one audited update per variant (the
        # f32 row audits trivially against itself → 1.0)
        cos = None
        if label == "f32":
            cos = 1.0
        else:
            try:
                with make_ctx():
                    _p, _pp, batch, cfg, update = _update_bench_setup(
                        device,
                        kw.get("fvp_subsample"),
                        kw.get("fvp_dtype"),
                        {**kw.get("cfg_overrides", {}),
                         "solve_audit_every": 1},
                    )
                    _, stats = jax.jit(update)(
                        _pp, batch, None, None, init_ladder(cfg)
                    )
                    cos = float(np.asarray(stats.solve_cosine))
            except Exception as e:
                _progress(
                    f"solve precision: cosine probe failed for {label} "
                    f"({type(e).__name__}: {e})"
                )
        if label == "f32":
            f32_ms = ms
        rows.append({
            "variant": label,
            "fvp_dtype": kw.get("fvp_dtype", "f32"),
            "fvp_subsample": kw.get("fvp_subsample"),
            "adaptive_budget": bool(
                kw.get("cfg_overrides", {}).get("cg_budget_adaptive")
            ),
            "full_update_ms": round(ms, 4),
            "runs_ms": [round(r, 4) for r in runs],
            "retried": retried,
            "runs_first_attempt": None
            if runs_first is None
            else [round(r, 4) for r in runs_first],
            "solve_cosine": None if cos is None else round(cos, 6),
            "speedup_vs_f32": None
            if f32_ms is None
            else round(f32_ms / ms, 3),
        })
    return {
        "rows": rows,
        "notes": [
            "ladder row: steady-state non-audit cost with a warmed "
            "LadderState threaded (budget converged before timing); "
            "the full-precision audit re-solve adds ~1/solve_audit_"
            "every of an f32 solve amortized",
            "solve_cosine: on-device audit cosine of ONE update "
            "(ladder step=0 forces the audit) vs the f32/full-batch "
            "solve of the same system",
        ],
    }


def bench_program_memory(problem: Problem, device=None, fvp_factory=None):
    """Compiled ``memory_analysis()`` bytes for the headline programs
    (ISSUE 5 satellite: the bench JSON carries a memory column next to
    every time column — HBM is the binding constraint at the flagship
    shapes, and a program whose temp bytes regressed will OOM a shape the
    previous round handled even when its timing held).

    Two programs, at the exact headline shapes from the timing phases:

    * ``fused_solve``  — ONE CG solve (CG_ITERS iterations, GGN FVP; with
      ``fvp_factory`` also a ``fused_solve_pallas`` row for the kernel
      that carried the headline);
    * ``full_update``  — one complete natural-gradient update
      (``_update_bench_setup``'s program: grad → solve → linesearch →
      rollback).

    Unlike the timing phases these are UNchained: the scan reuses its
    carry buffers, so a chained program's temp bytes describe one link
    anyway, while its argument bytes would scale with the chain — the
    single-shot program is the number a capacity planner wants. Cost: one
    XLA compile per analyzed program, nothing executed
    (``obs/memory.program_memory_analysis`` lowers against the real
    operands). ``BENCH_MEMORY=0`` skips. Failures null the field, never
    the bench."""
    import contextlib

    from trpo_tpu.obs.memory import program_memory_analysis
    from trpo_tpu.ops import conjugate_gradient, make_ggn_fvp

    ctx = (
        jax.default_device(device)
        if device is not None
        else contextlib.nullcontext()
    )
    out = {}
    with ctx:
        flat0, g = problem.flat0, problem.g
        if device is not None:
            flat0 = jax.device_put(np.asarray(flat0), device)
            g = jax.device_put(np.asarray(g), device)
        weight = jnp.ones((BATCH,), jnp.float32)

        def one_solve_prog(factory):
            @jax.jit
            def one_solve(flat0, g):
                if factory is not None:
                    fvp = factory(flat0)
                else:
                    fvp = make_ggn_fvp(
                        problem.apply_fn,
                        problem.fisher_weight,
                        flat0,
                        weight,
                        damping=DAMPING,
                    )
                return conjugate_gradient(
                    fvp, -g, CG_ITERS, residual_tol=0.0
                ).x

            return one_solve

        fields = program_memory_analysis(
            one_solve_prog(None), (flat0, g)
        )
        if fields:
            out["fused_solve"] = fields
        if fvp_factory is not None:
            fields = program_memory_analysis(
                one_solve_prog(fvp_factory), (flat0, g)
            )
            if fields:
                out["fused_solve_pallas"] = fields

        _policy, params, batch, _cfg, update = _update_bench_setup(device)
        fields = program_memory_analysis(
            jax.jit(update), (params, batch)
        )
        if fields:
            out["full_update"] = fields
    return out


def _pallas_fvp_factory(problem: Problem):
    """``flat0 -> fvp`` building the fused single-kernel Pallas GGN
    operator (``ops/fused_fvp.py``) in the flat-vector domain — the
    framework's default solve path on TPU (``cfg.fvp_mode="auto"``)."""
    from trpo_tpu.ops import flatten_params
    from trpo_tpu.ops.fused_fvp import make_fused_gaussian_mlp_fvp

    weight = jnp.ones((BATCH,), jnp.float32)

    def factory(flat0):
        params0 = problem.unravel(flat0)
        tree_fvp = make_fused_gaussian_mlp_fvp(
            params0["net"], problem.obs, weight, params0["log_std"],
            DAMPING, compute_dtype=jnp.bfloat16,
        )

        def fvp(v):
            return flatten_params(tree_fvp(problem.unravel(v)))[0]

        return fvp

    return factory


def time_fused_solve(problem: Problem, device=None, fvp_factory=None):
    """Our path: CG + FVP as ONE device program, forced to CG_ITERS iters
    (residual_tol=0 → no early exit; equal work vs the baseline loop),
    using the framework's DEFAULT Fisher-vector product — the Gauss-Newton
    factorization (``cfg.fvp_mode="ggn"``, ``ops/fvp.make_ggn_fvp``; 1.9×
    the jvp∘grad form on the v5e at this shape, identical solutions).

    CHAIN solves run as a single ``lax.scan`` whose carry makes each solve
    depend on the previous one — strictly sequential on device, timed with
    one result download, RTT-corrected (see ``_device_rtt``).

    ``device=None`` uses the default backend; passing an explicit device
    (the CPU-fallback path) pins compilation and data there — config-level
    platform switches don't work once backends are initialized.
    """
    import contextlib

    from trpo_tpu.ops import conjugate_gradient, make_ggn_fvp

    flat0, g = problem.flat0, problem.g
    ctx = (
        jax.default_device(device)
        if device is not None
        else contextlib.nullcontext()
    )
    with ctx:
        if device is not None:
            flat0 = jax.device_put(np.asarray(flat0), device)
            g = jax.device_put(np.asarray(g), device)
        # Chaining+RTT-correction exists for the tunneled accelerator; on
        # the CPU paths (fallback or forced) each solve is seconds, RTT is
        # microseconds — keep the chain short there. Like the full-update
        # chain, the round-5 kernel made CHAIN solves (~130 ms) sit too
        # close to the ~110 ms RTT — double the window so the correction's
        # jitter stops moving the headline by a few percent.
        n_chain = 2 * CHAIN if (_ACCEL and device is None) else 3
        n_reps = TIMING_REPS if (_ACCEL and device is None) else 1
        G = _chain_inputs(g, jax.random.key(7), n_chain)
        weight = jnp.ones((BATCH,), jnp.float32)

        @jax.jit
        def chained_solves(flat0, G):
            if fvp_factory is not None:
                fvp = fvp_factory(flat0)
            else:
                fvp = make_ggn_fvp(
                    problem.apply_fn,
                    problem.fisher_weight,
                    flat0,
                    weight,
                    damping=DAMPING,
                )

            def body(carry, g_i):
                # eps·carry[0] is float-noise-level but opaque to the
                # compiler — it serializes the solves and prevents hoisting
                rhs = -(g_i + jnp.float32(1e-30) * carry[0])
                x = conjugate_gradient(
                    fvp, rhs, CG_ITERS, residual_tol=0.0
                ).x
                return x, ()

            x_last, _ = jax.lax.scan(body, jnp.zeros_like(flat0), G)
            # scalar probe: the timed sync downloads 4 bytes, not the
            # ~660KB solution (whose transfer would pollute the timing)
            return x_last, x_last.sum()

        _progress("fused solve: compiling")
        x, probe = chained_solves(flat0, G)   # compile + warm
        np.asarray(probe)
        rtt = _device_rtt()
        _progress(f"fused solve: timing (rtt {rtt * 1e3:.0f} ms)")
        runs = []
        for _ in range(n_reps):
            t0 = time.perf_counter()
            x, probe = chained_solves(flat0, G)
            np.asarray(probe)          # the only reliable sync point
            runs.append(time.perf_counter() - t0)
        best = min(runs)
        np.asarray(x)                  # solution fetch, outside the timing
        _progress("fused solve: done")
    if best <= rtt:
        _progress(
            f"WARNING: timed chain ({best * 1e3:.1f} ms) not above RTT "
            f"({rtt * 1e3:.1f} ms) — per-iter time clamped"
        )
    to_per_iter = lambda s: max(s - rtt, 1e-6) / (n_chain * CG_ITERS) * 1e3
    return to_per_iter(best), x, [to_per_iter(s) for s in runs]


def width_study(widths, device=None):
    """MFU-vs-width scaling (VERDICT r2 item 2): the 256-wide headline
    shape runs bandwidth-bound; this measures the SAME fused solve at
    wider hiddens (same 376-obs/17-act, same batch) to show MFU climbing
    toward compute-bound as arithmetic intensity grows — turning "27% MFU
    is the shape's ceiling" from argument into data. Per-width numbers
    use the analytic tangent FLOP model (tagged as such in the JSON; the
    model is the same one the headline falls back to).

    Each width runs through the SAME ``fvp_factory`` selection as the
    headline (VERDICT r5 item 2: the r05 artifact of record quoted the
    XLA chain's 56.7% at width 512 while the shipping Pallas kernel does
    ~76% there): the single-kernel Pallas GGN operator wherever it is
    eligible (TPU backend, 128-multiple hidden width) and validated by a
    one-FVP cosine check against the XLA operator; every row carries an
    explicit ``solve_path`` tag, with ``fallback_reason`` on the rows
    that kept the XLA chain.

    ``device`` pins the whole study (build included) — after a TPU→CPU
    fallback the default backend is the wedged tunnel, which HANGS on
    compile rather than raising; every step here must stay guarded and
    pinned."""
    rows = []
    ctx = (
        jax.default_device(device)
        if device is not None
        else contextlib.nullcontext()
    )
    for w in widths:
        hidden = (w, w)
        _progress(f"width study: hidden {hidden}")
        try:
            with ctx:
                prob = build_problem(
                    jnp.bfloat16 if _ACCEL else jnp.float32, hidden=hidden
                )
        except Exception as e:
            _progress(f"width {w} build failed ({type(e).__name__}: {e})")
            continue
        solve_path, fallback_reason, factory = "pallas_fused", None, None
        if not (_ACCEL and device is None):
            solve_path, fallback_reason = "xla_ggn", "non-TPU backend"
        elif w % 128:
            solve_path, fallback_reason = (
                "xla_ggn", f"hidden width {w} is not a 128-lane multiple"
            )
        else:
            try:
                factory = _pallas_fvp_factory(prob)
                # one-FVP validation: same operator product as XLA GGN
                # (cosine), so a kernel row can never quote a timing for
                # a wrong operator
                from trpo_tpu.ops import make_ggn_fvp

                weight = jnp.ones((BATCH,), jnp.float32)
                hv_k = np.asarray(factory(prob.flat0)(prob.g))
                hv_x = np.asarray(
                    make_ggn_fvp(
                        prob.apply_fn, prob.fisher_weight, prob.flat0,
                        weight, DAMPING,
                    )(prob.g)
                )
                cos = float(
                    np.dot(hv_k, hv_x)
                    / (np.linalg.norm(hv_k) * np.linalg.norm(hv_x))
                )
                if not cos > 0.99:
                    solve_path, fallback_reason, factory = (
                        "xla_ggn", f"kernel FVP cosine {cos:.4f}", None
                    )
            except Exception as e:
                solve_path, fallback_reason, factory = (
                    "xla_ggn", f"{type(e).__name__}: {e}", None
                )
        try:
            ms, _x, _runs = time_fused_solve(
                prob, device=device, fvp_factory=factory
            )
        except Exception as e:
            if factory is None:
                _progress(f"width {w} failed ({type(e).__name__}: {e})")
                continue
            # kernel path died mid-timing — retry once on the XLA chain
            _progress(
                f"width {w} kernel solve failed ({type(e).__name__}: {e})"
                " — retrying on the XLA chain"
            )
            solve_path, fallback_reason, factory = (
                "xla_ggn", f"{type(e).__name__}: {e}", None
            )
            try:
                ms, _x, _runs = time_fused_solve(prob, device=device)
            except Exception as e2:
                _progress(f"width {w} failed ({type(e2).__name__}: {e2})")
                continue
        tangent = _analytic_fvp_tangent_flops(hidden)
        row = {
            "hidden": list(hidden),
            "solve_path": solve_path,
            "ms_per_iter": round(ms, 4),
            "analytic_flops_per_cg_iter": round(tangent, 0),
            "achieved_tflops": round(tangent / (ms * 1e-3) / 1e12, 2),
        }
        if fallback_reason is not None:
            row["fallback_reason"] = fallback_reason
        rows.append(row)
    return rows


def _host_cg_loop(fvp_host, b, iters=None):
    """The reference's host NumPy CG recurrence (``utils.py:185-201``) —
    shared by the CPU baseline and the fusion-ablation row so both compare
    the SAME solver semantics against the fused path."""
    x = np.zeros_like(b)
    r = b.copy()
    p = b.copy()
    rdotr = r.dot(r)
    for _ in range(iters or CG_ITERS):
        z = fvp_host(p)
        alpha = rdotr / p.dot(z)
        x += alpha * p
        r -= alpha * z
        new_rdotr = r.dot(r)
        p = r + (new_rdotr / rdotr) * p
        rdotr = new_rdotr
    return x


def time_host_driven_cg(problem: Problem):
    """Transport ablation: the SAME device FVP the fused solve uses (the
    Gauss-Newton form, bf16 matmuls on the accelerator) but the
    reference's host-driven CG loop (``utils.py:185-201``) — tangent
    uploaded, FVP run, result downloaded, damping and all CG vector
    arithmetic on the host, once per iteration.

    On this tunneled setup raw ≈ one ~100 ms round trip per iteration —
    transport dwarfs compute — so the row documents the transport cost;
    speedup claims come from the transport-free CPU pair in ``main``.
    The RTT-corrected value is dropped when it lands below the jitter
    floor (subtracting ~RTT from ~RTT is noise, round-2 lesson)."""
    from trpo_tpu.ops import make_ggn_fvp

    weight = jnp.ones((BATCH,), jnp.float32)

    @jax.jit
    def fvp_dev(flat, v):
        # damping added host-side (reference semantics)
        return make_ggn_fvp(
            problem.apply_fn, problem.fisher_weight, flat, weight, 0.0
        )(v)

    flat0 = problem.flat0

    def fvp_host(p):                          # one round trip per call
        out = fvp_dev(flat0, jnp.asarray(p, jnp.float32))
        return np.asarray(out) + DAMPING * p

    b = -np.asarray(problem.g)
    _progress("host-driven CG: compiling")
    fvp_host(b)                               # compile + warm
    rtt = _device_rtt()
    n_loops = 3
    _progress(f"host-driven CG: timing (rtt {rtt * 1e3:.0f} ms)")
    t0 = time.perf_counter()
    for _ in range(n_loops):
        x = _host_cg_loop(fvp_host, b)
    dt = time.perf_counter() - t0
    _progress("host-driven CG: done")
    raw_ms = dt / (n_loops * CG_ITERS) * 1e3
    corrected_ms = raw_ms - rtt * 1e3
    if corrected_ms < 0.05 * raw_ms:
        # raw ≈ one RTT per iteration, so the correction is the small
        # difference of two noisy numbers; when it lands below the RTT
        # jitter floor (a few % of the window) publishing it would turn
        # pure timing noise into a huge "speedup" — keep the raw row only
        _progress(
            f"WARNING: host-driven per-iter ({raw_ms:.1f} ms) within "
            f"noise of RTT ({rtt * 1e3:.1f} ms) — dropping the corrected "
            "row"
        )
        corrected_ms = None
    return raw_ms, corrected_ms, x


def time_standalone_fvp(problem: Problem, n_chain=400):
    """The STABLE kernel-level fusion ablation: per-call cost of one
    standalone FVP (Gauss-Newton form — same as the fused path) with a
    MOVING linearization point — the device work a host-driven CG loop
    cannot avoid even with zero transport (each call re-pays the primal
    linearization; the fused loop hoists it once per solve).
    Chained-dependent timing per `_device_rtt` rules, so unlike
    `time_host_driven_cg` (raw ≈ one tunnel RTT per iteration) this
    number reproduces run to run. this ÷ fused-per-iter = the
    kernel-level fusion factor (fusion_speedup_kernel_level); the rest
    of the host-driven gap is dispatch+transport. Dtypes match the fused
    path exactly (flat stays fp32; bf16 casting happens inside
    policy.apply on both paths)."""
    from trpo_tpu.ops import make_ggn_fvp

    weight = jnp.ones((BATCH,), jnp.float32)
    flat0, g = problem.flat0, problem.g

    @jax.jit
    def chained(flat0, g):
        def body(carry, _):
            # carry-dependent linearization point: float-noise-level but
            # opaque — forces the primal to recompute every call, as a
            # host loop's separate dispatches would
            flat = flat0 + jnp.float32(1e-30) * carry
            hv = make_ggn_fvp(
                problem.apply_fn, problem.fisher_weight, flat, weight,
                DAMPING,
            )(g + jnp.float32(1e-30) * carry)
            return hv, ()

        hv, _ = jax.lax.scan(
            body, jnp.zeros_like(g), None, length=n_chain
        )
        return hv, hv.sum()

    _progress("standalone FVP: compiling")
    hv, probe = chained(flat0, g)
    np.asarray(probe)
    rtt = _device_rtt()
    _progress(f"standalone FVP: timing (rtt {rtt * 1e3:.0f} ms)")
    best = float("inf")
    for _ in range(TIMING_REPS):
        t0 = time.perf_counter()
        hv, probe = chained(flat0, g)
        np.asarray(probe)
        best = min(best, time.perf_counter() - t0)
    _progress("standalone FVP: done")
    if best <= rtt:
        # an invalid measurement must not publish a ~0 ms row (which the
        # JSON would read as an infinite fusion win) — drop it instead
        _progress(
            f"WARNING: standalone-FVP chain ({best * 1e3:.1f} ms) not "
            f"above RTT ({rtt * 1e3:.1f} ms) — dropping the row"
        )
        return None
    return (best - rtt) / n_chain * 1e3


def time_reference_semantics(problem: Problem):
    """Reference path: host NumPy CG; ONE device FVP call per iteration
    with host transfer both ways + host-side damping (ref utils.py:185-201,
    trpo_inksci.py:124-126) — the FVP as the reference computes it, double
    backprop of the stop-grad KL (here jvp∘grad, same graph shape) — on
    the CPU backend."""
    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        flat_c = jax.device_put(np.asarray(problem.flat0), cpu)

        @jax.jit
        def fvp_dev(flat, v):
            grad_kl = jax.grad(problem.kl_fn)
            return jax.jvp(grad_kl, (flat,), (v,))[1]

        def fvp_host(p):                      # one round trip per call
            out = fvp_dev(flat_c, jax.device_put(p.astype(np.float32), cpu))
            return np.asarray(out) + DAMPING * p

        b = -np.asarray(problem.g)

        _progress("baseline: compiling")
        fvp_host(b)                           # compile + warm (one FVP)
        _progress("baseline: timing")
        t0 = time.perf_counter()
        for _ in range(BASELINE_REPS):
            x = _host_cg_loop(fvp_host, b)
        dt = time.perf_counter() - t0
        _progress("baseline: done")
    return dt / (BASELINE_REPS * CG_ITERS) * 1e3, x


def time_host_driven_cpu_ggn(problem: Problem):
    """The fusion isolator: the reference's host-driven CG loop on the
    in-process CPU backend but with the SAME Gauss-Newton FVP the fused
    solve uses — so (this ÷ fused-CPU) is pure loop fusion, uncontaminated
    by either transport (both in-process) or the FVP factorization swap
    (both GGN). The plain baseline above keeps the reference's jvp∘grad
    FVP; its ratio to the fused solve is the overall solver-vs-reference
    win on identical hardware."""
    from trpo_tpu.ops import make_ggn_fvp

    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        flat_c = jax.device_put(np.asarray(problem.flat0), cpu)
        weight = jnp.ones((BATCH,), jnp.float32)

        @jax.jit
        def fvp_dev(flat, v):
            return make_ggn_fvp(
                problem.apply_fn, problem.fisher_weight, flat, weight, 0.0
            )(v)

        def fvp_host(p):
            out = fvp_dev(flat_c, jax.device_put(p.astype(np.float32), cpu))
            return np.asarray(out) + DAMPING * p

        b = -np.asarray(problem.g)
        _progress("host-driven CPU (GGN): compiling")
        fvp_host(b)
        _progress("host-driven CPU (GGN): timing")
        t0 = time.perf_counter()
        x = _host_cg_loop(fvp_host, b)
        dt = time.perf_counter() - t0
        _progress("host-driven CPU (GGN): done")
    return dt / CG_ITERS * 1e3, x


def host_pipeline_bench(
    n_envs: int = 4,
    t_steps: int = 1,
    n_iters: int = 8,
    warmup_iters: int = 2,
):
    """End-to-end host-env driver metric: iterations/s, serial vs
    async-pipelined, on a sleep-bound simulator (ISSUE 1 tentpole).

    The probe env (``envs/sleep_env.SleepEnv`` over the worker-process
    adapter) spends its step time in ``time.sleep`` — the same
    core-releasing blocking profile as a real multicore simulator — and
    its per-step sleep is CALIBRATED against the measured zero-sleep
    iteration so one window of host stepping costs about one device
    update: the "host step time ≈ device update time" regime where the
    pipeline's overlap is the whole story (acceptance bar: ≥1.5× there).
    Both drivers use the same adapter, so worker-level stepping
    parallelism is identical and the measured gap isolates the DRIVER:
    the serial loop pays rollout + policy update + VF fit + stats fetch
    per iteration; the async one awaits only the policy phase and runs
    the VF-fit/stats program plus the stats round trip behind the next
    window's host stepping.

    ``t_steps`` defaults to 1 (one vectorized env step per window —
    ``n_envs`` transitions per update): on a single-execution-stream
    backend (this CPU; one TPU core), a deferred phase-B program can only
    slot into the queue behind inference the window has ALREADY issued,
    so the fully-hideable regime is one inference per window. Larger
    windows overlap fully when rollout inference runs on a separate
    backend (``host_inference="cpu"`` against a real accelerator) or a
    multi-core XLA pool. ``device_rtt_ms`` is published alongside so the
    hidden-latency claim is checkable against the transport cost.
    """
    import io

    from trpo_tpu import envs as envs_lib
    from trpo_tpu.agent import TRPOAgent
    from trpo_tpu.config import TRPOConfig
    from trpo_tpu.utils.metrics import StatsLogger

    def _cfg(**kw):
        # phase B (VF fit, 200 fused Adam steps on a 256×256 critic)
        # deliberately outweighs phase A (CG with a short budget): A gates
        # the next on-policy rollout, B is what the pipeline can hide —
        # this shape makes the hideable part dominant.
        return TRPOConfig(
            n_envs=n_envs,
            batch_timesteps=n_envs * t_steps,
            policy_hidden=(16,),
            vf_hidden=(256, 256),
            vf_train_steps=200,
            cg_iters=5,
            seed=0,
            **kw,
        )

    def _env(sleep_ms):
        return envs_lib.make(
            "gymproc:trpo_tpu.envs.sleep_env:SleepEnv",
            n_envs=n_envs,
            n_workers=n_envs,
            sleep_ms=sleep_ms,
            episode_len=200,
        )

    def _timed_learn(env, cfg, iters, warm):
        agent = TRPOAgent(env, cfg)
        logger = StatsLogger(stream=io.StringIO())
        state = agent.learn(n_iterations=warm, logger=logger)
        t0 = time.perf_counter()
        state = agent.learn(n_iterations=iters, state=state, logger=logger)
        jax.block_until_ready(state.policy_params)
        dt = time.perf_counter() - t0
        logger.close()
        return iters / dt, dt / iters * 1e3

    # -- calibrate: serial iteration time at zero sleep ≈ update + driver
    #    overhead; giving the window that much sleep makes host stepping
    #    ≈ one update per iteration --
    env0 = _env(0.0)
    try:
        _, iter0_ms = _timed_learn(env0, _cfg(), max(4, n_iters // 2), 2)
    finally:
        env0.close()
    sleep_ms = max(0.2, iter0_ms / t_steps)

    _progress(
        f"host-env pipeline bench: calibrated sleep {sleep_ms:.2f} ms/step "
        f"(zero-sleep iteration {iter0_ms:.1f} ms)"
    )
    env_s = _env(sleep_ms)
    try:
        serial_ips, serial_ms = _timed_learn(
            env_s, _cfg(), n_iters, warmup_iters
        )
    finally:
        env_s.close()
    env_p = _env(sleep_ms)
    try:
        piped_ips, piped_ms = _timed_learn(
            env_p,
            _cfg(host_async_pipeline=True),
            n_iters,
            warmup_iters,
        )
    finally:
        env_p.close()

    return {
        "metric": "host_env_iterations_per_sec_sleep_sim",
        "n_envs": n_envs,
        "steps_per_iteration": t_steps,
        "sleep_ms_per_step": round(sleep_ms, 3),
        "host_step_ms_per_iter": round(sleep_ms * t_steps, 2),
        "update_plus_overhead_ms": round(iter0_ms, 2),
        "n_iterations_timed": n_iters,
        "serial_iterations_per_sec": round(serial_ips, 3),
        "serial_ms_per_iter": round(serial_ms, 2),
        "pipelined_iterations_per_sec": round(piped_ips, 3),
        "pipelined_ms_per_iter": round(piped_ms, 2),
        "pipelined_speedup": round(piped_ips / serial_ips, 3),
        "device_rtt_ms": round(_device_rtt() * 1e3, 2),
    }


def training_overlap_bench(
    widths=(128, 512),
    t_steps: int = 128,
    n_iters: int = 8,
    real_iters: int = 3,
    warmup_iters: int = 2,
):
    """Pipelined actor/learner training loop (ISSUE 17): synchronous vs
    overlapped env-steps/s at a calibrated update cost, over 2-3 fleet
    widths, plus per-stage p99s from a rate-1.0 traced run of the REAL
    pipeline.

    Two measurements per width, same split as ``host_pipeline_bench``:

    1. **Real pipeline, traced.** ``agent._overlap_run`` with a
       rate-1.0 :class:`obs.trace.Tracer` — real stage programs, real
       env-steps/s, and per-stage p99s (rollout_chunk / transfer /
       advantage / fvp_cg_solve / linesearch / vf_fit / update) parsed
       from the span rows. On this 1-core CPU host both "devices" share
       the core, so the real-pipeline rate shows driver overhead, not
       overlap — the located stage rows are what this leg is for.
    2. **Calibrated-update drivers, gated.** The overlap win is
       rollout hidden behind the update, which needs the learner's
       compute OFF the actor's core — exactly the accelerator-resident
       regime the pipeline targets, and exactly what a 1-core CPU
       cannot stage with two compute-bound programs. So, following
       ``host_pipeline_bench``'s calibrated-sleep idiom, the gated
       sync-vs-overlap pair times the REAL chunked window collection
       against an update whose cost is CALIBRATED to one measured
       rollout window and spent core-releasing (``time.sleep`` — the
       blocking profile of a host thread awaiting a device update).
       Both drivers pay identical rollout + update costs; the measured
       gap isolates the DRIVER schedule — the ≥1.3× acceptance gate
       (check.sh) judges this pair.
    """
    import tempfile
    from concurrent.futures import ThreadPoolExecutor

    from trpo_tpu.agent import TRPOAgent
    from trpo_tpu.config import TRPOConfig
    from trpo_tpu.obs.events import EventBus, JsonlSink
    from trpo_tpu.obs.trace import Tracer

    # smoke-run scaling knobs (same idiom as BENCH_FLEET_*)
    env_widths = os.environ.get("BENCH_OVERLAP_WIDTHS")
    if env_widths:
        widths = tuple(int(w) for w in env_widths.split(",") if w)
    n_iters = int(os.environ.get("BENCH_OVERLAP_ITERS", n_iters))
    real_iters = int(os.environ.get("BENCH_OVERLAP_REAL_ITERS", real_iters))
    t_steps = int(os.environ.get("BENCH_OVERLAP_T", t_steps))
    # warmup must cover BOTH advantage programs (iteration 0 = the fill
    # window's plain batch, iteration 1+ = the stale/IS-corrected one) so
    # the traced leg's spans time execution, not compilation
    warmup_iters = max(warmup_iters, 2)

    _STAGES = (
        "rollout_chunk", "transfer", "advantage", "fvp_cg_solve",
        "linesearch", "vf_fit", "update",
    )

    def _fresh_carry(agent, state, key):
        carry = jax.device_put(
            jax.tree_util.tree_map(jnp.copy, state.env_carry),
            agent._actor_device,
        )
        rp = jax.device_put(
            (state.policy_params, state.obs_norm), agent._actor_device
        )
        return rp, carry, key

    rows = []
    for w in widths:
        cfg = TRPOConfig(
            env="cartpole",
            n_envs=w,
            batch_timesteps=w * t_steps,
            rollout_chunk=4,
            vf_train_steps=50,
            cg_iters=10,
            normalize_obs=True,
            seed=0,
            train_overlap=1,
        )
        agent = TRPOAgent("cartpole", cfg)
        state = agent.init_state()
        state, _ = agent.run_iterations(state, warmup_iters)  # compile

        # -- leg 1: real pipeline under a rate-1.0 tracer --
        with tempfile.NamedTemporaryFile(
            suffix=".jsonl", delete=False
        ) as f:
            trace_path = f.name
        bus = EventBus(JsonlSink(trace_path))
        tracer = Tracer(bus, 1.0, process="bench")
        t0 = time.perf_counter()
        state, _ = agent._overlap_run(state, real_iters, tracer=tracer)
        real_dt = time.perf_counter() - t0
        tracer.drain()
        tracer.close()
        bus.close()
        durs = {s: [] for s in _STAGES}
        with open(trace_path) as f:
            for line in f:
                ev = json.loads(line)
                if ev.get("kind") != "span":
                    continue
                stage = ev.get("name", "").removeprefix("train/")
                if stage in durs:
                    durs[stage].append(float(ev["dur_ms"]))
        os.unlink(trace_path)
        stage_p99 = {
            s: round(float(np.percentile(v, 99)), 3)
            for s, v in durs.items() if v
        }

        # -- calibrate: one measured window of REAL chunk streaming;
        #    the stand-in update costs exactly that (update ≈ rollout,
        #    the regime where the overlap is the whole story) --
        # window sizing note: the overlapped driver pays a few ms per
        # iteration in thread hand-off + sleep-wake latency (GIL-bound
        # on a 1-core host) — t_steps defaults keep the window ≥ ~25 ms
        # so that overhead cannot eat the gate's 1.3x margin
        key = jax.random.key(0)
        rp, carry, key = _fresh_carry(agent, state, key)
        agent._overlap_collect(rp, carry, key, None, None)  # warm path
        rp, carry, key = _fresh_carry(agent, state, key)
        t0 = time.perf_counter()
        carry, _ = agent._overlap_collect(rp, carry, key, None, None)
        roll_s = time.perf_counter() - t0
        upd_s = roll_s

        def _windows(agent, state, n):
            # independent window collections with the same params — the
            # drivers time the COLLECTION cost, not the training
            rp, carry, key = _fresh_carry(agent, state, jax.random.key(1))
            for i in range(n):
                key, k = jax.random.split(key)
                carry, _ = agent._overlap_collect(rp, carry, k, None, None)
                yield i

        # -- leg 2a: synchronous driver (collect, then update, serially)
        t0 = time.perf_counter()
        for _ in _windows(agent, state, n_iters):
            time.sleep(upd_s)
        sync_dt = time.perf_counter() - t0

        # -- leg 2b: overlapped driver (update k ∥ collect k+1) --
        with ThreadPoolExecutor(1) as ex:
            t0 = time.perf_counter()
            gen = _windows(agent, state, n_iters)
            next(gen)  # fill window
            for k in range(n_iters):
                fut = ex.submit(time.sleep, upd_s)
                if k + 1 < n_iters:
                    next(gen)
                fut.result()
            overlap_dt = time.perf_counter() - t0

        steps_per_iter = w * t_steps
        rows.append({
            "n_envs": w,
            "t_steps": t_steps,
            "env_steps_per_iter": steps_per_iter,
            "rollout_window_ms": round(roll_s * 1e3, 2),
            "calibrated_update_ms": round(upd_s * 1e3, 2),
            "sync_env_steps_per_sec": round(
                n_iters * steps_per_iter / sync_dt, 1
            ),
            "sync_ms_per_iter": round(sync_dt / n_iters * 1e3, 2),
            "overlap_env_steps_per_sec": round(
                n_iters * steps_per_iter / overlap_dt, 1
            ),
            "overlap_ms_per_iter": round(overlap_dt / n_iters * 1e3, 2),
            "overlap_speedup": round(sync_dt / overlap_dt, 3),
            "real_pipeline_env_steps_per_sec": round(
                real_iters * steps_per_iter / real_dt, 1
            ),
            "real_pipeline_ms_per_iter": round(
                real_dt / real_iters * 1e3, 2
            ),
            "stage_p99_ms": stage_p99,
        })
        _progress(
            f"training overlap w={w}: sync "
            f"{rows[-1]['sync_env_steps_per_sec']:.0f} steps/s, "
            f"overlapped {rows[-1]['overlap_env_steps_per_sec']:.0f} "
            f"steps/s ({rows[-1]['overlap_speedup']:.2f}x)"
        )

    return {
        "metric": "training_overlap_env_steps_per_sec",
        "n_iterations_timed": n_iters,
        "cpu_count": os.cpu_count(),
        "n_devices": len(jax.devices()),
        "note": (
            "sync/overlap pair: real chunked window collection vs a "
            "core-releasing update calibrated to one rollout window "
            "(the accelerator-resident-learner regime; see docstring). "
            "real_pipeline_* rows run the actual staged programs with "
            "rate-1.0 tracing — per-stage p99s come from those spans."
        ),
        "rows": rows,
    }


def serving_bench(
    batch_shapes=(1, 8, 64),
    closed_reps: int = 30,
    open_requests: int = 120,
    max_concurrency: int = 16,
    deadline_ms: float = 5.0,
):
    """Latency SLOs for the policy-serving tier (ISSUE 6): p50/p99 and
    actions/s per AOT batch rung, closed-loop and open-loop.

    Closed loop: back-to-back ``engine.infer`` calls at EXACTLY the rung
    size — the engine's intrinsic per-dispatch latency with zero queueing
    (the executable is AOT-compiled, so no call ever traces). Open loop:
    independent single-obs clients hammering the micro-batcher
    concurrently — what an HTTP front end actually sees, queueing and
    coalescing included (``mean_batch`` says how well the batcher filled
    the rung; concurrency is capped at ``max_concurrency`` so the probe
    measures the data plane, not this host's thread scheduler).
    """
    import threading as _threading

    from trpo_tpu.agent import TRPOAgent
    from trpo_tpu.config import TRPOConfig
    from trpo_tpu.serve import MicroBatcher
    from trpo_tpu.utils.metrics import quantile_nearest_rank as _q

    agent = TRPOAgent(
        "cartpole",
        TRPOConfig(
            n_envs=4, batch_timesteps=32, policy_hidden=(16,),
            vf_hidden=(16,), seed=0,
            serve_batch_shapes=tuple(batch_shapes),
        ),
    )
    state = agent.init_state(seed=0)
    engine = agent.serve_engine()
    engine.load(state.policy_params, state.obs_norm, step=0)
    rng = np.random.RandomState(0)
    obs_shape = agent.obs_shape

    rows = []
    for rung in engine.batch_shapes:
        obs = rng.randn(rung, *obs_shape).astype(np.float32)
        for _ in range(3):  # prime host-side caches; compiles are done
            engine.infer(obs)
        lats = []
        for _ in range(closed_reps):
            t0 = time.perf_counter()
            engine.infer(obs)
            lats.append((time.perf_counter() - t0) * 1e3)
        mean_s = (sum(lats) / len(lats)) / 1e3
        closed = {
            "p50_ms": round(_q(lats, 0.5), 4),
            "p99_ms": round(_q(lats, 0.99), 4),
            "actions_per_sec": round(rung / mean_s, 1),
        }

        # mirror the production default (cfg.serve_adaptive_deadline) —
        # the SLO numbers must measure the dispatch semantics serve.py
        # actually runs
        batcher = MicroBatcher(
            engine, deadline_ms=deadline_ms,
            adaptive_deadline=agent.cfg.serve_adaptive_deadline,
        )
        conc = min(rung, max_concurrency)
        per_client = max(1, open_requests // conc)
        open_lats: list = []
        lat_lock = _threading.Lock()

        def _client(seed: int) -> None:
            r = np.random.RandomState(seed)
            mine = []
            for _ in range(per_client):
                one = r.randn(*obs_shape).astype(np.float32)
                t0 = time.perf_counter()
                batcher.submit(one).result(timeout=60.0)
                mine.append((time.perf_counter() - t0) * 1e3)
            with lat_lock:
                open_lats.extend(mine)

        threads = [
            _threading.Thread(target=_client, args=(i,), daemon=True)
            for i in range(conc)
        ]
        t_start = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall_s = time.perf_counter() - t_start
        n_served = conc * per_client
        open_loop = {
            "concurrency": conc,
            "requests": n_served,
            "p50_ms": round(_q(open_lats, 0.5), 4),
            "p99_ms": round(_q(open_lats, 0.99), 4),
            "actions_per_sec": round(n_served / wall_s, 1),
            "mean_batch": round(
                batcher.requests_total / max(batcher.batches_total, 1), 2
            ),
        }
        batcher.close()
        rows.append({
            "batch_shape": rung,
            "closed_loop": closed,
            "open_loop": open_loop,
        })

    dev = jax.devices()[0]
    return {
        "metric": "serving_slo_cartpole_mlp16",
        "batch_shapes": list(engine.batch_shapes),
        "deadline_ms": deadline_ms,
        "backend": dev.platform,
        "rows": rows,
    }


def serving_scale_bench(
    replica_counts=(1, 2, 4),
    clients: int = 8,
    per_client: int = 8,
    sim_cost_ms: float = 60.0,
    batch_shapes=(1,),
):
    """Replica-scaling SLOs for the routing control plane (ISSUE 9):
    closed-loop actions/s and p50/p99 through the router at 1/2/4
    replicas, plus the scaling efficiency ``aps_N / (N × aps_1)``.

    Each replica's engine wears a ``SimulatedCostEngine`` sleep of
    ``sim_cost_ms`` — device time emulated GIL-free (the PR 1
    sleep-bound-sim pattern), so on a 2-core CPU box the measurement
    isolates the ROUTER/batcher scaling behavior from host core count:
    replicas are capacity-limited at their top rung
    (``batch_shapes[-1]`` per dispatch, ~1/sim_cost_ms dispatches/s),
    which is exactly the regime where adding replicas is supposed to
    pay — a model heavy enough to need replication is engine-bound,
    not router-bound. Clients hold keep-alive connections (the
    router holds its own pool to the replicas), so the measured path
    is steady-state routing, not per-request TCP setup. The default
    sim cost (60 ms) keeps the 4-replica aggregate well under this
    2-core box's Python-overhead ceiling (~150-200 req/s through two
    HTTP hops): nearer that ceiling the ratio swings with scheduler
    noise (observed 1.9-4.0x at 30 ms across identical runs); at
    60 ms the gate ratio repeats within ±0.1. The TPU-measured rows
    (real engines, no sleep) are the ROADMAP follow-up.
    """
    import http.client as _httpc
    import json as _json
    import socket as _socket
    import threading as _threading
    import urllib.parse as _urlparse

    from trpo_tpu.agent import TRPOAgent
    from trpo_tpu.config import TRPOConfig
    from trpo_tpu.serve import (
        InProcessReplica,
        MicroBatcher,
        PolicyServer,
        ReplicaSet,
        Router,
    )
    from trpo_tpu.serve.engine import SimulatedCostEngine
    from trpo_tpu.utils.metrics import quantile_nearest_rank as _q

    agent = TRPOAgent(
        "cartpole",
        TRPOConfig(
            n_envs=4, batch_timesteps=32, policy_hidden=(16,),
            vf_hidden=(16,), seed=0,
            serve_batch_shapes=tuple(batch_shapes),
        ),
    )
    state = agent.init_state(seed=0)
    obs_shape = agent.obs_shape

    def factory():
        engine = agent.serve_engine()
        engine.load(state.policy_params, state.obs_norm, step=0)
        sim = SimulatedCostEngine(engine, cost_ms=sim_cost_ms)
        batcher = MicroBatcher(
            sim, deadline_ms=10.0,
            adaptive_deadline=agent.cfg.serve_adaptive_deadline,
        )
        server = PolicyServer(sim, batcher, port=0)
        return server, [batcher]

    rows = []
    for n in replica_counts:
        replicaset = ReplicaSet(
            lambda rid: InProcessReplica(factory),
            n, health_interval=0.25,
        )
        replicaset.start()
        if not replicaset.wait_healthy(n, timeout=120.0):
            replicaset.close()
            raise RuntimeError(f"{n}-replica set never became healthy")
        router = Router(replicaset, port=0, max_inflight=256)
        body = _json.dumps(
            {"obs": [0.0] * int(np.prod(obs_shape))}
        ).encode()
        netloc = _urlparse.urlsplit(router.url).netloc

        lats: list = []
        errors: list = []
        lat_lock = _threading.Lock()

        def _nodelay_conn():
            # TCP_NODELAY on the client half: http.client sends headers
            # and body as two segments; Nagle + the peer's delayed ACK
            # would add ~40 ms stalls that read as engine latency
            conn = _httpc.HTTPConnection(netloc, timeout=60.0)
            conn.connect()
            conn.sock.setsockopt(
                _socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1
            )
            return conn

        def _client() -> None:
            conn = _nodelay_conn()
            mine = []
            for _ in range(per_client):
                t0 = time.perf_counter()
                try:
                    conn.request(
                        "POST", "/act", body=body,
                        headers={"Content-Type": "application/json"},
                    )
                    resp = conn.getresponse()
                    resp.read()
                    if resp.status != 200:
                        raise RuntimeError(f"status {resp.status}")
                except Exception as e:  # counted, never silently dropped
                    with lat_lock:
                        errors.append(repr(e))
                    conn.close()
                    conn = _nodelay_conn()
                    continue
                mine.append((time.perf_counter() - t0) * 1e3)
            conn.close()
            with lat_lock:
                lats.extend(mine)

        # warmup: one client pass primes every replica's host-side path
        # (urllib imports, first-dispatch EMA) before the timed window
        warm = _threading.Thread(target=_client, daemon=True)
        warm.start()
        warm.join()
        with lat_lock:
            lats.clear()
            errors.clear()  # a warmup hiccup must not fail the gate

        threads = [
            _threading.Thread(target=_client, daemon=True)
            for _ in range(clients)
        ]
        t_start = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall_s = time.perf_counter() - t_start
        n_ok = len(lats)
        rows.append({
            "replicas": n,
            "clients": clients,
            "requests": n_ok,
            "errors": len(errors),
            "actions_per_sec": round(n_ok / wall_s, 1),
            "p50_ms": round(_q(lats, 0.5), 3) if lats else None,
            "p99_ms": round(_q(lats, 0.99), 3) if lats else None,
            "retried": router.retried_total,
        })
        router.close()
        replicaset.close()

    # efficiency = per-replica rate vs the FIRST row's per-replica rate
    # (identical to aps_N/(N·aps_1) when the first row is 1 replica,
    # and still correct for replica_counts not starting at 1)
    base_rate = (
        rows[0]["actions_per_sec"] / rows[0]["replicas"]
        if rows and rows[0]["actions_per_sec"] else None
    )
    for row in rows:
        row["scaling_efficiency"] = (
            round(
                row["actions_per_sec"] / row["replicas"] / base_rate, 3
            )
            if base_rate else None
        )
    dev = jax.devices()[0]
    return {
        "metric": "serving_scale_router_cartpole_mlp16",
        "sim_cost_ms": sim_cost_ms,
        "batch_shapes": list(batch_shapes),
        "clients": clients,
        "backend": dev.platform,
        "note": (
            "per-dispatch device time simulated as a GIL-free "
            f"{sim_cost_ms} ms sleep (SimulatedCostEngine) so replica "
            "scaling is measured against a capacity-limited engine "
            "instead of this host's core count; TPU rows are the "
            "ROADMAP follow-up"
        ),
        "rows": rows,
    }


def serving_sessions_bench(
    concurrencies=(1, 4, 16),
    steps_per_session: int = 15,
    sim_cost_ms: float = 20.0,
    batch_shapes=(1, 8, 16),
    deadline_ms: float = 10.0,
):
    """Continuous-batching SLOs for recurrent serving (ISSUE 13):
    session-steps/s + p50/p99 over a concurrency ladder S, serialized
    batch-1 stepping vs the gather/scatter epoch plane.

    Each engine wears a ``SimulatedCostSessionEngine``: the device is
    ONE serial resource charging ``sim_cost_ms`` per DISPATCH (a
    GIL-free sleep behind a dispatch lock — the PR 1 / serving_scale
    calibration pattern), batch-1 or batched alike. That is exactly
    the economics continuous batching exploits: S serialized batch-1
    steps cost S × sim_cost_ms of device time per round, ONE
    ``(S, carry)`` epoch costs ~1 ×, so the measurement isolates the
    batcher/epoch control plane from this host's core count. The
    serialized baseline is the pre-ISSUE-13 engine shape (rung ladder
    ``(1,)``, every session a private dispatch); the batched side runs
    the production ``SessionBatcher`` over the AOT rung ladder. A
    ``RecompileMonitor`` spans the whole batched phase — epoch widths
    drift freely across rungs, and the steady state must show ZERO
    retraces. After timing, every batched session's action stream is
    replayed sequentially at batch 1 and must match BIT-EXACT
    (``action_parity``). The default sim cost (20 ms) keeps the
    serialized baseline clearly capacity-limited on this 2-core box
    (at 5 ms the 16-thread host overhead contaminates both sides and
    the measured ratio halves); the measured S=16 row is the ISSUE 13
    acceptance gate (>= 4x at equal-or-better p99 — observed ~7x with
    batched p99 ~50x BELOW the serialized baseline's). TPU re-run
    protocol: drop the sim-cost wrapper (real MXU dispatches), raise
    batch_shapes to the production ladder (1, 8, 64) and S to 64/256
    — the epoch win should GROW on hardware (a real batch-64 GRU step
    costs barely more than batch-1 on the MXU, while the CPU rows
    under-report at wide rungs where the batched step's host compute
    grows with S).
    """
    import threading as _threading

    from trpo_tpu.agent import TRPOAgent
    from trpo_tpu.config import TRPOConfig
    from trpo_tpu.obs.recompile import RecompileMonitor
    from trpo_tpu.serve import SessionBatcher, SimulatedCostSessionEngine
    from trpo_tpu.utils.metrics import quantile_nearest_rank as _q

    agent = TRPOAgent(
        "pendulum",
        TRPOConfig(
            n_envs=4, batch_timesteps=32, policy_hidden=(16,),
            vf_hidden=(16,), seed=0, policy_gru=16,
            serve_session_batch_shapes=tuple(batch_shapes),
        ),
    )
    state = agent.init_state(seed=0)
    obs_shape = agent.obs_shape

    # serialized baseline: the pre-ISSUE-13 engine — batch-1 ladder,
    # every session's step a private device dispatch
    serial_engine = SimulatedCostSessionEngine(
        agent.serve_session_engine(batch_shapes=(1,)), cost_ms=sim_cost_ms
    )
    serial_engine.load(state.policy_params, state.obs_norm, step=0)

    batched_inner = agent.serve_session_engine()
    retraces = None
    mon = RecompileMonitor()
    rows = []
    with mon:
        batched_engine = SimulatedCostSessionEngine(
            batched_inner, cost_ms=sim_cost_ms
        )
        batched_engine.load(state.policy_params, state.obs_norm, step=0)
        mon.mark_steady()  # the AOT rung ladder is the ONLY compilation

        def _run_clients(n, step_fn):
            """S closed-loop session clients; returns (wall_s, lats_ms,
            per-session (obs, action) streams for the parity replay)."""
            lats: list = []
            streams = [[] for _ in range(n)]
            lock = _threading.Lock()

            def _client(k: int) -> None:
                r = np.random.RandomState(1000 + k)
                carry = batched_inner.initial_carry()
                mine = []
                for _ in range(steps_per_session):
                    o = r.randn(*obs_shape).astype(np.float32)
                    t0 = time.perf_counter()
                    action, carry = step_fn(f"s{k}", carry, o)
                    mine.append((time.perf_counter() - t0) * 1e3)
                    streams[k].append((o, np.asarray(action)))
                with lock:
                    lats.extend(mine)

            threads = [
                _threading.Thread(target=_client, args=(k,), daemon=True)
                for k in range(n)
            ]
            t_start = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return time.perf_counter() - t_start, lats, streams

        def _serial_step(sid, carry, o):
            a, c = serial_engine.step(carry, o)
            return a, c

        for s_conc in concurrencies:
            batcher = SessionBatcher(
                batched_engine, deadline_ms=deadline_ms,
                adaptive_deadline=True,
            )

            def _batched_step(sid, carry, o, _b=batcher):
                a, c, _step = _b.submit(sid, carry, o).result(
                    timeout=120.0
                )
                return a, c

            # warmup both paths (host-side caches; compiles are done)
            _run_clients(min(s_conc, 2), _serial_step)
            _run_clients(min(s_conc, 2), _batched_step)
            # snapshot counters so mean_epoch reflects only the
            # measured phase (warmup epochs coalesce at width <= 2 and
            # would dilute the reported width)
            warm_requests = batcher.requests_total
            warm_epochs = batcher.epochs_total

            wall_ser, lats_ser, _ = _run_clients(s_conc, _serial_step)
            wall_bat, lats_bat, streams = _run_clients(
                s_conc, _batched_step
            )
            # bit-exact parity: replay every batched stream at batch 1
            parity = True
            for stream in streams:
                carry = batched_inner.initial_carry()
                for o, a in stream:
                    a_ref, carry = batched_inner.step(carry, o)
                    if not np.array_equal(np.asarray(a_ref), a):
                        parity = False
            n_steps = s_conc * steps_per_session
            ser_sps = n_steps / wall_ser
            bat_sps = n_steps / wall_bat
            rows.append({
                "sessions": s_conc,
                "steps_per_session": steps_per_session,
                "serial": {
                    "steps_per_sec": round(ser_sps, 1),
                    "p50_ms": round(_q(lats_ser, 0.5), 3),
                    "p99_ms": round(_q(lats_ser, 0.99), 3),
                },
                "batched": {
                    "steps_per_sec": round(bat_sps, 1),
                    "p50_ms": round(_q(lats_bat, 0.5), 3),
                    "p99_ms": round(_q(lats_bat, 0.99), 3),
                    "mean_epoch": round(
                        (batcher.requests_total - warm_requests)
                        / max(batcher.epochs_total - warm_epochs, 1), 2
                    ),
                },
                "speedup": round(bat_sps / ser_sps, 2),
                "action_parity": parity,
            })
            batcher.close()
        retraces = mon.unexpected_retraces()

    dev = jax.devices()[0]
    return {
        "metric": "serving_sessions_gru16",
        "sim_cost_ms": sim_cost_ms,
        "batch_shapes": list(batched_inner.batch_shapes),
        "deadline_ms": deadline_ms,
        "backend": dev.platform,
        "steady_retraces": {k: v for k, v in (retraces or {}).items()},
        "note": (
            "per-dispatch device time simulated as a GIL-free "
            f"{sim_cost_ms} ms sleep behind a dispatch lock "
            "(SimulatedCostSessionEngine) — the device is one serial "
            "resource, so S serialized batch-1 steps cost S x "
            "sim_cost_ms where one epoch costs ~1 x; TPU rows (real "
            "MXU dispatches, ladder 1,8,64, S=64/256) are the ROADMAP "
            "follow-up"
        ),
        "rows": rows,
    }


# closed-loop client worker for serving_wire_bench: numpy-only (wire.py
# is loaded by file path so the trpo_tpu package — and jax — never
# imports), N client threads, observations pre-generated so the
# measured loops time the PROTOCOL, not np.random. The baseline leg
# speaks the pre-wire client idiom — one JSON POST per fresh
# connection, Connection: close, exactly what every script and test in
# this repo did through PR 15 — while the native leg holds one
# persistent connection streaming binary frames. Two measured phases
# separated by stdio barriers (READY → GO → DONE1 → GO2 → result):
# phase 1 untraced (the throughput row), phase 2 with the router
# tracing at rate 1.0 (the per-stage p99 rows + the parity actions).
_WIRE_WORKER_SRC = r"""
import http.client, importlib.util, json, sys, threading, time
import numpy as np

cfg = json.loads(sys.argv[1])
spec = importlib.util.spec_from_file_location("twire", cfg["wire_path"])
wire = importlib.util.module_from_spec(spec)
spec.loader.exec_module(wire)
W = wire.WIRE_CONTENT_TYPE

def make_obs(seed):
    return np.random.RandomState(seed).randn(
        *cfg["obs_shape"]).astype(np.float32)

def act_keepalive(conn, o):
    frame = wire.encode_frame(None, {"obs": o})
    for attempt in (0, 1):
        try:
            conn[0].request("POST", "/act", body=frame,
                            headers={"Content-Type": W, "Accept": W})
            r = conn[0].getresponse()
            body = r.read()
            assert r.status == 200, (r.status, body[:200])
            return np.asarray(
                wire.decode_frame(body)[1]["action"], np.float64)
        except (ConnectionError, http.client.HTTPException):
            if attempt:
                raise
            conn[0].close()
            conn[0] = http.client.HTTPConnection(
                cfg["netloc"], timeout=30.0)

def act_oneshot(o):
    conn = http.client.HTTPConnection(cfg["netloc"], timeout=30.0)
    try:
        conn.request("POST", "/act",
                     body=json.dumps({"obs": o.tolist()}).encode(),
                     headers={"Content-Type": "application/json",
                              "Connection": "close"})
        r = conn.getresponse()
        body = r.read()
        assert r.status == 200, (r.status, body[:200])
        return np.asarray(json.loads(body)["action"], np.float64)
    finally:
        conn.close()

barrier = threading.Barrier(len(cfg["clients"]) + 1)
lock = threading.Lock()
lats1, lats2, acts, errors = [], [], {}, []

def run(k):
    warm_obs = [make_obs(9000 + 97 * k + i) for i in range(cfg["warm"])]
    obs = [make_obs(5000 + 97 * k + i) for i in range(cfg["acts"])]
    keep = cfg["keepalive"]
    conn = [http.client.HTTPConnection(cfg["netloc"], timeout=30.0)]
    step = (lambda o: act_keepalive(conn, o)) if keep else act_oneshot
    try:
        for o in warm_obs:
            step(o)
        barrier.wait()  # warmup done
        barrier.wait()  # GO: phase 1 (untraced throughput)
        mine1 = []
        for o in obs:
            t0 = time.perf_counter()
            step(o)
            mine1.append((time.perf_counter() - t0) * 1e3)
        barrier.wait()  # phase 1 done
        barrier.wait()  # GO2: phase 2 (traced stages + parity)
        mine2, out = [], []
        for o in obs:
            t0 = time.perf_counter()
            a = step(o)
            mine2.append((time.perf_counter() - t0) * 1e3)
            out.append(a.tolist())
        with lock:
            lats1.extend(mine1)
            lats2.extend(mine2)
            acts[str(k)] = out
    except Exception as e:
        with lock:
            errors.append(repr(e))
        barrier.abort()
    finally:
        conn[0].close()

threads = [threading.Thread(target=run, args=(k,), daemon=True)
           for k in cfg["clients"]]
for t in threads:
    t.start()
try:
    barrier.wait()
    print("READY", flush=True)
    sys.stdin.readline()
    barrier.wait()  # GO
    barrier.wait()  # phase 1 done
    print("DONE1", flush=True)
    sys.stdin.readline()
    barrier.wait()  # GO2
except threading.BrokenBarrierError:
    pass
for t in threads:
    t.join()
print(json.dumps({"errors": errors, "lats1": lats1, "lats2": lats2,
                  "acts": acts}), flush=True)
"""


def serving_wire_bench(
    concurrency: int = 16,
    acts_per_client: int = 25,
    warmup_acts: int = 4,
    n_replicas: int = 2,
    deadline_ms: float = 3.0,
    events_dir=None,
):
    """Native-speed serving data plane (ISSUE 16): JSON/TCP/thread vs
    binary/UDS/asyncio through the SAME router+replica stack, traced at
    rate 1.0 so the win is attributed per stage, not just asserted.

    Both legs run the identical tiny feed-forward engine (cartpole,
    hidden (8,)) behind the production ``MicroBatcher`` — device time is
    a real sub-millisecond dispatch, so the measurement is
    protocol-dominated by construction: what differs between the legs
    is ONLY the wire codec (JSON text vs the length-prefixed binary
    frame on BOTH hops), the router→replica transport (TCP loopback vs
    AF_UNIX), and the router core (thread-per-request vs the asyncio
    loop). S closed-loop clients drive keep-alive connections; every
    request is traced end-to-end (router root → dispatch hop → replica
    queue-wait → engine dispatch), and the per-stage p99s come from the
    same ``analyze`` assembler the ops tooling uses — the ``network``
    stage is the hop minus the remote handler, the ``queue`` stage is
    the batcher's gather wait. The deadline batcher AMPLIFIES protocol
    jitter honestly: spread arrivals miss the rung-fill fast path and
    stall toward the deadline, clustered arrivals fill the rung and
    dispatch early — exactly the production economics the binary plane
    exists to win. Actions must be BIT-EXACT across legs (same seeded
    obs streams, same loaded snapshot). The S=16 row is the ISSUE 16
    acceptance gate: native >= 2x actions/s at equal-or-better p99 with
    stage network AND queue p99 BOTH strictly smaller. With
    ``events_dir`` the four per-leg event logs (router + replicas per
    leg) are left on disk for ``validate_events.py`` — the check.sh
    smoke leg runs the validator over them.
    """
    import shutil as _shutil
    import subprocess as _subprocess
    import tempfile as _tempfile
    import urllib.parse as _urlparse

    from trpo_tpu.agent import TRPOAgent
    from trpo_tpu.config import TRPOConfig
    from trpo_tpu.obs.analyze import _summarize_traces, load_events
    from trpo_tpu.obs.events import EventBus, JsonlSink, manifest_fields
    from trpo_tpu.obs.trace import Tracer
    from trpo_tpu.serve import (
        InProcessReplica,
        MicroBatcher,
        PolicyServer,
        ReplicaSet,
        Router,
    )
    from trpo_tpu.serve import wire as _wire
    from trpo_tpu.utils.metrics import quantile_nearest_rank as _q

    # humanoid-sim: the 376-float observation is the point — the codec
    # has real bytes to win on (an /act body is ~8 KB of JSON text vs
    # ~1.6 KB of raw little-endian f32), while the tiny hidden layer
    # keeps device time sub-millisecond
    agent = TRPOAgent(
        "humanoid-sim",
        TRPOConfig(
            n_envs=4, batch_timesteps=32, policy_hidden=(8,),
            vf_hidden=(8,), seed=0,
            serve_batch_shapes=(1, max(2, concurrency // n_replicas)),
        ),
    )
    state = agent.init_state(seed=0)
    obs_shape = list(agent.obs_shape)

    evdir = events_dir or _tempfile.mkdtemp(prefix="wirebench-")
    os.makedirs(evdir, exist_ok=True)

    def _leg(tag: str, core: str, use_uds: bool, binary: bool):
        """One full stack + client fleet; returns (row, actions)."""
        rlog = os.path.join(evdir, f"{tag}_router.jsonl")
        clog = os.path.join(evdir, f"{tag}_replicas.jsonl")
        rbus = EventBus(JsonlSink(rlog))
        cbus = EventBus(JsonlSink(clog))
        for bus in (rbus, cbus):
            bus.emit(
                "run_manifest",
                **manifest_fields(
                    None, extra={"driver": "bench.serving_wire"}
                ),
            )
        # the router head-samples at rate 0 until warmup is done, so
        # the per-stage p99s cover exactly the measured phase; the
        # replica tracer stays at rate 0 and joins ONLY the router's
        # propagated X-Trace-Sampled verdict (its own head sample
        # would trace warmup hops too)
        rtracer = Tracer(rbus, 0.0, process="router")
        ctracer = Tracer(cbus, 0.0, process="replica")
        # AF_UNIX sockaddr_un caps paths at ~107 bytes — sockets live
        # under a short /tmp dir, never under a deep events dir
        udsdir = (
            _tempfile.mkdtemp(prefix="tw-", dir="/tmp")
            if use_uds else None
        )

        def factory(rid):
            def build():
                engine = agent.serve_engine()
                engine.load(state.policy_params, state.obs_norm, step=1)
                batcher = MicroBatcher(engine, deadline_ms=deadline_ms)
                server = PolicyServer(
                    engine, batcher, port=0, bus=cbus, tracer=ctracer,
                    replica_name=rid,
                    uds_path=(
                        os.path.join(udsdir, f"{rid}.sock")
                        if udsdir else None
                    ),
                )
                return server, [batcher]

            return build

        rs = ReplicaSet(
            lambda rid: InProcessReplica(factory(rid)), n_replicas,
            bus=rbus, health_interval=60.0, backoff=0.05,
            health_fail_threshold=1, max_restarts=2,
        )
        assert rs.wait_healthy(n_replicas, timeout=120.0), rs.snapshot()
        router = Router(rs, port=0, bus=rbus, tracer=rtracer, core=core)
        netloc = _urlparse.urlsplit(router.url).netloc

        # the client fleet runs OUT of process (numpy-only workers —
        # no jax import): in-process client threads would share the
        # server's GIL and the contention, not the protocol, would
        # dominate what the bench measures
        n_workers = max(1, min(4, concurrency))
        procs = []
        try:
            for w in range(n_workers):
                cfg = {
                    "netloc": netloc,
                    "keepalive": binary,
                    "clients": list(range(w, concurrency, n_workers)),
                    "acts": acts_per_client,
                    "warm": warmup_acts,
                    "obs_shape": obs_shape,
                    "wire_path": _wire.__file__,
                }
                procs.append(_subprocess.Popen(
                    [sys.executable, "-c", _WIRE_WORKER_SRC,
                     json.dumps(cfg)],
                    stdin=_subprocess.PIPE, stdout=_subprocess.PIPE,
                    text=True, bufsize=1,
                ))
            for p in procs:
                line = p.stdout.readline().strip()
                assert line == "READY", f"worker failed before GO: {line!r}"
            # phase 1: untraced closed-loop throughput (the headline)
            t_start = time.perf_counter()
            for p in procs:
                p.stdin.write("GO\n")
                p.stdin.flush()
            for p in procs:
                line = p.stdout.readline().strip()
                assert line == "DONE1", f"worker died in phase 1: {line!r}"
            wall = time.perf_counter() - t_start
            # phase 2: same obs streams with the router tracing at
            # rate 1.0 (head-sampling reads the rate per request) —
            # the per-stage p99 rows and the parity actions
            rtracer.sample_rate = 1.0
            t2_start = time.perf_counter()
            for p in procs:
                p.stdin.write("GO2\n")
                p.stdin.flush()
            outs = [json.loads(p.stdout.readline()) for p in procs]
            wall2 = time.perf_counter() - t2_start
            for p in procs:
                p.wait(timeout=30.0)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
            router.close()
            rs.close()
            rtracer.close()
            ctracer.close()
            rbus.close()
            cbus.close()
            if udsdir is not None:
                _shutil.rmtree(udsdir, ignore_errors=True)

        errors = [e for o in outs for e in o["errors"]]
        assert not errors, errors[:3]
        lats = [ms for o in outs for ms in o["lats1"]]
        lats2 = [ms for o in outs for ms in o["lats2"]]
        acts = {
            int(k): v for o in outs for k, v in o["acts"].items()
        }

        summary = _summarize_traces(
            load_events(rlog) + load_events(clog)
        )
        stages = (summary or {}).get("stages", {})
        wire_groups = (summary or {}).get("wire", {})
        n_acts = concurrency * acts_per_client
        row = {
            "leg": tag,
            "core": core,
            "codec": "binary" if binary else "json",
            "transport": "uds" if use_uds else "tcp",
            "connections": "keepalive" if binary else "oneshot",
            "actions_per_sec": round(n_acts / wall, 1),
            "p50_ms": round(_q(lats, 0.5), 3),
            "p99_ms": round(_q(lats, 0.99), 3),
            # the traced phase: same streams, router tracing at 1.0 —
            # slower in absolute terms (span bookkeeping shares the
            # core), quoted separately so the throughput row stays an
            # untraced measurement
            "traced_actions_per_sec": round(n_acts / wall2, 1),
            "traced_p99_ms": round(_q(lats2, 0.99), 3),
            "network_p99_ms": (stages.get("network") or {}).get("p99_ms"),
            "queue_p99_ms": (stages.get("queue") or {}).get("p99_ms"),
            "wire": {
                k: {
                    "hops": v["hops"],
                    "network_p99_ms": v["network_p99_ms"],
                }
                for k, v in wire_groups.items()
            },
            "events": [rlog, clog],
        }
        return row, acts

    base_row, base_acts = _leg("baseline", "thread", False, False)
    native_row, native_acts = _leg("native", "async", True, True)

    parity = sorted(base_acts) == sorted(native_acts) and all(
        np.array_equal(
            np.asarray(base_acts[k]), np.asarray(native_acts[k])
        )
        for k in base_acts
    )
    speedup = round(
        native_row["actions_per_sec"] / base_row["actions_per_sec"], 2
    )
    if events_dir is None:
        _shutil.rmtree(evdir, ignore_errors=True)
        for row in (base_row, native_row):
            row.pop("events")

    dev = jax.devices()[0]
    gates = {
        "speedup_ge_2x": speedup >= 2.0,
        "p99_not_worse": native_row["p99_ms"] <= base_row["p99_ms"],
        "network_p99_smaller": (
            native_row["network_p99_ms"] is not None
            and base_row["network_p99_ms"] is not None
            and native_row["network_p99_ms"] < base_row["network_p99_ms"]
        ),
        "queue_p99_smaller": (
            native_row["queue_p99_ms"] is not None
            and base_row["queue_p99_ms"] is not None
            and native_row["queue_p99_ms"] < base_row["queue_p99_ms"]
        ),
        "action_parity": parity,
    }
    return {
        "metric": "serving_wire_s16",
        "concurrency": concurrency,
        "acts_per_client": acts_per_client,
        "n_replicas": n_replicas,
        "deadline_ms": deadline_ms,
        "backend": dev.platform,
        "note": (
            "same tiny ff engine + MicroBatcher both legs (real "
            "sub-ms dispatches — protocol-dominated by construction); "
            "baseline = the pre-wire plane exactly as clients used it "
            "(one JSON POST per fresh TCP connection through the "
            "thread-per-request router core), native = binary wire "
            "frames on persistent connections over same-host AF_UNIX "
            "through the asyncio core; throughput from the untraced "
            "phase, per-stage p99s from a second rate-1.0-traced "
            "phase via the analyze assembler; actions bit-exact "
            "across legs"
        ),
        "rows": [base_row, native_row],
        "speedup": speedup,
        "action_parity": parity,
        "gates": gates,
    }


_FLEET_DEFAULTS = {
    # family -> (batch_timesteps, N ladder, K iterations per timed rep).
    # The batch holds T·N constant across the family's ladder (each N
    # divides it), so every rung does the SAME total env-step and update
    # work per iteration — the ladder isolates scan-depth-vs-vmap-width.
    "cartpole": (8192, (128, 1024, 4096), 30),
    "halfcheetah-sim": (5120, (128, 512, 1024), 20),
    "humanoid-sim": (50176, (128, 512, 1024), 3),
}


def env_fleet_bench(device=None, reps: int = 2):
    """Env fleet scale-out (ISSUE 10): env-steps/s across a wide-N ladder
    of the device-env families, plus rollout-program memory vs chunk size.

    Each rung reports TWO rates. ``env_steps_per_sec`` times K full fused
    iterations (``TRPOAgent.run_iterations`` — rollout → GAE → critic fit
    → update as ONE program) at the family's fixed batch budget with the
    fleet widened 128 → 1024/4096; T·N is held constant, so the curve is
    pure scan-depth→vmap-width trade. ``rollout_steps_per_sec`` times the
    rollout PROGRAM alone — the substrate the fleet actually scales. The
    distinction matters per backend: on a 2-core CPU the 50k-batch
    natural-gradient update dominates the iteration and is width-
    invariant, so the full-iteration curve is nearly flat there while the
    rollout substrate shows the real headroom; on the TPU the update is
    MXU-bound and the N=128 rollout leaves the VPU mostly idle, so the
    fleet win reaches the end-to-end number. Timing per the tunneled-TPU
    rules (min over reps, small-leaf sync, RTT subtracted).

    ``vs_n128`` reports each family's widest-rung full-iteration
    env-steps/s over its N=128 rung, and ``rollout_vs_n128_row`` the
    widest rung's ROLLOUT rate over the N=128 FULL-ITERATION row — the
    latter is the BENCH_LADDER acceptance number (≥3× on humanoid-sim on
    this CPU box): it bounds what the fleet substrate sustains once the
    update stops being the bottleneck, which is precisely the TPU
    situation. The check.sh fleet smoke asserts the same shape cheaply
    on cartpole.

    TPU re-run protocol (the ≥10× claim): the order-of-magnitude
    env-steps/s jump over the 3.44M/s N=128 humanoid-sim row
    (BENCH_LADDER r04) is reserved for hardware — on the TPU the N=128
    rollout leaves the VPU lanes mostly idle (128-wide env math against
    8×128 lanes) while the update is already MXU-saturated, so widening
    the fleet multiplies rollout throughput until the update dominates.
    Re-run THIS block there (``python bench.py`` with the TPU attached,
    or ``BENCH_FLEET_FAMILIES=humanoid-sim``) and quote the measured
    rows in BENCH_LADDER before claiming the 10×.

    The ``chunk_memory`` study compiles the narrow rung's rollout two
    ways — the flat ``(T, N)`` program and ``rollout.ChunkedRollout``'s
    per-chunk program at two chunk sizes — and quotes
    ``program_memory_analysis`` for each: the chunk program's bytes grow
    with chunk, not with T (the live rollout buffer is ``(chunk, N,
    ...)``), which is the memory headroom that lets T·N scale past what
    one flat rollout buffer allows.

    Env knobs: ``BENCH_ENV_FLEET=0`` skips the block;
    ``BENCH_FLEET_FAMILIES``/``BENCH_FLEET_NS``/``BENCH_FLEET_BATCH``/
    ``BENCH_FLEET_K`` override the ladder (smoke runs);
    ``BENCH_MEMORY=0`` skips both memory studies.
    """
    from trpo_tpu.agent import TRPOAgent
    from trpo_tpu.config import get_preset

    families = [
        f.strip()
        for f in os.environ.get(
            "BENCH_FLEET_FAMILIES", ",".join(_FLEET_DEFAULTS)
        ).split(",")
        if f.strip()
    ]
    ns_env = os.environ.get("BENCH_FLEET_NS")
    k_env = os.environ.get("BENCH_FLEET_K")
    batch_env = os.environ.get("BENCH_FLEET_BATCH")
    want_memory = os.environ.get("BENCH_MEMORY", "1") != "0"

    ctx = (
        contextlib.nullcontext()
        if device is None
        else jax.default_device(device)
    )
    # resolve each family's (batch, ladder, K) with the env overrides
    # applied ONCE, before any work — an unknown family must fail with
    # the supported list, not a bare KeyError after minutes of rungs
    resolved = {}
    for family in families:
        if family not in _FLEET_DEFAULTS:
            raise ValueError(
                f"unknown env_fleet family {family!r} "
                f"(BENCH_FLEET_FAMILIES); supported: "
                f"{sorted(_FLEET_DEFAULTS)}"
            )
        batch, ladder, k = _FLEET_DEFAULTS[family]
        if ns_env:
            ladder = tuple(int(n) for n in ns_env.split(",") if n.strip())
        if batch_env:
            batch = int(batch_env)
        if k_env:
            k = int(k_env)
        resolved[family] = (batch, ladder, k)
    rows = []
    with ctx:
        for family in families:
            batch, ladder, k = resolved[family]
            for n_envs in ladder:
                _progress(f"env fleet: {family} N={n_envs}")
                cfg = get_preset(family).replace(
                    batch_timesteps=batch, fleet_n_envs=n_envs,
                )
                agent = TRPOAgent(cfg.env, cfg)
                agent._capture_program_args = True
                steps_per_iter = agent.n_steps * agent.n_envs

                state = agent.init_state(seed=0)
                t0 = time.perf_counter()
                _, stats = agent.run_iterations(state, k)  # compile+warm
                np.asarray(stats["entropy"])
                compile_s = time.perf_counter() - t0
                rtt = _device_rtt()
                best = float("inf")
                for _ in range(reps):
                    # run_iterations DONATES its state — rebuild the
                    # identical seed-0 state outside the timed window
                    state = agent.init_state(seed=0)
                    t0 = time.perf_counter()
                    _, stats = agent.run_iterations(state, k)
                    np.asarray(stats["entropy"])  # small sync probe
                    best = min(best, time.perf_counter() - t0)
                ent = np.asarray(stats["entropy"], np.float64)
                assert np.all(np.isfinite(ent)), (
                    f"{family} N={n_envs}: non-finite entropy"
                )
                per_iter = max(best - rtt, 1e-9) / k

                # rollout PROGRAM rate (the substrate the fleet scales):
                # the same device_rollout the fused iteration traces,
                # jitted alone
                from trpo_tpu.rollout import device_rollout, init_carry

                roll = jax.jit(
                    lambda p, c, kk, _a=agent: device_rollout(
                        _a.env, _a.policy, p, c, kk, _a.n_steps
                    )
                )
                params = agent.init_state(seed=1).policy_params
                carry = init_carry(
                    agent.env, jax.random.key(0), n_envs,
                    policy=agent.policy,
                )
                carry, traj = roll(params, carry, jax.random.key(1))
                jax.block_until_ready(traj.rewards)  # compile + warm
                roll_best = float("inf")
                for rep in range(reps + 1):
                    t0 = time.perf_counter()
                    carry, traj = roll(
                        params, carry, jax.random.key(2 + rep)
                    )
                    jax.block_until_ready(traj.rewards)
                    roll_best = min(
                        roll_best, time.perf_counter() - t0
                    )
                roll_s = max(roll_best - rtt, 1e-9)

                peak_mib = None
                if want_memory and agent._program_args:
                    from trpo_tpu.obs.memory import (
                        program_memory_analysis,
                    )

                    fields = program_memory_analysis(
                        *agent._program_args[f"device_iterations[{k}]"]
                    )
                    if fields:
                        peak_mib = round(
                            fields["peak_estimate_bytes"] / 2**20, 1
                        )
                rows.append({
                    "family": family,
                    "n_envs": n_envs,
                    "n_steps": agent.n_steps,
                    "batch": steps_per_iter,
                    "iter_ms": round(per_iter * 1e3, 3),
                    "env_steps_per_sec": round(steps_per_iter / per_iter),
                    "rollout_ms": round(roll_s * 1e3, 3),
                    "rollout_steps_per_sec": round(
                        steps_per_iter / roll_s
                    ),
                    "compile_s": round(compile_s, 2),
                    "peak_mem_mib": peak_mib,
                })

        chunk_memory = None
        if want_memory and rows:
            # at the first family's OVERRIDE-resolved scale, so smoke
            # runs (BENCH_FLEET_BATCH/NS) stay inside their budget
            f0 = families[0]
            chunk_memory = _fleet_chunk_memory(
                f0, batch=resolved[f0][0], n_envs=resolved[f0][1][0]
            )

    vs_n128 = {}
    rollout_vs_n128_row = {}
    for family in families:
        fam = [r for r in rows if r["family"] == family]
        narrow = next((r for r in fam if r["n_envs"] == 128), None)
        if narrow and len(fam) > 1:
            widest = max(fam, key=lambda r: r["n_envs"])
            if widest["n_envs"] > narrow["n_envs"]:
                vs_n128[family] = round(
                    widest["env_steps_per_sec"]
                    / narrow["env_steps_per_sec"], 2
                )
                # the acceptance ratio: widest-rung ROLLOUT substrate
                # rate over the N=128 full-iteration row (docstring)
                rollout_vs_n128_row[family] = round(
                    widest["rollout_steps_per_sec"]
                    / narrow["env_steps_per_sec"], 2
                )
    return {
        "note": (
            "T*N held constant per family; min-over-reps RTT-corrected "
            "timing. env_steps_per_sec = full fused iteration; "
            "rollout_steps_per_sec = the rollout program alone (the "
            "substrate the fleet scales — on this CPU the width-"
            "invariant 50k-batch update dominates the iteration, so the "
            "fleet win shows there). rollout_vs_n128_row = widest-rung "
            "rollout rate / N=128 full-iteration rate (the acceptance "
            "gate); the >=10x END-TO-END claim vs the N=128 humanoid-sim "
            "row is RESERVED for the TPU re-run protocol in this "
            "block's docstring"
        ),
        "backend": jax.devices()[0].platform if device is None
        else device.platform,
        "rows": rows,
        "vs_n128": vs_n128,
        "rollout_vs_n128_row": rollout_vs_n128_row,
        "chunk_memory": chunk_memory,
    }


def _fleet_chunk_memory(family: str, batch: int, n_envs: int):
    """Compiled-memory comparison for the ``env_fleet`` block: the narrow
    rung's flat ``(T, N)`` rollout program vs the ``ChunkedRollout``
    chunk program at two chunk sizes — ``program_memory_analysis`` fields
    each, so BENCH_LADDER can quote that chunk-program memory grows with
    chunk, not with T. ``batch``/``n_envs`` arrive override-resolved
    from :func:`env_fleet_bench` (smoke scale stays smoke-sized)."""
    from trpo_tpu.agent import TRPOAgent
    from trpo_tpu.config import get_preset
    from trpo_tpu.obs.memory import abstract_args, program_memory_analysis
    from trpo_tpu.rollout import ChunkedRollout, device_rollout, init_carry

    cfg = get_preset(family).replace(
        batch_timesteps=batch, fleet_n_envs=n_envs,
    )
    agent = TRPOAgent(cfg.env, cfg)
    T = agent.n_steps
    params = agent.init_state(seed=0).policy_params
    carry = init_carry(agent.env, jax.random.key(0), n_envs,
                       policy=agent.policy)
    key = jax.random.key(1)

    flat = jax.jit(
        lambda p, c, k: device_rollout(
            agent.env, agent.policy, p, c, k, T
        ),
        donate_argnums=1,
    )
    out = {
        "family": family,
        "n_envs": n_envs,
        "n_steps": T,
        "flat": program_memory_analysis(
            flat, abstract_args((params, carry, key))
        ),
        "chunks": {},
    }
    chunks = [c for c in (max(1, T // 8), max(1, T // 2)) if c < T]
    for c in dict.fromkeys(chunks):  # dedupe, keep order
        cr = ChunkedRollout(agent.env, agent.policy, c)
        keys = jax.random.split(key, c)
        out["chunks"][str(c)] = program_memory_analysis(
            cr._fn, abstract_args((params, carry, keys))
        )
    return out


def _spread_pct(runs):
    if runs and len(runs) > 1 and min(runs) > 0:
        return (max(runs) - min(runs)) / min(runs) * 100
    return None


def _phase_contended(runs, load=None):
    """The contention test applied WHILE the bench runs (VERDICT r5 item
    3): wide spread across the phase's timed runs, or a hot host loadavg.
    ``load`` must be a sample taken BEFORE the phase ran — sampling here
    would read the bench's own just-finished compute as contention and
    fire retries on idle machines."""
    sp = _spread_pct(runs)
    return (sp is not None and sp > 10.0) or (
        load is not None and load > 1.8
    )


def _retry_phase_if_contended(label, first, rerun, load=None):
    """Self-defending timing (VERDICT r5 item 3: the r05 driver artifact
    shipped with 14.4% spread and needed local sidecars to interpret).
    When a phase's first attempt looks contended, re-run it ONCE and
    record both attempts: the retry becomes the published run list, the
    first attempt is preserved in ``runs_first_attempt``, and the value
    is the min over both (the min-estimator's sample set just grew).

    Returns ``(ms, x, runs, retried, runs_first_attempt)``.
    """
    ms, x, runs = first
    if not _phase_contended(runs, load):
        return ms, x, runs, False, None
    sp = _spread_pct(runs)
    _progress(
        f"{label}: contention suspected during timing (spread "
        f"{'n/a' if sp is None else f'{sp:.1f}%'}) — re-running the "
        "phase once"
    )
    try:
        ms2, x2, runs2 = rerun()
    except Exception as e:
        # the retry itself failed: the contended first attempt stands,
        # but the artifact must still SAY a retry was attempted —
        # runs_first_attempt == runs marks this case (schema_notes)
        _progress(
            f"{label}: retry failed ({type(e).__name__}: {e}) — keeping "
            "the (contended) first attempt, flagged as retried"
        )
        return ms, x, runs, True, runs
    return min(ms, ms2), x2, runs2, True, runs


def main():
    global _ACCEL
    # Fused path at the TPU operating point (bf16 matmuls, fp32 solve);
    # baseline at reference semantics (fp32 throughout). Params/g share
    # keys, so both solve the same system up to matmul precision — the
    # solution-cosine assert cross-checks them.
    problem = build_problem(
        jnp.bfloat16 if _ACCEL else jnp.float32
    )
    load_before = os.getloadavg()[0] if hasattr(os, "getloadavg") else None
    try:
        ours_ms, x_ours, ours_runs = time_fused_solve(problem)
    except Exception as e:  # tunnel flake mid-compile/run — retry once
        _progress(f"accelerator attempt failed ({type(e).__name__}: {e}); "
                  "retrying once")
        try:
            ours_ms, x_ours, ours_runs = time_fused_solve(problem)
        except Exception as e2:
            if not _ACCEL:
                raise  # already on CPU; a failure here is a real bug
            _progress(f"retry failed ({type(e2).__name__}); falling back to "
                      "CPU for the fused path")
            # backends are already initialized, so a config-level platform
            # switch is a no-op — pin the CPU device explicitly, and rebuild
            # the problem there (apply_fn closes over accelerator-resident
            # obs)
            _ACCEL = False
            cpu = jax.devices("cpu")[0]
            with jax.default_device(cpu):
                problem = build_problem()
            ours_ms, x_ours, ours_runs = time_fused_solve(
                problem, device=cpu
            )
    # self-defending timing (VERDICT r5 item 3): a contended first
    # attempt is re-run once, both attempts recorded
    xla_solve_rerun = lambda: time_fused_solve(
        problem, device=None if _ACCEL else jax.devices("cpu")[0]
    )
    ours_ms, x_ours, ours_runs, xla_retried, xla_runs_first = (
        _retry_phase_if_contended(
            "fused solve", (ours_ms, x_ours, ours_runs), xla_solve_rerun,
            load=load_before,
        )
    )
    # Fused single-Pallas-kernel solve — the framework's DEFAULT operator
    # on TPU (cfg.fvp_mode="auto" resolves to it at this shape). Becomes
    # the headline if it runs and matches the baseline solution; the XLA
    # chain above is kept as the comparison row either way.
    pallas_ms = pallas_runs = x_pallas = None
    pallas_retried, pallas_runs_first = False, None
    if _ACCEL:
        try:
            _progress("pallas fused-kernel solve")
            pallas_ms, x_pallas, pallas_runs = time_fused_solve(
                problem, fvp_factory=_pallas_fvp_factory(problem)
            )
            (
                pallas_ms, x_pallas, pallas_runs,
                pallas_retried, pallas_runs_first,
            ) = _retry_phase_if_contended(
                "pallas solve",
                (pallas_ms, x_pallas, pallas_runs),
                lambda: time_fused_solve(
                    problem, fvp_factory=_pallas_fvp_factory(problem)
                ),
                load=load_before,
            )
        except Exception as e:
            _progress(
                f"pallas fused-kernel solve failed ({type(e).__name__}: "
                f"{e}) — headline stays on the XLA chain"
            )
            pallas_ms = None
    # sample host load IMMEDIATELY after the headline timing window — the
    # later bench phases (CPU baseline, flop-accounting compiles, width
    # study) generate minutes of self-induced load that would contaminate
    # the contention verdict about THIS measurement
    load_after = os.getloadavg()[0] if hasattr(os, "getloadavg") else None
    # FLOP accounting on the same problem (loop-free lowered programs;
    # compile-only, nothing executed — see flop_accounting docstring).
    # After a TPU fallback, pin the lowering to CPU: compiling against a
    # wedged tunnel hangs rather than raising, so the try/except alone
    # would not protect this path. When the backend's cost analysis
    # reports nothing (probed cheaply first — the round-2 driver run spent
    # ~156 s lowering 50k-batch programs for nothing), fall back to the
    # analytic FLOP model so MFU is never null, tagged with its source
    # (VERDICT r2 item 1).
    acct_ctx = (
        contextlib.nullcontext()
        if _ACCEL
        else jax.default_device(jax.devices("cpu")[0])
    )
    acct, flops_source = {}, None
    try:
        with acct_ctx:
            # BENCH_FORCE_ANALYTIC exercises the fallback path on backends
            # where cost analysis works (tests; cross-checking the model)
            if (
                os.environ.get("BENCH_FORCE_ANALYTIC") != "1"
                and _cost_analysis_usable()
            ):
                _progress("flop accounting: lowering single-kernel programs")
                acct = flop_accounting(problem)
            else:
                _progress(
                    "flop accounting: backend reports no cost analysis — "
                    "using the analytic FLOP model"
                )
    except Exception as e:
        _progress(f"flop accounting failed ({type(e).__name__}: {e})")
        acct = {}
    # a COMPLETE measured accounting (per-iter and per-update both
    # positive) wins; anything partial or degenerate falls back to the
    # analytic model wholesale (mixing sources inside one composition
    # would mislabel the result), keeping only the measured bytes field —
    # traffic has no analytic model
    if acct.get("flops_per_cg_iter") and acct.get("flops_per_update"):
        flops_source = "xla_cost_analysis"
    else:
        measured_bytes = acct.get("bytes_per_cg_iter")
        acct = _analytic_acct()
        if measured_bytes:
            acct["bytes_per_cg_iter"] = measured_bytes
        flops_source = "analytic"
    # Fusion ablation (accelerator only): same device FVP, host CG loop.
    standalone_fvp_ms = None
    host_cg_raw_ms = host_cg_ms = None
    if _ACCEL:
        try:
            standalone_fvp_ms = time_standalone_fvp(problem)
        except Exception as e:
            _progress(
                f"standalone-FVP timing failed ({type(e).__name__}: {e})"
            )
        try:
            host_cg_raw_ms, host_cg_ms, x_hd = time_host_driven_cg(
                problem
            )
            # the ablation rows only mean something if they solved the
            # same system — same guard as the baseline's cosine check
            cos_hd = float(
                np.dot(np.asarray(x_ours), x_hd)
                / (np.linalg.norm(np.asarray(x_ours)) * np.linalg.norm(x_hd))
            )
            if not cos_hd > 0.99:
                _progress(
                    f"host-driven ablation solution mismatch (cosine "
                    f"{cos_hd:.4f}) — dropping the ablation rows"
                )
                host_cg_raw_ms = host_cg_ms = None
        except Exception as e:
            _progress(f"host-driven ablation failed ({type(e).__name__}: {e})")
    upd_dev = None if _ACCEL else jax.devices("cpu")[0]
    update_runs = None
    try:
        updates_per_sec, update_ms, update_runs = time_full_update(
            device=upd_dev
        )
    except Exception as e:  # secondary metric must not sink the headline
        _progress(f"full-update timing failed ({type(e).__name__}: {e})")
        updates_per_sec = update_ms = None
    # solver precision ladder harvest (ISSUE 8): f32 vs bf16 vs
    # subsampled vs full-ladder full update, each with a measured
    # solution-cosine tag; BENCH_SOLVE_PRECISION=0 skips
    precision = None
    if update_ms is not None and os.environ.get(
        "BENCH_SOLVE_PRECISION", "1"
    ) != "0":
        try:
            _progress("solve precision ladder")
            precision = solve_precision(
                device=upd_dev, f32_row=(update_ms, update_runs)
            )
        except Exception as e:
            _progress(
                f"solve-precision ladder failed ({type(e).__name__}: {e})"
            )
    # phase-level attribution of the full update (round-6 tentpole);
    # BENCH_TAIL=0 skips (smoke runs that only need the solve headline)
    tail_breakdown = None
    if update_ms is not None and os.environ.get("BENCH_TAIL", "1") != "0":
        try:
            _progress("update-tail breakdown")
            ladder_row = None
            if precision:
                ladder_row = next(
                    (
                        r for r in precision["rows"]
                        if r["variant"] == "ladder"
                    ),
                    None,
                )
            tail_breakdown = update_tail_breakdown(
                full_update_ms=update_ms, device=upd_dev,
                ladder_row=ladder_row,
            )
        except Exception as e:
            _progress(
                f"update-tail breakdown failed ({type(e).__name__}: {e})"
            )
    # Per-headline-program compiled memory accounting (ISSUE 5 satellite):
    # args/temp/output/peak bytes next to every time column. One extra
    # compile per program, nothing executed; BENCH_MEMORY=0 skips.
    program_memory = None
    if os.environ.get("BENCH_MEMORY", "1") != "0":
        try:
            _progress("program memory accounting (compiled memory_analysis)")
            program_memory = bench_program_memory(
                problem,
                device=None if _ACCEL else jax.devices("cpu")[0],
                fvp_factory=_pallas_fvp_factory(problem)
                if pallas_ms is not None
                else None,
            ) or None
        except Exception as e:
            _progress(
                f"program memory accounting failed "
                f"({type(e).__name__}: {e})"
            )
    # Framework operating point: curvature on every 1/FVP_SUB-th sample
    # (TRPOConfig.fvp_subsample) — skipped on the slow CPU fallback, and
    # skipped if the full-batch timing already failed (same problem shape).
    updates_per_sec_sub = None
    if _ACCEL and updates_per_sec is not None:
        try:
            updates_per_sec_sub, _, _ = time_full_update(
                device=upd_dev, fvp_subsample=FVP_SUB
            )
        except Exception as e:
            _progress(
                f"subsampled-update timing failed ({type(e).__name__}: {e})"
            )
    # Baseline at reference semantics: fp32 throughout. Off-accelerator the
    # fused problem already IS fp32 — reuse it (a second 50k-batch build
    # would be pure duplicate work); on-accelerator build the fp32 copy on
    # the CPU backend, where the baseline runs.
    if _ACCEL:
        with jax.default_device(jax.devices("cpu")[0]):
            problem32 = build_problem()
    else:
        problem32 = problem
    base_ms, x_base = time_reference_semantics(problem32)

    # Transport-free ablations (VERDICT r2 item 5) — every ratio below
    # compares programs on the SAME in-process CPU backend, so no ~100 ms
    # tunnel RTT contaminates either side (unlike the accelerator
    # host-driven row, whose corrected value subtracts ~RTT from ~RTT):
    #   fusion_speedup = host-driven CG with the SAME GGN FVP ÷ fused GGN
    #                    solve — pure loop fusion, matched factorization;
    #   solver_speedup_vs_reference_cpu = reference-semantics baseline
    #                    (host CG, jvp∘grad FVP) ÷ fused GGN solve — the
    #                    overall our-solver-vs-reference win per backend
    #                    (bundles fusion + the GGN factorization);
    #   chip_speedup_fused_vs_cpu = fused CPU ÷ fused accelerator — the
    #                    same program across backends.
    if _ACCEL:
        try:
            cpu = jax.devices("cpu")[0]
            fused_cpu_ms, _x_cpu, _runs = time_fused_solve(
                problem32, device=cpu
            )
        except Exception as e:
            _progress(f"CPU fused solve failed ({type(e).__name__}: {e})")
            fused_cpu_ms = None
    else:
        fused_cpu_ms = ours_ms  # already the same backend
    try:
        host_ggn_cpu_ms, _x_hg = time_host_driven_cpu_ggn(problem32)
    except Exception as e:
        _progress(
            f"host-driven CPU GGN loop failed ({type(e).__name__}: {e})"
        )
        host_ggn_cpu_ms = None

    # MFU-vs-width scaling study (VERDICT r2 item 2) — accelerator only
    # by default; BENCH_WIDTHS overrides (e.g. "8,16" for CPU smoke runs,
    # "" to skip).
    widths_env = os.environ.get("BENCH_WIDTHS")
    if widths_env is not None:
        widths = [int(w) for w in widths_env.split(",") if w.strip()]
    else:
        widths = [512, 1024] if _ACCEL else []
    # off-accelerator (incl. after a tunnel fallback) pin everything to
    # CPU — the default backend may be a wedged tunnel that hangs
    width_dev = None if _ACCEL else jax.devices("cpu")[0]
    width_rows = width_study(widths, device=width_dev) if widths else []

    # End-to-end host-env driver metric (serial vs async-pipelined learn
    # on a sleep-bound simulator) — BENCH_HOST_PIPELINE=0 skips.
    host_pipe = None
    if os.environ.get("BENCH_HOST_PIPELINE", "1") != "0":
        try:
            _progress("host-env pipeline bench (sleep-bound sim)")
            host_pipe = host_pipeline_bench()
        except Exception as e:
            _progress(
                f"host-env pipeline bench failed "
                f"({type(e).__name__}: {e})"
            )

    # Serving SLOs (ISSUE 6): p50/p99 + actions/s per AOT batch rung,
    # closed- and open-loop — BENCH_SERVING=0 skips.
    serving = None
    if os.environ.get("BENCH_SERVING", "1") != "0":
        try:
            _progress("serving SLO bench (AOT act ladder, micro-batcher)")
            serving = serving_bench()
        except Exception as e:
            _progress(f"serving bench failed ({type(e).__name__}: {e})")

    # Replica-scaling SLOs (ISSUE 9): closed-loop actions/s + p50/p99
    # through the router at 1/2/4 replicas, scaling efficiency vs the
    # single-replica row — BENCH_SERVING_SCALE=0 skips (follows
    # BENCH_SERVING: no data plane, no control plane to scale).
    serving_scale = None
    if (
        os.environ.get("BENCH_SERVING", "1") != "0"
        and os.environ.get("BENCH_SERVING_SCALE", "1") != "0"
    ):
        try:
            _progress(
                "serving scale bench (router over 1/2/4 replicas)"
            )
            serving_scale = serving_scale_bench()
        except Exception as e:
            _progress(
                f"serving scale bench failed ({type(e).__name__}: {e})"
            )

    # Continuous-batching SLOs for recurrent serving (ISSUE 13):
    # session-steps/s + p50/p99 over a concurrency ladder, serialized
    # batch-1 vs the gather/scatter epoch plane —
    # BENCH_SERVING_SESSIONS=0 skips (follows BENCH_SERVING).
    serving_sessions = None
    if (
        os.environ.get("BENCH_SERVING", "1") != "0"
        and os.environ.get("BENCH_SERVING_SESSIONS", "1") != "0"
    ):
        try:
            _progress(
                "serving sessions bench (batched epochs vs serialized "
                "batch-1)"
            )
            serving_sessions = serving_sessions_bench()
        except Exception as e:
            _progress(
                f"serving sessions bench failed ({type(e).__name__}: {e})"
            )

    # Native-speed data plane (ISSUE 16): JSON/TCP/thread vs
    # binary/UDS/asyncio through the same stack, bit-exact, with
    # per-stage p99 attribution from rate-1.0 traces —
    # BENCH_SERVING_WIRE=0 skips (follows BENCH_SERVING).
    serving_wire = None
    if (
        os.environ.get("BENCH_SERVING", "1") != "0"
        and os.environ.get("BENCH_SERVING_WIRE", "1") != "0"
    ):
        try:
            _progress(
                "serving wire bench (binary/UDS/async vs "
                "JSON/TCP/thread)"
            )
            serving_wire = serving_wire_bench()
        except Exception as e:
            _progress(
                f"serving wire bench failed ({type(e).__name__}: {e})"
            )

    # Pipelined actor/learner training loop (ISSUE 17): sync vs
    # overlapped env-steps/s at a calibrated update cost over 2-3 fleet
    # widths + per-stage p99s from a rate-1.0 traced run of the real
    # pipeline — BENCH_TRAINING_OVERLAP=0 skips (BENCH_OVERLAP_WIDTHS /
    # BENCH_OVERLAP_ITERS / BENCH_OVERLAP_T scale it for smoke runs).
    training_overlap = None
    if os.environ.get("BENCH_TRAINING_OVERLAP", "1") != "0":
        try:
            _progress(
                "training overlap bench (sync vs overlapped drivers)"
            )
            training_overlap = training_overlap_bench()
        except Exception as e:
            _progress(
                f"training overlap bench failed "
                f"({type(e).__name__}: {e})"
            )

    # Env fleet scale-out (ISSUE 10): env-steps/s across the wide-N
    # ladder of the device-env families + rollout-memory-vs-chunk study
    # — BENCH_ENV_FLEET=0 skips (the families/Ns/K scale via
    # BENCH_FLEET_* for smoke runs; see env_fleet_bench docstring for
    # the TPU re-run protocol behind the >=10x claim).
    env_fleet = None
    if os.environ.get("BENCH_ENV_FLEET", "1") != "0":
        try:
            _progress("env fleet scale-out bench (wide-N ladder)")
            env_fleet = env_fleet_bench(
                device=None if _ACCEL else jax.devices("cpu")[0]
            )
        except Exception as e:
            _progress(f"env fleet bench failed ({type(e).__name__}: {e})")

    # Both solvers must agree — a fast wrong solve is worthless.
    cos = float(
        np.dot(np.asarray(x_ours), x_base)
        / (np.linalg.norm(np.asarray(x_ours)) * np.linalg.norm(x_base))
    )
    assert cos > 0.99, f"solver mismatch: cosine {cos}"

    # Headline selection: the Pallas fused kernel is the default solve on
    # TPU, so it carries the headline — but ONLY if its solution matches
    # the reference-semantics baseline (same gate as the XLA path above).
    solve_path, xla_ms, xla_runs = "xla_ggn", ours_ms, ours_runs
    retried, runs_first = xla_retried, xla_runs_first
    if pallas_ms is not None:
        cos_p = float(
            np.dot(np.asarray(x_pallas), x_base)
            / (np.linalg.norm(np.asarray(x_pallas)) * np.linalg.norm(x_base))
        )
        if cos_p > 0.99:
            solve_path = "pallas_fused"
            ours_ms, ours_runs, x_ours, cos = (
                pallas_ms, pallas_runs, x_pallas, cos_p,
            )
            retried, runs_first = pallas_retried, pallas_runs_first
        else:
            _progress(
                f"pallas solve solution mismatch (cosine {cos_p:.4f}) — "
                "headline stays on the XLA chain"
            )
            # a timing for a WRONG solution must not publish: null the
            # pallas fields so the JSON never reports a speedup for a
            # solve that failed validation (ADVICE r5)
            pallas_ms = pallas_runs = x_pallas = None

    dev = list(x_ours.devices())[0]
    peak, hbm_gbps = _peak_tflops(dev)
    tflops_solve = tflops_update = None
    if acct.get("flops_per_cg_iter"):
        tflops_solve = acct["flops_per_cg_iter"] / (ours_ms * 1e-3) / 1e12
    if acct.get("flops_per_update") and update_ms:
        tflops_update = acct["flops_per_update"] / (update_ms * 1e-3) / 1e12
    # Roofline: which bound applies at this arithmetic intensity, and how
    # close the solve runs to it (MFU alone understates a bandwidth-bound
    # kernel; this says what the SHAPE allows on this chip). Caveat baked
    # into the field names: cost-analysis "bytes accessed" counts per-op
    # operand/result bytes, i.e. UNFUSED traffic — real HBM traffic after
    # fusion is lower, so the intensity is a lower bound and the derived
    # ceiling an under-estimate; a fraction > 1 means the fused kernel
    # beats the unfused-traffic bound, not that physics broke.
    intensity = roofline_tflops = roofline_frac = None
    if acct.get("bytes_per_cg_iter") and acct.get("flops_per_cg_iter"):
        intensity = acct["flops_per_cg_iter"] / acct["bytes_per_cg_iter"]
        if peak is not None and hbm_gbps is not None:
            roofline_tflops = min(peak, intensity * hbm_gbps / 1e3)
            if tflops_solve is not None:
                roofline_frac = tflops_solve / roofline_tflops

    def _r(v, nd=4):
        return None if v is None else round(v, nd)

    # -- variance honesty (VERDICT r3 item 1): the headline value is the
    #    min over TIMING_REPS independent runs of the timed program; the
    #    full per-run list and spread are published so a reader sees the
    #    band, not just the flattering end. The 1-core host runs loadavg
    #    near 1.0 when idle-but-for-us; sustained load well above that
    #    right after the timing window (load_after — sampled THERE, not
    #    here), or a wide spread, means another process competed for the
    #    host or the single-tenant chip during timing — flagged, never
    #    hidden.
    spread_pct = _spread_pct(ours_runs)
    # same thresholds as _phase_contended (the retry trigger), but on the
    # loadavg SAMPLED RIGHT AFTER the headline window (load_after) rather
    # than a fresh sample — by now the bench's own later phases have
    # loaded the host, which must not contaminate this verdict
    contention = bool(
        (spread_pct is not None and spread_pct > 10.0)
        or (load_after is not None and load_after > 1.8)
    )
    if contention:
        spread_str = (
            "n/a" if spread_pct is None else f"{spread_pct:.1f}%"
        )
        _progress(
            f"WARNING: contention suspected (spread {spread_str}, "
            f"loadavg {load_after}) — treat the headline as an upper bound"
        )

    def _mfu(achieved):
        if peak is None or achieved is None:
            return None
        return round(achieved / peak, 4)

    artifact = {
                # label tracks the actual batch (BENCH_BATCH smoke runs
                # must not masquerade as the full-size benchmark)
                "metric": (
                    "cg_solve_ms_per_iter_humanoid_shape_batch"
                    + (
                        f"{BATCH // 1000}k"
                        if BATCH % 1000 == 0
                        else str(BATCH)
                    )
                ),
                "value": round(ours_ms, 4),
                "unit": "ms/iter",
                # which operator carried the headline: "pallas_fused" =
                # the single-kernel Pallas GGN operator (ops/fused_fvp.py,
                # the TPU default via cfg.fvp_mode="auto");  "xla_ggn" =
                # the XLA-lowered GGN chain (the general path, and the
                # r01-r04 artifact lineage)
                "solve_path": solve_path,
                "xla_ggn_ms_per_iter": round(xla_ms, 4),
                "xla_ggn_runs_ms_per_iter": [round(r, 4) for r in xla_runs],
                "pallas_kernel_speedup_vs_xla": None
                if pallas_ms is None
                else round(xla_ms / pallas_ms, 3),
                # -- variance honesty (VERDICT r3 item 1): value = min over
                #    n_runs independent timed programs; the run list shows
                #    the band. contention_suspected flags wide spread or
                #    high host load during timing --
                "n_runs": len(ours_runs),
                "runs_ms_per_iter": [round(r, 4) for r in ours_runs],
                "spread_pct": _r(spread_pct, 1),
                "loadavg_before": _r(load_before, 2),
                "loadavg_after": _r(load_after, 2),
                "contention_suspected": contention,
                # -- self-defending retry (VERDICT r5 item 3): when the
                #    headline phase's first attempt looked contended it
                #    was re-run once — runs_ms_per_iter is then the
                #    retry, the first attempt is preserved here, and
                #    value is the min over both attempts --
                "retried": retried,
                "runs_first_attempt": None
                if runs_first is None
                else [round(r, 4) for r in runs_first],
                "vs_baseline": round(base_ms / ours_ms, 2),
                "baseline_ms_per_iter": round(base_ms, 3),
                "backend": dev.platform,
                "device_kind": dev.device_kind,
                "solution_cosine": round(cos, 6),
                "policy_updates_per_sec": _r(updates_per_sec, 2),
                "full_update_ms": _r(update_ms, 3),
                # precision tags for the headline full-update row
                # (ISSUE 8): reference semantics — the ladder variants
                # live in solve_precision.rows with the same tags
                "full_update_tags": {
                    "fvp_dtype": "f32",
                    "fvp_subsample": None,
                    "solve_cosine": 1.0,
                },
                "policy_updates_per_sec_fvp_subsample": _r(
                    updates_per_sec_sub, 2
                ),
                "fvp_subsample": FVP_SUB,
                # -- solver precision ladder (ISSUE 8): f32/bf16/
                #    subsampled/full-ladder full update at the flagship
                #    shape, min-over-reps with the contention retry,
                #    each row tagged with its measured on-device audit
                #    cosine vs the f32/full-batch solve --
                "solve_precision": precision,
                # -- phase-level attribution of the full update (round-6
                #    tentpole): each phase its own chained-dependent
                #    program; coverage = sum(phases)/full_update_ms --
                "update_tail_breakdown": tail_breakdown,
                # -- compiled memory_analysis per headline program
                #    (ISSUE 5): argument/output/temp/alias bytes + peak
                #    estimate for ONE solve and ONE full update at the
                #    headline shapes; BENCH_LADDER rows carry the same
                #    accounting per rung. None = skipped (BENCH_MEMORY=0)
                #    or the backend reported nothing --
                "program_memory": program_memory,
                # -- FLOP / MFU accounting. flops_source says where the
                #    FLOP counts came from: "xla_cost_analysis" (lowered
                #    loop-free programs, composed per flop_accounting) or
                #    "analytic" (the closed-form model — used whenever the
                #    backend reports no cost analysis, so MFU is never
                #    null while bytes-derived fields stay null when
                #    unmeasured) --
                "flops_source": flops_source,
                "peak_bf16_tflops": peak,
                "flops_per_cg_iter": _r(acct.get("flops_per_cg_iter"), 0),
                "analytic_flops_per_cg_iter": round(
                    _analytic_fvp_tangent_flops(), 0
                ),
                "achieved_tflops_solve": _r(tflops_solve, 2),
                "mfu_solve": _mfu(tflops_solve),
                "flops_per_update": _r(acct.get("flops_per_update"), 0),
                "achieved_tflops_update": _r(tflops_update, 2),
                "mfu_update": _mfu(tflops_update),
                "hbm_gbps": hbm_gbps,
                # unfused (per-op) traffic from cost analysis — a lower
                # bound on intensity, so the roofline is an under-estimate
                # and the fraction may legitimately exceed 1 (fusion)
                "unfused_bytes_per_cg_iter": _r(
                    acct.get("bytes_per_cg_iter"), 0
                ),
                "min_arithmetic_intensity_flops_per_byte": _r(intensity, 1),
                "unfused_traffic_roofline_tflops": _r(roofline_tflops, 1),
                "solve_vs_unfused_roofline": _r(roofline_frac, 3),
                # -- transport-free ablations (VERDICT r2 item 5): all
                #    CPU-side rows run on the in-process CPU backend, so
                #    no tunnel RTT contaminates any ratio. fusion_speedup
                #    pairs MATCHED GGN FVPs (host loop vs fused program —
                #    pure loop fusion); solver_speedup_vs_reference_cpu
                #    pairs our fused GGN solve against the reference-
                #    semantics baseline (host CG + jvp∘grad FVP) on the
                #    same backend (fusion + factorization bundled);
                #    chip_speedup_fused_vs_cpu compares the SAME fused
                #    program across backends --
                "fused_cpu_ms_per_iter": _r(fused_cpu_ms, 3),
                "host_driven_cpu_ggn_ms_per_iter": _r(host_ggn_cpu_ms, 3),
                "fusion_speedup": None
                if fused_cpu_ms is None or host_ggn_cpu_ms is None
                else round(host_ggn_cpu_ms / fused_cpu_ms, 2),
                "solver_speedup_vs_reference_cpu": None
                if fused_cpu_ms is None
                else round(base_ms / fused_cpu_ms, 2),
                # same XLA program across backends (the pallas kernel has
                # no CPU twin, so this ratio stays pinned to the XLA path)
                "chip_speedup_fused_vs_cpu": None
                if fused_cpu_ms is None
                else round(fused_cpu_ms / xla_ms, 2),
                # accelerator host-driven row: raw only (the corrected
                # variant subtracts ~RTT from ~RTT and is dropped as
                # noise; kept for the transport-cost story, not for
                # speedup claims)
                "host_driven_cg_ms_per_iter": _r(host_cg_ms, 3),
                "host_driven_cg_ms_per_iter_raw": _r(host_cg_raw_ms, 3),
                # stable variant: chained standalone FVPs (moving
                # linearization point) — the zero-transport lower bound on
                # any host-driven loop's per-iteration device cost
                "standalone_fvp_ms": _r(standalone_fvp_ms, 3),
                # NOT a kernel speedup: standalone-XLA-FVP ÷ in-chain
                # per-iter — a dispatch/loop-overhead ratio (~1.0 means
                # the fused CG loop's per-iter cost equals a bare FVP).
                # Kept under its historical name for artifact-lineage
                # comparability; dispatch_overhead_ratio is the same
                # number under the name that says what it is, and
                # schema_notes carries the in-artifact explanation
                # (VERDICT r5 item 6).
                "fusion_speedup_kernel_level": None
                if standalone_fvp_ms is None
                else round(standalone_fvp_ms / xla_ms, 2),
                "dispatch_overhead_ratio": None
                if standalone_fvp_ms is None
                else round(standalone_fvp_ms / xla_ms, 2),
                # -- end-to-end host-env driver: iterations/s with a
                #    sleep-bound sim, serial vs the async pipeline
                #    (--host-async-pipeline); device_rtt_ms published
                #    alongside so the hidden-latency claim is measurable --
                "host_env_pipeline": host_pipe,
                # -- serving SLOs (ISSUE 6): per AOT batch rung, p50/p99
                #    latency + actions/s, closed-loop (bare engine) and
                #    open-loop (concurrent clients through the
                #    micro-batcher, queueing + coalescing included) --
                "serving": serving,
                # -- continuous batching for recurrent serving
                #    (ISSUE 13): sessions/s + p50/p99 ladder over
                #    concurrency, batched epochs vs serialized batch-1
                "serving_sessions": serving_sessions,
                # -- native-speed data plane (ISSUE 16): closed-loop
                #    S=16 actions/s + p99, JSON/TCP/thread (one-shot
                #    connections, the pre-wire client idiom) vs
                #    binary/UDS/asyncio (persistent connections), with
                #    traced stage_network/stage_queue p99 rows and
                #    bit-exact action parity across legs --
                "serving_wire": serving_wire,
                # -- replica-scaling SLOs (ISSUE 9): closed-loop
                #    actions/s + p50/p99 through the router at 1/2/4
                #    replicas; scaling_efficiency = aps_N/(N·aps_1),
                #    device time simulated GIL-free (see note field) --
                "serving_scale": serving_scale,
                # -- pipelined actor/learner loop (ISSUE 17): sync vs
                #    overlapped env-steps/s at a calibrated update cost
                #    per fleet width, plus per-stage p99s from the
                #    rate-1.0 traced real pipeline (see the bench's
                #    note field for what each leg measures) --
                "training_overlap": training_overlap,
                # -- env fleet scale-out (ISSUE 10): env-steps/s across
                #    the wide-N ladder (T*N constant per family),
                #    vs_n128 ratios, and the rollout-memory-vs-chunk
                #    study; the >=10x claim is reserved for the TPU
                #    re-run protocol (env_fleet_bench docstring) --
                "env_fleet": env_fleet,
                # -- MFU-vs-width scaling study (VERDICT r2 item 2);
                #    analytic FLOP model per width --
                "width_study": [
                    {
                        **row,
                        "analytic_mfu": None
                        if peak is None
                        else round(row["achieved_tflops"] / peak, 4),
                    }
                    for row in width_rows
                ],
                # in-artifact schema notes (VERDICT r5 item 6): the
                # fields a reader without the source would misread
                "schema_notes": {
                    "fusion_speedup_kernel_level": (
                        "standalone-XLA-FVP ms ÷ in-chain per-iter ms — "
                        "a dispatch/loop-overhead ratio (~1.0 = the "
                        "fused CG loop adds no kernel-level win over a "
                        "bare FVP), NOT a kernel speedup; see "
                        "pallas_kernel_speedup_vs_xla for the kernel "
                        "win. dispatch_overhead_ratio is the same value "
                        "under its descriptive name."
                    ),
                    "retried": (
                        "true = the headline phase's first attempt "
                        "looked contended (spread >10% or loadavg >1.8) "
                        "and a re-run was attempted; runs_first_attempt "
                        "keeps the first attempt, value = min over both. "
                        "runs_ms_per_iter == runs_first_attempt means "
                        "the retry itself failed and the contended "
                        "first attempt stands"
                    ),
                    "width_study.solve_path": (
                        "the operator that produced the row: "
                        "pallas_fused (the shipping TPU default) or "
                        "xla_ggn (fallback_reason says why)"
                    ),
                },
    }
    print(json.dumps(artifact))
    _emit_bench_events(artifact, tail_breakdown, host_pipe)


def _emit_bench_events(artifact, tail_breakdown, host_pipe) -> None:
    """Re-emit the bench timings through the run-event bus
    (``BENCH_EVENTS_JSONL=<path>``): a manifest + one ``phase`` record per
    timed phase, in the SAME schema the training drivers log — so
    ``scripts/validate_events.py`` checks bench artifacts and training
    telemetry with one validator, and downstream tooling reads one format
    (the ISSUE 3 one-schema contract)."""
    path = os.environ.get("BENCH_EVENTS_JSONL")
    if not path:
        return
    from trpo_tpu.obs.events import EventBus, JsonlSink, manifest_fields

    bus = EventBus(JsonlSink(path))
    try:
        bus.emit(
            "run_manifest",
            **manifest_fields(
                config={
                    "bench": "north_star",
                    "batch": BATCH,
                    "obs_dim": OBS_DIM,
                    "act_dim": ACT_DIM,
                    "hidden": list(HIDDEN),
                    "cg_iters": CG_ITERS,
                    "damping": DAMPING,
                },
                extra={
                    "metric": artifact["metric"],
                    "solve_path": artifact["solve_path"],
                    "device_kind": artifact["device_kind"],
                },
            ),
        )
        bus.emit(
            "phase", name="solve/cg_iter", ms=artifact["value"],
            solve_path=artifact["solve_path"],
        )
        if artifact.get("full_update_ms"):
            bus.emit(
                "phase", name="update/full", ms=artifact["full_update_ms"]
            )
        if tail_breakdown:
            for name, ms in tail_breakdown["phases_ms"].items():
                bus.emit("phase", name=f"update_tail/{name}", ms=ms)
        # solve-precision rows: one phase record per ladder variant,
        # carrying the precision tags (extra fields are schema-legal)
        for row in (artifact.get("solve_precision") or {}).get("rows", []):
            bus.emit(
                "phase",
                name=f"solve_precision/{row['variant']}",
                ms=row["full_update_ms"],
                fvp_dtype=row["fvp_dtype"],
                fvp_subsample=row["fvp_subsample"],
                solve_cosine=row["solve_cosine"],
            )
        if host_pipe:
            for key in ("host_step_ms_per_iter", "device_rtt_ms"):
                if host_pipe.get(key) is not None:
                    bus.emit(
                        "phase",
                        name=f"host_pipeline/{key}",
                        ms=host_pipe[key],
                    )
        # serving SLO rows as phase records: one closed-loop p50 and one
        # open-loop p99 per AOT batch rung — the latency pair the
        # analyze gate judges (time-like: growth = regression)
        for row in (artifact.get("serving") or {}).get("rows", []):
            rung = row["batch_shape"]
            bus.emit(
                "phase",
                name=f"serving/b{rung}_closed_p50",
                ms=row["closed_loop"]["p50_ms"],
            )
            bus.emit(
                "phase",
                name=f"serving/b{rung}_open_p99",
                ms=row["open_loop"]["p99_ms"],
            )
        # replica-scaling rows (ISSUE 9): p99 per replica count, with
        # the throughput/efficiency tags riding as extra fields
        for row in (artifact.get("serving_scale") or {}).get("rows", []):
            if row.get("p99_ms") is None:
                continue
            bus.emit(
                "phase",
                name=f"serving_scale/r{row['replicas']}_p99",
                ms=row["p99_ms"],
                actions_per_sec=row["actions_per_sec"],
                scaling_efficiency=row["scaling_efficiency"],
            )
        # continuous-batching rows (ISSUE 13): per concurrency rung,
        # the batched p99 (time-like: growth = regression) plus a
        # ms-per-session-step phase so a sessions/s COLLAPSE also trips
        # the time-like gate (1000/steps_per_sec grows when throughput
        # shrinks); speedup/parity ride as extra fields. A live serving
        # run additionally gates through the standard `serve`-event
        # serving block — the SessionBatcher emits the same schema.
        for row in (artifact.get("serving_sessions") or {}).get(
            "rows", []
        ):
            s_conc = row["sessions"]
            bat = row["batched"]
            bus.emit(
                "phase",
                name=f"serving_sessions/s{s_conc}_batched_p99",
                ms=bat["p99_ms"],
                speedup=row["speedup"],
                action_parity=row["action_parity"],
            )
            if bat["steps_per_sec"]:
                bus.emit(
                    "phase",
                    name=f"serving_sessions/s{s_conc}_batched_ms_per_step",
                    ms=1e3 / bat["steps_per_sec"],
                    steps_per_sec=bat["steps_per_sec"],
                )
        # wire-plane rows (ISSUE 16): per leg, closed-loop p99 plus a
        # ms-per-act phase (time-like: an actions/s collapse trips the
        # gate), with the traced stage p99s riding as extra fields so
        # compare_runs can regress the located rows, not just the
        # aggregate
        for row in (artifact.get("serving_wire") or {}).get("rows", []):
            bus.emit(
                "phase",
                name=f"serving_wire/{row['leg']}_p99",
                ms=row["p99_ms"],
                network_p99_ms=row["network_p99_ms"],
                queue_p99_ms=row["queue_p99_ms"],
            )
            if row["actions_per_sec"]:
                bus.emit(
                    "phase",
                    name=f"serving_wire/{row['leg']}_ms_per_act",
                    ms=1e3 / row["actions_per_sec"],
                    actions_per_sec=row["actions_per_sec"],
                )
        # training-overlap rows (ISSUE 17): per width, the overlapped
        # driver's ms-per-iter (time-like: an env-steps/s collapse
        # grows it, so the rate gates through the standard time-like
        # judge — the serving_wire inversion idiom) with the rates and
        # speedup riding as extra fields, plus one p99 row per traced
        # training stage so compare_runs regresses the LOCATED stage,
        # not just the aggregate
        for row in (artifact.get("training_overlap") or {}).get(
            "rows", []
        ):
            w = row["n_envs"]
            bus.emit(
                "phase",
                name=f"training_overlap/n{w}_overlap_ms_per_iter",
                ms=row["overlap_ms_per_iter"],
                overlap_env_steps_per_sec=row["overlap_env_steps_per_sec"],
                sync_env_steps_per_sec=row["sync_env_steps_per_sec"],
                overlap_speedup=row["overlap_speedup"],
                n_envs=w,
            )
            for stage, p99 in (row.get("stage_p99_ms") or {}).items():
                bus.emit(
                    "phase",
                    name=f"training_overlap/n{w}_{stage}_p99",
                    ms=p99,
                    n_envs=w,
                )
        # env-fleet ladder rows (ISSUE 10): one phase record per
        # (family, N) rung with the throughput riding as extra fields —
        # the rate the BENCH_LADDER "Env fleet scale-out" section and
        # the analyze gate's env_steps_per_sec metric both speak
        for row in (artifact.get("env_fleet") or {}).get("rows", []):
            bus.emit(
                "phase",
                name=f"env_fleet/{row['family']}_n{row['n_envs']}",
                ms=row["iter_ms"],
                env_steps_per_sec=row["env_steps_per_sec"],
                rollout_steps_per_sec=row["rollout_steps_per_sec"],
                n_envs=row["n_envs"],
                batch=row["batch"],
            )
        ck = (artifact.get("env_fleet") or {}).get("chunk_memory") or {}
        for label, fields in [("flat_T", ck.get("flat"))] + [
            (f"chunk{c}", f) for c, f in (ck.get("chunks") or {}).items()
        ]:
            if fields:
                bus.emit(
                    "memory", scope="program",
                    program=f"env_fleet/rollout_{label}", **fields,
                )
        # one memory record per analyzed headline program — the same
        # scope="program" schema the training drivers emit under
        # --memory-accounting, so analyze_run.py --compare gates bench
        # artifacts' memory columns exactly like training logs'
        for pname, fields in (artifact.get("program_memory") or {}).items():
            bus.emit("memory", scope="program", program=pname, **fields)
    finally:
        bus.close()


if __name__ == "__main__":
    main()
