"""Config-ladder benchmark: full-training throughput per BASELINE rung.

Complements ``bench.py`` (the driver-facing north-star CG metric) with
end-to-end numbers across the BASELINE.json ladder's device-env rungs:
each rung times ``TRPOAgent.run_iterations`` — K complete training
iterations (rollout → GAE → critic fit → fused natural-gradient update)
as ONE device program — and reports policy-updates/sec and env-steps/sec.

Timing methodology per the tunneled-TPU rules in ``bench.py``: the K
iterations chain inside one ``lax.scan`` (sequential by construction), the
timed sync downloads one small stats leaf, and the trivial-fetch RTT is
subtracted. Run: ``python bench_ladder.py [--rungs cartpole,catch ...]``.
Results table: ``BENCH_LADDER.md``.
"""

import argparse
import json
import os
import sys
import time

# Importing bench FIRST reuses its wedged-tunnel gate: it probes backend
# liveness in a killable subprocess before any jax call in THIS process,
# and falls back to CPU if the single-tenant tunnel is stuck (bench.py's
# module preamble). It also provides the shared RTT measurement.
import bench as _bench
from bench import _device_rtt  # noqa: E402

import jax  # noqa: E402
import numpy as np  # noqa: E402

from trpo_tpu.agent import TRPOAgent  # noqa: E402
from trpo_tpu.config import get_preset  # noqa: E402

# name -> (K iterations, overrides) — device-env rungs: the ladder times
# the fused on-device pipeline. (Variant rungs below carry the base preset
# explicitly: name -> (preset, K, overrides).)
# K is sized so the timed window (K × iter time) is several× the ~110 ms
# tunnel RTT — shorter chains leave the RTT subtraction noise-dominated
# (round-1 numbers for the sub-ms rungs wobbled 2× between runs).
RUNGS = {
    "cartpole": (300, {}),
    "cartpole-po": (60, {}),          # recurrent (GRU) / POMDP rung
    "pendulum": (150, {}),
    "catch": (40, {}),                # conv/pixel rung
    "pong-sim": (6, {}),              # Atari-scale conv FVP: 84×84×4 obs,
    #                                   ≈1.7M-param Nature policy
    "halfcheetah-sim": (200, {}),
    "humanoid-sim": (12, {}),         # batch 50k — the north-star shape
}

# model-family variants: same env, different policy family — the ladder
# records every family's fused-iteration throughput
VARIANT_RUNGS = {
    "cartpole-po-lstm": ("cartpole-po", 60, {"policy_cell": "lstm"}),
    "cartpole-moe": ("cartpole", 300, {"policy_experts": 4}),
    # GAE/returns recurrence through the Pallas single-HBM-pass kernel
    # instead of the XLA associative scan (ops/pallas_scan.py) — the
    # whole-iteration view of the --pallas kernel shootout
    "humanoid-sim-pallas": ("humanoid-sim", 12, {"scan_backend": "pallas"}),
}

# Host-simulator rungs: env stepping on the host (real MuJoCo via
# gymnasium), policy inference on the device through the packed act path
# (rollout.make_host_act_fn(pack=True) — one fetch per step). Iteration =
# host rollout + the same fused GAE/critic/update program. Gated on the
# simulator being importable. Batch reduced vs the preset: per-step host
# latency through a tunneled TPU is RTT-bound, and the rung exists to
# record the steady-state env-steps/s of the host boundary, which is
# batch-size independent.
HOST_RUNGS = {
    "halfcheetah-host": (
        "halfcheetah", 2, {"batch_timesteps": 1000},
        ("gymnasium", "mujoco"),
    ),
    # host_inference="cpu": params pushed to the host CPU backend once per
    # iteration, rollout pays ZERO device round trips — the fix for the
    # RTT-bound row above (the policy is a 64×64 MLP; inference is
    # microseconds next to a ~100 ms tunnel round trip)
    "halfcheetah-host-cpuinf": (
        "halfcheetah", 2,
        {"batch_timesteps": 1000, "host_inference": "cpu"},
        ("gymnasium", "mujoco"),
    ),
}


def _missing(module: str) -> bool:
    import importlib.util

    return importlib.util.find_spec(module) is None


def _rung_program_memory(agent):
    """Compiled ``memory_analysis()`` per jitted program the rung ran
    (ISSUE 5 satellite: the ladder's memory column). The agent's
    ``--memory-accounting`` capture hook recorded each program's abstract
    argument shapes before donation; analyzing costs one extra compile
    per program, after the timed window. ``BENCH_MEMORY=0`` skips; a
    backend that reports nothing yields None."""
    if os.environ.get("BENCH_MEMORY", "1") == "0" or not agent._program_args:
        return None
    from trpo_tpu.obs.memory import program_memory_analysis

    out = {}
    for pname, (fn, pargs) in agent._program_args.items():
        fields = program_memory_analysis(fn, pargs)
        if fields:
            out[pname] = fields
    return out or None


def _peak_mem_mib(mem):
    """Resident-set headline for the table: the largest single program's
    peak estimate (the rung's programs run sequentially, so the max — not
    the sum — bounds the transient footprint)."""
    if not mem:
        return None
    return round(
        max(f["peak_estimate_bytes"] for f in mem.values()) / 2**20, 1
    )


def bench_rung(name: str, k: int, overrides: dict, reps: int = 3,
               preset: str = None):
    cfg = get_preset(preset or name).replace(**overrides)
    agent = TRPOAgent(cfg.env, cfg)
    agent._capture_program_args = True
    state = agent.init_state(seed=0)
    steps_per_iter = agent.n_steps * agent.n_envs

    t0 = time.perf_counter()
    new_state, stats = agent.run_iterations(state, k)   # compile + warm
    np.asarray(stats["entropy"])
    compile_s = time.perf_counter() - t0
    rtt = _device_rtt()

    best = float("inf")
    for _ in range(reps):
        # run_iterations DONATES its state (the PR 1 donation contract) —
        # each rep rebuilds the identical seed-0 state outside the timed
        # window instead of re-passing consumed buffers
        state = agent.init_state(seed=0)
        t0 = time.perf_counter()
        _, stats = agent.run_iterations(state, k)
        np.asarray(stats["entropy"])                    # small sync probe
        best = min(best, time.perf_counter() - t0)
    ent = np.asarray(stats["entropy"], np.float64)
    assert np.all(np.isfinite(ent)), f"{name}: non-finite entropy"

    per_iter = max(best - rtt, 1e-9) / k
    mem = _rung_program_memory(agent)
    return {
        "rung": name,
        "n_envs": agent.n_envs,
        "batch_timesteps": steps_per_iter,
        "updates_per_sec": 1.0 / per_iter,
        "env_steps_per_sec": steps_per_iter / per_iter,
        "iter_ms": per_iter * 1e3,
        "compile_s": compile_s,
        "backend": jax.devices()[0].platform,
        "program_memory": mem,
        "peak_mem_mib": _peak_mem_mib(mem),
    }


def bench_host_rung(name: str, preset: str, iters: int, overrides: dict):
    cfg = get_preset(preset).replace(**overrides)
    agent = TRPOAgent(cfg.env, cfg)
    agent._capture_program_args = True
    state = agent.init_state(seed=0)
    steps_per_iter = agent.n_steps * agent.n_envs

    t0 = time.perf_counter()
    state, stats = agent.run_iteration(state)           # compile + warm
    float(np.asarray(stats["entropy"]))
    compile_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    for _ in range(iters):
        state, stats = agent.run_iteration(state)
        float(np.asarray(stats["entropy"]))
    per_iter = (time.perf_counter() - t0) / iters
    assert np.isfinite(float(np.asarray(stats["entropy"])))
    mem = _rung_program_memory(agent)
    return {
        "rung": name,
        "n_envs": agent.n_envs,
        "batch_timesteps": steps_per_iter,
        "updates_per_sec": 1.0 / per_iter,
        "env_steps_per_sec": steps_per_iter / per_iter,
        "iter_ms": per_iter * 1e3,
        "compile_s": compile_s,
        "backend": jax.devices()[0].platform + "+host-sim",
        "program_memory": mem,
        "peak_mem_mib": _peak_mem_mib(mem),
    }


def bench_pallas_scan(shapes=((500, 128), (1000, 1024)), reps=3):
    """Kernel shootout: the returns/GAE reverse affine scan through the
    XLA associative scan vs the Pallas single-HBM-pass kernel
    (``ops/pallas_scan.py``), COMPILED on the current backend (the round-1
    verdict's gap: the kernel had only ever run interpreted on CPU).
    Chained-dependent timing per bench.py's tunneled-TPU rules; on-device
    agreement asserted between the two backends before timing counts."""
    import jax.numpy as jnp
    from jax import lax

    from trpo_tpu.ops.returns import _reverse_affine_scan

    rows = []
    for T, N in shapes:
        # these kernels run in ~µs-tens-of-µs — chain enough of them that
        # the timed window is several× the tunnel RTT, or the subtraction
        # leaves mostly noise
        n_chain = 10_000 if T * N >= 500_000 else 40_000
        kd, kx = jax.random.split(jax.random.key(T * N))
        coeffs = 0.99 * (
            jax.random.uniform(kd, (T, N)) > 0.02
        ).astype(jnp.float32)
        x = jax.random.normal(kx, (T, N), jnp.float32)
        timing = {}
        outs = {}
        for backend in ("xla", "pallas"):
            @jax.jit
            def chained(coeffs, x, _b=backend):
                def body(carry, _):
                    y = _reverse_affine_scan(
                        coeffs, x + jnp.float32(1e-30) * carry, backend=_b
                    )
                    return y, ()

                y, _ = lax.scan(
                    body, jnp.zeros_like(x), None, length=n_chain
                )
                return y, y.sum()

            y, probe = chained(coeffs, x)      # compile + warm
            np.asarray(probe)
            rtt = _device_rtt()
            best = float("inf")
            for _ in range(reps):
                t0 = time.perf_counter()
                y, probe = chained(coeffs, x)
                np.asarray(probe)
                best = min(best, time.perf_counter() - t0)
            timing[backend] = max(best - rtt, 1e-9) / n_chain * 1e3
            outs[backend] = y
        # agreement ON DEVICE between the compiled backends
        err = float(jnp.max(jnp.abs(outs["xla"] - outs["pallas"])))
        scale = float(jnp.max(jnp.abs(outs["xla"]))) + 1e-9
        assert err / scale < 1e-4, f"pallas/xla mismatch: {err} (scale {scale})"
        rows.append({
            "kernel": "reverse_affine_scan",
            "shape": f"{T}x{N}",
            "xla_ms": round(timing["xla"], 4),
            "pallas_ms": round(timing["pallas"], 4),
            "pallas_speedup": round(timing["xla"] / timing["pallas"], 3),
            "max_rel_err": err / scale,
            "backend": jax.devices()[0].platform,
        })
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--rungs",
        default=",".join(
            list(RUNGS) + list(VARIANT_RUNGS) + list(HOST_RUNGS)
        ),
    )
    ap.add_argument("--out", default=None, help="write a markdown table")
    ap.add_argument(
        "--pallas",
        action="store_true",
        help="run the pallas-vs-xla scan kernel shootout instead of the "
        "training-iteration rungs",
    )
    args = ap.parse_args()

    if args.pallas:
        rows = bench_pallas_scan()
        for row in rows:
            print(json.dumps(row))
        if args.out:
            with open(args.out, "w") as f:
                f.write(
                    "| shape (T×N) | xla ms | pallas ms | speedup |\n"
                    "|---|---|---|---|\n"
                    + "\n".join(
                        f"| {r['shape']} | {r['xla_ms']} | "
                        f"{r['pallas_ms']} | {r['pallas_speedup']}× |"
                        for r in rows
                    )
                    + "\n"
                )
        return

    rows = []
    for name in args.rungs.split(","):
        name = name.strip()
        if name in HOST_RUNGS:
            preset, iters, overrides, needs = HOST_RUNGS[name]
            missing = [m for m in needs if _missing(m)]
            if missing:
                print(
                    f"ladder: {name} skipped (no {', '.join(missing)})",
                    file=sys.stderr,
                )
                continue
            print(f"ladder: {name} (host sim) ...", file=sys.stderr)
            rows.append(bench_host_rung(name, preset, iters, overrides))
            print(json.dumps(rows[-1]))
            continue
        if name in VARIANT_RUNGS:
            preset, k, overrides = VARIANT_RUNGS[name]
        else:
            preset, (k, overrides) = name, RUNGS[name]
        print(f"ladder: {name} ...", file=sys.stderr)
        row = bench_rung(name, k, overrides, preset=preset)
        rows.append(row)
        print(json.dumps(row))

    if not rows:
        print("ladder: no rungs ran (all skipped)", file=sys.stderr)
        return
    if args.out:
        _write_out(args.out, rows)


_AUTO_START = "<!-- AUTO-TABLE-START -->"
_AUTO_END = "<!-- AUTO-TABLE-END -->"


def _write_out(path: str, rows) -> None:
    """Write/refresh the throughput table.

    When the target file carries the AUTO-TABLE markers, only the region
    between them is replaced — hand-written analysis sections (roofline,
    ablations, Pallas shootout) survive regeneration. A fresh file gets
    the markers so future runs behave the same."""
    lines = [
        "| rung | envs | batch | iter ms | updates/s | env steps/s "
        "| peak mem |",
        "|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        peak = r.get("peak_mem_mib")
        peak_str = "-" if peak is None else f"{peak:,.1f} MiB"
        lines.append(
            f"| {r['rung']} | {r['n_envs']} | {r['batch_timesteps']} "
            f"| {r['iter_ms']:.1f} | {r['updates_per_sec']:.2f} "
            f"| {r['env_steps_per_sec']:,.0f} | {peak_str} |"
        )
    note = ""
    if any(r["backend"].endswith("host-sim") for r in rows):
        note = (
            "\n`*-host` rungs step a REAL external simulator (MuJoCo via "
            "gymnasium) on the host; they measure the host boundary, not "
            "device compute. Plain `*-host` rows run device inference "
            "through the packed act path (one fetch per step, each a "
            f"full ~{_device_rtt() * 1e3:.0f} ms round trip here); "
            "`-cpuinf` rows run `host_inference=\"cpu\"` — the act "
            "program jitted on the host backend, zero device round "
            "trips during collection.\n"
        )
    auto = (
        "One iteration = rollout + GAE + critic fit + TRPO "
        "natural-gradient update, K iterations scanned into one device "
        "program (`TRPOAgent.run_iterations`); RTT-corrected timing (see "
        "`bench.py`). `peak mem` = the rung's largest jitted program by "
        "compiled `memory_analysis()` peak estimate (args + outputs + "
        "temps − donation aliasing, for ONE iteration/program — "
        "`BENCH_MEMORY=0` skips).\n\n" + "\n".join(lines) + "\n" + note
    )
    header = (
        "# Ladder throughput — full fused training iterations "
        f"({rows[0]['backend']})\n\n"
    )
    try:
        with open(path) as f:
            existing = f.read()
    except FileNotFoundError:
        existing = None
    if existing and _AUTO_START in existing and _AUTO_END in existing:
        pre, rest = existing.split(_AUTO_START, 1)
        _, post = rest.split(_AUTO_END, 1)
        content = pre + _AUTO_START + "\n" + auto + _AUTO_END + post
    else:
        content = header + _AUTO_START + "\n" + auto + _AUTO_END + "\n"
    with open(path, "w") as f:
        f.write(content)


if __name__ == "__main__":
    main()
