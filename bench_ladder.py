"""Config-ladder benchmark: full-training throughput per BASELINE rung.

Complements ``bench.py`` (the driver-facing north-star CG metric) with
end-to-end numbers across the BASELINE.json ladder's device-env rungs:
each rung times ``TRPOAgent.run_iterations`` — K complete training
iterations (rollout → GAE → critic fit → fused natural-gradient update)
as ONE device program — and reports policy-updates/sec and env-steps/sec.

Timing methodology per the tunneled-TPU rules in ``bench.py``: the K
iterations chain inside one ``lax.scan`` (sequential by construction), the
timed sync downloads one small stats leaf, and the trivial-fetch RTT is
subtracted. Run: ``python bench_ladder.py [--rungs cartpole,catch ...]``.
Results table: ``BENCH_LADDER.md``.
"""

import argparse
import json
import sys
import time

# Importing bench FIRST reuses its wedged-tunnel gate: it probes backend
# liveness in a killable subprocess before any jax call in THIS process,
# and falls back to CPU if the single-tenant tunnel is stuck (bench.py's
# module preamble). It also provides the shared RTT measurement.
import bench as _bench
from bench import _device_rtt  # noqa: E402

import jax  # noqa: E402
import numpy as np  # noqa: E402

from trpo_tpu.agent import TRPOAgent  # noqa: E402
from trpo_tpu.config import get_preset  # noqa: E402

# (preset, K iterations, overrides) — device-env rungs only: the ladder
# times the fused on-device pipeline; gym:/MuJoCo binaries are external.
RUNGS = {
    "cartpole": (20, {}),
    "cartpole-po": (20, {}),          # recurrent/POMDP rung
    "pendulum": (10, {}),
    "catch": (10, {}),                # conv/pixel rung
    "halfcheetah-sim": (10, {}),
    "humanoid-sim": (3, {}),          # batch 50k — the north-star shape
}


def bench_rung(name: str, k: int, overrides: dict, reps: int = 3):
    cfg = get_preset(name).replace(**overrides)
    agent = TRPOAgent(cfg.env, cfg)
    state = agent.init_state(seed=0)
    steps_per_iter = agent.n_steps * cfg.n_envs

    t0 = time.perf_counter()
    new_state, stats = agent.run_iterations(state, k)   # compile + warm
    np.asarray(stats["entropy"])
    compile_s = time.perf_counter() - t0
    rtt = _device_rtt()

    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        _, stats = agent.run_iterations(state, k)
        np.asarray(stats["entropy"])                    # small sync probe
        best = min(best, time.perf_counter() - t0)
    ent = np.asarray(stats["entropy"], np.float64)
    assert np.all(np.isfinite(ent)), f"{name}: non-finite entropy"

    per_iter = max(best - rtt, 1e-9) / k
    return {
        "rung": name,
        "n_envs": cfg.n_envs,
        "batch_timesteps": steps_per_iter,
        "updates_per_sec": 1.0 / per_iter,
        "env_steps_per_sec": steps_per_iter / per_iter,
        "iter_ms": per_iter * 1e3,
        "compile_s": compile_s,
        "backend": jax.devices()[0].platform,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rungs", default=",".join(RUNGS))
    ap.add_argument("--out", default=None, help="write a markdown table")
    args = ap.parse_args()

    rows = []
    for name in args.rungs.split(","):
        name = name.strip()
        k, overrides = RUNGS[name]
        print(f"ladder: {name} ...", file=sys.stderr)
        rows.append(bench_rung(name, k, overrides))
        print(json.dumps(rows[-1]))

    if args.out:
        lines = [
            "| rung | envs | batch | iter ms | updates/s | env steps/s |",
            "|---|---|---|---|---|---|",
        ]
        for r in rows:
            lines.append(
                f"| {r['rung']} | {r['n_envs']} | {r['batch_timesteps']} "
                f"| {r['iter_ms']:.1f} | {r['updates_per_sec']:.1f} "
                f"| {r['env_steps_per_sec']:,.0f} |"
            )
        with open(args.out, "w") as f:
            f.write(
                "# Ladder throughput — full fused training iterations "
                f"({rows[0]['backend']})\n\n"
                "One iteration = rollout + GAE + critic fit + TRPO "
                "natural-gradient update, K iterations scanned into one "
                "device program (`TRPOAgent.run_iterations`); RTT-corrected "
                "timing (see `bench.py`).\n\n" + "\n".join(lines) + "\n"
            )


if __name__ == "__main__":
    main()
