"""Value-function baseline: zeros-before-fit parity, regression ability."""

import jax
import jax.numpy as jnp
import numpy as np

from trpo_tpu.vf import create_value_function
from trpo_tpu.utils.metrics import explained_variance


def test_predict_zeros_before_first_fit():
    # Ref parity: VF.predict returns zeros before the net exists
    # (utils.py:88-89), so iteration-0 advantages are raw returns.
    vf = create_value_function(obs_dim=3)
    state = vf.init(jax.random.key(0))
    obs = jax.random.normal(jax.random.key(1), (10, 3))
    np.testing.assert_array_equal(np.asarray(vf.predict(state, obs)), 0.0)


def test_fit_regresses_linear_target():
    vf = create_value_function(obs_dim=2, train_steps=200, learning_rate=1e-2)
    state = vf.init(jax.random.key(0))
    obs = jax.random.normal(jax.random.key(1), (256, 2))
    targets = 2.0 * obs[:, 0] - obs[:, 1] + 0.5
    w = jnp.ones(256)
    for _ in range(5):
        state, loss = vf.fit(state, obs, targets, w)
    pred = vf.predict(state, obs)
    ev = float(explained_variance(pred, targets))
    assert ev > 0.95, f"explained variance {ev}, loss {float(loss)}"


def test_fit_is_jittable_and_respects_weights():
    vf = create_value_function(obs_dim=1, train_steps=50, learning_rate=5e-2)
    state = vf.init(jax.random.key(2))
    # Two clusters with contradictory targets; weights select cluster A.
    obs = jnp.concatenate([jnp.zeros((64, 1)), jnp.zeros((64, 1))])
    targets = jnp.concatenate([jnp.full(64, 1.0), jnp.full(64, -5.0)])
    w = jnp.concatenate([jnp.ones(64), jnp.zeros(64)])
    fit = jax.jit(vf.fit)
    for _ in range(6):
        state, _ = fit(state, obs, targets, w)
    pred = float(vf.predict(state, jnp.zeros((1, 1)))[0])
    assert abs(pred - 1.0) < 0.1, pred
