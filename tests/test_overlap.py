"""Pipelined actor/learner training loop (ISSUE 17).

The contracts under test:

- the staged update (``make_staged_trpo_update``: solve → finish over
  the host seam) composes BIT-EXACTLY to the fused
  ``make_trpo_update`` — feedforward, recurrent, and under a vmapped
  population-member axis;
- with ``train_overlap=1`` the FIRST overlapped iteration (fill window,
  staleness 0) is bit-exact vs the synchronous driver on every state
  leaf — params, obs-norm stats, env carry, and RNG all thread across
  the pipeline boundary identically;
- the importance-weight correction is exact: ``is_weight`` of ones is
  the plain surrogate bit-for-bit, and under staleness 1 the
  line-search KL bound still holds;
- invalid overlap configs fail at CONSTRUCTION time with clear errors.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from trpo_tpu.agent import TRPOAgent
from trpo_tpu.config import TRPOConfig
from trpo_tpu.envs import CartPole
from trpo_tpu.models import make_policy
from trpo_tpu.trpo import (
    TRPOBatch,
    make_staged_trpo_update,
    make_trpo_update,
    surrogate_loss,
)


def _np(x):
    if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jax.dtypes.prng_key):
        x = jax.random.key_data(x)
    return np.asarray(x)


def _assert_trees_equal(a, b, label=""):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb), label
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(_np(x), _np(y), label)


def _ff_batch(policy, params, n=32, seed=0):
    k1, k2, k3 = jax.random.split(jax.random.key(seed), 3)
    obs = jax.random.normal(k1, (n, 4))
    dist = policy.apply(params, obs)
    actions = policy.dist.sample(k2, dist)
    adv = jax.random.normal(k3, (n,))
    return TRPOBatch(
        obs=obs,
        actions=actions,
        advantages=adv,
        old_dist=jax.lax.stop_gradient(dist),
        weight=jnp.ones((n,)),
    )


def _agent_pair(overlap_extra=None, **kw):
    """(synchronous agent, overlapped agent) over identical configs —
    only ``train_overlap`` differs."""
    base = dict(
        env="cartpole",
        n_envs=8,
        batch_timesteps=8 * 16,
        rollout_chunk=4,
        cg_iters=3,
        vf_train_steps=3,
        policy_hidden=(8,),
        vf_hidden=(16,),
        normalize_obs=True,
        seed=0,
    )
    base.update(kw)
    env = base.pop("env")
    sync = TRPOAgent(env, TRPOConfig(**base))
    over = TRPOAgent(
        env, TRPOConfig(**base, train_overlap=1, **(overlap_extra or {}))
    )
    return sync, over


# ---------------------------------------------------------------------------
# staged update ≡ fused update
# ---------------------------------------------------------------------------


def test_staged_update_matches_fused():
    env = CartPole()
    policy = make_policy(env.obs_shape, env.action_spec, hidden=(8,))
    params = policy.init(jax.random.key(0))
    batch = _ff_batch(policy, params, n=16)
    cfg = TRPOConfig(cg_iters=3)

    ref_params, ref_stats = jax.jit(make_trpo_update(policy, cfg))(
        params, batch
    )
    solve, finish = make_staged_trpo_update(policy, cfg)
    pack = jax.jit(solve)(params, batch)
    new_params, stats = jax.jit(finish)(params, batch, pack)

    _assert_trees_equal(ref_params, new_params, "staged params")
    np.testing.assert_array_equal(
        np.asarray(ref_stats.kl), np.asarray(stats.kl), "staged kl"
    )
    np.testing.assert_array_equal(
        np.asarray(ref_stats.surrogate_after),
        np.asarray(stats.surrogate_after),
        "staged surrogate",
    )


@pytest.mark.slow
def test_staged_update_matches_fused_member_axis():
    """The staged seam composes with the population-member vmap: a
    member axis over solve → finish reproduces the vmapped fused
    update bit-exactly (the analogue Population relies on for
    train_overlap=0 members)."""
    env = CartPole()
    policy = make_policy(env.obs_shape, env.action_spec, hidden=(8,))
    params = jax.vmap(
        lambda k: policy.init(k)
    )(jax.random.split(jax.random.key(0), 3))
    batches = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs),
        *[
            _ff_batch(
                policy,
                jax.tree_util.tree_map(lambda x: x[i], params),
                n=16,
                seed=i,
            )
            for i in range(3)
        ],
    )
    cfg = TRPOConfig(cg_iters=3)

    fused = jax.jit(jax.vmap(make_trpo_update(policy, cfg)))
    ref_params, ref_stats = fused(params, batches)

    solve, finish = make_staged_trpo_update(policy, cfg)
    packs = jax.jit(jax.vmap(solve))(params, batches)
    new_params, stats = jax.jit(jax.vmap(finish))(params, batches, packs)

    _assert_trees_equal(ref_params, new_params, "member-axis params")
    np.testing.assert_array_equal(
        np.asarray(ref_stats.kl), np.asarray(stats.kl), "member-axis kl"
    )


# ---------------------------------------------------------------------------
# importance-weight correction
# ---------------------------------------------------------------------------


def test_is_weight_ones_is_plain_surrogate():
    env = CartPole()
    policy = make_policy(env.obs_shape, env.action_spec, hidden=(16,))
    params = policy.init(jax.random.key(1))
    batch = _ff_batch(policy, params, seed=2)
    plain = surrogate_loss(policy, params, batch)
    weighted = surrogate_loss(
        policy,
        params,
        batch._replace(is_weight=jnp.ones_like(batch.advantages)),
    )
    np.testing.assert_array_equal(
        np.asarray(plain), np.asarray(weighted)
    )


def test_is_weight_unity_when_policies_equal():
    """The stale-window weight exp(logp_anchor − logp_behavior) is
    exactly 1 when anchor and behavior params coincide — the correction
    vanishes on-policy."""
    env = CartPole()
    policy = make_policy(env.obs_shape, env.action_spec, hidden=(16,))
    params = policy.init(jax.random.key(3))
    batch = _ff_batch(policy, params, seed=4)
    dist = policy.apply(params, batch.obs)
    w = jnp.exp(
        policy.dist.logp(dist, batch.actions)
        - policy.dist.logp(batch.old_dist, batch.actions)
    )
    np.testing.assert_array_equal(
        np.asarray(w), np.ones_like(np.asarray(w))
    )


# ---------------------------------------------------------------------------
# overlap driver: staleness-0 bit-exactness + threading
# ---------------------------------------------------------------------------


def test_overlap_first_iteration_bitexact_sync():
    """Fill window (staleness 0): one overlapped iteration ≡ one
    synchronous iteration on EVERY TrainState leaf — policy/vf params,
    obs-norm stats, env carry, and rng."""
    sync, over = _agent_pair()
    s_sync, _ = sync.run_iterations(sync.init_state(), 1)
    s_over, _ = over.run_iterations(over.init_state(), 1)
    for name in s_sync._fields:
        _assert_trees_equal(
            getattr(s_sync, name), getattr(s_over, name), name
        )


@pytest.mark.slow
def test_overlap_first_iteration_bitexact_sync_recurrent():
    """Recurrent twin of the fill-window contract: ``policy_h`` threads
    through the tuple-params rollout wrapper and the SeqObs batch
    identically to the synchronous driver."""
    sync, over = _agent_pair(env="cartpole-po", policy_gru=8)
    s_sync, _ = sync.run_iterations(sync.init_state(), 1)
    s_over, _ = over.run_iterations(over.init_state(), 1)
    for name in s_sync._fields:
        _assert_trees_equal(
            getattr(s_sync, name), getattr(s_over, name), name
        )


@pytest.mark.slow
def test_overlap_staleness_one_kl_and_threading():
    """Three overlapped iterations: the line-search KL bound holds under
    staleness 1 (the IS-corrected surrogate's anchor is the CURRENT
    params, so kl_old_new stays a trust-region quantity), stats stay
    finite, and the obs-norm/timestep accounting threads exactly one
    batch per iteration."""
    _, over = _agent_pair()
    s0 = over.init_state()
    s, rows = over.run_iterations(s0, 3)
    kl = np.asarray(rows["kl_old_new"], np.float64)
    assert kl.shape[0] == 3
    # backtracking accepts kl <= 1.5 * max_kl (trpo.py line search)
    assert np.all(kl <= 1.5 * over.cfg.max_kl + 1e-6), kl
    assert np.all(np.isfinite(np.asarray(rows["entropy"])))
    assert int(s.iteration) == 3
    assert int(s.total_timesteps) == 3 * over.cfg.batch_timesteps
    if s.obs_norm is not None:
        assert float(np.asarray(s.obs_norm.count)) >= (
            3 * over.cfg.batch_timesteps
        )
    # rng advanced and the env carry left the initial state
    assert not np.array_equal(
        np.asarray(jax.random.key_data(s.rng)),
        np.asarray(jax.random.key_data(s0.rng)),
    )


# ---------------------------------------------------------------------------
# construction-time validation
# ---------------------------------------------------------------------------


def test_overlap_config_rejections():
    ok = dict(n_envs=8, batch_timesteps=8 * 16, rollout_chunk=4)
    with pytest.raises(ValueError, match="train_overlap"):
        TRPOConfig(train_overlap=2, **ok)
    with pytest.raises(ValueError, match="rollout_chunk"):
        TRPOConfig(train_overlap=1)
    with pytest.raises(ValueError, match="host_async_pipeline"):
        TRPOConfig(train_overlap=1, host_async_pipeline=True, **ok)
    with pytest.raises(ValueError, match="fuse_iterations"):
        TRPOConfig(train_overlap=1, fuse_iterations=2, **ok)
    with pytest.raises(ValueError, match="mesh"):
        TRPOConfig(train_overlap=1, mesh_shape=(2,), **ok)
    with pytest.raises(ValueError, match="recover_on_nan"):
        TRPOConfig(train_overlap=1, recover_on_nan="restore", **ok)
    with pytest.raises(ValueError, match="inject_faults"):
        TRPOConfig(train_overlap=1, inject_faults="nan_grad@2", **ok)


def test_overlap_rejects_host_env():
    cfg = TRPOConfig(
        train_overlap=1,
        rollout_chunk=4,
        n_envs=2,
        batch_timesteps=16,
        vf_train_steps=2,
        cg_iters=2,
    )
    with pytest.raises(ValueError, match="device env"):
        TRPOAgent("gym:CartPole-v1", cfg)


def test_population_rejects_overlap_agent():
    from trpo_tpu.population import Population

    _, over = _agent_pair()
    with pytest.raises(ValueError, match="train_overlap"):
        Population(over, seeds=[0, 1])
