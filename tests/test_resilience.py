"""Resilience subsystem (ISSUE 4): deterministic chaos suite.

Every failure mode the subsystem claims to survive is INJECTED here and
the recovery pinned: worker kill/hang → supervised restart → degraded
in-process fallback; NaN-poisoned iteration → last-good restore →
bit-exact continuation vs a clean run; SIGTERM mid-run → drained
shutdown, final checkpoint, requeue exit code, lossless resume;
``kill -9`` mid-save → the integrity gate never selects the torn step.
Faults come from ``resilience/inject.py`` specs (each fires once), so
the whole suite is reproducible — no sleeps-and-hope scheduling.
"""

import json
import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from trpo_tpu.agent import TRPOAgent
from trpo_tpu.config import TRPOConfig
from trpo_tpu.obs import EventBus
from trpo_tpu.resilience import (
    FaultInjector,
    Preempted,
    RecoveryPolicy,
    TrainingDiverged,
    parse_fault_specs,
)


def _recording_bus():
    events = []
    return EventBus(lambda rec: events.append(rec)), events


def _tree_equal(a, b):
    def raw(x):
        if hasattr(x, "dtype") and jax.dtypes.issubdtype(
            x.dtype, jax.dtypes.prng_key
        ):
            x = jax.random.key_data(x)
        return np.asarray(x)

    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for xa, xb in zip(la, lb):
        np.testing.assert_array_equal(raw(xa), raw(xb))


class _BusTelemetry:
    """Minimal stand-in threading only a bus through learn()."""

    profile_dir = None

    def __init__(self, bus):
        self.bus = bus

    def start_run(self, *a, **k):
        pass

    def mark_steady(self):
        pass

    def on_iteration(self, i, stats):
        pass

    def observe_drain(self, *a):
        pass

    def profile_tick(self, *a, **k):
        pass

    def finish_run(self, timer=None):
        pass


def _row_recorder(logger):
    rows = []
    orig = logger.log
    logger.log = lambda i, s: (rows.append((i, dict(s))), orig(i, s))[0]
    return rows


# ---------------------------------------------------------------------------
# fault-spec parsing
# ---------------------------------------------------------------------------


def test_parse_fault_specs_roundtrip():
    specs = parse_fault_specs(
        "kill_worker@step=3:worker=1; hang_worker@step=5;"
        "delay_step@step=2:seconds=0.5; nan_update@iter=4; sigterm@iter=9"
    )
    kinds = [s.kind for s in specs]
    assert kinds == [
        "kill_worker", "hang_worker", "delay_step", "nan_update", "sigterm"
    ]
    assert specs[0].worker == 1 and specs[0].at == 3
    assert specs[2].seconds == 0.5
    # str() round-trips through the parser
    again = parse_fault_specs(";".join(str(s) for s in specs))
    assert again == specs


@pytest.mark.parametrize("bad", [
    "explode@iter=1",          # unknown kind
    "nan_update@step=1",       # wrong trigger key
    "kill_worker@worker=0",    # missing trigger
    "nan_update@iter=0",       # out of range
    "kill_worker@step=2:pid=9",  # unknown key
    "",                        # empty
])
def test_parse_fault_specs_rejects(bad):
    with pytest.raises(ValueError):
        parse_fault_specs(bad)


def test_config_validates_fault_spec_at_construction():
    with pytest.raises(ValueError):
        TRPOConfig(inject_faults="explode@iter=1")
    with pytest.raises(ValueError):
        TRPOConfig(recover_on_nan="maybe")
    with pytest.raises(ValueError):
        TRPOConfig(on_preempt="pray")


def test_config_rejects_negative_timeout_and_backoff():
    """A negative env_step_timeout would make every reply gather 'time
    out' instantly and silently degrade the whole pool — reject it at
    construction like the other resilience knobs. 0/None stay valid
    (= wait forever)."""
    with pytest.raises(ValueError):
        TRPOConfig(env_step_timeout=-1.0)
    with pytest.raises(ValueError):
        TRPOConfig(worker_backoff=-0.5)
    TRPOConfig(env_step_timeout=0.0)
    TRPOConfig(env_step_timeout=None)


# ---------------------------------------------------------------------------
# worker death detection + supervision (needs gymnasium worker pools)
# ---------------------------------------------------------------------------

gym = pytest.importorskip("gymnasium")

from trpo_tpu.envs.proc_env import ProcVecEnv, WorkerDiedError  # noqa: E402
from trpo_tpu.resilience.supervisor import (  # noqa: E402
    SupervisedEnv,
    SupervisionConfig,
)

ENV = "CartPole-v1"


def _actions(env, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, env.action_spec.n, size=env.n_envs)


@pytest.mark.slow
def test_killed_worker_raises_worker_died_not_hang():
    """Satellite 1: a worker killed mid-episode must surface as a
    WorkerDiedError naming the worker (not hang host_step forever)."""
    env = ProcVecEnv(ENV, n_envs=2, seed=3, n_workers=2, step_timeout=30)
    try:
        env.host_step(_actions(env))
        os.kill(env._procs[0].pid, signal.SIGKILL)
        env._procs[0].join(timeout=10)
        with pytest.raises(WorkerDiedError) as ei:
            env.host_step(_actions(env, seed=1))
        assert ei.value.workers == [0]
        assert ei.value.last_action is not None
        assert "worker" in str(ei.value).lower()
    finally:
        env.close()


@pytest.mark.slow
def test_hung_worker_times_out():
    """SIGSTOP (alive but silent) trips the step_timeout path."""
    env = ProcVecEnv(ENV, n_envs=2, seed=3, n_workers=2, step_timeout=1.0)
    try:
        env.host_step(_actions(env))
        os.kill(env._procs[1].pid, signal.SIGSTOP)
        with pytest.raises(WorkerDiedError) as ei:
            env.host_step(_actions(env, seed=1))
        assert ei.value.kind == "timeout"
        assert 1 in ei.value.workers
    finally:
        env.close()


@pytest.mark.slow
def test_supervised_restart_continues_stepping():
    """Supervision revives a killed worker and the step RETRIES: the
    restarted slice restarts its episodes (running stats zeroed), the
    surviving slice keeps stepping, and a worker_restart health event
    lands on the bus."""
    bus, events = _recording_bus()
    raw = ProcVecEnv(ENV, n_envs=2, seed=3, n_workers=2, step_timeout=30)
    env = SupervisedEnv(
        raw, SupervisionConfig(max_worker_restarts=2, backoff_base=0.01),
        bus=bus,
    )
    try:
        for _ in range(3):
            env.host_step(_actions(env))
        os.kill(raw._procs[0].pid, signal.SIGKILL)
        raw._procs[0].join(timeout=10)
        out = env.host_step(_actions(env, seed=1))
        assert out[0].shape == (2,) + raw.obs_shape
        assert np.all(np.isfinite(out[0]))
        # episode-restart semantics for the revived slice only
        assert raw._running_lengths[0] <= 1
        assert raw._running_lengths[1] >= 4
        assert env.restarts == {0: 1}
        checks = [e["check"] for e in events if e["kind"] == "health"]
        assert "worker_restart" in checks
        # and the pool keeps working afterwards
        for _ in range(3):
            env.host_step(_actions(env, seed=2))
    finally:
        env.close()


@pytest.mark.slow
def test_supervised_degrades_to_in_process_slice():
    """Past max_worker_restarts the slice re-hosts IN-PROCESS: stepping
    continues (correct data, no process parallelism), worker_degraded is
    emitted, and snapshots still cover all envs."""
    bus, events = _recording_bus()
    raw = ProcVecEnv(ENV, n_envs=2, seed=3, n_workers=2, step_timeout=30)
    env = SupervisedEnv(
        raw, SupervisionConfig(max_worker_restarts=0, backoff_base=0.01),
        bus=bus,
    )
    try:
        env.host_step(_actions(env))
        os.kill(raw._procs[1].pid, signal.SIGKILL)
        raw._procs[1].join(timeout=10)
        out = env.host_step(_actions(env, seed=1))
        assert np.all(np.isfinite(out[0]))
        assert env.degraded_workers == (1,)
        assert raw.is_local_worker(1)
        checks = [e["check"] for e in events if e["kind"] == "health"]
        assert "worker_degraded" in checks
        # full surface still works over the mixed proc/local pool
        snap = env.env_state_snapshot()
        assert len(snap["sims"]) == 2
        env.reset_all(seed=11)
        for _ in range(3):
            env.host_step(_actions(env, seed=2))
    finally:
        env.close()


def test_restart_budget_resets_after_heal_window():
    """A revival that holds past heal_window is not a FAILED revival:
    the worker's budget resets on its next death, so rare isolated
    crashes over a long run never accumulate into degradation — only a
    crash-looping worker (deaths inside the window) degrades."""

    class _FakePool:
        env_id = "fake"
        n_workers = 2

        def __init__(self):
            self.restarted = []

        def restart_worker(self, w, local=False):
            self.restarted.append((w, local))

    pool = _FakePool()
    env = SupervisedEnv(
        pool,
        SupervisionConfig(
            max_worker_restarts=1, backoff_base=0.0, heal_window=60.0
        ),
    )
    err = WorkerDiedError(0, "fake")
    env._revive(err)
    assert env.restarts == {0: 1}
    # death long after the revival: budget resets, restarts again
    env._last_restart[0] -= 120.0
    env._revive(err)
    assert env.restarts == {0: 1}
    assert pool.restarted == [(0, False), (0, False)]
    assert env.degraded_workers == ()
    # death INSIDE the window: the revival failed — budget burns
    # through and the slice degrades
    env._revive(err)
    assert pool.restarted[-1] == (0, True)
    assert env.degraded_workers == (0,)


@pytest.mark.slow
def test_injected_kill_through_agent_rollout():
    """End-to-end: a kill_worker fault injected mid-rollout through the
    agent's supervised env — training completes, the fault and the
    restart both land on the bus."""
    bus, events = _recording_bus()
    cfg = TRPOConfig(
        env="gymproc:" + ENV,
        n_iterations=2,
        batch_timesteps=32,
        n_envs=2,
        env_step_timeout=30,
        worker_backoff=0.01,
        inject_faults="kill_worker@step=5:worker=0",
    )
    agent = TRPOAgent(cfg.env, cfg)
    try:
        final = agent.learn(telemetry=_BusTelemetry(bus))
        assert int(final.iteration) == 2
        kinds = [(e["kind"], e.get("check") or e.get("fault"))
                 for e in events]
        assert ("fault_injected", "kill_worker") in kinds
        assert ("health", "worker_restart") in kinds
    finally:
        agent.env.close()


# ---------------------------------------------------------------------------
# NaN recovery (device env — no gymnasium needed, but grouped here)
# ---------------------------------------------------------------------------


def _tiny_cfg(**kw):
    base = dict(n_iterations=4, batch_timesteps=64, n_envs=4, seed=7)
    base.update(kw)
    return TRPOConfig(**base)


@pytest.mark.slow  # tier-1 budget guard (ISSUE 7): bit-exactness leg;
# test_recovery_emits_events_and_counts stays the fast representative
def test_nan_recovery_bit_exact_continuation():
    """The acceptance pin: a NaN-poisoned iteration is detected, the
    last-good state restored, the batch skipped — and the continuation is
    BIT-EXACT vs a run that was never faulted (device env: the retried
    iteration re-runs the same program on the restored state)."""
    from trpo_tpu.utils.metrics import StatsLogger

    def run(fault):
        cfg = _tiny_cfg(
            recover_on_nan="restore",
            inject_faults=fault,
        ) if fault else _tiny_cfg()
        agent = TRPOAgent("cartpole", cfg)
        logger = StatsLogger()
        rows = _row_recorder(logger)
        final = agent.learn(logger=logger)
        return final, rows

    clean_final, clean_rows = run(None)
    fault_final, fault_rows = run("nan_update@iter=2")

    # the poisoned row is logged (iteration 2, NaN entropy), then 2 re-runs
    assert [i for i, _ in fault_rows] == [1, 2, 2, 3, 4]
    poisoned = fault_rows[1][1]
    assert poisoned["entropy"] != poisoned["entropy"]  # NaN
    finite = [(i, r) for i, r in fault_rows
              if r["entropy"] == r["entropy"]]
    assert [i for i, _ in finite] == [1, 2, 3, 4]
    numeric = (
        "entropy", "surrogate_loss", "kl_old_new", "grad_norm",
        "step_norm", "mean_episode_reward", "vf_loss",
    )
    for (ic, rc), (irf, rf) in zip(clean_rows, finite):
        assert ic == irf
        for key in numeric:
            vc, vf = rc[key], rf[key]
            assert (vc == vf) or (vc != vc and vf != vf), (
                f"iteration {ic} field {key}: clean {vc} != faulted {vf}"
            )
    _tree_equal(clean_final, fault_final)


@pytest.mark.slow  # tier-1 budget guard (ISSUE 7)
def test_nan_recovery_fused_chunk_no_duplicate_rows():
    """NaN inside a FUSED device chunk: only the first nonfinite row of
    the failed chunk is logged — the re-run's rows are the canonical
    ones, and logging the failed attempt's other rows would double-fold
    their episodes into reward_running (and let a clean prefix reset
    the consecutive-recovery counter). Continuation stays bit-exact vs
    a clean fused run."""
    from trpo_tpu.utils.metrics import StatsLogger

    def run(fault):
        kw = dict(fuse_iterations=2)
        if fault:
            kw.update(recover_on_nan="restore", inject_faults=fault)
        cfg = _tiny_cfg(**kw)
        agent = TRPOAgent("cartpole", cfg)
        logger = StatsLogger()
        rows = _row_recorder(logger)
        final = agent.learn(logger=logger)
        return final, rows

    clean_final, clean_rows = run(None)
    fault_final, fault_rows = run("nan_update@iter=3")
    assert [i for i, _ in clean_rows] == [1, 2, 3, 4]
    # the poison lands at the [3,4] chunk boundary, so BOTH its rows
    # are nonfinite — exactly one (iteration 3) is logged, then the
    # chunk re-runs clean from its snapshot
    assert [i for i, _ in fault_rows] == [1, 2, 3, 3, 4]
    poisoned = fault_rows[2][1]
    assert poisoned["entropy"] != poisoned["entropy"]  # NaN
    finite = [(i, r) for i, r in fault_rows
              if r["entropy"] == r["entropy"]]
    assert [i for i, _ in finite] == [1, 2, 3, 4]
    for (ic, rc), (irf, rf) in zip(clean_rows, finite):
        assert ic == irf
        for key in ("entropy", "surrogate_loss", "kl_old_new",
                    "grad_norm", "step_norm", "vf_loss"):
            vc, vf = rc[key], rf[key]
            assert (vc == vf) or (vc != vc and vf != vf), (
                f"iteration {ic} field {key}: clean {vc} != faulted {vf}"
            )
    _tree_equal(clean_final, fault_final)


def test_recovery_emits_events_and_counts():
    bus, events = _recording_bus()
    cfg = _tiny_cfg(
        recover_on_nan="restore", inject_faults="nan_update@iter=3"
    )
    agent = TRPOAgent("cartpole", cfg)
    final = agent.learn(telemetry=_BusTelemetry(bus))
    assert int(final.iteration) == 4
    recs = [e for e in events if e["kind"] == "recovery"]
    assert len(recs) == 1
    assert recs[0]["reason"] in ("nan_entropy", "nan_guard")
    assert recs[0]["iteration"] == 3
    faults = [e for e in events if e["kind"] == "fault_injected"]
    assert len(faults) == 1 and faults[0]["fault"] == "nan_update"


def test_unfired_fault_warns_at_completion():
    """A chaos spec that never triggers (here: nan_update far past the
    iteration budget) must not let the run green-light silently — a
    fault_unfired health warning lands on the bus at completion."""
    bus, events = _recording_bus()
    cfg = _tiny_cfg(inject_faults="nan_update@iter=50")
    agent = TRPOAgent("cartpole", cfg)
    final = agent.learn(telemetry=_BusTelemetry(bus))
    assert int(final.iteration) == 4
    warns = [e for e in events
             if e["kind"] == "health" and e["check"] == "fault_unfired"]
    assert len(warns) == 1
    assert warns[0]["data"]["unfired"] == ["nan_update@iter=50"]


def test_default_abort_path_unchanged():
    """recover_on_nan='off' (default): an injected NaN still raises the
    historical FloatingPointError — the opt-in leaves the abort path
    alone."""
    cfg = _tiny_cfg(inject_faults="nan_update@iter=2")
    agent = TRPOAgent("cartpole", cfg)
    with pytest.raises(FloatingPointError):
        agent.learn()


def test_recovery_policy_aborts_after_max_consecutive():
    cfg = _tiny_cfg(recover_on_nan="restore", max_recoveries=2)
    policy = RecoveryPolicy(cfg)
    state = TRPOAgent("cartpole", cfg).init_state()
    for n in range(2):
        policy.snapshot(n + 1, state)
        policy.flag(n + 1, "nan_entropy")
        _, state = policy.recover()
    policy.snapshot(3, state)
    policy.flag(3, "nan_entropy")
    with pytest.raises(TrainingDiverged):
        policy.recover()
    # a clean row AT the recovered iteration resets the counter...
    policy2 = RecoveryPolicy(cfg)
    policy2.snapshot(1, state)
    policy2.snapshot(3, state)
    policy2.flag(3, "nan_guard")
    policy2.recover()
    # ...but a clean row BEFORE it does not: a fused chunk's re-run
    # reproduces its clean prefix bit-exactly, and letting that prefix
    # reset the counter would turn a deterministic mid-chunk NaN into
    # an infinite restore loop instead of TrainingDiverged
    policy2.mark_clean(2)
    assert policy2.consecutive == 1
    policy2.mark_clean(3)
    assert policy2.consecutive == 0


def test_descendant_rows_while_flag_pending_do_not_reset_counter():
    """A finite row drained between flag() and recover() descends from
    the state being rewound (the async driver's detection lag): letting
    it reset the consecutive counter would keep a state-deterministic
    NaN restoring forever instead of reaching TrainingDiverged."""
    cfg = _tiny_cfg(recover_on_nan="restore", max_recoveries=2)
    policy = RecoveryPolicy(cfg)
    state = TRPOAgent("cartpole", cfg).init_state()
    policy.snapshot(1, state)
    for _ in range(2):
        policy.flag(1, "nan_guard")
        policy.mark_clean(2)  # descendant drains before the driver acts
        _, state = policy.recover()
        policy.snapshot(1, state)
    assert policy.consecutive == 2
    policy.flag(1, "nan_guard")
    with pytest.raises(TrainingDiverged):
        policy.recover()


def test_injector_skips_degraded_worker_and_reports_unfired():
    """An env-level fault aimed at a worker already degraded to the
    in-process fallback has nothing to signal: the spec must stay
    UNFIRED (so the end-of-run warning reports it) rather than be
    silently swallowed as exercised."""

    class _DegradedPool:
        _procs = [None]  # slice 0 re-hosted in-process

    inj = FaultInjector.from_spec("kill_worker@step=3:worker=0")
    inj.on_env_step(3, _DegradedPool())
    assert not inj.all_fired
    assert inj.unfired == ("kill_worker@step=3:worker=0",)


def test_recovery_escalates_adaptive_damping():
    cfg = _tiny_cfg(
        recover_on_nan="restore", adaptive_damping=True, cg_damping=0.1
    )
    policy = RecoveryPolicy(cfg)
    state = TRPOAgent("cartpole", cfg).init_state()
    assert state.cg_damping is not None
    policy.snapshot(1, state)
    policy.flag(1, "nan_guard")
    _, restored = policy.recover()
    assert float(restored.cg_damping) == pytest.approx(
        0.1 * cfg.damping_grow
    )


# ---------------------------------------------------------------------------
# preemption-safe shutdown
# ---------------------------------------------------------------------------


@pytest.mark.slow  # tier-1 budget guard (ISSUE 7): in-process resume
# leg; test_cli_exits_with_requeue_code stays the fast e2e
# representative of the preemption path
def test_sigterm_checkpoints_and_resumes(tmp_path):
    """SIGTERM mid-run: orderly shutdown writes a final checkpoint +
    raises Preempted with the requeue exit code; a resume loses NOTHING
    (≤ checkpoint_every was the bound, 0 is the actual)."""
    from trpo_tpu.utils.checkpoint import Checkpointer

    cfg = _tiny_cfg(
        n_iterations=6,
        checkpoint_every=2,
        inject_faults="sigterm@iter=3",
    )
    agent = TRPOAgent("cartpole", cfg)
    ck = Checkpointer(str(tmp_path / "ck"))
    with pytest.raises(Preempted) as ei:
        agent.learn(checkpointer=ck)
    # the signal lands before iteration 3 runs; the guard notices at the
    # top of iteration 4 — the final save covers everything completed
    assert ei.value.step == 3
    assert ei.value.exit_code == cfg.requeue_exit_code == 75
    assert ck.latest_step() == 3

    agent2 = TRPOAgent("cartpole", _tiny_cfg(n_iterations=6))
    state = ck.restore(agent2.init_state())
    assert int(state.iteration) == 3
    final = agent2.learn(n_iterations=1, state=state)
    assert int(final.iteration) == 4
    ck.close()


def test_cli_exits_with_requeue_code(tmp_path):
    from trpo_tpu.train import main

    code = main([
        "--preset", "cartpole", "--iterations", "6",
        "--batch-timesteps", "64", "--n-envs", "4", "--platform", "cpu",
        "--checkpoint-dir", str(tmp_path / "ck"), "--checkpoint-every", "2",
        "--inject-faults", "sigterm@iter=2",
    ])
    assert code == 75


def test_on_preempt_ignore_keeps_abort_semantics():
    """cfg.on_preempt='ignore': the guard is inert — SIGTERM keeps its
    default disposition (kills the process), so we only check the guard
    never installs handlers."""
    from trpo_tpu.resilience import PreemptionGuard

    prev = signal.getsignal(signal.SIGTERM)
    with PreemptionGuard(enabled=False) as g:
        assert signal.getsignal(signal.SIGTERM) is prev
        assert not g.triggered
    assert signal.getsignal(signal.SIGTERM) is prev


# ---------------------------------------------------------------------------
# checkpoint save-integrity gate (kill -9 mid-save)
# ---------------------------------------------------------------------------


def test_torn_save_never_selected_and_pruned(tmp_path):
    """A step whose completion marker is missing (= the save was torn by
    kill -9) must never be latest_step(); restore prunes it and reads the
    previous complete step."""
    from trpo_tpu.utils.checkpoint import Checkpointer

    bus, events = _recording_bus()
    cfg = _tiny_cfg()
    agent = TRPOAgent("cartpole", cfg)
    state = agent.init_state()
    ck = Checkpointer(str(tmp_path / "ck"), bus=bus)
    ck.save(2, state)
    state2, _ = agent.run_iteration(state)
    ck.save(4, state2)
    assert ck.latest_step() == 4
    # simulate the kill -9: the orbax step exists, the marker does not
    os.remove(ck._marker_path(4))
    assert ck.latest_step() == 2
    restored = ck.restore(agent.init_state())
    assert int(restored.iteration) == 0  # step 2 held the initial state
    assert 4 not in list(ck.manager.all_steps())
    checks = [e["check"] for e in events if e["kind"] == "health"]
    assert "checkpoint_incomplete" in checks
    ck.close()


def test_marker_files_written_and_pruned_with_steps(tmp_path):
    from trpo_tpu.utils.checkpoint import Checkpointer

    cfg = _tiny_cfg()
    agent = TRPOAgent("cartpole", cfg)
    state = agent.init_state()
    ck = Checkpointer(str(tmp_path / "ck"), max_to_keep=2)
    for step in (1, 2, 3):
        ck.save(step, state)
        state, _ = agent.run_iteration(state)
    # max_to_keep=2 garbage-collected step 1 — its marker too
    assert not os.path.exists(ck._marker_path(1))
    assert os.path.exists(ck._marker_path(2))
    assert os.path.exists(ck._marker_path(3))
    assert ck.latest_step() == 3
    ck.close()


def test_legacy_directory_without_markers_still_restores(tmp_path):
    """Pre-round-7 checkpoints have no markers at all: trust them (the
    gate only distrusts unmarked steps NEWER than the newest marker)."""
    from trpo_tpu.utils.checkpoint import Checkpointer

    cfg = _tiny_cfg()
    agent = TRPOAgent("cartpole", cfg)
    ck = Checkpointer(str(tmp_path / "ck"))
    ck.save(5, agent.init_state())
    # a real legacy directory predates BOTH the marker and the
    # markers-enabled sentinel — remove both to simulate one
    os.remove(ck._marker_path(5))
    os.remove(ck._sentinel_path())
    assert ck.latest_step() == 5
    restored = ck.restore(agent.init_state())
    assert int(restored.iteration) == 0
    ck.close()


# ---------------------------------------------------------------------------
# corrupt vs missing host-env sidecar (satellite 6)
# ---------------------------------------------------------------------------


def test_torn_first_save_in_fresh_directory_not_trusted(tmp_path):
    """kill -9 through the very FIRST save of a fresh directory leaves
    zero markers — which must read as "every save here tore", not as a
    trusted legacy directory (the sentinel written at init is what
    distinguishes the two)."""
    from trpo_tpu.utils.checkpoint import Checkpointer

    cfg = _tiny_cfg()
    agent = TRPOAgent("cartpole", cfg)
    ck = Checkpointer(str(tmp_path / "ck"))
    ck.save(2, agent.init_state())
    os.remove(ck._marker_path(2))  # the tear: orbax step, no marker
    assert ck.latest_step() is None
    with pytest.raises(FileNotFoundError):
        ck.restore(agent.init_state())
    assert 2 not in list(ck.manager.all_steps())  # pruned, not shadowed
    ck.close()


def test_corrupt_sidecar_surfaces_health_event(tmp_path):
    from trpo_tpu.utils.checkpoint import Checkpointer

    bus, events = _recording_bus()
    cfg = _tiny_cfg()
    agent = TRPOAgent("cartpole", cfg)
    ck = Checkpointer(str(tmp_path / "ck"), bus=bus)
    ck.save(1, agent.init_state())

    # missing sidecar: silent None (the documented fallback)
    assert ck.restore_host_env(1) is None
    assert not [e for e in events if e["kind"] == "health"]

    # corrupt sidecar: still None, but LOUD
    with open(ck._aux_path(1), "wb") as f:
        f.write(b"this is not an npz archive")
    assert ck.restore_host_env(1) is None
    checks = [e["check"] for e in events if e["kind"] == "health"]
    assert checks == ["host_env_sidecar_corrupt"]
    ck.close()


# ---------------------------------------------------------------------------
# event-log chaos contract (validate_events fault matching)
# ---------------------------------------------------------------------------


def _write_events(path, records):
    from trpo_tpu.obs.events import manifest_fields

    base = {"v": 1, "t": 0.0}
    rows = [
        {**base, "kind": "run_manifest", **manifest_fields()},
    ] + [{**base, **r} for r in records]
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


def test_validator_requires_matching_recovery(tmp_path):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "validate_events",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "validate_events.py"),
    )
    ve = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ve)

    fault = {"kind": "fault_injected", "fault": "nan_update", "at": 2,
             "spec": "nan_update@iter=2"}
    recovery = {"kind": "recovery", "action": "restore",
                "reason": "nan_entropy", "iteration": 2}
    perturb = {"kind": "fault_injected", "fault": "delay_step", "at": 1,
               "spec": "delay_step@step=1:seconds=0.5"}

    unmatched = tmp_path / "unmatched.jsonl"
    _write_events(unmatched, [fault])
    errs = ve.validate_file(str(unmatched))
    assert any("no matching detection/recovery" in e for e in errs)

    matched = tmp_path / "matched.jsonl"
    _write_events(matched, [fault, recovery, perturb])
    assert ve.validate_file(str(matched)) == []

    killfault = {"kind": "fault_injected", "fault": "kill_worker", "at": 3,
                 "spec": "kill_worker@step=3"}
    restart = {"kind": "health", "check": "worker_restart",
               "level": "warn", "message": "restarted"}
    kill_ok = tmp_path / "kill.jsonl"
    _write_events(kill_ok, [killfault, restart])
    assert ve.validate_file(str(kill_ok)) == []
    kill_bad = tmp_path / "kill_bad.jsonl"
    _write_events(kill_bad, [killfault])
    assert ve.validate_file(str(kill_bad)) != []


# ---------------------------------------------------------------------------
# async driver: recovery without racing the checkpoint (satellite 2)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_async_driver_retries_nan_in_final_iteration():
    """A NaN that only surfaces in the FINAL drain (poison at the last
    iteration) must still be retried: the run completes its full
    iteration budget — the serial driver's semantics — instead of
    restoring and returning one update short."""
    bus, events = _recording_bus()
    cfg = TRPOConfig(
        env="gym:" + ENV,
        n_iterations=3,
        batch_timesteps=32,
        n_envs=2,
        seed=5,
        host_async_pipeline=True,
        recover_on_nan="restore",
        inject_faults="nan_update@iter=3",
    )
    agent = TRPOAgent(cfg.env, cfg)
    try:
        final = agent.learn(telemetry=_BusTelemetry(bus))
        assert int(final.iteration) == 3
        recs = [e for e in events if e["kind"] == "recovery"]
        assert len(recs) == 1 and recs[0]["iteration"] == 3
    finally:
        agent.env.close()


@pytest.mark.slow
def test_async_driver_nan_recovery(tmp_path):
    """The async pipeline detects the poisoned row on the DRAIN thread —
    after the next iteration's phase A may have been dispatched. Recovery
    must still rewind to the flagged iteration, never checkpoint the
    poisoned state, and finish the full budget."""
    bus, events = _recording_bus()
    cfg = TRPOConfig(
        env="gym:" + ENV,
        n_iterations=4,
        batch_timesteps=32,
        n_envs=2,
        seed=5,
        host_async_pipeline=True,
        recover_on_nan="restore",
        checkpoint_every=2,
        inject_faults="nan_update@iter=2",
    )
    from trpo_tpu.utils.checkpoint import Checkpointer

    agent = TRPOAgent(cfg.env, cfg)
    ck = Checkpointer(str(tmp_path / "ck"))
    cb_finite = []

    def _cb(st, _stats):
        # inspect at delivery time: the driver only keeps the state's
        # buffers alive for the duration of the callback (the next
        # update donates them afterwards)
        cb_finite.append(
            all(
                bool(jnp.all(jnp.isfinite(leaf)))
                for leaf in jax.tree_util.tree_leaves(st.policy_params)
            )
        )

    try:
        final = agent.learn(
            checkpointer=ck, telemetry=_BusTelemetry(bus), callback=_cb
        )
        assert int(final.iteration) == 4
        recs = [e for e in events if e["kind"] == "recovery"]
        assert len(recs) == 1 and recs[0]["iteration"] == 2
        # the user callback never saw the poisoned state (or any
        # descendant of it): every delivered state was finite
        assert cb_finite and all(cb_finite)
        # every persisted step restores finite params (the poisoned
        # state never reached a save)
        for step in ck.manager.all_steps():
            restored = ck.restore(agent.init_state(), step=step)
            for leaf in jax.tree_util.tree_leaves(
                restored.policy_params
            ):
                assert bool(jnp.all(jnp.isfinite(leaf)))
    finally:
        ck.close()
        agent.env.close()
