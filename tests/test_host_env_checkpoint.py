"""Host-simulator checkpoint fidelity (VERDICT r1 item 6).

For gym:/native: envs the simulator lives outside TrainState; round 1
silently restarted episodes on resume. Now the adapters expose
``env_state_snapshot``/``env_state_restore`` and the Checkpointer stores
them as a sidecar next to the Orbax step: EXACT resume for ``native:``
envs (state/step/RNG buffers are host-side NumPy), best-effort for
``gym:`` (MuJoCo qpos/qvel/time, classic-control ``state``, TimeLimit
counters), documented episode-restart for opaque backends.
"""

import importlib.util

import numpy as np
import pytest

from trpo_tpu.agent import TRPOAgent
from trpo_tpu.config import TRPOConfig
from trpo_tpu import envs
from trpo_tpu.envs import native
from trpo_tpu.utils.checkpoint import Checkpointer

_has = lambda m: importlib.util.find_spec(m) is not None

needs_native = pytest.mark.skipif(
    not native.native_available(), reason="native env library unavailable"
)
needs_gym = pytest.mark.skipif(
    not _has("gymnasium"), reason="gymnasium unavailable"
)

_TINY = dict(
    n_envs=4, batch_timesteps=64, cg_iters=3, vf_train_steps=3,
    policy_hidden=(16,), vf_hidden=(16,), seed=9,
)


@needs_native
def test_native_resume_is_bitwise_identical(tmp_path):
    """Full resume: TrainState (Orbax) + env sidecar → the continued run
    is bit-identical to the uninterrupted one."""
    cfg = TRPOConfig(**_TINY)
    a = TRPOAgent("native:cartpole", cfg)
    state = a.init_state(seed=1)
    state, _ = a.run_iteration(state)
    snap = a.snapshot_host_env()

    ck = Checkpointer(str(tmp_path / "ck"))
    try:
        ck.save(int(state.iteration), state)
        ck.save_host_env(int(state.iteration), snap)

        # uninterrupted continuation
        cont, stats_a = a.run_iteration(state)

        # resumed continuation in a FRESH process-equivalent (new agent,
        # new adapter)
        b = TRPOAgent("native:cartpole", cfg)
        restored = ck.restore(b.init_state())
        b.restore_host_env(ck.restore_host_env())
        cont_b, stats_b = b.run_iteration(restored)
    finally:
        ck.close()
    for k in stats_a:
        np.testing.assert_array_equal(
            np.asarray(stats_a[k]), np.asarray(stats_b[k]), err_msg=k
        )
    np.testing.assert_array_equal(
        np.asarray(cont.total_episodes), np.asarray(cont_b.total_episodes)
    )


@needs_native
def test_native_snapshot_restores_mid_episode_counters():
    env = native.NativeVecEnv("cartpole", n_envs=3, seed=2)
    for _ in range(5):
        env.host_step(np.zeros(3, np.int64))
    snap = env.env_state_snapshot()
    obs_at_snap = env.current_obs()
    run_len = env._running_lengths.copy()

    for _ in range(4):
        env.host_step(np.ones(3, np.int64))

    env.env_state_restore(snap)
    np.testing.assert_array_equal(env.current_obs(), obs_at_snap)
    np.testing.assert_array_equal(env._running_lengths, run_len)
    # deterministic continuation: same actions → same observations
    o1, r1, t1, tr1, f1 = env.host_step(np.ones(3, np.int64))
    env.env_state_restore(snap)
    o2, r2, t2, tr2, f2 = env.host_step(np.ones(3, np.int64))
    np.testing.assert_array_equal(o1, o2)
    np.testing.assert_array_equal(r1, r2)


@needs_gym
def test_gym_classic_control_sim_state_restores():
    env = envs.make("gym:CartPole-v1", n_envs=2, seed=4)
    acts = np.zeros(2, np.int64)
    for _ in range(3):
        env.host_step(acts)
    snap = env.env_state_snapshot()
    o1 = env.host_step(acts)[0].copy()
    env.env_state_restore(snap)
    o2 = env.host_step(acts)[0].copy()
    np.testing.assert_allclose(o1, o2, rtol=0, atol=0)
    env.close()


@needs_gym
@pytest.mark.skipif(not _has("mujoco"), reason="mujoco unavailable")
def test_gym_mujoco_qpos_qvel_restore():
    env = envs.make("gym:HalfCheetah-v4", n_envs=1, seed=0)
    a = np.zeros((1, env.action_spec.dim), np.float32) \
        if hasattr(env.action_spec, "dim") else np.zeros((1, 6), np.float32)
    for _ in range(3):
        env.host_step(a)
    snap = env.env_state_snapshot()
    assert snap["sims"][0]["backend"] == "mujoco"
    o1 = env.host_step(a)[0].copy()
    env.env_state_restore(snap)
    o2 = env.host_step(a)[0].copy()
    np.testing.assert_allclose(o1, o2, atol=1e-10)
    env.close()


@needs_native
def test_learn_writes_sidecar_and_prunes(tmp_path):
    cfg = TRPOConfig(checkpoint_every=1, n_iterations=2, **_TINY)
    a = TRPOAgent("native:cartpole", cfg)
    ck = Checkpointer(str(tmp_path / "ck"))
    try:
        a.learn(n_iterations=2, checkpointer=ck)
        snap = ck.restore_host_env()
        assert snap is not None and snap["kind"] == "cartpole"
    finally:
        ck.close()


def test_device_env_has_no_sidecar():
    a = TRPOAgent("cartpole", TRPOConfig(**_TINY))
    assert a.snapshot_host_env() is None
    a.restore_host_env(None)  # no-op


@needs_gym
def test_opaque_backend_restore_restarts_cleanly():
    """Envs whose simulator exposes no state (sims=None) must restart on
    restore with the RESET obs and zeroed counters — not the dead
    pre-checkpoint episode's cache (round-2 review finding)."""
    env = envs.make("gym:CartPole-v1", n_envs=2, seed=7)
    for _ in range(3):
        env.host_step(np.zeros(2, np.int64))
    snap = env.env_state_snapshot()
    snap["sims"] = [None, None]  # simulate an opaque backend
    env.env_state_restore(snap)
    assert np.all(env._running_lengths == 0)
    assert np.all(env._running_returns == 0.0)
    for i in range(2):
        np.testing.assert_allclose(
            env.current_obs()[i],
            np.asarray(env.envs[i].unwrapped.state, np.float32),
        )
    env.close()


@needs_native
def test_restore_rejects_n_envs_mismatch():
    src = native.NativeVecEnv("cartpole", n_envs=3, seed=1)
    snap = src.env_state_snapshot()
    dst = native.NativeVecEnv("cartpole", n_envs=4, seed=1)
    with pytest.raises(ValueError, match="n_envs"):
        dst.env_state_restore(snap)


@needs_gym
def test_gym_restore_rejects_n_envs_mismatch():
    src = envs.make("gym:CartPole-v1", n_envs=2, seed=1)
    snap = src.env_state_snapshot()
    dst = envs.make("gym:CartPole-v1", n_envs=3, seed=1)
    with pytest.raises(ValueError, match="n_envs"):
        dst.env_state_restore(snap)
    src.close(); dst.close()


# -- pickle-free sidecar format (ADVICE r2) --------------------------------


@needs_native
def test_sidecar_is_pickle_free_npz(tmp_path):
    """The sidecar on disk must be loadable with allow_pickle=False — an
    untrusted checkpoint dir can never execute code on restore."""
    env = native.NativeVecEnv("cartpole", n_envs=2, seed=3)
    for _ in range(4):
        env.host_step(np.zeros(2, np.int64))
    snap = env.env_state_snapshot()
    ck = Checkpointer(str(tmp_path / "ck"))
    try:
        ck.save_host_env(7, snap)
        path = tmp_path / "ck" / "host_env_7.npz"
        assert path.exists(), "sidecar must be .npz, not .pkl"
        with np.load(path, allow_pickle=False):
            pass  # opening with pickle disabled must not raise
        back = ck.restore_host_env(7)
    finally:
        ck.close()
    assert back["kind"] == snap["kind"]
    for k in ("state", "t", "rng", "obs"):
        np.testing.assert_array_equal(back[k], snap[k])
    env.env_state_restore(back)  # adapter accepts the round-tripped form


def test_sidecar_codec_nested_and_bigints(tmp_path):
    """The codec must carry nested dict/list/None structures and
    arbitrary-precision ints (PCG64 state words exceed uint64)."""
    ck = Checkpointer(str(tmp_path / "ck"))
    snap = {
        "sims": [
            None,
            {
                "backend": "state",
                "state": np.arange(4.0),
                "elapsed": 12,
                "np_random": {
                    "bit_generator": "PCG64",
                    "state": {"state": 2**100 + 7, "inc": 2**90 + 1},
                    "has_uint32": 0,
                    "uinteger": 0,
                },
            },
        ],
        "obs": np.ones((2, 4), np.float32),
        "flag": True,
        "note": "hello",
    }
    try:
        ck.save_host_env(1, snap)
        back = ck.restore_host_env(1)
    finally:
        ck.close()
    assert back["sims"][0] is None
    assert back["sims"][1]["np_random"]["state"]["state"] == 2**100 + 7
    assert back["flag"] is True and back["note"] == "hello"
    np.testing.assert_array_equal(back["obs"], snap["obs"])


def test_sidecar_prunes_stale_tmp_and_reads_legacy_pkl(tmp_path):
    ck = Checkpointer(str(tmp_path / "ck"), allow_legacy_pickle=True)
    d = tmp_path / "ck"
    # a crash mid-save leaves a tmp: the next save must clean it up
    (d / "host_env_3.npz.tmp").write_bytes(b"partial")
    (d / "host_env_3.pkl.tmp").write_bytes(b"partial")
    # a legacy pickle sidecar from an older run must still restore
    # (behind the explicit opt-in — pickle.load can execute code)
    import pickle

    with open(d / "host_env_2.pkl", "wb") as f:
        pickle.dump({"obs": np.zeros(3)}, f)
    try:
        # legacy read works while the file exists
        legacy = ck.restore_host_env(2)
        np.testing.assert_array_equal(legacy["obs"], np.zeros(3))
        ck.save_host_env(5, {"obs": np.ones(3)})
        assert not (d / "host_env_3.npz.tmp").exists()
        assert not (d / "host_env_3.pkl.tmp").exists()
        # the legacy sidecar had no Orbax step → pruned with the rest
        assert not (d / "host_env_2.pkl").exists()
    finally:
        ck.close()


def test_legacy_pkl_refused_without_opt_in(tmp_path, capsys, monkeypatch):
    """ADVICE r3: pickle.load on a planted .pkl sidecar is an arbitrary-
    code-execution surface — the default must refuse it (episodes restart)
    and say so; the env-var opt-in re-enables it."""
    import pickle

    monkeypatch.delenv("TRPO_TPU_ALLOW_PICKLE_SIDECAR", raising=False)
    ck = Checkpointer(str(tmp_path / "ck"))
    d = tmp_path / "ck"
    with open(d / "host_env_4.pkl", "wb") as f:
        pickle.dump({"obs": np.zeros(2)}, f)
    try:
        assert ck.restore_host_env(4) is None
        err = capsys.readouterr().err
        assert "legacy .pkl" in err and "Refusing" in err
    finally:
        ck.close()

    # env-var opt-in (the constructor-flag path is covered above)
    monkeypatch.setenv("TRPO_TPU_ALLOW_PICKLE_SIDECAR", "1")
    ck2 = Checkpointer(str(tmp_path / "ck"))
    try:
        back = ck2.restore_host_env(4)
        np.testing.assert_array_equal(back["obs"], np.zeros(2))
    finally:
        ck2.close()


def test_sidecar_codec_preserves_tuples(tmp_path):
    """ADVICE r3: an adapter whose env_state_restore distinguishes tuple
    from list must see its tuples come back as tuples, not lists."""
    ck = Checkpointer(str(tmp_path / "ck"))
    snap = {
        "pair": (1, 2),
        "mixed": [(np.arange(3.0), "x"), [4, 5]],
        "nested": {"t": ("a", ("b", None))},
    }
    try:
        ck.save_host_env(1, snap)
        back = ck.restore_host_env(1)
    finally:
        ck.close()
    assert back["pair"] == (1, 2) and isinstance(back["pair"], tuple)
    assert isinstance(back["mixed"], list)
    assert isinstance(back["mixed"][0], tuple)
    np.testing.assert_array_equal(back["mixed"][0][0], np.arange(3.0))
    assert back["mixed"][1] == [4, 5] and isinstance(back["mixed"][1], list)
    assert back["nested"]["t"] == ("a", ("b", None))
    assert isinstance(back["nested"]["t"][1], tuple)


def test_sidecar_corrupt_falls_back_to_none(tmp_path, capsys):
    ck = Checkpointer(str(tmp_path / "ck"))
    (tmp_path / "ck" / "host_env_9.npz").write_bytes(b"not a zip at all")
    try:
        assert ck.restore_host_env(9) is None
    finally:
        ck.close()
    assert "unreadable" in capsys.readouterr().err


@needs_gym
def test_gym_snapshot_captures_reset_randomness():
    """Post-resume episode resets must replay the SAME randomness as the
    uninterrupted run (ADVICE r2: np_random bit-generator state rides the
    snapshot)."""
    env = envs.make("gym:CartPole-v1", n_envs=1, seed=11)
    for _ in range(3):
        env.host_step(np.zeros(1, np.int64))
    snap = env.env_state_snapshot()
    assert snap["sims"][0]["np_random"] is not None

    # uninterrupted: what obs does the next reset produce?
    o_uninterrupted, _ = env.envs[0].reset()

    # resumed: restore, then reset — must match bit-for-bit
    env.env_state_restore(snap)
    o_resumed, _ = env.envs[0].reset()
    np.testing.assert_array_equal(o_uninterrupted, o_resumed)
    env.close()
