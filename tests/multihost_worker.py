"""Worker process for the multi-host (DCN-layer) test.

Each of two processes joins a real ``jax.distributed`` cluster over
loopback (the Gloo CPU collectives backend), contributes 4 virtual CPU
devices, builds the GLOBAL 8-device mesh, and runs the SAME sharded TRPO
natural-gradient update multi-controller style: identical replicated
params, the batch constructed as a global array (each process provides
its addressable shards via ``make_array_from_callback``), cross-process
``psum``s inside the solve. Printed KL must match across processes.

Spawned by ``tests/test_multihost.py``; must force the CPU platform
BEFORE any backend touch (the machine's default platform is a
single-tenant TPU tunnel — see tests/conftest.py).
"""

import sys

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402


def main(pid: int, coord: str) -> None:
    from trpo_tpu.parallel import (
        initialize_distributed,
        make_mesh,
        make_sharded_update,
    )
    from trpo_tpu.config import TRPOConfig
    from trpo_tpu.models import DiscreteSpec, make_policy
    from trpo_tpu.trpo import TRPOBatch, standardize_advantages

    initialize_distributed(
        coordinator_address=coord, num_processes=2, process_id=pid
    )
    assert jax.device_count() == 2 * jax.local_device_count(), (
        jax.device_count(), jax.local_device_count())

    mesh = make_mesh()  # global mesh spanning both processes
    policy = make_policy((4,), DiscreteSpec(2), hidden=(8,))
    # identical on both processes (same seed) -> valid replicated input
    params = jax.tree_util.tree_map(
        np.asarray, policy.init(jax.random.key(0))
    )
    B = 64
    rng = np.random.default_rng(0)
    obs_np = rng.normal(size=(B, 4)).astype(np.float32)
    dist_np = jax.tree_util.tree_map(
        np.asarray, policy.apply(params, jnp.asarray(obs_np))
    )
    act_np = np.asarray(policy.dist.sample(
        jax.random.key(1), jax.tree_util.tree_map(jnp.asarray, dist_np)
    ))
    adv_np = np.asarray(standardize_advantages(
        jnp.asarray(rng.normal(size=(B,)).astype(np.float32)), jnp.ones(B)
    ))

    def gshard(x):
        sh = NamedSharding(mesh, P("data", *([None] * (x.ndim - 1))))
        return jax.make_array_from_callback(x.shape, sh, lambda idx: x[idx])

    batch = TRPOBatch(
        obs=gshard(obs_np),
        actions=gshard(act_np),
        advantages=gshard(adv_np),
        old_dist=jax.tree_util.tree_map(gshard, dist_np),
        weight=gshard(np.ones(B, np.float32)),
    )
    update = make_sharded_update(policy, TRPOConfig(cg_iters=5), mesh)
    _, stats = update(params, batch)
    kl = float(stats.kl)
    assert np.isfinite(kl) and bool(stats.linesearch_success)
    assert float(stats.surrogate_after) < float(stats.surrogate_before)
    # both processes print the same solve result — the test asserts
    # bitwise agreement, so print the exact bits
    print(f"MULTIHOST_OK pid={pid} kl={kl.hex()}")


if __name__ == "__main__":
    main(int(sys.argv[1]), sys.argv[2])
