"""Fused single-Pallas-kernel Gauss-Newton FVP (``ops/fused_fvp.py``).

The kernel replaces the XLA GGN matmul chain for plain-MLP Gaussian
policies (SURVEY §3.4; the Fisher the reference builds by double
backprop, ``trpo_inksci.py:56-70``).  These tests pin, in interpret mode
on the CPU mesh:

* operator parity against ``make_ggn_fvp`` (same math, same weighting,
  same damping) across activations, depths, weighted/padded batches;
* full-update equivalence: ``fvp_mode="fused"`` vs ``"ggn"`` produce the
  same accepted step;
* eligibility: explicit ``"fused"`` raises on unsupported architectures
  instead of silently falling back, and the VMEM cost model rejects
  shapes that cannot fit.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from trpo_tpu.config import TRPOConfig
from trpo_tpu.models import BoxSpec, DiscreteSpec, make_policy
from trpo_tpu.ops import flatten_params, make_ggn_fvp
from trpo_tpu.ops.fused_fvp import (
    _auto_block_rows,
    fused_fvp_supported,
    make_fused_gaussian_mlp_fvp,
)
from trpo_tpu.trpo import TRPOBatch, make_trpo_update


def _problem(hidden=(128, 128), activation="tanh", batch=300, obs_dim=11,
             act_dim=5, pad_tail=50, seed=0):
    policy = make_policy(
        (obs_dim,), BoxSpec(act_dim), hidden=hidden, activation=activation,
        compute_dtype=jnp.float32,
    )
    params = policy.init(jax.random.key(seed))
    obs = jax.random.normal(jax.random.key(1), (batch, obs_dim), jnp.float32)
    weight = jnp.concatenate(
        [jnp.ones((batch - pad_tail,)), jnp.zeros((pad_tail,))]
    )
    return policy, params, obs, weight


def _operators(policy, params, obs, weight, damping=0.1, **fused_kw):
    flat0, unravel = flatten_params(params)
    flat0 = jnp.asarray(flat0, jnp.float32)

    ggn = make_ggn_fvp(
        lambda f: policy.apply(unravel(f), obs),
        policy.dist.fisher_weight, flat0, weight, damping=damping,
    )
    tree_fvp = make_fused_gaussian_mlp_fvp(
        params["net"], obs, weight, params["log_std"], damping,
        compute_dtype=jnp.float32, interpret=True, **fused_kw,
    )
    fused = lambda v: flatten_params(tree_fvp(unravel(v)))[0]
    return flat0, jax.jit(ggn), jax.jit(fused)


@pytest.mark.parametrize("activation", ["tanh", "relu", "elu"])
def test_parity_vs_xla_ggn(activation):
    policy, params, obs, weight = _problem(activation=activation)
    flat0, ggn, fused = _operators(
        policy, params, obs, weight,
        activation=activation, block_rows=128,
    )
    v = jax.random.normal(jax.random.key(3), flat0.shape, jnp.float32)
    a = np.asarray(ggn(v), np.float64)
    b = np.asarray(fused(v), np.float64)
    assert np.linalg.norm(a - b) / np.linalg.norm(a) < 1e-5


def test_parity_three_hidden_layers_and_auto_block():
    policy, params, obs, weight = _problem(hidden=(128, 256, 128))
    flat0, ggn, fused = _operators(policy, params, obs, weight)
    v = jax.random.normal(jax.random.key(4), flat0.shape, jnp.float32)
    a = np.asarray(ggn(v), np.float64)
    b = np.asarray(fused(v), np.float64)
    assert np.linalg.norm(a - b) / np.linalg.norm(a) < 1e-5


def test_zero_damping_and_zero_weight_rows_exact():
    """Padding rows (weight 0) must contribute exactly nothing."""
    policy, params, obs, weight = _problem(pad_tail=0)
    obs2 = jnp.concatenate([obs, 100.0 * jnp.ones((64, obs.shape[1]))])
    w2 = jnp.concatenate([jnp.ones((obs.shape[0],)), jnp.zeros((64,))])
    flat0, _, fused_ref = _operators(policy, params, obs, jnp.ones(obs.shape[:1]), damping=0.0)
    _, _, fused_padded = _operators(policy, params, obs2, w2, damping=0.0)
    v = jax.random.normal(jax.random.key(5), flat0.shape, jnp.float32)
    a = np.asarray(fused_ref(v), np.float64)
    b = np.asarray(fused_padded(v), np.float64)
    assert np.linalg.norm(a - b) / max(np.linalg.norm(a), 1e-12) < 1e-5


def _batch_for(policy, params, obs, weight, seed=2):
    dist = policy.apply(params, obs)
    actions = policy.dist.sample(jax.random.key(seed), dist)
    adv = jax.random.normal(jax.random.key(seed + 1), weight.shape)
    return TRPOBatch(
        obs=obs, actions=actions, advantages=adv * weight,
        old_dist=dist, weight=weight,
    )


def test_full_update_fused_matches_ggn():
    policy, params, obs, weight = _problem()
    batch = _batch_for(policy, params, obs, weight)
    up_ggn = jax.jit(make_trpo_update(policy, TRPOConfig(fvp_mode="ggn")))
    up_fused = jax.jit(make_trpo_update(policy, TRPOConfig(fvp_mode="fused")))
    p_g, s_g = up_ggn(params, batch)
    p_f, s_f = up_fused(params, batch)
    np.testing.assert_allclose(
        np.asarray(s_f.kl), np.asarray(s_g.kl), rtol=1e-4, atol=1e-7
    )
    fg, _ = flatten_params(p_g)
    ff, _ = flatten_params(p_f)
    np.testing.assert_allclose(
        np.asarray(ff), np.asarray(fg), rtol=1e-4, atol=1e-5
    )


def test_full_update_fused_with_subsample_and_rtol():
    """The fused operator composes with curvature subsampling and the
    residual-aware exit (both act outside the kernel)."""
    policy, params, obs, weight = _problem()
    batch = _batch_for(policy, params, obs, weight)
    cfg = TRPOConfig(
        fvp_mode="fused", fvp_subsample=0.5, cg_residual_rtol=0.25,
        cg_iters=30,
    )
    cfg_ref = TRPOConfig(
        fvp_mode="ggn", fvp_subsample=0.5, cg_residual_rtol=0.25,
        cg_iters=30,
    )
    p_f, s_f = jax.jit(make_trpo_update(policy, cfg))(params, batch)
    p_g, s_g = jax.jit(make_trpo_update(policy, cfg_ref))(params, batch)
    assert int(s_f.cg_iterations) == int(s_g.cg_iterations)
    fg, _ = flatten_params(p_g)
    ff, _ = flatten_params(p_f)
    np.testing.assert_allclose(
        np.asarray(ff), np.asarray(fg), rtol=1e-4, atol=1e-5
    )


def test_explicit_fused_raises_on_categorical():
    policy = make_policy((11,), DiscreteSpec(4), hidden=(128,),
                         compute_dtype=jnp.float32)
    params = policy.init(jax.random.key(0))
    obs = jnp.zeros((8, 11))
    batch = TRPOBatch(
        obs=obs,
        actions=jnp.zeros((8,), jnp.int32),
        advantages=jnp.ones((8,)),
        old_dist=policy.apply(params, obs),
        weight=jnp.ones((8,)),
    )
    with pytest.raises(ValueError, match="diagonal-Gaussian"):
        make_trpo_update(policy, TRPOConfig(fvp_mode="fused"))(params, batch)


def test_explicit_fused_raises_on_non_lane_hidden():
    policy, params, obs, weight = _problem(hidden=(64,))
    batch = _batch_for(policy, params, obs, weight)
    with pytest.raises(ValueError, match="lane"):
        make_trpo_update(policy, TRPOConfig(fvp_mode="fused"))(params, batch)


def test_auto_mode_falls_back_cleanly_off_tpu():
    """fvp_mode='auto' (the default) must run fine for every policy on
    the CPU mesh — identical to 'ggn' there."""
    policy, params, obs, weight = _problem(hidden=(64,))
    batch = _batch_for(policy, params, obs, weight)
    p_a, s_a = jax.jit(make_trpo_update(policy, TRPOConfig()))(params, batch)
    p_g, s_g = jax.jit(
        make_trpo_update(policy, TRPOConfig(fvp_mode="ggn"))
    )(params, batch)
    fa, _ = flatten_params(p_a)
    fg, _ = flatten_params(p_g)
    np.testing.assert_array_equal(np.asarray(fa), np.asarray(fg))


def test_vmem_cost_model_rejects_oversized():
    with pytest.raises(ValueError, match="VMEM"):
        _auto_block_rows(8192, (8192, 8192), 128)


def test_supported_predicate():
    policy, params, _, _ = _problem()
    assert fused_fvp_supported("tanh", params["net"])
    assert not fused_fvp_supported("gelu", params["net"])
    assert not fused_fvp_supported("tanh", {"layers": []})
    assert not fused_fvp_supported("tanh", {"wrong": 1})


def test_sharded_fused_fvp_parity():
    """The fused kernel under shard_map (data-parallel): per-device
    kernels on local batch shards + the psum combine must equal both the
    sharded XLA GGN spelling and the single-device fused operator on the
    full batch."""
    import numpy as np
    from jax.sharding import Mesh
    from trpo_tpu.parallel.sharded import (
        make_sharded_fused_fvp,
        make_sharded_ggn_fvp,
        shard_batch,
    )

    policy, params, obs, weight = _problem(batch=320, pad_tail=40)
    batch = _batch_for(policy, params, obs, weight)
    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    cfg = TRPOConfig(cg_damping=0.1)
    sharded = shard_batch(mesh, batch)
    v = jax.random.normal(
        jax.random.key(9), flatten_params(params)[0].shape, jnp.float32
    )

    y_fused = np.asarray(
        make_sharded_fused_fvp(policy, cfg, mesh)(params, sharded, v),
        np.float64,
    )
    y_ggn = np.asarray(
        make_sharded_ggn_fvp(policy, cfg, mesh)(params, sharded, v),
        np.float64,
    )
    # single-device fused on the full batch (same damping)
    flat0, unravel = flatten_params(params)
    single = make_fused_gaussian_mlp_fvp(
        params["net"], obs, weight, params["log_std"], cfg.cg_damping,
        compute_dtype=jnp.float32, interpret=True,
    )
    y_single = np.asarray(
        flatten_params(jax.jit(lambda vv: single(unravel(vv)))(v))[0],
        np.float64,
    )
    assert np.linalg.norm(y_fused - y_ggn) / np.linalg.norm(y_ggn) < 1e-5
    assert (
        np.linalg.norm(y_fused - y_single) / np.linalg.norm(y_single)
        < 1e-5
    )


def test_sharded_fused_fvp_rejects_categorical():
    import numpy as np
    from jax.sharding import Mesh
    from trpo_tpu.parallel.sharded import make_sharded_fused_fvp

    policy = make_policy((11,), DiscreteSpec(4), hidden=(128,),
                         compute_dtype=jnp.float32)
    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    with pytest.raises(ValueError, match="diagonal-Gaussian"):
        make_sharded_fused_fvp(policy, TRPOConfig(), mesh)
