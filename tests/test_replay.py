"""Deterministic replay (ISSUE 18): request capture round-trip,
capture/trace sampling agreement, bounded-writer drop accounting,
bundle reconstruction (including the mid-window takeover seed from a
fenced zombie's frozen journal), the export CLI's exit-2 contract, the
bit-exact diff oracle, the validator's replay-complete contracts, and
— slow leg — a live in-process shadow replay driven end to end through
``scripts/replay_run.py``."""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from trpo_tpu.obs.capture import (
    RequestCapture,
    capture_records,
    decode_payload,
    encode_obs_payload,
)
from trpo_tpu.obs.events import (
    SCHEMA_VERSION,
    EventBus,
    JsonlSink,
    manifest_fields,
    validate_event,
)
from trpo_tpu.obs.replay import (
    BundleError,
    action_match,
    build_bundle,
    load_bundle,
    scan_journals,
    write_bundle,
)
from trpo_tpu.obs.trace import Tracer
from trpo_tpu.serve import wire as _wire

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_ANALYZE = os.path.join(_REPO, "scripts", "analyze_run.py")
_REPLAY = os.path.join(_REPO, "scripts", "replay_run.py")
_VALIDATE = os.path.join(_REPO, "scripts", "validate_events.py")


def _collect_bus():
    recs = []
    return recs, EventBus(lambda r: recs.append(r))


def _run(script, *argv):
    return subprocess.run(
        [sys.executable, script, *argv], capture_output=True, text=True
    )


# -- capture round-trip ----------------------------------------------------


def test_capture_roundtrip_json_body_bit_exact():
    """A JSON act body captured at the router comes back as the exact
    float32 obs array, with the seq the router stamped and the
    action/step parsed out of the recorded response."""
    recs, bus = _collect_bus()
    tracer = Tracer(bus, 1.0)
    cap = RequestCapture(bus, process="router")
    obs = (np.arange(5, dtype=np.float32) - 2.1) / 3.0
    body = json.dumps({"obs": obs.tolist(), "seq": 7}).encode()
    resp = json.dumps(
        {"action": [0.1234567890123456, -1.5], "step": 42}
    ).encode()
    ctx = tracer.begin("a" * 16, sampled=True)
    assert cap.record(
        ctx, path="/session/s1/act", endpoint="session_act",
        body=body, status=200, session="s1", replica="r0",
        response=resp, response_ctype="application/json",
    )
    cap.drain()
    cap.close()
    tracer.close()
    bus.close()
    caps = capture_records(recs)
    assert len(caps) == 1
    rec = caps[0]
    assert not validate_event(rec)
    assert rec["seq"] == 7
    assert rec["step"] == 42
    assert rec["action"] == [0.1234567890123456, -1.5]
    scalars, decoded = decode_payload(rec)
    assert decoded.dtype == np.float32
    assert np.array_equal(decoded, obs)


def test_capture_roundtrip_wire_body_bit_exact():
    """A binary wire-frame body (the PR 16 codec) round-trips through
    the base64 payload bit-exact, and the wire response yields the
    action + step."""
    recs, bus = _collect_bus()
    tracer = Tracer(bus, 1.0)
    cap = RequestCapture(bus, process="router")
    obs = np.random.RandomState(3).randn(4).astype(np.float32)
    body = _wire.encode_frame(scalars={"seq": 9}, arrays={"obs": obs})
    action = np.array([0.5, -0.25], np.float64)
    resp = _wire.encode_frame(
        scalars={"step": 6}, arrays={"action": action}
    )
    ctx = tracer.begin("b" * 16, sampled=True)
    assert cap.record(
        ctx, path="/session/s2/act", endpoint="session_act",
        body=body, binary=True, status=200, session="s2",
        response=resp, response_ctype=_wire.WIRE_CONTENT_TYPE,
    )
    cap.drain()
    cap.close()
    tracer.close()
    bus.close()
    (rec,) = capture_records(recs)
    assert rec["seq"] == 9 and rec["step"] == 6
    assert rec["action"] == action.tolist()
    _, decoded = decode_payload(rec)
    assert np.array_equal(decoded, obs)


def test_capture_unparseable_body_still_emits_payloadless():
    """Garbage bodies yield a capture record WITHOUT a payload — the
    miss must be loud downstream (bundle: not replayable), never a
    silently absent record."""
    recs, bus = _collect_bus()
    tracer = Tracer(bus, 1.0)
    cap = RequestCapture(bus)
    ctx = tracer.begin("c" * 16, sampled=True)
    assert cap.record(
        ctx, path="/act", endpoint="act", body=b"\x00not json",
        status=200,
    )
    cap.drain()
    cap.close()
    tracer.close()
    bus.close()
    (rec,) = capture_records(recs)
    assert "payload" not in rec
    assert decode_payload(rec) is None


# -- sampling agreement ----------------------------------------------------


def test_capture_agrees_with_head_sampling_verdict():
    """Capture records exactly the requests the tracer samples: an
    unsampled context is refused, a FORCED (anomaly) context is
    captured even when unsampled — span stream and capture log always
    name the same request set."""
    recs, bus = _collect_bus()
    tracer = Tracer(bus, 1.0)
    cap = RequestCapture(bus)
    body = json.dumps({"obs": [0.0]}).encode()
    sampled = tracer.begin("d" * 16, sampled=True)
    unsampled = tracer.begin("e" * 16, sampled=False)
    forced = tracer.begin("f" * 16, sampled=False)
    forced.force()
    assert cap.record(
        sampled, path="/act", endpoint="act", body=body, status=200
    )
    assert not cap.record(
        unsampled, path="/act", endpoint="act", body=body, status=200
    )
    assert cap.record(
        forced, path="/act", endpoint="act", body=body, status=500
    )
    assert not cap.record(
        None, path="/act", endpoint="act", body=body, status=200
    )
    cap.drain()
    cap.close()
    tracer.close()
    bus.close()
    traces = {r["trace"] for r in capture_records(recs)}
    assert traces == {"d" * 16, "f" * 16}
    forced_rec = [
        r for r in capture_records(recs) if r["trace"] == "f" * 16
    ][0]
    assert forced_rec.get("forced") is True


# -- drop accounting -------------------------------------------------------


def test_capture_backpressure_drops_counted_forced_overshoots():
    """The bounded write-behind buffer drops WHOLE requests, counted
    on dropped_total; a forced (anomaly) request overshoots the bound
    instead — the tracer-writer contract, applied to capture."""
    gate = threading.Event()
    emitted = []

    def blocking_sink(rec):
        gate.wait(10.0)
        emitted.append(rec)

    bus = EventBus(blocking_sink)
    tracer = Tracer(bus, 1.0)
    cap = RequestCapture(bus, max_pending=2, poll_interval=0.01)
    body = json.dumps({"obs": [1.0]}).encode()

    def rec_one(tid, force=False):
        ctx = tracer.begin(tid, sampled=not force)
        if force:
            ctx.force()
        return cap.record(
            ctx, path="/act", endpoint="act", body=body, status=200
        )

    # wedge the writer on the first record so the bound fills
    assert rec_one("1" * 16)
    time.sleep(0.15)  # writer now blocked inside the sink
    assert rec_one("2" * 16)
    assert rec_one("3" * 16)
    assert not rec_one("4" * 16)  # over the bound: dropped, counted
    assert cap.dropped_total == 1
    assert rec_one("5" * 16, force=True)  # forced overshoots
    assert cap.dropped_total == 1
    gate.set()
    cap.drain()
    cap.close()
    tracer.close()
    bus.close()
    got = {r["trace"] for r in emitted if r.get("kind") == "capture"}
    assert "4" * 16 not in got
    assert {"1" * 16, "2" * 16, "3" * 16, "5" * 16} <= got
    assert cap.requests_total == 4  # the drop is not a request


def test_capture_writer_failure_counts_drops_and_survives():
    """A sink error inside the writer drains counts the whole batch
    dropped and the writer keeps serving later records."""
    state = {"fail": True}
    emitted = []

    def flaky_sink(rec):
        if state["fail"]:
            raise RuntimeError("sink down")
        emitted.append(rec)

    bus = EventBus(flaky_sink)
    tracer = Tracer(bus, 1.0)
    cap = RequestCapture(bus, poll_interval=0.01)
    body = json.dumps({"obs": [1.0]}).encode()
    ctx = tracer.begin("a" * 16, sampled=True)
    cap.record(ctx, path="/act", endpoint="act", body=body, status=200)
    deadline = time.monotonic() + 5.0
    while cap.dropped_total == 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert cap.dropped_total == 1
    state["fail"] = False
    ctx2 = tracer.begin("b" * 16, sampled=True)
    cap.record(ctx2, path="/act", endpoint="act", body=body, status=200)
    cap.drain()
    cap.close()
    tracer.close()
    bus.close()
    assert [r["trace"] for r in emitted] == ["b" * 16]
    assert cap.dropped_total == 1


# -- bundle reconstruction -------------------------------------------------


def _mk_capture(tid, order, t, seq, obs, action, sid="s1", step=1):
    rec = {
        "v": SCHEMA_VERSION, "kind": "capture", "t": t,
        "trace": tid, "order": order, "path": f"/session/{sid}/act",
        "endpoint": "session_act", "status": 200, "session": sid,
        "seq": seq, "step": step, "action": list(action),
        "payload": encode_obs_payload(
            np.asarray(obs, np.float32), seq=seq
        ),
        "process": "router",
    }
    assert not validate_event(rec), validate_event(rec)
    return rec


def _mk_span(tid, name, t, dur=1.0, **attrs):
    rec = {
        "v": SCHEMA_VERSION, "kind": "span", "t": t, "trace": tid,
        "span": f"{name}-{t}", "name": name, "start": t,
        "dur_ms": dur, **attrs,
    }
    assert not validate_event(rec), validate_event(rec)
    return rec


def _journal_write(path, entries):
    with open(path, "w") as f:
        for e in entries:
            f.write(json.dumps(e) + "\n")


def test_bundle_mid_window_seed_from_zombie_journal(tmp_path):
    """The takeover scenario: the capture window opens at seq 5 of a
    session whose earlier life is journaled on the FENCED zombie
    replica (frozen at seq 4) while the survivor journals seqs 5-6.
    The bundle must seed from the zombie's seq-4 snapshot — the only
    aligned one — scanning ALL entries, not latest-per-file."""
    jdir = tmp_path / "cj"
    jdir.mkdir()
    mk = lambda seq: {
        "session": "s1", "steps": seq, "seq": seq,
        "carry": [0.1 * seq] * 3, "t": 100.0 + seq,
        "last_action": [0.5], "last_step": 1,
    }
    _journal_write(jdir / "hostA--r0.carry.jsonl", [mk(s) for s in (1, 2, 3, 4)])
    _journal_write(jdir / "hostB--r1.carry.jsonl", [mk(s) for s in (5, 6)])
    obs = np.ones(3, np.float32)
    records = []
    for i, seq in enumerate((5, 6)):
        tid = f"{seq:016x}"
        t = 200.0 + i
        records.append(_mk_capture(tid, i, t, seq, obs, [0.1]))
        records.append(_mk_span(tid, "router.session_act", t))
    bundle = build_bundle(
        records, window=(199.0, 203.0), journal_dir=str(jdir)
    )
    assert bundle["replayable"] is True, bundle["completeness"]
    sess = bundle["sessions"]["s1"]
    assert sess["first_seq"] == 5
    assert sess["seed"]["seq"] == 4
    assert sess["seed"]["journal"] == "hostA--r0.carry.jsonl"
    assert bundle["checkpoint_step"] == 1
    # scan_journals keeps every entry, fenced files included
    scanned = scan_journals(str(jdir))
    assert [e["seq"] for e in scanned["s1"]] == [1, 2, 3, 4, 5, 6]


def test_bundle_missing_journal_seed_named(tmp_path):
    """No snapshot at first_seq - 1 → the trace is marked
    non-replayable and the missing piece NAMES the seq it needs."""
    jdir = tmp_path / "cj"
    jdir.mkdir()
    _journal_write(
        jdir / "hostA--r0.carry.jsonl",
        [{"session": "s1", "steps": 2, "seq": 2, "carry": [0.0],
          "t": 100.0}],
    )
    obs = np.zeros(2, np.float32)
    records = [
        _mk_capture("a" * 16, 0, 200.0, 5, obs, [0.1]),
        _mk_span("a" * 16, "router.session_act", 200.0),
    ]
    bundle = build_bundle(
        records, trace_id="a" * 16, journal_dir=str(jdir)
    )
    assert bundle["replayable"] is False
    (comp,) = bundle["completeness"]
    assert not comp["replayable"]
    assert any("journal snapshot at seq 4" in m for m in comp["missing"])


def test_bundle_payloadless_and_spanless_named():
    """A capture without its obs payload, and a trace without
    assembled spans, each name the exact missing piece."""
    rec = {
        "v": SCHEMA_VERSION, "kind": "capture", "t": 50.0,
        "trace": "b" * 16, "order": 0, "path": "/act",
        "endpoint": "act", "status": 200,
    }
    assert not validate_event(rec)
    bundle = build_bundle([rec], trace_id="b" * 16)
    (comp,) = bundle["completeness"]
    assert not comp["replayable"]
    missing = " | ".join(comp["missing"])
    assert "capture payload" in missing
    assert "recorded action" in missing
    assert "assembled trace spans" in missing


def test_bundle_unknown_trace_and_uncaptured_trace_errors():
    spans_only = [_mk_span("c" * 16, "router.act", 10.0)]
    with pytest.raises(BundleError, match="unknown trace id"):
        build_bundle(spans_only, trace_id="9" * 16)
    # the trace EXISTS in the span stream but capture never saw it:
    # the refusal must say so (capture not armed ≠ unknown trace)
    with pytest.raises(BundleError, match="NO capture records"):
        build_bundle(spans_only, trace_id="c" * 16)
    with pytest.raises(BundleError, match="no capture records in window"):
        build_bundle(spans_only, window=(0.0, 100.0))
    with pytest.raises(BundleError, match="exactly one"):
        build_bundle(spans_only)


def test_bundle_roundtrip_and_version_gate(tmp_path):
    obs = np.ones(1, np.float32)
    records = [
        _mk_capture("d" * 16, 0, 10.0, 1, obs, [0.3]),
        _mk_span("d" * 16, "router.session_act", 10.0),
    ]
    bundle = build_bundle(records, trace_id="d" * 16)
    assert bundle["replayable"] is True  # seq 1 = born in-window
    path = str(tmp_path / "b.json")
    write_bundle(bundle, path)
    assert load_bundle(path) == bundle
    bad = dict(bundle, bundle_version=99)
    write_bundle(bad, path)
    with pytest.raises(BundleError, match="version"):
        load_bundle(path)
    with pytest.raises(BundleError, match="cannot read"):
        load_bundle(str(tmp_path / "absent.json"))


def test_assemble_traces_reports_dropped_records():
    """The ISSUE 18 silent-miss fix: span records the assembler cannot
    join by trace id are handed back via the out-param, not silently
    discarded."""
    from trpo_tpu.obs.analyze import assemble_traces

    good = _mk_span("e" * 16, "router.act", 5.0)
    bad = dict(_mk_span("e" * 16, "router.act", 6.0), trace=None)
    dropped = []
    traces = assemble_traces([good, bad], dropped=dropped)
    assert "e" * 16 in traces
    assert dropped == [bad]
    # the default path stays compatible: no out-param, no error
    assert "e" * 16 in assemble_traces([good, bad])


# -- diff oracle -----------------------------------------------------------


def test_action_match_is_bit_exact_float64():
    a = [0.1234567890123456, -1.0000000000000002]
    assert action_match(a, list(a))
    assert not action_match(a, [0.1234567890123456, -1.0])
    assert not action_match([0.1], [0.1, 0.2])
    assert not action_match([[0.1]], [0.1])
    assert not action_match(None, [0.1])
    assert action_match([1, 2], [1.0, 2.0])  # int/float same value


# -- export CLI ------------------------------------------------------------


def _write_log(path, records):
    mani = {
        "v": SCHEMA_VERSION, "kind": "run_manifest", "t": 1.0,
        **manifest_fields(None, extra={"driver": "test"}),
    }
    with open(path, "w") as f:
        for r in [mani] + records:
            f.write(json.dumps(r) + "\n")


def test_export_bundle_cli_contract(tmp_path):
    """--export-bundle: exit 0 + bundle on disk for a captured trace,
    exit 2 with a one-line named reason (never a stack trace) on an
    unknown trace or a missing selector."""
    obs = np.ones(2, np.float32)
    log = str(tmp_path / "run.jsonl")
    _write_log(log, [
        _mk_capture("f" * 16, 0, 10.0, 1, obs, [0.7]),
        _mk_span("f" * 16, "router.session_act", 10.0),
    ])
    out = str(tmp_path / "b.json")
    r = _run(_ANALYZE, log, "--export-bundle", "f" * 16, "--out", out)
    assert r.returncode == 0, r.stderr
    assert os.path.exists(out)
    assert load_bundle(out)["acts_total"] == 1

    r = _run(_ANALYZE, log, "--export-bundle", "0" * 16)
    assert r.returncode == 2
    assert "unknown trace id" in r.stderr
    assert "Traceback" not in r.stderr

    r = _run(_ANALYZE, log, "--export-bundle")
    assert r.returncode == 2
    assert "exactly one selector" in r.stderr
    assert "Traceback" not in r.stderr

    r = _run(
        _ANALYZE, log, "--export-bundle", "--window", "900.0", "901.0"
    )
    assert r.returncode == 2
    assert "no capture records in window" in r.stderr
    assert "Traceback" not in r.stderr


# -- validator replay-complete contracts -----------------------------------


def _replay_recs(drop_verdict=False, drop_complete=False, planned=None):
    tid = "a" * 16
    recs = [
        {"v": SCHEMA_VERSION, "kind": "replay", "t": 2.0,
         "event": "begin", "acts": planned or 1},
        {"v": SCHEMA_VERSION, "kind": "replay", "t": 3.0,
         "event": "act", "trace": tid, "order": 0, "status": 200},
    ]
    if not drop_verdict:
        recs.append(
            {"v": SCHEMA_VERSION, "kind": "replay", "t": 4.0,
             "event": "verdict", "trace": tid, "order": 0,
             "match": True}
        )
    if not drop_complete:
        recs.append(
            {"v": SCHEMA_VERSION, "kind": "replay", "t": 5.0,
             "event": "complete", "acts": planned or 1,
             "mismatches": 0}
        )
    return recs


def test_validator_replay_contracts(tmp_path):
    good = str(tmp_path / "good.jsonl")
    _write_log(good, _replay_recs())
    r = _run(_VALIDATE, good)
    assert r.returncode == 0, r.stderr

    no_verdict = str(tmp_path / "nv.jsonl")
    _write_log(no_verdict, _replay_recs(drop_verdict=True))
    r = _run(_VALIDATE, no_verdict)
    assert r.returncode == 1
    assert "no diff verdict" in r.stderr

    no_complete = str(tmp_path / "nc.jsonl")
    _write_log(no_complete, _replay_recs(drop_complete=True))
    r = _run(_VALIDATE, no_complete)
    assert r.returncode == 1
    assert "never emitted its complete" in r.stderr

    short = str(tmp_path / "short.jsonl")
    _write_log(short, _replay_recs(planned=2))
    r = _run(_VALIDATE, short)
    assert r.returncode == 1
    assert "planned 2" in r.stderr


# -- /metrics counters -----------------------------------------------------


def test_server_capture_fams_emit_counters():
    """The replica-side /metrics block names the three capture
    counters (and stays silent when capture is off)."""
    from types import SimpleNamespace

    from trpo_tpu.serve.server import PolicyServer

    recs, bus = _collect_bus()
    cap = RequestCapture(bus)
    cap.requests_total, cap.dropped_total, cap.bytes_total = 3, 1, 99
    rows = []

    def fam(name, mtype, help_, samples):
        rows.append((name, samples))

    PolicyServer._capture_fams(SimpleNamespace(capture=cap), fam)
    names = {n for n, _ in rows}
    assert names == {
        "trpo_capture_requests_total",
        "trpo_capture_dropped_total",
        "trpo_capture_bytes_total",
    }
    values = {n: s[0][1] for n, s in rows}
    assert values["trpo_capture_requests_total"] == 3
    assert values["trpo_capture_dropped_total"] == 1
    assert values["trpo_capture_bytes_total"] == 99
    rows.clear()
    PolicyServer._capture_fams(SimpleNamespace(capture=None), fam)
    assert rows == []
    cap.close()
    bus.close()


# -- live shadow replay (e2e, slow) ----------------------------------------


_E2E_CFG = dict(
    n_envs=4, batch_timesteps=32, cg_iters=2, vf_train_steps=2,
    policy_hidden=(8,), vf_hidden=(8,), seed=5, policy_gru=8,
)


def _post(url, payload=None, headers=None, timeout=30.0):
    import urllib.error

    data = b"" if payload is None else json.dumps(payload).encode()
    h = {"Content-Type": "application/json"}
    h.update(headers or {})
    req = urllib.request.Request(url, data=data, headers=h)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.mark.slow  # e2e replay leg: records a live in-process serve
# run with capture armed, exports a MID-WINDOW bundle (journal-seeded),
# and re-executes it through scripts/replay_run.py — bit-exact
def test_live_shadow_replay_bit_exact(tmp_path):
    from trpo_tpu.agent import TRPOAgent
    from trpo_tpu.config import TRPOConfig
    from trpo_tpu.obs.analyze import load_events
    from trpo_tpu.obs.trace import TRACE_HEADER, mint_trace_id
    from trpo_tpu.serve import (
        InProcessReplica,
        PolicyServer,
        ReplicaSet,
        Router,
    )
    from trpo_tpu.utils.checkpoint import Checkpointer

    agent = TRPOAgent("pendulum", TRPOConfig(**_E2E_CFG))
    state = agent.init_state(seed=0)
    ck_dir = str(tmp_path / "ck")
    ck = Checkpointer(ck_dir)
    ck.save(1, state)
    ck.close()

    log = str(tmp_path / "recorded.jsonl")
    bus = EventBus(JsonlSink(log))
    bus.emit(
        "run_manifest",
        **manifest_fields(None, extra={"driver": "test_replay"}),
    )
    tracer = Tracer(bus, 1.0, process="router")
    cap = RequestCapture(bus, process="router")
    jdir = str(tmp_path / "cj")

    def factory(rid):
        def build():
            engine = agent.serve_session_engine()
            engine.load(state.policy_params, state.obs_norm, step=1)
            server = PolicyServer(
                engine, None, port=0, bus=bus, tracer=tracer,
                replica_name=rid, carry_journal_dir=jdir,
            )
            return server, []

        return build

    rs = ReplicaSet(
        lambda rid: InProcessReplica(factory(rid)), 2, bus=bus,
        health_interval=60.0, backoff=0.05, health_fail_threshold=1,
        max_restarts=2,
    )
    assert rs.wait_healthy(2, timeout=120.0), rs.snapshot()
    router = Router(
        rs, port=0, bus=bus, journal_dir=jdir, tracer=tracer,
        capture=cap,
    )
    try:
        status, out = _post(router.url + "/session")
        assert status == 200, out
        sid = out["session"]
        obs_seq = [
            np.random.RandomState(100 + i)
            .randn(*agent.obs_shape).astype(np.float32)
            for i in range(6)
        ]
        for o in obs_seq:
            status, out = _post(
                router.url + f"/session/{sid}/act",
                {"obs": o.tolist()},
                headers={TRACE_HEADER: mint_trace_id()},
            )
            assert status == 200, (status, out)
        # the replica-side /metrics carries the capture counters too
        # (here capture is router-side, so the ROUTER scrape names
        # them; the replica wiring is scripts/serve.py --capture)
        body = urllib.request.urlopen(
            router.url + "/metrics", timeout=30.0
        ).read().decode()
        assert "trpo_capture_requests_total" in body
        assert "trpo_capture_dropped_total 0" in body
        cap.drain()
        assert cap.requests_total == 6
        assert cap.dropped_total == 0
    finally:
        router.close()
        tracer.drain()
        tracer.close()
        cap.close()
        rs.close()
        bus.close()

    # export a MID-WINDOW bundle: the last 3 acts, seeded from the
    # journal snapshot at the preceding seq
    records = load_events(log)
    caps = capture_records(records)
    assert [c["seq"] for c in caps] == [1, 2, 3, 4, 5, 6]
    bundle_path = str(tmp_path / "win.bundle.json")
    r = _run(
        _ANALYZE, log, "--export-bundle",
        "--window", str(caps[3]["t"] - 1e-4), str(time.time()),
        "--journal-dir", jdir, "--out", bundle_path,
    )
    assert r.returncode == 0, r.stderr
    bundle = load_bundle(bundle_path)
    assert bundle["replayable"] is True, bundle["completeness"]
    assert bundle["sessions"][sid]["first_seq"] == 4
    assert bundle["sessions"][sid]["seed"]["seq"] == 3

    # shadow re-execution through the CLI: bit-exact, validator-clean
    r = _run(_REPLAY, bundle_path, "--checkpoint-dir", ck_dir)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "REPLAY BIT-EXACT" in r.stdout
    assert "0 mismatch(es)" in r.stdout
    replay_log = bundle_path + ".replay_events.jsonl"
    r = _run(_VALIDATE, replay_log)
    assert r.returncode == 0, r.stderr
    replays = [
        rec for rec in load_events(replay_log)
        if rec.get("kind") == "replay"
    ]
    verdicts = [r_ for r_ in replays if r_.get("event") == "verdict"]
    assert len(verdicts) == 3
    assert all(v["match"] for v in verdicts)


@pytest.mark.slow  # a shadow set serving the WRONG weights must fail
# the diff loudly (exit 1 + named mismatches) — the oracle's teeth
def test_live_shadow_replay_detects_divergence(tmp_path):
    from trpo_tpu.agent import TRPOAgent
    from trpo_tpu.config import TRPOConfig
    from trpo_tpu.obs.trace import TRACE_HEADER, mint_trace_id
    from trpo_tpu.serve import (
        InProcessReplica,
        PolicyServer,
        ReplicaSet,
        Router,
    )
    from trpo_tpu.utils.checkpoint import Checkpointer

    agent = TRPOAgent("pendulum", TRPOConfig(**_E2E_CFG))
    state = agent.init_state(seed=0)
    ck_dir = str(tmp_path / "ck")
    ck = Checkpointer(ck_dir)
    ck.save(1, state)
    # a DIFFERENT step 2: replaying a step-1 recording against it
    # must diverge
    ck.save(2, agent.init_state(seed=123))
    ck.close()

    log = str(tmp_path / "recorded.jsonl")
    bus = EventBus(JsonlSink(log))
    bus.emit(
        "run_manifest",
        **manifest_fields(None, extra={"driver": "test_replay"}),
    )
    tracer = Tracer(bus, 1.0, process="router")
    cap = RequestCapture(bus, process="router")
    jdir = str(tmp_path / "cj")

    def factory(rid):
        def build():
            engine = agent.serve_session_engine()
            engine.load(state.policy_params, state.obs_norm, step=1)
            server = PolicyServer(
                engine, None, port=0, bus=bus, tracer=tracer,
                replica_name=rid, carry_journal_dir=jdir,
            )
            return server, []

        return build

    rs = ReplicaSet(
        lambda rid: InProcessReplica(factory(rid)), 1, bus=bus,
        health_interval=60.0, backoff=0.05, health_fail_threshold=1,
        max_restarts=2,
    )
    assert rs.wait_healthy(1, timeout=120.0), rs.snapshot()
    router = Router(
        rs, port=0, bus=bus, journal_dir=jdir, tracer=tracer,
        capture=cap,
    )
    try:
        status, out = _post(router.url + "/session")
        sid = out["session"]
        obs = np.random.RandomState(7).randn(
            *agent.obs_shape
        ).astype(np.float32)
        status, out = _post(
            router.url + f"/session/{sid}/act", {"obs": obs.tolist()},
            headers={TRACE_HEADER: mint_trace_id()},
        )
        assert status == 200
        cap.drain()
    finally:
        router.close()
        tracer.drain()
        tracer.close()
        cap.close()
        rs.close()
        bus.close()

    from trpo_tpu.obs.analyze import load_events

    bundle = build_bundle(
        load_events(log), window=(0.0, time.time()), journal_dir=jdir
    )
    # lie about the step: point the shadow at the seed-123 weights
    bundle["checkpoint_step"] = 2
    bundle_path = str(tmp_path / "b.json")
    write_bundle(bundle, bundle_path)
    r = _run(_REPLAY, bundle_path, "--checkpoint-dir", ck_dir)
    assert r.returncode == 1, (r.returncode, r.stdout, r.stderr)
    assert "MISMATCH" in r.stdout
    assert "REPLAY DIVERGED" in r.stdout
