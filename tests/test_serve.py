"""Serving tier (ISSUE 6): AOT engine, micro-batcher, HTTP front end,
checkpoint hot-reload, serve events, and the serving analyze gate.

Contracts pinned here:

* the engine pads requests to the AOT ladder and the action for a row is
  independent of the rung it padded to; steady-state serving performs
  ZERO retraces (recompile monitor);
* the batcher coalesces to a full rung, flushes on the half-deadline,
  survives engine failures (failing only that batch's requests), and
  emits schema-valid ``serve`` events;
* the HTTP front end scopes errors per request (400/503/500), serves
  Prometheus ``trpo_serve_*``, and hot-reloads a newer marker-gated
  checkpoint with zero dropped requests under concurrent load;
* ``obs/analyze`` summarizes serving logs and ``compare_runs`` judges
  latency time-like and actions/s rate-like, with the analyze CLI's
  0/1/2 exit contract.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from trpo_tpu.agent import TRPOAgent
from trpo_tpu.config import TRPOConfig
from trpo_tpu.obs.events import EventBus, validate_event
from trpo_tpu.serve import InferenceEngine, MicroBatcher, PolicyServer

_CFG = dict(
    n_envs=4, batch_timesteps=32, cg_iters=2, vf_train_steps=2,
    policy_hidden=(8,), vf_hidden=(8,), seed=7,
    serve_batch_shapes=(1, 4, 8),
)


def _agent(**kw):
    return TRPOAgent("cartpole", TRPOConfig(**{**_CFG, **kw}))


@pytest.fixture(scope="module")
def loaded_engine():
    agent = _agent()
    state = agent.init_state(seed=0)
    engine = agent.serve_engine()
    engine.load(state.policy_params, state.obs_norm, step=0)
    return agent, engine


def _post(url, payload, timeout=30.0):
    data = payload if isinstance(payload, bytes) else json.dumps(
        payload
    ).encode()
    req = urllib.request.Request(
        url, data=data, headers={"Content-Type": "application/json"}
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------


def test_engine_ladder_padding_and_chunking(loaded_engine):
    _, engine = loaded_engine
    assert engine.batch_shapes == (1, 4, 8)
    assert engine.padded_shape(1) == 1
    assert engine.padded_shape(2) == 4
    assert engine.padded_shape(5) == 8
    assert engine.padded_shape(64) == 8  # over-sized batches chunk
    rng = np.random.RandomState(0)
    for n in (1, 3, 8, 20):  # 20 > top rung: chunked at 8
        actions = engine.infer(rng.randn(n, 4).astype(np.float32))
        assert actions.shape == (n,)


def test_engine_actions_independent_of_padding_rung(loaded_engine):
    _, engine = loaded_engine
    rng = np.random.RandomState(1)
    obs = rng.randn(8, 4).astype(np.float32)
    a8 = engine.infer(obs)
    a1 = np.stack([engine.infer(obs[i : i + 1])[0] for i in range(8)])
    a4 = np.concatenate([engine.infer(obs[:4]), engine.infer(obs[4:])])
    np.testing.assert_array_equal(a8, a1)
    np.testing.assert_array_equal(a8, a4)


def test_engine_is_deterministic(loaded_engine):
    _, engine = loaded_engine
    obs = np.random.RandomState(2).randn(3, 4).astype(np.float32)
    np.testing.assert_array_equal(engine.infer(obs), engine.infer(obs))


def test_engine_zero_retraces_after_load():
    from trpo_tpu.obs.recompile import RecompileMonitor

    agent = _agent()
    state = agent.init_state(seed=1)
    engine = agent.serve_engine()
    rng = np.random.RandomState(3)
    mon = RecompileMonitor()
    with mon:
        engine.load(state.policy_params, state.obs_norm, step=0)
        mon.mark_steady()  # the AOT ladder is the ONLY compilation
        for _ in range(3):
            for n in (1, 2, 4, 7, 8, 11):
                engine.infer(rng.randn(n, 4).astype(np.float32))
        # a hot swap must not retrace either (same shapes, new buffers)
        state2 = agent.init_state(seed=2)
        engine.load(state2.policy_params, state2.obs_norm, step=1)
        engine.infer(rng.randn(5, 4).astype(np.float32))
    assert mon.unexpected_retraces() == {}
    assert engine.loaded_step == 1


def test_engine_rejects_unloaded_and_bad_shapes(loaded_engine):
    _, engine = loaded_engine
    fresh = _agent().serve_engine()
    with pytest.raises(RuntimeError, match="no params snapshot"):
        fresh.infer(np.zeros((1, 4), np.float32))
    with pytest.raises(ValueError, match="obs must be"):
        engine.infer(np.zeros((2, 5), np.float32))
    with pytest.raises(ValueError, match="obs must be"):
        engine.infer(np.zeros(4, np.float32))  # missing batch axis
    with pytest.raises(ValueError, match="batch_shapes"):
        InferenceEngine(None, (4,), batch_shapes=())
    with pytest.raises(ValueError, match="batch_shapes"):
        InferenceEngine(None, (4,), batch_shapes=(0, 4))


def test_engine_obs_norm_presence_contract():
    """A normalized policy served without its statistics (or vice versa)
    is silently-wrong-actions territory — both directions refuse."""
    agent_n = TRPOAgent(
        "cartpole", TRPOConfig(**{**_CFG, "normalize_obs": True})
    )
    state_n = agent_n.init_state(seed=0)
    eng_n = agent_n.serve_engine()
    assert eng_n.with_obs_norm
    with pytest.raises(ValueError, match="obs_norm=None"):
        eng_n.load(state_n.policy_params, None)
    eng_n.load(state_n.policy_params, state_n.obs_norm, step=0)
    assert eng_n.infer(np.zeros((2, 4), np.float32)).shape == (2,)

    agent_r = _agent()
    state_r = agent_r.init_state(seed=0)
    eng_r = agent_r.serve_engine()
    with pytest.raises(ValueError, match="with_obs_norm=True"):
        eng_r.load(state_r.policy_params, state_n.obs_norm)


def test_recurrent_agent_refuses_serve_engine():
    agent = TRPOAgent(
        "cartpole-po",
        TRPOConfig(
            n_envs=4, batch_timesteps=32, policy_hidden=(8,),
            vf_hidden=(8,), policy_gru=8,
        ),
    )
    with pytest.raises(ValueError, match="feedforward"):
        agent.serve_engine()


# ---------------------------------------------------------------------------
# micro-batcher
# ---------------------------------------------------------------------------


def test_batcher_coalesces_to_full_rung(loaded_engine):
    _, engine = loaded_engine
    events = []
    bus = EventBus(lambda rec: events.append(rec))
    # huge deadline: only the FULL trigger can dispatch this batch
    batcher = MicroBatcher(engine, deadline_ms=5000.0, bus=bus)
    try:
        rng = np.random.RandomState(4)
        futures = [
            batcher.submit(rng.randn(4).astype(np.float32))
            for _ in range(8)
        ]
        results = [f.result(timeout=30.0) for f in futures]
        # futures resolve to (action, step-of-the-snapshot-that-ran)
        assert all(a.shape == () for a, _step in results)
        assert all(step == 0 for _a, step in results)
        assert batcher.batches_total == 1
        assert batcher.requests_total == 8
    finally:
        batcher.close()
    (ev,) = [e for e in events if e["kind"] == "serve"]
    assert ev["requests"] == 8 and ev["padded"] == 8
    assert ev["queue_depth"] == 0 and ev["latency_ms"] >= 0
    assert validate_event(ev) == []


def test_batcher_deadline_flushes_partial_batch(loaded_engine):
    _, engine = loaded_engine
    events = []
    bus = EventBus(lambda rec: events.append(rec))
    batcher = MicroBatcher(engine, deadline_ms=40.0, bus=bus)
    try:
        t0 = time.perf_counter()
        action, _step = batcher.submit(
            np.zeros(4, np.float32)
        ).result(timeout=30.0)
        elapsed_ms = (time.perf_counter() - t0) * 1e3
        assert action.shape == ()
        # dispatched by the half-deadline rule, not by a full batch
        assert elapsed_ms < 5000
    finally:
        batcher.close()
    (ev,) = [e for e in events if e["kind"] == "serve"]
    assert ev["requests"] == 1 and ev["padded"] == 1


def test_batcher_engine_failure_fails_only_that_batch():
    class _FlakyEngine:
        obs_shape = (2,)
        obs_dtype = np.dtype(np.float32)
        max_batch = 4

        def __init__(self):
            self.fail_next = True

        def padded_shape(self, n):
            return 4 if n > 1 else 1

        def infer(self, obs, return_step=False):
            if self.fail_next:
                self.fail_next = False
                raise RuntimeError("boom")
            out = np.zeros(len(obs), np.int32)
            return (out, 7) if return_step else out

    batcher = MicroBatcher(_FlakyEngine(), deadline_ms=5.0)
    try:
        bad = batcher.submit(np.zeros(2, np.float32))
        with pytest.raises(RuntimeError, match="boom"):
            bad.result(timeout=30.0)
        assert batcher.errors_total == 1
        good = batcher.submit(np.zeros(2, np.float32))
        action, step = good.result(timeout=30.0)
        assert action == 0 and step == 7  # dispatcher survived
    finally:
        batcher.close()


def test_batcher_adaptive_deadline_cuts_idle_wait():
    """Satellite (ROADMAP serving follow-on): with adaptive_deadline the
    dispatcher caps its wait at ~2x the observed dispatch-cost EMA, so a
    fast model under a SLOW request rate stops idling the fixed
    half-budget — p50 drops to roughly the dispatch cost itself, while
    the fixed-deadline batcher holds every lone request for
    deadline/2."""
    class _InstantEngine:
        obs_shape = (2,)
        obs_dtype = np.dtype(np.float32)
        max_batch = 8

        def padded_shape(self, n):
            return 8 if n > 1 else 1

        def infer(self, obs, return_step=False):
            out = np.zeros(len(obs), np.int32)
            return (out, 0) if return_step else out

    deadline_ms = 80.0  # fixed half-budget: 40 ms of pure idle wait

    def p50_of_lone_requests(batcher, n=9):
        lats = []
        for _ in range(n):
            t0 = time.perf_counter()
            batcher.submit(np.zeros(2, np.float32)).result(timeout=30.0)
            lats.append((time.perf_counter() - t0) * 1e3)
        return sorted(lats)[len(lats) // 2]

    fixed = MicroBatcher(_InstantEngine(), deadline_ms=deadline_ms)
    adaptive = MicroBatcher(
        _InstantEngine(), deadline_ms=deadline_ms, adaptive_deadline=True
    )
    try:
        # warm the EMA: the first adaptive dispatch has no cost sample
        # yet and honors the fixed budget (upper-bound semantics)
        adaptive.submit(np.zeros(2, np.float32)).result(timeout=30.0)
        assert adaptive.dispatch_cost_ema_ms is not None
        fixed_p50 = p50_of_lone_requests(fixed)
        adaptive_p50 = p50_of_lone_requests(adaptive)
        # fixed: every lone request idles the full half-budget
        assert fixed_p50 >= deadline_ms / 2 * 0.8, fixed_p50
        # adaptive: the wait collapses to ~the (sub-ms) dispatch cost
        assert adaptive_p50 < fixed_p50 / 2, (adaptive_p50, fixed_p50)
        assert adaptive_p50 < deadline_ms / 4, adaptive_p50
        # the effective budget never EXCEEDS the configured half-budget
        assert (
            adaptive._effective_half_budget_ms() <= deadline_ms / 2
        )
    finally:
        fixed.close()
        adaptive.close()
    with pytest.raises(ValueError, match="adaptive_headroom"):
        MicroBatcher(_InstantEngine(), adaptive_headroom=0)
    with pytest.raises(ValueError, match="cost_ema_alpha"):
        MicroBatcher(_InstantEngine(), cost_ema_alpha=0)


def test_batcher_close_drains_then_rejects(loaded_engine):
    _, engine = loaded_engine
    batcher = MicroBatcher(engine, deadline_ms=1000.0)
    futures = [
        batcher.submit(np.zeros(4, np.float32)) for _ in range(3)
    ]
    batcher.close()
    # already-accepted requests still resolved (drain-on-close)
    for f in futures:
        action, _step = f.result(timeout=5.0)
        assert action.shape == ()
    with pytest.raises(RuntimeError, match="closed"):
        batcher.submit(np.zeros(4, np.float32))


def test_batcher_rejects_bad_config_and_shapes(loaded_engine):
    _, engine = loaded_engine
    with pytest.raises(ValueError, match="deadline_ms"):
        MicroBatcher(engine, deadline_ms=0)
    with pytest.raises(ValueError, match="max_queue"):
        MicroBatcher(engine, max_queue=0)
    batcher = MicroBatcher(engine, deadline_ms=5.0)
    try:
        with pytest.raises(ValueError, match="obs must have shape"):
            batcher.submit(np.zeros((2, 4), np.float32))  # batched obs
    finally:
        batcher.close()


# ---------------------------------------------------------------------------
# serve event schema (satellite: validator strict, readers tolerant)
# ---------------------------------------------------------------------------


def test_serve_event_schema_strictness(tmp_path):
    good = {
        "v": 1, "kind": "serve", "t": 1.0,
        "requests": 3, "padded": 4, "queue_depth": 0, "latency_ms": 2.5,
    }
    assert validate_event(good) == []
    for broken in (
        {**good, "requests": 0},          # no empty batches
        {**good, "padded": "8"},          # wrong type
        {**good, "latency_ms": -1},       # negative latency
        {k: v for k, v in good.items() if k != "queue_depth"},
    ):
        assert validate_event(broken), broken

    # the CLI validator FAILS a log with a malformed serve record
    import sys
    sys.path.insert(0, "scripts")
    from validate_events import validate_file

    from trpo_tpu.obs.events import manifest_fields

    path = tmp_path / "serve.jsonl"
    manifest = {"v": 1, "kind": "run_manifest", "t": 0.0,
                **manifest_fields(None)}
    with open(path, "w") as f:
        f.write(json.dumps(manifest) + "\n")
        f.write(json.dumps(good) + "\n")
    assert validate_file(str(path)) == []
    with open(path, "a") as f:
        f.write(json.dumps({**good, "requests": 0}) + "\n")
    errs = validate_file(str(path))
    assert errs and any("requests" in e for e in errs)


# ---------------------------------------------------------------------------
# HTTP front end
# ---------------------------------------------------------------------------


def test_policy_server_routes_and_errors(loaded_engine):
    _, engine = loaded_engine
    batcher = MicroBatcher(engine, deadline_ms=5.0)
    srv = PolicyServer(engine, batcher, port=0)
    try:
        status, out = _post(srv.url + "/act", {"obs": [0.1, 0.2, 0.3, 0.4]})
        assert status == 200
        assert isinstance(out["action"], int)
        assert out["step"] == engine.loaded_step

        with pytest.raises(urllib.error.HTTPError) as e:
            _post(srv.url + "/act", {"obs": [1.0, 2.0]})  # wrong shape
        assert e.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(srv.url + "/act", b"not json{")
        assert e.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(srv.url + "/act", {"nope": 1})
        assert e.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(srv.url + "/nope", {"obs": [0, 0, 0, 0]})
        assert e.value.code == 404

        with urllib.request.urlopen(srv.url + "/healthz", timeout=5) as r:
            health = json.loads(r.read())
        assert health["ok"] and health["requests_total"] >= 1
        with urllib.request.urlopen(srv.url + "/metrics", timeout=5) as r:
            body = r.read().decode()
        assert "trpo_serve_requests_total" in body
        assert 'trpo_serve_batch_shape_total{shape="1"}' in body
        # the adaptive-deadline signal is observable once a dispatch
        # has seeded the EMA (the /act above did)
        assert "trpo_serve_dispatch_cost_ema_ms" in body
        for ln in body.splitlines():
            if ln and not ln.startswith("#"):
                float(ln.rsplit(" ", 1)[1])  # prometheus-parseable
    finally:
        srv.close()
        batcher.close()


def test_policy_server_503_before_first_checkpoint():
    agent = _agent()
    engine = agent.serve_engine()  # never loaded
    batcher = MicroBatcher(engine, deadline_ms=5.0)
    srv = PolicyServer(engine, batcher, port=0)
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(srv.url + "/act", {"obs": [0, 0, 0, 0]})
        assert e.value.code == 503
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(srv.url + "/healthz", timeout=5)
        assert e.value.code == 503
    finally:
        srv.close()
        batcher.close()


def test_policy_server_checkpointer_template_pairing(loaded_engine):
    _, engine = loaded_engine
    batcher = MicroBatcher(engine, deadline_ms=5.0)
    try:
        with pytest.raises(ValueError, match="come together"):
            PolicyServer(engine, batcher, port=0, checkpointer=object())
    finally:
        batcher.close()


# ---------------------------------------------------------------------------
# hot reload across a live swap
# ---------------------------------------------------------------------------


@pytest.mark.slow  # tier-1 budget guard (ISSUE 7): the e2e hot-swap
# scenario; test_reload_failure_keeps_serving_last_good stays the
# fast tier-1 representative of the reload path
def test_hot_reload_under_concurrent_load(tmp_path):
    from trpo_tpu.utils.checkpoint import Checkpointer

    agent = _agent()
    trainer_ck = Checkpointer(str(tmp_path / "ck"))
    state = agent.init_state(seed=0)
    state, _ = agent.run_iteration(state)
    trainer_ck.save(1, state)

    events = []
    bus = EventBus(lambda rec: events.append(rec))
    engine = agent.serve_engine()
    batcher = MicroBatcher(engine, deadline_ms=5.0, bus=bus)
    srv = PolicyServer(
        engine, batcher, port=0,
        checkpointer=Checkpointer(str(tmp_path / "ck")),
        template=agent.init_state(),
        poll_interval=0.05,
        bus=bus,
    )
    errors = []
    try:
        assert engine.loaded_step == 1  # synchronous first load

        def client(seed):
            r = np.random.RandomState(seed)
            for _ in range(12):
                try:
                    status, out = _post(
                        srv.url + "/act", {"obs": r.randn(4).tolist()}
                    )
                    if status != 200:
                        errors.append(status)
                except Exception as e:  # noqa: BLE001 — collected below
                    errors.append(repr(e))

        threads = [
            threading.Thread(target=client, args=(i,)) for i in range(4)
        ]
        for t in threads:
            t.start()

        # a newer checkpoint lands WHILE the clients hammer /act
        state, _ = agent.run_iteration(state)
        trainer_ck.save(2, state)
        deadline = time.time() + 30.0
        while engine.loaded_step != 2 and time.time() < deadline:
            time.sleep(0.02)
        for t in threads:
            t.join(timeout=60.0)
        assert engine.loaded_step == 2, "hot reload never landed"
        assert not errors, errors[:5]
        assert batcher.errors_total == 0
        assert srv.reloads_total >= 1
        # the swap is announced on the bus and the new step serves
        assert any(
            e.get("check") == "serve_reload" for e in events
        )
        status, out = _post(srv.url + "/act", {"obs": [0, 0, 0, 0]})
        assert status == 200 and out["step"] == 2
        # every serve record emitted under load is schema-valid
        for ev in events:
            assert validate_event(ev) == [], ev
    finally:
        srv.close()
        batcher.close()
        trainer_ck.close()


def test_reload_failure_keeps_serving_last_good(tmp_path, loaded_engine):
    """A checkpoint the watcher cannot restore (here: a template
    mismatch) must surface as a health finding while the endpoint keeps
    serving the last good snapshot."""
    from trpo_tpu.utils.checkpoint import Checkpointer

    agent = _agent()
    trainer_ck = Checkpointer(str(tmp_path / "ck"))
    state = agent.init_state(seed=0)
    trainer_ck.save(1, state)

    events = []
    bus = EventBus(lambda rec: events.append(rec))
    engine = agent.serve_engine()
    engine.load(state.policy_params, state.obs_norm, step=1)
    batcher = MicroBatcher(engine, deadline_ms=5.0)
    bad_template = {"totally": "wrong structure"}
    srv = PolicyServer(
        engine, batcher, port=0,
        checkpointer=Checkpointer(str(tmp_path / "ck")),
        template=bad_template,
        poll_interval=0.05,
        bus=bus,
    )
    try:
        trainer_ck.save(2, state)
        deadline = time.time() + 10.0
        while time.time() < deadline and not any(
            e.get("check") == "serve_reload_failed" for e in events
        ):
            time.sleep(0.02)
        assert any(
            e.get("check") == "serve_reload_failed" for e in events
        )
        assert engine.loaded_step == 1  # still the last good snapshot
        status, _ = _post(srv.url + "/act", {"obs": [0, 0, 0, 0]})
        assert status == 200
    finally:
        srv.close()
        batcher.close()
        trainer_ck.close()


# ---------------------------------------------------------------------------
# analyze: serving summaries + the SLO compare gate
# ---------------------------------------------------------------------------


def _serve_log(path, latency_scale=1.0, n=20, t0=100.0):
    from trpo_tpu.obs.events import manifest_fields

    with open(path, "w") as f:
        f.write(json.dumps({
            "v": 1, "kind": "run_manifest", "t": t0,
            **manifest_fields(None, extra={"driver": "serve"}),
        }) + "\n")
        for i in range(n):
            f.write(json.dumps({
                "v": 1, "kind": "serve", "t": t0 + 0.1 * (i + 1),
                "requests": 2 + (i % 3), "padded": 4 if i % 2 else 8,
                "queue_depth": i % 2,
                "latency_ms": latency_scale * (2.0 + (i % 5)),
            }) + "\n")


def test_summarize_run_serving_block(tmp_path):
    from trpo_tpu.obs.analyze import load_events, summarize_run

    path = tmp_path / "serve.jsonl"
    _serve_log(str(path))
    summary = summarize_run(load_events(str(path)))
    srv = summary["serving"]
    assert srv["batches_total"] == 20
    assert srv["requests_total"] == sum(2 + (i % 3) for i in range(20))
    assert srv["actions_per_sec"] is not None
    assert srv["latency_p50_ms"] is not None
    assert srv["latency_p99_ms"] >= srv["latency_p50_ms"]
    assert set(srv["shapes"]) == {"4", "8"}
    assert srv["queue_depth_max"] == 1
    # a training-only log has no serving block
    assert summarize_run(
        [{"kind": "iteration", "iteration": 1, "stats": {}}]
    )["serving"] is None


def test_compare_runs_serving_verdicts():
    from trpo_tpu.obs.analyze import compare_runs

    base = {
        "serving": {
            "latency_p50_ms": 2.0, "latency_p99_ms": 5.0,
            "actions_per_sec": 1000.0,
            "shapes": {"8": {"p50_ms": 2.0}},
        }
    }
    slower = {
        "serving": {
            "latency_p50_ms": 6.0, "latency_p99_ms": 15.0,
            "actions_per_sec": 300.0,
            "shapes": {"8": {"p50_ms": 6.0}},
        }
    }
    result = compare_runs(base, slower, threshold_pct=50.0)
    by = {v["metric"]: v["verdict"] for v in result["verdicts"]}
    assert by["serve/latency_p50_ms"] == "regressed"   # time-like: grew
    assert by["serve/latency_p99_ms"] == "regressed"
    assert by["serve/actions_per_sec"] == "regressed"  # rate-like: shrank
    assert by["serve/shape8/p50_ms"] == "regressed"
    assert result["regressed"]
    # the improved direction reads as improved, not regressed
    back = compare_runs(slower, base, threshold_pct=50.0)
    by = {v["metric"]: v["verdict"] for v in back["verdicts"]}
    assert by["serve/latency_p50_ms"] == "improved"
    assert not back["regressed"]
    # training-only comparisons grow NO serve rows
    plain = compare_runs({}, {}, threshold_pct=50.0)
    assert not any(
        v["metric"].startswith("serve/") for v in plain["verdicts"]
    )


def test_analyze_cli_exit_contract_on_serving_logs(tmp_path):
    import sys
    sys.path.insert(0, "scripts")
    from analyze_run import main as analyze_main

    base = tmp_path / "base.jsonl"
    same = tmp_path / "same.jsonl"
    slow = tmp_path / "slow.jsonl"
    _serve_log(str(base))
    _serve_log(str(same))
    _serve_log(str(slow), latency_scale=10.0)
    # 0 = clean, 1 = regressed, 2 = unreadable (the documented contract)
    assert analyze_main([str(same), "--compare", str(base)]) == 0
    assert analyze_main([str(slow), "--compare", str(base)]) == 1
    assert analyze_main([str(tmp_path / "missing.jsonl")]) == 2
    # the single-run report renders the serving table
    assert analyze_main([str(base)]) == 0


def test_serve_cli_parser_and_overrides():
    """The serve CLI's config plumbing (the live path is exercised by
    the check.sh serving smoke): flags map onto the config fields that
    shape the restore template and the serving knobs."""
    import sys
    sys.path.insert(0, "scripts")
    from serve import build_parser

    with pytest.raises(SystemExit):  # --checkpoint-dir is required
        build_parser().parse_args([])
    args = build_parser().parse_args([
        "--checkpoint-dir", "/tmp/ck", "--n-envs", "4",
        "--policy-hidden", "32,32", "--vf-hidden", "16",
        "--batch-shapes", "1,2,4", "--deadline-ms", "7.5",
        "--poll-interval", "0.2", "--serve-seconds", "1",
    ])
    assert args.checkpoint_dir == "/tmp/ck"
    assert args.n_envs == 4
    assert args.batch_shapes == "1,2,4"
    assert args.deadline_ms == 7.5


# ---------------------------------------------------------------------------
# shared httpd plumbing
# ---------------------------------------------------------------------------


def test_background_httpd_post_limits_and_handler_errors():
    from trpo_tpu.utils.httpd import BackgroundHTTPServer

    def boom():
        raise RuntimeError("handler bug")

    def echo(body):
        return 200, "application/json", body or b"{}"

    srv = BackgroundHTTPServer(
        0,
        get={"/boom": boom},
        post={"/echo": echo},
        max_body_bytes=64,
    )
    try:
        status, out = _post(srv.url + "/echo", {"x": 1})
        assert status == 200 and out == {"x": 1}
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(srv.url + "/boom", timeout=5)
        assert e.value.code == 500  # handler bug -> 500, server survives
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(srv.url + "/echo", {"x": "y" * 200})
        assert e.value.code == 413  # oversized body refused pre-read
        status, out = _post(srv.url + "/echo", {"x": 2})
        assert status == 200  # still serving after both failures
    finally:
        srv.close()
