"""Train→serve flywheel (ISSUE 19): promotion controller, reward-aware
canary gating, PBT exploit/explore, served-return feedback, and the
boundary-chaos validator contracts.

Contracts pinned here:

* :func:`pick_winner` names the best finished member THROUGH the fleet
  compare-gate (regressed/unreadable/culled/failed never promote;
  ``skipped`` passes — no clean baseline is not a verdict against the
  member), ties break toward the lower member id;
* the :class:`PromotionController` state machine: ``candidate`` →
  ``canary`` → ``promoted``/``rejected``/``rolled_back`` with every
  transition journaled; a terminal promotion is cached (never
  re-published, never re-gated — the no-double-promote guarantee); a
  controller killed mid-promotion (``kill_promoter``) RESTARTS and
  converges on the journal + completion markers without re-publishing;
  a torn ``publishing`` phase re-publishes the SAME serving step; a
  rejected serving step is never reassigned;
* the reward-aware gate verdicts: clean pass, judged regression (the
  reason MUST name the realized return — the validator's
  ``regress_checkpoint`` matcher keys on it), starved canary window and
  thin incumbent baseline are TRANSIENT (prefix-matched against
  ``_TRANSIENT_REASONS`` so they never blacklist), a canary death
  mid-window resolves transient; the gate is disarmed by default
  (``reward_window_episodes=0`` — the PR 11 behavior);
* the router's flywheel half: session CREATES stride
  ``canary_fraction`` onto the canary, and client-reported per-act
  ``reward``/``done`` books completed-episode returns per replica;
* PBT exploit/explore: a culled member respawns FROM THE WINNER'S
  checkpoint with deterministically perturbed hypers, its event log
  rotates aside, the fleet gate skips the respawn segment, and the
  fleet result carries the ``fleet/wall`` BENCH row;
* served feedback blends into member scores episode-weighted;
* the validator fails a stranded ``promote`` candidate and matches the
  three boundary faults by their REQUIRED detectors.
"""

import json
import math
import os
import random
import sys
import threading
import types
import urllib.error
import urllib.request

import numpy as np
import pytest

from trpo_tpu.fleet import FleetScheduler, FleetSpec, MemberSpec
from trpo_tpu.fleet.promote import (
    JOURNAL_NAME,
    PromotionController,
    feedback_scores,
    pick_winner,
)
from trpo_tpu.obs.events import EventBus, validate_event
from trpo_tpu.resilience.inject import FaultInjector, PromoterKilled
from trpo_tpu.serve.replicaset import CanaryController

# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _recording_bus():
    events = []
    return EventBus(lambda rec: events.append(rec)), events


def _post(url, payload=None, timeout=30.0):
    data = b"" if payload is None else json.dumps(payload).encode()
    req = urllib.request.Request(
        url, data=data, headers={"Content-Type": "application/json"}
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class FakeCheckpointer:
    """Marker-faithful in-memory checkpointer: per-directory backing
    store shared across handles (the on-disk persistence a restarted
    controller converges on), a save/marker split so torn publishes can
    be staged, and a save counter pinning no-double-publish."""

    registry: dict = {}

    def __init__(self, directory):
        self.dir = os.path.abspath(directory)
        self.store = self.registry.setdefault(
            self.dir, {"steps": {}, "markers": set(), "saves": 0}
        )

    def latest_step(self, refresh=False):
        return max(self.store["markers"]) if self.store["markers"] else None

    def restore(self, template, step=None, prune=True):
        return self.store["steps"][step]

    def save(self, step, state):
        self.store["steps"][step] = state
        self.store["markers"].add(step)
        self.store["saves"] += 1

    def refresh(self):
        pass

    def prune_incomplete(self):
        for s in list(self.store["steps"]):
            if s not in self.store["markers"]:
                del self.store["steps"][s]

    def _complete_steps(self):
        return sorted(self.store["markers"])

    def close(self):
        pass


def _seed_member_ck(directory, step, state):
    FakeCheckpointer(directory).save(step, state)


class FakeCanary:
    """A scripted gate: ``script[serve_step]`` is ``"promote"`` /
    ``"reject"`` / absent (never resolves — the controller's deadline
    fires). Carries the real controller's observable surface — the
    shared ``incumbent`` cell and the ``_rejected_steps`` blacklist —
    which is all :meth:`PromotionController._drive_gate` reads."""

    def __init__(self, serve_dir, script=None):
        self.serve_dir = serve_dir
        self.script = dict(script or {})
        self.incumbent = {"step": None}
        self._rejected_steps = set()
        self.ticks = 0
        self.router = None
        self.replicaset = None

    def tick(self):
        self.ticks += 1
        step = FakeCheckpointer(self.serve_dir).latest_step()
        if (
            step is None
            or step == self.incumbent["step"]
            or step in self._rejected_steps
        ):
            return
        verdict = self.script.get(step)
        if verdict == "promote":
            self.incumbent["step"] = step
        elif verdict == "reject":
            self._rejected_steps.add(step)


def _controller(serve_dir, canary, bus=None, injector=None, **kw):
    kw.setdefault("gate_timeout_s", 10.0)
    kw.setdefault("poll_interval", 0.005)
    return PromotionController(
        serve_dir, template=None, canary=canary, bus=bus,
        injector=injector, checkpointer_factory=FakeCheckpointer, **kw,
    )


@pytest.fixture(autouse=True)
def _fresh_fake_stores():
    FakeCheckpointer.registry.clear()
    yield
    FakeCheckpointer.registry.clear()


# ---------------------------------------------------------------------------
# pick_winner / feedback_scores (pure)
# ---------------------------------------------------------------------------


def test_pick_winner_goes_through_the_gate():
    result = {
        "scores": {"a": 3.0, "b": 9.0, "c": 7.0, "d": 8.0, "e": 6.0,
                   "f": float("-inf")},
        "culled": ["c"],
        "failed": ["e"],
        "gate": {"members": {
            "a": {"verdict": "ok"},
            "b": {"verdict": "regressed"},
            "d": {"verdict": "skipped"},
        }},
    }
    # b scored best but the gate judged it regressed; c culled, e
    # failed, f non-finite — d (gate skipped) wins
    assert pick_winner(result) == "d"
    result["gate"]["members"]["d"] = {"verdict": "unreadable"}
    assert pick_winner(result) == "a"
    assert pick_winner({"scores": {}}) is None
    # ties break toward the lower member id, deterministically
    tied = {"scores": {"m2": 5.0, "m1": 5.0, "m0": 4.0}, "gate": {}}
    assert pick_winner(tied) == "m1"


def test_feedback_scores_pools_episode_weighted():
    def fb(member, mean, episodes, **extra):
        return {"v": 1, "t": 1.0, "kind": "promote", "member": member,
                "event": "feedback", "step": 1, "mean_return": mean,
                "episodes": episodes, **extra}

    records = [
        fb("m0", 2.0, 3),
        fb("m0", 4.0, 1),
        fb("m1", -1.0, 2),
        fb("m2", float("nan"), 2),          # non-finite mean: skipped
        fb("m3", 1.0, 0),                    # zero episodes: skipped
        {"v": 1, "kind": "promote", "member": "m0",
         "event": "promoted", "step": 1},     # not a feedback record
        {"kind": "iteration", "iteration": 1},
    ]
    scores = feedback_scores(records)
    assert scores == {"m0": ((2.0 * 3 + 4.0) / 4, 4), "m1": (-1.0, 2)}
    assert feedback_scores([]) == {}


# ---------------------------------------------------------------------------
# PromotionController state machine (stub canary + checkpointer, no HTTP)
# ---------------------------------------------------------------------------


def test_promote_walks_candidate_canary_promoted_and_caches(tmp_path):
    src = str(tmp_path / "member")
    serve = str(tmp_path / "serve")
    _seed_member_ck(src, 7, {"w": [1.0, 2.0]})
    canary = FakeCanary(serve, script={1: "promote"})
    bus, events = _recording_bus()
    ctrl = _controller(serve, canary, bus=bus)

    res = ctrl.promote("m0", src)
    assert res["outcome"] == "promoted" and res["reason"] is None
    assert res["member"] == "m0"
    assert res["src_step"] == 7 and res["serve_step"] == 1
    # the member's state landed, marker-complete, in the serving dir
    serve_store = FakeCheckpointer(serve).store
    assert serve_store["steps"][1] == {"w": [1.0, 2.0]}
    assert serve_store["saves"] == 1
    assert canary.incumbent["step"] == 1
    # typed promote events, in lifecycle order, schema-valid
    promote_evs = [e for e in events if e["kind"] == "promote"]
    assert [(e["event"], e["step"]) for e in promote_evs] == [
        ("candidate", 1), ("canary", 1), ("promoted", 1),
    ]
    for e in events:
        assert validate_event(e) == [], e
    # the journal holds the terminal entry
    with open(os.path.join(serve, JOURNAL_NAME)) as f:
        journal = json.load(f)
    assert journal["entries"]["m0@7"]["outcome"] == "promoted"

    # no-double-promote: the repeat is answered from the journal —
    # no new publish, no new gate, no new events
    n_events = len(events)
    res2 = ctrl.promote("m0", src)
    assert res2["outcome"] == "promoted"
    assert serve_store["saves"] == 1
    assert len(events) == n_events


def test_rejected_step_blacklists_and_is_never_reassigned(tmp_path):
    src = str(tmp_path / "member")
    serve = str(tmp_path / "serve")
    _seed_member_ck(src, 3, {"w": [0.5]})
    canary = FakeCanary(serve, script={1: "reject", 2: "promote"})
    bus, events = _recording_bus()
    ctrl = _controller(serve, canary, bus=bus)

    res = ctrl.promote("m0", src)
    assert res["outcome"] == "rejected"
    assert "rejected" in res["reason"]
    assert 1 in canary._rejected_steps
    assert canary.incumbent["step"] is None
    # a different candidate NEVER reuses the blacklisted serving step
    src2 = str(tmp_path / "member2")
    _seed_member_ck(src2, 5, {"w": [0.7]})
    res2 = ctrl.promote("m1", src2)
    assert res2["serve_step"] == 2 and res2["outcome"] == "promoted"
    terminal = [
        (e["member"], e["event"], e["step"])
        for e in events
        if e["kind"] == "promote"
        and e["event"] in ("promoted", "rejected", "rolled_back")
    ]
    assert terminal == [("m0", "rejected", 1), ("m1", "promoted", 2)]


def test_unresolved_gate_rolls_back_on_deadline(tmp_path):
    src = str(tmp_path / "member")
    serve = str(tmp_path / "serve")
    _seed_member_ck(src, 2, {"w": [1.0]})
    canary = FakeCanary(serve, script={})  # the gate never resolves
    ctrl = _controller(serve, canary)
    res = ctrl.promote("m0", src, timeout_s=0.15)
    assert res["outcome"] == "rolled_back"
    assert "did not resolve" in res["reason"]
    assert canary.ticks > 0  # the controller was driving the gate


def test_kill_promoter_restart_converges_without_republishing(tmp_path):
    src = str(tmp_path / "member")
    serve = str(tmp_path / "serve")
    _seed_member_ck(src, 4, {"w": [9.0]})
    bus, events = _recording_bus()
    injector = FaultInjector.from_spec("kill_promoter@step=1", bus=bus)
    canary = FakeCanary(serve, script={1: "promote"})
    ctrl = _controller(serve, canary, bus=bus, injector=injector)

    with pytest.raises(PromoterKilled):
        ctrl.promote("m0", src)
    assert injector.all_fired
    serve_store = FakeCheckpointer(serve).store
    # the controller died AFTER the durable publish, BEFORE the gate
    assert serve_store["saves"] == 1 and 1 in serve_store["markers"]
    with open(os.path.join(serve, JOURNAL_NAME)) as f:
        entry = json.load(f)["entries"]["m0@4"]
    assert entry["phase"] == "published" and entry["outcome"] is None
    # mid-promotion: a candidate event exists but no terminal yet
    assert [(e["event"]) for e in events if e["kind"] == "promote"] == [
        "candidate"
    ]

    # the restarted controller (fresh instance, no injector) re-reads
    # journal + markers and converges — WITHOUT a second publish
    ctrl2 = _controller(serve, canary, bus=bus)
    res = ctrl2.promote("m0", src)
    assert res["outcome"] == "promoted" and res["serve_step"] == 1
    assert serve_store["saves"] == 1
    for e in events:
        assert validate_event(e) == [], e
    kinds = [(e["event"], e["step"]) for e in events
             if e["kind"] == "promote"]
    # candidate emitted ONCE (before the kill); the restart goes
    # straight to the gate and lands the terminal
    assert kinds == [("candidate", 1), ("canary", 1), ("promoted", 1)]


def test_torn_publishing_phase_republishes_same_step(tmp_path):
    src = str(tmp_path / "member")
    serve = str(tmp_path / "serve")
    _seed_member_ck(src, 6, {"w": [3.0]})
    canary = FakeCanary(serve, script={2: "promote"})
    ctrl = _controller(serve, canary)
    # a previous incarnation died mid-publish: journal says publishing
    # at serve step 2, and the serving dir holds a TORN (marker-less)
    # half-save of that step
    os.makedirs(serve, exist_ok=True)
    with open(os.path.join(serve, JOURNAL_NAME), "w") as f:
        json.dump({"entries": {"m0@6": {
            "member": "m0", "src_step": 6, "serve_step": 2,
            "phase": "publishing", "outcome": None, "reason": None,
        }}}, f)
    serve_store = FakeCheckpointer(serve).store
    serve_store["steps"][2] = {"w": ["TORN"]}  # no marker
    res = ctrl.promote("m0", src)
    # the SAME serving step was pruned, re-published and promoted
    assert res["serve_step"] == 2 and res["outcome"] == "promoted"
    assert serve_store["steps"][2] == {"w": [3.0]}
    assert serve_store["saves"] == 1


def test_next_serve_step_is_monotonic_over_all_floors(tmp_path):
    serve = str(tmp_path / "serve")
    canary = FakeCanary(serve)
    ctrl = _controller(serve, canary)
    assert ctrl._next_serve_step() == 1
    canary.incumbent["step"] = 5
    assert ctrl._next_serve_step() == 6
    FakeCheckpointer(serve).save(7, {})
    assert ctrl._next_serve_step() == 8
    # journal-assigned steps floor it too — a blacklisted step from a
    # crashed promotion is never handed to the next candidate
    ctrl._save_entry("mX@1", {"serve_step": 11})
    assert ctrl._next_serve_step() == 12


def test_promote_without_source_checkpoint_raises(tmp_path):
    ctrl = _controller(
        str(tmp_path / "serve"), FakeCanary(str(tmp_path / "serve"))
    )
    with pytest.raises(FileNotFoundError, match="no complete checkpoint"):
        ctrl.promote("m0", str(tmp_path / "empty"))


def test_promotion_feedback_pools_served_episodes(tmp_path):
    serve = str(tmp_path / "serve")
    canary = FakeCanary(serve)
    canary.router = types.SimpleNamespace(
        replica_episode_returns=lambda rid: {
            "r0": [1.0, 3.0], "r1": [5.0]
        }.get(rid, [])
    )
    canary.replicaset = types.SimpleNamespace(
        lock=threading.Lock(), replicas={"r0": None, "r1": None}
    )
    bus, events = _recording_bus()
    ctrl = _controller(serve, canary, bus=bus)
    out = ctrl.feedback("m0", 3)
    assert out["episodes"] == 3 and out["mean_return"] == 3.0
    fb = [e for e in events if e["kind"] == "promote"]
    assert len(fb) == 1 and fb[0]["event"] == "feedback"
    assert validate_event(fb[0]) == []
    # round-trips through the reader the next fleet round uses
    assert feedback_scores(events) == {"m0": (3.0, 3)}


# ---------------------------------------------------------------------------
# reward-aware gate verdicts (stub router/replicaset, no HTTP)
# ---------------------------------------------------------------------------


class _StubRouter:
    def __init__(self):
        self.eps = {}
        self.bodies = []

    def replica_episode_returns(self, rid):
        return list(self.eps.get(rid, []))

    def reset_replica_episodes(self):
        self.eps.clear()

    def recent_act_bodies(self, n=8):
        return self.bodies[-n:]


def _reward_gate(**kw):
    rs = types.SimpleNamespace(lock=threading.Lock(), replicas={})
    router = _StubRouter()
    kw.setdefault("window_requests", 1)
    kw.setdefault("gate_timeout_s", 0.25)
    kw.setdefault("poll_interval", 0.01)
    ctrl = CanaryController(rs, router, lambda: None, **kw)
    rec = types.SimpleNamespace(id="c0", state="healthy", restarts=0)
    return ctrl, router, rec


def test_reward_gate_passes_within_budget():
    ctrl, router, rec = _reward_gate(
        reward_window_episodes=3, reward_min_episodes=2,
        reward_budget=0.5,
    )
    router.eps = {"c0": [1.0, 1.2, 0.8], "r0": [1.1], "r1": [1.3]}
    ok, reason = ctrl._judge_reward(rec, ["r0", "r1"], 0)
    assert ok and reason is None
    # worse — but within the budget — still passes
    router.eps["c0"] = [0.8, 0.8, 0.8]
    ok, _ = ctrl._judge_reward(rec, ["r0", "r1"], 0)
    assert ok


def test_reward_gate_judges_regression_naming_realized_return():
    ctrl, router, rec = _reward_gate(
        reward_window_episodes=2, reward_budget=0.5,
    )
    router.eps = {"c0": [0.0, 0.1], "r0": [2.0, 2.2]}
    ok, reason = ctrl._judge_reward(rec, ["r0"], 0)
    assert not ok
    # the validator's regress_checkpoint matcher keys on this phrase —
    # a reworded reason silently breaks the chaos contract
    assert "realized return" in reason
    assert "2 canary vs 2 incumbent" in reason
    # a JUDGED reason is not transient: it must blacklist
    assert not any(
        reason.startswith(t) for t in CanaryController._TRANSIENT_REASONS
    )


def test_reward_gate_starved_and_thin_baseline_are_transient():
    ctrl, router, rec = _reward_gate(
        reward_window_episodes=3, reward_min_episodes=2,
    )
    # canary never fills its window within the gate timeout
    router.eps = {"c0": [1.0], "r0": [1.0, 1.0]}
    ok, reason = ctrl._judge_reward(rec, ["r0"], 0)
    assert not ok and reason.startswith("reward window starved")
    # incumbents under the min-episode floor: unusable baseline
    router.eps = {"c0": [1.0, 1.0, 1.0], "r0": [1.0]}
    ok, reason = ctrl._judge_reward(rec, ["r0"], 0)
    assert not ok and reason.startswith("no usable reward baseline")
    # both are prefix-matched transient — retried, never blacklisted
    for r in ("reward window starved: 1/3", "no usable reward baseline"):
        assert any(
            r.startswith(t) for t in CanaryController._TRANSIENT_REASONS
        )


def test_reward_gate_canary_death_is_transient():
    ctrl, router, rec = _reward_gate(reward_window_episodes=2)
    router.eps = {"c0": []}
    rec.restarts = 1  # relaunched mid-window: the snapshot is gone
    ok, reason = ctrl._judge_reward(rec, [], 0)
    assert not ok and reason == "canary died mid-gate"


def test_reward_gate_defaults_disarmed_and_validates_params():
    ctrl, _, _ = _reward_gate()
    assert ctrl.reward_window_episodes == 0  # PR 11 behavior untouched
    assert ctrl.reward_min_episodes == 1
    assert ctrl.reward_budget == 0.0
    with pytest.raises(ValueError, match="reward_window_episodes"):
        _reward_gate(reward_window_episodes=-1)
    with pytest.raises(ValueError, match="reward_min_episodes"):
        _reward_gate(reward_min_episodes=0)
    with pytest.raises(ValueError, match="reward_budget"):
        _reward_gate(reward_budget=-0.1)


def test_config_reward_fields_validate():
    from trpo_tpu.config import TRPOConfig

    cfg = TRPOConfig(serve_reward_window=4, serve_reward_min_episodes=2,
                     serve_reward_budget=0.5)
    assert cfg.serve_reward_window == 4
    with pytest.raises(ValueError, match="serve_reward_window"):
        TRPOConfig(serve_reward_window=-1)
    with pytest.raises(ValueError, match="serve_reward_budget"):
        TRPOConfig(serve_reward_budget=-0.5)


# ---------------------------------------------------------------------------
# router: session striding + realized-return booking (recurrent stack)
# ---------------------------------------------------------------------------

_CFG = dict(
    n_envs=4, batch_timesteps=32, cg_iters=2, vf_train_steps=2,
    policy_hidden=(8,), vf_hidden=(8,), seed=11,
)


@pytest.fixture(scope="module")
def rec():
    from trpo_tpu.agent import TRPOAgent
    from trpo_tpu.config import TRPOConfig

    agent = TRPOAgent("pendulum", TRPOConfig(**{**_CFG, "policy_gru": 8}))
    state = agent.init_state(seed=0)
    return agent, state


def _rec_factory(agent, state, bus=None):
    from trpo_tpu.serve import PolicyServer

    def make(rid):
        def factory():
            engine = agent.serve_session_engine()
            engine.load(state.policy_params, state.obs_norm, step=1)
            server = PolicyServer(
                engine, None, port=0, bus=bus, replica_name=rid,
            )
            return server, []

        return factory

    return make


def _replicaset(make, n, bus=None, **kw):
    from trpo_tpu.serve import InProcessReplica, ReplicaSet

    kw.setdefault("health_interval", 60.0)
    kw.setdefault("backoff", 0.05)
    kw.setdefault("health_fail_threshold", 1)
    kw.setdefault("max_restarts", 2)
    rs = ReplicaSet(
        lambda rid: InProcessReplica(make(rid)), n, bus=bus, **kw
    )
    assert rs.wait_healthy(n, timeout=60.0), rs.snapshot()
    return rs


def test_session_stride_and_episode_booking(rec):
    from trpo_tpu.serve import Router

    agent, state = rec
    bus, events = _recording_bus()
    rs = _replicaset(_rec_factory(agent, state, bus=bus), 2, bus=bus)
    router = Router(rs, port=0, bus=bus, canary_fraction=0.5)
    try:
        with rs.lock:
            rs.replicas["r1"].canary = True
        pins = []
        for _ in range(8):
            s, out = _post(router.url + "/session")
            assert s == 200
            pins.append((out["session"], out["replica"]))
        # deterministic session stride at 0.5: exactly half the CREATES
        # pin to the canary — whole episodes, the reward gate's unit
        assert sum(1 for _, r in pins if r == "r1") == 4, pins
        obs = np.zeros(agent.obs_shape, np.float32).tolist()
        for sid, rid in pins:
            reward = 1.0 if rid == "r1" else 0.5
            for t in range(3):
                s, out = _post(
                    router.url + f"/session/{sid}/act",
                    {"obs": obs, "reward": reward, "done": t == 2},
                )
                assert s == 200, out
        assert sorted(router.replica_episode_returns("r1")) == [3.0] * 4
        assert sorted(router.replica_episode_returns("r0")) == [1.5] * 4
        assert router.episodes_total == 8
        # episode events rode the bus (the fleet feedback path)
        eps = [e for e in events if e["kind"] == "session"
               and e["event"] == "episode"]
        assert len(eps) == 8
        for e in eps:
            assert validate_event(e) == [], e
        assert {e["replica"] for e in eps} == {"r0", "r1"}
        # a malformed reward is ignored, not booked and not a 500
        s, out = _post(router.url + "/session")
        sid = out["session"]
        s, out = _post(
            router.url + f"/session/{sid}/act",
            {"obs": obs, "reward": "seven"},
        )
        assert s == 200
        assert router.episodes_total == 8
        # the gate's fresh-window reset
        router.reset_replica_episodes()
        assert router.replica_episode_returns("r1") == []
    finally:
        router.close()
        rs.close()


# ---------------------------------------------------------------------------
# PBT exploit/explore on the fleet (stub subprocess members)
# ---------------------------------------------------------------------------

_STUB_MEMBER = """
import sys, os, json
member_dir, reward = sys.argv[1], float(sys.argv[2])
with open(os.path.join(member_dir, "events.jsonl"), "a") as f:
    f.write(json.dumps({"v":1,"t":0.0,"kind":"run_manifest",
        "schema":"trpo-tpu-events","jax_version":"0","backend":"cpu",
        "config_hash":"0123456789abcdef","config":None}) + "\\n")
    for i in (1, 2):
        f.write(json.dumps({"v":1,"t":float(i),"kind":"iteration",
            "iteration":i,"stats":{"iteration_ms":5.0,
            "cg_iters_total":1,"linesearch_trials_total":1,
            "mean_episode_reward":reward,"episodes_in_batch":4}}) + "\\n")
sys.exit(0)
"""


def _member_launcher(rewards, respawn_reward=None):
    calls = {}

    def launcher(member, ctx):
        mid = member.member_id
        n = calls.get(mid, 0)
        calls[mid] = n + 1
        reward = rewards[mid]
        if n > 0 and respawn_reward is not None:
            reward = respawn_reward  # the explore segment paid off
        return [sys.executable, "-c", _STUB_MEMBER, ctx["member_dir"],
                str(reward)]

    return launcher


def _pbt_spec(members, **kw):
    kw.setdefault("requeue_backoff", 0.01)
    kw.setdefault("poll_interval", 0.02)
    kw.setdefault("scrape_interval", 60.0)
    kw.setdefault("max_workers", 3)
    return FleetSpec(members=tuple(members), **kw)


def test_pbt_spec_fields_validate():
    spec = _pbt_spec([MemberSpec("m0")], pbt_rounds=2,
                     pbt_iterations=3, pbt_perturb=0.25)
    assert spec.pbt_rounds == 2 and spec.pbt_perturb == 0.25
    with pytest.raises(ValueError, match="pbt_rounds"):
        _pbt_spec([MemberSpec("m0")], pbt_rounds=-1)
    with pytest.raises(ValueError, match="pbt_iterations"):
        _pbt_spec([MemberSpec("m0")], pbt_iterations=0)
    with pytest.raises(ValueError, match="pbt_perturb"):
        _pbt_spec([MemberSpec("m0")], pbt_perturb=1.5)


def test_pbt_respawns_culled_member_from_winner(tmp_path):
    rewards = {"good": 2.0, "mid": 1.0, "bad": 0.0}
    spec = _pbt_spec(
        [
            MemberSpec("good"),
            MemberSpec("mid"),
            MemberSpec("bad", (("lam", "0.9"), ("cg_damping", "0.2"),
                               ("seed", "3"))),
        ],
        cull_bottom_k=1, pbt_rounds=1, pbt_iterations=2,
        pbt_perturb=0.2,
    )
    bus, events = _recording_bus()
    sch = FleetScheduler(
        spec, str(tmp_path), bus=bus,
        launcher=_member_launcher(rewards, respawn_reward=3.0),
        latest_step_fn=lambda d: 5 if os.path.isdir(d) else None,
    )
    # the winner's "checkpoint": a real directory the exploit copies
    win_ck = sch.members["good"].checkpoint_dir
    os.makedirs(win_ck, exist_ok=True)
    with open(os.path.join(win_ck, "5.ckpt"), "w") as f:
        f.write("winner-weights")
    try:
        result = sch.run()
    finally:
        sch.close()
        bus.close()
    # bad was culled, then respawned from good@5 with perturbed hypers
    assert result["respawned"] == ["bad"]
    rec = sch.members["bad"]
    assert rec.respawned is True
    assert os.path.exists(
        os.path.join(rec.checkpoint_dir, "5.ckpt")
    ), "exploit did not copy the winner's checkpoint"
    # deterministic explore: recompute from the same (member, attempt)
    # seed — the respawn perturbed at attempt 1, BEFORE the relaunch
    # bumped the counter to 2
    assert rec.attempt == 2
    ov = rec.spec.overrides_dict
    rng = random.Random(f"bad:{rec.attempt - 1}")
    factor = 0.8 if rng.random() < 0.5 else 1.2
    assert int(ov["seed"]) == rng.randrange(2 ** 31)
    assert float(ov["lam"]) == round(
        min(max(1.0 - (1.0 - 0.9) * factor, 0.0), 1.0), 6
    )
    assert float(ov["cg_damping"]) == round(0.2 * factor, 8)
    # the explore segment resumes at the winner's step, bounded
    assert rec.resume_step == 5 and rec.total_override == 7
    # the first segment's log rotated aside; the respawn ran fresh
    assert os.path.exists(os.path.join(rec.member_dir,
                                       "events.gen1.jsonl"))
    assert rec.state == "finished"
    # lifecycle events: culled -> respawned (with the exploit recipe),
    # all schema-valid
    fleet_evs = [e for e in events if e["kind"] == "fleet"]
    for e in fleet_evs:
        assert validate_event(e) == [], e
    resp = [e for e in fleet_evs if e["state"] == "respawned"]
    assert len(resp) == 1 and resp[0]["member"] == "bad"
    assert "pbt exploit good@5" in resp[0]["reason"]
    assert resp[0]["resume_step"] == 5
    # the gate skips the respawn segment (a resumed explore budget is
    # not comparable to a full reference run)
    assert result["gate"]["members"]["bad"]["verdict"] == "skipped"
    assert "respawn" in result["gate"]["members"]["bad"]["reason"]
    # the fleet BENCH row rode the result and the bus
    bench = result["bench"]
    assert bench["fleet_wall_ms"] > 0
    assert bench["members_wall_ms"] >= 0
    assert bench["max_workers"] == 3
    walls = [e for e in events if e["kind"] == "phase"
             and e["name"] == "fleet/wall"]
    assert walls and walls[-1]["ms"] > 0
    for e in walls:
        assert validate_event(e) == [], e


def test_member_final_scores_blends_served_feedback(tmp_path):
    spec = _pbt_spec([MemberSpec("m0")])
    sch = FleetScheduler(
        spec, str(tmp_path),
        launcher=_member_launcher({"m0": 2.0}),
        feedback={"m0": (10.0, 4)},
    )
    try:
        sch.run()
        # training: reward 2.0 over 2 iterations x 4 episodes (8 eps);
        # served: mean 10.0 over 4 episodes — pooled episode-weighted
        scores = sch.member_final_scores()
    finally:
        sch.close()
    assert scores["m0"] == pytest.approx(
        (2.0 * 8 + 10.0 * 4) / 12
    )


# ---------------------------------------------------------------------------
# validator contracts: stranded promotions + the three boundary faults
# ---------------------------------------------------------------------------


def _write(path, recs):
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    return str(path)


@pytest.fixture()
def validate_file():
    sys.path.insert(0, "scripts")
    from validate_events import validate_file as vf

    return vf


def _manifest():
    from trpo_tpu.obs.events import manifest_fields

    return {"v": 1, "kind": "run_manifest", "t": 0.0,
            **manifest_fields(None)}


def _promote(event, step, t, **extra):
    return {"v": 1, "kind": "promote", "t": t, "member": "m0",
            "event": event, "step": step, **extra}


def test_validator_fails_stranded_promote_candidate(
    tmp_path, validate_file
):
    manifest = _manifest()
    ok = _write(tmp_path / "ok.jsonl", [
        manifest,
        _promote("candidate", 2, 1.0, src_step=5),
        _promote("canary", 2, 2.0),
        _promote("promoted", 2, 3.0),
        _promote("feedback", 2, 4.0, episodes=3, mean_return=1.5),
    ])
    assert validate_file(ok) == []
    # no terminal: stranded
    errs = validate_file(_write(tmp_path / "bad.jsonl", [
        manifest, _promote("candidate", 2, 1.0),
    ]))
    assert errs and any("stranded promotion" in e for e in errs)
    # a terminal for a DIFFERENT serving step does not resolve it
    errs = validate_file(_write(tmp_path / "bad2.jsonl", [
        manifest, _promote("candidate", 2, 1.0),
        _promote("rejected", 3, 2.0),
    ]))
    assert any("stranded promotion" in e for e in errs)
    # malformed promote records fail outright
    assert validate_event(_promote("teleported", 2, 1.0))
    assert validate_event({k: v for k, v in
                           _promote("candidate", 2, 1.0).items()
                           if k != "member"})


def _fault(kind, at):
    return {"v": 1, "kind": "fault_injected", "t": 1.0, "fault": kind,
            "at": at, "spec": f"{kind}@step={at}"}


def test_validator_matches_corrupt_checkpoint(tmp_path, validate_file):
    manifest = _manifest()
    health = {
        "v": 1, "kind": "health", "t": 2.0, "check": "canary_rejected",
        "level": "warn", "message": "reload failed",
        "data": {"step": 3, "replica": "r1"},
    }
    assert validate_file(_write(tmp_path / "ok.jsonl", [
        manifest, _fault("corrupt_checkpoint", 3), health,
    ])) == []
    # the promotion controller's own terminal also satisfies it
    assert validate_file(_write(tmp_path / "ok2.jsonl", [
        manifest, _promote("candidate", 3, 0.5),
        _fault("corrupt_checkpoint", 3),
        _promote("rejected", 3, 2.0),
    ])) == []
    errs = validate_file(_write(tmp_path / "bad.jsonl", [
        manifest, _fault("corrupt_checkpoint", 3),
        {**health, "data": {"step": 4, "replica": "r1"}},
    ]))
    assert any("no matching detection" in e for e in errs)


def test_validator_regress_requires_realized_return(
    tmp_path, validate_file
):
    manifest = _manifest()
    rolled = {
        "v": 1, "kind": "canary", "t": 2.0, "step": 5,
        "event": "rolled_back", "replica": "r1",
        "reason": "canary realized return -3.1 under incumbent -0.2 "
                  "by more than budget 0.5",
    }
    assert validate_file(_write(tmp_path / "ok.jsonl", [
        manifest, _fault("regress_checkpoint", 5),
        {**rolled, "t": 1.5, "event": "started", "reason": None},
        rolled,
    ])) == []
    # a p99 rejection of the same step does NOT satisfy the matcher —
    # the regression itself went undetected
    errs = validate_file(_write(tmp_path / "bad.jsonl", [
        manifest, _fault("regress_checkpoint", 5),
        {**rolled, "t": 1.5, "event": "started", "reason": None},
        {**rolled, "reason": "canary p99 9.0ms over budget 5.0ms"},
    ]))
    assert any("no matching detection" in e for e in errs)


def test_validator_kill_promoter_needs_convergence(
    tmp_path, validate_file
):
    manifest = _manifest()
    assert validate_file(_write(tmp_path / "ok.jsonl", [
        manifest, _promote("candidate", 4, 0.5),
        _fault("kill_promoter", 4),
        _promote("promoted", 4, 3.0),
    ])) == []
    errs = validate_file(_write(tmp_path / "bad.jsonl", [
        manifest, _fault("kill_promoter", 4),
    ]))
    assert any("no matching detection" in e for e in errs)


# ---------------------------------------------------------------------------
# chaos hooks at the plane boundary (real injector, no serving stack)
# ---------------------------------------------------------------------------


def test_boundary_fault_specs_parse_and_hooks_fire():
    from collections import namedtuple

    from trpo_tpu.resilience.inject import parse_fault_specs

    specs = parse_fault_specs(
        "corrupt_checkpoint@step=2;regress_checkpoint@step=3;"
        "kill_promoter@step=4"
    )
    assert [s.kind for s in specs] == [
        "corrupt_checkpoint", "regress_checkpoint", "kill_promoter",
    ]
    assert all(s.serve_level for s in specs)
    for s in specs:
        assert parse_fault_specs(str(s))[0] == s
    inj = FaultInjector(specs)
    # training hook sites never fire serving faults
    assert inj.before_iteration(2, None, span=10) is None
    assert not inj._fired
    # regress: float policy leaves scale x8 (finite — only the reward
    # gate can catch it); other steps pass through untouched
    State = namedtuple("State", ["policy_params", "vf_params"])
    state = State(
        policy_params={"w": np.ones(3, np.float32),
                       "n": np.ones(2, np.int32)},
        vf_params={"v": np.ones(2, np.float32)},
    )
    out = inj.on_checkpoint_publish(3, state)
    w = np.asarray(out.policy_params["w"])
    assert np.all(w == 8.0) and np.all(np.isfinite(w))
    assert np.all(np.asarray(out.policy_params["n"]) == 1)
    assert np.all(np.asarray(out.vf_params["v"]) == 1.0)  # policy only
    # one-shot: a second publish at the same step is clean
    again = inj.on_checkpoint_publish(3, state)
    assert np.all(np.asarray(again.policy_params["w"]) == 1.0)
    # kill: raises exactly once at its step
    inj.on_promotion(99)  # not its step: no-op
    with pytest.raises(PromoterKilled, match="serving step 4"):
        inj.on_promotion(4)
    inj.on_promotion(4)  # fired: converging restart passes through


def test_corrupt_checkpoint_tears_published_files(tmp_path):
    inj = FaultInjector.from_spec("corrupt_checkpoint@step=2")
    step_dir = tmp_path / "2"
    (step_dir / "sub").mkdir(parents=True)
    (step_dir / "weights.bin").write_bytes(b"x" * 100)
    (step_dir / "sub" / "meta.json").write_bytes(b"y" * 40)
    inj.on_checkpoint_published(2, str(step_dir))
    assert (step_dir / "weights.bin").stat().st_size == 50
    assert (step_dir / "sub" / "meta.json").stat().st_size == 20
    assert inj.all_fired
    # an empty step dir cannot execute the fault: loud, and UNFIRED
    inj2 = FaultInjector.from_spec("corrupt_checkpoint@step=3")
    empty = tmp_path / "3"
    empty.mkdir()
    with pytest.raises(ValueError, match="no payload files"):
        inj2.on_checkpoint_published(3, str(empty))
    assert not inj2.all_fired


# ---------------------------------------------------------------------------
# analyze rows
# ---------------------------------------------------------------------------


def test_analyze_promote_and_episode_rows():
    from trpo_tpu.obs.analyze import (
        compare_runs,
        render_summary,
        summarize_run,
    )

    def rec_(kind, t, **f):
        return {"v": 1, "kind": kind, "t": t, **f}

    records = [
        rec_("run_manifest", 0.0, schema="trpo-tpu-events",
             jax_version="x", backend="cpu", config_hash="0" * 16,
             config=None),
        rec_("session", 1.0, session="a", event="episode", replica="r0",
             ep_return=1.0, ep_steps=10),
        rec_("session", 2.0, session="b", event="episode", replica="r1",
             ep_return=3.0, ep_steps=10),
        rec_("promote", 3.0, member="m0", event="candidate", step=2,
             src_step=5),
        rec_("promote", 4.0, member="m0", event="canary", step=2),
        rec_("promote", 5.0, member="m0", event="rejected", step=2,
             reason="canary realized return -2 under incumbent 0"),
        rec_("promote", 6.0, member="m1", event="candidate", step=3),
        rec_("promote", 7.0, member="m1", event="promoted", step=3),
        rec_("promote", 8.0, member="m1", event="feedback", step=3,
             episodes=2, mean_return=2.0),
    ]
    summary = summarize_run(records)
    rt = summary["router"]
    assert rt["episodes"]["episodes"] == 2
    assert rt["episodes"]["mean_return"] == 2.0
    pr = rt["promote"]
    assert pr["candidates"] == 2
    assert pr["promoted"] == 1 and pr["rejected"] == 1
    assert pr["steps"]["2"]["outcome"] == "rejected"
    assert pr["steps"]["3"]["member"] == "m1"
    assert pr["feedback_episodes"] == 2
    text = render_summary(summary)
    assert "promote:" in text and "episodes" in text
    # a rolled_back rise is a strict-counter regression
    worse = records + [
        rec_("promote", 9.0, member="m2", event="candidate", step=4),
        rec_("promote", 10.0, member="m2", event="rolled_back", step=4),
    ]
    cmp_bad = compare_runs(summarize_run(records), summarize_run(worse))
    rows = {v["metric"]: v for v in cmp_bad["verdicts"]}
    assert rows["router/promote_rolled_back"]["verdict"] == "regressed"
    assert cmp_bad["regressed"]


# ---------------------------------------------------------------------------
# the end-to-end flywheel smoke (slow: trains a real fleet, serves it)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_flywheel_smoke_driver(tmp_path):
    """The check.sh acceptance scenario, runnable standalone: a small
    trained fleet's winner promotes through the reward-aware canary
    under live session traffic; an injected ``regress_checkpoint`` is
    rejected by the realized-return gate; ``kill_promoter`` converges
    on restart; zero client-visible errors; all logs validator-clean
    (the driver asserts all of it and exits nonzero otherwise)."""
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts",
                                      "flywheel_smoke.py"),
         "--tmp", str(tmp_path), "--quick"],
        cwd=repo, env=env, capture_output=True, text=True, timeout=900,
    )
    assert proc.returncode == 0, (
        f"flywheel smoke failed\n--- stdout ---\n{proc.stdout[-4000:]}"
        f"\n--- stderr ---\n{proc.stderr[-4000:]}"
    )
